"""Pod resource registry: TTL-leased self-adverts.

Reference: python/edl/utils/resource_pods.py + utils/register.py — each
pod advertises its JSON under the ``resource`` table with a 15 s lease
refreshed at ttl/2; vanishing from the table (TTL expiry) **is** the
failure signal the leader's generator acts on.
"""

from __future__ import annotations

import time

from edl_tpu.cluster import paths
from edl_tpu.cluster.pod import Pod
from edl_tpu.coord.kv import KVStore
from edl_tpu.coord.register import Register
from edl_tpu.utils import constants


def register_pod(store: KVStore, job_id: str, pod: Pod,
                 ttl: float = constants.ETCD_TTL) -> Register:
    return Register(store, paths.key(job_id, constants.ETCD_POD_RESOURCE, pod.pod_id),
                    pod.to_json().encode(), ttl=ttl)


def load_resource_pods(store: KVStore, job_id: str) -> dict[str, Pod]:
    recs, _ = store.get_prefix(paths.table_prefix(job_id, constants.ETCD_POD_RESOURCE))
    pods = {}
    for r in recs:
        pod = Pod().from_json(r.value.decode())
        pods[pod.pod_id] = pod
    return pods


def wait_until_alone(store: KVStore, job_id: str, pod_id: str, timeout: float) -> bool:
    """Leader exit path: wait until every other pod's advert is gone
    (reference wait_resource, resource_pods.py:57-71)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pods = load_resource_pods(store, job_id)
        if set(pods) <= {pod_id}:
            return True
        time.sleep(1.0)
    return False
