"""Elastic collective control plane (reference layer L3a, SURVEY.md §2.3).

``python -m edl_tpu.collective.launch`` runs on every TPU host: it
advertises the pod in the coordination store, elects a leader, lets the
leader generate the cluster, barriers on membership, spawns trainer
processes with the ``EDL_TPU_*`` env ABI, and stop-resumes them from
checkpoints whenever membership changes.
"""
