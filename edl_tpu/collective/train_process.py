"""Trainer subprocess management: spawn with the env ABI, watch exit
codes, terminate process trees.

Reference: python/edl/utils/train_process.py — per-trainer env
(:46-56), proxy vars stripped (:40-42), per-rank ``workerlog.N`` files
(:115-127), exit-code watch (:130-175), psutil child-tree SIGTERM then
SIGKILL (:89-112).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

import psutil

from edl_tpu.cluster.env import JobEnv, trainer_env_vars
from edl_tpu.cluster.status import Status
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_PROXY_VARS = ("http_proxy", "https_proxy", "HTTP_PROXY", "HTTPS_PROXY")


class _LogTail(threading.Thread):
    """Follow a workerlog and echo new bytes to the launcher's stdout —
    the reference tailed pod-local rank 0's log through the launcher
    (train_process.py:115-127) so a user watching the launcher sees
    training progress without hunting for workerlog files."""

    def __init__(self, path: str, start_offset: int, period: float = 0.5):
        super().__init__(daemon=True, name=f"logtail:{os.path.basename(path)}")
        self._path = path
        self._offset = start_offset
        self._period = period
        # NB: not named _stop — threading.Thread uses that name internally
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self._period):
            self._drain()
        self._drain()  # final flush so exit-time lines are not lost

    def _drain(self) -> None:
        try:
            with open(self._path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return
        if chunk:
            self._offset += len(chunk)
            sys.stdout.write(chunk.decode(errors="replace"))
            sys.stdout.flush()

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=2.0)


@dataclass
class TrainerProc:
    proc: subprocess.Popen
    global_rank: int
    rank_in_pod: int
    log_path: str
    tail: _LogTail | None = field(default=None, repr=False)


def start_trainers(job_env: JobEnv, pod, cluster, training_script: str,
                   script_args: list[str], log_dir: str,
                   extra_env: dict[str, str] | None = None,
                   ) -> list[TrainerProc]:
    """``extra_env`` wins over the inherited environment — the launcher
    uses it to hand each spawned trainer the current resize epoch's
    trace context (EDL_TPU_TRACE_CONTEXT, obs/context.py)."""
    os.makedirs(log_dir, exist_ok=True)
    procs = []
    for trainer in pod.trainers:
        env = dict(os.environ)
        for var in _PROXY_VARS:
            env.pop(var, None)
        env.update(trainer_env_vars(job_env, pod, trainer, cluster))
        if extra_env:
            env.update(extra_env)
        log_path = os.path.join(log_dir, f"workerlog.{trainer.rank_in_pod}")
        logf = open(log_path, "ab", buffering=0)
        offset = logf.tell()
        cmd = [sys.executable, "-u", training_script] + list(script_args)
        proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=subprocess.STDOUT)
        logf.close()  # child holds its own fd
        logger.info("spawned trainer global_rank=%d pid=%d log=%s",
                    trainer.global_rank, proc.pid, log_path)
        tail = None
        if trainer.rank_in_pod == 0:
            tail = _LogTail(log_path, offset)
            tail.start()
        procs.append(TrainerProc(proc, trainer.global_rank, trainer.rank_in_pod,
                                 log_path, tail))
    return procs


def watch_procs(procs: list[TrainerProc]) -> Status:
    """RUNNING while any child lives; FAILED on first nonzero exit;
    SUCCEED when all exited zero (reference train_process.py:130-175).
    DESCALED when the world exits with PREEMPT_EXIT_CODE — the
    coordinated preemption-point-checkpoint departure, neither success
    nor crash (cluster/preempt.py)."""
    from edl_tpu.utils import constants

    alive = False
    preempted = False
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive = True
        elif ret == constants.PREEMPT_EXIT_CODE:
            preempted = True
        elif ret != 0:
            logger.error("trainer rank %d exited with %d; tail of %s:\n%s",
                         tp.global_rank, ret, tp.log_path, _tail(tp.log_path))
            return Status.FAILED
    if alive:
        return Status.RUNNING
    # stop tails with their final drain NOW: on the terminal paths the
    # launcher may exit without terminate_procs finishing the tail
    # thread, losing rank 0's last log lines (advisor r2)
    for tp in procs:
        if tp.tail is not None:
            tp.tail.stop()
            tp.tail = None
    return Status.DESCALED if preempted else Status.SUCCEED


def terminate_procs(procs: list[TrainerProc], grace: float = 3.0) -> None:
    """SIGTERM every child's whole process tree, then SIGKILL stragglers
    (reference train_process.py:89-112)."""
    victims: list[psutil.Process] = []
    for tp in procs:
        try:
            parent = psutil.Process(tp.proc.pid)
            victims.extend(parent.children(recursive=True))
            victims.append(parent)
        except psutil.NoSuchProcess:
            continue
    for p in victims:
        try:
            p.send_signal(signal.SIGTERM)
        except psutil.NoSuchProcess:
            pass
    _, survivors = psutil.wait_procs(victims, timeout=grace)
    for p in survivors:
        try:
            p.kill()
        except psutil.NoSuchProcess:
            pass
    for tp in procs:
        try:
            tp.proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:  # pragma: no cover - kill-resistant child
            logger.warning("trainer pid %d did not die", tp.proc.pid)
        if tp.tail is not None:
            tp.tail.stop()


def _tail(path: str, n: int = 30) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 8192))
            return "\n".join(f.read().decode(errors="replace").splitlines()[-n:])
    except OSError:
        return "<no log>"
