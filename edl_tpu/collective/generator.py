"""Cluster generator: the leader's reconciliation loop.

Reference: python/edl/utils/cluster_generator.py (272).  Every 3 s the
leader reads the resource adverts + pod statuses and reconciles the
cluster record:

- no cluster yet → build one from resource pods, leader rank 0
  (cluster_generator.py:95-134);
- a member vanished (TTL expiry) or FAILED → rebuild from the alive
  set, new stage (:179-192);
- new INITIAL pods, room under the live cap, and train status still
  INITIAL/RUNNING → append them with new ranks, new stage (:136-153,
  :200-215) — the NEARTHEEND anti-meaningless-scaling rule;
- alive membership below ``min_nodes`` → log and wait (:255-264).

The live cap is ``min(max_nodes, desired)`` where ``desired`` is the
controller's desired-size record (cluster/scale.py) — beyond the
reference, whose controller could only add/remove k8s replicas and
wait for the TTL machinery.  When the alive membership EXCEEDS the
cap, the generator rebuilds without the highest-rank pods (scale-in);
the excluded launchers exit cleanly as DESCALED.

Every write is the guarded transaction "leader seat still mine"
(:223-250) so a deposed leader can never clobber its successor.
"""

from __future__ import annotations

import threading

from edl_tpu.cluster import scale
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.pod import Pod
from edl_tpu.cluster.status import Status, load_pods_status
from edl_tpu.cluster.train_status import SCALABLE, load_train_statuses
from edl_tpu.collective.resource import load_resource_pods
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlTableError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def _natural_id(pod_id: str):
    """Sort key treating a trailing ``-<int>`` (StatefulSet ordinal)
    numerically; plain ids order lexically among themselves."""
    head, _, tail = pod_id.rpartition("-")
    if head and tail.isdigit():
        return (head, int(tail), "")
    return (pod_id, -1, pod_id)


class ClusterGenerator(threading.Thread):
    def __init__(self, store, job_id: str, leader_pod_id: str,
                 min_nodes: int, max_nodes: int,
                 period: float = constants.GENERATOR_PERIOD):
        super().__init__(daemon=True, name=f"generator:{leader_pod_id[:8]}")
        self._store = store
        self._job_id = job_id
        self._leader_id = leader_pod_id
        self._min_nodes = min_nodes
        self._max_nodes = max_nodes
        self._period = period
        self._halt = threading.Event()

    def run(self):
        while not self._halt.is_set():
            try:
                self.reconcile_once()
            except EdlTableError as e:
                logger.warning("generator lost leadership mid-write: %s", e)
                return
            except Exception:  # noqa: BLE001
                logger.exception("generator iteration failed")
            self._halt.wait(self._period)

    def stop(self):
        self._halt.set()

    # one reconciliation step; factored out for direct unit testing
    def reconcile_once(self) -> Cluster | None:
        resource = load_resource_pods(self._store, self._job_id)
        if self._leader_id not in resource:
            return None  # our own advert hasn't landed / expired; wait
        statuses = load_pods_status(self._store, self._job_id)
        current = Cluster.load_from_store(self._store, self._job_id)
        self._publish_range()

        if current is None:
            return self._write(self._build_initial(resource))

        cap = self._cap()
        alive = [p for p in current.pods
                 if p.pod_id in resource and statuses.get(p.pod_id) != Status.FAILED]
        gone = [p for p in current.pods if p.pod_id not in {a.pod_id for a in alive}]
        # a MEMBER that left after SUCCEEDing (job completion) is not a
        # membership change — rebuilding would pointlessly restart the
        # survivors while they finish.  A member gone with any other
        # status — including DESCALED — requires a rebuild: a preempted
        # pod departs DESCALED while still a member, and the survivors
        # wait on the shrunk cluster to stop-resume.  (Controller
        # scale-in never hits this: the cap rebuild removes the pod
        # from the cluster BEFORE it exits DESCALED, so it is not in
        # ``gone``.)
        lost = any(statuses.get(p.pod_id) != Status.SUCCEED for p in gone)

        # only *members'* SUCCEED blocks scale-out (job is finishing); a
        # stale unleased SUCCEED left by a previous run of this job_id is
        # not in the current cluster and must not freeze it forever
        any_succeeded = any(statuses.get(p.pod_id) == Status.SUCCEED
                            for p in current.pods)
        new_ids = [pid for pid in resource if current.get_pod(pid) is None
                   and statuses.get(pid, Status.INITIAL) == Status.INITIAL]
        joiners: list[Pod] = []
        if new_ids and not any_succeeded and self._scaling_allowed():
            room = cap - len(alive)
            joiners = [resource[pid] for pid in sorted(new_ids)[:max(0, room)]]

        # controller scale-in: alive membership above the cap and the
        # job can still legally resize -> drop the highest ranks (the
        # leader is rank 0 and always survives)
        shrink = (len(alive) > cap and not any_succeeded
                  and self._scaling_allowed())

        if not lost and not joiners and not shrink:
            return current

        pods = self._leader_first(alive + joiners, resource)
        if shrink and len(pods) > cap:     # _cap() already floors at min_nodes
            pods = pods[:cap]
        if len(pods) < self._min_nodes:
            logger.error("alive pods %d below min_nodes %d; waiting",
                         len(pods), self._min_nodes)
            return current
        cluster = Cluster.from_pods(pods)
        logger.info("cluster stage %s: %d pods (%s%s%s)", cluster.stage[:8],
                    len(pods),
                    f"-{len(current.pods) - len(alive)} lost " if lost else "",
                    f"+{len(joiners)} joined" if joiners else "",
                    f"capped at {cap}" if shrink else "")
        return self._write(cluster)

    def _cap(self) -> int:
        """Live membership cap: max_nodes bounded below by min_nodes and
        overridden downward by the controller's desired record."""
        desired = None
        try:
            desired = scale.load_desired_nodes(self._store, self._job_id)
        except Exception:  # noqa: BLE001 — a bad record must not kill us
            logger.exception("desired-nodes record unreadable; ignoring")
        if desired is None:
            return self._max_nodes
        return max(self._min_nodes, min(self._max_nodes, desired))

    _range_published = False

    def _publish_range(self) -> None:
        """One-time nodes_range advert for external controllers."""
        if self._range_published:
            return
        try:
            scale.save_nodes_range(self._store, self._job_id,
                                   self._min_nodes, self._max_nodes)
            self._range_published = True
        except Exception:  # noqa: BLE001 — advisory only
            logger.exception("nodes_range publish failed")

    def _scaling_allowed(self) -> bool:
        """Only scale out while training is INITIAL/RUNNING (NEARTHEEND rule)."""
        ts = load_train_statuses(self._store, self._job_id)
        return all(s in SCALABLE for s in ts.values())

    def _build_initial(self, resource: dict[str, Pod]) -> Cluster | None:
        if len(resource) < self._min_nodes:
            logger.info("waiting for pods: %d/%d registered",
                        len(resource), self._min_nodes)
            return None
        pods = self._leader_first(list(resource.values()), resource)[:self._cap()]
        cluster = Cluster.from_pods(pods)
        logger.info("initial cluster stage %s with %d pods", cluster.stage[:8], len(pods))
        return cluster

    def _leader_first(self, pods: list[Pod], resource: dict[str, Pod]) -> list[Pod]:
        """Leader pod first (it must be rank 0), stable order for the rest:
        surviving members keep relative rank order, joiners sort by id —
        NATURALLY, so StatefulSet-style ids ('job-10' after 'job-2') get
        ranks tracking their pod ordinals and a k8s scale-in (highest
        ordinal first) kills the same pods the cap retires."""
        uniq = {p.pod_id: p for p in pods}
        leader = uniq.pop(self._leader_id, None) or resource.get(self._leader_id)
        rest = sorted(uniq.values(),
                      key=lambda p: (p.rank if p.rank >= 0 else 1 << 30,
                                     _natural_id(p.pod_id)))
        return ([leader] if leader else []) + rest

    def _write(self, cluster: Cluster | None) -> Cluster | None:
        if cluster is not None:
            cluster.save_to_store(self._store, self._job_id, self._leader_id)
        return cluster
