"""Replica actuators: make the infra match a desired pod count.

The desired-size RECORD (cluster/scale.py) handles the in-band half —
the generator shrinks/permits-growth and excluded launchers exit
DESCALED.  An actuator handles the out-of-band half: actually creating
or destroying pod replicas.  Standalone process deployments need none
(operators start/stop launchers); under k8s the controller patches the
workload's replica count, which is exactly what the reference's
controller binary did to its TrainingJob TPR.
"""

from __future__ import annotations

import subprocess

from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


class NullActuator:
    """Record-only deployments: the store record is the whole signal."""

    def scale(self, job_id: str, replicas: int) -> bool:
        return True


class KubectlActuator:
    """``kubectl scale`` on the workload backing a job.

    ``workload_of(job_id)`` maps job ids to k8s workload refs
    (``statefulset/edl-train``); by default the job id IS the workload
    name of a StatefulSet, matching k8s/train-job.yaml.  StatefulSets
    terminate the highest ordinals first on scale-in, and the generator
    ranks joiners by pod ordinal (generator._natural_id), so the record
    and the replica patch USUALLY agree about which pods leave.  They
    can differ — the leader holds rank 0 whatever its ordinal, so when
    the leader is not ordinal 0 one retired rank may not be the pod k8s
    kills; the cost is one extra stop-resume rebuild (the killed pod's
    TTL expiry triggers it), never a correctness problem.
    """

    def __init__(self, namespace: str = "default", kubectl: str = "kubectl",
                 workload_of=None):
        self._ns = namespace
        self._kubectl = kubectl
        self._workload_of = workload_of or (lambda job_id: f"statefulset/{job_id}")

    def scale(self, job_id: str, replicas: int) -> bool:
        ref = self._workload_of(job_id)
        cmd = [self._kubectl, "-n", self._ns, "scale", ref,
               f"--replicas={replicas}"]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            logger.error("kubectl scale failed: %s (%s)", cmd, e)
            return False
        if r.returncode != 0:
            logger.error("kubectl scale failed (%d): %s", r.returncode,
                         r.stderr.strip()[:300])
            return False
        logger.info("scaled %s to %d replicas", ref, replicas)
        return True
