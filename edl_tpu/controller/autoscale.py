"""Serving-fleet autoscaler: the controller's demand signal for
``kind="serving"`` jobs (ROADMAP item 2c).

The PR-8 rule engine already computes the windowed signals
(``gateway-p99-slo``, ``gateway-reject-burn``); this module turns them
into a replica TARGET the arbitration policy treats as the serving
job's demand cap (policy.JobView.demand):

- **scale-out** — two inputs, folded with max():

  * the demand record (``cluster/scale.py save_demand``) the
    remediation dispatcher writes on a firing gateway alert — the
    store is the channel, so the dispatcher (aggregator process) and
    the controller need no direct wiring; a record older than
    ``EDL_TPU_DEMAND_TTL`` is ignored, so a dead dispatcher's last
    spike decays instead of pinning the fleet out forever;
  * an optional direct ``/alerts`` poll (``alerts_url``): when the
    controller is pointed at the job's aggregator it reads the firing
    set itself and steps the target by ``EDL_TPU_AUTOSCALE_STEP``
    per firing window — the loop closes even with remediation in
    dry-run;

- **scale-in on sustained quiet** — no demand signal for
  ``EDL_TPU_AUTOSCALE_QUIET`` seconds decays the target one replica
  per quiet window, down to the job's ``min_nodes``.  The decay is
  deliberately slower than the growth (one step per window vs one
  step per firing) so a bursty workload holds its headroom.

The controller applies the target through the SAME desired-size
record + actuator as trainer pods — replicas scale exactly like
training capacity, under the same priorities and cooldowns.
"""

from __future__ import annotations

import json
import time
import urllib.request

from edl_tpu.cluster import scale
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.constants import env_float
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_DEMAND_G = obs_metrics.gauge(
    "edl_controller_serving_demand",
    "The autoscaler's current replica target per serving job", ("job",))

#: gateway-family builtin alerts that mean "the fleet needs headroom"
GATEWAY_ALERTS = ("gateway-p99-slo", "gateway-reject-burn")


class ServingAutoscaler:
    """Per-serving-job replica targets from alerts + demand records."""

    def __init__(self, store, alerts_url: str | None = None,
                 step: int | None = None, quiet_s: float | None = None,
                 demand_ttl: float | None = None, poll_timeout: float = 2.0):
        self._store = store
        self._alerts_url = alerts_url
        self._step = (int(env_float("EDL_TPU_AUTOSCALE_STEP", 1))
                      if step is None else int(step))
        self._quiet = (env_float("EDL_TPU_AUTOSCALE_QUIET", 120.0)
                       if quiet_s is None else float(quiet_s))
        self._demand_ttl = (env_float("EDL_TPU_DEMAND_TTL", 120.0)
                            if demand_ttl is None else float(demand_ttl))
        self._poll_timeout = poll_timeout
        # job -> (last_signal_mono, target)
        self._state: dict[str, tuple[float, int]] = {}
        self._alerts_cache: tuple[float, set[str]] | None = None

    # -- inputs --------------------------------------------------------------
    def _firing(self, now: float) -> set[str]:
        """Names of firing gateway-family alerts from the aggregator's
        /alerts endpoint (cached ~1s; empty on any failure — a dead
        aggregator must never wedge the controller)."""
        if self._alerts_url is None:
            return set()
        cached = self._alerts_cache
        if cached is not None and now - cached[0] < 1.0:
            return cached[1]
        names: set[str] = set()
        try:
            body = json.loads(urllib.request.urlopen(
                self._alerts_url, timeout=self._poll_timeout).read().decode())
            names = {str(a.get("alert")) for a in body.get("firing", [])}
            names &= set(GATEWAY_ALERTS)
        except Exception as e:  # noqa: BLE001 — alerts are advisory input
            logger.debug("alerts poll failed: %s", e)
        self._alerts_cache = (now, names)
        return names

    def _demand_record(self, job_id: str) -> int | None:
        try:
            rec = scale.load_demand(self._store, job_id)
        except Exception:  # noqa: BLE001 — a store blip is not a demand
            logger.exception("demand record read failed for %s", job_id)
            return None
        if rec is None:
            return None
        # edl-lint: disable=clock — rec["at"] is the dispatcher's
        # wall-clock stamp read from the store; freshness across
        # processes can only be judged wall-to-wall
        if time.time() - rec["at"] > self._demand_ttl:
            return None
        return int(rec["replicas"])

    # -- the decision --------------------------------------------------------
    def desired(self, job_id: str, min_nodes: int, max_nodes: int,
                current: int, now: float | None = None) -> int:
        """The serving job's replica target this tick.  Monotone while
        signals fire, decays one step per quiet window, clamped to
        [min_nodes, max_nodes]."""
        now = time.monotonic() if now is None else now
        demand = self._demand_record(job_id)
        firing = self._firing(now)
        last, target = self._state.get(
            job_id, (now, max(min_nodes, min(max_nodes, current))))
        if demand is not None or firing:
            want = target
            if firing:
                want = max(want, current + self._step)
            if demand is not None:
                want = max(want, demand)
            target = max(min_nodes, min(max_nodes, want))
            last = now
        elif now - last > self._quiet and target > min_nodes:
            target -= 1                  # one step per quiet window
            last = now
            logger.info("serving job %s quiet for %.0fs: scaling in to %d",
                        job_id, self._quiet, target)
        target = max(min_nodes, min(max_nodes, target))
        self._state[job_id] = (last, target)
        _DEMAND_G.labels(job=job_id).set(target)
        return target


_DISTILL_DEMAND_G = obs_metrics.gauge(
    "edl_controller_distill_demand",
    "The distill autoscaler's current teacher target per fleet job",
    ("job",))


class DistillAutoscaler:
    """Teacher-count targets for ``kind="distill"`` fleet jobs, from
    the students' durable backlog records (``scale/backlog/<student>``,
    written by :class:`~edl_tpu.distill.backlog.StudentFeed`).

    The signal is **backlog seconds** — total queued rows across fresh
    student records divided by the observed teacher throughput.  Growth
    is deliberately two-staged so a single burst can't flap the fleet:
    backlog above ``EDL_TPU_DISTILL_BACKLOG_GROW`` seconds, held
    continuously for ``EDL_TPU_DISTILL_BACKLOG_HOLD`` seconds, steps
    the target by ``EDL_TPU_AUTOSCALE_STEP`` and re-arms (so 1→3 takes
    two held windows).  Decay mirrors the ServingAutoscaler: one step
    per ``EDL_TPU_AUTOSCALE_QUIET`` window without a growth-worthy
    signal, down to min_nodes.  Records older than
    ``EDL_TPU_DEMAND_TTL`` are ignored — a dead student's last backlog
    decays instead of pinning teachers out.  Targets are clamped to
    the job's published nodes range, and the controller feeds them
    into the SAME arbitration (priority classes, cooldowns, eviction
    grace) as every other demand."""

    def __init__(self, store, step: int | None = None,
                 grow_s: float | None = None, hold_s: float | None = None,
                 quiet_s: float | None = None,
                 demand_ttl: float | None = None):
        self._store = store
        self._step = (int(env_float("EDL_TPU_AUTOSCALE_STEP", 1))
                      if step is None else int(step))
        self._grow = (env_float("EDL_TPU_DISTILL_BACKLOG_GROW", 5.0)
                      if grow_s is None else float(grow_s))
        self._hold = (env_float("EDL_TPU_DISTILL_BACKLOG_HOLD", 15.0)
                      if hold_s is None else float(hold_s))
        self._quiet = (env_float("EDL_TPU_AUTOSCALE_QUIET", 120.0)
                       if quiet_s is None else float(quiet_s))
        self._demand_ttl = (env_float("EDL_TPU_DEMAND_TTL", 120.0)
                            if demand_ttl is None else float(demand_ttl))
        # job -> (above_since | None, last_signal_mono, target)
        self._state: dict[str, tuple[float | None, float, int]] = {}

    # -- inputs --------------------------------------------------------------
    def backlog_seconds(self, job_id: str) -> float | None:
        """Summed fresh backlog across students, in seconds of work at
        the observed aggregate teacher rate; None = no fresh records
        (unknown, which never grows the fleet)."""
        try:
            records = scale.load_backlogs(self._store, job_id)
        except Exception:  # noqa: BLE001 — a store blip is not a signal
            logger.exception("backlog records unreadable for %s", job_id)
            return None
        # edl-lint: disable=clock — rec["at"] is the student's
        # wall-clock stamp read from the store; freshness across
        # processes can only be judged wall-to-wall
        now_wall = time.time()
        fresh = [r for r in records.values()
                 if now_wall - r["at"] <= self._demand_ttl]
        if not fresh:
            return None
        queued = sum(r["queued_rows"] for r in fresh)
        rate = sum(r["rows_per_s"] for r in fresh)
        # rows-as-seconds floor when no throughput was observed yet, the
        # same convention the StudentFeed gauge uses
        return queued / rate if rate > 0 else float(queued)

    # -- the decision --------------------------------------------------------
    def desired(self, job_id: str, min_nodes: int, max_nodes: int,
                current: int, now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        backlog_s = self.backlog_seconds(job_id)
        above_since, last, target = self._state.get(
            job_id, (None, now, max(min_nodes, min(max_nodes, current))))
        if backlog_s is not None and backlog_s > self._grow:
            if above_since is None:
                above_since = now
            if now - above_since >= self._hold:
                target = min(max_nodes, target + self._step)
                above_since = now        # re-arm: one step per held window
                logger.info("distill job %s backlog %.1fs held %.0fs: "
                            "scaling out to %d", job_id, backlog_s,
                            self._hold, target)
            last = now
        else:
            above_since = None
            if backlog_s is not None and backlog_s > 0:
                # fresh-but-small backlog: teachers are keeping up but
                # the fleet is in use — refresh the quiet clock
                last = now
            elif now - last > self._quiet and target > min_nodes:
                target -= 1              # one step per quiet window
                last = now
                logger.info("distill job %s quiet for %.0fs: scaling in "
                            "to %d", job_id, self._quiet, target)
        target = max(min_nodes, min(max_nodes, target))
        self._state[job_id] = (above_since, last, target)
        _DISTILL_DEMAND_G.labels(job=job_id).set(target)
        return target
