"""Elastic controller: the in-tree replacement for the reference's k8s
TrainingJob controller/autoscaler (k8s/edl_controller.yaml)."""

from edl_tpu.controller.controller import Controller
from edl_tpu.controller.policy import JobView, compute_desired

__all__ = ["Controller", "JobView", "compute_desired"]
