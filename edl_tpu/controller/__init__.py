"""Elastic controller: the in-tree replacement for the reference's k8s
TrainingJob controller/autoscaler (k8s/edl_controller.yaml), grown into
the multi-job arbiter + alert-driven remediation loop (ROADMAP 4)."""

from edl_tpu.controller.autoscale import ServingAutoscaler
from edl_tpu.controller.controller import Controller
from edl_tpu.controller.policy import KIND_PRIORITY, JobView, compute_desired
from edl_tpu.controller.remediate import CircuitBreaker, RemediationDispatcher

__all__ = ["Controller", "JobView", "compute_desired", "KIND_PRIORITY",
           "ServingAutoscaler", "RemediationDispatcher", "CircuitBreaker"]
