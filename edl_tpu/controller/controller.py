"""The controller loop: observe jobs in the coordination store, compute
desired sizes, write the scaling records, drive the replica actuator.

Reference parity: the k8s TrainingJob controller+autoscaler
(k8s/edl_controller.yaml, doc/usage.md "Auto-scaling experiment") —
the one reference subsystem with no in-tree analogue until now.  The
difference in design: the reference controller could only patch k8s
replica counts and let TTL expiry do the rest; this controller speaks
the SAME coordination store as the launchers, so scale-in is an
explicit record the generator honors deterministically (highest ranks
leave, leader survives) and scale-out headroom opens before the new
replicas even boot.

Job discovery: jobs publish their ``nodes_range`` via the generator
(cluster/scale.py save_nodes_range); the controller scans the store
root for them, so ``--job_id`` lists are optional.
"""

from __future__ import annotations

import threading
import time

from edl_tpu.cluster import paths, scale
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.cluster.train_status import SCALABLE, load_train_statuses
from edl_tpu.controller.actuator import NullActuator
from edl_tpu.controller.autoscale import DistillAutoscaler, ServingAutoscaler
from edl_tpu.controller.policy import KIND_PRIORITY, JobView, compute_desired
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import context as obs_context
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_DECISIONS_TOTAL = obs_metrics.counter(
    "edl_controller_scale_decisions_total",
    "Desired-size changes written, by job and direction",
    ("job", "direction"))
_DESIRED_NODES = obs_metrics.gauge(
    "edl_controller_desired_nodes", "Last desired size written per job",
    ("job",))
_RESIZE_COST = obs_metrics.gauge(
    "edl_controller_resize_cost_seconds",
    "Last measured stop-resume cost per job (recovery records)",
    ("job",))
_EVICTIONS_TOTAL = obs_metrics.counter(
    "edl_controller_evictions_total",
    "Pods flagged for graceful (preempt-grace) eviction on a "
    "controller shrink, by job and reason", ("job", "reason"))


class Controller:
    def __init__(self, store, *, capacity: int = 0,
                 max_load_desired: float = 0.9,
                 job_ids: list[str] | None = None,
                 actuator=None, period: float = 5.0,
                 cooldown: float = 30.0,
                 cooldown_per_resize_s: float = 10.0,
                 observe_window_s: float = 900.0,
                 alerts_url: str | None = None,
                 autoscaler: ServingAutoscaler | None = None,
                 distill_autoscaler: DistillAutoscaler | None = None,
                 preempt_grace_s: float = 0.0):
        """``capacity``: schedulable pod slots across the cluster (the
        k8s node budget; the thing ``max_load_desired`` scales).
        **0 = observe**: the high-water mark of concurrently live pod
        adverts (members + pending) across managed jobs over the last
        ``observe_window_s`` seconds — the store shows what the infra
        actually scheduled, so the budget tracks reality instead of a
        constant someone typed once (round-4 verdict weak #5).  The
        mark is WINDOWED, not lifetime (ADVICE r5): infra that shrank
        for good ages out of the window, so the controller stops
        writing unschedulable scale-ups for capacity that no longer
        exists every cooldown.  ``job_ids``: explicit jobs to manage;
        None = discover every job that published a nodes_range.
        ``cooldown``: minimum seconds between desired-size changes per
        job — scaled UP per job by ``cooldown_per_resize_s`` x its
        last measured stop-resume cost (recovery records), so a job
        that takes 30 s to resize flaps an order of magnitude slower
        than one that takes 2 s.

        Multi-job arbitration: every managed job's ``scale/spec``
        record (kind/priority/gang — cluster/scale.py) feeds the
        policy; ``kind="serving"`` jobs are counted by their replica
        adverts and capped by the :class:`ServingAutoscaler`'s demand
        (``alerts_url`` points it at the job aggregator's ``/alerts``).
        ``preempt_grace_s`` > 0 turns a training/distill SHRINK into a
        graceful eviction: the retiring pods (highest ranks — the same
        pods the generator will drop) are preempt-flagged with a
        reason (``priority-yield`` when a higher class's demand forced
        the shrink, else ``descale``) so trainers checkpoint at an
        agreed step and depart DESCALED; the desired record is written
        once they leave (or the grace expires)."""
        import collections
        self._store = store
        self._capacity = capacity
        self._capacity_observed = 0        # last windowed mark computed
        self._capacity_window_s = observe_window_s
        self._capacity_samples: collections.deque[tuple[float, int]] = \
            collections.deque()
        self._max_load = max_load_desired
        self._job_ids = job_ids
        self._actuator = actuator or NullActuator()
        self._period = period
        self._cooldown = cooldown
        self._cooldown_per_resize = cooldown_per_resize_s
        self._last_change: dict[str, float] = {}
        self._resize_cost_cache: dict[str, tuple[float, float]] = {}
        self._reaped: set[str] = set()
        self._autoscaler = autoscaler or ServingAutoscaler(
            store, alerts_url=alerts_url)
        self._distill_autoscaler = distill_autoscaler or DistillAutoscaler(
            store)
        self._preempt_grace = float(preempt_grace_s)
        # job -> in-flight graceful eviction {want, pods, stage, deadline}
        self._evictions: dict[str, dict] = {}
        self._halt = threading.Event()
        self._thread: threading.Thread | None = None

    # -- observation ---------------------------------------------------------
    def discover_jobs(self) -> list[str]:
        if self._job_ids is not None:
            return list(self._job_ids)
        # every job that published a nodes_range owns a
        # /<root>/<job>/scale/range key
        recs, _ = self._store.get_prefix(paths.ROOT + "/")
        jobs = set()
        suffix = f"/{constants.ETCD_SCALE}/range"
        for r in recs:
            if r.key.endswith(suffix):
                jobs.add(r.key[len(paths.ROOT) + 1:-len(suffix)])
        return sorted(jobs)

    def _terminal(self, job_id: str) -> bool:
        """SUCCEED is always terminal; FAILED only counts once no pod
        holds a live resource lease — the launcher writes a PROVISIONAL
        job FAILED on any pod death (launcher.py _report_and_cleanup)
        that an elastic recovery overwrites, and reaping a recovering
        job would kill it."""
        status = load_job_status(self._store, job_id)
        if status == Status.SUCCEED:
            return True
        if status != Status.FAILED:
            return False
        from edl_tpu.collective.resource import load_resource_pods
        return not load_resource_pods(self._store, job_id)

    def job_view(self, job_id: str) -> JobView | None:
        """None = job is terminal or not observable (skip it)."""
        rng = scale.load_nodes_range(self._store, job_id)
        if rng is None:
            return None
        if self._terminal(job_id):
            return None
        spec = scale.load_job_spec(self._store, job_id) or {}
        kind = str(spec.get("kind", "training"))
        priority = int(spec.get("priority", KIND_PRIORITY.get(kind, 0)))
        gang = bool(spec.get("gang", False))
        fleet = kind == "serving" or (kind == "distill"
                                      and bool(spec.get("fleet")))
        if fleet:
            # an advert-backed fleet has no cluster record or train
            # status: the live serving adverts ARE the membership, and
            # an autoscaler's demand caps its surplus take — gateway
            # alerts/demand records for serving, the students' backlog
            # records for a distill teacher fleet
            from edl_tpu.gateway.fleet import list_replicas
            current = len(list_replicas(self._store, job_id))
            view = JobView(job_id=job_id, min_nodes=rng[0],
                           max_nodes=rng[1], current_nodes=current,
                           kind=kind, priority=priority, gang=gang)
            scaler = (self._autoscaler if kind == "serving"
                      else self._distill_autoscaler)
            view.demand = scaler.desired(job_id, rng[0], rng[1], current)
            return view
        cluster = Cluster.load_from_store(self._store, job_id)
        current = len(cluster.pods) if cluster else 0
        ts = load_train_statuses(self._store, job_id)
        scalable = all(s in SCALABLE for s in ts.values())
        # observed signals: live adverts not in the cluster = replicas
        # the infra scheduled that the record hasn't admitted yet;
        # resize cost = the job's last complete recovery record
        from edl_tpu.collective.resource import load_resource_pods
        live = set(load_resource_pods(self._store, job_id))
        members = set(cluster.pod_ids()) if cluster else set()
        return JobView(job_id=job_id, min_nodes=rng[0], max_nodes=rng[1],
                       current_nodes=current, scalable=scalable,
                       pending_pods=len(live - members),
                       resize_cost_s=self._resize_cost(job_id),
                       kind=kind, priority=priority, gang=gang)

    _RESIZE_COST_TTL = 60.0

    def _resize_cost(self, job_id: str) -> float:
        """Last measured stop-resume total for this job (seconds), from
        the recovery records both halves of the launcher/trainer write;
        0.0 when never measured.  Cached per job (the prefix scan
        re-parses every historical stage; re-reading each 5 s tick for
        the life of a long job is pure store traffic)."""
        cached = self._resize_cost_cache.get(job_id)
        now = time.monotonic()
        if cached is not None and now - cached[0] < self._RESIZE_COST_TTL:
            return cached[1]
        cost = 0.0
        try:
            from edl_tpu.cluster.recovery import summarize_recovery
            complete = [s for s in summarize_recovery(self._store, job_id)
                        if "total" in s]
            cost = float(complete[-1]["total"]) if complete else 0.0
        except Exception:  # noqa: BLE001 — metrics must not stop scaling
            logger.exception("recovery records unreadable for %s", job_id)
        self._resize_cost_cache[job_id] = (now, cost)
        _RESIZE_COST.labels(job=job_id).set(cost)
        return cost

    def _effective_cooldown(self, view: JobView) -> float:
        """Per-job cooldown scaled by the measured resize cost."""
        return max(self._cooldown,
                   self._cooldown_per_resize * view.resize_cost_s)

    def _effective_capacity(self, views: list[JobView],
                            now: float | None = None) -> int:
        """Configured capacity, or (capacity=0) the WINDOWED high-water
        mark of concurrently live pods across managed jobs: the max of
        the last ``observe_window_s`` of samples, never below the
        current liveness.  A lifetime mark (the old behavior) pinned
        the budget at a peak the infra may never offer again, so every
        cooldown re-proposed a scale-up no replica could satisfy; a
        windowed mark decays back to demonstrated reality.  ``now`` is
        injectable for tests."""
        if self._capacity > 0:
            return self._capacity
        now = time.monotonic() if now is None else now
        live_now = sum(v.current_nodes + v.pending_pods for v in views)
        self._capacity_samples.append((now, live_now))
        cutoff = now - self._capacity_window_s
        while self._capacity_samples and self._capacity_samples[0][0] < cutoff:
            self._capacity_samples.popleft()
        self._capacity_observed = max(
            1, max(v for _, v in self._capacity_samples))
        return self._capacity_observed

    # -- one reconciliation tick (unit-test entry point) ---------------------
    def reconcile_once(self) -> dict[str, int]:
        """Returns the desired sizes it ACTED on this tick."""
        jobs = self.discover_jobs()
        self._reap_finished(jobs)
        views = [v for v in (self.job_view(j) for j in jobs)
                 if v is not None]
        # observe mode: the high-water mark IS demonstrated usage, so
        # no max_load trim — trimming 0.9x below what is already
        # running would evict healthy pods from every job it watches
        if self._capacity > 0:
            desired = compute_desired(views, self._capacity, self._max_load)
        else:
            desired = compute_desired(views, self._effective_capacity(views),
                                      1.0)
        now = time.monotonic()
        acted = self._drive_evictions(now)
        for v in views:
            want = desired[v.job_id]
            if v.job_id in self._evictions:
                if want >= v.current_nodes:
                    # the pressure lifted before the record landed: the
                    # flagged pods still depart (a preemption cannot be
                    # unwritten — trainers may already be checkpointing)
                    # but no shrink record follows them out
                    logger.info("job %s: pending eviction overtaken by "
                                "scale-up; dropping the shrink record",
                                v.job_id)
                    self._evictions.pop(v.job_id, None)
                continue                 # eviction draining: hands off
            if want == v.current_nodes:
                continue
            last = self._last_change.get(v.job_id, -float("inf"))
            if now - last < self._effective_cooldown(v):
                continue
            if (want < v.current_nodes and self._preempt_grace > 0
                    and v.kind in ("training", "distill")
                    and self._begin_eviction(v, want, views, desired, now)):
                continue
            prev = None
            try:
                prev = scale.load_desired_nodes(self._store, v.job_id)
            except Exception:  # noqa: BLE001
                logger.exception("desired record unreadable for %s", v.job_id)
            if prev == want and v.current_nodes != want:
                # record already says so; the cluster just hasn't
                # converged (e.g. waiting for replicas) — don't re-stamp
                # the cooldown, but do re-drive the actuator
                self._actuator.scale(v.job_id, want)
                continue
            logger.info("job %s: %d -> %d pods (range %d:%d, capacity %d)",
                        v.job_id, v.current_nodes, want, v.min_nodes,
                        v.max_nodes, self._capacity)
            scale.save_desired_nodes(self._store, v.job_id, want)
            self._actuator.scale(v.job_id, want)
            self._last_change[v.job_id] = now
            acted[v.job_id] = want
            direction = "up" if want > v.current_nodes else "down"
            _DECISIONS_TOTAL.labels(job=v.job_id, direction=direction).inc()
            _DESIRED_NODES.labels(job=v.job_id).set(want)
            # each scale decision roots its own distributed trace — the
            # controller is the first cause of the resize epoch the
            # launchers will measure, so its event is id-linkable
            with obs_context.use(obs_context.new_trace(job=v.job_id)):
                obs_trace.emit("controller/scale", job=v.job_id,
                               from_nodes=v.current_nodes, to_nodes=want,
                               resize_cost_s=v.resize_cost_s)
        return acted

    # -- graceful (preempt-grace) shrink -------------------------------------
    def _begin_eviction(self, v: JobView, want: int, views: list[JobView],
                        desired: dict[str, int], now: float) -> bool:
        """Flag the retiring pods (highest ranks — the same pods the
        generator's desired cap will drop) for preemption with a
        machine-readable reason, so trainers checkpoint at an agreed
        step BEFORE the shrink record yanks membership.  True = the
        eviction is in flight (the desired record follows once the
        pods depart or the grace expires); False = fall back to the
        direct record write."""
        from edl_tpu.cluster import preempt
        try:
            cluster = Cluster.load_from_store(self._store, v.job_id)
        except Exception:  # noqa: BLE001 — fall back to the direct write
            logger.exception("cluster read failed for %s", v.job_id)
            return False
        if cluster is None or len(cluster.pods) <= want:
            return False
        retiring = cluster.pod_ids()[want:]
        # WHY the shrink: a higher class growing this tick means this
        # job is yielding chips to it; otherwise it is a plain descale
        reason = ("priority-yield" if any(
            o.priority > v.priority
            and desired.get(o.job_id, 0) > o.current_nodes
            for o in views) else "descale")
        try:
            for pod in retiring:
                preempt.flag_preempt(self._store, v.job_id, cluster.stage,
                                     pod, reason=reason)
        except Exception:  # noqa: BLE001 — fall back to the direct write
            logger.exception("preempt flag write failed for %s", v.job_id)
            return False
        _EVICTIONS_TOTAL.labels(job=v.job_id, reason=reason).inc(
            len(retiring))
        logger.info("job %s: graceful shrink %d -> %d (reason=%s); "
                    "flagged %s", v.job_id, v.current_nodes, want, reason,
                    [p[:8] for p in retiring])
        with obs_context.use(obs_context.new_trace(job=v.job_id)):
            obs_trace.emit("controller/evict", job=v.job_id, reason=reason,
                           pods=[p[:8] for p in retiring],
                           from_nodes=v.current_nodes, to_nodes=want)
        self._evictions[v.job_id] = {
            "want": want, "pods": retiring, "stage": cluster.stage,
            "deadline": now + self._preempt_grace}
        return True

    def _drive_evictions(self, now: float) -> dict[str, int]:
        """Commit the shrink record for evictions whose pods departed
        (or whose grace expired — the generator then drops them the
        hard way); returns what was committed this tick."""
        done: dict[str, int] = {}
        for job_id, ev in list(self._evictions.items()):
            try:
                cluster = Cluster.load_from_store(self._store, job_id)
                live = set(cluster.pod_ids()) if cluster else set()
            except Exception:  # noqa: BLE001 — retry next tick
                logger.exception("cluster read failed for %s", job_id)
                continue
            if (set(ev["pods"]) & live) and now < ev["deadline"]:
                continue                 # still draining gracefully
            if now >= ev["deadline"] and set(ev["pods"]) & live:
                logger.warning("job %s: preempt grace expired with %s "
                               "still in the cluster; committing the "
                               "shrink record anyway", job_id,
                               [p[:8] for p in set(ev["pods"]) & live])
            want = ev["want"]
            try:
                scale.save_desired_nodes(self._store, job_id, want)
            except Exception:  # noqa: BLE001 — retry next tick
                logger.exception("desired record write failed for %s",
                                 job_id)
                continue
            self._actuator.scale(job_id, want)
            del self._evictions[job_id]
            self._last_change[job_id] = now
            done[job_id] = want
            _DECISIONS_TOTAL.labels(job=job_id, direction="down").inc()
            _DESIRED_NODES.labels(job=job_id).set(want)
        return done

    def _reap_finished(self, jobs: list[str]) -> None:
        """Scale terminal jobs' workloads to zero, once — the reference
        controller reaped finished TrainingJobs; without this a
        SUCCEEDed StatefulSet restart-loops its exit-0 launchers."""
        for job_id in jobs:
            if job_id in self._reaped:
                continue
            if self._terminal(job_id):
                logger.info("job %s terminal; scaling workload to 0", job_id)
                if self._actuator.scale(job_id, 0):
                    self._reaped.add(job_id)

    # -- the loop ------------------------------------------------------------
    def run_forever(self) -> None:
        while not self._halt.is_set():
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("controller tick failed")
            self._halt.wait(self._period)

    def start(self) -> "Controller":
        self._thread = threading.Thread(target=self.run_forever, daemon=True,
                                        name="edl-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
