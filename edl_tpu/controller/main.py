"""``edl-controller`` CLI: the elastic autoscaler daemon.

    edl-controller --coord_endpoints host:2379 --capacity 16
    edl-controller --coord_endpoints host:2379 --capacity 16 \
        --k8s_namespace training   # also patch StatefulSet replicas

Reference: the TrainingJob controller deployment
(/root/reference/k8s/edl_controller.yaml) with ``-max_load_desired``.
"""

from __future__ import annotations

import argparse
import signal
import threading


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="EDL-TPU elastic controller")
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--capacity", type=int, default=0,
                   help="schedulable pod slots across the cluster; "
                        "0 (default) = observe: track the high-water "
                        "mark of concurrently live pod adverts")
    p.add_argument("--max_load_desired", type=float, default=0.9,
                   help="fill the cluster to at most this fraction "
                        "(reference edl_controller.yaml:21)")
    p.add_argument("--job_id", action="append", default=None,
                   help="manage only these jobs (repeatable); default: "
                        "discover every job that published a nodes_range")
    p.add_argument("--period", type=float, default=5.0)
    p.add_argument("--cooldown", type=float, default=30.0,
                   help="min seconds between resizes per job")
    p.add_argument("--cooldown_per_resize_s", type=float, default=10.0,
                   help="scale each job's cooldown by this x its last "
                        "measured stop-resume cost (recovery records)")
    p.add_argument("--k8s_namespace", default="",
                   help="when set, also `kubectl scale` the job's "
                        "StatefulSet in this namespace")
    p.add_argument("--kubectl", default="kubectl")
    p.add_argument("--alerts_endpoint", default="",
                   help="a job aggregator's host:port; the serving "
                        "autoscaler polls its /alerts for firing "
                        "gateway SLO rules (demand records from the "
                        "remediation dispatcher work without it)")
    p.add_argument("--preempt_grace", type=float, default=0.0,
                   help="> 0: shrink training/distill jobs through the "
                        "preemption-grace path (flag + checkpoint + "
                        "DESCALED departure) instead of yanking the "
                        "desired record; the value bounds the wait")
    return p


def run(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    from edl_tpu import obs
    from edl_tpu.utils.logger import configure

    configure()
    obs.install_from_env("controller")  # /metrics + JSONL trace, env-gated

    from edl_tpu.controller.actuator import KubectlActuator, NullActuator
    from edl_tpu.controller.controller import Controller
    from edl_tpu.coord.client import connect

    actuator = (KubectlActuator(namespace=args.k8s_namespace,
                                kubectl=args.kubectl)
                if args.k8s_namespace else NullActuator())
    ctl = Controller(connect(args.coord_endpoints), capacity=args.capacity,
                     max_load_desired=args.max_load_desired,
                     job_ids=args.job_id, actuator=actuator,
                     period=args.period, cooldown=args.cooldown,
                     cooldown_per_resize_s=args.cooldown_per_resize_s,
                     alerts_url=(f"http://{args.alerts_endpoint}/alerts"
                                 if args.alerts_endpoint else None),
                     preempt_grace_s=args.preempt_grace)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    ctl.start()
    stop.wait()
    ctl.stop()
    return 0


def main():  # pragma: no cover - thin wrapper
    import sys

    sys.exit(run())


if __name__ == "__main__":
    main()
