"""Alert-driven remediation: the dispatcher that turns a firing alert
into an ACTION, behind safety rails.

PR 12 gave the rule engine an action hook and one read-only action
(``profile``).  This module grows it into the self-driving loop
(ROADMAP item 4): the aggregator registers these handlers with its
:class:`~edl_tpu.obs.rules.RuleEngine`, and a firing transition becomes

- ``restart`` (``trainer-hang``) — a targeted restart of the hung
  job's trainers: a single-pod job gets a per-pod restart flag
  (``cluster/heartbeat.py flag_pod_restart``; its launcher kills +
  respawns the trainers in place, no membership change); a multi-pod
  job — one shared collective world, where killing one pod's trainers
  unilaterally just crashes the peers — takes the coordinated hang
  flag (kill + instant re-barrier at the unchanged stage).  Either
  way, OTHER jobs on the cluster are untouched;
- ``evict`` (``trainer-straggler``) — the slow pod leaves through the
  preemption-grace path (``cluster/preempt.py``, reason
  ``straggler-evict``): trainers checkpoint at an agreed step, the
  evicted pod departs DESCALED, survivors recover with no span lost.
  Refused (``no_capacity``) when the job is already at ``min_nodes`` —
  remediation must never starve the job it is healing;
- ``scale-out`` (``gateway-p99-slo`` / ``gateway-reject-burn``) — a
  demand record (``cluster/scale.py save_demand``) asks the controller
  for more serving replicas; the controller's autoscaler
  (controller/autoscale.py) honors it and scales the fleet like
  trainer pods, and scales back in on sustained quiet;
- ``bundle`` (prepended to EVERY builtin rule's action list;
  ``EDL_TPU_OBS_BUNDLE=0`` strips it) — the host-provided postmortem
  capturer (:mod:`edl_tpu.obs.bundle`, normally the aggregator's):
  flight-recorder rings, the TSDB window, coord state and workerlog
  tails frozen into one archive BEFORE a restart/evict action destroys
  the evidence it would explain.

An actuator wired to an alert is a NEW failure mode, so every action
runs behind rails:

- **per-(rule, action) cooldown** (``EDL_TPU_REMEDIATE_COOLDOWN``) —
  one alert transition = at most one action per window;
- **circuit breaker** per action (``EDL_TPU_REMEDIATE_BREAKER_N``
  executions inside ``EDL_TPU_REMEDIATE_BREAKER_WINDOW`` seconds trips
  it OPEN for ``EDL_TPU_REMEDIATE_BREAKER_RESET`` seconds): a flapping
  rule cannot restart-storm a healthy job.  Open surfaces as the
  ``edl_remediation_breaker_open`` gauge, which the builtin
  ``remediation-breaker-open`` rule turns into its own alert.  After
  the reset the breaker HALF-OPENS: one trial action is allowed; a
  re-trigger inside the window re-opens it, a quiet window closes it;
- **dry-run** (``EDL_TPU_REMEDIATE=0``) — the dispatcher resolves
  targets and records what it WOULD do (outcome ``dryrun``) without
  touching the store;
- **audit** — every trigger lands in the durable incident log
  (``action/<name>`` records joined to the job's current generation
  trace, next to the alert's own record) and in the in-memory
  recent-actions ring served on ``/alerts`` (the ``edl-obs-top``
  "recent actions" pane); executions count into
  ``edl_alert_actions_total{action,outcome}`` with the new
  ``cooldown`` / ``breaker_open`` / ``dryrun`` / ``noop`` outcomes.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.constants import env_float
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_BREAKER_G = obs_metrics.gauge(
    "edl_remediation_breaker_open",
    "1 while the named remediation action's circuit breaker is OPEN "
    "(flapping rule; actions suppressed until half-open)", ("action",))
_BREAKER_TRIPS = obs_metrics.counter(
    "edl_remediation_breaker_trips_total",
    "Circuit-breaker open transitions, by action", ("action",))


class CircuitBreaker:
    """Per-action breaker: ``allow()`` records an execution or denies.

    closed --(N executions inside window)--> open --(reset_s)-->
    half-open --(one trial; re-trigger inside window)--> open
             \\--(window of quiet)--> closed
    """

    def __init__(self, max_actions: int = 3, window_s: float = 120.0,
                 reset_s: float = 300.0):
        self.max_actions = max(1, int(max_actions))
        self.window_s = float(window_s)
        self.reset_s = float(reset_s)
        self.state = "closed"
        self._times: collections.deque[float] = collections.deque()
        self._open_at = 0.0

    def allow(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        while self._times and self._times[0] <= now - self.window_s:
            self._times.popleft()
        if self.state == "open":
            if now - self._open_at < self.reset_s:
                return False
            # half-open: the window starts empty; ONE trial may run
            self.state = "half_open"
            self._times.clear()
        elif self.state == "half_open":
            if self._times:
                # the trial's window hasn't drained and the rule fired
                # again: still flapping — re-open without executing
                self.state = "open"
                self._open_at = now
                return False
            self.state = "closed"      # trial survived a quiet window
        if len(self._times) >= self.max_actions:
            self.state = "open"
            self._open_at = now
            return False
        self._times.append(now)
        return True


class RemediationDispatcher:
    """The action handlers + rails; host-agnostic (needs only the coord
    store and the job id), normally owned by the job's aggregator."""

    ACTIONS = ("restart", "evict", "scale-out", "bundle")

    def __init__(self, store, job_id: str, incident_log=None,
                 trace_provider=None, bundle_fn=None,
                 enabled: bool | None = None,
                 cooldown_s: float | None = None,
                 breaker_n: int | None = None,
                 breaker_window_s: float | None = None,
                 breaker_reset_s: float | None = None,
                 scale_step: int | None = None, recent_cap: int = 64):
        self.store = store
        self.job_id = job_id
        self.incidents = incident_log
        self._trace_provider = trace_provider
        # the ``bundle`` actuator is host-provided: assembling a
        # postmortem needs the aggregator's TSDB/history/incident-log,
        # which the dispatcher deliberately doesn't own.  None -> noop.
        self._bundle_fn = bundle_fn
        self.enabled = (os.environ.get("EDL_TPU_REMEDIATE", "1") != "0"
                        if enabled is None else bool(enabled))
        self.cooldown_s = (env_float("EDL_TPU_REMEDIATE_COOLDOWN", 30.0)
                           if cooldown_s is None else float(cooldown_s))
        n = (int(env_float("EDL_TPU_REMEDIATE_BREAKER_N", 3))
             if breaker_n is None else int(breaker_n))
        window = (env_float("EDL_TPU_REMEDIATE_BREAKER_WINDOW", 300.0)
                  if breaker_window_s is None else float(breaker_window_s))
        reset = (env_float("EDL_TPU_REMEDIATE_BREAKER_RESET", 600.0)
                 if breaker_reset_s is None else float(breaker_reset_s))
        self._scale_step = (int(env_float("EDL_TPU_AUTOSCALE_STEP", 1))
                            if scale_step is None else int(scale_step))
        self._breakers = {a: CircuitBreaker(n, window, reset)
                          for a in self.ACTIONS}
        self._last: dict[tuple[str, str], float] = {}
        self._recent: collections.deque[dict] = collections.deque(
            maxlen=recent_cap)
        self._lock = threading.Lock()

    # -- engine integration --------------------------------------------------
    def handlers(self) -> dict:
        """``{action_name: handler}`` for RuleEngine(actions=...)."""
        return {a: (lambda rule, group, value, _a=a:
                    self.dispatch(_a, rule, group, value))
                for a in self.ACTIONS}

    def recent(self) -> list[dict]:
        """The recent alert->action ring, oldest first (the
        ``/alerts`` ``actions`` list; edl-obs-top renders it)."""
        with self._lock:
            return list(self._recent)

    def breakers(self) -> dict[str, str]:
        with self._lock:
            return {a: b.state for a, b in self._breakers.items()}

    # -- the dispatch path ---------------------------------------------------
    def dispatch(self, action: str, rule, group: str, value: float,
                 now: float | None = None) -> str:
        """Rails, then the action; returns the outcome string the
        engine counts.  Never raises past the engine's own catch."""
        now = time.monotonic() if now is None else now
        detail: dict = {}
        if not self.enabled:
            # dry-run observes ONLY: no rail state moves — a rehearsal
            # must never trip the breaker (and page the operator with
            # a critical alert) over actions that would not execute
            try:
                detail = self._plan(action, rule, group)
            except Exception as e:  # noqa: BLE001 — a dry run must never fail
                logger.debug("dry-run plan for %s failed: %s", action, e)
            return self._record(action, rule, group, "dryrun", detail)
        denied: tuple[str, bool] | None = None     # (outcome, incident?)
        # rails under the lock; the audit write happens OUTSIDE it
        # (incident records are file + store I/O)
        with self._lock:
            last = self._last.get((rule.name, action))
            if last is not None and now - last < self.cooldown_s:
                denied = ("cooldown", False)
            else:
                breaker = self._breakers[action]
                before = breaker.state
                allowed = breaker.allow(now)
                self._breaker_transition(action, breaker, before)
                if not allowed:
                    denied = ("breaker_open", before != "open")
                else:
                    self._last[(rule.name, action)] = now
        if denied is not None:
            return self._record(action, rule, group, denied[0], detail,
                                incident=denied[1])
        try:
            outcome, detail = self._execute(action, rule, group)
        except Exception:  # noqa: BLE001 — engine counts "error"
            self._record(action, rule, group, "error", detail)
            raise
        return self._record(action, rule, group, outcome, detail)

    def _breaker_transition(self, action: str, breaker: CircuitBreaker,
                            before: str) -> None:
        """Gauge + log + trip counter on state changes (lock held)."""
        if breaker.state == before:
            return
        _BREAKER_G.labels(action=action).set(
            1.0 if breaker.state == "open" else 0.0)
        if breaker.state == "open":
            _BREAKER_TRIPS.labels(action=action).inc()
            logger.error("remediation breaker OPEN for %r: %d actions "
                         "inside %.0fs — a flapping rule is suppressed "
                         "for %.0fs", action, breaker.max_actions,
                         breaker.window_s, breaker.reset_s)
        else:
            logger.warning("remediation breaker for %r: %s -> %s", action,
                           before, breaker.state)

    def _record(self, action: str, rule, group: str, outcome: str,
                detail: dict, incident: bool = True) -> str:
        rec = {"ts": time.time(), "rule": rule.name, "action": action,
               "group": group, "outcome": outcome}
        if detail:
            rec["detail"] = detail
        with self._lock:
            self._recent.append(rec)
            breaker_state = self._breakers[action].state
        rec["breaker"] = breaker_state
        log = logger.info if outcome in ("ok", "noop") else logger.warning
        log("remediation %s -> %s [%s]%s (breaker %s)", rule.name, action,
            outcome, f" {detail}" if detail else "", breaker_state)
        if incident and self.incidents is not None:
            trace_id = None
            if self._trace_provider is not None:
                try:
                    trace_id = self._trace_provider()
                except Exception as e:  # noqa: BLE001 — audit is best-effort
                    logger.debug("action trace lookup failed: %s", e)
            try:
                self.incidents.write_action(action, rule, group, outcome,
                                            detail, trace_id=trace_id)
            except Exception:  # noqa: BLE001 — audit must not stop actions
                logger.exception("action incident record failed")
        return outcome

    # -- target resolution (shared by execute and dry-run) -------------------
    def _cluster(self):
        from edl_tpu.cluster.cluster import Cluster
        return Cluster.load_from_store(self.store, self.job_id)

    def _pod_of_instance(self, group: str) -> str | None:
        """Map an alert group (a /metrics instance endpoint) to the pod
        that advertised it (the ``pod`` advert extra)."""
        if not group:
            return None
        from edl_tpu.obs import advert as obs_advert
        for payload in obs_advert.list_metrics_targets(
                self.store, self.job_id).values():
            if str(payload.get("endpoint")) == group and payload.get("pod"):
                return str(payload["pod"])
        return None

    def _stale_pods(self, cluster, window_s: float) -> list[str]:
        """Cluster pods whose liveness beat exists and is stale — the
        per-pod blame the summed trainer-hang signal can't assign.  The
        trainer-published threshold wins; a pod that never published
        one is judged against the alert rule's own window."""
        from edl_tpu.cluster import heartbeat
        stale = []
        for pod_id in cluster.pod_ids():
            try:
                info = heartbeat.last_beat_info(self.store, self.job_id,
                                                pod_id)
            except Exception:  # noqa: BLE001 — a blip is not a hang
                logger.debug("beat read failed for %s", pod_id,
                             exc_info=True)
                continue
            if info is None:
                continue
            ts, published = info
            threshold = heartbeat.stale_threshold(published) or window_s
            # edl-lint: disable=clock — ts is the trainer's wall-clock
            # beat read from the store; cross-process staleness can
            # only be judged wall-to-wall (launcher._hung precedent)
            if time.time() - ts > threshold:
                stale.append(pod_id)
        return stale

    def _plan(self, action: str, rule, group: str) -> dict:
        """Dry-run: what _execute would target, read-only."""
        if action == "restart":
            cluster = self._cluster()
            if cluster is None:
                return {"target": None}
            mode = "targeted" if len(cluster.pods) == 1 else "coordinated"
            return {"mode": mode, "pods": cluster.pod_ids(),
                    "stage": cluster.stage,
                    "stale": self._stale_pods(cluster, rule.window)}
        if action == "evict":
            return {"pod": self._pod_of_instance(group)}
        if action == "scale-out":
            from edl_tpu.gateway.fleet import list_replicas
            live = len(list_replicas(self.store, self.job_id))
            return {"replicas": live + self._scale_step}
        if action == "bundle":
            from edl_tpu.obs import advert as obs_advert
            from edl_tpu.obs.bundle import bundle_dir_from_env
            return {"dir": bundle_dir_from_env(),
                    "targets": sorted(obs_advert.list_metrics_targets(
                        self.store, self.job_id))}
        return {}

    # -- the actions ---------------------------------------------------------
    def _execute(self, action: str, rule, group: str) -> tuple[str, dict]:
        if action == "restart":
            return self._act_restart(rule)
        if action == "evict":
            return self._act_evict(rule, group)
        if action == "scale-out":
            return self._act_scale_out(rule)
        if action == "bundle":
            if self._bundle_fn is None:
                return "noop", {"error": "no bundle capturer on this host"}
            return self._bundle_fn(rule, group)
        return "noop", {"error": f"unknown action {action!r}"}

    def _act_restart(self, rule) -> tuple[str, dict]:
        """trainer-hang: a SINGLE-pod job's trainers restart in place
        via the per-pod flag (kill + respawn, no membership change).
        A multi-pod job ALWAYS takes the coordinated hang flag — the
        pods share one collective world, and killing one pod's
        trainers unilaterally just crashes the peers with no
        membership change to recover through (cluster/heartbeat.py's
        invariant; the coordinated restart is one kill + instant
        re-barrier at the unchanged stage).  The stale-beat pods still
        ride the audit detail so the operator sees who was blamed."""
        from edl_tpu.cluster import heartbeat
        cluster = self._cluster()
        if cluster is None or not cluster.pods:
            return "noop", {"error": "no cluster record"}
        if len(cluster.pods) == 1:
            pod = cluster.pods[0].pod_id
            heartbeat.flag_pod_restart(self.store, self.job_id,
                                       cluster.stage, pod, reason=rule.name)
            return "ok", {"mode": "targeted", "pods": [pod],
                          "stage": cluster.stage}
        heartbeat.flag_hang(self.store, self.job_id, cluster.stage,
                            f"remediation:{rule.name}")
        return "ok", {"mode": "coordinated", "stage": cluster.stage,
                      "stale": self._stale_pods(cluster, rule.window)}

    def _act_evict(self, rule, group: str) -> tuple[str, dict]:
        """trainer-straggler: the slow pod leaves through the
        preemption-grace path, reason ``straggler-evict``."""
        from edl_tpu.cluster import preempt, scale
        pod_id = self._pod_of_instance(group)
        if pod_id is None:
            return "noop", {"error": f"no pod advert for group {group!r}"}
        cluster = self._cluster()
        if cluster is None or cluster.get_pod(pod_id) is None:
            return "noop", {"error": f"pod {pod_id[:8]} not in the cluster"}
        rng = scale.load_nodes_range(self.store, self.job_id)
        min_nodes = rng[0] if rng else 1
        if len(cluster.pods) - 1 < max(1, min_nodes):
            # the rail: healing must not starve the job below its floor
            return "no_capacity", {"pod": pod_id,
                                   "min_nodes": max(1, min_nodes)}
        preempt.flag_preempt(self.store, self.job_id, cluster.stage, pod_id,
                             reason="straggler-evict")
        return "ok", {"pod": pod_id, "stage": cluster.stage,
                      "reason": "straggler-evict"}

    def _act_scale_out(self, rule) -> tuple[str, dict]:
        """gateway SLO burn: ask the controller for one more serving
        replica via the demand record (the controller's autoscaler
        clamps to the job's nodes_range and scales back on quiet)."""
        from edl_tpu.cluster import scale
        from edl_tpu.gateway.fleet import list_replicas
        live = len(list_replicas(self.store, self.job_id))
        rng = scale.load_nodes_range(self.store, self.job_id)
        want = live + self._scale_step
        if rng is not None and want > rng[1]:
            want = rng[1]
        if want <= live:
            return "noop", {"replicas": live, "error": "already at max"}
        scale.save_demand(self.store, self.job_id, want, reason=rule.name)
        return "ok", {"replicas": want, "from": live}
