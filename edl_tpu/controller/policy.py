"""Autoscaling policy: cluster capacity -> per-job desired pod counts.

The reference controller's contract (k8s/edl_controller.yaml:21,
``-max_load_desired 0.9``; doc/usage.md): keep the cluster filled to at
most ``max_load_desired`` of its schedulable capacity, splitting the
budget fairly across running elastic jobs, each clamped to its own
``nodes_range``.  This module is the PURE half — no store, no k8s —
so the policy is unit-testable against fabricated job views.

Rules (reference behavior + the repo's own scaling gates):

- budget = floor(capacity * max_load_desired), at least one pod;
- fair share: each active job gets budget // n_jobs, remainder to the
  earliest jobs (stable by job_id) — the reference's fragment-avoiding
  fair division;
- clamp to [min_nodes, max_nodes] per job;
- a job whose train status is not scalable (NEARTHEEND — the
  anti-meaningless-scaling rule, train_status.py) keeps its current
  size;
- never scale a terminal (SUCCEED/FAILED) job — it leaves the view.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class JobView:
    """What the controller knows about one live job."""

    job_id: str
    min_nodes: int
    max_nodes: int
    current_nodes: int
    scalable: bool = True     # train status INITIAL/RUNNING (SCALABLE set)


def compute_desired(jobs: list[JobView], capacity: int,
                    max_load_desired: float = 0.9) -> dict[str, int]:
    """Desired pod count per job_id (only jobs whose target differs
    from ``current_nodes`` need acting on; all are returned)."""
    if not jobs:
        return {}
    budget = max(1, int(capacity * max_load_desired))
    out: dict[str, int] = {}
    # frozen (NEARTHEEND etc.) jobs keep their pods AND their pods keep
    # consuming the budget — otherwise total desired could exceed the
    # max_load_desired contract while a job finishes
    flexible = []
    for job in sorted(jobs, key=lambda j: j.job_id):
        if job.scalable:
            flexible.append(job)
        else:
            out[job.job_id] = job.current_nodes
            budget -= job.current_nodes
    if not flexible:
        return out
    base, rem = divmod(max(0, budget), len(flexible))
    for i, job in enumerate(flexible):
        share = base + (1 if i < rem else 0)
        out[job.job_id] = max(job.min_nodes, min(job.max_nodes, share))
    return out
