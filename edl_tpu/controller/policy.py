"""Arbitration policy: one cluster capacity pool -> per-job desired
pod counts across HETEROGENEOUS job kinds.

The reference controller's contract (k8s/edl_controller.yaml:21,
``-max_load_desired 0.9``; doc/usage.md): keep the cluster filled to at
most ``max_load_desired`` of its schedulable capacity, splitting the
budget fairly across running elastic jobs, each clamped to its own
``nodes_range``.  This module is the PURE half — no store, no k8s —
so the policy is unit-testable against fabricated job views.

The multi-job extension (ROADMAP item 4 — elasticity as a *cluster
utilization* story): training jobs, the distill teacher fleet, and
serving replica fleets are arbitrated against ONE pool with

- **priorities** — surplus capacity is handed out by priority class
  (serving > distill > training by default, ``JobView.priority``);
  a higher class's demand squeezes lower classes down toward their
  floors — training yields chips to serving under traffic and
  reclaims them when the demand signal decays;
- **floors** — every job's ``min_nodes`` comes off the top before any
  surplus is split, so no job ever starves (a floor is granted even
  over budget, the original single-job rule);
- **gang scheduling** — a ``gang=True`` job is placed atomically: its
  floor is granted whole or the job gets exactly 0 — a partial gang
  is never stranded holding chips it cannot use;
- **demand caps** — a job with ``demand`` set (the serving autoscaler's
  replica target, controller/autoscale.py) takes surplus only up to
  that demand, leaving the rest for lower classes, instead of growing
  to its fair share of everything.

Rules retained from the single-kind policy:

- budget = floor(capacity * max_load_desired), at least one pod;
- within one priority class, fair division: each job gets
  class_budget // n, remainder first to jobs with PENDING pods (a
  registered-but-unplaced replica means the infra already scheduled
  the hardware — growing that job is a free join), then earliest by
  job_id;
- clamp to [min_nodes, max_nodes] per job;
- a job whose train status is not scalable (NEARTHEEND — the
  anti-meaningless-scaling rule, train_status.py) keeps its current
  size and its pods keep consuming the budget;
- never scale a terminal (SUCCEED/FAILED) job — it leaves the view.

The policy stays PURE: every observed signal (live pod counts, pending
replicas, autoscaler demand, measured resize cost) arrives in the
JobView / arguments; the controller does the observing.
"""

from __future__ import annotations

from dataclasses import dataclass

# default priority per job kind when the job's spec doesn't set one:
# serving fronts users (latency budget), the distill teacher fleet
# feeds students (throughput budget), training absorbs what's left —
# the paper's "training yields chips to serving" ordering
KIND_PRIORITY = {"serving": 100, "distill": 50, "training": 0}


@dataclass
class JobView:
    """What the controller knows about one live job."""

    job_id: str
    min_nodes: int
    max_nodes: int
    current_nodes: int
    scalable: bool = True     # train status INITIAL/RUNNING (SCALABLE set)
    # live resource adverts not (yet) in the cluster: replicas the
    # infra scheduled that the desired record hasn't admitted
    pending_pods: int = 0
    # last measured stop-resume cost in seconds (recovery records);
    # 0 = never measured.  The controller scales each job's resize
    # cooldown with this, so expensive-to-resize jobs flap less.
    resize_cost_s: float = 0.0
    # -- multi-job arbitration ------------------------------------------
    kind: str = "training"    # training | distill | serving
    priority: int = 0         # higher wins surplus capacity first
    gang: bool = False        # atomic placement: min_nodes or nothing
    # autoscaler replica target (serving): caps this job's surplus take
    # at clamp(demand, min, max); None = fair share of the class budget
    demand: int | None = None

    def cap(self) -> int:
        """Upper clamp for this job's grant."""
        if self.demand is None:
            return self.max_nodes
        return max(self.min_nodes, min(self.max_nodes, self.demand))


def compute_desired(jobs: list[JobView], capacity: int,
                    max_load_desired: float = 0.9) -> dict[str, int]:
    """Desired pod count per job_id (only jobs whose target differs
    from ``current_nodes`` need acting on; all are returned)."""
    if not jobs:
        return {}
    budget = max(1, int(capacity * max_load_desired))
    out: dict[str, int] = {}
    # frozen (NEARTHEEND etc.) jobs keep their pods AND their pods keep
    # consuming the budget — otherwise total desired could exceed the
    # max_load_desired contract while a job finishes
    flexible = []
    for job in sorted(jobs, key=lambda j: j.job_id):
        if job.scalable:
            flexible.append(job)
        else:
            out[job.job_id] = job.current_nodes
            budget -= job.current_nodes
    if not flexible:
        return out
    budget = max(0, budget)

    # pass 1 — floors, highest priority first: min_nodes comes off the
    # top so no job starves.  A gang job whose whole floor no longer
    # fits is granted exactly 0 (all-or-nothing — never a partial gang
    # stranding chips); a non-gang floor is sacred even over budget
    # (the original single-job rule: the job's own min wins).
    floor: dict[str, int] = {}
    for job in sorted(flexible, key=lambda j: (-j.priority, j.job_id)):
        if job.gang and job.min_nodes > budget:
            floor[job.job_id] = 0
            out[job.job_id] = 0
            continue
        floor[job.job_id] = job.min_nodes
        budget -= job.min_nodes
    budget = max(0, budget)

    # pass 2 — surplus by priority class, highest first; within a class
    # the fair division: class_budget // n each, remainder first to
    # jobs with pending replicas (free join), then earliest; stable.
    classes: dict[int, list[JobView]] = {}
    for job in flexible:
        if job.gang and floor[job.job_id] == 0:
            continue  # denied gang: granted exactly 0, takes no surplus
        classes.setdefault(job.priority, []).append(job)
    for prio in sorted(classes, reverse=True):
        members = classes[prio]          # job_id-sorted (flexible is)
        headroom = sum(max(0, j.cap() - floor[j.job_id]) for j in members)
        take = min(budget, headroom)
        budget -= take
        class_budget = sum(floor[j.job_id] for j in members) + take
        base, rem = divmod(class_budget, len(members))
        order = sorted(range(len(members)),
                       key=lambda i: (0 if members[i].pending_pods > 0
                                      else 1, i))
        gets_extra = set(order[:rem])
        for i, job in enumerate(members):
            share = base + (1 if i in gets_extra else 0)
            out[job.job_id] = max(floor[job.job_id], min(job.cap(), share))
        # waterfill the remainder: a member clamped down by its demand
        # cap must not strand capacity its classmates still have
        # headroom for (slots a serving job stopped asking for belong
        # to whoever can use them, in-class first, lower classes next)
        leftover = class_budget - sum(out[j.job_id] for j in members)
        while leftover > 0:
            takers = [members[i] for i in order
                      if out[members[i].job_id] < members[i].cap()]
            if not takers:
                break
            for job in takers:
                if leftover <= 0:
                    break
                out[job.job_id] += 1
                leftover -= 1
        budget += max(0, leftover)       # truly unusable: next class's
    return out
