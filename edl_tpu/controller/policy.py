"""Autoscaling policy: cluster capacity -> per-job desired pod counts.

The reference controller's contract (k8s/edl_controller.yaml:21,
``-max_load_desired 0.9``; doc/usage.md): keep the cluster filled to at
most ``max_load_desired`` of its schedulable capacity, splitting the
budget fairly across running elastic jobs, each clamped to its own
``nodes_range``.  This module is the PURE half — no store, no k8s —
so the policy is unit-testable against fabricated job views.

Rules (reference behavior + the repo's own scaling gates):

- budget = floor(capacity * max_load_desired), at least one pod;
- fair share: each active job gets budget // n_jobs, remainder first
  to jobs with PENDING pods (a registered-but-unplaced replica means
  the infra already scheduled the hardware — growing that job is a
  free join, no actuator round-trip), then earliest by job_id — the
  reference's fragment-avoiding fair division, load-informed;
- clamp to [min_nodes, max_nodes] per job;
- a job whose train status is not scalable (NEARTHEEND — the
  anti-meaningless-scaling rule, train_status.py) keeps its current
  size;
- never scale a terminal (SUCCEED/FAILED) job — it leaves the view.

The policy stays PURE: every observed signal (live pod counts, pending
replicas, measured resize cost) arrives in the JobView / arguments;
the controller does the observing.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class JobView:
    """What the controller knows about one live job."""

    job_id: str
    min_nodes: int
    max_nodes: int
    current_nodes: int
    scalable: bool = True     # train status INITIAL/RUNNING (SCALABLE set)
    # live resource adverts not (yet) in the cluster: replicas the
    # infra scheduled that the desired record hasn't admitted
    pending_pods: int = 0
    # last measured stop-resume cost in seconds (recovery records);
    # 0 = never measured.  The controller scales each job's resize
    # cooldown with this, so expensive-to-resize jobs flap less.
    resize_cost_s: float = 0.0


def compute_desired(jobs: list[JobView], capacity: int,
                    max_load_desired: float = 0.9) -> dict[str, int]:
    """Desired pod count per job_id (only jobs whose target differs
    from ``current_nodes`` need acting on; all are returned)."""
    if not jobs:
        return {}
    budget = max(1, int(capacity * max_load_desired))
    out: dict[str, int] = {}
    # frozen (NEARTHEEND etc.) jobs keep their pods AND their pods keep
    # consuming the budget — otherwise total desired could exceed the
    # max_load_desired contract while a job finishes
    flexible = []
    for job in sorted(jobs, key=lambda j: j.job_id):
        if job.scalable:
            flexible.append(job)
        else:
            out[job.job_id] = job.current_nodes
            budget -= job.current_nodes
    if not flexible:
        return out
    base, rem = divmod(max(0, budget), len(flexible))
    # remainder pods go first to jobs that already have a pending
    # replica registered (free join: the hardware is up and waiting),
    # then earliest job_id; stable within each class
    order = sorted(range(len(flexible)),
                   key=lambda i: (0 if flexible[i].pending_pods > 0 else 1, i))
    gets_extra = set(order[:rem])
    for i, job in enumerate(flexible):
        share = base + (1 if i in gets_extra else 0)
        out[job.job_id] = max(job.min_nodes, min(job.max_nodes, share))
    return out
