"""On-demand g++ build of the native library.

Replaces the reference's cmake-driven native deps (CMakeLists.txt,
scripts/build.sh) with a zero-config build: first use compiles
``csrc/*.cc`` into ``build/libedl_native.so``; failures degrade to the
pure-Python fallbacks rather than breaking the import.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_ROOT, "csrc")
_OUT = os.path.join(_ROOT, "build", "libedl_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed = False


# standalone binaries (own main()), not part of the shared library
_STANDALONE = {"coordd.cc"}

# sources with extra link deps, dropped (with their flags) when the dep
# is missing on the host — the library still builds without them and
# the Python wrappers fall back (imagedec -> cv2 path)
_OPTIONAL = {"imagedec.cc": ["-ljpeg"]}


def _sources() -> list[str]:
    if not os.path.isdir(_SRC_DIR):
        return []
    return sorted(os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
                  if f.endswith(".cc") and f not in _STANDALONE)


def _stale(sources: list[str]) -> bool:
    if not os.path.exists(_OUT):
        return True
    out_mtime = os.path.getmtime(_OUT)
    return any(os.path.getmtime(s) > out_mtime for s in sources)


def ensure_built() -> ctypes.CDLL | None:
    """Compile (if stale) and dlopen the native library; None if the
    toolchain or sources are unavailable."""
    global _lib, _failed
    # edl-lint: disable=blocking-under-lock — once-only build gate:
    # serializing the compile subprocess is this lock's whole purpose
    with _lock:
        if _lib is not None or _failed:
            return _lib
        sources = _sources()
        if not sources:
            _failed = True
            return None
        try:
            if _stale(sources):
                extra = sorted({f for s in sources
                                for f in _OPTIONAL.get(os.path.basename(s),
                                                       [])})
                try:
                    _compile(["-O3", "-shared", "-fPIC", *sources, *extra],
                             _OUT)
                except subprocess.CalledProcessError as e:
                    # retry without the optional sources (missing dep,
                    # e.g. no libjpeg): the core library must still build
                    core = [s for s in sources
                            if os.path.basename(s) not in _OPTIONAL]
                    if core == sources:
                        raise
                    logger.warning(
                        "optional native sources dropped (%s); %s",
                        ", ".join(sorted(_OPTIONAL)),
                        (getattr(e, "stderr", "") or str(e)).strip()[:300])
                    _compile(["-O3", "-shared", "-fPIC", *core], _OUT)
            _lib = ctypes.CDLL(_OUT)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("native build unavailable (%s); using Python "
                           "fallbacks", detail.strip()[:500])
            _failed = True
        return _lib


def _compile(flags: list[str], out: str) -> None:
    """g++ to a process-unique tmp then atomic rename: concurrent
    builders (launcher subprocesses) must never tear the output."""
    os.makedirs(os.path.dirname(out), exist_ok=True)
    tmp = f"{out}.tmp.{os.getpid()}"
    cmd = ["g++", "-std=c++17", "-pthread", *flags, "-o", tmp]
    logger.info("building native: %s", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def native_available() -> bool:
    return ensure_built() is not None


def ensure_coordd() -> str | None:
    """Compile (if stale) the native coordination daemon
    (csrc/coordd.cc); returns the binary path or None if the toolchain
    is unavailable."""
    src = os.path.join(_SRC_DIR, "coordd.cc")
    out = os.path.join(_ROOT, "build", "coordd")
    if not os.path.exists(src):
        return None
    # edl-lint: disable=blocking-under-lock — same build gate: one
    # compile at a time is the point
    with _lock:
        try:
            if (not os.path.exists(out)
                    or os.path.getmtime(src) > os.path.getmtime(out)):
                _compile(["-O2", src], out)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("coordd build failed: %s", detail.strip()[:500])
            return None
    return out
