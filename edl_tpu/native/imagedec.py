"""Native JPEG decode + augment bindings (csrc/imagedec.cc).

One call decodes a WHOLE batch of recordio samples with a C++ thread
pool (libjpeg with DCT-domain downscaling) — no Python per record, no
GIL.  Falls back to None when the host has no libjpeg (the cv2 path in
edl_tpu/data/images.py remains the reference implementation; output
format is identical: uint8 BGR [n, size, size, 3] + int32 labels).
"""

from __future__ import annotations

import ctypes

import numpy as np

from edl_tpu.native.build import ensure_built


def available() -> bool:
    lib = ensure_built()
    return lib is not None and hasattr(lib, "edl_imgdec_batch")


def decode_batch(records: list[bytes], size: int, *, seed: int = 0,
                 train: bool = True, threads: int = 8,
                 ) -> tuple[np.ndarray, np.ndarray, int]:
    """Decode+augment ``records`` -> (images u8 BGR [n,s,s,3],
    labels i32 [n], failed_count).  Failed records have zero images and
    label -1 (mirrors the C side).  Raises RuntimeError when the native
    library is unavailable — call :func:`available` first."""
    lib = ensure_built()
    if lib is None or not hasattr(lib, "edl_imgdec_batch"):
        raise RuntimeError("native imagedec unavailable (no libjpeg?)")
    n = len(records)
    imgs = np.empty((n, size, size, 3), np.uint8)
    labels = np.empty((n,), np.int32)
    if n == 0:
        return imgs, labels, 0
    bufs = (ctypes.c_char_p * n)(*records)
    lens = np.asarray([len(r) for r in records], np.int64)
    fn = lib.edl_imgdec_batch
    fn.restype = ctypes.c_int
    failed = fn(
        ctypes.cast(bufs, ctypes.POINTER(ctypes.c_char_p)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int(n), ctypes.c_int(size),
        ctypes.c_uint64(np.uint64(seed & (2**64 - 1))),
        ctypes.c_int(1 if train else 0), ctypes.c_int(threads),
        imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return imgs, labels, int(failed)
