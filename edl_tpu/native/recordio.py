"""Record IO bindings: CRC-checked record files + shuffle reader.

C++ implementation in csrc/recordio.cc; the pure-Python classes here
implement the identical on-disk format (zlib.crc32 == the C++ IEEE
crc32), so files are interchangeable and the test suite cross-checks
both.  ``use_native=None`` auto-selects.
"""

from __future__ import annotations

import ctypes
import os
import random
import struct
import threading
import zlib
from collections import deque
from typing import Iterator

from edl_tpu.native.build import ensure_built

MAGIC = b"EDLR"
VERSION = 1
_HDR = struct.Struct("<II")  # len, crc


def _want_native(use_native: bool | None) -> ctypes.CDLL | None:
    if use_native is False:
        return None
    lib = ensure_built()
    if lib is None and use_native is True:
        raise RuntimeError("native recordio requested but unavailable")
    return lib


# -- writer ------------------------------------------------------------------
class RecordWriter:
    def __init__(self, path: str, use_native: bool | None = None):
        self._lib = _want_native(use_native)
        if self._lib is not None:
            self._lib.edl_recordio_writer_open.restype = ctypes.c_void_p
            self._h = self._lib.edl_recordio_writer_open(path.encode())
            if not self._h:
                raise OSError(f"cannot open {path}")
            self._f = None
        else:
            self._f = open(path, "wb")
            self._f.write(MAGIC + struct.pack("<I", VERSION))

    def write(self, payload: bytes) -> None:
        if self._f is None:
            rc = self._lib.edl_recordio_write(
                ctypes.c_void_p(self._h),
                (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload),
                len(payload))
            if rc != 0:
                raise OSError("native record write failed")
        else:
            self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
            self._f.write(payload)

    def close(self) -> None:
        if self._f is None:
            self._lib.edl_recordio_writer_close(ctypes.c_void_p(self._h))
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: list[bytes],
                  use_native: bool | None = None) -> None:
    with RecordWriter(path, use_native) as w:
        for r in records:
            w.write(r)


# -- sequential reader -------------------------------------------------------
class RecordReader:
    def __init__(self, path: str, use_native: bool | None = None):
        self._lib = _want_native(use_native)
        self._path = path
        if self._lib is not None:
            self._lib.edl_recordio_reader_open.restype = ctypes.c_void_p
            self._lib.edl_recordio_read.restype = ctypes.c_int64
            self._h = self._lib.edl_recordio_reader_open(path.encode())
            if not self._h:
                raise OSError(f"cannot open/parse {path}")
            self._f = None
        else:
            self._f = open(path, "rb")
            head = self._f.read(8)
            if head[:4] != MAGIC:
                self._f.close()
                raise OSError(f"bad magic in {path}")

    def __iter__(self) -> Iterator[bytes]:
        if self._f is None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = self._lib.edl_recordio_read(ctypes.c_void_p(self._h),
                                                ctypes.byref(out))
                if n == -1:
                    return
                if n < 0:
                    raise OSError(f"corrupt record file {self._path}")
                yield ctypes.string_at(out, n)
        else:
            while True:
                hdr = self._f.read(_HDR.size)
                if not hdr:
                    return
                length, crc = _HDR.unpack(hdr)
                payload = self._f.read(length)
                if len(payload) != length or zlib.crc32(payload) != crc:
                    raise OSError(f"corrupt record file {self._path}")
                yield payload

    def close(self) -> None:
        if self._f is None:
            self._lib.edl_recordio_reader_close(ctypes.c_void_p(self._h))
        else:
            self._f.close()


# -- shuffle reader ----------------------------------------------------------
class ShuffleReader:
    """Uniform sampling from a bounded look-ahead window over many
    record files; the native version reads and CRC-checks on a C++
    thread (no GIL in the hot loop)."""

    def __init__(self, paths: list[str], buffer_size: int = 1024,
                 seed: int = 0, use_native: bool | None = None):
        self._lib = _want_native(use_native)
        self._paths = list(paths)
        self._buffer_size = buffer_size
        self._seed = seed
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
            self._lib.edl_shuffle_reader_open.restype = ctypes.c_void_p
            self._lib.edl_shuffle_reader_next.restype = ctypes.c_int64
            self._lib.edl_shuffle_reader_peek_len.restype = ctypes.c_uint64
            self._lib.edl_shuffle_reader_error.restype = ctypes.c_char_p
            self._h = self._lib.edl_shuffle_reader_open(
                arr, len(paths), buffer_size, seed)
            self._cap = 1 << 16
            self._buf = ctypes.create_string_buffer(self._cap)

    def __iter__(self) -> Iterator[bytes]:
        if self._lib is not None:
            yield from self._iter_native()
        else:
            yield from self._iter_python()

    def _iter_native(self) -> Iterator[bytes]:
        while True:
            n = self._lib.edl_shuffle_reader_next(
                ctypes.c_void_p(self._h), ctypes.cast(
                    self._buf, ctypes.POINTER(ctypes.c_uint8)), self._cap)
            if n == -3:  # grow to the largest buffered record
                need = self._lib.edl_shuffle_reader_peek_len(
                    ctypes.c_void_p(self._h))
                self._cap = max(self._cap * 2, int(need) + 1)
                self._buf = ctypes.create_string_buffer(self._cap)
                continue
            if n == -1:
                return
            if n == -2:
                err = self._lib.edl_shuffle_reader_error(
                    ctypes.c_void_p(self._h)).decode()
                raise OSError(f"shuffle reader failed: {err}")
            yield ctypes.string_at(self._buf, n)  # copies n bytes, not _cap

    def _iter_python(self) -> Iterator[bytes]:
        rng = random.Random(self._seed)
        window: deque[bytes] = deque()
        for path in self._paths:
            reader = RecordReader(path, use_native=False)
            try:
                for rec in reader:
                    window.append(rec)
                    if len(window) >= self._buffer_size:
                        idx = rng.randrange(len(window))
                        window[idx], window[-1] = window[-1], window[idx]
                        yield window.pop()
            finally:
                reader.close()
        while window:
            idx = rng.randrange(len(window))
            window[idx], window[-1] = window[-1], window[idx]
            yield window.pop()

    def close(self) -> None:
        if self._lib is not None and self._h:
            self._lib.edl_shuffle_reader_close(ctypes.c_void_p(self._h))
            self._h = None
