"""Native (C++) runtime components and their Python bindings.

The reference's native capabilities were all external (NCCL, DALI,
bRPC, etcd — SURVEY.md §0).  This package holds the in-tree native
layer: ``csrc/`` C++ built on demand with g++ (no pybind11 in the
image; bindings are ctypes over a C ABI), with pure-Python fallbacks so
every feature works unbuilt and the formats stay bit-identical between
the two implementations.
"""

from edl_tpu.native.build import ensure_built, native_available
from edl_tpu.native.recordio import (
    RecordReader, RecordWriter, ShuffleReader, write_records,
)

__all__ = ["ensure_built", "native_available", "RecordReader",
           "RecordWriter", "ShuffleReader", "write_records"]
