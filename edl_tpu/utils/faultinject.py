"""Env-driven fault injection for the control plane.

Chaos testing needs failures that are *systematic*, not hand-rolled per
test: one spec grammar, hook points on both RPC ends, and a counter so
a run can prove its faults actually fired.  Enable with::

    EDL_TPU_FAULTS="kv_put:error:0.3;connect:delay:1.5"

Grammar — semicolon-separated rules, each ``point:action:arg[:prob]``
with an optional ``client:``/``server:`` side prefix on the point:

- **point** — the RPC wire method (``kv_put``, ``lease_keepalive``,
  ``cache_fetch`` …), the transport pseudo-point ``connect`` (dialing a
  TCP connection), or ``*`` (every point).
- **action** ``error`` — raise :class:`EdlCoordError` (a transport-class
  retryable failure) with probability ``arg``.
- **action** ``delay`` — sleep ``arg`` seconds, with probability
  ``prob`` (default 1.0) — models slow disks/links without killing the
  call.
- side prefix — ``client:kv_put`` fires only in
  :mod:`edl_tpu.rpc.client` (before the request leaves),
  ``server:kv_put`` only in the handler loop; a bare point fires on
  both sides of whichever process carries the env var.

``EDL_TPU_FAULTS_SEED`` pins the RNG so a chaos run is reproducible.
``fire()`` is called on every RPC; with no spec configured it is one
falsy check — the hot path pays nothing.

Injected errors surface as ``EdlCoordError`` precisely because that is
the transport-failure type the whole retry stack keys on
(``retry_until_timeout``, ``ResilientCoordClient``, the gateway's
failover): a chaos run exercises the SAME healing code a real outage
does.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.exceptions import EdlCoordError

_INJECTED = obs_metrics.counter(
    "edl_faults_injected_total",
    "Faults injected by utils/faultinject.py, by point and action",
    ("point", "action"))

_SIDES = ("client", "server")


@dataclass(frozen=True)
class Rule:
    point: str              # method name, "connect", or "*"
    side: str | None        # "client" | "server" | None (both)
    action: str             # "error" | "delay"
    arg: float              # error: probability; delay: seconds
    prob: float             # delay only: firing probability

    def matches(self, point: str, side: str) -> bool:
        return (self.point in ("*", point)
                and (self.side is None or self.side == side))


class FaultSpecError(ValueError):
    pass


def parse(spec: str) -> list[Rule]:
    rules: list[Rule] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        fields = raw.split(":")
        side = None
        if fields and fields[0] in _SIDES:
            side = fields[0]
            fields = fields[1:]
        if len(fields) not in (3, 4):
            raise FaultSpecError(
                f"bad fault rule {raw!r}: want [side:]point:action:arg[:prob]")
        point, action = fields[0], fields[1]
        try:
            arg = float(fields[2])
            prob = float(fields[3]) if len(fields) == 4 else 1.0
        except ValueError as e:
            raise FaultSpecError(f"bad fault rule {raw!r}: {e}") from e
        if action == "error":
            if len(fields) == 4:
                raise FaultSpecError(
                    f"bad fault rule {raw!r}: error takes ONE number — "
                    f"its probability (point:error:prob)")
            prob, arg = arg, 0.0  # error's arg IS its probability
        elif action != "delay":
            raise FaultSpecError(
                f"bad fault rule {raw!r}: unknown action {action!r}")
        if not 0.0 <= prob <= 1.0:
            raise FaultSpecError(f"bad fault rule {raw!r}: prob {prob}")
        rules.append(Rule(point, side, action, arg, prob))
    return rules


_rules: list[Rule] = []
_rng = random.Random()


def configure(spec: str | None, seed: int | None = None) -> list[Rule]:
    """(Re)load the active rule set; tests call this directly, normal
    processes get it from the env at import."""
    global _rules, _rng
    _rules = parse(spec) if spec else []
    _rng = random.Random(seed)
    return _rules


def active() -> bool:
    return bool(_rules)


def fire(point: str, side: str = "client") -> None:
    """Hook point: maybe delay, maybe raise.  Called per RPC on both
    ends (rpc/client.py before the request leaves and around connect;
    rpc/server.py around the handler)."""
    if not _rules:
        return
    for rule in _rules:
        if not rule.matches(point, side):
            continue
        if rule.prob < 1.0 and _rng.random() >= rule.prob:
            continue
        _INJECTED.labels(point=point, action=rule.action).inc()
        if rule.action == "delay":
            time.sleep(rule.arg)
        else:
            raise EdlCoordError(
                f"injected fault ({side}:{point}, EDL_TPU_FAULTS)")


_seed = os.environ.get("EDL_TPU_FAULTS_SEED")
configure(os.environ.get("EDL_TPU_FAULTS"),
          int(_seed) if _seed else None)
del _seed
