"""Half-open record-span arithmetic shared by the data checkpoint
(cluster/state.py) and the data service work queue (data/data_server.py).
"""

from __future__ import annotations


def merge_span(spans: list[list[int]], begin: int, end: int) -> None:
    """Insert [begin,end) into a list of disjoint [b,e) spans, merging
    overlaps/adjacency in place; keeps the list sorted."""
    if end <= begin:
        return
    out: list[list[int]] = []
    for b, e in spans:
        if e < begin or b > end:  # strictly disjoint, not even adjacent
            out.append([b, e])
        else:  # overlapping or adjacent: absorb into the new span
            begin = min(begin, b)
            end = max(end, e)
    out.append([begin, end])
    out.sort()
    spans[:] = out


def in_spans(spans: list[list[int]], record_no: int) -> bool:
    return any(b <= record_no < e for b, e in spans)


def intersect_spans(a: list[list[int]], b: list[list[int]]) -> list[list[int]]:
    """Merged intersection of two span lists (each need not be sorted
    or disjoint)."""
    am: list[list[int]] = []
    for begin, end in a:
        merge_span(am, begin, end)
    bm: list[list[int]] = []
    for begin, end in b:
        merge_span(bm, begin, end)
    out: list[list[int]] = []
    for ab, ae in am:
        for bb, be in bm:
            lo, hi = max(ab, bb), min(ae, be)
            if lo < hi:
                merge_span(out, lo, hi)
    return out
