"""Version shims for jax API drift.

The repo targets the ``jax.shard_map`` public API (jax >= 0.6, per
pyproject), but deployment images pin older runtimes where shard_map
still lives in ``jax.experimental`` with the pre-rename kwargs
(``check_rep``; manual-axes via ``auto=`` complement instead of
``axis_names=``).  One shim so kernels never branch on version.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` when available, else the experimental one with
    the kwargs translated (check_vma -> check_rep; axis_names -> the
    complementary ``auto`` set)."""
    import jax

    if hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    # NB: no ``auto=`` translation for axis_names — the old partial-
    # automatic mode is broken on SPMD backends ("PartitionId ... not
    # supported"), so the fallback runs FULL manual: axes the caller
    # wanted automatic see replicated specs (P() entries), trading
    # their data parallelism for redundant compute on old runtimes.
    # Correct either way; the parity tests pin that down.
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
