"""Backend liveness probe for driver artifacts.

VERDICT r5 headline: a wedged TPU runtime turned ``jax.devices()`` into
an in-process hang, so the driver's artifacts (``__graft_entry__.py``,
``edl_tpu/bench.py``) died rc=124 with NOTHING emitted.  The first
``jax.devices()`` call initializes the backend irreversibly in-process,
so the only safe probe is a SUBPROCESS with a timeout: if the child
hangs or errors, this process pins ``JAX_PLATFORMS=cpu`` *before* its
own first jax touch and the artifact still runs (virtual CPU mesh) and
still emits parseable output.
"""

from __future__ import annotations

import os
import subprocess
import sys

PROBE_TIMEOUT = float(os.environ.get("EDL_TPU_BACKEND_PROBE_TIMEOUT", 60.0))

_PROBE_CODE = "import jax; print(len(jax.devices()))"


def probe_backend(timeout_s: float | None = None) -> int | None:
    """Device count per ``jax.devices()`` in a fresh subprocess, or
    None when the backend hangs past ``timeout_s`` or errors out."""
    timeout_s = PROBE_TIMEOUT if timeout_s is None else timeout_s
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=dict(os.environ))
    except (subprocess.TimeoutExpired, OSError):
        return None
    if r.returncode != 0:
        return None
    try:
        return int(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None


def devices_or_cpu():
    """The caller's FIRST in-process backend touch, hardened.  The
    subprocess probe (:func:`ensure_live_backend`) catches hangs, but a
    backend can probe alive in a fresh child and still fail to
    *initialize* in this process (BENCH_r05: ``RuntimeError: Unable to
    initialize backend`` at exactly ``jax.devices()``, rc=1, no
    artifact) — catch the init error (``jax.errors.JaxRuntimeError``
    subclasses RuntimeError), pin the CPU platform through BOTH the env
    var and the live config, and retry so artifact-emitting entry
    points (bench.py, serving_perf_smoke.py) always ship their one
    JSON line."""
    import jax
    try:
        return jax.devices()
    except RuntimeError as e:
        print(f"backend init failed ({type(e).__name__}: {e}); "
              f"falling back to JAX_PLATFORMS=cpu", file=sys.stderr,
              flush=True)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()


def ensure_live_backend(timeout_s: float | None = None) -> int | None:
    """Probe; on hang/error force the CPU platform for THIS process so
    the caller's subsequent jax init cannot wedge.  Returns the probed
    device count (None = fell back).  Must run before jax initializes.
    """
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the CPU platform cannot hang at init: skip the probe (it
        # cold-starts a whole jax subprocess) — None = count unknown
        return None
    n = probe_backend(timeout_s)
    if n is None:
        print("backend probe hung or errored; falling back to "
              "JAX_PLATFORMS=cpu", file=sys.stderr, flush=True)
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "jax" in sys.modules:
            # jax already imported (backend not yet initialized): the
            # env var alone can lose to sitecustomize plugin side
            # effects — pin through the config too, like the trainer
            # bootstrap's force_platform_from_env
            sys.modules["jax"].config.update("jax_platforms", "cpu")
    return n
