"""Typed error hierarchy that survives RPC boundaries.

Reference behavior (python/edl/utils/exceptions.py:20-117): servers
serialize the exception *class name* plus detail into the response
status; clients re-raise the same typed exception.  We keep that
contract — an error raised inside a remote servicer arrives at the
caller as the same Python type — but serialize to a plain dict carried
in the RPC envelope instead of a proto ``Status``.
"""

from __future__ import annotations

import re
import traceback


class EdlError(Exception):
    """Base class for all framework errors."""


class EdlRetryableError(EdlError):
    """Base for errors that callers may retry (transient cluster states)."""


# -- coordination / cluster lifecycle ---------------------------------------
class EdlCoordError(EdlRetryableError):
    """Coordination-store communication failed."""


class EdlBarrierError(EdlRetryableError):
    """Barrier not yet complete (some stage members missing)."""


class EdlLeaderChangedError(EdlRetryableError):
    """The leader lost its seat mid-operation."""


class EdlTableError(EdlRetryableError):
    """A coordination-store table is missing or malformed."""


class EdlRegisterError(EdlRetryableError):
    """TTL-leased registration could not be established/refreshed."""


class EdlDescaledError(EdlError):
    """This pod is surplus to the controller's desired size: the cluster
    is at/over the desired-nodes record without it.  Not retryable —
    the launcher exits cleanly (DESCALED)."""


class EdlStopIteration(EdlError):
    """Remote signals end-of-data (maps to StopIteration client-side)."""


# -- serving gateway --------------------------------------------------------
class EdlOverloadedError(EdlRetryableError):
    """Admission control rejected the request (queue full / rate limit /
    no live replicas).  Carries ``retry_after`` seconds; since only the
    (type, detail) pair crosses the RPC wire, the constructor recovers
    it from a ``retry_after=N`` token in the detail string, so gateways
    embed it there and remote callers still see the backoff hint."""

    def __init__(self, detail: str = "", retry_after: float | None = None):
        super().__init__(detail)
        if retry_after is None:
            m = re.search(r"retry_after=([0-9.]+)", detail)
            retry_after = float(m.group(1)) if m else 1.0
        self.retry_after = float(retry_after)


class EdlUnavailableError(EdlRetryableError):
    """This server cannot take or finish the work (draining, stopped
    mid-generation) — try another replica or retry later."""


# -- data plane -------------------------------------------------------------
class EdlDataError(EdlRetryableError):
    """Data-server state not ready (e.g. balanced metas not computed)."""


class EdlReaderGoneError(EdlTableError):
    """The addressed DataService has no state for this reader
    generation (a successor leader with no/torn journal, or the
    generation was GC'd).  Readers REATTACH — re-seed the generation
    from their own checkpoint + claimed spans — instead of plain
    retrying; a retry alone would loop on the same answer."""


class EdlStreamError(EdlError):
    """Streamed-response protocol violation (sequence gap/duplicate,
    short stream, or a non-streaming answer where frames were
    expected).  NOT retryable on the same connection — the two ends
    have desynchronized and the transport must be torn down; callers
    that hold alternatives (another holder of the same shard) may
    retry there."""


class EdlFileListNotMatchError(EdlError):
    """Pod's file-list slice doesn't match the checkpointed one."""


# -- hard failures ----------------------------------------------------------
class EdlInternalError(EdlError):
    """Unexpected server-side failure (carries remote traceback)."""


class EdlUnauthorizedError(EdlError):
    """Token mismatch on a discovery register call."""


_REGISTRY = {
    cls.__name__: cls
    for cls in (
        EdlError,
        EdlRetryableError,
        EdlCoordError,
        EdlBarrierError,
        EdlLeaderChangedError,
        EdlTableError,
        EdlRegisterError,
        EdlOverloadedError,
        EdlUnavailableError,
        EdlStopIteration,
        EdlDataError,
        EdlReaderGoneError,
        EdlStreamError,
        EdlFileListNotMatchError,
        EdlInternalError,
        EdlUnauthorizedError,
    )
}


def serialize(exc: BaseException) -> dict:
    """Exception → wire dict (mirrors exceptions.py:95-106 serialize)."""
    if isinstance(exc, EdlError):
        return {"type": type(exc).__name__, "detail": str(exc)}
    return {
        "type": "EdlInternalError",
        "detail": "".join(traceback.format_exception(exc)),
    }


def deserialize(status: dict | None) -> None:
    """Wire dict → raise typed exception; no-op on OK (exceptions.py:108-117)."""
    if not status:
        return
    cls = _REGISTRY.get(status.get("type", ""), EdlInternalError)
    raise cls(status.get("detail", ""))
