"""Coordination-store table names and timing constants.

Reference: python/edl/utils/constants.py:15-39.  The table set is the
same contract: a job's coordination state lives under
``/edl_tpu/<job_id>/<table>/...``.
"""

# tables (key prefixes under the job root)
ETCD_POD_RESOURCE = "resource"      # live pod adverts (TTL-leased)
ETCD_POD_RANK = "rank"              # leader seat lives at rank/0
ETCD_POD_STATUS = "pod_status"      # per-pod Status
ETCD_JOB_STATUS = "job_status"      # singleton job flag
ETCD_TRAIN_STATUS = "train_status"  # per-pod TrainStatus
ETCD_CLUSTER = "cluster"            # the generated Cluster JSON
ETCD_READER = "reader"              # distributed-reader registry
ETCD_STATE = "state"                # train State (data checkpoint etc.)
ETCD_DIST_READER = "dist_reader"
ETCD_RECOVERY = "recovery"          # per-stage resize timing records
ETCD_HEARTBEAT = "heartbeat"        # per-pod trainer liveness beats
ETCD_SCALE = "scale"                # controller desired-size + nodes_range
ETCD_MEMSTATE = "memstate"          # peer checkpoint-cache adverts + commit record
ETCD_SERVING = "serving"            # leased LM replica adverts (gateway fleet)
ETCD_OBS = "obs"                    # leased /metrics endpoint adverts (obs agg)
ETCD_RESHARD = "reshard"            # delta-resize handshake (flag/go/done/worldsvc)

ALL_TABLES = [
    ETCD_POD_RESOURCE,
    ETCD_POD_RANK,
    ETCD_POD_STATUS,
    ETCD_JOB_STATUS,
    ETCD_TRAIN_STATUS,
    ETCD_CLUSTER,
    ETCD_READER,
    ETCD_STATE,
    ETCD_DIST_READER,
    ETCD_RECOVERY,
    ETCD_HEARTBEAT,
    ETCD_SCALE,
    ETCD_MEMSTATE,
    ETCD_SERVING,
    ETCD_OBS,
    ETCD_RESHARD,
]

LEADER_KEY = "0"  # rank table key seized by the leader (leader_pod.py:57)

# key under which data-service batches carry their record spans from
# producer to the train loop, which marks them into the DataCheckpoint
# at consumption time (elastic_input.py <-> train/trainer.py)
DATA_SPANS_KEY = "__consumed_spans__"

# timing (reference constants.py:26 + register.py:59-68); every value is
# env-overridable so integration tests can run with sub-second TTLs the
# way the reference's tests ran a dedicated fast etcd
import os as _os


def _f(env: str, default: float) -> float:
    return float(_os.environ.get(env, default))


def env_float(env: str, default: float) -> float:
    """Runtime (not import-time) env float with a tolerant fallback: a
    malformed value reads as the default instead of raising — for
    knobs read lazily inside long-lived services (controller
    autoscaler/remediation), where one typo must not kill the loop."""
    try:
        return float(_os.environ.get(env, default))
    except ValueError:
        return default


ETCD_TTL = _f("EDL_TPU_TTL", 15)                  # registration lease TTL (s)
TTL_REFRESH_FRACTION = 0.5                        # refresh at ttl/2
GENERATOR_PERIOD = _f("EDL_TPU_GENERATOR_PERIOD", 3.0)
WATCHER_PERIOD = _f("EDL_TPU_WATCHER_PERIOD", 3.0)
SUPERVISOR_PERIOD = _f("EDL_TPU_SUPERVISOR_PERIOD", 3.0)
BARRIER_TIMEOUT_INIT = _f("EDL_TPU_BARRIER_TIMEOUT", 600.0)    # launcher.py:175
BARRIER_TIMEOUT_RESIZE = _f("EDL_TPU_RESIZE_BARRIER_TIMEOUT", 60.0)
# grace between a local trainer crash and failing the job, so collateral
# crashes from a peer pod's death can resolve into a membership change
# instead; -1 = auto (ttl + generator + watcher slack)
FAIL_GRACE = _f("EDL_TPU_FAIL_GRACE", -1.0)
# cap on the leader's wait for member pods' final statuses before it
# writes the job flag from what it sees (launcher._leader_final_verdict)
VERDICT_TIMEOUT = _f("EDL_TPU_VERDICT_TIMEOUT", 600.0)
# hang watchdog: the launcher restarts its trainers when the pod's
# trainer heartbeat (written per step by ElasticTrainer) goes stale.
# 0 (the default) = AUTO: the trainer publishes its own threshold,
# max(10 x EMA step time, 120 s), with each beat — on by default, no
# tuning.  > 0 = explicit override in seconds (set it comfortably
# above the longest expected step; the trainer automatically beats at
# least 3x faster than the threshold, so the throttle can never
# outpace the watchdog).  < 0 = disabled entirely.  Single-pod:
# in-place trainer restart; multi-pod: a store flag coordinates a
# cluster-wide stop-resume (launcher._supervise + cluster/heartbeat.py).
HANG_TIMEOUT = _f("EDL_TPU_HANG_TIMEOUT", 0.0)
# max in-place trainer restarts per cluster stage before the pod gives
# up and fails (a trainer that hangs every time is not going to recover)
HANG_MAX_RESTARTS = int(_f("EDL_TPU_HANG_MAX_RESTARTS", 3))

# -- SIGTERM preemption grace (cluster/preempt.py) -----------------------
# exit code trainers use after a preemption-point checkpoint: tells the
# launcher "clean coordinated departure", not success and not a crash
PREEMPT_EXIT_CODE = 94
# trainers poll the preempt flag (and, multi-process, OR the sightings
# via allgather so the save step is agreed) at a step-aligned cadence.
# PREEMPT_CHECK_STEPS is the INITIAL cadence (the first check lands on
# a step multiple so every process enters the collective together);
# after that the cadence adapts so checks cost the hot loop one tiny
# collective roughly every PREEMPT_CHECK_SECONDS of wall time, however
# long a step takes (ADVICE r5: a fixed every-8-steps allgather taxed
# fast-step jobs and starved slow-step ones)
PREEMPT_CHECK_STEPS = int(_f("EDL_TPU_PREEMPT_CHECK_STEPS", 8))
PREEMPT_CHECK_SECONDS = _f("EDL_TPU_PREEMPT_CHECK_SECONDS", 2.0)
# how long the signalled launcher waits for its trainers to finish the
# preemption-point checkpoint before giving up and departing with
# whatever the last periodic checkpoint was.  NOTE the deployment
# coupling: the pod's terminationGracePeriodSeconds (k8s/train-job.yaml)
# must exceed this value, or the kubelet SIGKILLs the launcher before
# the grace path can run (doc/usage.md "Preemption grace").
PREEMPT_GRACE = _f("EDL_TPU_PREEMPT_GRACE", 120.0)

# -- coordination-store fault tolerance (coord/wal.py, coord/resilient.py) --
# WAL + snapshot directory for the Python coord server; empty = pure
# in-memory (a restart loses everything, the pre-WAL behavior)
COORD_DATA_DIR = _os.environ.get("EDL_TPU_COORD_DATA_DIR", "")
# cut a snapshot + truncate the WAL every N appended records
COORD_SNAPSHOT_EVERY = int(_f("EDL_TPU_COORD_SNAPSHOT_EVERY", 4096))
# after a WAL-backed restart, expiry sweeps stay suspended this long so
# holders can reconnect and refresh the restored leases before anything
# is mass-expired; -1 = auto (one registration TTL)
COORD_RESTART_GRACE = _f("EDL_TPU_COORD_RESTART_GRACE", -1.0)
# ResilientCoordClient: total retry budget per op (exponential backoff
# + jitter + endpoint failover inside it) before the EdlCoordError
# finally propagates; callers with tighter latency needs scope it down
# (heartbeat beats use scoped_deadline)
COORD_RETRY_DEADLINE = _f("EDL_TPU_COORD_RETRY_DEADLINE", 30.0)
COORD_BACKOFF_INIT = _f("EDL_TPU_COORD_BACKOFF_INIT", 0.05)
COORD_BACKOFF_MAX = _f("EDL_TPU_COORD_BACKOFF_MAX", 2.0)

# -- delta resize: live reshard instead of stop-resume (ISSUE 12) ----------
# 1 enables the delta-resize path: on a membership change, surviving
# trainer PROCESSES stay alive, the collective world re-forms in place
# (train/distributed.reform_world) and only the shards whose owner
# changed move over the streaming plane (memstate/reshard.py).  Any
# failure mid-reshard falls back to the proven stop-resume path.  ON
# by default since the ROADMAP item 3 burn-in (ISSUE 17);
# EDL_TPU_RESIZE_DELTA=0 is the documented opt-out back to pure
# stop-resume.
RESIZE_DELTA = int(_f("EDL_TPU_RESIZE_DELTA", 1))
# reshard barrier timeout: bounds BOTH the trainer's wait for the
# post-barrier "go" record + the re-formed world, and the launcher's
# wait for its trainers' reshard-done records; expiry on either side
# falls back to stop-resume
RESIZE_RESHARD_TIMEOUT = _f("EDL_TPU_RESIZE_RESHARD_TIMEOUT", 60.0)
# minimum fraction of cached checkpoint bytes that stay on surviving
# owners for delta to be attempted: below it, moving almost everything
# anyway, stop-resume (which overlaps the fetch with process respawn)
# is cheaper.  0 = always attempt delta when enabled
RESIZE_MIN_DELTA = _f("EDL_TPU_RESIZE_MIN_DELTA", 0.0)

# -- delta replication plane: sub-checkpoint-loss failover (ISSUE 17) ------
# stream optimizer/param-state DELTAS to the consistent-hash ring
# replica every N steps, off the critical path, so a crash loses at
# most N steps instead of a checkpoint interval (memstate/delta.py).
# 0 disables the plane entirely (no step hook, no chains); requires
# EDL_TPU_MEMSTATE=1 and a committed base checkpoint to be active
DELTA_EVERY = int(_f("EDL_TPU_DELTA_EVERY", 10))
# bound on delta records retained per chain in a cache service; when a
# chain grows past it the two OLDEST records merge (freshest bytes win,
# linkage preserved), so freshness keeps growing under a fixed RAM cap
DELTA_MAX_CHAIN = int(_f("EDL_TPU_DELTA_MAX_CHAIN", 64))

# -- first-class world-derived hyperparameter re-scale (ISSUE 17) ----------
# 1 wraps every trainer-built optimizer with a world-scale stage
# (train/lr.world_scaled) and linearly re-scales the effective LR with
# the global batch (new_world / old_world) on every resize — the
# reference's linear-scaling rule (state.py:142) without ad-hoc
# trainer.adjust hooks.  Off by default: it changes the opt_state
# pytree (one extra scalar leaf), so flipping it mid-job invalidates
# checkpoints taken without it
LR_RESCALE = int(_f("EDL_TPU_LR_RESCALE", 0))

# -- in-memory peer checkpoint cache (edl_tpu/memstate) -------------------
# 0 disables the cache entirely (saves are not teed, restores go
# straight to storage); on by default — the cache is best-effort and
# every miss falls back to the Orbax/storage path
MEMSTATE = int(_f("EDL_TPU_MEMSTATE", 1))
# per-RPC chunk size for multi-MB shard transfers (rpc/chunks.py)
MEMSTATE_CHUNK_BYTES = int(_f("EDL_TPU_MEMSTATE_CHUNK_BYTES", 4 << 20))
# cap on bytes a pod's cache service will hold (staged + committed);
# 0 = unlimited.  An over-cap push is REJECTED (the set never commits,
# restore sees a miss and falls back to storage) — RAM safety beats
# cache completeness
MEMSTATE_MAX_BYTES = int(_f("EDL_TPU_MEMSTATE_MAX_BYTES", 0))

# -- streaming data plane (rpc/client pool, rpc/transfer) ------------------
# connections per endpoint in an RpcChannelPool: bulk transfers occupy
# one channel each, so this bounds per-peer transfer parallelism
TRANSFER_CONNS = int(_f("EDL_TPU_TRANSFER_CONNS", 4))
# chunk requests in flight per channel on the pipelined/streaming paths
# (1 = the legacy one-chunk-per-round-trip behavior, bit-identical)
TRANSFER_WINDOW = int(_f("EDL_TPU_TRANSFER_WINDOW", 8))
# worker threads a restore/push fans distinct shards across
TRANSFER_WORKERS = int(_f("EDL_TPU_TRANSFER_WORKERS", 4))
# a single shard at least this large is STRIPED across all live holders
# (primary + ring replica) instead of fetched from one; smaller shards
# gain more from per-shard concurrency than from splitting
STRIPE_MIN_BYTES = int(_f("EDL_TPU_STRIPE_MIN_BYTES", 8 << 20))
# cap on fetched-but-not-yet-assembled restore bytes: leaves are
# fetched+assembled in batches of at most this many manifest bytes, so
# peak host RAM stays ~one batch above the assembled arrays instead of
# the process's whole checkpoint share.  0 = unlimited (one batch).
# A single leaf larger than the budget still fetches whole (floor).
TRANSFER_BUDGET_BYTES = int(_f("EDL_TPU_TRANSFER_BUDGET_BYTES", 1 << 30))

# -- data-plane fault tolerance (data/journal, data/resilient) -------------
# journal the leader DataService's generation state into the coord
# store (write-ahead) so a successor leader rebuilds live generations
# and readers reattach without restarting the epoch; 0 disables the
# journal — a successor then answers EdlReaderGoneError and live
# readers RE-SEED the generation from their own checkpoint + claimed
# spans (published-but-unfetched batches re-produce via the reattach
# position repair); only a reader that ALSO died still needs the full
# stop-resume-from-DataCheckpoint path
DATA_JOURNAL = int(_f("EDL_TPU_DATA_JOURNAL", 1))
# per-journal-op store budget: a write that can't land within this
# raises the retryable EdlCoordError to the reader (which retries), so
# the journal never silently falls behind what a reader observed
DATA_JOURNAL_BUDGET = _f("EDL_TPU_DATA_JOURNAL_BUDGET", 5.0)
# reader-side resilient data RPCs: total retry budget per leader call
# (backoff + full jitter + leader re-resolution inside it)
DATA_RETRY_DEADLINE = _f("EDL_TPU_DATA_RETRY_DEADLINE", 30.0)
DATA_BACKOFF_INIT = _f("EDL_TPU_DATA_BACKOFF_INIT", 0.05)
DATA_BACKOFF_MAX = _f("EDL_TPU_DATA_BACKOFF_MAX", 2.0)
# after a leader rebuild, parked (journal-recovered) batch metas and
# new file grants are held back this long so live readers can reattach
# and reclaim their in-flight work first — releasing earlier could
# hand a reattaching reader's unacked batch to a second consumer.
# Keep it >= DATA_RETRY_DEADLINE's typical blip recovery (readers
# reattach on their first post-failover call, normally within ~1 s)
DATA_REBUILD_GRACE = _f("EDL_TPU_DATA_REBUILD_GRACE", 5.0)

# -- streamed batch delivery + consumer prefetch (data/distribute_reader) --
# fetch worker threads per consumer: batch fetches from distinct
# producers run concurrently, so one dead producer costs the workers
# ONE fetch timeout in parallel instead of N in series
DATA_PREFETCH_WORKERS = int(_f("EDL_TPU_DATA_PREFETCH_WORKERS", 2))
# bound on batches fetched-or-in-flight ahead of the consumer loop —
# the prefetch backpressure: new metas are requested only below it, so
# a fast producer can never run the consumer's RAM (or the producers'
# caches) away from it
DATA_PREFETCH_DEPTH = int(_f("EDL_TPU_DATA_PREFETCH_DEPTH", 16))
# batch metas requested per leader round trip (DistributedReader's
# meta_prefetch default)
DATA_PREFETCH_META = int(_f("EDL_TPU_DATA_PREFETCH_META", 4))
# 0 forces the legacy one-batch-per-RPC fetch everywhere (the demotion
# path old peers get automatically); 1 streams framed batch groups
DATA_PREFETCH_STREAM = int(_f("EDL_TPU_DATA_PREFETCH_STREAM", 1))
# max batch payloads pushed per get_batch_stream request: caps how long
# one stream occupies a channel (and how much one EdlStreamError costs)
DATA_STREAM_BATCH = int(_f("EDL_TPU_DATA_STREAM_BATCH", 8))
# producer-side meta coalescing: report_batch_meta carries up to this
# many freshly produced batches per leader RPC (1 = the legacy
# call-per-batch cadence); buffered metas flush at file end and ride
# the reattach handshake, so availability lags by at most one chunk
DATA_PRODUCE_META_BATCH = int(_f("EDL_TPU_DATA_PRODUCE_META_BATCH", 8))

# -- elastic serving gateway (edl_tpu/gateway, serving/replica) -----------
# how often a replica refreshes its leased advert with live load stats
# (free slots, queue depth, prefill stall) and republishes engine gauges
SERVING_ADVERT_PERIOD = _f("EDL_TPU_SERVING_ADVERT_PERIOD", 1.0)
# gateway fleet-view refresh cadence (store poll; failures also trigger
# an immediate refresh)
GATEWAY_POLL_PERIOD = _f("EDL_TPU_GATEWAY_POLL_PERIOD", 0.25)
# after a transport failure a replica is quarantined from routing this
# long (its advert may outlive the process by up to the lease TTL)
GATEWAY_QUARANTINE_S = _f("EDL_TPU_GATEWAY_QUARANTINE", 5.0)
# completed-generation buffers a replica holds for gateway fetch are
# evicted after this long without an ack (gateway died mid-fetch)
SERVING_RESULT_TTL = _f("EDL_TPU_SERVING_RESULT_TTL", 600.0)

# -- elastic distill fleet (distill/fleet.py, distill/backlog.py) ---------
# how often a fleet teacher refreshes BOTH its adverts (the serving
# table replica advert and the balance-table registration) with live
# stats() — queue depth, rows/s; the student-side DistillFleet view is
# at most one period stale
DISTILL_ADVERT_PERIOD = _f("EDL_TPU_DISTILL_ADVERT_PERIOD", 1.0)
# how often a StudentFeed publishes its durable backlog record
# (scale/backlog/<student>) and gauges; a thread, not an inline hook —
# backlog grows exactly while the student iteration is blocked
DISTILL_BACKLOG_PERIOD = _f("EDL_TPU_DISTILL_BACKLOG_PERIOD", 2.0)
# DistillAutoscaler growth trigger: backlog (queued rows / observed
# teacher rows/s) above GROW seconds, held continuously for HOLD
# seconds, steps the teacher target by EDL_TPU_AUTOSCALE_STEP; decay
# reuses EDL_TPU_AUTOSCALE_QUIET.  Read at runtime (env_float) so the
# controller picks up tuning without a restart.
DISTILL_BACKLOG_GROW_DEFAULT = 5.0    # EDL_TPU_DISTILL_BACKLOG_GROW
DISTILL_BACKLOG_HOLD_DEFAULT = 15.0   # EDL_TPU_DISTILL_BACKLOG_HOLD

# -- paged KV cache + session migration (serving/kv_cache.py) -------------
# KV block size in tokens for the replica CLI's engine; 0 keeps the
# pre-paged contiguous slabs (no prefix reuse, no migration).  Library
# constructors take kv_block= directly.  ON by default since the
# ROADMAP item 3 burn-in (ISSUE 17) — EDL_TPU_KV_BLOCK=0 is the
# documented opt-out to contiguous slabs.  Mesh (tp-sharded) engines
# page too since ISSUE 20: the pool shards over the same ``tp`` axis
# as the heads, one host-side trie indexes every shard at once
# (doc/serving.md "Mesh-sharded paged KV").
KV_BLOCK = int(_f("EDL_TPU_KV_BLOCK", 16))
# pool capacity in blocks; 0 sizes it at 2x the slot pool's worth so a
# full fleet of lanes can commit without evicting each other
KV_POOL_BLOCKS = int(_f("EDL_TPU_KV_POOL_BLOCKS", 0))
# prefix reuse on admission (0 = commit/migrate only, prefill cold)
KV_REUSE = int(_f("EDL_TPU_KV_REUSE", 1))
# push pinned session chains to an adoptive replica on drain()
KV_MIGRATE = int(_f("EDL_TPU_KV_MIGRATE", 1))
# max pinned session chains per replica (LRU unpin beyond this)
KV_SESSIONS = int(_f("EDL_TPU_KV_SESSIONS", 64))

# -- serving fast path (serving/engine.py, ISSUE 20) ----------------------
# chunked prefill: admissions whose prompt exceeds this many tokens
# prefill in chunks of this size, ONE chunk per engine tick,
# interleaved with decode — a long prompt costs streaming sessions one
# chunk of stall per tick instead of one monolithic prefill (0 = off:
# every admission prefills in one dispatch).  Library constructors
# take prefill_chunk= directly.
PREFILL_CHUNK = int(_f("EDL_TPU_PREFILL_CHUNK", 512))
# speculative decoding: a draft model proposes this many tokens per
# tick round and the target verifies them in ONE multi-token pass;
# greedy acceptance keeps outputs bit-identical to plain decode, so
# this is a pure latency knob (0 = off; greedy engines only — the
# constructor rejects spec_k > 0 with temperature > 0).  The replica
# CLI builds a seeded draft from the --draft_* args; library
# constructors pass draft_cfg/draft_params.
SPEC_K = int(_f("EDL_TPU_SPEC_K", 0))
