"""Retry-until-timeout decorator.

Reference: python/edl/utils/error_utils.py:20-39
(``handle_errors_until_timeout``).  Retryable framework errors are
swallowed and retried until ``timeout`` seconds elapse, then the last
error propagates.  Non-retryable errors propagate immediately.

Coordination-path callers pass ``backoff`` > 1 so a store outage is
probed at an exponentially widening interval with full jitter (every
retry at a fixed 1 s across a whole job's processes is a synchronized
stampede on the recovering server); ``edl_retry_attempts_total{fn}``
counts the retries per wrapped function so blip history is visible on
/metrics.
"""

from __future__ import annotations

import functools
import random
import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.exceptions import EdlRetryableError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_ATTEMPTS = obs_metrics.counter(
    "edl_retry_attempts_total",
    "retry_until_timeout retries, by wrapped function", ("fn",))


def retry_until_timeout(func=None, *, interval: float = 1.0,
                        backoff: float = 1.0, max_interval: float = 30.0,
                        jitter: bool = True):
    """Decorate ``func(..., timeout=N)`` to retry EdlRetryableError.

    The wrapped function must accept a ``timeout`` keyword (seconds).
    ``interval`` is the first retry delay; each subsequent delay is
    multiplied by ``backoff`` (1.0 = the legacy fixed interval) and
    capped at ``max_interval``.  With ``jitter`` each sleep is drawn
    uniformly from (0, delay] — full jitter — so synchronized callers
    fan out instead of stampeding.
    """

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, timeout: float = 60.0, **kwargs):
            deadline = time.monotonic() + timeout
            delay = interval
            while True:
                try:
                    return f(*args, **kwargs)
                except EdlRetryableError as e:
                    if time.monotonic() >= deadline:
                        raise
                    _ATTEMPTS.labels(fn=f.__name__).inc()
                    logger.debug("retrying %s after %s: %s", f.__name__, type(e).__name__, e)
                    sleep = random.uniform(0, delay) if jitter else delay
                    time.sleep(min(sleep, max(0.0, deadline - time.monotonic())))
                    delay = min(delay * backoff, max_interval)

        return wrapper

    return decorate(func) if func is not None else decorate
