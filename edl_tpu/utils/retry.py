"""Retry-until-timeout decorator.

Reference: python/edl/utils/error_utils.py:20-39
(``handle_errors_until_timeout``).  Retryable framework errors are
swallowed and retried on an interval until ``timeout`` seconds elapse,
then the last error propagates.  Non-retryable errors propagate
immediately.
"""

from __future__ import annotations

import functools
import time

from edl_tpu.utils.exceptions import EdlRetryableError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def retry_until_timeout(func=None, *, interval: float = 1.0):
    """Decorate ``func(..., timeout=N)`` to retry EdlRetryableError.

    The wrapped function must accept a ``timeout`` keyword (seconds).
    """

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, timeout: float = 60.0, **kwargs):
            deadline = time.monotonic() + timeout
            while True:
                try:
                    return f(*args, **kwargs)
                except EdlRetryableError as e:
                    if time.monotonic() >= deadline:
                        raise
                    logger.debug("retrying %s after %s: %s", f.__name__, type(e).__name__, e)
                    time.sleep(min(interval, max(0.0, deadline - time.monotonic())))

        return wrapper

    return decorate(func) if func is not None else decorate
