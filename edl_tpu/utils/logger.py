"""Per-module loggers (reference: python/edl/utils/log_utils.py:20-32).

Unlike the reference we never call ``logging.basicConfig`` at import time
(that would hijack the root logger of embedding applications); each
module asks for a namespaced logger and the CLI entry points install the
handler.
"""

import logging
import os
import sys

_FMT = "%(asctime)s %(levelname)s [%(name)s:%(lineno)d] %(message)s"


def get_logger(name: str, level: int | str | None = None) -> logging.Logger:
    logger = logging.getLogger(f"edl_tpu.{name}" if not name.startswith("edl_tpu") else name)
    if level is not None:
        logger.setLevel(level)
    return logger


def configure(level: str | None = None, log_dir: str | None = None, filename: str | None = None) -> None:
    """Install a stderr (and optional file) handler on the edl_tpu root logger.

    Called by CLI entry points (launcher, servers), never by library code.
    """
    level = level or os.environ.get("EDL_TPU_LOG_LEVEL", "INFO")
    root = logging.getLogger("edl_tpu")
    root.setLevel(level)
    if not any(isinstance(h, logging.StreamHandler) for h in root.handlers):
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FMT))
        root.addHandler(handler)
    if log_dir and filename:
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.abspath(os.path.join(log_dir, filename))
        # idempotent like the stderr handler above: repeated configure()
        # calls (relaunch paths, embedding apps) must not stack handlers
        # that duplicate every line into the same file
        if not any(isinstance(h, logging.FileHandler)
                   and getattr(h, "baseFilename", None) == path
                   for h in root.handlers):
            fh = logging.FileHandler(path)
            fh.setFormatter(logging.Formatter(_FMT))
            root.addHandler(fh)
