"""Socket helpers: free-port finder and TCP liveness probe.

Reference: python/edl/utils/network_utils.py (free port) and
python/edl/discovery/server_alive.py:19-34 (1.5 s connect probe).
"""

from __future__ import annotations

import socket
from contextlib import closing

ALIVE_PROBE_TIMEOUT = 1.5


def find_free_port() -> int:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]


def find_free_ports(n: int) -> list[int]:
    """Reserve n distinct free ports (best effort; tiny race window)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        return ports
    finally:
        for s in socks:
            s.close()


def split_endpoint(endpoint: str) -> tuple[str, int]:
    host, _, port = endpoint.rpartition(":")
    return host, int(port)


def is_server_alive(endpoint: str, timeout: float = ALIVE_PROBE_TIMEOUT) -> tuple[bool, str | None]:
    """TCP-connect probe; returns (alive, local_ip_used_to_reach_it)."""
    host, port = split_endpoint(endpoint)
    try:
        with closing(socket.create_connection((host, port), timeout=timeout)) as s:
            return True, s.getsockname()[0]
    except OSError:
        return False, None


def local_ip(probe_endpoint: str | None = None) -> str:
    """Best-effort local IP (UDP-connect trick; no traffic sent)."""
    try:
        with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
            s.connect((probe_endpoint or "8.8.8.8", 53))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
