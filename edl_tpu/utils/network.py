"""Socket helpers: free-port finder and TCP liveness probe.

Reference: python/edl/utils/network_utils.py (free port) and
python/edl/discovery/server_alive.py:19-34 (1.5 s connect probe).
"""

from __future__ import annotations

import socket
from contextlib import closing

ALIVE_PROBE_TIMEOUT = 1.5


def find_free_port() -> int:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]


def find_free_ports(n: int) -> list[int]:
    """Reserve n distinct free ports (best effort; tiny race window)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        return ports
    finally:
        for s in socks:
            s.close()


def split_endpoint(endpoint: str) -> tuple[str, int]:
    host, _, port = endpoint.rpartition(":")
    return host, int(port)


def is_server_alive(endpoint: str, timeout: float = ALIVE_PROBE_TIMEOUT) -> tuple[bool, str | None]:
    """TCP-connect probe; returns (alive, local_ip_used_to_reach_it)."""
    host, port = split_endpoint(endpoint)
    try:
        with closing(socket.create_connection((host, port), timeout=timeout)) as s:
            return True, s.getsockname()[0]
    except OSError:
        return False, None


_local_ip_cache: dict[str | None, str] = {}


def _self_connectable(ip: str, timeout: float = 0.5) -> bool:
    """Can a TCP listener bound on ``ip`` be reached at that address?
    A sandboxed environment may route egress through an interface whose
    address (e.g. TEST-NET 192.0.2.x) accepts no inbound connections —
    advertising it would give peers an unreachable endpoint."""
    try:
        with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as srv:
            srv.bind((ip, 0))
            srv.listen(1)
            port = srv.getsockname()[1]
            with closing(socket.create_connection((ip, port), timeout=timeout)):
                return True
    except OSError:
        return False


def local_ip(probe_endpoint: str | None = None) -> str:
    """Local IP that peers can actually connect to.

    Order: ``EDL_TPU_HOST_IP`` env override → UDP-connect trick
    (no traffic sent) validated by a self-connect probe → loopback.
    The probe matters: the UDP trick returns the egress interface's
    address, which in NATed/sandboxed environments may be unroutable
    for inbound TCP (the jax.distributed coordinator, RPC servers)."""
    import os
    override = os.environ.get("EDL_TPU_HOST_IP", "")
    if override:
        return override
    if probe_endpoint in _local_ip_cache:
        return _local_ip_cache[probe_endpoint]
    try:
        with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
            s.connect((probe_endpoint or "8.8.8.8", 53))
            candidate = s.getsockname()[0]
        if _self_connectable(candidate):
            # only successful probes are cached — a transient failure
            # (NIC not up yet) must not pin loopback for the process life
            _local_ip_cache[probe_endpoint] = candidate
            return candidate
    except OSError:
        pass
    return "127.0.0.1"
