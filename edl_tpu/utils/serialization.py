"""JSON round-trip base for cluster-model objects.

Reference: python/edl/utils/json_serializable.py:20-61 — reflection over
``__dict__``.  We keep the reflective approach (the cluster model is
plain data) but handle nested JsonSerializable objects and lists
explicitly so Pod-in-Cluster round-trips without custom glue.
"""

from __future__ import annotations

import json
from typing import Any


class JsonSerializable:
    def to_dict(self) -> dict:
        def conv(v: Any):
            if isinstance(v, JsonSerializable):
                return {"__cls__": type(v).__name__, **v.to_dict()}
            if isinstance(v, (list, tuple)):
                return [conv(x) for x in v]
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            return v

        return {k: conv(v) for k, v in self.__dict__.items() if not k.startswith("__")}

    def from_dict(self, d: dict) -> "JsonSerializable":
        for k, v in d.items():
            if k == "__cls__":
                continue
            cur = self.__dict__.get(k)
            self.__dict__[k] = _rebuild(v, cur, type(self), k)
        return self

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def from_json(self, s: str) -> "JsonSerializable":
        return self.from_dict(json.loads(s))

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __ne__(self, other) -> bool:
        return not self.__eq__(other)

    def __hash__(self):
        return hash(self.to_json())

    def __str__(self):
        return self.to_json()


# registry of concrete classes for nested reconstruction
_CLASSES: dict[str, type] = {}


def register_serializable(cls):
    """Class decorator: make nested instances reconstructible by name."""
    _CLASSES[cls.__name__] = cls
    return cls


def _rebuild(v: Any, current: Any, owner: type, key: str) -> Any:
    if isinstance(v, dict):
        if "__cls__" in v:
            cls = _CLASSES.get(v["__cls__"])
            if cls is None:
                raise KeyError(f"unregistered serializable class {v['__cls__']} (field {owner.__name__}.{key})")
            return cls.__new__(cls).from_dict(v)
        return {k: _rebuild(x, None, owner, key) for k, x in v.items()}
    if isinstance(v, list):
        return [_rebuild(x, None, owner, key) for x in v]
    return v
