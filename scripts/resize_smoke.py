"""CI resize smoke: sub-second-class live resize — delta-resharding
instead of stop-resume (ISSUE 12).

Three phases against REAL launchers + REAL jax trainers (CPU/gloo
collectives), EDL_TPU_RESIZE_DELTA=1 and EDL_TPU_MEMSTATE_VERIFY=1
throughout (every cache/delta restore is bit-compared against the
storage checkpoint inside the trainer — a divergence crashes the job):

1. **Grow-by-one, delta** — pods A+B train a 2-host world; pod C
   joins.  A's and B's trainer PROCESSES must survive (same PIDs, one
   "spawned trainer" line each), the recovery record must carry
   ``resize_mode=delta`` with a reshard ack instead of a respawn, and
   the job must finish SUCCEED at world=3 with every epoch recorded
   exactly once.
2. **Shrink-by-one, delta** — a 3-pod world loses its highest-rank pod
   to SIGKILL.  Survivors' collectives fail instantly; the handshake
   converts the crash into an in-place rollback reshard (same PIDs
   again), sourced from the surviving caches (owner or ring replica).
3. **Shard-holder SIGKILL mid-reshard → fallback** — while a grow
   reshard is in flight (resize flag present), SIGKILL the rank-0 pod:
   the leader/coordinator/shard-holder all at once.  Every delta
   precondition trips; the survivors must fall back to the PROVEN
   stop-resume path and still finish SUCCEED, restoring bit-identical
   state from the dead holder's ring replica.

Prints one JSON line with ``resize_delta_mttr_s`` (grow) and
``resize_shrink_mttr_s`` so the numbers trend in the CI log.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/resize_smoke.py
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "examples", "collective", "train_linear.py")

TTL = 1.0
FAST = {
    "EDL_TPU_TTL": str(TTL),
    "EDL_TPU_GENERATOR_PERIOD": "0.2",
    "EDL_TPU_WATCHER_PERIOD": "0.2",
    "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
    "EDL_TPU_BARRIER_TIMEOUT": "60",
    "EDL_TPU_RESIZE_BARRIER_TIMEOUT": "40",
    "EDL_TPU_RESIZE_DELTA": "1",
    "EDL_TPU_RESIZE_RESHARD_TIMEOUT": "30",
    "EDL_TPU_MEMSTATE_VERIFY": "1",
    "EDL_TPU_PREEMPT_CHECK_STEPS": "2",
    "EDL_TPU_PREEMPT_CHECK_SECONDS": "1",
    "EDL_TPU_DEMO_STEP_SLEEP": "0.25",
    "JAX_PLATFORMS": "cpu",
}


def spawn_coord(tmp: str):
    from edl_tpu.coord.server import spawn_subprocess, wait_ready
    from edl_tpu.utils.network import find_free_port
    port = find_free_port()
    env = dict(os.environ, EDL_TPU_TTL=str(TTL))
    env.pop("EDL_TPU_METRICS_PORT", None)
    proc = spawn_subprocess(port, os.path.join(tmp, "coord"), env=env)
    wait_ready(f"127.0.0.1:{port}")
    return proc, f"127.0.0.1:{port}"


def spawn_launcher(job_id, coord_ep, tmp, name, ckpt, epochs=12, steps=4):
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["EDL_TPU_DEMO_MARKER"] = os.path.join(tmp, f"marker-{name}")
    log = open(os.path.join(tmp, f"launcher-{name}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", job_id, "--coord_endpoints", coord_ep,
         "--nodes_range", "1:3", "--nproc_per_node", "1",
         "--checkpoint_dir", ckpt,
         "--log_dir", os.path.join(tmp, f"log-{name}"), TRAIN,
         "--", "--epochs", str(epochs), "--steps_per_epoch", str(steps)],
        env=env, cwd=tmp, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    return proc


def trainer_pids(launcher) -> set[int]:
    import psutil
    try:
        kids = psutil.Process(launcher.pid).children(recursive=True)
    except psutil.NoSuchProcess:
        return set()
    out = set()
    for k in kids:
        try:
            if any("train_linear.py" in c for c in k.cmdline()):
                out.add(k.pid)
        except psutil.NoSuchProcess:
            continue
    return out


def kill_tree(proc) -> None:
    import psutil
    try:
        victims = psutil.Process(proc.pid).children(recursive=True)
        victims.append(psutil.Process(proc.pid))
    except psutil.NoSuchProcess:
        return
    for p in victims:
        try:
            p.send_signal(signal.SIGKILL)
        except psutil.NoSuchProcess:
            pass


def wait_first_checkpoint(ckpt: str, procs, deadline_s=180):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        done = [d for d in (os.listdir(ckpt) if os.path.isdir(ckpt) else [])
                if d.isdigit()]
        if done:
            return
        for p in procs:
            assert p.poll() is None, f"launcher died in warmup (rc={p.poll()})"
        time.sleep(0.2)
    raise AssertionError("no checkpoint committed in warmup")


def wait_resize_record(client, job_id, mode, deadline_s=120,
                      min_count=1) -> dict:
    """Poll summarize_recovery until >= min_count records of ``mode``
    exist with a completed trainer half; returns the newest."""
    from edl_tpu.cluster.recovery import summarize_recovery
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            recs = [s for s in summarize_recovery(client, job_id)
                    if s.get("resize_mode") == mode and "total" in s]
        except Exception:  # noqa: BLE001 — store warming up
            recs = []
        if len(recs) >= min_count:
            return recs[-1]
        time.sleep(0.3)
    raise AssertionError(f"no completed {mode} resize record in "
                         f"{deadline_s}s")


def finish(proc, timeout):
    try:
        rc = proc.wait(timeout)
    except subprocess.TimeoutExpired:
        kill_tree(proc)
        raise AssertionError("launcher did not finish in time")
    finally:
        if getattr(proc, "_logfile", None):
            proc._logfile.close()  # noqa: SLF001
    return rc


def log_text(tmp, name) -> str:
    path = os.path.join(tmp, f"launcher-{name}.log")
    with open(path, "rb") as f:
        return f.read().decode(errors="replace")


def spawn_count(tmp, name) -> int:
    return log_text(tmp, name).count("spawned trainer")


def wait_world(client, job_id, n_pods, deadline_s=120):
    """Poll the cluster record until the membership has ``n_pods`` and
    stays unchanged for a full second — the pre-resize baseline must
    not be captured during the warmup joins' own stop-resumes."""
    from edl_tpu.cluster.cluster import Cluster
    deadline = time.monotonic() + deadline_s
    stable_since, stage = None, None
    while time.monotonic() < deadline:
        try:
            c = Cluster.load_from_store(client, job_id)
        except Exception:  # noqa: BLE001 — store warming up
            c = None
        if c is not None and len(c.pods) == n_pods:
            if stage == c.stage:
                if stable_since and time.monotonic() - stable_since > 1.0:
                    return c
            else:
                stage, stable_since = c.stage, time.monotonic()
        else:
            stage, stable_since = None, None
        time.sleep(0.1)
    raise AssertionError(f"cluster never stabilized at {n_pods} pods")


def phase_grow(tmp, coord_ep) -> float:
    from edl_tpu.cluster.status import Status, load_job_status
    from edl_tpu.coord.client import connect
    job = "resize-grow"
    ckpt = os.path.join(tmp, "ckpt-grow")
    pa = spawn_launcher(job, coord_ep, tmp, "ga", ckpt)
    pb = spawn_launcher(job, coord_ep, tmp, "gb", ckpt)
    try:
        client = connect(coord_ep)
        wait_world(client, job, 2)
        wait_first_checkpoint(ckpt, (pa, pb))
        time.sleep(1.0)  # settle past the warmup join's own resize
        pids_a, pids_b = trainer_pids(pa), trainer_pids(pb)
        spawns = {n: spawn_count(tmp, n) for n in ("ga", "gb")}
        assert pids_a and pids_b, "no trainer processes found pre-resize"

        pc = spawn_launcher(job, coord_ep, tmp, "gc", ckpt)
        rec = wait_resize_record(client, job, "delta")
        assert trainer_pids(pa) == pids_a, "pod A trainer was replaced"
        assert trainer_pids(pb) == pids_b, "pod B trainer was replaced"
        assert rec.get("restore_source") in ("delta", "peer"), rec

        assert finish(pa, 240) == 0 and finish(pb, 240) == 0 \
            and finish(pc, 240) == 0, "grow job failed"
        assert load_job_status(client, job) == Status.SUCCEED
        client.close()
        for n in ("ga", "gb"):
            after = spawn_count(tmp, n)
            assert after == spawns[n], (
                f"launcher {n} respawned trainers across the delta "
                f"resize ({spawns[n]} -> {after}):\n"
                f"{log_text(tmp, n)[-3000:]}")
        done = [l for n in ("ga", "gb", "gc")
                for l in open(os.path.join(tmp, f"marker-{n}"))
                .read().splitlines() if l.startswith("done")]
        assert done and all("world=3" in l for l in done), done
        print(f"resize smoke: GROW delta OK — mttr {rec['total']:.2f}s, "
              f"reshard {rec.get('barrier_to_reshard', -1):.2f}s, "
              f"restore_source={rec.get('restore_source')}")
        return float(rec["total"])
    finally:
        for p in (pa, pb):
            if p.poll() is None:
                kill_tree(p)
        if "pc" in locals() and pc.poll() is None:
            kill_tree(pc)


def phase_shrink(tmp, coord_ep) -> float:
    from edl_tpu.cluster.status import Status, load_job_status
    from edl_tpu.coord.client import connect
    job = "resize-shrink"
    ckpt = os.path.join(tmp, "ckpt-shrink")
    procs = {n: spawn_launcher(job, coord_ep, tmp, n, ckpt)
             for n in ("sa", "sb", "sc")}
    try:
        client = connect(coord_ep)
        cluster = wait_world(client, job, 3)
        wait_first_checkpoint(ckpt, tuple(procs.values()))
        # let the 3-pod world commit a world=3 checkpoint before the kill
        time.sleep(2.0)
        # the highest-rank pod is PREEMPTED (SIGTERM + grace — the
        # controlled scale-in every real scheduler performs): the whole
        # old world checkpoints at an agreed step, the departing pod
        # exits DESCALED, and the survivors unwind into the live
        # reshard.  NOT the leader: the jax coordination service rides
        # the leader pod's launcher (leader death is the fallback
        # path, phase 3).  A SIGKILLed pod instead lands on the
        # stop-resume fallback — gloo cannot error collectives started
        # after a silent peer death (doc/robustness.md).  Map pod id ->
        # launcher via the "pod <id> ... launching" line each one logs.
        victim_pod = cluster.pods[-1].pod_id
        victim = next(n for n in procs
                      if f"pod {victim_pod}" in log_text(tmp, n))
        survivors = {n: p for n, p in procs.items() if n != victim}
        pids = {n: trainer_pids(p) for n, p in survivors.items()}
        spawns = {n: spawn_count(tmp, n) for n in survivors}
        assert all(pids.values()), "no trainer processes found pre-kill"

        procs[victim].send_signal(signal.SIGTERM)
        rec = wait_resize_record(client, job, "delta")
        assert finish(procs[victim], 240) == 0, \
            "preempted pod must exit cleanly (DESCALED)"
        for n, p in survivors.items():
            assert trainer_pids(p) == pids[n], f"pod {n} trainer replaced"
        assert all(finish(p, 240) == 0 for p in survivors.values()), \
            "shrink job failed"
        assert load_job_status(client, job) == Status.SUCCEED
        client.close()
        for n in survivors:
            after = spawn_count(tmp, n)
            assert after == spawns[n], (
                f"launcher {n} respawned trainers across the delta "
                f"shrink ({spawns[n]} -> {after}):\n"
                f"{log_text(tmp, n)[-3000:]}")
        done = [l for n in survivors
                for l in open(os.path.join(tmp, f"marker-{n}"))
                .read().splitlines() if l.startswith("done")]
        assert done and all("world=2" in l for l in done), done
        print(f"resize smoke: SHRINK delta OK — mttr {rec['total']:.2f}s, "
              f"restore_source={rec.get('restore_source')}")
        return float(rec["total"])
    finally:
        for p in procs.values():
            if p.poll() is None:
                kill_tree(p)


def phase_fallback(tmp, coord_ep) -> None:
    """SIGKILL the rank-0 pod (leader + jax coordinator + replica-0
    shard holder) while a grow reshard is in flight: survivors must
    fall back to stop-resume and still finish, restoring from the dead
    holder's ring replica (bit-verified by EDL_TPU_MEMSTATE_VERIFY)."""
    from edl_tpu.cluster import paths
    from edl_tpu.cluster.status import Status, load_job_status
    from edl_tpu.coord.client import connect
    from edl_tpu.utils import constants
    job = "resize-fb"
    ckpt = os.path.join(tmp, "ckpt-fb")
    procs = {n: spawn_launcher(job, coord_ep, tmp, n, ckpt, epochs=14)
             for n in ("fa", "fb")}
    try:
        client = connect(coord_ep)
        cluster = wait_world(client, job, 2)
        wait_first_checkpoint(ckpt, tuple(procs.values()))
        time.sleep(1.0)
        # the leader pod = rank 0 = jax-coordination host = the holder
        # of the replica-0 shard set (it owns the committed copy every
        # restore leans on)
        leader_pod = cluster.pods[0].pod_id
        leader = next(n for n in procs
                      if f"pod {leader_pod}" in log_text(tmp, n))
        procs["fc"] = spawn_launcher(job, coord_ep, tmp, "fc", ckpt,
                                     epochs=14)
        # wait for the resize flag = the grow reshard is IN FLIGHT
        prefix = paths.table_prefix(job, constants.ETCD_RESHARD)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            recs, _rev = client.get_prefix(prefix)
            if any("flag/" in r.key for r in recs):
                break
            assert all(p.poll() is None
                       for n, p in procs.items() if n != "fc")
            time.sleep(0.05)
        else:
            raise AssertionError("resize flag never appeared")
        kill_tree(procs[leader])  # the shard holder dies mid-reshard

        # survivors must converge through stop-resume and SUCCEED
        survivors = [n for n in procs if n != leader]
        assert all(finish(procs[n], 300) == 0 for n in survivors), \
            "fallback job failed"
        assert load_job_status(client, job) == Status.SUCCEED
        client.close()
        text = "".join(log_text(tmp, n) for n in survivors)
        assert ("falling back to stop-resume" in text
                or "restart trainers (stop-resume)" in text), \
            "no stop-resume fallback found in survivor logs"
        done = [l for n in survivors
                for l in open(os.path.join(tmp, f"marker-{n}"))
                .read().splitlines() if l.startswith("done")]
        assert done and all("world=2" in l for l in done), done
        print("resize smoke: FALLBACK OK — holder SIGKILL mid-reshard "
              "fell back to stop-resume, job SUCCEEDed bit-identical")
    finally:
        for p in procs.values():
            if p.poll() is None:
                kill_tree(p)


def main() -> None:
    # optional phase filter for targeted debugging:
    #   python scripts/resize_smoke.py [grow|shrink|fallback]
    only = sys.argv[1] if len(sys.argv) > 1 else None
    tmp = tempfile.mkdtemp(prefix="edl-resize-smoke-")
    coord, coord_ep = spawn_coord(tmp)
    try:
        grow_mttr = shrink_mttr = -1.0
        if only in (None, "grow"):
            grow_mttr = phase_grow(tmp, coord_ep)
        if only in (None, "shrink"):
            shrink_mttr = phase_shrink(tmp, coord_ep)
        if only in (None, "fallback"):
            phase_fallback(tmp, coord_ep)
        if only is None:
            print(json.dumps(
                {"resize_delta_mttr_s": round(grow_mttr, 3),
                 "resize_shrink_mttr_s": round(shrink_mttr, 3)}))
        print("resize smoke OK")
    finally:
        if coord.poll() is None:
            coord.kill()
            coord.wait(timeout=30)


if __name__ == "__main__":
    main()
