#!/usr/bin/env python
"""CI observability smoke (scripts/ci.sh): run a few real trainer
steps with the /metrics endpoint enabled via the env contract
(EDL_TPU_METRICS_PORT=0), push one resize record through the unified
write path, then fetch /metrics over HTTP and PARSE it back —
asserting the step-latency and resize-phase series are present — and
check the dump CLI reproduces summarize_recovery's per-phase totals.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["EDL_TPU_METRICS_PORT"] = "0"  # auto free port, the env contract

# runnable without `pip install -e .` (air-gapped checkouts)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import urllib.request

import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.cluster.state import State
from edl_tpu.train import ElasticTrainer, TrainConfig

RNG = np.random.default_rng(0)


def loss(params, extra, batch, rng):
    pred = batch["x"] @ params["w"]
    mse = jnp.mean((pred - batch["y"]) ** 2)
    return mse, (extra, {"mse": mse})


def batches():
    for _ in range(5):
        x = RNG.normal(size=(8, 4)).astype(np.float32)
        yield {"x": x, "y": x @ np.ones((4, 1), np.float32)}


def main() -> None:
    trainer = ElasticTrainer(loss, TrainConfig(log_every=0))
    state = trainer.create_state(lambda: ({"w": jnp.zeros((4, 1))}, None),
                                 optax.sgd(0.1))
    trainer.fit(state, State(), lambda e: batches(), epochs=1)

    # one resize through the unified write path: the same times dicts
    # drive the store record, the trace, and the phase histogram
    from edl_tpu.cluster import recovery
    from edl_tpu.coord.memory import MemoryKV
    kv = MemoryKV()
    recovery.write_launcher_half(
        kv, "smoke", "s1", "pod0",
        {"detect": 10.0, "killed": 10.5, "barrier": 11.0, "spawn": 11.25})
    recovery.write_trainer_half(kv, "smoke", "s1", "pod0",
                                restored=13.0, first_step=14.0)

    from edl_tpu import obs
    srv = obs.installed_server()
    assert srv is not None, "EDL_TPU_METRICS_PORT did not install /metrics"
    url = f"http://127.0.0.1:{srv.port}/metrics"
    text = urllib.request.urlopen(url, timeout=10).read().decode()
    samples = obs.parse_exposition(text)  # raises if the page is invalid

    def sample(name, **labels):
        return samples.get((name, tuple(sorted(labels.items()))), 0.0)

    assert sample("edl_train_steps_total") == 5.0, samples
    assert sample("edl_train_step_seconds_count") >= 4.0, samples
    assert sample("edl_resize_phase_seconds_count",
                  phase="kill_to_barrier") == 1.0, samples
    assert sample("edl_resize_phase_seconds_count",
                  phase="restored_to_first_step") == 1.0, samples

    # the dump CLI agrees with summarize_recovery by construction
    from edl_tpu.cluster.recovery import summarize_recovery
    from edl_tpu.obs.dump import job_report, render_report
    report = job_report(kv, "smoke")
    assert report["resizes"] == summarize_recovery(kv, "smoke")
    (resize,) = report["resizes"]
    assert resize["total"] == 4.0, resize
    rendered = render_report(report)
    assert "restored_to_first_step" in rendered, rendered
    kv.close()
    print("obs smoke OK")


if __name__ == "__main__":
    main()
