"""CI smoke: the elastic serving gateway's transparent-failover proof.

Two REAL replica processes (``python -m edl_tpu.serving.replica``, each
a ContinuousBatcher behind the EDL1 RPC wire with a TTL-leased advert)
against an in-process coordination server, fronted by an in-process
Gateway.  The contract under test, end to end:

1. both replicas serve greedy-parity-correct tokens through the
   gateway (least-loaded routing, chunked result fetch);
2. hedging rescues a slow tail: with a tight hedge deadline, hedge
   legs fire and every result is still correct (losers released);
3. **SIGKILL one replica under sustained load** — every accepted
   request still completes (replayed on the survivor), with at least
   one observed retry;
4. a saturated gateway REJECTS (EdlOverloadedError + retry_after)
   immediately instead of hanging;
5. ``edl_gateway_*`` metrics appear on this process's /metrics page,
   ``edl_serving_*`` engine gauges on the surviving replica's page,
   and gateway/route + gateway/hedge + gateway/retry spans land in the
   trace JSONL.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/gateway_smoke.py
"""

import json
import os
import selectors
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
_TRACE_DIR = os.environ.setdefault("EDL_TPU_TRACE_DIR",
                                   tempfile.mkdtemp(prefix="edl-gw-trace-"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB, LAYERS, EMBED, HEADS, MLP, MAX_LEN = 53, 1, 32, 2, 64, 64


def _spawn_replica(coord_ep: str, rid: str, metrics_dir: str):
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               EDL_TPU_METRICS_PORT="0", EDL_TPU_METRICS_DIR=metrics_dir)
    env.pop("XLA_FLAGS", None)   # single-device replicas
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.serving.replica",
         "--coord_endpoints", coord_ep, "--job_id", "smoke",
         "--replica_id", rid, "--host", "127.0.0.1",
         "--vocab", str(VOCAB), "--layers", str(LAYERS),
         "--embed", str(EMBED), "--heads", str(HEADS), "--mlp", str(MLP),
         "--max_len", str(MAX_LEN), "--slots", "2", "--steps_per_sync", "4",
         "--temperature", "0", "--seed", "0", "--ttl", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.time() + 300
    while time.time() < deadline:
        if not sel.select(timeout=1.0):
            if proc.poll() is not None:
                raise AssertionError(f"replica {rid} died silently")
            continue
        line = proc.stdout.readline()
        if "serving on" in line:
            return proc
        if not line and proc.poll() is not None:
            raise AssertionError(f"replica {rid} died before announcing")
    raise AssertionError(f"replica {rid} never announced")


def main() -> None:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.gateway import Gateway, GatewayConfig
    from edl_tpu.gateway.gateway import _HEDGES, _RETRIES
    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.obs import exposition, trace
    from edl_tpu.obs.metrics import parse_exposition
    from edl_tpu.utils.exceptions import EdlOverloadedError

    trace.configure_from_env("gateway")
    srv_metrics = exposition.serve_from_env("gateway")
    assert srv_metrics is not None, "metrics endpoint must be up for the smoke"

    cfg = TransformerConfig(vocab_size=VOCAB, num_layers=LAYERS,
                            embed_dim=EMBED, num_heads=HEADS, mlp_dim=MLP,
                            max_len=MAX_LEN, remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(                    # replica --seed 0
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]

    def want(prompt, n):
        return np.asarray(generate(cfg, params, jnp.asarray(prompt[None]),
                                   n, temperature=0.0))[0]

    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    metrics_dir = tempfile.mkdtemp(prefix="edl-gw-metrics-")
    procs = {rid: _spawn_replica(coord_ep, rid, metrics_dir)
             for rid in ("rep-0", "rep-1")}
    store = CoordClient(coord_ep)
    gw = Gateway(store, "smoke", GatewayConfig(
        max_inflight=8, max_queue=32, request_timeout_s=300.0,
        wait_slice_s=0.1, poll_period_s=0.1, quarantine_s=30.0))
    try:
        assert gw.wait_for_replicas(2, 60), "replicas never advertised"
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, VOCAB, (n,)).astype(np.int32)
                   for n in (3, 7, 5, 9, 4, 6)]

        # 1 -- both replicas, correctness through the full stack
        futs = [gw.submit(p, 8) for p in prompts]
        for p, f in zip(prompts, futs):
            np.testing.assert_array_equal(f.result(timeout=300), want(p, 8))
        print("smoke: 2-replica routing + greedy parity OK")

        # 2 -- hedging: SIGSTOP rep-0, pin requests to it via session
        # affinity — the stuck legs trip the hedge deadline and the
        # hedge legs on rep-1 deliver correct results (deterministic
        # tail: a warm tiny model finishes faster than any deadline)
        import signal

        hedges0 = _HEDGES.value
        gw_hedge = Gateway(store, "smoke", GatewayConfig(
            max_inflight=4, max_queue=16, hedge_after_s=0.1,
            request_timeout_s=300.0, wait_slice_s=0.05, poll_period_s=0.1))
        try:
            assert gw_hedge.wait_for_replicas(2, 60)
            sess = next(s for s in (f"s{i}" for i in range(1000))
                        if gw_hedge._fleet.ring.get_node(s) == "rep-0")
            os.kill(procs["rep-0"].pid, signal.SIGSTOP)
            try:
                futs = [gw_hedge.submit(p, 16, session=sess)
                        for p in prompts[:4]]
                for p, f in zip(prompts, futs):
                    np.testing.assert_array_equal(f.result(timeout=300),
                                                  want(p, 16))
            finally:
                os.kill(procs["rep-0"].pid, signal.SIGCONT)
        finally:
            gw_hedge.close()
        assert _HEDGES.value > hedges0, "stuck replica never tripped a hedge"
        print(f"smoke: hedging fired ({int(_HEDGES.value - hedges0)} legs), "
              "results correct")
        # rep-0's lease lapsed while stopped; wait for its re-register
        assert gw.wait_for_replicas(2, 60), "rep-0 never re-advertised"

        # 3 -- SIGKILL a replica under sustained load: zero lost requests
        retries0 = _RETRIES.value
        load = [rng.integers(1, VOCAB, (rng.integers(3, 10),)).astype(np.int32)
                for _ in range(24)]
        futs = [gw.submit(p, 16) for p in load]
        deadline = time.monotonic() + 120
        while gw.stats()["inflight"].get("rep-0", 0) < 1:
            assert time.monotonic() < deadline, "no request ever hit rep-0"
            time.sleep(0.02)
        procs["rep-0"].kill()                      # SIGKILL, no grace
        procs["rep-0"].wait(timeout=30)
        outs = [f.result(timeout=300) for f in futs]
        for p, o in zip(load, outs):
            np.testing.assert_array_equal(o, want(p, 16))
        assert _RETRIES.value > retries0, "kill under load must cause a retry"
        print(f"smoke: SIGKILL under load -> all {len(load)} accepted "
              f"requests completed on the survivor "
              f"({int(_RETRIES.value - retries0)} retries)")

        # 4 -- saturation rejects immediately (no hang)
        gw_tiny = Gateway(store, "smoke", GatewayConfig(
            max_inflight=1, max_queue=0, request_timeout_s=300.0,
            wait_slice_s=0.1, poll_period_s=0.1))
        try:
            slow = gw_tiny.submit(load[0], 40)
            rejects = 0
            for _ in range(5):
                t0 = time.monotonic()
                try:
                    gw_tiny.submit(load[1], 4)
                except EdlOverloadedError as e:
                    rejects += 1
                    assert e.retry_after > 0
                assert time.monotonic() - t0 < 1.0, "reject must not block"
            assert rejects == 5, f"expected 5 rejects, got {rejects}"
            slow.result(timeout=300)
        finally:
            gw_tiny.close()
        print("smoke: saturated gateway rejects with retry_after, no hang")

        # 5 -- observability: gateway metrics, replica engine gauges, spans
        page = urllib.request.urlopen(
            f"http://{srv_metrics.endpoint}/metrics", timeout=10
        ).read().decode()
        metrics = parse_exposition(page)
        for name, labels in [("edl_gateway_requests_total",
                              (("outcome", "ok"),)),
                             ("edl_gateway_retries_total", ()),
                             ("edl_gateway_hedges_total", ()),
                             ("edl_gateway_rejects_total",
                              (("reason", "queue_full"),))]:
            assert metrics.get((name, labels), 0) > 0, (name, labels)
        survivor_pid = procs["rep-1"].pid
        addr_path = os.path.join(metrics_dir,
                                 f"metrics-replica-{survivor_pid}.addr")
        with open(addr_path) as f:
            rep_page = urllib.request.urlopen(
                f"http://{f.read().strip()}/metrics", timeout=10
            ).read().decode()
        rep_metrics = parse_exposition(rep_page)
        for name in ("edl_serving_free_slots", "edl_serving_queue_depth",
                     "edl_serving_prefill_stall_seconds",
                     "edl_serving_tokens_per_s"):
            assert (name, ()) in rep_metrics, name
        assert rep_metrics[("edl_serving_tokens_per_s", ())] > 0
        spans = set()
        for fn in os.listdir(_TRACE_DIR):
            with open(os.path.join(_TRACE_DIR, fn)) as f:
                for line in f:
                    spans.add(json.loads(line).get("name"))
        for name in ("gateway/route", "gateway/hedge", "gateway/retry"):
            assert name in spans, f"missing trace span {name} in {spans}"
        print("smoke: edl_gateway_*/edl_serving_* metrics + "
              "route/hedge/retry spans present")

        # 6 -- end-to-end distributed tracing: a trace_id stamped by the
        # GATEWAY must appear in spans emitted by a REPLICA process, and
        # `edl-obs-dump --merge` must render them as one ordered
        # timeline with a valid Perfetto export
        from edl_tpu.obs import dump as obs_dump

        events, _skipped = obs_dump.read_trace_dir(_TRACE_DIR)
        gw_traces = [e["trace_id"] for e in events
                     if e.get("name") == "gateway/request"
                     and "trace_id" in e]
        assert gw_traces, "gateway requests must stamp trace ids"
        replica_tids = {e.get("trace_id") for e in events
                        if e.get("component") == "replica"}
        tid = next((t for t in gw_traces if t in replica_tids), None)
        assert tid is not None, \
            "no gateway trace_id reached a replica process's spans"
        tl = obs_dump.merge_timeline(events, tid)
        comps = {e.get("component") for e in tl}
        assert {"gateway", "replica"} <= comps, comps
        assert len({e["file"] for e in tl}) >= 2, "must span processes"
        # semantic causal order (merge_timeline sorts by ts, so assert
        # the STAMPED begin timestamps, not the sort): the gateway's
        # request root begins before any replica accepted it, and some
        # replica finished it afterwards (hedged traces may carry a
        # submit per leg, hence min/max)
        req_ts = min(e["ts"] for e in tl if e["name"] == "gateway/request")
        submits = [e["ts"] for e in tl if e["name"] == "serving/submit"]
        completes = [e["ts"] for e in tl if e["name"] == "serving/complete"]
        assert submits and req_ts <= min(submits), tl
        assert completes and min(submits) <= max(completes), tl
        out_json = os.path.join(_TRACE_DIR, "request.perfetto.json")
        rc = obs_dump.main(["--merge", "--trace_dir", _TRACE_DIR,
                            "--trace", tid, "--perfetto", out_json])
        assert rc == 0
        with open(out_json) as f:
            pf = json.load(f)
        assert pf["traceEvents"], "empty Perfetto export"
        assert any(e.get("name") == "serving/submit"
                   for e in pf["traceEvents"])
        print(f"smoke: gateway trace {tid[:8]} spans {len(tl)} events "
              f"across {sorted(comps)}; merged timeline + Perfetto OK")
    finally:
        gw.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        store.close()
        coord.stop()
    print("gateway smoke OK")


if __name__ == "__main__":
    main()
