"""CI smoke: distributed tracing + the job-level obs aggregator, end to
end across REAL processes.

Two child worker processes (each: a /metrics endpoint + a TTL-leased
coord-store advert + an EDL1 RPC server whose handler emits a span)
plus this parent, against an in-process coordination server:

1. the parent establishes ONE trace context and calls each child's
   handler over the wire — the spans the children emit (in their own
   processes, into their own trace files) must carry the parent's
   trace_id, and so must the handlers' ambient contexts;
2. ``edl-obs-agg`` (in-process AggregatorServer) discovers all three
   processes via the coord store and serves a merged, Prometheus-
   parseable job /metrics — same-name metrics from different processes
   disambiguated by ``component``/``instance`` labels, HELP/TYPE once
   per family — plus a /healthz job summary;
3. ``edl-obs-dump --merge`` joins the shared trace directory into one
   causally-ordered timeline for that trace_id spanning all three
   processes, and exports valid Perfetto JSON.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/obs_agg_smoke.py
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
_TRACE_DIR = os.environ.setdefault("EDL_TPU_TRACE_DIR",
                                   tempfile.mkdtemp(prefix="edl-agg-trace-"))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_CHILD = r"""
import sys, threading
sys.path.insert(0, {repo!r})
from edl_tpu import obs
from edl_tpu.coord.client import CoordClient
from edl_tpu.obs import advert, context as obs_context, metrics, trace
from edl_tpu.rpc.server import RpcServer

coord_ep, job = sys.argv[1], sys.argv[2]
obs.install_from_env("worker")
store = CoordClient(coord_ep)
reg = advert.advertise_installed(store, job, "worker")
assert reg is not None, "child metrics endpoint/advert missing"
work_total = metrics.counter("edl_smoke_child_total",
                             "work() calls handled by a child")
# same NAME as the parent's metric but a DIFFERENT label set: the
# aggregator's merged page must survive this (satellite: HELP/TYPE
# dedupe across conflicting label sets)
metrics.gauge("edl_smoke_shared", "child flavor").set(1)

def work(n=1):
    work_total.inc(n)
    trace.emit("child/work", n=n)
    cur = obs_context.current()
    return {{"trace": cur.trace_id if cur else None}}

srv = RpcServer("127.0.0.1", 0)
srv.register("work", work)
srv.start()
print("child rpc on", srv.endpoint, flush=True)
threading.Event().wait()
"""


def _spawn_child(coord_ep: str, job: str) -> tuple[subprocess.Popen, str]:
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD.format(repo=_REPO),
         coord_ep, job],
        env=dict(os.environ), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "child rpc on" in line:
            return proc, line.rsplit(" ", 1)[-1].strip()
        if not line and proc.poll() is not None:
            raise AssertionError("child died before announcing")
    raise AssertionError("child never announced")


def main() -> None:
    from edl_tpu import obs
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.obs import context as obs_context
    from edl_tpu.obs import dump as obs_dump
    from edl_tpu.obs import metrics as obs_metrics, trace as obs_trace
    from edl_tpu.obs.advert import advertise_installed
    from edl_tpu.obs.agg import AggregatorServer
    from edl_tpu.rpc.client import RpcClient

    obs.install_from_env("parent")
    obs_metrics.gauge("edl_smoke_shared", "parent flavor",
                      ("role",)).labels(role="parent").set(2)

    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    store = CoordClient(coord_ep)
    job = "aggsmoke"
    parent_reg = advertise_installed(store, job, "parent")
    assert parent_reg is not None, "parent metrics endpoint must be up"
    children = [_spawn_child(coord_ep, job) for _ in range(2)]
    agg_srv = None
    try:
        # 1 -- one trace context spans parent + both child PROCESSES
        ctx = obs_context.new_trace(job=job)
        with obs_context.use(ctx):
            obs_trace.emit("parent/fanout", children=len(children))
            for _proc, ep in children:
                with RpcClient(ep) as c:
                    r = c.call("work", n=1)
                assert r["trace"] == ctx.trace_id, \
                    "handler did not inherit the caller's trace"
        print("smoke: one trace_id propagated over the wire into "
              f"{len(children)} child processes")

        # 2 -- the aggregator: coord-store discovery + merged /metrics
        agg_srv = AggregatorServer(store, job, host="127.0.0.1",
                                   cache_s=0.0).start()
        deadline = time.time() + 60
        while True:
            page = urllib.request.urlopen(
                f"http://{agg_srv.endpoint}/metrics", timeout=10
            ).read().decode()
            parsed = obs_metrics.parse_exposition(page)  # byte-parseable
            child_samples = [
                (name, labels) for name, labels in parsed
                if name == "edl_smoke_child_total"
                and dict(labels).get("component") == "worker"]
            if len(child_samples) == 2:
                break
            assert time.time() < deadline, \
                f"aggregator never saw both children: {child_samples}"
            time.sleep(0.2)
        instances = {dict(labels)["instance"] for _, labels in child_samples}
        assert len(instances) == 2, "children must be distinct instances"
        # conflicting label sets for edl_smoke_shared: headers once
        assert page.count("# TYPE edl_smoke_shared gauge") == 1
        assert page.count("# HELP edl_smoke_shared") == 1
        health = json.loads(urllib.request.urlopen(
            f"http://{agg_srv.endpoint}/healthz", timeout=10
        ).read().decode())
        assert health["live_targets"] >= 3, health
        assert health["components"].get("worker") == 2, health
        assert health["components"].get("parent") == 1, health
        print(f"smoke: edl-obs-agg discovered {health['live_targets']} "
              "processes via the coord store; merged /metrics parseable, "
              "HELP/TYPE deduped, /healthz live")

        # 3 -- merged timeline + Perfetto export for that one trace
        events, _skipped = obs_dump.read_trace_dir(_TRACE_DIR)
        tl = obs_dump.merge_timeline(events, ctx.trace_id)
        files = {e["file"] for e in tl}
        assert len(files) >= 3, \
            f"trace {ctx.trace_id[:8]} must span parent+children: {files}"
        # semantic causal order on the STAMPED begin timestamps: the
        # parent's fan-out event precedes every child's handler span
        fanout_ts = next(e["ts"] for e in tl if e["name"] == "parent/fanout")
        child_ts = [e["ts"] for e in tl if e["name"] == "child/work"]
        assert len(child_ts) == 2 and all(fanout_ts <= t for t in child_ts)
        out_json = os.path.join(_TRACE_DIR, "smoke.perfetto.json")
        rc = obs_dump.main(["--merge", "--trace_dir", _TRACE_DIR,
                            "--trace", ctx.trace_id,
                            "--perfetto", out_json])
        assert rc == 0
        with open(out_json) as f:
            pf = json.load(f)
        assert any(e.get("name") == "child/work"
                   for e in pf["traceEvents"]), pf["traceEvents"][:5]
        print(f"smoke: edl-obs-dump --merge ordered {len(tl)} events from "
              f"{len(files)} processes; Perfetto JSON valid")
    finally:
        if agg_srv is not None:
            agg_srv.stop()
        for proc, _ in children:
            proc.kill()
        parent_reg.stop()
        store.close()
        coord.stop()
    print("obs-agg smoke OK")


if __name__ == "__main__":
    main()
