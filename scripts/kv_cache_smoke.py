"""CI smoke: the paged KV cache's three contracts, end to end.

1. **bit-exactness** — the same mixed workload (shared prefixes,
   divergent sessions, unrelated prompts) through a paged engine and an
   unpaged engine yields byte-identical greedy outputs;
2. **throughput** — the heavy-prefix bench section must show prefix-hit
   tokens/s >= cold tokens/s with a prefill-skipped fraction > 0.5, and
   the drain handoff must produce a migration-latency number;
3. **SIGTERM-drain under sustained sessions** — two REAL replica
   processes (``edl-replica --kv_block``) behind an in-process Gateway:
   a session's turn lands on its ring owner, the owner is SIGTERMed
   under load, every accepted request still completes, the session's
   KV chain migrates to the survivor (pin advert published), and the
   session's next turn resumes THERE without re-prefilling (the
   survivor's ``edl_serving_kv_prefill_tokens_skipped`` moves).

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/kv_cache_smoke.py
"""

import json
import os
import selectors
import signal
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
os.environ.setdefault("EDL_TPU_TRACE_DIR",
                      tempfile.mkdtemp(prefix="edl-kv-trace-"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB, LAYERS, EMBED, HEADS, MLP, MAX_LEN = 53, 1, 32, 2, 64, 64


def _spawn_replica(coord_ep: str, rid: str, metrics_dir: str):
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               EDL_TPU_METRICS_PORT="0", EDL_TPU_METRICS_DIR=metrics_dir)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.serving.replica",
         "--coord_endpoints", coord_ep, "--job_id", "kvsmoke",
         "--replica_id", rid, "--host", "127.0.0.1",
         "--vocab", str(VOCAB), "--layers", str(LAYERS),
         "--embed", str(EMBED), "--heads", str(HEADS), "--mlp", str(MLP),
         "--max_len", str(MAX_LEN), "--slots", "2", "--steps_per_sync", "4",
         "--temperature", "0", "--seed", "0", "--ttl", "3",
         "--kv_block", "4", "--kv_pool_blocks", "64"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.time() + 300
    while time.time() < deadline:
        if not sel.select(timeout=1.0):
            if proc.poll() is not None:
                raise AssertionError(f"replica {rid} died silently")
            continue
        line = proc.stdout.readline()
        if "serving on" in line:
            return proc
        if not line and proc.poll() is not None:
            raise AssertionError(f"replica {rid} died before announcing")
    raise AssertionError(f"replica {rid} never announced")


def _parity_section() -> None:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.serving import ContinuousBatcher

    cfg = TransformerConfig(vocab_size=97, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(5)
    shared = rng.integers(1, 97, (11,)).astype(np.int32)
    work = []     # (prompt, max_new, session)
    for i, n in enumerate((3, 6, 2, 4)):
        tail = rng.integers(1, 97, (n,)).astype(np.int32)
        work.append((np.concatenate([shared, tail]), 6, f"s{i % 2}"))
    work.append((rng.integers(1, 97, (7,)).astype(np.int32), 8, None))

    def run(kv_block: int):
        eng = ContinuousBatcher(cfg, params, slots=2, temperature=0.0,
                                prefill_buckets=(8, 16), steps_per_sync=4,
                                kv_block=kv_block, kv_pool_blocks=64)
        try:
            outs = [eng.generate(p, n, timeout=300) if s is None else
                    eng.submit(p, n, session=s).result(300)
                    for p, n, s in work]
            # second turns per session, extending divergent lines
            convs = {}
            for (p, _n, s), o in zip(work, outs):
                if s is not None and s not in convs:
                    convs[s] = np.concatenate(
                        [p, o, np.asarray([1, 9], np.int32)])
            outs += [eng.submit(convs[s], 5, session=s).result(300)
                     for s in sorted(convs)]
            return outs, eng.stats()
        finally:
            eng.stop()

    paged, stats = run(kv_block=4)
    unpaged, _ = run(kv_block=0)
    assert len(paged) == len(unpaged)
    for a, b in zip(paged, unpaged):
        np.testing.assert_array_equal(a, b)
    assert stats["kv_prefix_hits"] > 0, stats
    print(f"smoke: paged-vs-unpaged greedy parity over {len(paged)} "
          f"generations ({stats['kv_prefix_hits']} prefix hits, "
          f"{stats['kv_prefill_tokens_skipped']} prompt tokens skipped)")


def _throughput_section() -> dict:
    from edl_tpu.bench import _bench_serving_kv

    res = _bench_serving_kv()
    print("smoke: kv bench section ->", json.dumps(res))
    assert res["serving_prefix_tokens_s"] >= res["serving_cold_tokens_s"], \
        f"prefix reuse lost to cold prefill: {res}"
    assert res["serving_prefill_skipped_frac"] > 0.5, res
    assert res.get("serving_kv_migration_ms") is not None, \
        f"drain handoff produced no migration latency: {res}"
    return res


def _sigterm_drain_section() -> None:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.gateway import Gateway, GatewayConfig, fleet
    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.obs.metrics import parse_exposition

    cfg = TransformerConfig(vocab_size=VOCAB, num_layers=LAYERS,
                            embed_dim=EMBED, num_heads=HEADS, mlp_dim=MLP,
                            max_len=MAX_LEN, remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(                    # replica --seed 0
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]

    def want(prompt, n):
        return np.asarray(generate(cfg, params, jnp.asarray(prompt[None]),
                                   n, temperature=0.0))[0]

    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    metrics_dir = tempfile.mkdtemp(prefix="edl-kv-metrics-")
    procs = {rid: _spawn_replica(coord_ep, rid, metrics_dir)
             for rid in ("rep-0", "rep-1")}
    store = CoordClient(coord_ep)
    gw = Gateway(store, "kvsmoke", GatewayConfig(
        max_inflight=8, max_queue=32, request_timeout_s=300.0,
        wait_slice_s=0.1, poll_period_s=0.1, quarantine_s=5.0))
    try:
        assert gw.wait_for_replicas(2, 60), "replicas never advertised"
        rng = np.random.default_rng(1)
        # a session whose ring owner is the replica we will SIGTERM
        sess = next(s for s in (f"conv-{i}" for i in range(1000))
                    if gw._fleet.ring.get_node(s) == "rep-0")
        p1 = rng.integers(1, VOCAB, (9,)).astype(np.int32)
        out1 = gw.generate(p1, 8, session=sess, timeout=300)
        np.testing.assert_array_equal(out1, want(p1, 8))

        # sustained load in flight while the owner drains away
        load = [rng.integers(1, VOCAB,
                             (int(rng.integers(3, 10)),)).astype(np.int32)
                for _ in range(12)]
        futs = [gw.submit(p, 12) for p in load]
        os.kill(procs["rep-0"].pid, signal.SIGTERM)
        outs = [f.result(timeout=300) for f in futs]
        for p, o in zip(load, outs):
            np.testing.assert_array_equal(o, want(p, 12))
        procs["rep-0"].wait(timeout=120)
        print(f"smoke: SIGTERM-drain under load -> all {len(load)} "
              "accepted requests completed")

        # the drain handoff re-pinned the session onto the survivor
        deadline = time.monotonic() + 60
        while fleet.list_session_pins(store, "kvsmoke").get(sess) != "rep-1":
            assert time.monotonic() < deadline, \
                f"session never re-pinned: " \
                f"{fleet.list_session_pins(store, 'kvsmoke')}"
            time.sleep(0.1)
        gw._fleet.refresh()
        assert gw._fleet.session_pin(sess) == "rep-1"

        # next turn resumes WARM on the survivor: bit-exact output and
        # a moving prefill-skipped counter (no re-prefill of the
        # migrated prefix)
        p2 = np.concatenate([p1, out1,
                             rng.integers(1, VOCAB, (2,)).astype(np.int32)])
        out2 = gw.generate(p2, 6, session=sess, timeout=300)
        np.testing.assert_array_equal(out2, want(p2, 6))
        addr_path = os.path.join(
            metrics_dir, f"metrics-replica-{procs['rep-1'].pid}.addr")
        with open(addr_path) as f:
            survivor_metrics = f.read().strip()
        skipped = 0.0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            page = urllib.request.urlopen(
                f"http://{survivor_metrics}/metrics", timeout=10
            ).read().decode()
            parsed = parse_exposition(page)
            skipped = parsed.get(
                ("edl_serving_kv_prefill_tokens_skipped", ()), 0.0)
            if skipped > 0:
                break
            time.sleep(0.25)     # gauge updates on the advert period
        assert skipped > 0, \
            "migrated session re-prefilled on the survivor"
        assert parsed.get(("edl_serving_kv_sessions", ()), 0) >= 1
        print(f"smoke: session {sess} resumed on rep-1 with {int(skipped)} "
              "prompt tokens skipped (migrated chain, no re-prefill)")
    finally:
        gw.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        store.close()
        coord.stop()


def main() -> None:
    _parity_section()
    _throughput_section()
    _sigterm_drain_section()
    print("kv cache smoke OK")


if __name__ == "__main__":
    main()
