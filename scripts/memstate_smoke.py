"""CI smoke: two-pod kill-one-restore-from-peer on the CPU mesh.

The memstate contract in one minute, no launcher subprocesses: two
simulated pods (StateCacheService + RpcServer each) over an in-process
MemoryKV, a real CheckpointManager save teed through pod A, ring
replication to pod B, then pod A dies — and the restore must still
come out of pod B's RAM, bit-identical to the original, with the
checksum-corruption case falling back to Orbax storage.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/memstate_smoke.py
"""

import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from edl_tpu import memstate
    from edl_tpu.cluster.state import State
    from edl_tpu.coord.memory import MemoryKV
    from edl_tpu.memstate import restore as ms_restore
    from edl_tpu.memstate.service import StateCacheService
    from edl_tpu.memstate.tee import StateCacheTee
    from edl_tpu.rpc.server import RpcServer
    from edl_tpu.train.checkpoint import CheckpointManager

    store = MemoryKV(sweep_period=0.25)
    job = "smoke"
    pods = {}
    for pid in ("pod-a", "pod-b"):
        srv = RpcServer("127.0.0.1", 0)
        svc = StateCacheService(store, job, pid)
        srv.register_instance(svc)
        srv.start()
        reg = memstate.advertise(store, job, pid, f"127.0.0.1:{srv.port}",
                                 ttl=60)
        pods[pid] = (svc, srv, reg)

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    sharded = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    state = {
        "w": jax.device_put(np.random.default_rng(0).normal(
            size=(64, 32)).astype(np.float32), sharded),
        "b": jax.device_put(np.arange(16, dtype=np.float32), repl),
    }
    abstract = {"w": jax.ShapeDtypeStruct((64, 32), np.float32,
                                          sharding=repl),
                "b": jax.ShapeDtypeStruct((16,), np.float32,
                                          sharding=sharded)}

    tmp = tempfile.mkdtemp(prefix="edl-memstate-smoke-")
    tee = StateCacheTee(store, job, "pod-a")
    ck = CheckpointManager(tmp, tee=tee)
    assert ck.save(3, state, State(total_batch_size=8))
    ck.wait()
    deadline = time.monotonic() + 60
    while memstate.read_committed_step(store, job) != 3:
        assert time.monotonic() < deadline, "tee never sealed step 3"
        time.sleep(0.05)
    while "pod-a" not in pods["pod-b"][0].cache_manifest():
        assert time.monotonic() < deadline, "replica never landed on pod-b"
        time.sleep(0.05)
    print("smoke: save teed to pod-a and replicated to pod-b")

    # kill pod A (server down, advert gone): the owner of every shard
    pods["pod-a"][2].stop()
    pods["pod-a"][1].stop()
    store.delete(f"/edl_tpu/{job}/memstate/nodes/pod-a")

    res = ms_restore.try_restore(store, job, abstract, expect_step=3)
    assert res is not None, "restore must hit pod-b's replica"
    got, meta_json, info = res
    assert info["peers"] == ["pod-b"], info
    for k in state:
        assert np.array_equal(np.asarray(got[k]), np.asarray(state[k])), k
    assert State().from_json(meta_json).total_batch_size == 8
    print(f"smoke: peer restore from surviving pod OK ({info['shards']} "
          f"shards, {info['bytes']} bytes, resharded)")

    # corrupt the replica -> checksum miss -> storage fallback
    sset = pods["pod-b"][0]._sets["pod-a"]  # noqa: SLF001 — fault injection
    for key in list(sset.shards):
        if "w" in key:
            sset.shards[key] = b"\x00" * len(sset.shards[key])
    assert ms_restore.try_restore(store, job, abstract,
                                  expect_step=3) is None
    stored = ck.restore(abstract)
    assert stored is not None
    assert np.array_equal(np.asarray(stored[0]["w"]), np.asarray(state["w"]))
    print("smoke: checksum-bad replica refused; storage fallback OK")

    ck.close()
    pods["pod-b"][2].stop()
    pods["pod-b"][1].stop()
    store.close()
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    print("memstate smoke OK")


if __name__ == "__main__":
    main()
