"""CI chaos smoke: a coordination-store outage is a bounded hiccup.

The control plane's whole fault-tolerance story, end to end, against a
REAL durable coord server killed with SIGKILL and restarted:

1. **WAL bit-exactness** — populate keys + leases, ``dump_state``,
   SIGKILL the server, restart it on the same data dir: the dump must
   match bit-exactly (revision counter, lease table, every record), and
   a fresh lease grant must never collide with a pre-kill id.
2. **Mid-training + mid-serving kill** — a single-pod training job
   (real launcher, inert trainer) and a serving fleet (real replica
   process + in-process gateway under sustained load) share one durable
   coord server.  SIGKILL it mid-flight, restart it:

   - every accepted gateway request completes with greedy-parity
     correct tokens (zero lost);
   - training resumes without restore-from-scratch: the trainer is
     started exactly once and the launcher never takes the
     membership-changed restart path;
   - every advert (pod resource, memstate cache, serving fleet, obs
     /metrics) is back within one TTL + restart grace;
   - ``coord_restart_mttr_s`` and the advert re-registration latency
     are recorded (and gated) — the headline robustness numbers.
3. **Fault-injection harness** — with ``kv_put`` failing 30% of the
   time (utils/faultinject.py), the resilient client must hide every
   fault; the injection counter proves faults actually fired.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/chaos_smoke.py
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EDL_TPU_TTL", "2")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "tests", "helpers", "demo_trainer.py")

TTL = 2.0
GRACE = 2.0
VOCAB, LAYERS, EMBED, HEADS, MLP, MAX_LEN = 53, 1, 32, 2, 64, 64


def _spawn_coord(port: int, data_dir: str) -> subprocess.Popen:
    from edl_tpu.coord.server import spawn_subprocess
    env = dict(os.environ, EDL_TPU_TTL=str(TTL))
    env.pop("EDL_TPU_METRICS_PORT", None)
    return spawn_subprocess(port, data_dir, restart_grace=GRACE, env=env)


def _wait_ping(ep: str, deadline_s: float = 120.0) -> float:
    from edl_tpu.coord.server import wait_ready
    return wait_ready(ep, deadline_s)


def phase1_wal_bit_exactness(tmp: str, port: int) -> float:
    from edl_tpu.coord.client import CoordClient

    data_dir = os.path.join(tmp, "coord-p1")
    proc = _spawn_coord(port, data_dir)
    try:
        _wait_ping(f"127.0.0.1:{port}")
        client = CoordClient(f"127.0.0.1:{port}")
        client.put("/chaos/a", b"1")
        client.put("/chaos/b", b"2")
        client.put("/chaos/a", b"3")
        client.delete("/chaos/b")
        lids = [client.lease_grant(300.0) for _ in range(3)]
        client.put("/chaos/leased", b"x", lids[0])
        client.lease_revoke(lids[1])
        before = client.dump_state()
        client.close()

        t_kill = time.monotonic()
        proc.kill()
        proc.wait(timeout=30)
        proc = _spawn_coord(port, data_dir)
        _wait_ping(f"127.0.0.1:{port}")
        mttr = time.monotonic() - t_kill

        client = CoordClient(f"127.0.0.1:{port}")
        after = client.dump_state()
        assert after == before, (
            f"WAL replay must restore state bit-exactly:\n"
            f"before={before}\nafter={after}")
        fresh = client.lease_grant(300.0)
        assert fresh > max(lids), \
            f"fresh lease {fresh} collides with pre-kill ids {lids}"
        assert client.lease_keepalive(lids[0]) is True, \
            "pre-kill lease must survive the restart"
        client.close()
        print(f"chaos: WAL bit-exact across SIGKILL "
              f"(revision={after['revision']}, {len(after['keys'])} keys, "
              f"{len(after['leases'])} leases; restart mttr {mttr:.2f}s)")
        return mttr
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def _spawn_launcher(coord_ep: str, tmp: str) -> tuple[subprocess.Popen, str, str]:
    env = dict(os.environ)
    env.update({
        "EDL_TPU_TTL": str(TTL),
        "EDL_TPU_GENERATOR_PERIOD": "0.2",
        "EDL_TPU_WATCHER_PERIOD": "0.2",
        "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
        "EDL_TPU_BARRIER_TIMEOUT": "60",
        "EDL_TPU_DEMO_SLEEP_SOLO": "45",
        "EDL_TPU_DEMO_MARKER": os.path.join(tmp, "marker-train"),
        "EDL_TPU_METRICS_PORT": "0",  # serve /metrics -> obs advert
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    })
    log_path = os.path.join(tmp, "launcher.log")
    log = open(log_path, "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", "chaos-train", "--coord_endpoints", coord_ep,
         "--nodes_range", "1:1", "--nproc_per_node", "1",
         "--log_dir", os.path.join(tmp, "log-train"), DEMO],
        env=env, cwd=tmp, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    return proc, log_path, env["EDL_TPU_DEMO_MARKER"]


def _spawn_replica(coord_ep: str, tmp: str) -> subprocess.Popen:
    import selectors

    env = dict(os.environ, JAX_PLATFORMS="cpu", EDL_TPU_TTL=str(TTL),
               EDL_TPU_METRICS_PORT="0",
               EDL_TPU_METRICS_DIR=os.path.join(tmp, "metrics"))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.serving.replica",
         "--coord_endpoints", coord_ep, "--job_id", "chaos-serve",
         "--replica_id", "rep-0", "--host", "127.0.0.1",
         "--vocab", str(VOCAB), "--layers", str(LAYERS),
         "--embed", str(EMBED), "--heads", str(HEADS), "--mlp", str(MLP),
         "--max_len", str(MAX_LEN), "--slots", "2", "--steps_per_sync", "4",
         "--temperature", "0", "--seed", "0", "--ttl", str(TTL)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.time() + 300
    while time.time() < deadline:
        if not sel.select(timeout=1.0):
            if proc.poll() is not None:
                raise AssertionError("replica died silently")
            continue
        line = proc.stdout.readline()
        if "serving on" in line:
            return proc
        if not line and proc.poll() is not None:
            raise AssertionError("replica died before announcing")
    raise AssertionError("replica never announced")


def _adverts_present(store) -> dict[str, bool]:
    from edl_tpu.gateway import fleet
    from edl_tpu.memstate import advert as mem_advert
    from edl_tpu.obs import advert as obs_advert

    return {
        "resource": bool(store.get_prefix(
            "/edl_tpu/chaos-train/resource/")[0]),
        "memstate": bool(mem_advert.list_adverts(store, "chaos-train")),
        "serving": bool(fleet.list_replicas(store, "chaos-serve")),
        "obs": bool(obs_advert.list_metrics_targets(store, "chaos-train"))
        and bool(obs_advert.list_metrics_targets(store, "chaos-serve")),
    }


def phase2_joint_chaos(tmp: str, port: int, out: dict) -> None:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.cluster.status import Status, load_job_status
    from edl_tpu.coord.client import connect
    from edl_tpu.coord.resilient import _RETRIES
    from edl_tpu.gateway import Gateway, GatewayConfig
    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM

    coord_ep = f"127.0.0.1:{port}"
    data_dir = os.path.join(tmp, "coord-p2")
    coord = _spawn_coord(port, data_dir)
    launcher = replica = gw = store = None
    halt = threading.Event()
    try:
        _wait_ping(coord_ep)
        launcher, log_path, marker = _spawn_launcher(coord_ep, tmp)
        replica = _spawn_replica(coord_ep, tmp)

        cfg = TransformerConfig(vocab_size=VOCAB, num_layers=LAYERS,
                                embed_dim=EMBED, num_heads=HEADS,
                                mlp_dim=MLP, max_len=MAX_LEN, remat=False,
                                dtype=jnp.float32)
        params = TransformerLM(cfg).init(
            jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]

        def want(prompt, n):
            return np.asarray(generate(cfg, params, jnp.asarray(prompt[None]),
                                       n, temperature=0.0))[0]

        store = connect(coord_ep)
        gw = Gateway(store, "chaos-serve", GatewayConfig(
            max_inflight=8, max_queue=64, request_timeout_s=300.0,
            wait_slice_s=0.1, poll_period_s=0.1, quarantine_s=30.0))
        assert gw.wait_for_replicas(1, 120), "replica never advertised"

        # wait for the trainer to be running and every advert to exist
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.exists(marker) and all(_adverts_present(store).values()):
                break
            assert launcher.poll() is None, "launcher died in warmup"
            time.sleep(0.25)
        else:
            raise AssertionError(
                f"warmup never completed: {_adverts_present(store)}")

        # sustained gateway load straight through the outage
        rng = np.random.default_rng(0)
        accepted: list = []
        load_err: list = []

        def load_loop():
            from edl_tpu.utils.exceptions import EdlOverloadedError
            while not halt.is_set():
                p = rng.integers(1, VOCAB, (int(rng.integers(3, 9)),)
                                 ).astype(np.int32)
                try:
                    accepted.append((p, gw.submit(p, 8)))
                except EdlOverloadedError:
                    pass  # rejected = not accepted; no promise broken
                except Exception as e:  # noqa: BLE001
                    load_err.append(e)
                    return
                time.sleep(0.15)

        loader = threading.Thread(target=load_loop)
        loader.start()
        time.sleep(2.0)  # some requests in flight pre-kill

        retries_before = sum(
            _RETRIES.labels(op=op).value
            for op in ("put", "get", "get_prefix", "lease_keepalive"))
        t_kill = time.monotonic()
        coord.kill()
        coord.wait(timeout=30)
        time.sleep(1.0)  # the outage window: > one advert refresh period
        coord = _spawn_coord(port, data_dir)
        _wait_ping(coord_ep)
        mttr = time.monotonic() - t_kill
        out["coord_restart_mttr_s"] = round(mttr, 3)

        # every advert back within one TTL + restart grace (+ scheduling
        # slack): the WAL froze the leases, so nothing should even expire
        t_up = time.monotonic()
        advert_deadline = t_up + TTL + GRACE + 10.0
        last = {}
        while time.monotonic() < advert_deadline:
            last = _adverts_present(store)
            if all(last.values()):
                break
            time.sleep(0.2)
        assert all(last.values()), f"adverts missing after restart: {last}"
        out["coord_advert_reregister_s"] = round(time.monotonic() - t_up, 3)

        # keep load flowing a few TTLs past recovery, then settle
        time.sleep(3 * TTL)
        halt.set()
        loader.join(timeout=30)
        assert not load_err, f"load loop died: {load_err[0]}"
        assert len(accepted) >= 20, f"only {len(accepted)} accepted requests"
        for p, fut in accepted:
            np.testing.assert_array_equal(fut.result(timeout=300), want(p, 8))
        retries_after = sum(
            _RETRIES.labels(op=op).value
            for op in ("put", "get", "get_prefix", "lease_keepalive"))
        assert retries_after > retries_before, \
            "outage must have exercised the resilient retry path"
        print(f"chaos: SIGKILL+restart mid-serving -> all {len(accepted)} "
              f"accepted requests correct; mttr {mttr:.2f}s, adverts back in "
              f"{out['coord_advert_reregister_s']:.2f}s, "
              f"{int(retries_after - retries_before)} coord retries")

        # training: ran straight through — exactly one trainer start, no
        # membership-changed restart, job SUCCEEDs
        rc = launcher.wait(timeout=300)
        launcher._logfile.close()  # noqa: SLF001
        log = open(log_path, errors="replace").read()
        assert rc == 0, f"launcher failed rc={rc}:\n{log[-3000:]}"
        starts = sum(1 for line in open(marker) if line.startswith("start"))
        assert starts == 1, \
            f"trainer restarted {starts}x — coord outage must not " \
            f"trigger restore-from-scratch:\n{log[-3000:]}"
        assert "membership changed" not in log, log[-3000:]
        assert load_job_status(store, "chaos-train") == Status.SUCCEED
        print("chaos: SIGKILL+restart mid-training -> trainer started once, "
              "no stop-resume, job SUCCEED")
    finally:
        halt.set()
        if gw is not None:
            gw.close()
        if store is not None:
            store.close()
        for proc in (launcher, replica, coord):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


def phase3_fault_injection(tmp: str) -> None:
    from edl_tpu.coord.resilient import ResilientCoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.utils import faultinject
    from edl_tpu.utils.faultinject import _INJECTED

    server = start_server("127.0.0.1", 0, data_dir=os.path.join(tmp, "p3"))
    try:
        faultinject.configure("client:kv_put:error:0.3", seed=1234)
        before = _INJECTED.labels(point="kv_put", action="error").value
        rc = ResilientCoordClient([f"127.0.0.1:{server.port}"],
                                  retry_deadline=60.0, backoff_init=0.01)
        for i in range(50):
            assert rc.put(f"/fi/{i}", b"v") > 0
        fired = _INJECTED.labels(point="kv_put", action="error").value - before
        assert fired > 0, "a 30% fault rate over 50 puts must fire"
        for i in range(50):
            assert rc.get(f"/fi/{i}").value == b"v"
        rc.close()
        print(f"chaos: fault injection (kv_put:error:0.3) fired {int(fired)}x"
              " and the resilient client hid every one")
    finally:
        faultinject.configure(None)
        server.stop()
        server.kv.close()


def main() -> None:
    from edl_tpu.utils.network import find_free_ports

    out: dict = {}
    tmp = tempfile.mkdtemp(prefix="edl-chaos-")
    p1, p2 = find_free_ports(2)
    phase1_wal_bit_exactness(tmp, p1)
    phase2_joint_chaos(tmp, p2, out)
    phase3_fault_injection(tmp)
    assert out["coord_restart_mttr_s"] < 60.0, out
    assert out["coord_advert_reregister_s"] < TTL + GRACE + 10.0, out
    print("CHAOS " + json.dumps(out))
    print("chaos smoke OK")


if __name__ == "__main__":
    main()
