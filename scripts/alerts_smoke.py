"""CI smoke: the closed observability loop — TSDB + built-in ruleset +
incident records — detecting REAL injected failures end to end.

Three "trainer" child processes (each: a /metrics endpoint serving a
live ``edl_train_step_seconds`` histogram + a TTL-leased coord advert)
against an in-process coordination server, scraped by a real
``AggregatorServer`` background loop running the BUILT-IN ruleset
(windows shrunk via ``EDL_TPU_ALERT_SCALE`` — same rules, CI speed):

1. **straggler** — one child steps 5x slower than the fleet; the
   ``trainer-straggler`` outlier rule must fire on that child's
   instance within its window+hold;
2. **hang** — every child stalls at an agreed instant through the
   ``EDL_TPU_FAULTS`` delay action (``train_step:delay:...`` — the
   same injection grammar the chaos smokes use); the ``trainer-hang``
   rule must fire within ~its declared window+hold, ``/alerts`` must
   show it, and ``edl_alerts_firing`` must appear on the merged page;
3. **incident join** — the parent publishes a generation trace
   (``publish_job_trace``, exactly what the launcher does); the
   incident JSONL record must carry that trace_id and
   ``edl-obs-dump --merge`` must land the alert INSIDE that trace's
   causal timeline next to the generation's span events;
4. **killed data leader** — a journaled DataService is killed
   mid-epoch and a successor rebuilds; the reader's resilient client
   records the observed outage and the built-in
   ``data-leader-mttr-regression`` rule (threshold shrunk via
   ``EDL_TPU_ALERT_MTTR_THRESHOLD``) must fire on it.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/alerts_smoke.py
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

_TRACE_DIR = os.environ.setdefault("EDL_TPU_TRACE_DIR",
                                   tempfile.mkdtemp(prefix="edl-alerts-"))
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
os.environ.setdefault("EDL_TPU_ALERT_SCALE", "0.1")       # 6s hang window
os.environ.setdefault("EDL_TPU_ALERT_MTTR_THRESHOLD", "0.02")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from edl_tpu.coord.client import CoordClient
from edl_tpu.obs import advert
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.metrics import Registry
from edl_tpu.utils import faultinject

coord_ep, job, step_s, stall_at = (sys.argv[1], sys.argv[2],
                                   float(sys.argv[3]), float(sys.argv[4]))
reg = Registry()
steps = reg.histogram("edl_train_step_seconds", "per-step wall time")
srv = MetricsServer(reg, host="127.0.0.1").start()
store = CoordClient(coord_ep)
handle = advert.advertise_metrics(store, job, "trainer", srv.endpoint,
                                  name=f"trainer-{{os.getpid()}}", ttl=60)
print("trainer up", srv.endpoint, flush=True)
while True:
    if stall_at and time.time() >= stall_at:
        # the injected stall: the EDL_TPU_FAULTS delay action parks the
        # step loop exactly where a wedged collective would
        faultinject.fire("train_step")
    time.sleep(step_s)
    steps.observe(step_s)
"""


def _spawn_trainer(coord_ep, job, step_s, stall_at):
    env = dict(os.environ, EDL_TPU_FAULTS="train_step:delay:600",
               EDL_TPU_METRICS_PORT="")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD.format(repo=_REPO),
         coord_ep, job, str(step_s), str(stall_at)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "trainer up" in line:
            return proc, line.rsplit(" ", 1)[-1].strip()
        if not line and proc.poll() is not None:
            raise AssertionError("trainer child died before announcing")
    raise AssertionError("trainer child never announced")


def _get_json(url):
    return json.loads(urllib.request.urlopen(url, timeout=10).read().decode())


def _wait_alert(agg_ep, name, deadline, every=0.2):
    while time.time() < deadline:
        alerts = _get_json(f"http://{agg_ep}/alerts")
        hit = [a for a in alerts["firing"] if a["alert"] == name]
        if hit:
            return time.time(), hit[0]
        time.sleep(every)
    raise AssertionError(f"alert {name} never fired; last state: "
                         f"{_get_json(f'http://{agg_ep}/alerts')}")


def _data_leader_kill(store):
    """Kill a journaled data leader mid-epoch; the reader's resilient
    client rides it out and records the observed outage gauge."""
    from edl_tpu.data import DistributedReader, PodDataServer
    from edl_tpu.data.data_server import DataService
    from edl_tpu.data.journal import DataJournal
    from edl_tpu.rpc.server import RpcServer

    data_dir = tempfile.mkdtemp(prefix="edl-alerts-data-")
    for f in range(4):
        with open(os.path.join(data_dir, f"part-{f}.txt"), "w") as fh:
            fh.writelines(f"f{f}r{r}\n" for r in range(20))
    files = sorted(os.path.join(data_dir, f) for f in os.listdir(data_dir))

    def serve(journal):
        srv = RpcServer("127.0.0.1", 0)
        srv.register_instance(DataService(journal=journal,
                                          rebuild_grace=0.5))
        srv.start()
        return srv, f"127.0.0.1:{srv.port}"

    journal = DataJournal(store, "alertsmoke-data")
    srv1, ep1 = serve(journal)
    endpoint = {"ep": ep1}
    cache = PodDataServer("alerts-pod")
    srv2 = None
    try:
        reader = DistributedReader("alerts@e0", "alerts-pod",
                                   lambda: endpoint["ep"], cache,
                                   batch_size=8, retry_deadline=60.0,
                                   meta_prefetch=1)
        reader.create(files)
        seen = 0
        for i, (_bid, _payload) in enumerate(iter(reader)):
            seen += 1
            if i == 3:
                srv1.stop()
                # deterministic outage floor: keep the seat EMPTY for
                # 5x the smoke's 20ms EDL_TPU_ALERT_MTTR_THRESHOLD
                # before the successor serves.  Without it the observed
                # outage is just the resilient client's first jittered
                # backoff, which can land UNDER the threshold when the
                # box is otherwise loaded (tier-1 running concurrently)
                # and the rule never fires — rerun luck, not a gate.
                time.sleep(
                    5 * float(os.environ["EDL_TPU_ALERT_MTTR_THRESHOLD"]))
                srv2, ep2 = serve(journal)
                endpoint["ep"] = ep2
        assert seen > 4, f"reader finished too early ({seen} batches)"
    finally:
        cache.stop()
        for s in (srv1, srv2):
            if s is not None:
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 — teardown
                    pass


def main() -> None:
    from edl_tpu import obs
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.obs import context as obs_context
    from edl_tpu.obs import dump as obs_dump
    from edl_tpu.obs import rules as obs_rules
    from edl_tpu.obs import trace as obs_trace
    from edl_tpu.obs.advert import advertise_installed, publish_job_trace
    from edl_tpu.obs.agg import AggregatorServer
    from edl_tpu.obs.metrics import parse_exposition

    obs.install_from_env("parent")
    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    store = CoordClient(coord_ep)
    job = "alertsmoke"

    rules = {r.name: r for r in obs_rules.builtin_rules()}
    hang, strag = rules["trainer-hang"], rules["trainer-straggler"]
    stall_at = time.time() + (strag.window + strag.for_s) * 3 + 20.0
    # the parent's own /metrics rides along too (the data-leader outage
    # gauge lands in THIS process's registry)
    parent_reg = advertise_installed(store, job, "parent")
    assert parent_reg is not None
    # the generation trace the incident must join (what the launcher
    # publishes every time it roots a cluster-generation trace)
    ctx = obs_context.new_trace(job=job)
    publish_job_trace(store, job, ctx, stage="gen0")
    with obs_context.use(ctx):
        obs_trace.emit("smoke/generation", stage="gen0")

    children = [_spawn_trainer(coord_ep, job, s, stall_at)
                for s in (0.05, 0.05, 0.25)]
    agg_srv = None
    try:
        agg_srv = AggregatorServer(
            store, job, host="127.0.0.1", cache_s=0.0,
            scrape_interval=0.25, incident_dir=_TRACE_DIR).start()
        agg_ep = agg_srv.endpoint

        # 1 -- straggler: the slow child vs the fleet median
        t0 = time.time()
        bound = (strag.window + strag.for_s) * 2 + 15.0
        fired_at, alert = _wait_alert(agg_ep, "trainer-straggler",
                                      t0 + bound)
        slow_ep = children[2][1]
        assert alert.get("instance") == slow_ep, \
            f"straggler fired on {alert.get('instance')}, want {slow_ep}"
        print(f"smoke: trainer-straggler fired on the slow pod "
              f"({alert['instance']}, ratio {alert['value']:.1f}x) "
              f"in {fired_at - t0:.1f}s")

        # 2 -- hang: every trainer stalls at stall_at via EDL_TPU_FAULTS
        wait = stall_at - time.time()
        assert wait > 0, "stall instant already passed; widen the margin"
        time.sleep(wait)
        hang_bound = (hang.window + hang.for_s) * 2 + 10.0
        fired_at, alert = _wait_alert(agg_ep, "trainer-hang",
                                      stall_at + hang_bound)
        detect_s = fired_at - stall_at
        assert detect_s <= hang_bound, \
            f"hang detection took {detect_s:.1f}s > {hang_bound:.1f}s"
        print(f"smoke: trainer-hang fired {detect_s:.1f}s after the "
              f"injected stall (rule bound "
              f"{hang.window + hang.for_s:.1f}s + scrape slack)")
        page = urllib.request.urlopen(
            f"http://{agg_ep}/metrics", timeout=10).read().decode()
        parsed = parse_exposition(page)
        firing = [v for (n, labels), v in parsed.items()
                  if n == "edl_alerts_firing"
                  and dict(labels).get("alert") == "trainer-hang"]
        assert firing and max(firing) >= 1, \
            "edl_alerts_firing{alert=trainer-hang} missing from merged page"

        # 3 -- the incident record joins the generation trace
        inc_path = agg_srv.aggregator.engine.incidents.path
        with open(inc_path, encoding="utf-8") as f:
            incidents = [json.loads(line) for line in f if line.strip()]
        hang_inc = [r for r in incidents
                    if r["name"] == "alert/trainer-hang"
                    and r["state"] == "firing"]
        assert hang_inc, f"no hang incident record in {inc_path}"
        assert hang_inc[0].get("trace_id") == ctx.trace_id, \
            f"incident trace_id {hang_inc[0].get('trace_id')} != " \
            f"published generation trace {ctx.trace_id}"
        events, _skipped = obs_dump.read_trace_dir(_TRACE_DIR)
        tl = obs_dump.merge_timeline(events, ctx.trace_id)
        names = [e["name"] for e in tl]
        assert "smoke/generation" in names and "alert/trainer-hang" in names, \
            f"merged timeline must join generation span + incident: {names}"
        print(f"smoke: incident record joined trace {ctx.trace_id[:8]} "
              f"({len(tl)} events in the merged timeline)")

        # 4 -- killed data leader: outage gauge -> built-in MTTR rule
        _data_leader_kill(store)
        fired_at, alert = _wait_alert(
            agg_ep, "data-leader-mttr-regression", time.time() + 30.0)
        print(f"smoke: data-leader-mttr-regression fired on an observed "
              f"{alert['value']:.3f}s leader outage")
    finally:
        if agg_srv is not None:
            agg_srv.stop()
        for proc, _ in children:
            proc.kill()
        parent_reg.stop()
        store.close()
        coord.stop()
    print("alerts smoke OK")


if __name__ == "__main__":
    main()
