"""CI smoke: the self-driving cluster loop end to end (ISSUE 15).

THREE job kinds on one capacity pool, arbitrated by one in-process
Controller against an in-process coordination server, each job watched
by a real Aggregator scrape loop running the BUILT-IN ruleset (windows
shrunk via ``EDL_TPU_ALERT_SCALE``) with the remediation dispatcher
armed:

- **train** — three REAL launcher processes (``edl_tpu.collective
  .launch``) running the instrumented inert trainer
  (tests/helpers/metrics_trainer.py: live step histogram + heartbeat
  + preempt-flag compliance), one pod 6x slower than the fleet;
- **distill** — one launcher pod (gang spec), whose trainer can be
  wedged through a stall file (steps AND beats stop, process alive);
- **svc** — fake-engine replica processes behind a real in-process
  Gateway with a tight admission rate.

The proof, phase by phase:

1. **arbitration baseline** — the controller reconciles all three
   kinds without flapping anyone;
2. **straggler -> evict** — the builtin ``trainer-straggler`` rule
   fires on the slow pod's instance; the dispatcher evicts it through
   the preemption-grace path; the pod's workerlog says WHY it died
   (``reason=straggler-evict``), the survivors' recovery record
   carries the eviction reason, and the job keeps running;
3. **hang -> targeted restart** — the distill trainer wedges; the
   ``trainer-hang`` rule fires; the dispatcher's restart flag respawns
   the pod's trainers IN PLACE: launcher pid unchanged, cluster stage
   unchanged — no stop-resume touches any healthy pod;
4. **gateway spike -> scale-out** — a load spike over the admission
   rate fires ``gateway-reject-burn``; the dispatcher writes a demand
   record; the controller scales the replica fleet out (visible in
   the advert table) and EVERY accepted request completes (zero lost);
5. **priority yield + reclaim** — serving demand squeezes the
   training job, which yields a pod through the graceful-preemption
   path (``reason=priority-yield``); when the demand decays on quiet
   the autoscaler scales the fleet back in and training RECLAIMS the
   chips (the controller's actuator spawns replacement launchers);
6. **audit** — the per-job incident logs show each
   alert -> action -> recovery handoff.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/remediation_smoke.py
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_TMP = tempfile.mkdtemp(prefix="edl-remed-")
os.environ.setdefault("EDL_TPU_TRACE_DIR", os.path.join(_TMP, "trace"))
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
os.environ.setdefault("EDL_TPU_ALERT_SCALE", "0.1")
os.environ.setdefault("EDL_TPU_REMEDIATE_COOLDOWN", "2")
os.environ.setdefault("EDL_TPU_AUTOSCALE_QUIET", "8")
os.environ.setdefault("EDL_TPU_DEMAND_TTL", "30")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
_TRAINER = os.path.join(_REPO, "tests", "helpers", "metrics_trainer.py")

FAST = {
    "EDL_TPU_TTL": "1",
    "EDL_TPU_GENERATOR_PERIOD": "0.2",
    "EDL_TPU_WATCHER_PERIOD": "0.2",
    "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
    "EDL_TPU_BARRIER_TIMEOUT": "60",
    "EDL_TPU_RESIZE_BARRIER_TIMEOUT": "30",
    # the launchers' OWN hang watchdog is OFF: the smoke proves the
    # ALERT loop (aggregator rule -> dispatcher -> per-pod flag) does
    # the healing, not the local heartbeat threshold
    "EDL_TPU_HANG_TIMEOUT": "-1",
}

_REPLICA_CHILD = r"""
import signal, sys, threading, time
sys.path.insert(0, {repo!r})
import numpy as np
from concurrent.futures import Future
from edl_tpu.coord.client import connect
from edl_tpu.serving.replica import ReplicaServer

class FakeEngine:
    slots = 8
    def submit(self, ids, max_new, session=None):
        fut = Future()
        def run():
            time.sleep(0.02)
            fut.set_result(np.arange(max_new, dtype=np.int32) + int(ids[0]))
        threading.Thread(target=run, daemon=True).start()
        return fut
    def stats(self):
        return {{"slots": 8, "active_slots": 0, "queue_depth": 0,
                 "prefill_stall_s": 0.0, "tokens_per_s": 100.0,
                 "max_prompt_len": 63, "draining": False}}
    def drain(self, timeout=None):
        return True
    def stop(self):
        pass

coord_ep, rid = sys.argv[1], sys.argv[2]
store = connect(coord_ep)
srv = ReplicaServer(store, "svc", FakeEngine(), replica_id=rid,
                    host="127.0.0.1", ttl=2.0, advert_period=0.25,
                    migrate_sessions=False)
stop = threading.Event()
signal.signal(signal.SIGTERM, lambda *_: stop.set())
print("replica up", rid, flush=True)
stop.wait()
srv.stop()
"""


def _wait(cond, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if cond():
                return
        except Exception:  # noqa: BLE001 — condition may race a restart
            pass
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def _read_incidents(job_dir):
    out = []
    if not os.path.isdir(job_dir):
        return out
    for name in os.listdir(job_dir):
        if not name.startswith("incidents-"):
            continue
        with open(os.path.join(job_dir, name), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    return out


def _has(incidents, name, state=None):
    return any(r.get("name") == name
               and (state is None or r.get("state") == state)
               for r in incidents)


def _grep_logs(root, needle):
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            p = os.path.join(dirpath, fn)
            try:
                with open(p, errors="replace") as f:
                    if needle in f.read():
                        return p
            except OSError:
                continue
    return None


class Pool:
    """The out-of-band actuator: spawn/kill launcher + replica
    processes to match the controller's desired sizes."""

    def __init__(self, coord_ep, tmp):
        self.coord_ep = coord_ep
        self.tmp = tmp
        self.launchers = {}      # name -> Popen
        self.replicas = {}       # rid -> Popen
        self._n = 0

    def spawn_launcher(self, job, name, nodes_range, extra_env=None):
        env = dict(os.environ)
        env.update(FAST)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["EDL_TPU_DEMO_MARKER"] = os.path.join(self.tmp,
                                                  f"marker-{job}.txt")
        env.update(extra_env or {})
        log = open(os.path.join(self.tmp, f"launcher-{job}-{name}.log"),
                   "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.collective.launch",
             "--job_id", job, "--coord_endpoints", self.coord_ep,
             "--nodes_range", nodes_range, "--nproc_per_node", "1",
             "--log_dir", os.path.join(self.tmp, f"log-{job}-{name}"),
             _TRAINER],
            env=env, cwd=self.tmp, stdout=log, stderr=subprocess.STDOUT)
        proc._logfile = log  # noqa: SLF001
        self.launchers[f"{job}-{name}"] = proc
        return proc

    def spawn_replica(self, rid):
        env = dict(os.environ, EDL_TPU_METRICS_PORT="")
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c",
             _REPLICA_CHILD.format(repo=_REPO), self.coord_ep, rid],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "replica up" in line:
                self.replicas[rid] = proc
                return proc
            if not line and proc.poll() is not None:
                raise AssertionError(f"replica {rid} died before announcing")
        raise AssertionError(f"replica {rid} never announced")

    def alive_launchers(self, job):
        return [n for n, p in self.launchers.items()
                if n.startswith(job + "-") and p.poll() is None]

    def alive_replicas(self):
        return [r for r, p in self.replicas.items() if p.poll() is None]

    # the controller's Actuator surface
    def scale(self, job_id, replicas):
        if job_id == "svc":
            live = self.alive_replicas()
            for i in range(len(live), replicas):
                self._n += 1
                self.spawn_replica(f"r{self._n}")
            for rid in live[replicas:]:
                self.replicas[rid].send_signal(signal.SIGTERM)
        elif job_id == "train":
            live = self.alive_launchers("train")
            for i in range(len(live), replicas):
                self._n += 1
                self.spawn_launcher("train", f"re{self._n}", "1:3",
                                    {"EDL_TPU_SMOKE_STEP_S": "0.05"})
        return True

    def kill_all(self):
        for p in list(self.launchers.values()) + list(self.replicas.values()):
            if p.poll() is None:
                p.kill()
        for p in self.launchers.values():
            try:
                p._logfile.close()  # noqa: SLF001
            except Exception:  # noqa: BLE001 — teardown
                pass


def main() -> None:
    from edl_tpu import obs
    from edl_tpu.cluster import scale as scale_mod
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.recovery import summarize_recovery
    from edl_tpu.coord.client import connect
    from edl_tpu.coord.server import start_server
    from edl_tpu.controller import Controller
    from edl_tpu.gateway import Gateway, GatewayConfig
    from edl_tpu.gateway.fleet import list_replicas
    from edl_tpu.obs import advert as obs_advert
    from edl_tpu.obs.agg import Aggregator, AggregatorServer
    from edl_tpu.utils.exceptions import EdlOverloadedError

    obs.install_from_env("gateway")
    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    store = connect(coord_ep)
    pool = Pool(coord_ep, _TMP)
    inc_dir = {j: os.path.join(_TMP, "incidents", j)
               for j in ("train", "distill", "svc")}
    stall_file = os.path.join(_TMP, "stall-distill")

    aggs, agg_srv, gw, ctl = [], None, None, None
    try:
        # -- boot the three job kinds ------------------------------------
        scale_mod.save_job_spec(store, "train", kind="training")
        scale_mod.save_job_spec(store, "distill", kind="distill", gang=True)
        scale_mod.save_job_spec(store, "svc", kind="serving")
        scale_mod.save_nodes_range(store, "svc", 1, 4)
        for name, step in (("a", "0.05"), ("b", "0.05"), ("c", "0.3")):
            pool.spawn_launcher("train", name, "1:3",
                                {"EDL_TPU_SMOKE_STEP_S": step})
        pool.spawn_launcher("distill", "d0", "1:1",
                            {"EDL_TPU_SMOKE_STEP_S": "0.05",
                             "EDL_TPU_SMOKE_STALL_FILE": stall_file})
        pool.spawn_replica("r0")
        pool.spawn_replica("r1")
        obs_advert.advertise_installed(store, "svc", "gateway")

        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) == 3, 60, "train cluster of 3")
        _wait(lambda: Cluster.load_from_store(store, "distill") is not None,
              60, "distill cluster")
        _wait(lambda: len(list_replicas(store, "svc")) == 2, 30,
              "2 replica adverts")

        # one aggregator + armed dispatcher per job (the svc one behind
        # HTTP so /alerts carries the recent-actions audit)
        for job in ("train", "distill"):
            agg = Aggregator(store, job, cache_s=0.0, scrape_interval=0.25,
                             incident_dir=inc_dir[job])
            agg.start_loop()
            aggs.append(agg)
        agg_srv = AggregatorServer(store, "svc", host="127.0.0.1",
                                   cache_s=0.0, scrape_interval=0.25,
                                   incident_dir=inc_dir["svc"]).start()

        gw = Gateway(store, "svc", GatewayConfig(
            max_inflight=8, max_queue=16, rate=4.0, burst=4.0,
            request_timeout_s=30.0, wait_slice_s=0.05, poll_period_s=0.1))

        ctl = Controller(store, capacity=6, max_load_desired=1.0,
                         actuator=pool, cooldown=1.0,
                         cooldown_per_resize_s=0.0,
                         preempt_grace_s=30.0, period=0.5,
                         alerts_url=f"http://{agg_srv.endpoint}/alerts")
        assert sorted(ctl.discover_jobs()) == ["distill", "svc", "train"] \
            or set(ctl.discover_jobs()) == {"train", "distill", "svc"}
        ctl.start()

        # -- 1: arbitration baseline — nobody flaps ----------------------
        time.sleep(3.0)
        assert len(Cluster.load_from_store(store, "train").pods) == 3
        assert len(pool.alive_replicas()) == 2
        print("smoke 1: three job kinds under one controller, "
              "baseline stable (train=3 distill=1 svc=2 of capacity 6)")

        # -- 2: straggler -> evict through the preemption path -----------
        _wait(lambda: _has(_read_incidents(inc_dir["train"]),
                           "alert/trainer-straggler", "firing"),
              90, "trainer-straggler to fire on the slow pod")
        _wait(lambda: _has(_read_incidents(inc_dir["train"]),
                           "action/evict", "ok"),
              30, "the evict action to run")
        # the slow launcher (train-c) departs DESCALED (exit 0) — not a
        # crash; the controller is free to RECLAIM the freed slot with a
        # replacement pod afterwards, so pod count is not the signal
        _wait(lambda: pool.launchers["train-c"].poll() == 0, 90,
              "the evicted launcher to exit 0 (DESCALED, not a crash)")
        _wait(lambda: _grep_logs(_TMP, "reason=straggler-evict") is not None,
              30, "the evicted pod's workerlog to carry the reason")
        _wait(lambda: any(s.get("evicted")
                          and "straggler-evict" in s["evicted"].values()
                          for s in summarize_recovery(store, "train")),
              30, "the recovery record to carry the eviction reason")
        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) >= 2, 60,
              "the surviving train pods to keep running")
        print("smoke 2: straggler evicted via preemption grace "
              "(workerlog + recovery record carry reason=straggler-evict), "
              "survivors kept training")

        # -- 3: hang -> targeted in-place restart ------------------------
        d_launcher = pool.launchers["distill-d0"]
        d_pid = d_launcher.pid
        d_stage = Cluster.load_from_store(store, "distill").stage
        # cross-job blast radius: every train launcher alive NOW must
        # still be alive after the distill job heals
        train_alive = [pool.launchers[n] for n in
                       pool.alive_launchers("train")]
        marker = os.path.join(_TMP, "marker-distill.txt")
        starts_before = sum(1 for _ in open(marker))
        with open(stall_file, "w") as f:
            f.write("wedged\n")
        _wait(lambda: _has(_read_incidents(inc_dir["distill"]),
                           "alert/trainer-hang", "firing"),
              90, "trainer-hang to fire on the wedged distill trainer")
        _wait(lambda: _has(_read_incidents(inc_dir["distill"]),
                           "action/restart", "ok"),
              30, "the restart action to run")
        os.remove(stall_file)
        _wait(lambda: sum(1 for _ in open(marker)) > starts_before, 60,
              "the distill trainer to be respawned in place")
        assert d_launcher.poll() is None and d_launcher.pid == d_pid, \
            "the launcher process must survive a targeted restart"
        assert Cluster.load_from_store(store, "distill").stage == d_stage, \
            "a targeted restart must not change the cluster stage"
        assert all(p.poll() is None for p in train_alive), \
            "a distill restart must not touch the healthy train job"
        _wait(lambda: _has(_read_incidents(inc_dir["distill"]),
                           "alert/trainer-hang", "resolved"),
              60, "trainer-hang to resolve after the restart")
        rec = [r for r in _read_incidents(inc_dir["distill"])
               if r["name"] == "action/restart" and r["state"] == "ok"]
        assert rec and rec[0].get("detail", {}).get("mode") == "targeted", rec
        print(f"smoke 3: trainer-hang healed by a targeted in-place "
              f"restart (launcher pid {d_pid} unchanged, stage unchanged, "
              f"alert resolved)")

        # -- 4: gateway spike -> scale-out, zero lost accepted ------------
        futures, rejects = [], 0
        t_end = time.time() + 12.0
        while time.time() < t_end:
            try:
                futures.append(gw.submit([7], 4))
            except EdlOverloadedError:
                rejects += 1
            time.sleep(0.08)                    # ~12 req/s vs rate 4/s
        assert rejects > 0, "the spike never saturated admission"
        _wait(lambda: _has(_read_incidents(inc_dir["svc"]),
                           "alert/gateway-reject-burn", "firing"),
              60, "gateway-reject-burn to fire")
        _wait(lambda: _has(_read_incidents(inc_dir["svc"]),
                           "action/scale-out", "ok"),
              30, "the scale-out action to run")
        _wait(lambda: len(list_replicas(store, "svc")) >= 3, 90,
              "the scaled-out replica to appear in the advert table")
        lost = 0
        for fut in futures:
            if fut.exception(timeout=60) is not None:
                lost += 1
        assert lost == 0, f"{lost}/{len(futures)} accepted requests lost"
        alerts_body = json.loads(__import__("urllib.request", fromlist=["r"])
                                 .urlopen(f"http://{agg_srv.endpoint}/alerts",
                                          timeout=10).read().decode())
        acts = alerts_body.get("actions", [])
        assert any(a["action"] == "scale-out" and a["outcome"] == "ok"
                   for a in acts), acts
        assert alerts_body.get("breakers", {}).get("scale-out") == "closed"
        print(f"smoke 4: spike absorbed — {len(futures)} accepted requests "
              f"all completed ({rejects} shed at admission), fleet scaled "
              f"out to {len(list_replicas(store, 'svc'))} replicas, "
              f"audit on /alerts")

        # -- 5: priority yield + reclaim ---------------------------------
        train_cluster = Cluster.load_from_store(store, "train")
        scale_mod.save_demand(store, "svc", 4, reason="gateway-p99-slo")
        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) == 1, 90,
              "training to yield a pod to serving demand")
        _wait(lambda: _grep_logs(_TMP, "reason=priority-yield") is not None,
              30, "the yielded pod's workerlog to carry priority-yield")
        _wait(lambda: len(pool.alive_replicas()) >= 4, 90,
              "the fleet to scale out to the demanded 4")
        # quiet: the demand record ages out, the autoscaler decays the
        # fleet and training reclaims the chips (replacement launchers)
        scale_mod.clear_demand(store, "svc")
        _wait(lambda: len(pool.alive_replicas()) <= 2, 120,
              "the fleet to scale back in on sustained quiet")
        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) >= 2, 120,
              "training to reclaim capacity after the spike")
        print("smoke 5: training yielded to serving demand "
              "(reason=priority-yield) and reclaimed the chips on quiet")

        # -- 6: the audit trail ------------------------------------------
        chains = {
            "train": [("alert/trainer-straggler", "firing"),
                      ("action/evict", "ok")],
            "distill": [("alert/trainer-hang", "firing"),
                        ("action/restart", "ok"),
                        ("alert/trainer-hang", "resolved")],
            "svc": [("alert/gateway-reject-burn", "firing"),
                    ("action/scale-out", "ok")],
        }
        for job, chain in chains.items():
            recs = _read_incidents(inc_dir[job])
            for name, state in chain:
                assert _has(recs, name, state), \
                    f"{job}: missing {name}/{state} in the incident log"
        print("smoke 6: incident logs show every alert -> action -> "
              "recovery handoff")
    except BaseException:
        sys.stdout.flush()
        for root, _dirs, files in os.walk(_TMP):
            for fn in files:
                if fn.endswith(".log"):
                    p = os.path.join(root, fn)
                    print(f"==== {p} ====")
                    print(open(p, errors="replace").read()[-4000:])
        raise
    finally:
        if ctl is not None:
            ctl.stop()
        if gw is not None:
            gw.close()
        for agg in aggs:
            agg.stop_loop()
        if agg_srv is not None:
            agg_srv.stop()
        pool.kill_all()
        store.close()
        coord.stop()
    print("remediation smoke OK")


if __name__ == "__main__":
    main()
