"""CI data-plane chaos smoke: leader death, producer death, injected
faults — and still every record trains exactly once.

The full data-plane fault-tolerance story against REAL processes:

1. A durable coord server (WAL, SIGKILL-proof — the PR-6 substrate),
   TWO data-leader candidates contending for the exclusive seat
   (``edl_tpu.data.leader``, journaled DataService), and THREE pod
   processes each producing + consuming through a
   :class:`DistributedReader` over the resilient data-RPC client, with
   transport faults injected on every data RPC and coord put
   (``EDL_TPU_FAULTS``).
2. Mid-epoch the ACTIVE leader is SIGKILLed: the standby seizes the
   seat within one TTL, **rebuilds every generation from the coord
   journal**, readers re-resolve + reattach, and the epoch continues —
   ``data_leader_mttr_s`` is recorded and gated.  No stop-resume, no
   restart.
3. Later one pod is SIGKILLed mid-epoch: its registry advert expires,
   the leader requeues its files and unconsumed batches *minus the
   consumed union*, and the survivors finish the epoch.
4. The exactly-once audit over the pods' raw span logs gates the whole
   run: the union of trained spans equals the file set, ZERO records
   dropped, and duplicates are permitted ONLY inside the killed pod's
   own consumed-but-unacked tail (the documented at-least-once caveat
   of consumer death) — never among survivors.
5. The surviving pods report their ``edl_data_rpc_retries_total``:
   the injected faults and the failover must be visible as retries in
   metrics, with ZERO reader failures.

Since ISSUE 11 the readers deliver over the STREAMED path by default
(framed ``get_batch_stream`` groups + multi-worker prefetch), so the
SIGKILLs here land mid-stream and mid-prefetch — this smoke is the
chaos audit of that pipeline, not just of the per-batch fallback.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/data_chaos_smoke.py
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EDL_TPU_TTL", "2")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TTL = 2.0
JOB = "data-chaos"
READER = "chaos@e0"
N_FILES, PER_FILE, BATCH = 16, 50, 4
POD_FAULTS = ("client:get_batch_meta:error:0.05;"
              "client:next_file:error:0.05;"
              "client:report_batch_meta:error:0.05;"
              "client:kv_put:error:0.05")
LEADER_FAULTS = "client:kv_put:error:0.1"


# ---------------------------------------------------------------------------
# pod worker (re-exec'd role): produce + consume + audit-log every batch
# ---------------------------------------------------------------------------

def run_pod(args) -> int:
    from edl_tpu.coord.client import connect_wait
    from edl_tpu.data import DistributedReader, PodDataServer, register_reader
    from edl_tpu.data.leader import resolve_data_leader
    from edl_tpu.data.resilient import _RETRIES

    store = connect_wait(args.coord_endpoints)
    files = sorted(os.path.join(args.data_dir, f)
                   for f in os.listdir(args.data_dir))
    server = PodDataServer(args.pod_id)
    reg = register_reader(store, JOB, READER, args.pod_id, server.endpoint)
    reader = DistributedReader(
        READER, args.pod_id, lambda: resolve_data_leader(store, JOB),
        server, batch_size=BATCH, retry_deadline=90.0)
    reader.create(files)
    audit = open(args.audit, "a", buffering=1)
    consumed = 0
    for bid, payload in reader:
        audit.write(json.dumps({"pod": args.pod_id, "bid": bid,
                                "spans": payload["spans"]}) + "\n")
        consumed += len(payload["records"])
        time.sleep(args.step_sleep)
    retries = sum(_RETRIES.labels(op=op).value
                  for op in ("create_reader", "next_file",
                             "report_batch_meta", "get_batch_meta",
                             "file_done", "nack_batches"))
    audit.write(json.dumps({"pod": args.pod_id, "done": True,
                            "records": consumed,
                            "data_rpc_retries": retries}) + "\n")
    audit.close()
    # keep serving the local batch cache briefly: peers may still hold
    # metas pointing at it (exiting instantly would force nack churn)
    time.sleep(2 * TTL)
    reg.stop()
    server.stop()
    store.close()
    return 0


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def _spawn_coord(port: int, data_dir: str) -> subprocess.Popen:
    from edl_tpu.coord.server import spawn_subprocess
    env = dict(os.environ, EDL_TPU_TTL=str(TTL))
    env.pop("EDL_TPU_METRICS_PORT", None)
    env.pop("EDL_TPU_FAULTS", None)
    return spawn_subprocess(port, data_dir, restart_grace=TTL, env=env)


def _spawn_leader(coord_ep: str, tmp: str, name: str) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", EDL_TPU_TTL=str(TTL),
               EDL_TPU_FAULTS=LEADER_FAULTS, EDL_TPU_FAULTS_SEED="11",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("EDL_TPU_METRICS_PORT", None)
    log = open(os.path.join(tmp, f"leader-{name}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.data.leader",
         "--coord_endpoints", coord_ep, "--job_id", JOB,
         "--host", "127.0.0.1", "--ttl", str(TTL),
         "--rebuild_grace", "3.0"],
        env=env, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    proc._logpath = os.path.join(tmp, f"leader-{name}.log")  # noqa: SLF001
    return proc


def _spawn_pod(coord_ep: str, tmp: str, data_dir: str, pod_id: str,
               seed: int) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", EDL_TPU_TTL=str(TTL),
               EDL_TPU_FAULTS=POD_FAULTS, EDL_TPU_FAULTS_SEED=str(seed),
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("EDL_TPU_METRICS_PORT", None)
    log = open(os.path.join(tmp, f"pod-{pod_id}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--role", "pod",
         "--coord_endpoints", coord_ep, "--pod_id", pod_id,
         "--data_dir", data_dir,
         "--audit", os.path.join(tmp, f"audit-{pod_id}.jsonl"),
         "--step_sleep", "0.1"],
        env=env, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    return proc


def _write_data(data_dir: str) -> None:
    os.makedirs(data_dir, exist_ok=True)
    for f in range(N_FILES):
        with open(os.path.join(data_dir, f"part-{f:02d}.txt"), "w") as fh:
            for r in range(PER_FILE):
                fh.write(f"f{f}r{r}\n")


def _seat_endpoint(store) -> str | None:
    from edl_tpu.data.leader import _seat_key
    rec = store.get(_seat_key(JOB))
    return rec.value.decode() if rec is not None and rec.value else None


def _consumed_batches(tmp: str, pods: list[str]) -> int:
    n = 0
    for pod in pods:
        path = os.path.join(tmp, f"audit-{pod}.jsonl")
        if os.path.exists(path):
            with open(path) as fh:
                n += sum(1 for line in fh if '"spans"' in line)
    return n


def _load_audit(tmp: str, pod: str) -> tuple[list, dict | None]:
    spans, final = [], None
    path = os.path.join(tmp, f"audit-{pod}.jsonl")
    if not os.path.exists(path):
        return spans, final
    with open(path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail of a SIGKILLed pod
            if rec.get("done"):
                final = rec
            else:
                spans.extend(rec["spans"])
    return spans, final


def _dump_dup_forensics(tmp: str, pods: list[str]) -> None:
    """On audit failure: which pods trained each multi-trained record,
    via which batch ids — names the double-production path for triage."""
    by_record: dict = {}
    for pod in pods:
        path = os.path.join(tmp, f"audit-{pod}.jsonl")
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("done"):
                    continue
                for f, b, e in rec["spans"]:
                    for r in range(b, e):
                        by_record.setdefault((f, r), []).append(
                            (pod, rec.get("bid", "?")))
    dups = {k: v for k, v in by_record.items() if len(v) > 1}
    print(f"data-chaos FORENSICS: {len(dups)} multi-trained records")
    for k in sorted(dups)[:40]:
        print(f"  record {k}: {dups[k]}")


def main() -> None:
    sys.path.insert(0, REPO)  # tests.helpers
    from edl_tpu.coord.client import connect
    from edl_tpu.utils.network import find_free_ports
    from tests.helpers.exactly_once import audit_spans, span_counts

    out: dict = {}
    tmp = tempfile.mkdtemp(prefix="edl-data-chaos-")
    data_dir = os.path.join(tmp, "data")
    _write_data(data_dir)
    port = find_free_ports(1)[0]
    coord_ep = f"127.0.0.1:{port}"
    pods = ["pod-0", "pod-1", "pod-2"]
    coord = _spawn_coord(port, os.path.join(tmp, "coord"))
    leaders: list[subprocess.Popen] = []
    pod_procs: dict[str, subprocess.Popen] = {}
    store = None
    try:
        from edl_tpu.coord.server import wait_ready
        wait_ready(coord_ep, 120.0)
        store = connect(coord_ep)
        leaders = [_spawn_leader(coord_ep, tmp, "a"),
                   _spawn_leader(coord_ep, tmp, "b")]
        deadline = time.time() + 60
        while _seat_endpoint(store) is None:
            assert time.time() < deadline, "no data leader ever seated"
            time.sleep(0.1)
        active_ep = _seat_endpoint(store)
        print(f"data-chaos: leader seated at {active_ep}")

        for i, pod in enumerate(pods):
            pod_procs[pod] = _spawn_pod(coord_ep, tmp, data_dir, pod,
                                        seed=100 + i)

        # --- phase 1: SIGKILL the ACTIVE leader mid-epoch ---------------
        deadline = time.time() + 120
        while _consumed_batches(tmp, pods) < 20:
            assert time.time() < deadline, "pods never started consuming"
            for pod, proc in pod_procs.items():
                assert proc.poll() is None, f"{pod} died in warmup"
            time.sleep(0.2)
        victim = next(p for p in leaders
                      if f"serving on {active_ep}" in open(
                          p._logpath, errors="replace").read())  # noqa: SLF001
        t_kill = time.monotonic()
        victim.kill()
        victim.wait(timeout=30)
        print(f"data-chaos: SIGKILLed active leader {active_ep}")
        deadline = time.time() + 60
        new_ep = None
        while time.time() < deadline:
            new_ep = _seat_endpoint(store)
            if new_ep is not None and new_ep != active_ep:
                break
            time.sleep(0.05)
        assert new_ep is not None and new_ep != active_ep, \
            "standby never seized the data-leader seat"
        # MTTR = kill -> the successor ANSWERS for the rebuilt generation
        from edl_tpu.rpc.client import RpcClient
        cli = RpcClient(new_ep, timeout=5.0)
        while True:
            assert time.time() < deadline, "successor never answered"
            try:
                st = cli.call("reader_status", reader=READER)
                break
            except Exception:  # noqa: BLE001 — booting/rebuilding
                time.sleep(0.05)
        cli.close()
        mttr = time.monotonic() - t_kill
        out["data_leader_mttr_s"] = round(mttr, 3)
        assert st["files"] == N_FILES, st
        standby_log = next(p._logpath for p in leaders  # noqa: SLF001
                           if p.poll() is None)
        print(f"data-chaos: standby {new_ep} took over in {mttr:.2f}s "
              f"({st['parked']} parked, {len(st['consumed'])} consumed "
              f"files rebuilt)")

        # --- phase 2: SIGKILL one pod mid-epoch -------------------------
        before = _consumed_batches(tmp, pods)
        deadline = time.time() + 120
        while _consumed_batches(tmp, pods) < before + 20:
            assert time.time() < deadline, "no progress after failover"
            time.sleep(0.2)
        pod_procs["pod-2"].kill()
        pod_procs["pod-2"].wait(timeout=30)
        print("data-chaos: SIGKILLed pod-2 mid-epoch")

        # --- survivors finish the epoch ---------------------------------
        for pod in ("pod-0", "pod-1"):
            rc = pod_procs[pod].wait(timeout=300)
            assert rc == 0, (
                f"{pod} failed rc={rc}:\n"
                + open(os.path.join(tmp, f"pod-{pod}.log"),
                       errors="replace").read()[-3000:])
        print("data-chaos: surviving pods drained the epoch (rc=0)")

        # --- the exactly-once audit ------------------------------------
        all_spans: list = []
        finals = {}
        for pod in pods:
            spans, final = _load_audit(tmp, pod)
            all_spans.extend(spans)
            finals[pod] = final
        killed_spans, _ = _load_audit(tmp, "pod-2")
        killed_records = set(span_counts(killed_spans))
        try:
            stats = audit_spans(all_spans, N_FILES, PER_FILE,
                                allow_duplicates_of=killed_records)
        except AssertionError:
            _dump_dup_forensics(tmp, pods)
            raise
        out.update(stats)
        # duplicates among SURVIVORS alone are forbidden outright
        surv_spans = []
        for pod in ("pod-0", "pod-1"):
            surv_spans.extend(_load_audit(tmp, pod)[0])
        surv_dups = {k: c for k, c in span_counts(surv_spans).items()
                     if c > 1}
        assert not surv_dups, (
            f"survivors double-trained {len(surv_dups)} records: "
            f"{sorted(surv_dups)[:10]}")
        retries = sum((finals[p] or {}).get("data_rpc_retries", 0)
                      for p in ("pod-0", "pod-1"))
        out["data_rpc_retries"] = int(retries)
        assert retries > 0, \
            "faults + failover must be visible as data-RPC retries"
        log_text = open(standby_log, errors="replace").read()
        assert "rebuilt from journal" in log_text, \
            f"standby never rebuilt from the journal:\n{log_text[-2000:]}"
        assert out["data_leader_mttr_s"] < 30.0, out
        print(f"data-chaos: {stats['records_total']} records — "
              f"{stats['records_exactly_once']} exactly once, "
              f"{stats['records_duplicated']} duplicated (all inside the "
              f"killed pod's unacked tail), 0 dropped; "
              f"{int(retries)} reader retries, 0 reader failures")
        print("DATA_CHAOS " + json.dumps(out))
        print("data chaos smoke OK")
    finally:
        for proc in list(pod_procs.values()) + leaders + [coord]:
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGKILL)
                    proc.wait(timeout=30)
                except Exception:  # noqa: BLE001 — teardown
                    pass
        if store is not None:
            store.close()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--role", default="main", choices=("main", "pod"))
    p.add_argument("--coord_endpoints")
    p.add_argument("--pod_id")
    p.add_argument("--data_dir")
    p.add_argument("--audit")
    p.add_argument("--step_sleep", type=float, default=0.1)
    args = p.parse_args()
    if args.role == "pod":
        raise SystemExit(run_pod(args))
    main()
