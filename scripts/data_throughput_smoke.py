"""CI smoke for streamed batch delivery: run the data-delivery
microbench (2 producer pods + 1 consumer over loopback — the same code
path as ``bench.py``'s delivery section) and gate it two ways:

- **throughput**: the streamed pipeline (framed ``get_batch_stream``
  groups + multi-worker prefetch) must not lose to the legacy
  per-batch request/reply consumer.  The fetch ops carry a small
  injected per-dispatch wire delay (see ``_bench_data_delivery``) —
  loopback RTT is ~0 and would hide exactly the round-trip-per-batch
  cost the streamed transport removes; with it, the comparison is
  structural: the same work with ~8x fewer request round trips cannot
  be slower, so a loss here means the streamed path quietly demoted or
  the prefetcher collapsed — what this stage exists to catch.
- **exactly-once**: every run in the section (including the one that
  stops a producer's server mid-epoch) audits its raw span log — a
  drop or a duplicate fails the bench section itself, and this smoke
  re-asserts the counts on the artifact.

The absolute records/s land in the CI log for trend-eyeballing.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# small-but-real epoch: ~180 batches, best-of-2 to damp CI noise
os.environ.setdefault("EDL_TPU_BENCH_DELIVERY_FILES", "6")
os.environ.setdefault("EDL_TPU_BENCH_DELIVERY_RECORDS", "240")
os.environ.setdefault("EDL_TPU_BENCH_DELIVERY_REPS", "2")

from edl_tpu.bench import _bench_data_delivery  # noqa: E402


def main() -> int:
    r = _bench_data_delivery()
    print(json.dumps(r))
    streamed = r["data_delivery_samples_s"]
    per_batch = r["data_delivery_rpc_samples_s"]
    print(f"data throughput smoke: streamed={streamed} rec/s, "
          f"per-batch={per_batch} rec/s "
          f"({r['data_delivery_stream_ratio']:.2f}x), consumed="
          f"{r['data_delivery_consumed_samples_s']} rec/s "
          f"(stall {r['data_delivery_consumed_stall_s']}s), "
          f"pod-loss={r['data_delivery_pod_loss_samples_s']} rec/s")
    if streamed < per_batch:
        print("FAIL: streamed delivery slower than the per-batch "
              "request/reply baseline", file=sys.stderr)
        return 1
    # the bench audits every epoch internally (and raises on failure);
    # assert the artifact agrees so a silent audit regression cannot
    # pass this stage
    if r.get("data_delivery_records", 0) <= 0:
        print("FAIL: delivery bench reported no audited records",
              file=sys.stderr)
        return 1
    print("data throughput smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
