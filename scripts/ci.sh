#!/usr/bin/env bash
# CI: install the package and run the suite (the reference's
# scripts/build.sh:67-74 booted an external etcd before ctest; our
# coordination store is in-tree, so the suite is self-contained).
set -euo pipefail
cd "$(dirname "$0")/.."

# air-gapped runners (deps preinstalled) fall back to no-build-isolation
python -m pip install -e ".[image,test]" \
    || python -m pip install -e . --no-deps --no-build-isolation

# static-analysis gate (edl-lint, doc/lint.md): project-aware AST
# checks for the defect classes PRs 6-8 kept re-finding by hand —
# blocking I/O under service locks, lock-order cycles, untyped errors
# on the RPC wire, wall-clock deadlines, untracked threads, knob- and
# metric-catalog drift.  Fails on any NEW finding or any STALE waiver
# against the committed lint_baseline.json (the baseline only ratchets
# down); runs before the test tiers because it is seconds, not minutes
python -m edl_tpu.lint --root .

# fast tier: everything but the multi-process e2e tests
python -m pytest tests/ -q -m "not slow"

# full tier (FULL=1): launcher/jax.distributed end-to-end + the live
# recovery-time measurement (north-star metric)
if [[ "${FULL:-0}" == "1" ]]; then
    python -m pytest tests/ -q -m slow
    python examples/collective/recovery_bench.py
fi

# observability smoke: a few real trainer steps with the /metrics
# endpoint enabled, fetched over HTTP and parsed back — the
# step-latency and resize-phase series must be present, and the dump
# CLI must reproduce summarize_recovery's per-phase totals
JAX_PLATFORMS=cpu python scripts/obs_smoke.py

# memstate smoke: two-pod kill-one-restore-from-peer on the CPU mesh —
# a checkpoint teed into pod A's in-RAM cache + ring-replicated to pod
# B must restore bit-identically from B alone after A dies, and a
# checksum-corrupted replica must fall back to Orbax storage
JAX_PLATFORMS=cpu python scripts/memstate_smoke.py

# gateway smoke: 2 replica processes + gateway on the virtual CPU mesh —
# SIGKILL one under sustained load and every accepted request must still
# complete on the survivor; a saturated gateway must reject (not hang);
# edl_gateway_*/edl_serving_* metrics and route/hedge/retry trace spans
# must be served; a gateway-stamped trace_id must reach a REPLICA
# process's spans and merge into one ordered Perfetto-exportable timeline
JAX_PLATFORMS=cpu python scripts/gateway_smoke.py

# kv cache smoke: the paged KV cache's three contracts — paged-vs-
# unpaged greedy outputs byte-identical over a mixed shared-prefix +
# divergent-session workload; the heavy-prefix bench section gates
# prefix-hit tokens/s >= cold tokens/s with prefill-skipped frac > 0.5
# and a real migration latency; and a SIGTERM-drain of a replica
# PROCESS under sustained sessions loses zero accepted requests while
# >=1 session chain migrates and resumes on the survivor WITHOUT
# re-prefilling (pin advert + moving kv_prefill_tokens_skipped)
JAX_PLATFORMS=cpu python scripts/kv_cache_smoke.py

# chaos smoke: SIGKILL + restart the durable coord server mid-training
# AND mid-serving — WAL replay must restore revision counter, lease
# table and keys bit-exactly; training must resume without
# restore-from-scratch (one trainer start, no membership-changed path);
# zero accepted gateway requests lost; every advert (resource, memstate,
# serving, obs) back within one TTL + restart grace; coord_restart_mttr_s
# recorded; and the EDL_TPU_FAULTS injection harness must fire and be
# healed by the resilient client
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

# data chaos smoke: durable coord + two elected data-leader candidates
# + three reader pods with faults injected on every data RPC — SIGKILL
# the ACTIVE leader mid-epoch (standby seizes the seat, rebuilds every
# generation from the coord journal, readers reattach; data_leader_mttr_s
# gated) and SIGKILL a producer pod mid-epoch (its files requeue minus
# consumed spans); the exactly-once audit over the raw span logs must
# show zero drops and zero duplicates outside the killed pod's unacked
# tail, with zero reader failures (retries visible in metrics only)
JAX_PLATFORMS=cpu python scripts/data_chaos_smoke.py

# resize smoke: delta-resharding instead of stop-resume — grow-by-one
# and shrink-by-one must complete WITHOUT killing surviving trainer
# processes (same PIDs, exactly one spawn per pod, resize_mode=delta in
# the recovery record, every restore bit-verified against storage), and
# a SIGKILL of the shard-holding leader pod mid-reshard must fall back
# cleanly to the proven stop-resume path and still SUCCEED
JAX_PLATFORMS=cpu python scripts/resize_smoke.py

# delta failover smoke: sub-checkpoint-loss recovery — real launchers +
# durable coord, one pod SIGKILLed mid-delta-interval (sealed chain
# records observably past the committed checkpoint): the job must
# SUCCEED with restore_source=delta, the restore must land at/past the
# freshest sealed step (steps lost <= the delta cadence, not the
# checkpoint interval), and the identical kill with the plane disabled
# must resume AT the checkpoint — badput-per-failure strictly below
# the stop-resume baseline
JAX_PLATFORMS=cpu python scripts/delta_failover_smoke.py

# obs-agg smoke: 2 child processes + parent — one trace_id propagated
# over the EDL1 wire into both children's trace files, the aggregator
# discovers all three via coord-store adverts and serves a merged
# Prometheus-parseable /metrics + /healthz, and edl-obs-dump --merge
# renders one cross-process timeline with valid Perfetto JSON
JAX_PLATFORMS=cpu python scripts/obs_agg_smoke.py

# alerts smoke: the closed observability loop — three trainer child
# processes scraped by a real aggregator background loop running the
# BUILT-IN ruleset (windows scaled): the straggler rule must fire on
# the slow pod, an EDL_TPU_FAULTS-injected stall must fire trainer-hang
# within the rule's window+hold, the incident JSONL record must carry
# the published generation trace_id and land inside that trace's
# edl-obs-dump --merge timeline, and a killed+rebuilt data leader must
# fire the data-leader MTTR rule off the reader's observed outage
JAX_PLATFORMS=cpu python scripts/alerts_smoke.py

# profiling + goodput smoke: the continuous-profiling layer end to
# end — a real instrumented trainer's phase ledger must account for
# >=95% of step wall time and publish live MFU; the aggregator's
# /healthz must carry the goodput block and a resize record must move
# edl_badput_seconds_total{reason="resize"} and nothing else; a
# straggler alert must auto-trigger a profile capture whose manifest
# carries the generation trace id and joins the merged timeline, with
# Perfetto counter tracks alongside the span rows
JAX_PLATFORMS=cpu python scripts/profiling_smoke.py

# remediation smoke: the self-driving cluster loop — three job kinds
# (real launchers + instrumented trainers, a gang distill pod, a
# fake-engine replica fleet behind a real gateway) arbitrated by ONE
# controller, with the alert->action dispatcher armed: a straggler is
# evicted through the preemption-grace path (workerlog + recovery
# record carry reason=straggler-evict), a wedged trainer is healed by
# a TARGETED in-place restart (launcher pid + cluster stage unchanged,
# healthy jobs untouched), a gateway load spike fires reject-burn and
# scales the replica fleet out with zero lost accepted requests,
# serving demand makes training yield a pod (reason=priority-yield)
# and reclaim it on quiet, and the per-job incident logs show every
# alert -> action -> recovery handoff
JAX_PLATFORMS=cpu python scripts/remediation_smoke.py

# postmortem smoke: the black-box flight recorder + bundle loop — an
# induced straggler must AUTOMATICALLY produce a self-contained bundle
# (flight-recorder rings from >=2 processes, TSDB window, coord
# dump_state, workerlog tail, incident record, all joined by the
# generation trace_id on the edl-obs-dump --merge timeline); a
# SIGKILLed aggregator restarted onto the same --history_dir must
# answer windowed rates immediately, resume the goodput observation
# window, and keep the straggler's original firing_since; and
# edl-obs-bundle --incident must reassemble the bundle from the
# durable pieces alone
JAX_PLATFORMS=cpu python scripts/postmortem_smoke.py

# distill chaos smoke: elastic distillation as a production workload
# (ISSUE 18) — real teacher child processes advertised through the
# serving table, a serving spike makes training yield a pod
# (reason=priority-yield in its workerlog) while the teacher floor
# holds, a student stream's backlog record grows the fleet 1->3
# through the controller's arbitration (and fires the distill-backlog
# alert), a teacher SIGKILL mid-epoch costs retries not rows (the
# 800-row stream audits exactly-once, in order), edl_distill_* gauges
# ride the merged /metrics + /healthz, and quiet decays the fleet back
JAX_PLATFORMS=cpu python scripts/distill_chaos_smoke.py

# fleet-sim smoke: the control-plane scale observatory (doc/scale.md)
# at CI-scale decades (N=25/100/400) — a real durable coord server +
# real aggregator under N pod actors; gates: watch-based membership
# propagation stays flat (<2x smallest->largest N) while poll-based
# propagation visibly grows, the scrape cycle stays bounded at the
# largest N, ZERO coord op failures, and the report renderer parses
# its own SIM artifact with growth exponents
JAX_PLATFORMS=cpu python scripts/fleet_sim_smoke.py

# transfer smoke: the streaming data plane's microbench (loopback,
# small payload, subprocess holders) — pipelined/striped fetch must not
# regress below the serial baseline, and the MiB/s numbers land in the
# CI log so throughput trends are visible per run
JAX_PLATFORMS=cpu python scripts/transfer_smoke.py

# data throughput smoke: streamed batch delivery (framed
# get_batch_stream groups + multi-worker prefetch) must not lose to
# the legacy per-batch request/reply consumer under a modeled wire
# RTT, and every epoch in the section — including the one that stops
# a producer mid-epoch — must audit exactly-once
JAX_PLATFORMS=cpu python scripts/data_throughput_smoke.py

# serving perf smoke: the big-model fast path — a tp=2 CPU-mesh
# replica with the sharded paged pool + chunked prefill + self-draft
# speculation behind a real gateway (mixed traffic, bit-exact), the
# chunked starvation bound (warm-short p99 within 2x of monolithic),
# and 100+ prompts bit-identical spec vs plain greedy
JAX_PLATFORMS=cpu python scripts/serving_perf_smoke.py

# bench smoke: the driver's bench entry must always produce its JSON
# line (tiny CPU knobs; LM/pipeline sections skipped off-TPU).  bench
# now exits 0 even on failure (partial-artifact contract), so CI must
# assert the artifact is COMPLETE — no error/partial keys, real value
EDL_TPU_BENCH_SIZE=32 EDL_TPU_BENCH_BS=4 EDL_TPU_BENCH_STEPS=2 \
EDL_TPU_BENCH_WIDTH=8 EDL_TPU_BENCH_PIPELINE=0 EDL_TPU_BENCH_LM=0 \
EDL_TPU_BENCH_MEMSTATE_MB=8 EDL_TPU_BENCH_TRANSFER_MB=8 \
EDL_TPU_BENCH_DELIVERY_FILES=2 EDL_TPU_BENCH_DELIVERY_RECORDS=96 \
EDL_TPU_BENCH_SERVING_REQS=6 EDL_TPU_BENCH_SERVING_LONG=96 \
EDL_TPU_BENCH_SERVING_CHUNK=16 \
JAX_PLATFORMS=cpu python bench.py | tail -1 \
    | python -c "
import json, sys
out = json.loads(sys.stdin.read())
assert 'error' not in out and not out.get('partial'), out
assert out.get('value'), out
# streamed data delivery (ISSUE 11) must land in the artifact
assert out.get('data_delivery_samples_s'), out
# alerting loop (ISSUE 9): detection latency must land near the rule's
# declared window+hold, and the background scrape loop must cost the
# step loop ~nothing (<2% target on real hosts; 5% absorbs 1-core CI
# noise without masking a pathological regression)
lat, bound = out['alert_detect_latency_s'], out['alert_rule_bound_s']
assert lat <= bound * 2 + 5, (lat, bound)
assert out['obs_scrape_overhead_pct'] < 5, out['obs_scrape_overhead_pct']
# live resize (ISSUE 12): delta-resharding must not lose to stop-resume
# on the same grow-by-one (it skips process respawn + jax cold import)
dl, sr = out['resize_delta_mttr_s'], out['resize_stop_resume_mttr_s']
assert dl <= sr, (dl, sr)
# delta replication plane (ISSUE 17): a cadence step must ship fewer
# bytes than a full shard set (only the hot slice changes), the chain
# restore must work, and an induced mid-interval failure must lose
# fewer steps on the chain path than the checkpoint rollback
assert out['delta_bytes_per_step_mb'] < out['delta_full_shard_mb'], out
assert out.get('delta_lag_p50_ms') is not None, out
assert out['delta_steps_lost_per_failure'] \
    < out['checkpoint_steps_lost_per_failure'], out
# continuous profiling (ISSUE 13): the per-step phase ledger must cost
# the hot loop under 2% of step time (measured directly, noise-immune)
assert out['step_phase_overhead_pct'] < 2, out['step_phase_overhead_pct']
# flight recorder (ISSUE 19): the always-on ring tap must cost the
# step loop under 2% (per-event delta measured directly, noise-immune)
# and a live bundle capture must complete and report its wall time
assert out['flightrec_overhead_pct'] < 2, out['flightrec_overhead_pct']
assert out.get('bundle_capture_seconds') is not None, out
# paged KV cache (ISSUE 14): on the shared-system-prompt workload the
# prefix-hit engine must not lose to cold prefill and must actually
# skip most of the prompt; the drain handoff must yield a latency
pw, pc = out['serving_prefix_tokens_s'], out['serving_cold_tokens_s']
assert pw >= pc, (pw, pc)
assert out['serving_prefill_skipped_frac'] > 0.5, out
assert out.get('serving_kv_migration_ms') is not None, out
# serving fast path (ISSUE 20): the mesh throughput, chunked-prefill
# p99, and spec accept-rate sections must land in the artifact, and
# the self-draft spec run must accept near-everything (bit-exactness
# itself is gated by tests + serving_perf_smoke)
assert out.get('serving_mesh_tokens_s'), out
assert out.get('serving_prefill_p99_ms') is not None, out
assert out['serving_spec_accept_rate'] > 0.9, out
# distill fleet elasticity (ISSUE 18): three teachers must beat one on
# the same slow-teacher stream (routing/fan-out actually helps), and a
# published backlog record must step the autoscaler's target promptly
s1, s3 = out['distill_student_rows_s_1'], out['distill_student_rows_s_3']
assert s3 >= s1, (s1, s3)
assert out.get('distill_backlog_scale_latency_s') is not None, out
print('bench smoke OK')"

# packaging sanity: console scripts resolve
edl-lint --help >/dev/null 2>&1 || { echo "edl-lint missing"; exit 1; }
edl-coord --help >/dev/null 2>&1 || { echo "edl-coord missing"; exit 1; }
edl-launch --help >/dev/null 2>&1 || { echo "edl-launch missing"; exit 1; }
edl-controller --help >/dev/null 2>&1 || { echo "edl-controller missing"; exit 1; }
edl-obs-dump --help >/dev/null 2>&1 || { echo "edl-obs-dump missing"; exit 1; }
edl-obs-agg --help >/dev/null 2>&1 || { echo "edl-obs-agg missing"; exit 1; }
edl-obs-top --help >/dev/null 2>&1 || { echo "edl-obs-top missing"; exit 1; }
edl-obs-bundle --help >/dev/null 2>&1 || { echo "edl-obs-bundle missing"; exit 1; }
edl-gateway --help >/dev/null 2>&1 || { echo "edl-gateway missing"; exit 1; }
edl-replica --help >/dev/null 2>&1 || { echo "edl-replica missing"; exit 1; }

# doc drift: every CLI the operator guide teaches must exist
for cmd in edl-coord edl-launch edl-controller edl-discovery edl-bench \
           edl-obs-dump edl-obs-agg edl-obs-top edl-obs-bundle \
           edl-gateway edl-replica edl-lint; do
    grep -q "$cmd" doc/usage.md || { echo "doc/usage.md missing $cmd"; exit 1; }
done
for f in examples/lm/serve_lm.py examples/collective/collector.py \
         examples/collective/recovery_bench.py \
         examples/collective/imagenet_to_recordio.py \
         examples/collective/decode_bench.py; do
    [[ -f "$f" ]] || { echo "missing $f"; exit 1; }
    grep -q "$(basename "$f")" doc/usage.md \
        || { echo "doc/usage.md missing $(basename "$f")"; exit 1; }
done
echo "CI OK"
