#!/usr/bin/env python
"""CI continuous-profiling + goodput smoke (scripts/ci.sh, ISSUE 13).

One instrumented run proves the whole layer end to end:

1. **phase ledger** — a real ``ElasticTrainer`` job runs with the
   step ledger on; the ledger's phase sum must account for >= 95% of
   step wall time (``edl_step_ledger_coverage_ratio``) and the live
   MFU gauges (``edl_tflops_per_chip`` / ``edl_mfu`` — shared
   obs/flops.py cost analysis, ``EDL_TPU_PEAK_TFLOPS`` pinned) must
   publish;
2. **goodput on /healthz** — a real AggregatorServer scrape loop over
   a 3-"trainer" fleet reports the goodput block; a resize record
   pushed through the unified recovery write path must move
   ``edl_badput_seconds_total{reason="resize"}`` by exactly its
   launcher span and NOTHING else (restore/hang/idle stay 0);
3. **profile-on-alert** — the built-in ``trainer-straggler`` rule
   (windows shrunk via ``EDL_TPU_ALERT_SCALE``) fires on the slow
   fleet member; the aggregator's ``action="profile"`` hook must GET
   that instance's ``/profile`` endpoint, and the capture manifest
   must land on disk carrying the published generation trace_id;
4. **timeline join** — the capture's ``profile/capture`` event and the
   ledger's ``train/step_phases`` events join the generation trace in
   ``edl-obs-dump --merge``, and the Perfetto export carries ``"C"``
   counter samples (step phases / goodput) next to the span rows.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/profiling_smoke.py
"""

import glob
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_TRACE_DIR = os.environ.setdefault("EDL_TPU_TRACE_DIR",
                                   tempfile.mkdtemp(prefix="edl-prof-"))
_PROFILE_DIR = os.environ.setdefault("EDL_TPU_PROFILE_DIR",
                                     tempfile.mkdtemp(prefix="edl-prof-out-"))
os.environ["EDL_TPU_METRICS_PORT"] = "0"
os.environ.setdefault("EDL_TPU_ALERT_SCALE", "0.1")   # 6s straggler window
os.environ.setdefault("EDL_TPU_PEAK_TFLOPS", "1")     # CPU: any peak -> MFU
os.environ.setdefault("EDL_TPU_PROFILE_DURATION", "0.5")
os.environ.setdefault("EDL_TPU_PROFILE_COOLDOWN", "0")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# a fleet "trainer": /metrics + a TTL-leased advert + the /profile
# route backed by a phase ledger — the straggler's capture surface
_CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from edl_tpu.coord.client import CoordClient
from edl_tpu.obs import advert, context as obs_context
from edl_tpu.obs import profile as obs_profile, trace as obs_trace
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.ledger import StepPhaseLedger
from edl_tpu.obs.metrics import Registry

coord_ep, job, step_s = sys.argv[1], sys.argv[2], float(sys.argv[3])
obs_context.install_from_env()                 # the generation trace
obs_trace.configure_from_env("trainer")
reg = Registry()
steps = reg.histogram("edl_train_step_seconds", "per-step wall time")
ledger = StepPhaseLedger(enabled=True, component="trainer")
obs_profile.install_route(obs_profile.ProfileCapture("trainer",
                                                     ledger=ledger))
srv = MetricsServer(reg, host="127.0.0.1").start()
store = CoordClient(coord_ep)
advert.advertise_metrics(store, job, "trainer", srv.endpoint,
                         name=f"trainer-{{os.getpid()}}", ttl=60)
print("trainer up", srv.endpoint, flush=True)
while True:
    time.sleep(step_s)
    steps.observe(step_s)
    with ledger.phase("compute"):
        pass
    ledger.step_done(step_s)
"""


def _spawn_trainer(coord_ep, job, step_s, ctx):
    env = dict(os.environ, EDL_TPU_METRICS_PORT="",
               EDL_TPU_TRACE_CONTEXT=ctx.to_env())
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD.format(repo=_REPO),
         coord_ep, job, str(step_s)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "trainer up" in line:
            return proc, line.rsplit(" ", 1)[-1].strip()
        if not line and proc.poll() is not None:
            raise AssertionError("trainer child died before announcing")
    raise AssertionError("trainer child never announced")


def _get_json(url):
    return json.loads(urllib.request.urlopen(url, timeout=10).read().decode())


def _train_instrumented() -> None:
    """A real ElasticTrainer run under the ledger; gates coverage and
    the live MFU gauges from this process's registry."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu.cluster.state import State
    from edl_tpu.obs.metrics import REGISTRY
    from edl_tpu.train import ElasticTrainer, TrainConfig

    rng = np.random.default_rng(0)

    def loss(params, extra, batch, _rng):
        pred = jnp.tanh(batch["x"] @ params["w1"]) @ params["w2"]
        return jnp.mean((pred - batch["y"]) ** 2), (extra, {})

    def batches():
        # batch/width sized so a step costs a few ms: the coverage gate
        # measures the ledger against realistic steps, not loop glue on
        # microsecond toy steps
        for _ in range(60):
            x = rng.normal(size=(128, 384)).astype(np.float32)
            yield {"x": x, "y": rng.normal(size=(128, 1)).astype(np.float32)}

    trainer = ElasticTrainer(loss, TrainConfig(log_every=0))
    state = trainer.create_state(
        lambda: ({"w1": jnp.zeros((384, 384)), "w2": jnp.zeros((384, 1))},
                 None), optax.sgd(0.01))
    trainer.fit(state, State(), lambda e: batches(), epochs=2)

    cover = REGISTRY.get("edl_step_ledger_coverage_ratio").value
    assert cover >= 0.95, \
        f"phase ledger covers {cover:.3f} < 0.95 of step wall time"
    phase_count = sum(
        REGISTRY.get("edl_step_phase_seconds").labels(phase=p).count
        for p in ("data_wait", "h2d", "compute", "hooks", "checkpoint"))
    assert phase_count > 0, "no phase observations recorded"
    # cost analysis runs on a background thread (it must never stall
    # the train loop) and publishes the gauges when it lands
    deadline = time.time() + 20
    while (time.time() < deadline
           and REGISTRY.get("edl_tflops_per_chip").value == 0):
        time.sleep(0.1)
    tflops = REGISTRY.get("edl_tflops_per_chip").value
    mfu = REGISTRY.get("edl_mfu").value
    assert tflops > 0, "edl_tflops_per_chip never published"
    assert mfu > 0, "edl_mfu never published (EDL_TPU_PEAK_TFLOPS is set)"
    print(f"smoke: ledger coverage {cover:.3f}, live mfu {mfu:.3g} "
          f"({tflops:.3g} TFLOP/s/chip vs pinned peak)")


def main() -> None:
    from edl_tpu import obs
    from edl_tpu.cluster import recovery
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.obs import context as obs_context
    from edl_tpu.obs import dump as obs_dump
    from edl_tpu.obs import rules as obs_rules
    from edl_tpu.obs import trace as obs_trace
    from edl_tpu.obs.advert import publish_job_trace
    from edl_tpu.obs.agg import AggregatorServer
    from edl_tpu.obs.metrics import parse_exposition

    job = "profsmoke"
    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    store = CoordClient(coord_ep)

    # the generation trace everything must join (the launcher contract)
    ctx = obs_context.new_trace(job=job)
    obs_context.set_process_root(ctx)
    obs.install_from_env("parent")
    publish_job_trace(store, job, ctx, stage="gen0")
    obs_trace.emit("smoke/generation", stage="gen0")

    # 1 -- instrumented training in THIS process
    _train_instrumented()

    strag = {r.name: r for r in obs_rules.builtin_rules()}["trainer-straggler"]
    children = [_spawn_trainer(coord_ep, job, s, ctx)
                for s in (0.05, 0.05, 0.25)]
    slow_ep = children[2][1]
    agg_srv = None
    try:
        agg_srv = AggregatorServer(
            store, job, host="127.0.0.1", cache_s=0.0,
            scrape_interval=0.25, incident_dir=_TRACE_DIR).start()
        agg_ep = agg_srv.endpoint

        # 2 -- goodput on /healthz; a resize moves ONLY reason="resize"
        health = _get_json(f"http://{agg_ep}/healthz")
        assert "goodput" in health and "ratio" in health["goodput"], health
        # the record's span must land INSIDE the goodput observation
        # window (badput is clipped to what the ledger watched — an
        # aggregator restarted onto an old job must not inherit its
        # history), so let the ledger open first, then backdate less
        # than that
        time.sleep(1.5)
        t0 = time.time()
        recovery.write_launcher_half(
            store, job, "stageA", "pod0",
            {"detect": t0 - 0.9, "killed": t0 - 0.6, "barrier": t0 - 0.5,
             "spawn": t0 - 0.2})                 # 0.7s launcher span
        deadline = time.time() + 30
        gp = None
        while time.time() < deadline:
            gp = _get_json(f"http://{agg_ep}/healthz").get("goodput", {})
            if gp.get("badput", {}).get("resize"):
                break
            time.sleep(0.25)
        assert gp and abs(gp["badput"]["resize"] - 0.7) < 0.01, gp
        for other in ("restore", "hang", "idle"):
            assert gp["badput"][other] == 0.0, \
                f"resize moved badput[{other}] too: {gp}"
        assert 0.0 <= gp["ratio"] < 1.0, gp
        page = urllib.request.urlopen(f"http://{agg_ep}/metrics",
                                      timeout=10).read().decode()
        parsed = parse_exposition(page)
        assert parsed[("edl_badput_seconds_total",
                       (("component", "obs-agg"), ("instance", "self"),
                        ("reason", "resize")))] > 0
        assert any(n == "edl_goodput_ratio" for n, _l in parsed), \
            "edl_goodput_ratio missing from the merged page"
        print(f"smoke: goodput on /healthz, resize badput "
              f"{gp['badput']['resize']:.1f}s (ratio {gp['ratio']:.3f}), "
              f"no other reason moved")

        # 3 -- straggler alert -> automatic profile capture on the slow pod
        bound = (strag.window + strag.for_s) * 2 + 20.0
        deadline = time.time() + bound
        alert = None
        while time.time() < deadline:
            firing = _get_json(f"http://{agg_ep}/alerts")["firing"]
            hit = [a for a in firing if a["alert"] == "trainer-straggler"]
            if hit:
                alert = hit[0]
                break
            time.sleep(0.2)
        assert alert is not None, "trainer-straggler never fired"
        assert alert.get("instance") == slow_ep, alert
        manifest = None
        deadline = time.time() + 30
        while time.time() < deadline:
            for path in glob.glob(os.path.join(_PROFILE_DIR,
                                               "profile-*.json")):
                with open(path, encoding="utf-8") as f:
                    m = json.load(f)
                if m.get("trigger") == "alert":
                    manifest = m
                    break
            if manifest:
                break
            time.sleep(0.25)
        assert manifest is not None, \
            f"no alert-triggered capture landed in {_PROFILE_DIR}"
        assert manifest.get("trace_id") == ctx.trace_id, \
            f"capture trace_id {manifest.get('trace_id')} != generation " \
            f"trace {ctx.trace_id}"
        print(f"smoke: straggler alert on {slow_ep} auto-captured a "
              f"{manifest['kind']} profile carrying trace "
              f"{ctx.trace_id[:8]}")

        # 4 -- the capture + step phases join the merged timeline, and
        # Perfetto gets counter tracks
        events, _skipped = obs_dump.read_trace_dir(_TRACE_DIR)
        tl = obs_dump.merge_timeline(events, ctx.trace_id)
        names = {e["name"] for e in tl}
        assert "profile/capture" in names, sorted(names)
        assert "train/step_phases" in names, sorted(names)
        pf = obs_dump.to_perfetto(obs_dump.merge_timeline(events))
        counter_tracks = {e["name"] for e in pf["traceEvents"]
                          if e.get("ph") == "C"}
        assert "train/step_phases" in counter_tracks, counter_tracks
        json.dumps(pf)
        print(f"smoke: capture + step phases joined trace "
              f"{ctx.trace_id[:8]} ({len(tl)} events); Perfetto counter "
              f"tracks: {sorted(counter_tracks)}")
    finally:
        if agg_srv is not None:
            agg_srv.stop()
        for proc, _ in children:
            proc.kill()
        store.close()
        coord.stop()
    print("profiling smoke OK")


if __name__ == "__main__":
    main()
