"""CI smoke: the black-box flight recorder + postmortem bundle loop —
an induced incident must automatically produce a self-contained
archive, and a SIGKILLed aggregator must resume with its windowed
history, goodput window and alert holds intact.

Three "trainer" child processes (each: a /metrics endpoint with a live
``edl_train_step_seconds`` histogram, a flight recorder serving
``GET /flightrec``, and a TTL-leased coord advert) against an
in-process coordination server.  A real ``edl-obs-agg`` SUBPROCESS
(built-in ruleset, windows shrunk via ``EDL_TPU_ALERT_SCALE``) runs
with ``--history_dir`` + ``EDL_TPU_OBS_BUNDLE_DIR`` +
``EDL_TPU_REMEDIATE=1``:

1. **automated bundle** — one child steps 5x slower than the fleet;
   ``trainer-straggler`` fires, and its built-in ``bundle`` action
   must land a postmortem archive: manifest stamped with the published
   generation trace_id, flight-recorder rings from >=2 processes,
   the TSDB window, the coord ``dump_state``, a workerlog tail, and
   the triggering incident record — and ``edl-obs-dump``'s reader must
   join the ring events + incident on that trace's timeline;
2. **aggregator restart continuity** — the aggregator is SIGKILLed
   and restarted onto the same ``--history_dir``; its first /healthz
   must already answer windowed rates (replayed raw tier), the goodput
   observation window must RESUME (observed_s keeps growing, not reset
   to zero), and /alerts must still show the straggler FIRING with its
   original ``firing_since`` — the hold survived the restart;
3. **after-the-fact reassembly** — ``edl-obs-bundle --incident <id>``
   rebuilds a bundle for the same incident from the durable pieces
   alone (incident JSONL + history segments), no live fleet needed.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/postmortem_smoke.py
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

_TRACE_DIR = os.environ.setdefault("EDL_TPU_TRACE_DIR",
                                   tempfile.mkdtemp(prefix="edl-pm-"))
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
os.environ.setdefault("EDL_TPU_ALERT_SCALE", "0.1")
# short quantile window so windowed rates have coverage within the smoke
os.environ.setdefault("EDL_TPU_OBS_QUANTILE_WINDOW", "20")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_CHILD = r"""
import dataclasses, os, sys, time
sys.path.insert(0, {repo!r})
from edl_tpu.coord.client import CoordClient
from edl_tpu.obs import advert, flightrec
from edl_tpu.obs import context as obs_context
from edl_tpu.obs import trace as obs_trace
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.metrics import Registry

coord_ep, job, step_s = sys.argv[1], sys.argv[2], float(sys.argv[3])
reg = Registry()
steps = reg.histogram("edl_train_step_seconds", "per-step wall time")
srv = MetricsServer(reg, host="127.0.0.1").start()
store = CoordClient(coord_ep)
handle = advert.advertise_metrics(store, job, "trainer", srv.endpoint,
                                  name=f"trainer-{{os.getpid()}}", ttl=60)
# the black box: ring-only tracing (no tracer installed -> NullTracer),
# events land in the flight recorder and are served on GET /flightrec
flightrec.install("trainer")
jt = advert.current_job_trace(store, job)
ctx = dataclasses.replace(obs_context.new_trace(), trace_id=jt["trace_id"])
print("trainer up", srv.endpoint, flush=True)
i = 0
with obs_context.use(ctx):
    while True:
        time.sleep(step_s)
        steps.observe(step_s)
        obs_trace.emit("train/step", step=i)
        i += 1
"""


def _spawn_trainer(coord_ep, job, step_s):
    env = dict(os.environ, EDL_TPU_METRICS_PORT="")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD.format(repo=_REPO),
         coord_ep, job, str(step_s)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "trainer up" in line:
            return proc, line.rsplit(" ", 1)[-1].strip()
        if not line and proc.poll() is not None:
            raise AssertionError("trainer child died before announcing")
    raise AssertionError("trainer child never announced")


def _spawn_agg(coord_ep, job, history_dir, env):
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "edl_tpu.obs.agg",
         "--coord_endpoints", coord_ep, "--job_id", job,
         "--host", "127.0.0.1", "--cache_s", "0",
         "--scrape_interval", "0.25", "--history_dir", history_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "serving merged /metrics" in line:
            return proc, line.rsplit(" ", 1)[-1].strip()
        if not line and proc.poll() is not None:
            raise AssertionError("aggregator died before announcing")
    raise AssertionError("aggregator never announced its endpoint")


def _get_json(url):
    return json.loads(urllib.request.urlopen(url, timeout=10).read().decode())


def _wait(pred, deadline, what, every=0.2):
    while time.time() < deadline:
        got = pred()
        if got is not None:
            return got
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {what}")


def _find_bundle(bundle_dir):
    for name in sorted(os.listdir(bundle_dir) if os.path.isdir(bundle_dir)
                       else []):
        mf = os.path.join(bundle_dir, name, "manifest.json")
        if os.path.exists(mf):
            with open(mf, encoding="utf-8") as f:
                manifest = json.load(f)
            manifest["path"] = os.path.join(bundle_dir, name)
            return manifest
    return None


def main() -> None:
    from edl_tpu import obs
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.obs import context as obs_context
    from edl_tpu.obs import dump as obs_dump
    from edl_tpu.obs import trace as obs_trace
    from edl_tpu.obs.advert import advertise_installed, publish_job_trace

    obs.install_from_env("parent")
    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    store = CoordClient(coord_ep)
    job = "pmsmoke"

    history = tempfile.mkdtemp(prefix="edl-pm-hist-")
    bundles = tempfile.mkdtemp(prefix="edl-pm-bundles-")
    # a workerlog for the bundler to tail (what the launcher leaves
    # under EDL_TPU_LOG_DIR on a real pod)
    log_dir = tempfile.mkdtemp(prefix="edl-pm-logs-")
    os.makedirs(os.path.join(log_dir, "pod-smoke"))
    with open(os.path.join(log_dir, "pod-smoke", "workerlog.0"), "w") as f:
        f.writelines(f"step {i} ok\n" for i in range(200))

    # the generation trace every piece of evidence must join
    ctx = obs_context.new_trace(job=job)
    publish_job_trace(store, job, ctx, stage="gen0")
    with obs_context.use(ctx):
        obs_trace.emit("smoke/generation", stage="gen0")
    parent_reg = advertise_installed(store, job, "parent")
    assert parent_reg is not None

    agg_env = dict(os.environ,
                   EDL_TPU_REMEDIATE="1",
                   EDL_TPU_PROFILE_ON_ALERT="0",
                   EDL_TPU_OBS_BUNDLE_DIR=bundles,
                   EDL_TPU_LOG_DIR=log_dir,
                   EDL_TPU_METRICS_PORT="")

    children = [_spawn_trainer(coord_ep, job, s) for s in (0.05, 0.05, 0.25)]
    agg = agg2 = None
    try:
        agg, agg_ep = _spawn_agg(coord_ep, job, history, agg_env)

        # 1 -- straggler fires -> the bundle action freezes the evidence
        t0 = time.time()
        alert = _wait(
            lambda: next((a for a in
                          _get_json(f"http://{agg_ep}/alerts")["firing"]
                          if a["alert"] == "trainer-straggler"), None),
            t0 + 60.0, "trainer-straggler to fire")
        firing_since = alert["firing_since"]
        manifest = _wait(lambda: _find_bundle(bundles), time.time() + 30.0,
                         "postmortem bundle to land")
        assert manifest["rule"] == "trainer-straggler", manifest
        assert manifest["trace_id"] == ctx.trace_id, \
            f"bundle trace_id {manifest['trace_id']} != generation " \
            f"trace {ctx.trace_id}"
        assert manifest["flightrec_rings"] >= 2, manifest
        members = set(manifest["members"])
        for want in ("tsdb-window.json", "coord-state.json",
                     "incidents-bundle-0.jsonl"):
            assert want in members, (want, sorted(members))
        assert any(m.startswith("workerlogs/") for m in members), \
            f"no workerlog tail in bundle: {sorted(members)}"
        # the rings replay as dump-mergeable trace files: child step
        # events + the incident land on ONE causal timeline by trace_id
        events, _skipped = obs_dump.read_trace_dir(manifest["path"])
        tl = obs_dump.merge_timeline(events, ctx.trace_id)
        names = {e["name"] for e in tl}
        assert "train/step" in names, \
            f"no flight-recorder step events on the timeline: {sorted(names)}"
        assert "alert/trainer-straggler" in names, sorted(names)
        print(f"smoke: bundle {manifest['id']} landed at "
              f"{manifest['path']} ({len(members)} members, "
              f"{manifest['flightrec_rings']} rings, "
              f"{len(tl)} timeline events)")

        # 2 -- SIGKILL the aggregator; the successor resumes the watch
        pre = _wait(
            lambda: (lambda h: h if h.get("rates", {})
                     .get("train_steps_per_s") else None)(
                _get_json(f"http://{agg_ep}/healthz")),
            time.time() + 30.0, "windowed rates before the kill")
        pre_observed = pre["goodput"]["observed_s"]
        assert pre_observed > 0, pre
        agg.send_signal(signal.SIGKILL)
        agg.wait(timeout=30)
        kill_ts = time.time()

        agg2, agg2_ep = _spawn_agg(coord_ep, job, history, agg_env)
        health = _wait(
            lambda: (lambda h: h if h.get("rates", {})
                     .get("train_steps_per_s") else None)(
                _get_json(f"http://{agg2_ep}/healthz")),
            time.time() + 20.0, "windowed rates after the restart")
        # goodput RESUMED the dead aggregator's observation window:
        # observed_s kept growing across the kill instead of resetting
        assert health["goodput"]["observed_s"] >= pre_observed, \
            (health["goodput"], pre_observed)
        alerts2 = _get_json(f"http://{agg2_ep}/alerts")
        survived = [a for a in alerts2["firing"]
                    if a["alert"] == "trainer-straggler"]
        assert survived, f"straggler hold lost in restart: {alerts2}"
        assert abs(survived[0]["firing_since"] - firing_since) < 1.0, \
            (survived[0]["firing_since"], firing_since)
        assert survived[0]["firing_since"] < kill_ts
        print(f"smoke: aggregator restart kept windowed rates "
              f"({health['rates']}), goodput window "
              f"({health['goodput']['observed_s']:.1f}s observed) and the "
              f"straggler hold (firing since "
              f"{kill_ts - firing_since:.1f}s before the kill)")

        # 3 -- after-the-fact reassembly from the durable pieces alone
        from edl_tpu.obs import bundle as obs_bundle
        re_out = tempfile.mkdtemp(prefix="edl-pm-re-")
        rc = obs_bundle.main([
            "--incident", manifest["id"], "--out", re_out,
            "--history_dir", history, "--trace_dir", _TRACE_DIR,
            "--job_id", job])
        assert rc == 0, f"edl-obs-bundle --incident exited {rc}"
        re_manifest = _find_bundle(re_out)
        assert re_manifest and re_manifest["source"] == "reassembled"
        assert re_manifest["trace_id"] == ctx.trace_id
        assert "tsdb-window.json" in re_manifest["members"]
        print(f"smoke: edl-obs-bundle --incident {manifest['id']} "
              f"reassembled {len(re_manifest['members'])} members "
              f"from history alone")
    finally:
        for p in (agg, agg2):
            if p is not None:
                p.kill()
        for proc, _ in children:
            proc.kill()
        parent_reg.stop()
        store.close()
        coord.stop()
    print("postmortem smoke OK")


if __name__ == "__main__":
    main()
