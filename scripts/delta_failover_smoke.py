"""CI delta-failover smoke: sub-checkpoint-loss recovery (ISSUE 17).

Two phases against REAL launchers + a durable coordinator, each
SIGKILLing one non-leader pod mid-epoch (between per-epoch
checkpoints), on a paced 2-host CPU/gloo world:

1. **Delta plane ON** (EDL_TPU_DELTA_EVERY=2) — the kill lands
   mid-delta-interval, after the smoke has OBSERVED (probe_freshest)
   sealed chain records past the committed checkpoint.  The job must
   finish SUCCEED, the recovery record must carry
   ``restore_source=delta``, and the restore log must show the landed
   step F strictly past the committed base AND >= the freshest sealed
   step observed at kill time — i.e. the failure lost at most one
   delta interval of steps, not the checkpoint interval.
2. **Baseline OFF** (EDL_TPU_DELTA_EVERY=0) — the identical kill with
   the delta plane disabled resumes AT the committed checkpoint step
   (``restore_source`` peer/storage): every step past the last save is
   badput.

The gate: preserved-steps-per-failure with the plane on is strictly
positive while the stop-resume baseline preserves zero by construction
— badput-per-failure (lost steps x paced step time, the goodput
ledger's checkpoint_loss component) is strictly below the baseline for
equivalently timed kills.  Prints one JSON line so the numbers trend
in the CI log.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/delta_failover_smoke.py
"""

import glob
import json
import os
import re
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from resize_smoke import (  # noqa: E402  (same harness, same knobs)
    FAST, finish, kill_tree, spawn_coord, spawn_launcher, trainer_pids,
    wait_first_checkpoint, wait_world,
)

STEP_SLEEP = float(FAST["EDL_TPU_DEMO_STEP_SLEEP"])
DELTA_EVERY = 2

_DELTA_RESTORE = re.compile(
    r"memstate: restored step (\d+) from peers .*base (\d+) \+ delta chains")


def _logs_text(tmp: str, names) -> str:
    """All launcher+trainer log text for THIS phase's pods only — both
    phases share one tmp dir, so an unscoped glob would leak phase 1's
    delta-restore lines into phase 2's no-delta assertion."""
    out = []
    for path in glob.glob(os.path.join(tmp, "**"), recursive=True):
        if not os.path.isfile(path):
            continue
        rel = os.path.relpath(path, tmp)
        if not any(rel.startswith((f"launcher-{n}", f"log-{n}"))
                   for n in names):
            continue
        try:
            with open(path, "rb") as f:
                out.append(f.read().decode(errors="replace"))
        except OSError:
            continue
    return "\n".join(out)


def _wait_recovery_source(client, job_id, deadline_s=180) -> dict:
    from edl_tpu.cluster.recovery import summarize_recovery
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            recs = [s for s in summarize_recovery(client, job_id)
                    if s.get("restore_source")]
        except Exception:  # noqa: BLE001 — store warming up
            recs = []
        if recs:
            return recs[-1]
        time.sleep(0.3)
    raise AssertionError("no recovery record with a restore_source")


def _pick_victim(tmp, procs, cluster):
    """The highest-rank (non-leader) pod's launcher: leader death also
    kills the jax coordination service — a different, slower scenario
    than the shard-loss this smoke measures."""
    from resize_smoke import log_text
    victim_pod = cluster.pods[-1].pod_id
    return next(n for n in procs if f"pod {victim_pod}" in log_text(tmp, n))


def phase_delta(tmp, coord_ep) -> dict:
    from edl_tpu import memstate
    from edl_tpu.cluster.status import Status, load_job_status
    from edl_tpu.coord.client import connect
    job = "delta-fo"
    ckpt = os.path.join(tmp, "ckpt-delta")
    env = {"EDL_TPU_DELTA_EVERY": str(DELTA_EVERY)}
    os.environ.update(env)
    procs = {n: spawn_launcher(job, coord_ep, tmp, n, ckpt, epochs=12,
                               steps=8) for n in ("da", "db")}
    try:
        client = connect(coord_ep)
        cluster = wait_world(client, job, 2)
        wait_first_checkpoint(ckpt, tuple(procs.values()))
        # mid-delta-interval kill: wait until sealed chain records are
        # OBSERVABLY past the committed base, remember the freshest —
        # the restore may not land below it
        deadline = time.monotonic() + 120
        committed = freshest = None
        while time.monotonic() < deadline:
            try:
                committed, freshest = memstate.probe_freshest(client, job)
            except Exception:  # noqa: BLE001 — caches still warming up
                committed = freshest = None
            if committed is not None and freshest is not None \
                    and freshest > committed:
                break
            assert all(p.poll() is None for p in procs.values()), \
                "a launcher died before any delta record sealed"
            time.sleep(0.1)
        assert freshest is not None, "no delta chain sealed in 120s"

        victim = _pick_victim(tmp, procs, cluster)
        assert trainer_pids(procs[victim]), "victim has no trainer yet"
        kill_tree(procs[victim])  # SIGKILL: pod + cache service, all gone
        t_kill = time.monotonic()

        rec = _wait_recovery_source(client, job)
        survivors = [p for n, p in procs.items() if n != victim]
        assert all(finish(p, 300) == 0 for p in survivors), \
            "survivors failed after the mid-interval SIGKILL"
        assert load_job_status(client, job) == Status.SUCCEED
        client.close()

        assert rec.get("restore_source") == "delta", (
            f"expected restore_source=delta, got {rec}")
        hits = [(int(a), int(b))
                for a, b in _DELTA_RESTORE.findall(_logs_text(tmp, procs))]
        assert hits, "no base+chain restore line found in any log"
        landed, base = max(hits)
        assert landed > base, (landed, base)
        assert landed >= freshest, (
            f"restore landed at {landed}, below the freshest sealed "
            f"step {freshest} observed before the kill")
        print(f"delta failover smoke: ON OK — killed past committed "
              f"{committed} with chains at {freshest}; restored at "
              f"{landed} (base {base}), restore_source=delta, "
              f"mttr {rec.get('total', -1):.2f}s")
        return {"landed": landed, "base": base,
                "preserved_steps": landed - base,
                "mttr_s": float(rec.get("total", -1)),
                "t_recover_s": round(time.monotonic() - t_kill, 2)}
    finally:
        for k in env:
            os.environ.pop(k, None)
        for p in procs.values():
            if p.poll() is None:
                kill_tree(p)


def phase_baseline(tmp, coord_ep) -> dict:
    from edl_tpu.cluster.status import Status, load_job_status
    from edl_tpu.coord.client import connect
    job = "delta-fo-base"
    ckpt = os.path.join(tmp, "ckpt-base")
    env = {"EDL_TPU_DELTA_EVERY": "0"}  # stop-resume loss window
    os.environ.update(env)
    procs = {n: spawn_launcher(job, coord_ep, tmp, n, ckpt, epochs=12,
                               steps=8) for n in ("ba", "bb")}
    try:
        client = connect(coord_ep)
        cluster = wait_world(client, job, 2)
        wait_first_checkpoint(ckpt, tuple(procs.values()))
        # the same mid-epoch kill point, timed instead of probed (there
        # are no chains to probe): a few paced steps past the save
        time.sleep(max(1.0, (DELTA_EVERY + 1) * STEP_SLEEP))
        victim = _pick_victim(tmp, procs, cluster)
        kill_tree(procs[victim])

        rec = _wait_recovery_source(client, job)
        survivors = [p for n, p in procs.items() if n != victim]
        assert all(finish(p, 300) == 0 for p in survivors), \
            "baseline survivors failed after SIGKILL"
        assert load_job_status(client, job) == Status.SUCCEED
        client.close()

        assert rec.get("restore_source") in ("peer", "storage", "delta"), rec
        assert not _DELTA_RESTORE.findall(_logs_text(tmp, procs)), \
            "baseline run must not restore from delta chains"
        print(f"delta failover smoke: BASELINE OK — resumed at the "
              f"committed step (restore_source={rec.get('restore_source')}, "
              f"mttr {rec.get('total', -1):.2f}s)")
        return {"preserved_steps": 0,
                "mttr_s": float(rec.get("total", -1))}
    finally:
        for k in env:
            os.environ.pop(k, None)
        for p in procs.values():
            if p.poll() is None:
                kill_tree(p)


def main() -> None:
    only = sys.argv[1] if len(sys.argv) > 1 else None
    tmp = tempfile.mkdtemp(prefix="edl-delta-fo-")
    coord, coord_ep = spawn_coord(tmp)
    try:
        delta_res = base_res = None
        if only in (None, "delta"):
            delta_res = phase_delta(tmp, coord_ep)
        if only in (None, "baseline"):
            base_res = phase_baseline(tmp, coord_ep)
        if delta_res and base_res:
            # the badput gate: lost-work-per-failure strictly below the
            # stop-resume baseline (which preserves nothing past the
            # checkpoint by construction)
            assert delta_res["preserved_steps"] > base_res["preserved_steps"]
            print(json.dumps({
                "delta_preserved_steps": delta_res["preserved_steps"],
                "delta_restore_step": delta_res["landed"],
                "delta_base_step": delta_res["base"],
                "delta_mttr_s": round(delta_res["mttr_s"], 3),
                "baseline_preserved_steps": base_res["preserved_steps"],
                "baseline_mttr_s": round(base_res["mttr_s"], 3),
                "badput_steps_saved_per_failure":
                    delta_res["preserved_steps"],
            }))
        print("delta failover smoke OK")
    finally:
        if coord.poll() is None:
            coord.kill()
            coord.wait(timeout=30)


if __name__ == "__main__":
    main()
