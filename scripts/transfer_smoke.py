"""CI smoke for the streaming data plane: run the transfer microbench
(loopback, small payload, subprocess holders — the same code path as
``bench.py``'s transfer section) and assert the pipelined/striped
paths did not regress below the serial baseline.

Small-payload loopback numbers are noisy (scheduler, shared CI hosts),
so the gate compares the BEST of the new paths against serial —
structurally, pipelining the same work can't be slower than
serializing it, so a loss here means a protocol-level regression
(e.g. the window collapsed to 1 or streaming quietly fell back),
which is exactly what this stage exists to catch.  The absolute
bandwidth numbers go to the CI log for trend-eyeballing.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# small-but-not-tiny payload: enough chunks for a real window, fast on CPU
os.environ.setdefault("EDL_TPU_BENCH_TRANSFER_MB", "24")
os.environ.setdefault("EDL_TPU_BENCH_TRANSFER_CHUNK", str(1 << 20))
os.environ.setdefault("EDL_TPU_BENCH_TRANSFER_REPS", "3")

from edl_tpu.bench import _bench_transfer  # noqa: E402


def main() -> int:
    r = _bench_transfer()
    print(json.dumps(r))
    serial = r["transfer_serial_mib_s"]
    best_new = max(r["transfer_pipelined_mib_s"], r["transfer_striped_mib_s"])
    ratio = best_new / max(serial, 1e-9)
    print(f"transfer smoke: serial={serial} MiB/s, "
          f"pipelined={r['transfer_pipelined_mib_s']} MiB/s, "
          f"striped={r['transfer_striped_mib_s']} MiB/s "
          f"(best new path {ratio:.2f}x serial)")
    if best_new < serial:
        print("FAIL: pipelined/striped transfer slower than the serial "
              "baseline", file=sys.stderr)
        return 1
    print("transfer smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
