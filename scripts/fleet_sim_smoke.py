"""CI smoke: the control-plane scale observatory (edl_tpu/sim).

Runs a REAL fleet-simulation sweep — N pod actors (TTL-leased adverts,
heartbeats, status writes, reads) against a real durable coordination
server subprocess, with a real Aggregator scraping the fleet's
/metrics stubs through watch-based discovery — at CI-scale decades
(N=25/100/400 by default), then gates the scaling curves:

1. watch-based membership propagation stays FLAT: p50 at the largest N
   under 2x the smallest N (long-poll delivery must not degrade with
   fleet size);
2. poll-based propagation VISIBLY GROWS with N (the O(N) prefix scan a
   polling discoverer pays — the reason the aggregator switched to
   watches) — and pays more than the watch path at the largest N;
3. the aggregator scrape cycle stays bounded at the largest N;
4. ZERO coordination op failures across every round;
5. the report renderer parses its own artifact (subprocess
   ``python -m edl_tpu.sim.report``) and renders growth exponents.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/fleet_sim_smoke.py
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from edl_tpu.sim.harness import SimConfig, run_sweep  # noqa: E402
from edl_tpu.sim.report import fit_exponent, render_report  # noqa: E402

_NS = tuple(int(n) for n in os.environ.get(
    "EDL_TPU_SIM_SMOKE_NS", "25,100,400").split(","))
_ROUND_S = float(os.environ.get("EDL_TPU_SIM_SMOKE_ROUND_S", "10"))
# CI boxes are small + noisy: the propagation-flatness gate uses a
# ratio (largest/smallest), the scrape gate an absolute ceiling
_WATCH_FLAT_RATIO = 2.0
_POLL_GROWTH_RATIO = 1.2
_SCRAPE_BOUND_S = 8.0


def main() -> None:
    out = os.path.join(tempfile.mkdtemp(prefix="edl-sim-smoke-"),
                       "SIM_smoke.json")
    cfg = SimConfig(ns=_NS, round_s=_ROUND_S, ttl=6.0,
                    heartbeat_period=1.5, propagation_trials=6,
                    scrape_cycles=2, alert_trials=1, job_id="sim-smoke")
    artifact = run_sweep(cfg, out_path=out)
    print(render_report(artifact))

    rounds = artifact["rounds"]
    assert len(rounds) == len(_NS), rounds
    by_n = {r["n"]: r for r in rounds}
    n_min, n_max = min(by_n), max(by_n)

    # gate 4 first: latency gates on a round with failed ops are noise
    failures = {r["n"]: r["op_failures"] for r in rounds}
    assert all(v == 0 for v in failures.values()), \
        f"coordination op failures during sim: {failures}"
    print(f"smoke: zero coord op failures across ns={sorted(by_n)}")

    watch_lo = by_n[n_min]["propagation"]["watch"]
    watch_hi = by_n[n_max]["propagation"]["watch"]
    poll_lo = by_n[n_min]["propagation"]["poll"]
    poll_hi = by_n[n_max]["propagation"]["poll"]
    for name, stats in (("watch", watch_lo), ("watch", watch_hi),
                        ("poll", poll_lo), ("poll", poll_hi)):
        assert stats["samples"] > 0, f"no {name} propagation samples: {stats}"

    # gate 1: watch propagation flat across the sweep
    ratio = watch_hi["p50_s"] / watch_lo["p50_s"]
    assert ratio < _WATCH_FLAT_RATIO, (
        f"watch propagation degraded with fleet size: p50 "
        f"{watch_lo['p50_s']}s @ N={n_min} -> {watch_hi['p50_s']}s "
        f"@ N={n_max} ({ratio:.2f}x >= {_WATCH_FLAT_RATIO}x)")
    print(f"smoke: watch propagation flat ({ratio:.2f}x from N={n_min} "
          f"to N={n_max}, bound {_WATCH_FLAT_RATIO}x)")

    # gate 2: poll propagation visibly grows, and loses to the watch
    growth = poll_hi["p50_s"] / poll_lo["p50_s"]
    assert growth > _POLL_GROWTH_RATIO, (
        f"poll propagation did not grow with fleet size: p50 "
        f"{poll_lo['p50_s']}s @ N={n_min} -> {poll_hi['p50_s']}s "
        f"@ N={n_max} ({growth:.2f}x <= {_POLL_GROWTH_RATIO}x) — is the "
        f"poll observer actually paying the O(N) scan?")
    assert poll_hi["p50_s"] > watch_hi["p50_s"], (
        f"poll should lose to watch at N={n_max}: "
        f"poll p50 {poll_hi['p50_s']}s vs watch p50 {watch_hi['p50_s']}s")
    print(f"smoke: poll propagation grows ({growth:.2f}x) and loses to "
          f"watch at N={n_max}")

    # gate 3: scrape cycle bounded at the largest N
    wall = by_n[n_max]["scrape"]["mean_wall_s"]
    assert wall is not None and wall < _SCRAPE_BOUND_S, (
        f"aggregator scrape cycle unbounded at N={n_max}: "
        f"{wall}s >= {_SCRAPE_BOUND_S}s")
    print(f"smoke: scrape cycle at N={n_max} targets: {wall}s "
          f"(bound {_SCRAPE_BOUND_S}s)")

    # coord telemetry actually moved: leases tracked the fleet, the
    # server's watch instrumentation saw the observers
    sweep = by_n[n_max]["lease_sweep"]
    assert sweep["leases_live"] >= n_max, sweep
    assert sweep["sweeps"] > 0 and sweep["mean_s"] is not None, sweep
    assert by_n[n_max]["watch_server"]["wakeups"] > 0, \
        by_n[n_max]["watch_server"]
    print(f"smoke: coord telemetry live (leases_live="
          f"{sweep['leases_live']:g}, sweep mean {sweep['mean_s']}s, "
          f"wakeups={by_n[n_max]['watch_server']['wakeups']:g})")

    # gate 5: the report renderer parses its own artifact
    proc = subprocess.run(
        [sys.executable, "-m", "edl_tpu.sim.report", out],
        capture_output=True, text=True, cwd=_REPO, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "growth exponent" in proc.stdout, proc.stdout[:500]
    print("smoke: report renderer parsed the artifact standalone")

    # the exponent fit itself is sane on this artifact
    alpha = fit_exponent([(r["n"], r["propagation"]["poll"]["p50_s"])
                          for r in rounds])
    assert alpha is not None and alpha > 0, alpha
    with open(out) as f:
        assert json.load(f)["schema"] == "edl-sim/1"

    print("fleet-sim smoke OK")


if __name__ == "__main__":
    main()
