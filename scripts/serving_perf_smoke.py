"""CI smoke: the big-model serving fast path, end to end (ISSUE 20).

Three phases, each gating one fast-path claim with the same greedy
parity contract the base gateway smoke proves:

1. **everything-on replica through a real gateway** — one replica
   PROCESS on a tp=2 virtual CPU mesh with the paged pool sharded over
   it, chunked prefill AND self-draft speculative decoding enabled,
   fronted by an in-process Gateway.  Mixed traffic (shared-prefix
   shorts, unrelated shorts, a long prompt) must come back
   bit-identical to local ``generate()``, and the replica's /metrics
   page must show the fast path engaged: prefix hits, prefill chunks,
   accepted draft tokens.
2. **chunked-prefill starvation bound** — warm (prefix-reuse) short
   requests admitted while a long prompt prefills: p99 with chunking
   ON must stay within 2x of chunking OFF (chunking bounds the
   per-tick stall a long admission inflicts on live traffic).
3. **speculative decoding** — 100+ prompts through a spec engine,
   every output bit-identical to plain greedy; self-draft accept rate
   > 0.9; tokens/s recorded both ways.

Emits one JSON artifact line (``serving_mesh_tokens_s``,
``serving_prefill_p99_ms``, ``serving_spec_accept_rate``, ...) so the
driver can track the fast path like any bench section.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/serving_perf_smoke.py
"""

import json
import os
import selectors
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB, LAYERS, EMBED, HEADS, MLP, MAX_LEN = 53, 2, 32, 2, 64, 128


def _spawn_replica(coord_ep: str, rid: str, metrics_dir: str):
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               EDL_TPU_METRICS_PORT="0", EDL_TPU_METRICS_DIR=metrics_dir,
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.serving.replica",
         "--coord_endpoints", coord_ep, "--job_id", "perfsmoke",
         "--replica_id", rid, "--host", "127.0.0.1",
         "--vocab", str(VOCAB), "--layers", str(LAYERS),
         "--embed", str(EMBED), "--heads", str(HEADS), "--mlp", str(MLP),
         "--max_len", str(MAX_LEN), "--slots", "2", "--steps_per_sync", "2",
         "--temperature", "0", "--seed", "0", "--ttl", "2",
         # the whole fast path at once: tp=2 sharded paged pool,
         # chunked prefill, self-draft speculation (draft dims + seed
         # match the target, so acceptance ~1 and parity is strict)
         "--tp", "2", "--kv_block", "4", "--kv_pool_blocks", "96",
         "--prefill_chunk", "32", "--spec_k", "3",
         "--draft_layers", str(LAYERS), "--draft_embed", str(EMBED),
         "--draft_heads", str(HEADS), "--draft_mlp", str(MLP)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.time() + 300
    while time.time() < deadline:
        if not sel.select(timeout=1.0):
            if proc.poll() is not None:
                raise AssertionError(f"replica {rid} died silently")
            continue
        line = proc.stdout.readline()
        if "serving on" in line:
            return proc
        if not line and proc.poll() is not None:
            raise AssertionError(f"replica {rid} died before announcing")
    raise AssertionError(f"replica {rid} never announced")


def _phase_stack(out: dict) -> None:
    """tp=2 mesh + paged + chunked + spec replica behind a real
    gateway: mixed traffic, bit-exact, fast path visibly engaged."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import start_server
    from edl_tpu.gateway import Gateway, GatewayConfig
    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.obs.metrics import parse_exposition

    cfg = TransformerConfig(vocab_size=VOCAB, num_layers=LAYERS,
                            embed_dim=EMBED, num_heads=HEADS, mlp_dim=MLP,
                            max_len=MAX_LEN, remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(                    # replica --seed 0
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]

    def want(prompt, n):
        return np.asarray(generate(cfg, params, jnp.asarray(prompt[None]),
                                   n, temperature=0.0))[0]

    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    metrics_dir = tempfile.mkdtemp(prefix="edl-perf-metrics-")
    proc = _spawn_replica(coord_ep, "rep-fast", metrics_dir)
    store = CoordClient(coord_ep)
    gw = Gateway(store, "perfsmoke", GatewayConfig(
        max_inflight=8, max_queue=32, request_timeout_s=300.0,
        wait_slice_s=0.1, poll_period_s=0.1))
    try:
        assert gw.wait_for_replicas(1, 60), "replica never advertised"
        rng = np.random.default_rng(0)
        prefix = rng.integers(1, VOCAB, (12,)).astype(np.int32)
        prompts = [np.concatenate(
            [prefix, rng.integers(1, VOCAB, (n,)).astype(np.int32)])
            for n in (3, 5, 2)]
        prompts += [rng.integers(1, VOCAB, (n,)).astype(np.int32)
                    for n in (4, 6)]
        prompts.append(rng.integers(1, VOCAB, (96,)).astype(np.int32))
        news = [8, 8, 8, 8, 8, 8]

        # lead request first, alone: it commits the shared-prefix
        # chain, so the burst behind it admits through the trie
        t0 = time.monotonic()
        outs = [gw.submit(prompts[0], news[0]).result(timeout=300)]
        futs = [gw.submit(p, n)
                for p, n in zip(prompts[1:], news[1:])]
        outs += [f.result(timeout=300) for f in futs]
        wall = time.monotonic() - t0
        for p, n, o in zip(prompts, news, outs):
            np.testing.assert_array_equal(o, want(p, n))
        out["serving_mesh_tokens_s"] = round(sum(news) / wall, 1)

        # the fast path must have ENGAGED, not just not broken: the
        # replica's /metrics page carries the engine's lifetime stats
        addr_path = os.path.join(metrics_dir,
                                 f"metrics-replica-{proc.pid}.addr")
        deadline = time.time() + 60
        while True:                      # published by the advert loop
            with open(addr_path) as f:
                page = urllib.request.urlopen(
                    f"http://{f.read().strip()}/metrics", timeout=10
                ).read().decode()
            m = parse_exposition(page)
            if m.get(("edl_serving_spec_accepted_total", ()), 0) > 0:
                break
            assert time.time() < deadline, "spec counters never published"
            time.sleep(0.5)
        assert m.get(("edl_serving_kv_prefix_hits", ()), 0) >= 2, \
            "shared-prefix traffic must hit the sharded pool's trie"
        assert m.get(("edl_serving_prefill_chunks_total", ()), 0) >= 2, \
            "the 96-token prompt must have prefilled in chunks"
        assert m.get(("edl_serving_spec_proposed_total", ()), 0) > 0
        rate = (m[("edl_serving_spec_accepted_total", ())]
                / m[("edl_serving_spec_proposed_total", ())])
        assert rate > 0.9, f"self-draft accept rate {rate:.2f}"
        print(f"smoke: tp=2 mesh+paged+chunk+spec replica through the "
              f"gateway — {len(prompts)} mixed requests bit-exact, "
              f"{int(m[('edl_serving_kv_prefix_hits', ())])} prefix hits, "
              f"{int(m[('edl_serving_prefill_chunks_total', ())])} chunks, "
              f"spec accept {rate:.2f}")
    finally:
        gw.close()
        if proc.poll() is None:
            proc.kill()
        store.close()
        coord.stop()


def _phase_chunk_p99(out: dict) -> None:
    """Warm short requests while a long prompt prefills: chunking must
    bound the stall — p99 within 2x of the unchunked engine."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.serving import ContinuousBatcher

    cfg = TransformerConfig(vocab_size=VOCAB, num_layers=LAYERS,
                            embed_dim=EMBED, num_heads=HEADS, mlp_dim=MLP,
                            max_len=256, remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(1)
    prefix = rng.integers(1, VOCAB, (12,)).astype(np.int32)
    longs = [rng.integers(1, VOCAB, (224,)).astype(np.int32)
             for _ in range(3)]

    def p99(chunk: int) -> tuple[float, dict]:
        eng = ContinuousBatcher(cfg, params, slots=3, temperature=0.0,
                                steps_per_sync=1, kv_block=4,
                                kv_pool_blocks=256, prefill_buckets=(8, 16),
                                prefill_chunk=chunk)
        try:
            # commit the prefix chain so measured shorts admit via
            # reuse (reuse admissions run every tick, so they see the
            # per-tick stall directly — the thing chunking bounds)
            eng.generate(np.concatenate(
                [prefix, np.asarray([1, 2], np.int32)]), 4, timeout=120)
            # one unmeasured warm short: compiles the reuse-admission
            # jit family so the percentile measures ticks, not XLA
            eng.generate(np.concatenate(
                [prefix, np.asarray([3, 4], np.int32)]), 4, timeout=120)
            lats = []
            for long in longs:
                f_long = eng.submit(long, 2)
                for i in range(6):
                    p = np.concatenate(
                        [prefix,
                         rng.integers(1, VOCAB, (2,)).astype(np.int32)])
                    t0 = time.monotonic()
                    eng.generate(p, 4, timeout=120)
                    lats.append(time.monotonic() - t0)
                f_long.result(timeout=120)
            return float(np.percentile(lats, 99) * 1e3), eng.stats()
        finally:
            eng.stop()

    on_ms, on_stats = p99(32)
    off_ms, off_stats = p99(0)
    assert on_stats["prefill_chunks"] > 0, on_stats
    assert off_stats["prefill_chunks"] == 0, off_stats
    # generous 2x + absolute cushion: the bound protects against the
    # pathological monolithic stall, not CI scheduler jitter
    assert on_ms <= off_ms * 2 + 25, (on_ms, off_ms)
    out["serving_prefill_p99_ms"] = round(on_ms, 1)
    out["serving_prefill_p99_off_ms"] = round(off_ms, 1)
    print(f"smoke: warm-short p99 with a long admission in flight — "
          f"{on_ms:.1f} ms chunked vs {off_ms:.1f} ms monolithic "
          f"({on_stats['prefill_chunks']} chunks)")


def _phase_spec(out: dict) -> None:
    """100+ prompts, spec on vs off: bit-identical everywhere, accept
    rate ~1 on the self-draft, tokens/s recorded both ways."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.serving import ContinuousBatcher

    cfg = TransformerConfig(vocab_size=VOCAB, num_layers=LAYERS,
                            embed_dim=EMBED, num_heads=HEADS, mlp_dim=MLP,
                            max_len=64, remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, VOCAB, (int(n),)).astype(np.int32)
               for n in rng.integers(3, 12, (104,))]

    def run(**kw):
        eng = ContinuousBatcher(cfg, params, slots=4, temperature=0.0,
                                steps_per_sync=2, kv_block=0,
                                prefill_buckets=(8, 16), **kw)
        try:
            t0 = time.monotonic()
            futs = [eng.submit(p, 8) for p in prompts]
            outs = [f.result(120) for f in futs]
            return outs, 8 * len(prompts) / (time.monotonic() - t0), \
                eng.stats()
        finally:
            eng.stop()

    spec_outs, spec_tps, spec_stats = run(spec_k=3, draft_cfg=cfg,
                                          draft_params=params)
    plain_outs, plain_tps, _ = run()
    for p, a, b in zip(prompts, spec_outs, plain_outs):
        np.testing.assert_array_equal(a, b)
        want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 8,
                                   temperature=0.0))[0]
        np.testing.assert_array_equal(a, want)
    assert spec_stats["spec_accept_rate"] > 0.9, spec_stats
    out["serving_spec_accept_rate"] = spec_stats["spec_accept_rate"]
    out["serving_spec_tokens_s"] = round(spec_tps, 1)
    out["serving_nospec_tokens_s"] = round(plain_tps, 1)
    print(f"smoke: {len(prompts)} prompts bit-identical spec vs plain "
          f"(accept {spec_stats['spec_accept_rate']}, "
          f"{spec_tps:.0f} vs {plain_tps:.0f} tok/s on the toy model)")


def main() -> None:
    out: dict = {}
    _phase_stack(out)
    _phase_chunk_p99(out)
    _phase_spec(out)
    print(json.dumps(out))
    print("serving perf smoke OK")


if __name__ == "__main__":
    main()
