"""CI smoke: elastic distillation as a production workload (ISSUE 18).

THREE job kinds arbitrated by ONE in-process Controller against an
in-process coordination server:

- **train** — two real launcher processes (``edl_tpu.collective
  .launch``) running the instrumented inert trainer;
- **svc** — a serving fleet (advert-backed; the demand record is the
  spike, the gateway path is proven by remediation_smoke.py);
- **teach** — a ``kind="distill" fleet=True`` teacher fleet: real
  teacher CHILD PROCESSES (TeacherServer + TeacherReplica, dual advert
  on one CoordSession) spawned/killed by the controller's actuator,
  fed by a real student (DistillReader + DistillFleet + StudentFeed)
  in the parent.

The proof, phase by phase:

1. **baseline** — train=2, teach=1, svc=1 on capacity 6, nobody flaps;
2. **serving spike → training yields, distill absorbs** — a demand
   record for 4 replicas squeezes the pool; training departs a pod
   through the preemption-grace path (``reason=priority-yield`` in its
   workerlog); the teacher fleet's floor holds throughout;
3. **reclaim** — the demand decays on quiet, serving scales back in,
   training reclaims its pod;
4. **backlog → teachers 1→3** — the student streams against ONE slow
   teacher; its StudentFeed publishes backlog records; the
   DistillAutoscaler grows the fleet to 3 (grow+hold ladder), the
   ``distill-backlog`` alert fires, and ``edl_distill_*`` metrics +
   the /healthz distill block ride the merged aggregator page;
5. **teacher SIGKILL mid-epoch** — one teacher child is SIGKILLed
   while the stream is in flight; the pool requeues onto survivors and
   the controller respawns the advert gap; the finished stream audits
   EXACTLY-ONCE: every row id present once, in order, predictions
   correct — teacher death cost a retry, not a batch;
6. **decay on quiet** — the student finishes, backlog records clear,
   the fleet decays back to 1 teacher.

Run by scripts/ci.sh:  JAX_PLATFORMS=cpu python scripts/distill_chaos_smoke.py
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_TMP = tempfile.mkdtemp(prefix="edl-distill-chaos-")
os.environ.setdefault("EDL_TPU_TRACE_DIR", os.path.join(_TMP, "trace"))
os.environ.setdefault("EDL_TPU_METRICS_PORT", "0")
os.environ.setdefault("EDL_TPU_ALERT_SCALE", "0.1")
os.environ.setdefault("EDL_TPU_ALERT_DISTILL_BACKLOG_SLO", "2")
os.environ.setdefault("EDL_TPU_AUTOSCALE_QUIET", "4")
os.environ.setdefault("EDL_TPU_DEMAND_TTL", "30")
os.environ.setdefault("EDL_TPU_DISTILL_BACKLOG_GROW", "1")
os.environ.setdefault("EDL_TPU_DISTILL_BACKLOG_HOLD", "1")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
_TRAINER = os.path.join(_REPO, "tests", "helpers", "metrics_trainer.py")

FAST = {
    "EDL_TPU_TTL": "1",
    "EDL_TPU_GENERATOR_PERIOD": "0.2",
    "EDL_TPU_WATCHER_PERIOD": "0.2",
    "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
    "EDL_TPU_BARRIER_TIMEOUT": "60",
    "EDL_TPU_RESIZE_BARRIER_TIMEOUT": "30",
    "EDL_TPU_HANG_TIMEOUT": "-1",
}

N_BATCHES, BS, TBS = 200, 4, 4          # 800 student rows, 200 teacher tasks

_TEACHER_CHILD = r"""
import signal, sys, threading, time
sys.path.insert(0, {repo!r})
from edl_tpu import obs
from edl_tpu.coord.client import connect
from edl_tpu.distill.fleet import TeacherReplica
from edl_tpu.distill.teacher import TeacherServer
from edl_tpu.obs import advert as obs_advert

coord_ep, name, delay = sys.argv[1], sys.argv[2], float(sys.argv[3])
obs.install_from_env("teacher")
store = connect(coord_ep)

def predict_fn(feed):
    time.sleep(delay)                   # a deliberately slow teacher
    return {{"prediction": feed["x"] * 2.0}}

server = TeacherServer(predict_fn, port=0)
replica = TeacherReplica(store, "teach", server, "smoke-svc",
                         replica_id=name, ttl=2.0, advert_period=0.25)
obs_advert.advertise_installed(store, "teach", "teacher")
print("teacher up", name, flush=True)
stop = threading.Event()
signal.signal(signal.SIGTERM, lambda *_: stop.set())
stop.wait()
replica.stop()
store.close()
"""


def _wait(cond, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if cond():
                return
        except Exception:  # noqa: BLE001 — condition may race a restart
            pass
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def _grep_logs(root, needle):
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            p = os.path.join(dirpath, fn)
            try:
                with open(p, errors="replace") as f:
                    if needle in f.read():
                        return p
            except OSError:
                continue
    return None


def _http_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def _http_text(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


class Pool:
    """The out-of-band actuator: launchers for train, in-process
    adverts for svc, real teacher child processes for teach."""

    def __init__(self, store, coord_ep, tmp):
        self.store = store
        self.coord_ep = coord_ep
        self.tmp = tmp
        self.launchers = {}              # name -> Popen
        self.teachers = {}               # name -> Popen
        self.svc_adverts = {}            # rid -> Register handle
        self._n = 0

    def spawn_launcher(self, job, name, nodes_range, extra_env=None):
        env = dict(os.environ)
        env.update(FAST)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.update(extra_env or {})
        log = open(os.path.join(self.tmp, f"launcher-{job}-{name}.log"),
                   "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.collective.launch",
             "--job_id", job, "--coord_endpoints", self.coord_ep,
             "--nodes_range", nodes_range, "--nproc_per_node", "1",
             "--log_dir", os.path.join(self.tmp, f"log-{job}-{name}"),
             _TRAINER],
            env=env, cwd=self.tmp, stdout=log, stderr=subprocess.STDOUT)
        proc._logfile = log  # noqa: SLF001
        self.launchers[f"{job}-{name}"] = proc
        return proc

    def spawn_teacher(self, name, delay="0.3"):
        env = dict(os.environ, EDL_TPU_METRICS_PORT="0")
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        log = open(os.path.join(self.tmp, f"teacher-{name}.log"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c",
             _TEACHER_CHILD.format(repo=_REPO), self.coord_ep, name, delay],
            env=env, stdout=log, stderr=subprocess.STDOUT)
        proc._logfile = log  # noqa: SLF001
        self.teachers[name] = proc
        return proc

    def alive_launchers(self, job):
        return [n for n, p in self.launchers.items()
                if n.startswith(job + "-") and p.poll() is None]

    def alive_teachers(self):
        return [n for n, p in self.teachers.items() if p.poll() is None]

    # the controller's Actuator surface
    def scale(self, job_id, replicas):
        if job_id == "svc":
            from edl_tpu.gateway import fleet as gw_fleet
            live = sorted(self.svc_adverts)
            for i in range(len(live), replicas):
                self._n += 1
                rid = f"r{self._n}"
                self.svc_adverts[rid] = gw_fleet.advertise(
                    self.store, "svc", rid,
                    {"endpoint": f"fake:{self._n}", "slots": 8,
                     "free_slots": 8, "draining": False}, ttl=2.0)
            for rid in live[replicas:]:
                self.svc_adverts.pop(rid).stop()
        elif job_id == "teach":
            live = self.alive_teachers()
            for i in range(len(live), replicas):
                self._n += 1
                self.spawn_teacher(f"t{self._n}")
            for name in sorted(live)[replicas:]:
                self.teachers[name].send_signal(signal.SIGTERM)
        elif job_id == "train":
            live = self.alive_launchers("train")
            for i in range(len(live), replicas):
                self._n += 1
                self.spawn_launcher("train", f"re{self._n}", "1:2",
                                    {"EDL_TPU_SMOKE_STEP_S": "0.05"})
        return True

    def kill_all(self):
        for p in list(self.launchers.values()) + list(self.teachers.values()):
            if p.poll() is None:
                p.kill()
        for p in list(self.launchers.values()) + list(self.teachers.values()):
            try:
                p._logfile.close()  # noqa: SLF001
            except Exception:  # noqa: BLE001 — teardown
                pass
        for reg in self.svc_adverts.values():
            try:
                reg.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass


def _student_gen():
    import numpy as np

    def gen():
        for b in range(N_BATCHES):
            yield [(np.full((3,), b * BS + i, np.float32), b * BS + i)
                   for i in range(BS)]
    return gen


def _lm_teacher_phase(store):
    """ISSUE 20 / ROADMAP item 4 residual: a TeacherReplica serving a
    PAGED LM engine.  Every distillation batch carries the same system
    prompt, so after the first row commits its chain the rest must
    admit through prefix reuse — asserted via the engine's
    kv_prefix_hits AND via the advert payload (the extra_stats hook),
    with every returned row bit-identical to generate() greedy."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from edl_tpu.distill.fleet import TeacherReplica
    from edl_tpu.distill.predict_client import TeacherClient
    from edl_tpu.distill.teacher import TeacherServer, lm_teacher
    from edl_tpu.gateway.fleet import list_replicas
    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.serving import ContinuousBatcher

    max_new = 4
    cfg = TransformerConfig(vocab_size=61, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=128,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(11)
    system = rng.integers(1, 61, (24,)).astype(np.int32)
    tails = [rng.integers(1, 61, (4,)).astype(np.int32) for _ in range(6)]
    prompts = [np.concatenate([system, t]) for t in tails]

    engine = ContinuousBatcher(cfg, params, slots=4, temperature=0.0,
                               steps_per_sync=2, kv_block=8,
                               prefill_buckets=(8, 16, 32))
    server = replica = client = None
    try:
        server = TeacherServer(
            lm_teacher(engine, max_new=max_new), port=0,
            extra_stats=lambda: {f"engine_{k}": v
                                 for k, v in engine.stats().items()})
        replica = TeacherReplica(store, "teach-lm", server, "lm-svc",
                                 replica_id="lm-t1", ttl=5.0,
                                 advert_period=0.25)
        client = TeacherClient(server.endpoint, fetch=["tokens"])
        # two batches: the first's lead row commits the system-prompt
        # chain, everything after rides it
        ids = np.zeros((len(prompts), len(prompts[0])), np.int32)
        for i, p in enumerate(prompts):
            ids[i] = p
        lens = np.full((len(prompts),), len(prompts[0]), np.int32)
        got = [client.predict({"ids": ids[:3], "lens": lens[:3]}),
               client.predict({"ids": ids[3:], "lens": lens[3:]})]
        toks = np.concatenate([g["tokens"] for g in got])
        for p, row in zip(prompts, toks):
            want = np.asarray(generate(cfg, params, jnp.asarray(p[None]),
                                       max_new, temperature=0.0))[0]
            np.testing.assert_array_equal(row[:len(want)], want)
        st = engine.stats()
        assert st["kv_prefix_hits"] > 0, st
        _wait(lambda: list_replicas(store, "teach-lm").get(
            "lm-t1", {}).get("engine_kv_prefix_hits", 0) > 0, 30,
            "the kv hit rate to ride the teacher advert")
        print(f"smoke 0: KV-aware LM teacher — {st['kv_prefix_hits']} of "
              f"{len(prompts)} admissions rode the shared system prompt "
              f"({st['kv_prefill_tokens_skipped']} prefill tokens "
              f"skipped), outputs greedy-exact, hit rate on the advert")
    finally:
        if client is not None:
            client.close()
        if replica is not None:
            replica.stop()
        elif server is not None:
            server.stop()
        engine.stop()


def main() -> None:
    import numpy as np

    from edl_tpu import obs
    from edl_tpu.cluster import scale as scale_mod
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.coord.client import connect
    from edl_tpu.coord.server import start_server
    from edl_tpu.controller import Controller
    from edl_tpu.distill.backlog import StudentFeed
    from edl_tpu.distill.fleet import DistillFleet
    from edl_tpu.distill.reader import DistillReader
    from edl_tpu.gateway.fleet import list_replicas
    from edl_tpu.obs import advert as obs_advert
    from edl_tpu.obs.agg import AggregatorServer

    obs.install_from_env("student")
    coord = start_server("127.0.0.1", 0)
    coord_ep = f"127.0.0.1:{coord.port}"
    store = connect(coord_ep)
    pool = Pool(store, coord_ep, _TMP)

    agg_srv, ctl, fleet = None, None, None
    try:
        _lm_teacher_phase(store)

        # -- boot the three job kinds ------------------------------------
        scale_mod.save_job_spec(store, "train", kind="training")
        scale_mod.save_job_spec(store, "svc", kind="serving")
        scale_mod.save_job_spec(store, "teach", kind="distill", fleet=True)
        scale_mod.save_nodes_range(store, "svc", 1, 4)
        scale_mod.save_nodes_range(store, "teach", 1, 3)
        for name in ("a", "b"):
            pool.spawn_launcher("train", name, "1:2",
                                {"EDL_TPU_SMOKE_STEP_S": "0.05"})
        pool.scale("svc", 1)
        pool.scale("teach", 1)
        obs_advert.advertise_installed(store, "teach", "student")

        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) == 2, 90, "train cluster of 2")
        _wait(lambda: len(list_replicas(store, "teach")) == 1, 60,
              "the first teacher's replica advert")

        agg_srv = AggregatorServer(store, "teach", host="127.0.0.1",
                                   cache_s=0.0, scrape_interval=0.25,
                                   incident_dir=os.path.join(
                                       _TMP, "incidents")).start()

        ctl = Controller(store, capacity=6, max_load_desired=1.0,
                         actuator=pool, cooldown=1.0,
                         cooldown_per_resize_s=0.0,
                         preempt_grace_s=30.0, period=0.5)
        assert set(ctl.discover_jobs()) == {"train", "svc", "teach"}
        ctl.start()

        # -- 1: arbitration baseline -------------------------------------
        time.sleep(3.0)
        assert len(Cluster.load_from_store(store, "train").pods) == 2
        assert len(pool.alive_teachers()) == 1
        print("smoke 1: three job kinds under one controller, baseline "
              "stable (train=2 svc=1 teach=1 of capacity 6)")

        # -- 2: serving spike -> training yields, distill absorbs --------
        scale_mod.save_demand(store, "svc", 4, reason="gateway-p99-slo")
        _wait(lambda: len(pool.svc_adverts) >= 4, 60,
              "the serving fleet to scale out to the demanded 4")
        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) == 1, 90,
              "training to yield a pod to serving demand")
        _wait(lambda: _grep_logs(_TMP, "reason=priority-yield") is not None,
              30, "the yielded pod's workerlog to carry priority-yield")
        # the distill fleet's floor holds through the squeeze
        assert len(list_replicas(store, "teach")) >= 1, \
            "the teacher fleet must keep its floor during the spike"
        print("smoke 2: serving spike absorbed — training yielded "
              "(reason=priority-yield), the teacher fleet's floor held")

        # -- 3: quiet -> serving decays, training reclaims ---------------
        scale_mod.clear_demand(store, "svc")
        _wait(lambda: len(pool.svc_adverts) <= 1, 120,
              "the serving fleet to scale back in on sustained quiet")
        _wait(lambda: (c := Cluster.load_from_store(store, "train"))
              is not None and len(c.pods) == 2, 120,
              "training to reclaim its pod after the spike")
        print("smoke 3: demand decayed on quiet, training reclaimed "
              "the chips")

        # -- 4: student stream -> backlog -> teachers 1->3 ---------------
        fleet = DistillFleet(store, "teach", period=0.25)
        dr = DistillReader(ins=["x", "idx"], predicts=["prediction"],
                           feeds=["x"], teacher_batch_size=TBS)
        dr.set_sample_list_generator(_student_gen())
        dr.set_servers_fn(fleet.endpoints_fn())
        dr._pool_kw = {"manage_period": 0.25, "no_teacher_timeout": 60.0}
        feed = StudentFeed(store, "teach", dr, student_id="smoke-student",
                           period=0.5)

        batches = []
        stream_err = []

        def consume():
            try:
                for b in feed:
                    batches.append(b)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                stream_err.append(e)

        # the distill-backlog alert fires during the 1-teacher phase and
        # may resolve once the fleet catches up — sample /alerts
        # continuously instead of racing a point-in-time read
        alert_seen = threading.Event()
        sample_halt = threading.Event()

        def sample_alerts():
            while not sample_halt.wait(0.5):
                try:
                    firing = _http_json(
                        f"http://{agg_srv.endpoint}/alerts").get("firing", [])
                except Exception:  # noqa: BLE001 — the server may lag boot
                    continue
                if any(a.get("alert") == "distill-backlog" for a in firing):
                    alert_seen.set()
                    return

        sampler = threading.Thread(target=sample_alerts, daemon=True)
        sampler.start()

        t0 = time.time()
        consumer = threading.Thread(target=consume, daemon=True)
        consumer.start()

        _wait(lambda: len(list_replicas(store, "teach")) >= 3, 90,
              "the teacher fleet to scale 1->3 on student backlog")
        scale_latency = time.time() - t0
        print(f"smoke 4: student backlog grew the teacher fleet 1->3 "
              f"in {scale_latency:.1f}s")

        # merged observability: metrics + the /healthz distill block
        metrics = _http_text(f"http://{agg_srv.endpoint}/metrics")
        for name in ("edl_distill_backlog_rows", "edl_distill_fleet_teachers",
                     "edl_distill_student_rows_total",
                     "edl_controller_distill_demand"):
            assert name in metrics, f"{name} missing from merged /metrics"
        health = _http_json(f"http://{agg_srv.endpoint}/healthz")
        assert "distill" in health, health.keys()
        assert health["distill"].get("teachers", 0) >= 1, health["distill"]
        print("smoke 4b: edl_distill_* on merged /metrics, distill block "
              "on /healthz")

        # -- 5: teacher SIGKILL mid-epoch --------------------------------
        assert len(batches) < N_BATCHES, "stream finished before the kill"
        victim = pool.alive_teachers()[0]
        pool.teachers[victim].kill()                    # SIGKILL, no drain
        print(f"smoke 5: SIGKILLed teacher {victim} mid-epoch "
              f"({len(batches)}/{N_BATCHES} batches delivered)")

        _wait(alert_seen.is_set, 60,
              "the distill-backlog alert to fire while backlogged")
        sample_halt.set()
        print("smoke 5a: distill-backlog alert fired during the "
              "backlogged window")

        consumer.join(timeout=180)
        assert not consumer.is_alive(), "student stream wedged after SIGKILL"
        if stream_err:
            raise AssertionError(f"student stream failed: {stream_err[0]}")
        assert len(batches) == N_BATCHES, \
            f"student got {len(batches)}/{N_BATCHES} batches"
        ids = np.concatenate([b[1] for b in batches])
        np.testing.assert_array_equal(ids, np.arange(N_BATCHES * BS))
        preds = np.concatenate([b[2] for b in batches])
        np.testing.assert_allclose(preds[:, 0],
                                   np.arange(N_BATCHES * BS) * 2.0)
        print(f"smoke 5b: exactly-once audit over {N_BATCHES * BS} student "
              f"rows — zero lost, zero duplicated, order preserved, "
              f"predictions correct across the SIGKILL")

        # -- 6: decay on quiet -------------------------------------------
        _wait(lambda: len(pool.alive_teachers()) <= 1, 120,
              "the teacher fleet to decay to 1 on quiet")
        print("smoke 6: backlog cleared, teacher fleet decayed back to 1")
    except BaseException:
        sys.stdout.flush()
        for root, _dirs, files in os.walk(_TMP):
            for fn in files:
                if fn.endswith(".log"):
                    p = os.path.join(root, fn)
                    print(f"==== {p} ====")
                    print(open(p, errors="replace").read()[-4000:])
        raise
    finally:
        if ctl is not None:
            ctl.stop()
        if fleet is not None:
            fleet.stop()
        if agg_srv is not None:
            agg_srv.stop()
        pool.kill_all()
        store.close()
        coord.stop()
    print("distill chaos smoke OK")


if __name__ == "__main__":
    main()
