"""Control-plane fault tolerance: WAL durability, lease semantics across
restart, self-healing clients/sessions, snapshot-marked watches, and
the fault-injection harness.

The restart battery runs against BOTH the plain in-memory engine (an
amnesiac restart forgets everything, but its clock-seeded counters keep
stale lease ids from colliding with fresh grants) and the WAL-backed
store (which must restore revision counter, lease table and keys
bit-exactly).
"""

import os
import threading
import time

import pytest

from edl_tpu.coord.client import CoordClient, connect, connect_wait
from edl_tpu.coord.kv import PrefixWatcher
from edl_tpu.coord.memory import MemoryKV
from edl_tpu.coord.register import Register
from edl_tpu.coord.resilient import ResilientCoordClient
from edl_tpu.coord.session import CoordSession
from edl_tpu.coord.server import start_server
from edl_tpu.coord.wal import load_state, open_durable
from edl_tpu.utils import faultinject
from edl_tpu.utils.exceptions import EdlCoordError, EdlRegisterError


# ---------------------------------------------------------------------------
# WAL durability
# ---------------------------------------------------------------------------

def test_wal_restart_restores_state_bit_exactly(tmp_path):
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.1)
    kv.put("/a", b"1")
    kv.put("/b", b"2")
    kv.put("/a", b"3")          # overwrite: revision history matters
    kv.delete("/b")
    lid = kv.lease_grant(30.0)
    kv.put("/leased", b"x", lid)
    before = kv.dump_state()
    kv.close()

    kv2 = open_durable(d, sweep_period=0.1)
    assert kv2.dump_state() == before
    assert kv2.get("/a").value == b"3"
    assert kv2.get("/b") is None
    # restored lease is live and still owns its key
    assert kv2.lease_keepalive(lid) is True
    assert kv2.get("/leased").lease_id == lid
    kv2.close()


def test_wal_restart_restores_revision_and_lease_counters(tmp_path):
    d = str(tmp_path / "coord")
    kv = open_durable(d)
    rev = kv.put("/k", b"v")
    l1 = kv.lease_grant(30.0)
    l2 = kv.lease_grant(30.0)
    kv.close()

    kv2 = open_durable(d)
    # revisions keep climbing: watchers' since_revision stays meaningful
    assert kv2.put("/k2", b"v") > rev
    # stale lease ids can never collide with fresh grants
    l3 = kv2.lease_grant(30.0)
    assert l3 > max(l1, l2)
    kv2.close()


def test_close_joins_inflight_sweeper_snapshot(tmp_path):
    # an off-lock snapshot write still in flight when close() is called
    # must land BEFORE close() returns: a successor opened on the same
    # data_dir may cut its own snapshot and truncate the log, and a
    # straggler write_snapshot after that would atomically replace
    # snapshot.bin with the stale pre-close image — rewinding the
    # revision counter and losing every mutation since the image was cut
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.05, snapshot_every=1)
    in_write = threading.Event()
    release = threading.Event()
    finished = threading.Event()
    real_write = kv._journal.write_snapshot

    def slow_write(state):
        in_write.set()
        release.wait(10)
        real_write(state)
        finished.set()

    kv._journal.write_snapshot = slow_write
    kv.put("/k", b"v")                      # marks a snapshot due
    assert in_write.wait(10), "sweeper never started the snapshot write"
    closed = threading.Event()
    t = threading.Thread(target=lambda: (kv.close(), closed.set()))
    t.start()
    time.sleep(0.3)
    assert not closed.is_set(), \
        "close() returned with a snapshot write still in flight"
    release.set()
    t.join(10)
    assert closed.is_set() and finished.is_set()
    assert not kv._sweeper.is_alive()


def test_wal_data_dir_is_exclusive(tmp_path):
    # two instances appending to one wal.log from independent handles
    # interleave records and clobber each other's snapshot.bin — replay
    # then truncates at the first CRC mismatch and silently discards
    # later state.  The misconfiguration must be loud at startup, and
    # the flock must release on close so a restart can re-acquire.
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.1)
    with pytest.raises(RuntimeError, match="locked"):
        open_durable(d, sweep_period=0.1)
    kv.put("/k", b"v")
    kv.close()
    kv2 = open_durable(d, sweep_period=0.1)
    assert kv2.get("/k").value == b"v"
    kv2.close()


def test_snapshot_now_serialized_with_sweeper_cycle(tmp_path):
    # sweeper cuts image I1, releases the KV lock, stalls in the
    # off-lock write; a put M is journaled; snapshot_now() writes I2
    # (with M) and truncates the log.  If the sweeper's stale I1 then
    # lands via os.replace, disk state is I1 + empty log: the
    # acknowledged M is durably lost.  The whole cycle must serialize.
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.05, snapshot_every=1)
    real_write = kv._journal.write_snapshot
    in_first = threading.Event()
    release = threading.Event()
    calls = []

    def gated_write(state):
        calls.append(state["revision"])
        if len(calls) == 1:
            in_first.set()
            release.wait(10)
        real_write(state)

    kv._journal.write_snapshot = gated_write
    kv.put("/a", b"1")                    # marks a snapshot due
    assert in_first.wait(10), "sweeper never started the snapshot write"
    kv.put("/m", b"2")                    # journaled after I1 was cut
    t = threading.Thread(target=kv.snapshot_now)
    t.start()
    time.sleep(0.3)
    assert t.is_alive(), \
        "snapshot_now overtook an in-flight sweeper snapshot cycle"
    release.set()
    t.join(10)
    assert not t.is_alive()
    kv.close()
    kv2 = open_durable(d)
    assert kv2.get("/m").value == b"2", \
        "acknowledged put lost to a stale snapshot replacing a newer one"
    kv2.close()


def test_stale_lease_ids_cannot_collide_after_amnesiac_restart():
    """The motivating bug, pinned (and closed): a plain in-memory
    restart used to reset the lease counter to 1, so a fresh grant
    REUSED a pre-restart id — a holder still refreshing its stale id
    silently kept a DIFFERENT owner's lease alive and revoked it on
    shutdown.  Amnesiac boots now clock-seed the lease counter (both
    engines), so stale ids simply read as dead; the lease itself is
    still LOST — only the WAL path above preserves it — which sessions
    heal by re-granting."""
    kv = MemoryKV(sweep_period=0.1)
    stale = kv.lease_grant(30.0)
    kv.close()
    time.sleep(0.002)                          # a real restart spans >1 ms
    kv2 = MemoryKV(sweep_period=0.1)           # "restart" without a WAL
    fresh = kv2.lease_grant(30.0)
    assert fresh != stale                      # no silent collision
    assert kv2.lease_keepalive(stale) is False  # stale id is simply dead
    kv2.close()


def test_wal_snapshot_truncates_and_still_replays(tmp_path):
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.05, snapshot_every=10)
    for i in range(35):                 # > 3 snapshot cycles due
        kv.put(f"/k{i % 5}", str(i).encode())
    # snapshots are cut by the sweeper, OFF the mutation path: no put
    # above paid for one, but the next sweep supersedes the whole log
    wal_path = os.path.join(d, "wal.log")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and os.path.getsize(wal_path) > 0:
        time.sleep(0.02)
    assert os.path.getsize(wal_path) == 0, "sweeper never cut the snapshot"
    before = kv.dump_state()
    kv.close()
    kv2 = open_durable(d, snapshot_every=10)
    assert kv2.dump_state() == before
    kv2.close()


def test_snapshot_raced_by_append_leaves_log_whole(tmp_path):
    # the sweeper serializes + writes the snapshot image OFF the KV
    # lock; a mutation landing in that window must not be truncated
    # away — the cut is skipped and snapshot + whole log replay
    # converges (older records re-apply onto the image harmlessly)
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=30.0)   # sweeper effectively idle
    kv.put("/a", b"1")
    lid = kv.lease_grant(30.0)
    kv.put("/b", b"2", lid)
    with kv._lock:
        image = kv._snapshot_state_locked()
        mark = kv._journal.mark()
    kv._journal.write_snapshot(image)         # off-lock write...
    kv.put("/late", b"3")                     # ...raced by a mutation
    with kv._lock:
        assert kv._journal.truncate_if_unmoved(mark) is False
    before = kv.dump_state()
    kv.close()
    kv2 = open_durable(d)                     # snapshot + WHOLE log replay
    assert kv2.dump_state() == before
    assert kv2.get("/late").value == b"3"
    kv2.close()


def test_keepalive_journal_records_coalesce(tmp_path):
    # the hottest steady-state op must not pay one journal append
    # (flush) per beat: one ka record per half-TTL per lease
    from edl_tpu.coord.wal import iter_records
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.1)
    lid = kv.lease_grant(1.0)
    for _ in range(20):                       # ~1 s of 20 Hz refreshes
        assert kv.lease_keepalive(lid) is True
        time.sleep(0.05)
    kv.close()
    kas = [r for r in iter_records(os.path.join(d, "wal.log"))
           if r.get("op") == "ka"]
    assert len(kas) <= 6, f"{len(kas)} ka records for 20 beats: not coalesced"
    # and replay still restores the lease live
    kv2 = open_durable(d)
    assert kv2.lease_keepalive(lid) is True
    kv2.close()


def test_wal_torn_tail_is_truncated(tmp_path):
    d = str(tmp_path / "coord")
    kv = open_durable(d)
    kv.put("/good", b"1")
    kv.close()
    with open(os.path.join(d, "wal.log"), "ab") as f:
        f.write(b"\x00\x00\x00\x40GARBAGE")   # torn record: length lies
    kv2 = open_durable(d)
    assert kv2.get("/good").value == b"1"     # everything durable survives
    kv2.put("/after", b"2")                   # and the log keeps working
    kv2.close()
    kv3 = open_durable(d)
    assert kv3.get("/after").value == b"2"
    kv3.close()


def test_wal_restart_freezes_lease_ttl_and_grace(tmp_path):
    """A lease near its TTL at the crash must NOT be expired right at
    restart: remaining TTL is measured against the server's last-alive
    instant, and the post-restart grace holds sweeps off so the holder
    can refresh first."""
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.05)
    lid = kv.lease_grant(2.0)
    kv.put("/adv", b"x", lid)
    kv.close()
    time.sleep(3.0)  # downtime far beyond the TTL
    kv2 = open_durable(d, sweep_period=0.05, restart_grace=2.0)
    assert kv2.get("/adv") is not None, "downtime must not count against TTL"
    assert kv2.lease_keepalive(lid) is True
    # after the holder stops refreshing, expiry resumes post-grace
    time.sleep(5.0)
    assert kv2.get("/adv") is None
    kv2.close()


def test_wal_restart_expires_unrefreshed_leases_after_grace(tmp_path):
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.05)
    lid = kv.lease_grant(0.4)
    kv.put("/dead", b"x", lid)
    kv.close()
    kv2 = open_durable(d, sweep_period=0.05, restart_grace=1.5)
    assert kv2.get("/dead") is not None       # grace window
    time.sleep(3.0)                           # grace + TTL both elapsed
    assert kv2.get("/dead") is None           # nobody refreshed: swept
    assert kv2.lease_keepalive(lid) is False
    kv2.close()


def test_load_state_empty_dir(tmp_path):
    assert load_state(str(tmp_path / "nothing")) is None


def test_load_state_end_ts_advances_on_puts(tmp_path):
    # replay measures remaining TTL against the LAST record's wall
    # timestamp; put/del records are timestamped too, so a put-only log
    # tail (ka coalescing, busy store) cannot leave the last-alive
    # estimate stale and over-extend a dead holder's lease past the
    # TTL + grace bound the failure matrix promises
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=30.0)
    lid = kv.lease_grant(1.0)
    time.sleep(0.7)
    kv.put("/busy", b"x")          # timestamped: the new last-alive instant
    kv.close()
    st = load_state(d)
    remaining = {l[0]: l[2] for l in st["leases"]}[lid]
    assert remaining <= 0.5, \
        f"remaining {remaining:.2f}s: puts did not advance end_ts"


def test_keepalive_tolerates_journal_error(tmp_path):
    # a sick data_dir disk must not fail keepalives for healthy
    # holders: a lost ka record only costs replay a staler remaining
    # TTL (covered by the restart grace), so the in-memory refresh
    # lands and the journal error is deferred — same tolerance as the
    # expiry sweep
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=0.05)
    lid = kv.lease_grant(0.5)
    kv.put("/adv", b"x", lid)

    def full_disk(rec):
        raise OSError("No space left on device")
    kv._journal.append = full_disk

    deadline = time.monotonic() + 1.2
    while time.monotonic() < deadline:
        assert kv.lease_keepalive(lid) is True
        time.sleep(0.1)
    # refreshes really landed: the key outlived the original TTL
    assert kv.get("/adv") is not None
    kv.close()


def test_wait_resyncs_when_amnesiac_restart_catches_up():
    # the residual rewind hole: a NON-durable restart used to restart
    # the revision counter from zero, so re-registration churn could
    # push it back PAST a watcher's old position before its next poll —
    # the watcher then got a truncated incremental delta (phantom keys
    # kept, revisions 1..since never delivered).  Clock-seeded counters
    # land every new boot AHEAD of any prior position, forcing the
    # snapshot resync.
    kv = MemoryKV(sweep_period=0.1)
    for i in range(5):
        kv.put(f"/w/k{i}", b"x")
    since = kv.get_prefix("/w/")[1]
    kv.close()
    time.sleep(0.05)                  # clock advances past the 5 puts
    kv2 = MemoryKV(sweep_period=0.1)  # amnesiac restart
    for i in range(50):               # churn "catches up" a zero-seeded counter
        kv2.put(f"/w/new{i}", b"y")
    res = kv2.wait("/w/", since, timeout=0.2)
    assert res.snapshot, "must resync, not deliver a truncated delta"
    keys = {e.record.key for e in res.events}
    assert "/w/k0" not in keys and "/w/new0" in keys
    kv2.close()


def test_keepalive_cannot_resurrect_half_revoked_lease(tmp_path):
    # once a lease's revoke record is durable in the WAL, the live
    # server must never extend it again: a journal error that defers
    # the expiry sweep's key deletes leaves the lease in the table for
    # retry, but a restart WILL replay the revoke and drop it — a
    # keepalive resurrecting it live would diverge the store from its
    # own log (holder told True forever, state lost at next restart)
    d = str(tmp_path / "coord")
    kv = open_durable(d, sweep_period=3600.0)   # manual sweeps only
    lid = kv.lease_grant(0.2)
    kv.put("/half", b"x", lid)
    time.sleep(0.3)                             # lease expired
    real_append = kv._journal.append

    def sick_for_deletes(rec):
        if rec.get("op") == "del":
            raise OSError("EIO")
        return real_append(rec)

    kv._journal.append = sick_for_deletes
    with kv._lock:
        kv._expire_locked(time.monotonic())     # revoke lands, del fails
    assert kv.lease_keepalive(lid) is False, \
        "a durably-revoked lease must not be resurrected"
    with pytest.raises(KeyError):
        kv.put("/half2", b"y", lid)             # nor accept new keys
    kv._journal.append = real_append
    with kv._lock:
        kv._expire_locked(time.monotonic())     # retry finishes the job
    assert kv.get("/half") is None
    before = kv.dump_state()
    kv.close()
    kv2 = open_durable(d)                       # replay agrees with live
    assert kv2.dump_state() == before
    assert kv2.lease_keepalive(lid) is False
    kv2.close()


# ---------------------------------------------------------------------------
# lease semantics battery — plain engine AND WAL-backed server
# ---------------------------------------------------------------------------

@pytest.fixture(params=["memory", "wal-server"])
def battery_kv(request, tmp_path):
    if request.param == "memory":
        kv = MemoryKV(sweep_period=0.1)
        yield kv
        kv.close()
    else:
        server = start_server("127.0.0.1", 0,
                              data_dir=str(tmp_path / "coord"))
        client = CoordClient(f"127.0.0.1:{server.port}")
        yield client
        client.close()
        server.stop()


def test_keepalive_on_revoked_lease(battery_kv):
    lid = battery_kv.lease_grant(5.0)
    battery_kv.put("/rk", b"v", lid)
    battery_kv.lease_revoke(lid)
    assert battery_kv.lease_keepalive(lid) is False
    assert battery_kv.get("/rk") is None


def test_advert_reregisters_after_forced_lease_expiry(battery_kv):
    reg = Register(battery_kv, "/svc/nodes/n0", b"ep", ttl=0.6)
    first = reg._lease_id
    battery_kv.lease_revoke(first)            # forced expiry
    deadline = time.time() + 10
    while time.time() < deadline:
        rec = battery_kv.get("/svc/nodes/n0")
        if rec is not None and rec.lease_id != first:
            break
        time.sleep(0.05)
    rec = battery_kv.get("/svc/nodes/n0")
    assert rec is not None and rec.value == b"ep", \
        "advert must re-register after its lease was torn away"
    assert rec.lease_id != first, "a NEW lease must back the re-registration"
    assert not reg.is_stopped
    reg.stop()
    assert battery_kv.get("/svc/nodes/n0") is None


# ---------------------------------------------------------------------------
# self-healing client
# ---------------------------------------------------------------------------

def test_resilient_client_fails_over_to_live_endpoint(coord_server):
    live = f"127.0.0.1:{coord_server.port}"
    rc = ResilientCoordClient(["127.0.0.1:1", live], timeout=2.0,
                              retry_deadline=20.0, backoff_init=0.01)
    assert rc.put("/r/k", b"v") > 0          # dead first endpoint survived
    assert rc.get("/r/k").value == b"v"
    assert rc.endpoint == live               # seated on the survivor
    rc.close()


def test_resilient_client_survives_server_restart(tmp_path):
    d = str(tmp_path / "coord")
    server = start_server("127.0.0.1", 0, data_dir=d)
    port = server.port
    rc = ResilientCoordClient([f"127.0.0.1:{port}"], timeout=2.0,
                              retry_deadline=20.0, backoff_init=0.01)
    lid = rc.lease_grant(30.0)
    rc.put("/sr/k", b"v", lid)
    server.stop()
    server.kv.close()  # release the WAL before the restart reopens it

    done = threading.Event()
    result: dict = {}

    def op():
        try:
            result["rec"] = rc.get("/sr/k")
        except Exception as e:  # noqa: BLE001
            result["err"] = e
        done.set()

    t = threading.Thread(target=op)
    t.start()                                 # retries against the dead port
    time.sleep(0.5)
    server2 = start_server("127.0.0.1", port, data_dir=d)
    assert done.wait(15), "op never completed after restart"
    assert "err" not in result, result.get("err")
    assert result["rec"].value == b"v"
    assert result["rec"].lease_id == lid      # WAL restored the lease link
    assert rc.lease_keepalive(lid) is True
    rc.close()
    server2.stop()


def test_resilient_client_scoped_deadline_bounds_blocking():
    rc = ResilientCoordClient(["127.0.0.1:1"], timeout=0.2,
                              retry_deadline=60.0, backoff_init=0.01)
    t0 = time.monotonic()
    with pytest.raises(EdlCoordError):
        with rc.scoped_deadline(0.5):
            rc.put("/x", b"v")
    assert time.monotonic() - t0 < 5.0, "scoped budget must bound retrying"
    rc.close()


def test_hung_endpoint_fails_over_within_one_op(coord_server):
    # a blackholed endpoint (TCP accepts via the listen backlog, never
    # answers) must not eat the whole retry budget in one in-flight
    # attempt: with a standby available the per-attempt transport cap
    # splits the remaining budget so FAILOVER_AFTER hung attempts still
    # leave room to reach the healthy endpoint — the op SUCCEEDS inside
    # its own budget instead of raising while a standby sat idle
    import socket
    sink = socket.socket()
    sink.bind(("127.0.0.1", 0))
    sink.listen(1)
    hung = f"127.0.0.1:{sink.getsockname()[1]}"
    rc = ResilientCoordClient([hung, f"127.0.0.1:{coord_server.port}"],
                              timeout=30.0, retry_deadline=8.0,
                              backoff_init=0.01)
    try:
        t0 = time.monotonic()
        rc.put("/ho/k", b"v")                  # must not raise
        assert time.monotonic() - t0 < 8.0
        assert rc.get("/ho/k").value == b"v"
    finally:
        rc.close()
        sink.close()


def test_scoped_deadline_budget_shared_across_ops():
    # the scope's budget is one absolute deadline for EVERY op inside
    # it: a heartbeat beat (keepalive + k heal ops under _op_lock)
    # against a dead store must give up after ~one TTL total, not one
    # TTL per op — per-op budgets would hold the session's _op_lock for
    # k·TTL and expire the very lease the scope protects
    rc = ResilientCoordClient(["127.0.0.1:1"], timeout=0.2,
                              retry_deadline=60.0, backoff_init=0.01)
    t0 = time.monotonic()
    with rc.scoped_deadline(0.8):
        for _ in range(3):
            with pytest.raises(EdlCoordError):
                rc.put("/x", b"v")
    assert time.monotonic() - t0 < 2.0, \
        "scoped budget must be shared across the scope's ops"
    rc.close()


def test_scoped_deadline_bounds_inflight_rpc_on_hung_server(coord_server,
                                                            clean_faults):
    """A HUNG endpoint (connection accepted, answer delayed) must stay
    inside the scoped budget too — the in-flight transport timeout is
    capped by the remaining budget, not just the sleeps between
    retries (else heartbeat.beat's 5s cap could stall a full 30s
    transport timeout, or 60s with the internal redial)."""
    faultinject.configure("server:kv_put:delay:6")
    rc = ResilientCoordClient([f"127.0.0.1:{coord_server.port}"],
                              timeout=30.0, retry_deadline=60.0,
                              backoff_init=0.01)
    t0 = time.monotonic()
    with pytest.raises(EdlCoordError):
        with rc.scoped_deadline(1.0):
            rc.put("/hang/k", b"v")
    assert time.monotonic() - t0 < 5.0, \
        "scoped budget must bound the in-flight RPC, not only retries"
    rc.close()


def test_resilient_wait_snapshot_resync_after_failover():
    """Failover lands on an INDEPENDENT store whose revisions are
    unrelated to the watch position: the first wait answered by the new
    endpoint must be a snapshot resync (old store's keys become
    phantoms otherwise, and the new store's existing keys would never
    be delivered as events)."""
    a = start_server("127.0.0.1", 0)
    b = start_server("127.0.0.1", 0)
    ep_a, ep_b = f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"
    try:
        # store B has pre-existing state the watcher must discover
        cb = CoordClient(ep_b)
        cb.put("/fo/only-on-b", b"b1")
        cb.close()
        rc = ResilientCoordClient([ep_a, ep_b], timeout=2.0,
                                  retry_deadline=20.0, backoff_init=0.01)
        rc.put("/fo/only-on-a", b"a1")
        res = rc.wait("/fo/", 0, 0.2)
        seen_rev = res.revision
        assert any(e.record.key == "/fo/only-on-a" for e in res.events)

        # "kill" store A.  stop() closes the listener but an in-process
        # ThreadingTCPServer leaves live handler threads serving already-
        # open sockets (a real SIGKILL kills those too), so also drop
        # the client's pooled connection to make the death real.
        a.stop()
        with rc._lock:
            stale = rc._clients.pop(ep_a, None)
        if stale is not None:
            stale.close()
        assert rc.put("/fo/healed", b"h") > 0  # retried + failed over to B
        assert rc.endpoint == ep_b
        res2 = rc.wait("/fo/", seen_rev, 0.2)
        assert res2.snapshot is True, \
            "wait answered by a different independent store must resync"
        keys = {e.record.key for e in res2.events}
        assert keys == {"/fo/only-on-b", "/fo/healed"}
        assert all(e.type == "put" for e in res2.events)
        rc.close()
    finally:
        b.stop()
        try:
            a.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass


def test_resilient_wait_resyncs_when_baseline_came_from_dead_endpoint():
    """PrefixWatcher baselines its view with get_prefix; if that was
    served by an endpoint that dies before the FIRST wait, the wait —
    answered by the other independent store — must still resync."""
    a = start_server("127.0.0.1", 0)
    b = start_server("127.0.0.1", 0)
    ep_a, ep_b = f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"
    try:
        cb = CoordClient(ep_b)
        cb.put("/fb/on-b", b"b1")
        cb.close()
        rc = ResilientCoordClient([ep_a, ep_b], timeout=2.0,
                                  retry_deadline=20.0, backoff_init=0.01)
        rc.put("/fb/on-a", b"a1")
        recs, rev = rc.get_prefix("/fb/")  # baseline view, served by A
        assert {r.key for r in recs} == {"/fb/on-a"}

        a.stop()  # see the note in the test above: make the death real
        with rc._lock:
            stale = rc._clients.pop(ep_a, None)
        if stale is not None:
            stale.close()
        assert rc.put("/fb/poke", b"p") > 0   # drives the failover to B
        res = rc.wait("/fb/", rev, 0.2)       # FIRST wait on this prefix
        assert res.snapshot is True
        assert {e.record.key for e in res.events} == {"/fb/on-b", "/fb/poke"}
        rc.close()
    finally:
        b.stop()
        try:
            a.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass


def test_connect_returns_resilient_and_reports_cause(coord_server):
    store = connect(f"127.0.0.1:{coord_server.port}")
    assert isinstance(store, ResilientCoordClient)
    store.put("/c/k", b"v")
    store.close()
    with pytest.raises(ConnectionError) as ei:
        connect("127.0.0.1:1", timeout=0.2)
    # ping's transport error is surfaced, not swallowed into "None"
    assert "None" not in str(ei.value)


def test_connect_wait_tolerates_late_server():
    from edl_tpu.utils.network import find_free_ports
    port = find_free_ports(1)[0]
    holder: dict = {}

    def boot_later():
        time.sleep(1.0)
        holder["server"] = start_server("127.0.0.1", port)

    t = threading.Thread(target=boot_later)
    t.start()
    store = connect_wait(f"127.0.0.1:{port}", timeout=2.0, wait=30.0)
    store.put("/late/k", b"v")
    store.close()
    t.join()
    holder["server"].stop()


def test_ping_distinguishes_transport_from_handler_errors():
    # transport-unreachable RAISES (connect()'s last_err gets populated)
    with pytest.raises(EdlCoordError):
        CoordClient("127.0.0.1:1", timeout=0.2).ping()
    # a reachable server that is NOT a coord store answers False
    from edl_tpu.rpc.server import RpcServer
    srv = RpcServer("127.0.0.1", 0).start()
    try:
        client = CoordClient(f"127.0.0.1:{srv.port}", timeout=2.0)
        assert client.ping() is False
        client.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# CoordSession
# ---------------------------------------------------------------------------

def test_session_owns_multiple_keys_one_lease(memkv):
    s = CoordSession(memkv, ttl=5.0)
    s.register("/m/a", b"1")
    s.register("/m/b", b"2")
    assert memkv.get("/m/a").lease_id == s.lease_id
    assert memkv.get("/m/b").lease_id == s.lease_id
    s.update("/m/a", b"1b")
    assert memkv.get("/m/a").value == b"1b"
    s.unregister("/m/b")
    assert memkv.get("/m/b") is None
    s.close()
    assert memkv.get("/m/a") is None          # revoke swept the lease's keys


def test_session_regrants_and_reputs_after_lease_loss(memkv):
    s = CoordSession(memkv, ttl=0.6)
    s.register("/h/a", b"1")
    s.register("/h/b", b"2")
    first = s.lease_id
    memkv.lease_revoke(first)                 # blip longer than one TTL
    deadline = time.time() + 10
    while time.time() < deadline and (
            memkv.get("/h/a") is None or s.lease_id == first):
        time.sleep(0.05)
    assert s.lease_id != first
    assert memkv.get("/h/a").value == b"1"
    assert memkv.get("/h/b").value == b"2"
    assert memkv.get("/h/a").lease_id == s.lease_id
    assert not s.is_stopped
    s.close()


def test_session_exclusive_key_stops_on_lease_loss(memkv):
    lost: list = []
    s = CoordSession(memkv, ttl=0.6, on_lost=lost.append)
    s.register("/seat/x", b"A", exclusive=True)
    memkv.lease_revoke(s.lease_id)
    memkv.put("/seat/x", b"B")                # usurper takes the seat
    deadline = time.time() + 10
    while not s.is_stopped and time.time() < deadline:
        time.sleep(0.05)
    assert s.is_stopped and isinstance(s.error, EdlRegisterError)
    assert lost and isinstance(lost[0], EdlRegisterError)
    assert memkv.get("/seat/x").value == b"B"  # usurper untouched


def test_session_survives_nondurable_server_restart(tmp_path):
    """No WAL: the restarted server forgot the lease entirely — the
    session must re-grant and re-put, healing the 'blip longer than one
    TTL permanently unregisters a healthy component' failure mode."""
    server = start_server("127.0.0.1", 0)      # NOT durable, on purpose
    port = server.port
    rc = ResilientCoordClient([f"127.0.0.1:{port}"], timeout=2.0,
                              retry_deadline=15.0, backoff_init=0.01)
    s = CoordSession(rc, ttl=1.0)
    s.register("/nv/adv", b"ep")
    server.stop()
    time.sleep(1.5)                            # outage > one TTL
    server2 = start_server("127.0.0.1", port)  # fresh empty store
    deadline = time.time() + 20
    while time.time() < deadline:
        rec = rc.get("/nv/adv")
        if rec is not None:
            break
        time.sleep(0.1)
    assert rc.get("/nv/adv") is not None, \
        "session must re-register on the amnesiac server"
    assert not s.is_stopped
    s.close()
    rc.close()
    server2.stop()


def test_advert_modules_share_one_session(memkv):
    from edl_tpu.gateway import fleet
    from edl_tpu.memstate import advert as mem_advert
    from edl_tpu.obs import advert as obs_advert

    s = CoordSession(memkv, ttl=5.0)
    h1 = mem_advert.advertise(memkv, "j", "pod0", "1.2.3.4:1", session=s)
    h2 = fleet.advertise(memkv, "j", "rep0", {"endpoint": "1.2.3.4:2"},
                         session=s)
    h3 = obs_advert.advertise_metrics(memkv, "j", "trainer", "1.2.3.4:3",
                                      name="t0", session=s)
    assert mem_advert.list_adverts(memkv, "j") == {"pod0": "1.2.3.4:1"}
    assert "rep0" in fleet.list_replicas(memkv, "j")
    assert "t0" in obs_advert.list_metrics_targets(memkv, "j")
    # all three ride ONE lease
    lease_ids = {memkv.get(k).lease_id
                 for k in ("/edl_tpu/j/memstate/nodes/pod0",
                           "/edl_tpu/j/serving/nodes/rep0",
                           "/edl_tpu/j/obs/metrics/t0")}
    assert lease_ids == {s.lease_id}
    h2.update(b'{"endpoint": "1.2.3.4:2", "free_slots": 3}')
    assert fleet.list_replicas(memkv, "j")["rep0"]["free_slots"] == 3
    h1.stop()
    assert mem_advert.list_adverts(memkv, "j") == {}
    assert "rep0" in fleet.list_replicas(memkv, "j")  # others unaffected
    h3.stop()
    h2.stop()
    s.close()


def test_unregister_failure_retried_by_heartbeat(memkv):
    # a delete that fails mid-blip must not leave the key pinned to the
    # shared lease (which the session keeps refreshing forever) — the
    # heartbeat retries the orphaned removal until it lands
    s = CoordSession(memkv, ttl=0.4)
    s.register("/u/a", b"1")
    real_delete = memkv.delete
    fails = {"n": 2}

    def flaky_delete(key):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise EdlCoordError("blip")
        return real_delete(key)

    memkv.delete = flaky_delete
    try:
        s.unregister("/u/a")              # parked as an orphan, no raise
        deadline = time.time() + 10
        while time.time() < deadline and memkv.get("/u/a") is not None:
            time.sleep(0.05)
        assert memkv.get("/u/a") is None, \
            "heartbeat must retry the orphaned delete"
        assert fails["n"] == 0
    finally:
        memkv.delete = real_delete
        s.close()


def test_unregister_wins_over_racing_heal_reput(memkv):
    # the heartbeat's heal loop snapshots _keys, then re-puts any key
    # missing from the store; an unregister racing that window must
    # still end with the key GONE — not re-put on the refreshed shared
    # lease with nothing left tracking it
    s = CoordSession(memkv, ttl=0.3)
    s.register("/r/k", b"v")
    real_get = memkv.get
    in_heal = threading.Event()
    release = threading.Event()

    def gated_get(key):
        if key == "/r/k" and not release.is_set():
            in_heal.set()
            release.wait(10)
        return real_get(key)

    memkv.delete("/r/k")      # swept out from under the session
    memkv.get = gated_get
    try:
        assert in_heal.wait(10), "heartbeat never entered heal"
        # heal is mid-window (sees the key missing, will re-put it);
        # unregister must serialize behind it and delete LAST
        t = threading.Thread(target=lambda: s.unregister("/r/k"))
        t.start()
        time.sleep(0.2)
        release.set()
        t.join(10)
        assert not t.is_alive()
    finally:
        memkv.get = real_get
        release.set()
    deadline = time.time() + 5
    while time.time() < deadline and real_get("/r/k") is not None:
        time.sleep(0.05)
    assert real_get("/r/k") is None, \
        "unregister racing a heal re-put must still remove the key"
    s.close()


def test_unregister_untracked_key_is_a_noop(memkv):
    # stop(revoke=False) called twice (a drain path and a shutdown path
    # both releasing the same advert) must not turn the second call into
    # an immediate store delete — and unregister of a key this session
    # never owned must not tear down someone else's record
    s = CoordSession(memkv, ttl=5.0)
    s.register("/n/k", b"v")
    s.unregister("/n/k", delete=False)     # moved to a throwaway lease
    assert memkv.get("/n/k") is not None   # lapses at TTL, not now
    s.unregister("/n/k", delete=False)     # double-stop: must be a no-op
    assert memkv.get("/n/k") is not None
    s.unregister("/n/k")                   # even delete=True: not ours anymore
    assert memkv.get("/n/k") is not None
    memkv.put("/n/foreign", b"x")
    s.unregister("/n/foreign")             # never registered here
    assert memkv.get("/n/foreign") is not None
    s.close()


def test_update_losing_race_to_unregister_never_puts(memkv):
    # SessionKey.update records the new value, then puts under
    # _op_lock; an unregister whose pop lands while the update is
    # still waiting for that lock must win outright — the update's
    # membership re-check skips the put instead of landing it around
    # the delete and resurrecting an untracked advert on the refreshed
    # shared lease
    s = CoordSession(memkv, ttl=5.0)
    s.register("/r/u", b"v0")
    puts = []
    real_put = memkv.put

    def spy_put(key, value, lease_id=0):
        puts.append((key, value))
        return real_put(key, value, lease_id)

    memkv.put = spy_put
    try:
        s._op_lock.acquire()          # pin both racers at the lock
        t_upd = threading.Thread(target=lambda: s.update("/r/u", b"v1"))
        t_upd.start()
        deadline = time.time() + 5    # value recorded before the lock wait
        while time.time() < deadline and s._keys["/r/u"].value != b"v1":
            time.sleep(0.01)
        assert s._keys["/r/u"].value == b"v1"
        t_unr = threading.Thread(target=lambda: s.unregister("/r/u"))
        t_unr.start()
        deadline = time.time() + 5    # the pop precedes its lock wait
        while time.time() < deadline and "/r/u" in s._keys:
            time.sleep(0.01)
        assert "/r/u" not in s._keys
        s._op_lock.release()          # let them race in either order
        t_upd.join(10)
        t_unr.join(10)
        assert not t_upd.is_alive() and not t_unr.is_alive()
        assert memkv.get("/r/u") is None, "unregister must win"
        assert ("/r/u", b"v1") not in puts, \
            "an update that lost the race must skip its put"
    finally:
        memkv.put = real_put
        s.close()


def test_reregister_cancels_pending_orphaned_unregister(memkv):
    # an unregister whose delete failed mid-blip parks the key as an
    # orphan; re-advertising the SAME key must cancel that orphan, or
    # the heartbeat's drain would delete the fresh advert a beat later
    s = CoordSession(memkv, ttl=0.4)
    s.register("/o/k", b"old")
    real_delete = memkv.delete

    def failing_delete(key):
        raise EdlCoordError("blip")

    memkv.delete = failing_delete
    try:
        s.unregister("/o/k")          # parked as an orphan, no raise
    finally:
        memkv.delete = real_delete
    deleted = []

    def spy_delete(key):
        deleted.append(key)
        return real_delete(key)

    memkv.delete = spy_delete
    try:
        s.register("/o/k", b"new")    # re-advertise: cancels the orphan
        time.sleep(1.2)               # several beats of _drain_orphans
        assert "/o/k" not in deleted, \
            "orphan drain deleted the re-registered advert"
        rec = memkv.get("/o/k")
        assert rec is not None and rec.value == b"new"
    finally:
        memkv.delete = real_delete
        s.close()


def test_failed_exclusive_seize_spawns_no_heartbeat_thread():
    # every follower probes the leader seat each retry_period for the
    # whole job — a failed seize must cost round trips only, not a
    # heartbeat thread spawn + join per attempt
    kv = MemoryKV(sweep_period=0.1)
    winner = Register(kv, "/seat", b"w", ttl=5.0, exclusive=True)
    for _ in range(3):
        with pytest.raises(EdlRegisterError):
            Register(kv, "/seat", b"l", ttl=5.0, exclusive=True)
    seat_threads = [t for t in threading.enumerate()
                    if t.name == "coord-session:/seat"]
    assert len(seat_threads) == 1, "losers must not have started threads"
    assert len(kv.dump_state()["leases"]) == 1, "losers' leases revoked"
    assert kv.get("/seat").value == b"w"
    winner.stop()
    kv.close()


# ---------------------------------------------------------------------------
# snapshot-marked waits / replace-not-merge watchers
# ---------------------------------------------------------------------------

def test_wait_compaction_result_is_marked_snapshot(memkv):
    memkv.put("/s/live", b"v")
    for i in range(5000):
        memkv.put("/junk/k", str(i).encode())
    res = memkv.wait("/s/", 0, timeout=0.5)
    assert res.snapshot is True
    assert [e.record.key for e in res.events] == ["/s/live"]
    # an in-log wait stays incremental
    res2 = memkv.wait("/s/", res.revision, timeout=0.1)
    assert res2.snapshot is False


def test_wait_snapshot_flag_crosses_the_wire(coord_client):
    coord_client.put("/w/live", b"v")
    for i in range(5000):
        coord_client.put("/junk/k", str(i).encode())
    res = coord_client.wait("/w/", 0, timeout=1.0)
    assert res.snapshot is True
    assert any(e.record.key == "/w/live" for e in res.events)


def test_prefix_watcher_learns_deletes_across_compaction(memkv):
    """The satellite fix: a watcher whose revision fell out of the event
    log must not keep a phantom key — the snapshot resync REPLACES its
    view, surfacing the compacted-away delete as a synthetic event."""
    memkv.put("/pw/a", b"1")
    memkv.put("/pw/b", b"2")
    seen: list = []
    w = PrefixWatcher(memkv, "/pw/", lambda evs: seen.extend(evs),
                      period=0.5)
    # mutate BEFORE the watcher's first poll, then blow out the log so
    # its since_revision predates every buffered event
    memkv.delete("/pw/a")
    for i in range(5000):
        memkv.put("/junk/k", str(i).encode())
    w.start()
    deadline = time.time() + 10
    while time.time() < deadline and not any(
            e.type == "delete" and e.record.key == "/pw/a" for e in seen):
        time.sleep(0.05)
    w.stop()
    assert any(e.type == "delete" and e.record.key == "/pw/a"
               for e in seen), f"phantom key never deleted: {seen}"
    assert any(e.type == "put" and e.record.key == "/pw/b" for e in seen)


def test_wait_after_wal_restart_serves_snapshot_to_old_watcher(tmp_path):
    """After a restart the event log is empty but the revision counter
    is restored: an old watcher must get a snapshot resync, not hang."""
    d = str(tmp_path / "coord")
    kv = open_durable(d)
    kv.put("/ws/a", b"1")
    rev_then = kv.put("/ws/b", b"2")
    kv.delete("/ws/b")
    kv.close()
    kv2 = open_durable(d)
    res = kv2.wait("/ws/", rev_then - 1, timeout=1.0)
    assert res.snapshot is True
    assert [e.record.key for e in res.events] == ["/ws/a"]
    kv2.close()


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

@pytest.fixture
def clean_faults():
    yield
    faultinject.configure(None)


def test_faultinject_parse_grammar():
    rules = faultinject.parse("kv_put:error:0.3;connect:delay:1.5;"
                              "server:wait:delay:0.2:0.5")
    assert rules[0].point == "kv_put" and rules[0].action == "error" \
        and rules[0].prob == 0.3 and rules[0].side is None
    assert rules[1].action == "delay" and rules[1].arg == 1.5 \
        and rules[1].prob == 1.0
    assert rules[2].side == "server" and rules[2].prob == 0.5
    for bad in ("nope", "a:b:c", "kv_put:error:2.0", "kv_put:explode:1",
                "kv_put:error:1.0:0.3"):  # error takes ONE number
        with pytest.raises(faultinject.FaultSpecError):
            faultinject.parse(bad)
    assert faultinject.parse("") == []


def test_faultinject_error_fires_and_counts(clean_faults):
    from edl_tpu.utils.faultinject import _INJECTED
    faultinject.configure("kv_put:error:1.0", seed=7)
    before = _INJECTED.labels(point="kv_put", action="error").value
    with pytest.raises(EdlCoordError):
        faultinject.fire("kv_put")
    assert _INJECTED.labels(point="kv_put", action="error").value == before + 1
    faultinject.fire("kv_get")                 # other points untouched


def test_faultinject_probability_is_seeded(clean_faults):
    faultinject.configure("kv_put:error:0.5", seed=123)
    outcomes1 = []
    for _ in range(20):
        try:
            faultinject.fire("kv_put")
            outcomes1.append(False)
        except EdlCoordError:
            outcomes1.append(True)
    faultinject.configure("kv_put:error:0.5", seed=123)
    outcomes2 = []
    for _ in range(20):
        try:
            faultinject.fire("kv_put")
            outcomes2.append(False)
        except EdlCoordError:
            outcomes2.append(True)
    assert outcomes1 == outcomes2, "seeded runs must reproduce"
    assert any(outcomes1) and not all(outcomes1)


def test_faultinject_client_side_hits_rpc_path(coord_server, clean_faults):
    client = CoordClient(f"127.0.0.1:{coord_server.port}")
    faultinject.configure("client:kv_put:error:1.0")
    with pytest.raises(EdlCoordError, match="injected"):
        client.put("/fi/k", b"v")
    faultinject.configure(None)
    assert client.put("/fi/k", b"v") > 0
    client.close()


def test_faultinject_server_side_crosses_wire_as_retryable(coord_server,
                                                          clean_faults):
    client = CoordClient(f"127.0.0.1:{coord_server.port}")
    faultinject.configure("server:kv_get:error:1.0")
    with pytest.raises(EdlCoordError, match="injected"):
        client.get("/fi/k")
    assert client.put("/fi/other", b"v") > 0   # only kv_get is poisoned
    client.close()


def test_faultinject_delay(coord_server, clean_faults):
    client = CoordClient(f"127.0.0.1:{coord_server.port}")
    faultinject.configure("client:kv_put:delay:0.3")
    t0 = time.monotonic()
    client.put("/fi/slow", b"v")
    assert time.monotonic() - t0 >= 0.3
    client.close()


def test_resilient_client_heals_injected_faults(coord_server, clean_faults):
    """The harness proves the healing stack end to end: a 50% kv_put
    error rate must be invisible above ResilientCoordClient."""
    faultinject.configure("client:kv_put:error:0.5", seed=42)
    rc = ResilientCoordClient([f"127.0.0.1:{coord_server.port}"],
                              retry_deadline=30.0, backoff_init=0.01)
    for i in range(20):
        assert rc.put(f"/heal/{i}", b"v") > 0
    rc.close()


# ---------------------------------------------------------------------------
# retry backoff satellite
# ---------------------------------------------------------------------------

def test_retry_backoff_and_counter(monkeypatch):
    from edl_tpu.utils.retry import _ATTEMPTS, retry_until_timeout

    sleeps: list = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    calls = {"n": 0}

    @retry_until_timeout(interval=0.1, backoff=2.0, max_interval=0.5,
                         jitter=False)
    def flaky():
        calls["n"] += 1
        if calls["n"] < 5:
            raise EdlCoordError("blip")
        return "ok"

    before = _ATTEMPTS.labels(fn="flaky").value
    assert flaky(timeout=60.0) == "ok"
    assert _ATTEMPTS.labels(fn="flaky").value == before + 4
    assert sleeps == [0.1, 0.2, 0.4, 0.5]      # exponential, capped


def test_retry_jitter_bounded(monkeypatch):
    from edl_tpu.utils.retry import retry_until_timeout

    sleeps: list = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    calls = {"n": 0}

    @retry_until_timeout(interval=0.2, backoff=2.0, jitter=True)
    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise EdlCoordError("blip")
        return "ok"

    assert flaky(timeout=60.0) == "ok"
    assert len(sleeps) == 3
    for s, cap in zip(sleeps, (0.2, 0.4, 0.8)):
        assert 0.0 <= s <= cap


def test_retry_jitter_applies_without_backoff(monkeypatch):
    # jitter=True must fan out even at the legacy fixed interval
    # (backoff=1.0) — a whole job retrying at exactly 1 s is the
    # synchronized stampede the knob exists to prevent
    from edl_tpu.utils.retry import retry_until_timeout

    monkeypatch.setattr("random.uniform", lambda a, b: 0.123)
    sleeps: list = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    calls = {"n": 0}

    @retry_until_timeout(interval=1.0, jitter=True)
    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise EdlCoordError("blip")
        return "ok"

    assert flaky(timeout=60.0) == "ok"
    assert sleeps == [0.123, 0.123]
