"""Ring attention == dense attention, on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.attention import dense_attention, dot_product_attention
from edl_tpu.ops.ring import ring_attention
from edl_tpu.parallel import MeshSpec, build_mesh, logical_sharding

KEY = jax.random.key(7)


def _qkv(B=2, L=32, H=4, D=16, dtype=jnp.float32):
    ks = jax.random.split(KEY, 3)
    shape = (B, L, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("spec", [MeshSpec(dp=1, sp=8), MeshSpec(dp=2, sp=4),
                                  MeshSpec(dp=2, sp=2, tp=2)])
def test_ring_matches_dense(causal, spec):
    mesh = build_mesh(spec)
    q, k, v = _qkv()
    expected = dense_attention(q, k, v, causal=causal)
    sharding = logical_sharding(("batch", "seq", "heads", None), mesh)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, causal=causal))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16():
    mesh = build_mesh(MeshSpec(sp=4))
    q, k, v = _qkv(dtype=jnp.bfloat16)
    expected = dense_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, causal=True))(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(expected, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_transformer_with_ring_matches_dense():
    from edl_tpu.models import TransformerConfig, TransformerLM
    mesh = build_mesh(MeshSpec(dp=2, sp=4))
    base = dict(vocab_size=64, num_layers=2, embed_dim=32, num_heads=4,
                mlp_dim=64, max_len=32, dtype=jnp.float32, remat=False)
    dense_model = TransformerLM(TransformerConfig(attention_impl="dense", **base))
    ring_model = TransformerLM(TransformerConfig(attention_impl="ring",
                                                 mesh=mesh, **base))
    ids = jax.random.randint(KEY, (4, 32), 0, 64)
    variables = dense_model.init(KEY, ids)
    expected = dense_model.apply(variables, ids)
    gids = jax.device_put(ids, logical_sharding(("batch", "seq"), mesh))
    out = jax.jit(lambda p, i: ring_model.apply({"params": p}, i))(
        variables["params"], gids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-3, atol=2e-3)


def test_ring_with_grouped_kv_matches_dense():
    """GQA through the ring path: dispatch expands the kv groups before
    the shard_map, so grouped K/V must equal dense grouped attention."""
    mesh = build_mesh(MeshSpec(dp=2, sp=4))
    rng = np.random.default_rng(9)
    H, Hk = 4, 2
    q = jnp.asarray(rng.normal(size=(2, 32, H, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 32, Hk, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 32, Hk, 8)), jnp.float32)
    expected = dense_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b, c: dot_product_attention(
        a, b, c, causal=True, impl="ring", mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_dispatch_requires_mesh_for_ring():
    q, k, v = _qkv(L=8)
    with pytest.raises(ValueError, match="needs the mesh"):
        dot_product_attention(q, k, v, impl="ring")


@pytest.mark.parametrize("chunk", [4, 5, 7, 32])
def test_ring_kv_chunking_exact(chunk):
    """Chunked inner folds == unchunked == dense, any divisor outcome."""
    mesh = build_mesh(MeshSpec(dp=2, sp=4))
    q, k, v = _qkv()
    expected = dense_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, causal=True, kv_chunk=chunk))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_ring_kv_chunking_with_masked_tail():
    """Non-divisor shard lengths use ceil chunks + a masked pad tail."""
    mesh = build_mesh(MeshSpec(dp=2, sp=4))
    q, k, v = _qkv(B=2, L=28, H=2, D=8)  # 7 per shard: 7 = 2*3+1 w/ chunk 3
    for causal in (False, True):
        expected = dense_attention(q, k, v, causal=causal)
        out = jax.jit(lambda a, b, c, cz=causal: ring_attention(
            a, b, c, mesh, causal=cz, kv_chunk=3))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)
