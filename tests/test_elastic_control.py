"""Elastic control plane, in-process: leader failover, cluster
generation/scale-out/scale-in, barrier protocol.

Mirrors reference tests test_leader_pod.py, test_cluster_generator.py,
test_cluster_watcher.py — with MemoryKV standing in for the per-test
etcd the reference booted.
"""

import time

import pytest

from edl_tpu.cluster import paths
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.status import Status, save_pod_status
from edl_tpu.cluster.train_status import TrainStatus, save_train_status
from edl_tpu.collective import pod_client
from edl_tpu.collective.generator import ClusterGenerator
from edl_tpu.collective.leader import LeaderElector, load_leader_pod
from edl_tpu.collective.pod_server import start_pod_server
from edl_tpu.collective.resource import load_resource_pods, register_pod
from edl_tpu.collective.watcher import ClusterWatcher
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlBarrierError
from tests.test_cluster_model import make_pod

JOB = "job-x"


def wait_for(pred, timeout=10.0, period=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(period)
    return False


def test_resource_registry_ttl(memkv):
    pod = make_pod()
    reg = register_pod(memkv, JOB, pod, ttl=0.6)
    assert wait_for(lambda: pod.pod_id in load_resource_pods(memkv, JOB))
    loaded = load_resource_pods(memkv, JOB)[pod.pod_id]
    assert loaded == pod
    reg.stop_heartbeat_only()
    assert wait_for(lambda: pod.pod_id not in load_resource_pods(memkv, JOB), 5.0)


def test_leader_failover_on_ttl_expiry(memkv):
    pod_a, pod_b = make_pod("10.0.0.1"), make_pod("10.0.0.2")
    rega = register_pod(memkv, JOB, pod_a, ttl=0.6)
    regb = register_pod(memkv, JOB, pod_b, ttl=0.6)
    ea = LeaderElector(memkv, JOB, pod_a.pod_id, ttl=0.6, retry_period=0.1)
    time.sleep(0.05)
    ea.start()
    assert wait_for(lambda: ea.is_leader)
    eb = LeaderElector(memkv, JOB, pod_b.pod_id, ttl=0.6, retry_period=0.1)
    eb.start()
    time.sleep(0.5)
    assert not eb.is_leader
    assert load_leader_pod(memkv, JOB).pod_id == pod_a.pod_id

    # kill A the reference way: stop refreshing, let the lease lapse
    ea._register.stop_heartbeat_only()
    ea.stop()
    assert wait_for(lambda: eb.is_leader, 10.0)
    assert load_leader_pod(memkv, JOB).pod_id == pod_b.pod_id
    eb.stop()
    rega.stop()
    regb.stop()


@pytest.fixture
def three_pods(memkv):
    pods = [make_pod(f"10.0.0.{i}") for i in range(3)]
    regs = [register_pod(memkv, JOB, p, ttl=0.8) for p in pods]
    memkv.put(paths.key(JOB, constants.ETCD_POD_RANK, "0"), pods[0].pod_id.encode())
    yield pods, regs
    for r in regs:
        r.stop()


def test_generator_initial_scale_out_and_loss(memkv, three_pods):
    pods, regs = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=2, max_nodes=3,
                           period=0.1)
    # initial build: leader first, all three members
    c1 = gen.reconcile_once()
    assert c1 is not None and c1.pods[0].pod_id == pods[0].pod_id
    assert len(c1.pods) == 3 and c1.world_size == 6

    # no change -> same stage (idempotent)
    c2 = gen.reconcile_once()
    assert c2.stage == c1.stage

    # pod 2 dies (stop refresh, lease expires) -> rebuild without it
    regs[2].stop_heartbeat_only()
    assert wait_for(lambda: pods[2].pod_id not in load_resource_pods(memkv, JOB), 5.0)
    c3 = gen.reconcile_once()
    assert c3.stage != c1.stage
    assert c3.pod_ids() == [pods[0].pod_id, pods[1].pod_id]
    # surviving ranks renumbered contiguously
    assert [p.rank for p in c3.pods] == [0, 1]
    assert [t.global_rank for p in c3.pods for t in p.trainers] == [0, 1, 2, 3]

    # new pod joins (INITIAL status implied by absence) -> scale out
    pod_new = make_pod("10.0.0.9")
    reg_new = register_pod(memkv, JOB, pod_new, ttl=0.8)
    c4 = gen.reconcile_once()
    assert c4.stage != c3.stage and pod_new.pod_id in c4.pod_ids()
    # survivors keep their relative order
    assert c4.pod_ids()[:2] == c3.pod_ids()
    reg_new.stop()


def test_generator_respects_neartheend_and_max_nodes(memkv, three_pods):
    pods, regs = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=1, max_nodes=2,
                           period=0.1)
    c1 = gen.reconcile_once()
    assert len(c1.pods) == 2  # max_nodes caps the initial build

    # NEARTHEEND: a late joiner must NOT trigger a resize
    save_train_status(memkv, JOB, pods[0].pod_id, TrainStatus.NEARTHEEND)
    pod_new = make_pod("10.0.0.8")
    reg_new = register_pod(memkv, JOB, pod_new, ttl=0.8)
    c2 = gen.reconcile_once()
    assert c2.stage == c1.stage and pod_new.pod_id not in c2.pod_ids()
    reg_new.stop()


def test_generator_below_min_nodes_keeps_old_cluster(memkv, three_pods):
    pods, regs = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=3, max_nodes=3,
                           period=0.1)
    c1 = gen.reconcile_once()
    assert len(c1.pods) == 3
    regs[1].stop_heartbeat_only()
    regs[2].stop_heartbeat_only()
    assert wait_for(lambda: len(load_resource_pods(memkv, JOB)) == 1, 5.0)
    c2 = gen.reconcile_once()  # below min: hold the old cluster, don't shrink
    assert c2.stage == c1.stage and len(c2.pods) == 3


def test_generator_deposed_leader_cannot_write(memkv, three_pods):
    pods, _ = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=1, max_nodes=3)
    gen.reconcile_once()
    # usurper takes the seat
    memkv.put(paths.key(JOB, constants.ETCD_POD_RANK, "0"), b"other-pod")
    from edl_tpu.utils.exceptions import EdlTableError
    save_pod_status(memkv, JOB, pods[1].pod_id, Status.FAILED)  # force a rewrite
    with pytest.raises(EdlTableError):
        gen.reconcile_once()


def test_barrier_protocol(memkv):
    pods = [make_pod("127.0.0.1") for _ in range(3)]
    memkv.put(paths.key(JOB, constants.ETCD_POD_RANK, "0"), pods[0].pod_id.encode())
    cluster = Cluster.from_pods(pods)
    memkv.put(paths.key(JOB, constants.ETCD_CLUSTER, "cluster"),
              cluster.to_json().encode())
    server = start_pod_server(memkv, JOB, pods[0].pod_id)
    pods[0].port = server.port
    # re-advertise leader with live port so load_leader_pod finds the server
    memkv.put(paths.key(JOB, constants.ETCD_POD_RESOURCE, pods[0].pod_id),
              pods[0].to_json().encode())
    try:
        # a lone arrival times out (others missing)
        with pytest.raises(EdlBarrierError, match="barrier timed out"):
            pod_client.barrier(memkv, JOB, pods[0].pod_id, timeout=1.0, period=0.2)

        # all three arrive concurrently -> everyone gets the cluster
        import threading
        results, errors = {}, []

        def arrive(pid):
            try:
                results[pid] = pod_client.barrier(memkv, JOB, pid, timeout=10.0,
                                                  period=0.1)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=arrive, args=(p.pod_id,)) for p in pods]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert all(c.stage == cluster.stage for c in results.values())

        # outsider pod is rejected even when the stage is complete
        with pytest.raises(EdlBarrierError):
            pod_client.barrier(memkv, JOB, "stranger", timeout=1.0, period=0.3)
    finally:
        server.stop()


def test_watcher_detects_stage_change(memkv, three_pods):
    pods, _ = three_pods
    c1 = Cluster.from_pods(pods)
    memkv.put(paths.key(JOB, constants.ETCD_CLUSTER, "cluster"), c1.to_json().encode())
    w = ClusterWatcher(memkv, JOB, c1, period=0.1)
    w.start()
    time.sleep(0.4)
    assert not w.changed
    c2 = Cluster.from_pods(pods[:2])
    memkv.put(paths.key(JOB, constants.ETCD_CLUSTER, "cluster"), c2.to_json().encode())
    assert wait_for(lambda: w.changed, 5.0)
    assert w.latest.stage == c2.stage
    w.stop()
