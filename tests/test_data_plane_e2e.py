"""Data-plane end-to-end: the distributed data service feeding real
elastic training, with a mid-epoch pod kill.

The round-3 integration gate (VERDICT r2 #1): two pods train from the
leader's DataService via ElasticInput; pod B is SIGKILLed mid-epoch;
pod A's trainer is restarted solo by the launcher, resumes THE SAME
epoch from the checkpointed record spans, and finishes the job.  The
sidecar's per-epoch span log must show every record of every epoch
trained exactly once — the no-silent-drops / no-replay guarantee the
reference's WIP data server never achieved.
"""

import json
import os
import re
import subprocess
import sys
import time

import pytest

from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.coord.client import CoordClient
from tests.helpers.harness import kill_tree
from tests.test_launch_integration import FAST, finish

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "examples", "collective", "train_dist_data.py")

N_FILES, PER_FILE = 4, 40  # 160 records/epoch


def write_data(data_dir) -> None:
    os.makedirs(data_dir, exist_ok=True)
    total = N_FILES * PER_FILE
    for f in range(N_FILES):
        with open(os.path.join(data_dir, f"part-{f}.txt"), "w") as fh:
            for r in range(PER_FILE):
                # zero-mean, pseudo-shuffled x so sequential batches keep
                # the (w, b) least-squares problem well conditioned
                g = (f * PER_FILE + r) * 37 % total
                fh.write(f"f{f}r{r} {g / total * 4 - 2:.4f}\n")


def spawn(job_id, coord_ep, tmp, name, ckpt_dir, data_dir, epochs="3"):
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # 1 device/process (drop the 8-dev test mesh)
    env["EDL_TPU_DEMO_STEP_SLEEP"] = "0.2"
    env["EDL_TPU_DEMO_MARKER"] = os.path.join(tmp, f"marker-{name}")
    log = open(os.path.join(tmp, f"launcher-{name}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", job_id, "--coord_endpoints", coord_ep,
         "--nodes_range", "1:2", "--nproc_per_node", "1",
         "--checkpoint_dir", ckpt_dir,
         "--log_dir", os.path.join(tmp, f"log-{name}"), TRAIN,
         "--", "--data_dir", data_dir, "--epochs", epochs,
         "--batch_size", "4", "--save_every_steps", "2",
         "--base_lr", "0.3"],
        env=env, cwd=tmp, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    return proc


def wait_for_log(path, pattern, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            text = open(path, "rb").read().decode(errors="replace")
            if re.search(pattern, text):
                return text
        time.sleep(0.25)
    raise AssertionError(f"{pattern!r} never appeared in {path}")


FULL = {f"{f}": [[0, PER_FILE]] for f in range(N_FILES)}


def assert_exactly_once(spans_by_epoch, epochs):
    from tests.helpers.exactly_once import audit_union
    for e in epochs:
        spans = spans_by_epoch.get(f"spans_e{e}")
        assert spans is not None, f"epoch {e} missing span log"
        # merged disjoint spans covering [0,PER_FILE) per file == every
        # record delivered, no gap (the shared audit helper; these are
        # checkpoint-merged spans, so overlap is asserted by the raw-log
        # audits in test_data_service/test_data_resilience instead)
        audit_union(spans, N_FILES, PER_FILE)


@pytest.mark.slow
def test_mid_epoch_kill_exactly_once(coord_server, tmp_path):
    ep = f"127.0.0.1:{coord_server.port}"
    data_dir = str(tmp_path / "data")
    ckpt = str(tmp_path / "ckpt")
    write_data(data_dir)

    pa = spawn("dd-e2e", ep, str(tmp_path), "a", ckpt, data_dir)
    pb = spawn("dd-e2e", ep, str(tmp_path), "b", ckpt, data_dir)
    # let the 2-pod world train into epoch 1, then kill B mid-epoch
    wait_for_log(str(tmp_path / "launcher-a.log"),
                 r"epoch 1 start", timeout=180)
    time.sleep(1.5)
    kill_tree(pb)
    assert finish(pa, 300) == 0
    try:
        finish(pb, 10)
    except Exception:  # noqa: BLE001 — B was SIGKILLed; exit code is moot
        pass

    client = CoordClient(ep)
    assert load_job_status(client, "dd-e2e") == Status.SUCCEED
    client.close()

    marker = (tmp_path / "marker-a").read_text()
    done = [l for l in marker.splitlines() if l.startswith("done ")]
    assert done, marker
    final = json.loads(done[-1][5:])
    assert final["epochs"] == [0, 1, 2]
    assert_exactly_once(final["spans"], range(3))
    assert final["w_err"] < 0.2 and final["b_err"] < 0.2, final

    la = (tmp_path / "launcher-a.log").read_bytes().decode(errors="replace")
    # the post-kill restart resumed inside an epoch with restored spans
    resumes = re.findall(r"resume_epoch=(\d+) in_epoch=(-?\d+) "
                         r"resumed_spans=(\d+)", la)
    assert len(resumes) >= 2, la[-2000:]
    assert any(int(ie) >= 0 and int(sp) > 0 for _e, ie, sp in resumes[1:]), \
        resumes
