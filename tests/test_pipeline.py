"""Pipeline parallelism (ops/pipeline.py): GPipe schedule over the pp
mesh axis must match the sequential stage composition exactly — forward
and gradients — and train under ElasticTrainer on a dp x pp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.ops.pipeline import pipeline_apply
from edl_tpu.parallel.mesh import MeshSpec, build_mesh

S, D = 4, 16


def stage(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def make_params(rng, s=S):
    return {"w": jnp.asarray(rng.normal(0, 0.3, (s, D, D)), jnp.float32),
            "b": jnp.asarray(rng.normal(0, 0.1, (s, D)), jnp.float32)}


def sequential(params, x, s=S):
    h = x
    for i in range(s):
        h = stage(jax.tree.map(lambda a: a[i], params), h)
    return h


@pytest.mark.parametrize("spec,mb", [
    (MeshSpec(dp=2, pp=4), 4),
    (MeshSpec(dp=4, pp=2), 2),  # 2 layers per pp shard
    (MeshSpec(dp=8, pp=1), 2),  # S==1 fallback: plain scan
])
def test_pipeline_matches_sequential(spec, mb):
    mesh = build_mesh(spec)
    rng = np.random.default_rng(0)
    params = make_params(rng)
    x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)

    out = jax.jit(lambda p, xx: pipeline_apply(
        stage, p, xx, mesh, n_microbatches=mb))(params, x)
    ref = sequential(params, x)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    def loss_pipe(p, xx):
        return (pipeline_apply(stage, p, xx, mesh, n_microbatches=mb) ** 2).sum()

    def loss_ref(p, xx):
        return (sequential(p, xx) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_pipe))(params, x)
    g2 = jax.grad(loss_ref)(params, x)
    gerr = max(float(jnp.max(jnp.abs(a - b)))
               for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)))
    assert gerr < 1e-4


def test_pipeline_trains_under_elastic_trainer():
    """A pipelined regressor actually LEARNS on a dp2 x pp4 mesh: the
    full train step (grads through ppermute, optimizer update, sharded
    stage params) drops the loss by >10x."""
    from edl_tpu.train import ElasticTrainer, TrainConfig

    mesh_spec = MeshSpec(dp=2, pp=4)
    rng = np.random.default_rng(1)
    w_true = rng.normal(size=(D, D)).astype(np.float32) / np.sqrt(D)

    def loss_fn(params, extra, batch, step_rng):
        trainer_mesh = build_mesh(mesh_spec)
        pred = pipeline_apply(stage, params, batch["x"], trainer_mesh,
                              n_microbatches=4)
        loss = ((pred - batch["y"]) ** 2).mean()
        return loss, (extra, {})

    tr = ElasticTrainer(loss_fn, TrainConfig(mesh_spec=mesh_spec, log_every=0))

    def init():
        prng = np.random.default_rng(2)
        return make_params(prng), None

    # stage params sharded over pp via the "stage" logical axis
    logical = {"w": ("stage", None, None), "b": ("stage", None)}
    state = tr.create_state(init, optax.adam(1e-2), param_logical=logical)

    losses = []
    for step in range(120):
        x = rng.normal(size=(16, D)).astype(np.float32)
        y = np.tanh(x @ w_true)
        from edl_tpu.parallel.sharding import shard_host_batch
        gb = shard_host_batch({"x": x, "y": y}, tr.mesh, tr.rules)
        state, metrics = tr.step_fn(state, gb, jax.random.key(step))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] / 5, (losses[0], losses[-1])