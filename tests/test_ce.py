"""Blockwise fused cross-entropy == dense log_softmax CE (value + grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.ce import blockwise_cross_entropy


def _dense_nll(hidden, weight, targets):
    logits = (hidden @ weight).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]


@pytest.mark.parametrize("V,block", [(1000, 256), (512, 512), (300, 1024)])
def test_forward_matches_dense(V, block):
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(17, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, V)), jnp.float32)
    t = jnp.asarray(rng.integers(0, V, (17,)), jnp.int32)
    got = blockwise_cross_entropy(h, w, t, block_size=block)
    np.testing.assert_allclose(got, _dense_nll(h, w, t), rtol=1e-5, atol=1e-5)


def test_grads_match_dense():
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(size=(11, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 700)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 700, (11,)), jnp.int32)

    def fused(h, w):
        return blockwise_cross_entropy(h, w, t, block_size=128).mean()

    def dense(h, w):
        return _dense_nll(h, w, t).mean()

    gh_f, gw_f = jax.grad(fused, argnums=(0, 1))(h, w)
    gh_d, gw_d = jax.grad(dense, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gh_f, gh_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gw_f, gw_d, rtol=1e-5, atol=1e-6)


def test_leading_dims_and_jit():
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(2, 5, 8)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(8, 96)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 96, (2, 5)), jnp.int32)
    got = jax.jit(lambda h, w, t: blockwise_cross_entropy(
        h, w, t, block_size=32))(h, w, t)
    assert got.shape == (2, 5)
    np.testing.assert_allclose(got, _dense_nll(h, w, t), rtol=1e-5, atol=1e-5)


def test_bf16_hidden_runs_close():
    rng = np.random.default_rng(3)
    h32 = jnp.asarray(rng.normal(size=(9, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 256)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 256, (9,)), jnp.int32)
    got = blockwise_cross_entropy(h32.astype(jnp.bfloat16),
                                  w.astype(jnp.bfloat16), t, block_size=64)
    ref = _dense_nll(h32, w, t)
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.15)

    g = jax.grad(lambda h: blockwise_cross_entropy(
        h, w.astype(jnp.bfloat16), t, block_size=64).mean())(
        h32.astype(jnp.bfloat16))
    assert g.dtype == jnp.bfloat16 and np.isfinite(
        np.asarray(g, np.float32)).all()


def test_mismatched_shapes_raise():
    h = jnp.zeros((4, 8))
    w = jnp.zeros((8, 32))
    t = jnp.zeros((5,), jnp.int32)
    with pytest.raises(ValueError):
        blockwise_cross_entropy(h, w, t)


def test_transformer_fused_loss_matches_dense():
    """lm_loss(model logits) == lm_loss_fused(hidden) — values and grads."""
    from edl_tpu.models.transformer import (
        TransformerConfig, TransformerLM, lm_loss, lm_loss_fused,
    )

    cfg = TransformerConfig(vocab_size=97, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=16,
                            dtype=jnp.float32, attention_impl="dense",
                            remat=False)
    model = TransformerLM(cfg)
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, 97, (3, 12)), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]

    def dense(p):
        return lm_loss(model.apply({"params": p}, ids[:, :-1]), ids[:, 1:])

    def fused(p):
        h = model.apply({"params": p}, ids[:, :-1], return_hidden=True)
        return lm_loss_fused(p, h, ids[:, 1:], cfg, block_size=32)

    np.testing.assert_allclose(dense(params), fused(params),
                               rtol=1e-5, atol=1e-6)
    gd = jax.grad(dense)(params)
    gf = jax.grad(fused)(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-5), gd, gf)


def test_transformer_fused_loss_tied_embeddings():
    from edl_tpu.models.transformer import (
        TransformerConfig, TransformerLM, lm_loss, lm_loss_fused,
    )

    cfg = TransformerConfig(vocab_size=64, num_layers=1, embed_dim=16,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dtype=jnp.float32, attention_impl="dense",
                            remat=False, tie_embeddings=True)
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.random.default_rng(6).integers(0, 64, (2, 8)),
                      jnp.int32)
    params = model.init(jax.random.key(1), ids)["params"]

    def dense(p):
        return lm_loss(model.apply({"params": p}, ids[:, :-1]), ids[:, 1:])

    def fused(p):
        h = model.apply({"params": p}, ids[:, :-1], return_hidden=True)
        return lm_loss_fused(p, h, ids[:, 1:], cfg, block_size=16)

    np.testing.assert_allclose(fused(params), dense(params),
                               rtol=1e-5, atol=1e-6)
    # the tied path routes the head grad back into tok_embed — compare
    # the full grad trees, not just values
    gd = jax.grad(dense)(params)
    gf = jax.grad(fused)(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-5), gd, gf)
