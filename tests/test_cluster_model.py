"""Cluster model: serialization round-trips, rank renumbering, the env
ABI, status tables, train state (reference test_pod.py/test_cluster.py/
test_state.py)."""

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.env import JobEnv, TrainerEnv, trainer_env_vars
from edl_tpu.cluster.pod import Pod
from edl_tpu.cluster.state import AdjustRegistry, State
from edl_tpu.cluster.status import Status, load_job_status, load_pods_status, save_job_status, save_pod_status
from edl_tpu.cluster.train_status import TrainStatus, load_train_status, save_train_status


def make_pod(addr="10.0.0.1", nproc=2, devices=(0, 1)):
    pod = Pod(addr=addr, port=9000, device_ids=list(devices))
    pod.make_trainers(nproc, [9100 + i for i in range(nproc)])
    return pod


def test_pod_roundtrip_and_device_split():
    pod = make_pod(nproc=2, devices=(0, 1, 2, 3))
    assert [t.device_ids for t in pod.trainers] == [[0, 1], [2, 3]]
    pod.rank = 3
    pod2 = Pod().from_json(pod.to_json())
    assert pod2 == pod
    assert pod2.rank == 3
    assert pod2.trainers[1].endpoint == pod.trainers[1].endpoint


def test_cluster_global_ranks_and_stage():
    pods = [make_pod(f"10.0.0.{i}") for i in range(3)]
    c = Cluster.from_pods(pods)
    assert [p.rank for p in c.pods] == [0, 1, 2]
    assert [t.global_rank for p in c.pods for t in p.trainers] == list(range(6))
    assert c.world_size == 6
    assert c.leader.pod_id == pods[0].pod_id
    assert len(c.get_trainers_endpoints()) == 6

    c2 = Cluster().from_json(c.to_json())
    assert c2 == c and c2.same_membership(c)

    # membership change ⇒ new stage ⇒ not same_membership
    c3 = Cluster.from_pods(pods[:2])
    assert not c3.same_membership(c)


def test_cluster_store_roundtrip_guarded(memkv):
    c = Cluster.from_pods([make_pod()])
    memkv.put("/edl_tpu/j1/rank/0", b"boss")
    c.save_to_store(memkv, "j1", "boss")
    got = Cluster.load_from_store(memkv, "j1")
    assert got == c
    # non-leader write refused
    import pytest
    from edl_tpu.utils.exceptions import EdlTableError
    with pytest.raises(EdlTableError):
        c.save_to_store(memkv, "j1", "impostor")


def test_trainer_env_abi():
    pods = [make_pod("10.0.0.1"), make_pod("10.0.0.2")]
    cluster = Cluster.from_pods(pods)

    class _A:
        job_id = "j1"
        coord_endpoints = "h:2379"

    env = trainer_env_vars(JobEnv(_A()), pods[1], pods[1].trainers[1], cluster)
    te = TrainerEnv(env)
    assert te.job_id == "j1"
    assert te.global_rank == 3 and te.rank_in_pod == 1
    assert te.world_size == 4 and len(te.trainer_endpoints) == 4
    assert te.coordinator == cluster.get_trainers_endpoints()[0]
    assert te.endpoint == pods[1].trainers[1].endpoint
    assert te.pod_rank == 1 and te.cluster_stage == cluster.stage
    assert te.is_distributed


def test_status_tables(memkv):
    save_pod_status(memkv, "j", "p0", Status.RUNNING)
    save_pod_status(memkv, "j", "p1", Status.FAILED)
    assert load_pods_status(memkv, "j") == {"p0": Status.RUNNING, "p1": Status.FAILED}
    save_job_status(memkv, "j", Status.SUCCEED)
    assert load_job_status(memkv, "j") == Status.SUCCEED
    save_train_status(memkv, "j", "p0", TrainStatus.NEARTHEEND)
    assert load_train_status(memkv, "j", "p0") == TrainStatus.NEARTHEEND
    # reference defect fixed: NEARTHEEND and SUCCEED are distinct
    assert TrainStatus.NEARTHEEND != TrainStatus.SUCCEED


def test_state_epochs_data_checkpoint_and_adjust(memkv):
    s = State(total_batch_size=1024, user_defined={"lr": 0.1})
    s.record_epoch(0, world_size=8, step_num=100, avg_step_time=0.5)
    s.record_epoch(1, world_size=6, step_num=120, avg_step_time=0.6)
    s.data_checkpoint.reader_name = "imagenet"
    s.data_checkpoint.file_list = ["a.rec", "b.rec"]
    s.data_checkpoint.mark_processed(0, 0, 100)
    s.data_checkpoint.mark_processed(0, 100, 200)  # merges -> [0,200)
    s.data_checkpoint.mark_processed(1, 50, 60)

    s.save_to_store(memkv, "j", "imagenet")
    s2 = State.load_from_store(memkv, "j", "imagenet")
    assert s2 == s
    assert s2.next_epoch == 2
    assert len(s2.data_checkpoint.processed) == 2
    assert s2.data_checkpoint.is_processed(0, 150)
    assert not s2.data_checkpoint.is_processed(0, 200)
    assert s2.epoch_attr(1).world_size == 6

    adj = AdjustRegistry()
    calls = []
    adj.register(lambda old, new, st: calls.append((old, new)))
    adj.run(8, 8, s2)
    assert calls == []
    adj.run(8, 6, s2)
    assert calls == [(8, 6)]
