"""Utils layer: serialization round-trips, typed-error wire contract, retry."""

import time

import pytest

from edl_tpu.utils import exceptions
from edl_tpu.utils.retry import retry_until_timeout
from edl_tpu.utils.serialization import JsonSerializable, register_serializable


@register_serializable
class _Inner(JsonSerializable):
    def __init__(self, x=0, tags=None):
        self.x = x
        self.tags = tags or []


@register_serializable
class _Outer(JsonSerializable):
    def __init__(self):
        self.name = "outer"
        self.items = [_Inner(1, ["a"]), _Inner(2)]
        self.child = _Inner(3)
        self.meta = {"k": 1}


def test_nested_roundtrip():
    o = _Outer()
    o2 = _Outer().from_json(o.to_json())
    assert o == o2
    assert isinstance(o2.items[0], _Inner)
    assert o2.items[0].x == 1 and o2.child.x == 3
    o2.child.x = 99
    assert o != o2


def test_exception_wire_roundtrip():
    status = exceptions.serialize(exceptions.EdlBarrierError("not yet"))
    with pytest.raises(exceptions.EdlBarrierError, match="not yet"):
        exceptions.deserialize(status)
    # unknown/untyped exceptions arrive as EdlInternalError with traceback
    status = exceptions.serialize(ValueError("boom"))
    with pytest.raises(exceptions.EdlInternalError, match="boom"):
        exceptions.deserialize(status)
    assert exceptions.deserialize(None) is None


def test_retry_until_timeout_succeeds_then_gives_up():
    calls = {"n": 0}

    @retry_until_timeout(interval=0.01)
    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise exceptions.EdlBarrierError("wait")
        return "ok"

    assert flaky(timeout=5.0) == "ok"
    assert calls["n"] == 3

    @retry_until_timeout(interval=0.01)
    def always_fails():
        raise exceptions.EdlBarrierError("never")

    t0 = time.monotonic()
    with pytest.raises(exceptions.EdlBarrierError):
        always_fails(timeout=0.1)
    assert time.monotonic() - t0 < 2.0

    @retry_until_timeout(interval=0.01)
    def hard_error():
        calls["n"] += 1
        raise ValueError("no retry")

    calls["n"] = 0
    with pytest.raises(ValueError):
        hard_error(timeout=1.0)
    assert calls["n"] == 1


def test_logger_configure_file_handler_idempotent(tmp_path):
    """Repeated configure(log_dir=...) must not stack duplicate file
    handlers (every line would log N times); a DIFFERENT file is a new
    handler."""
    import logging

    from edl_tpu.utils.logger import configure

    root = logging.getLogger("edl_tpu")
    before = list(root.handlers)
    try:
        configure(log_dir=str(tmp_path), filename="a.log")
        configure(log_dir=str(tmp_path), filename="a.log")
        configure(log_dir=str(tmp_path), filename="a.log")
        added = [h for h in root.handlers if h not in before]
        files = [h for h in added if isinstance(h, logging.FileHandler)]
        assert len(files) == 1
        configure(log_dir=str(tmp_path), filename="b.log")
        added = [h for h in root.handlers if h not in before]
        files = [h for h in added if isinstance(h, logging.FileHandler)]
        assert len(files) == 2
    finally:
        for h in list(root.handlers):
            if h not in before:
                root.removeHandler(h)
                h.close()
