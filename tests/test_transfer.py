"""Streaming data plane (rpc/client pool, rpc/transfer, streaming
serve_fetch): pipelined window equivalence, raw streamed frames, strict
sequence validation (gap / duplicate / dropped frame), striped
multi-holder fetch with mid-transfer demotion, and the cache-first
restore completing when a holder dies mid-stripe."""

import functools
import socket
import threading
import time
import zlib

import numpy as np
import pytest

from edl_tpu.rpc import chunks, framing, transfer
from edl_tpu.rpc.client import RpcChannelPool, RpcClient
from edl_tpu.rpc.server import RpcServer, Streaming
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import (
    EdlCoordError, EdlInternalError, EdlStreamError,
)

_RNG = np.random.default_rng(7)


# -- crc32_combine ------------------------------------------------------------
def test_crc32_combine_matches_zlib():
    data = _RNG.bytes(1 << 18)
    for cut in (0, 1, 100, 1 << 17, len(data) - 1, len(data)):
        a, b = data[:cut], data[cut:]
        assert transfer.crc32_combine(
            zlib.crc32(a), zlib.crc32(b), len(b)) == zlib.crc32(data)


def test_split_ranges_cover_and_align():
    for nbytes, n, cb in ((100, 3, 7), (1, 4, 64), (1 << 20, 2, 1 << 16),
                          (5, 8, 2)):
        ranges = transfer._split_ranges(nbytes, n, cb)
        pos = 0
        for off, ln in ranges:
            assert off == pos and ln > 0
            assert off % cb == 0
            pos += ln
        assert pos == nbytes


# -- server/pool fixtures -----------------------------------------------------
@pytest.fixture
def blob_server():
    """An RpcServer exposing chunk fetch (legacy + streaming) and a
    seq-validated push over a mutable blob store."""
    data = _RNG.bytes(3 * (1 << 20) + 123)
    staged = {}

    def fetch(offset, length):
        return data[offset:offset + length]

    def fetch_stream(offset=0, length=-1, chunk_bytes=0):
        cb = chunk_bytes or (1 << 18)
        end = len(data) if length < 0 else min(len(data), offset + length)

        def gen():
            for pos in range(offset, end, cb):
                yield memoryview(data)[pos:min(end, pos + cb)]
        return Streaming(gen())

    def push(key, seq, data, eof):
        st = staged.setdefault(key, {"buf": bytearray(), "seq": 0})
        if seq != st["seq"]:
            raise EdlInternalError(f"seq {seq} != {st['seq']}")
        st["buf"].extend(data)
        st["seq"] += 1
        st["eof"] = bool(eof)

    srv = RpcServer("127.0.0.1", 0)
    srv.register("fetch", fetch)
    srv.register("fetch_stream", fetch_stream)
    srv.register("push", push)
    srv.start()
    srv.blob = data  # type: ignore[attr-defined]
    srv.staged = staged  # type: ignore[attr-defined]
    yield srv
    srv.stop()


# -- pipelined / streaming equivalence ---------------------------------------
def test_pipelined_window1_equals_legacy_serial(blob_server):
    data = blob_server.blob
    with RpcClient(f"127.0.0.1:{blob_server.port}") as c:
        legacy = chunks.fetch_bytes(
            functools.partial(c.call, "fetch"), len(data),
            chunk_bytes=1 << 18)
    with RpcChannelPool(f"127.0.0.1:{blob_server.port}", size=1) as pool:
        w1 = chunks.fetch_bytes_pipelined(pool, "fetch", len(data),
                                          chunk_bytes=1 << 18, window=1)
        w8 = chunks.fetch_bytes_pipelined(pool, "fetch", len(data),
                                          chunk_bytes=1 << 18, window=8)
    assert legacy == data and w1 == legacy and w8 == legacy


def test_streaming_fetch_roundtrip_raw_frames(blob_server):
    data = blob_server.blob
    with RpcChannelPool(f"127.0.0.1:{blob_server.port}") as pool:
        got = b"".join(chunks.iter_fetch_streaming(
            pool, "fetch_stream", len(data), chunk_bytes=1 << 18))
        assert got == data
        # offset/length sub-range too (what a stripe asks for)
        sub = b"".join(chunks.iter_fetch_streaming(
            pool, "fetch_stream", 1 << 20, offset=12345,
            chunk_bytes=1 << 18))
        assert sub == data[12345:12345 + (1 << 20)]


def test_push_pipelined_ordered_and_windowed(blob_server):
    payload = _RNG.bytes((1 << 20) + 17)
    with RpcChannelPool(f"127.0.0.1:{blob_server.port}", size=2) as pool:
        n = chunks.push_bytes_pipelined(pool, "push", payload,
                                        chunk_bytes=1 << 16, window=6,
                                        key="k")
    assert n == -(-len(payload) // (1 << 16))
    st = blob_server.staged["k"]
    assert bytes(st["buf"]) == payload and st["eof"]


def test_pipelined_typed_error_leaves_connection_usable(blob_server):
    with RpcChannelPool(f"127.0.0.1:{blob_server.port}", size=1) as pool:
        with pytest.raises(EdlInternalError):
            # second chunk violates seq -> typed error mid-batch
            pool.call_pipelined("push", [
                {"key": "x", "seq": 0, "data": b"a", "eof": False},
                {"key": "x", "seq": 5, "data": b"b", "eof": True},
                {"key": "y", "seq": 0, "data": b"c", "eof": True},
            ], window=3)
        # frames after the error were drained; the channel still works
        assert pool.call("fetch", offset=0, length=4) == blob_server.blob[:4]
    # inc/dec paired even through the error path: nothing left in flight
    from edl_tpu.obs import metrics as obs_metrics
    assert obs_metrics.REGISTRY.get("edl_transfer_inflight_window").value == 0


# -- fault injection: crafted streams ----------------------------------------
def _crafted_stream_server(frames):
    """A raw socket server speaking just enough EDL1 to answer one
    request with pre-crafted frames (the protocol-violation injector a
    real server can't be talked into being)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def run():
        c, _ = srv.accept()
        try:
            framing.recv_frame(c)  # the request
            for f in frames:
                framing.send_frame(c, f)
            time.sleep(0.2)  # let the client parse before RST
        finally:
            c.close()
    threading.Thread(target=run, daemon=True).start()
    return srv


@pytest.mark.parametrize("frames,match", [
    # sequence gap: frame 1 lost somewhere
    ([{"s": None, "r": b"aa", "q": 0},
      {"s": None, "r": b"cc", "q": 2}], "gap"),
    # duplicated frame
    ([{"s": None, "r": b"aa", "q": 0},
      {"s": None, "r": b"aa", "q": 0}], "duplicate"),
    # a non-streaming answer where frames were expected
    ([{"s": None, "r": b"aa"}], "single frame"),
])
def test_stream_sequence_violations_raise_typed(frames, match):
    srv = _crafted_stream_server(frames)
    try:
        with RpcChannelPool(
                f"127.0.0.1:{srv.getsockname()[1]}", size=1) as pool:
            with pytest.raises(EdlStreamError, match=match):
                list(pool.call_streaming("m"))
    finally:
        srv.close()


def test_stream_dropped_frame_surfaces_as_short_stream():
    # server "finishes" (eof) having sent fewer bytes than the manifest
    # says: the length check, not silence, must fire
    srv = _crafted_stream_server([
        {"s": None, "r": b"x" * 10, "q": 0},
        {"s": None, "r": None, "q": 1, "eof": True},
    ])
    try:
        with RpcChannelPool(
                f"127.0.0.1:{srv.getsockname()[1]}", size=1) as pool:
            with pytest.raises(EdlStreamError, match="short"):
                list(chunks.iter_fetch_streaming(pool, "m", 64))
    finally:
        srv.close()


def test_streaming_handler_error_midway_is_typed(blob_server):
    def half_then_fail(n):
        def gen():
            yield b"z" * n
            raise EdlInternalError("holder evicted the set")
        return Streaming(gen())
    blob_server.register("flaky", half_then_fail)
    with RpcChannelPool(f"127.0.0.1:{blob_server.port}", size=1) as pool:
        got = []
        with pytest.raises(EdlInternalError, match="evicted"):
            for c in pool.call_streaming("flaky", n=7):
                got.append(c)
        assert len(got) == 1  # the good frame arrived before the error


# -- striped fetch + demotion -------------------------------------------------
def _mem_iter(data):
    def make(holder, off, ln, cb=1 << 16):
        def gen():
            for p in range(off, off + ln, cb):
                yield data[p:min(off + ln, p + cb)]
        return gen()
    return make


def test_striped_fetch_roundtrip():
    data = _RNG.bytes((1 << 21) + 999)
    buf, crc = transfer.fetch_striped(
        len(data), ["h1", "h2", "h3"],
        lambda h, off, ln: _mem_iter(data)(h, off, ln),
        chunk_bytes=1 << 16)
    assert bytes(buf) == data and crc == zlib.crc32(data)


def test_striped_holder_death_demotes_to_survivor():
    data = _RNG.bytes(1 << 21)
    served = []

    def make(holder, off, ln):
        def gen():
            if holder == "bad":
                yield data[off:off + 1024]
                raise ConnectionError("holder killed mid-stripe")
            served.append((off, ln))
            yield from _mem_iter(data)(holder, off, ln)
        return gen()

    buf, crc = transfer.fetch_striped(len(data), ["bad", "good"], make,
                                      chunk_bytes=1 << 16)
    assert bytes(buf) == data and crc == zlib.crc32(data)
    # the survivor served its own range AND the dead holder's remainder
    assert len(served) >= 2


def test_striped_every_holder_dead_raises():
    def make(holder, off, ln):
        def gen():
            raise ConnectionError(f"{holder} down")
            yield  # noqa — generator marker
        return gen()
    with pytest.raises(ConnectionError):
        transfer.fetch_striped(1 << 20, ["a", "b"], make,
                               chunk_bytes=1 << 16)


# -- fetch_bytes diagnostics (the unsafe-len fix) -----------------------------
def test_fetch_bytes_bad_result_diagnostic_is_safe():
    with pytest.raises(ConnectionError, match=r"cache_fetch w@pod.*dict"):
        chunks.fetch_bytes(lambda offset, length: {"oops": 1}, 10,
                           chunk_bytes=4, label="cache_fetch w@pod")
    with pytest.raises(ConnectionError, match="NoneType"):
        chunks.fetch_bytes(lambda offset, length: None, 10, chunk_bytes=4)
    with pytest.raises(ConnectionError, match="3 bytes"):
        chunks.fetch_bytes(lambda offset, length: b"abc", 10, chunk_bytes=4)


# -- restore completes when a holder dies mid-stripe --------------------------
def test_restore_survives_holder_killed_mid_stripe(memkv, monkeypatch):
    import jax

    from edl_tpu import memstate
    from edl_tpu.memstate import restore as ms_restore
    from edl_tpu.memstate.service import StateCacheService

    # small knobs so a 4 MB shard stripes across both holders
    monkeypatch.setattr(constants, "STRIPE_MIN_BYTES", 1 << 20)
    monkeypatch.setattr(constants, "MEMSTATE_CHUNK_BYTES", 1 << 18)

    arr = np.arange(1 << 20, dtype=np.float32)  # 4 MB
    data = arr.tobytes()
    key = "['w']@0:%d" % len(arr)
    ent = {"crc": zlib.crc32(data), "nbytes": len(data), "dtype": "float32",
           "shape": [len(arr)], "index": [[0, len(arr)]],
           "gshape": [len(arr)], "leaf": "['w']"}

    servers, regs = [], []
    try:
        for pid in ("pod-a", "pod-b"):
            svc = StateCacheService(memkv, "job", pid)
            svc.cache_put_chunk("pod-a", 3, key, 0, data, True)
            svc.cache_commit("pod-a", 3, manifest={key: ent}, meta=b"{}")
            srv = RpcServer("127.0.0.1", 0)
            srv.register_instance(svc)
            if pid == "pod-a":
                # pod-a dies one chunk into ANY streamed range
                orig = svc.cache_fetch_stream

                def flaky(owner, key, offset=0, length=-1, chunk_bytes=0,
                          _orig=orig):
                    inner = _orig(owner, key, offset=offset, length=length,
                                  chunk_bytes=chunk_bytes).it

                    def gen():
                        yield next(inner)
                        raise ConnectionError("holder killed mid-stripe")
                    return Streaming(gen())
                srv.register("cache_fetch_stream", flaky)
            srv.start()
            servers.append(srv)
            regs.append(memstate.advertise(memkv, "job", pid,
                                           f"127.0.0.1:{srv.port}", ttl=30))
        memstate.write_committed_step(memkv, "job", 3)

        rep = jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])
        abstract = {"w": jax.ShapeDtypeStruct((len(arr),), np.float32,
                                              sharding=rep)}
        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=3)
        assert res is not None, "restore must complete from the survivor"
        got, meta_json, info = res
        assert np.array_equal(np.asarray(got["w"]), arr)
        assert meta_json == "{}"
        assert "pod-b" in info["peers"]
    finally:
        for r in regs:
            r.stop()
        for s in servers:
            s.stop()


def test_restore_from_old_peer_without_streaming(memkv):
    """Fallback matrix: a peer that predates ``cache_fetch_stream``
    (only the one-chunk-per-call surface) still serves a restore via
    the pipelined legacy path."""
    import jax

    from edl_tpu import memstate
    from edl_tpu.memstate import restore as ms_restore
    from edl_tpu.memstate.service import StateCacheService

    arr = np.linspace(0, 1, 4096).astype(np.float32)
    data = arr.tobytes()
    key = "['w']@0:%d" % len(arr)
    ent = {"crc": zlib.crc32(data), "nbytes": len(data), "dtype": "float32",
           "shape": [len(arr)], "index": [[0, len(arr)]],
           "gshape": [len(arr)], "leaf": "['w']"}
    svc = StateCacheService(memkv, "job", "old-pod")
    svc.cache_put_chunk("old-pod", 9, key, 0, data, True)
    svc.cache_commit("old-pod", 9, manifest={key: ent}, meta=b"{}")
    srv = RpcServer("127.0.0.1", 0)
    # an OLD peer: expose everything EXCEPT the streaming method
    for name in ("cache_manifest", "cache_fetch", "cache_meta"):
        srv.register(name, getattr(svc, name))
    srv.start()
    reg = memstate.advertise(memkv, "job", "old-pod",
                             f"127.0.0.1:{srv.port}", ttl=30)
    try:
        memstate.write_committed_step(memkv, "job", 9)
        rep = jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])
        abstract = {"w": jax.ShapeDtypeStruct((len(arr),), np.float32,
                                              sharding=rep)}
        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=9)
        assert res is not None
        assert np.array_equal(np.asarray(res[0]["w"]), arr)
    finally:
        reg.stop()
        srv.stop()


# -- bench backend-init fallback (BENCH_r05 regression) -----------------------
def test_bench_devices_falls_back_to_cpu_on_backend_init_error(monkeypatch):
    """The subprocess probe catches HANGS; an in-process ``RuntimeError:
    Unable to initialize backend`` (BENCH_r05, rc=1, no artifact) must
    pin the CPU platform and retry instead of killing the artifact."""
    import jax

    from edl_tpu import bench

    real_cpu = jax.devices("cpu")
    calls = []

    def fake_devices():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError(
                "Unable to initialize backend 'axon': UNAVAILABLE")
        return real_cpu

    updates = []
    monkeypatch.setattr(jax, "devices", fake_devices)
    monkeypatch.setattr(jax.config, "update",
                        lambda k, v: updates.append((k, v)))
    assert bench._devices_or_cpu() == real_cpu
    assert len(calls) == 2
    assert ("jax_platforms", "cpu") in updates


# -- the connect-outside-the-lock regression ----------------------------------
def test_dead_endpoint_does_not_serialize_concurrent_callers(monkeypatch):
    """PR-2 bug: RpcClient.call held the client lock across _connect,
    so one dead endpoint cost N callers N × the connect timeout, in
    series.  Connects now happen outside the lock: N callers fail in
    ~one timeout, in parallel."""
    from edl_tpu.rpc import client as client_mod

    delay = 0.4

    def slow_connect(endpoint, timeout):
        time.sleep(delay)
        raise OSError("connect timed out")

    monkeypatch.setattr(client_mod, "_connect", slow_connect)
    c = RpcClient("198.51.100.1:9", timeout=1.0)
    outcomes = []

    def worker():
        try:
            c.call("ping")
        except EdlCoordError:
            outcomes.append("coord")
        except Exception as e:  # noqa: BLE001
            outcomes.append(repr(e))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    assert outcomes == ["coord"] * 4
    # each caller: 2 attempts x 0.4 s, all callers in PARALLEL.  The
    # serialized behavior would take >= 4 x 0.8 = 3.2 s; allow slack
    assert wall < 2.4, f"dead-endpoint connects serialized: {wall:.2f}s"
