"""Elastic goodput accounting (obs/goodput.py, ISSUE 13): wall-clock
classification across every resize shape, and the ledger's live
idle/productive split."""

from edl_tpu.obs import goodput as gp
from edl_tpu.obs.goodput import GoodputLedger, classify_records


def _stop_resume(stage="s1", detect=100.0):
    # a summarize_recovery entry for a full stop-resume resize
    return {"stage": stage, "resize_mode": "stop_resume",
            "detect_at": detect,
            "detect_to_kill": 1.0, "kill_to_barrier": 2.0,
            "barrier_to_spawn": 3.0,
            "spawn_to_restored": 4.0, "restored_to_first_step": 5.0,
            "total": 15.0}


def _delta_fallback(stage="s2", detect=200.0):
    # a delta attempt that fell back: BOTH flagged and killed phases
    # present (the delta attempt sits inside detect_to_kill), plus the
    # trainer half from the eventual stop-resume
    return {"stage": stage, "resize_mode": "stop_resume",
            "detect_at": detect,
            "detect_to_flag": 0.5, "flag_to_barrier": 1.5,
            "detect_to_kill": 4.0, "kill_to_barrier": 1.0,
            "barrier_to_spawn": 2.0,
            "spawn_to_restored": 1.0, "restored_to_first_step": 2.0,
            "total": 10.0}


def _hang(stage="s1+hang1700000000", detect=300.0):
    return {"stage": stage, "resize_mode": "stop_resume",
            "detect_at": detect,
            "detect_to_kill": 0.5, "kill_to_barrier": 0.5,
            "barrier_to_spawn": 1.0,
            "spawn_to_restored": 1.0, "restored_to_first_step": 1.0,
            "total": 4.0}


def _delta(stage="s3", detect=400.0):
    return {"stage": stage, "resize_mode": "delta", "detect_at": detect,
            "detect_to_flag": 0.2, "flag_to_barrier": 0.8,
            "barrier_to_reshard": 1.5,
            "spawn_to_restored": 0.5, "restored_to_first_step": 1.0,
            "total": 4.0}


def test_stop_resume_split():
    out = classify_records([_stop_resume()])
    # restore = spawn_to_restored + restored_to_first_step = 9; the
    # launcher-side remainder of the 15s total is resize
    assert out["restore"] == 9.0
    assert out["resize"] == 6.0
    assert out["hang"] == 0.0 and out["idle"] == 0.0


def test_delta_and_fallback_split():
    out = classify_records([_delta(), _delta_fallback()])
    # delta: total 4 = 1.5 restore + 2.5 resize; fallback: total 10 =
    # 3 restore + 7 resize (the failed delta attempt is resize badput)
    assert out["restore"] == 1.5 + 3.0
    assert out["resize"] == 2.5 + 7.0


def test_hang_record_is_all_hang():
    out = classify_records([_hang()])
    assert out["hang"] == 4.0
    assert out["resize"] == 0.0 and out["restore"] == 0.0


def test_launcher_half_only_counts_as_resize():
    rec = {"stage": "s9", "detect_at": 100.0,
           "detect_to_kill": 1.0, "kill_to_barrier": 2.0}
    out = classify_records([rec])
    assert out["resize"] == 3.0
    assert out["restore"] == 0.0


def test_launcher_half_fallback_record_is_not_double_counted():
    # a delta FALLBACK record carries phases of BOTH chains over the
    # SAME wall-clock (the delta attempt sits inside detect_to_kill):
    # the span is the LONGER chain, never the sum of both
    rec = {"stage": "sf", "detect_at": 100.0,
           "detect_to_flag": 0.5, "flag_to_barrier": 1.0,      # delta: 1.5
           "detect_to_kill": 4.0, "kill_to_barrier": 1.0,
           "barrier_to_spawn": 2.0}                            # resume: 7.0
    out = classify_records([rec])
    assert out["resize"] == 7.0


def test_negative_durations_clamped():
    # the PR-11 edge: fallback phase arithmetic can go negative in raw
    # records; classification must clamp, never emit negative badput
    rec = {"stage": "s8", "detect_at": 100.0,
           "spawn_to_restored": -2.0, "restored_to_first_step": 1.0,
           "total": 0.5}
    out = classify_records([rec])
    assert out["restore"] == 0.5          # capped by the record's total
    assert out["resize"] == 0.0
    assert all(v >= 0.0 for v in out.values())


def test_restore_never_exceeds_total():
    rec = {"stage": "s7", "detect_at": 0.0, "spawn_to_restored": 50.0,
           "restored_to_first_step": 50.0, "total": 10.0}
    out = classify_records([rec])
    assert out["restore"] == 10.0 and out["resize"] == 0.0


def _counter(reason):
    return gp.BADPUT_SECONDS.labels(reason=reason).value


def test_ledger_idle_and_productive_split():
    led = GoodputLedger(emit_trace=False)
    base = {r: _counter(r) for r in gp.BADPUT_REASONS}
    led.update(1000.0, [], trainers_live=True)      # window opens
    s = led.update(1010.0, [], trainers_live=True)
    assert s["ratio"] == 1.0 and s["productive_s"] == 10.0
    # 5s with no live trainers and no recovery window -> idle
    s = led.update(1015.0, [], trainers_live=False)
    assert s["badput"]["idle"] == 5.0
    assert s["productive_s"] == 10.0
    assert abs(s["ratio"] - 10.0 / 15.0) < 1e-4  # summary rounds to 4dp
    assert _counter("idle") - base["idle"] == 5.0


def test_ledger_records_move_only_their_reason():
    led = GoodputLedger(emit_trace=False)
    base = {r: _counter(r) for r in gp.BADPUT_REASONS}
    led.update(1000.0, [], trainers_live=True)
    # a resize record lands (launcher half only -> pure resize badput):
    # ONLY reason="resize" may move
    rec = {"stage": "sx", "detect_at": 1001.0, "detect_to_kill": 2.0}
    s = led.update(1010.0, [rec], trainers_live=True)
    assert _counter("resize") - base["resize"] == 2.0
    for other in ("restore", "hang", "idle"):
        assert _counter(other) - base[other] == 0.0
    assert s["badput"]["resize"] == 2.0
    # records are monotone: a second update with the same set moves nothing
    led.update(1020.0, [rec], trainers_live=True)
    assert _counter("resize") - base["resize"] == 2.0


def test_ledger_no_idle_during_recovery_window():
    led = GoodputLedger(emit_trace=False)
    base_idle = _counter("idle")
    led.update(1000.0, [], trainers_live=True)
    # trainers dead AT a covering resize record's instant: that time is
    # the resize's, not idle's — no double count
    rec = {"stage": "sy", "detect_at": 999.0, "detect_to_kill": 30.0}
    led.update(1005.0, [rec], trainers_live=False)
    assert _counter("idle") - base_idle == 0.0


def test_classify_records_window_clipping():
    rec = {"stage": "sw", "detect_at": 100.0, "detect_to_kill": 10.0}
    # fully inside / fully before / straddling the window
    assert classify_records([rec], since=90.0, until=200.0)["resize"] == 10.0
    assert classify_records([rec], since=120.0, until=200.0)["resize"] == 0.0
    half = classify_records([rec], since=105.0, until=200.0)["resize"]
    assert abs(half - 5.0) < 1e-9
    # monotone in a growing `until`
    early = classify_records([rec], since=90.0, until=104.0)["resize"]
    later = classify_records([rec], since=90.0, until=108.0)["resize"]
    assert early < later <= 10.0


def test_ledger_prewindow_records_are_not_observed_badput():
    # the aggregator-restart scenario: a job with 400s of historical
    # resize badput must not zero a fresh ledger's ratio
    led = GoodputLedger(emit_trace=False)
    base = _counter("resize")
    old = {"stage": "old", "detect_at": 0.0, "detect_to_kill": 400.0}
    led.update(1000.0, [old], trainers_live=True)
    s = led.update(1300.0, [old], trainers_live=True)
    assert _counter("resize") - base == 0.0
    assert s["ratio"] == 1.0 and s["productive_s"] == 300.0


def test_ledger_store_blip_keeps_baseline():
    # a failed record read (resizes=None) must not reset the baseline:
    # the next successful read would otherwise re-add all prior badput
    led = GoodputLedger(emit_trace=False)
    base = _counter("resize")
    led.update(1000.0, [], trainers_live=True)
    rec = {"stage": "sb", "detect_at": 1001.0, "detect_to_kill": 2.0}
    led.update(1010.0, [rec], trainers_live=True)
    assert _counter("resize") - base == 2.0
    led.update(1020.0, None, trainers_live=True)       # blip
    led.update(1030.0, [rec], trainers_live=True)      # store recovers
    assert _counter("resize") - base == 2.0            # NOT 4.0


def test_ledger_idle_then_record_does_not_double_count():
    # a recovery longer than the advert TTL: trainers vanish, idle
    # accrues, THEN the record lands covering the same wall-clock —
    # that time must stay idle, not be re-counted as resize
    led = GoodputLedger(emit_trace=False)
    base = {r: _counter(r) for r in gp.BADPUT_REASONS}
    led.update(1000.0, [], trainers_live=True)
    led.update(1010.0, [], trainers_live=False)   # idle span [1000,1010]
    assert _counter("idle") - base["idle"] == 10.0
    rec = {"stage": "sd", "detect_at": 1002.0, "detect_to_kill": 6.0}
    led.update(1020.0, [rec], trainers_live=True)
    # the record's [1002,1008] span is fully inside the idle span
    assert _counter("resize") - base["resize"] == 0.0
    assert _counter("idle") - base["idle"] == 10.0


def test_ledger_partial_idle_overlap_attributes_remainder():
    led = GoodputLedger(emit_trace=False)
    base = {r: _counter(r) for r in gp.BADPUT_REASONS}
    led.update(1000.0, [], trainers_live=True)
    led.update(1010.0, [], trainers_live=False)   # idle span [1000,1010]
    # record spans [1005,1015]: 5s already idle, 5s genuinely new
    rec = {"stage": "sp", "detect_at": 1005.0, "detect_to_kill": 10.0}
    led.update(1020.0, [rec], trainers_live=True)
    assert _counter("resize") - base["resize"] == 5.0


def test_ledger_idle_starts_after_a_record_tail():
    # a recovery ends mid-scrape-interval while trainers stay dead:
    # the tail the record already claimed must not also accrue as idle
    led = GoodputLedger(emit_trace=False)
    base = {r: _counter(r) for r in gp.BADPUT_REASONS}
    led.update(1000.0, [], trainers_live=True)
    rec = {"stage": "st", "detect_at": 1001.0, "detect_to_kill": 6.0}
    led.update(1005.0, [rec], trainers_live=True)      # partial: 4s resize
    # next scrape past the record's end (1007) + grace, trainers dead:
    # idle covers only [1007, 1012], not the record's [1005, 1007] tail
    led.update(1012.0, [rec], trainers_live=False)
    assert _counter("idle") - base["idle"] == 5.0
    # the record completes its 6s of resize; total badput == wall-clock
    # of the bad period, attributed exactly once
    led.update(1020.0, [rec], trainers_live=True)
    assert _counter("resize") - base["resize"] == 6.0


def test_ledger_serving_only_job_never_accrues_idle():
    # a gateway+replica fleet with no trainer component ever: ratio
    # must stay 1.0 (the goodput-regression rule must not latch on a
    # healthy serving job)
    led = GoodputLedger(emit_trace=False)
    base = _counter("idle")
    led.update(1000.0, [], trainers_live=False)
    s = led.update(1100.0, [], trainers_live=False)
    assert _counter("idle") - base == 0.0
    assert s["ratio"] == 1.0


def test_ledger_ratio_gauge_and_badput_capped_by_observation():
    led = GoodputLedger(emit_trace=False)
    led.update(1000.0, [], trainers_live=True)
    # a record whose span predates the window entirely: badput must not
    # exceed observed wall-clock (ratio floors at 0, never negative)
    rec = {"stage": "sz", "detect_at": 0.0, "detect_to_kill": 1e6}
    s = led.update(1001.0, [rec], trainers_live=True)
    assert 0.0 <= s["ratio"] <= 1.0
    assert gp.GOODPUT_RATIO_G.value == s["ratio"]
