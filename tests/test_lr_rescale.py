"""World-derived LR re-scaling (EDL_TPU_LR_RESCALE, edl_tpu/train/lr):
the trailing world_scaled transform multiplies the FINAL update, its
scalar lives in the optimizer state (rides checkpoints and deltas),
and rescale_state applies new_world/old_world on grow AND shrink,
compounding across repeated resizes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.train import lr as lr_mod


def _setup(lr=0.1):
    params = {"w": jnp.ones((4,), jnp.float32)}
    tx = lr_mod.world_scaled(optax.sgd(lr))
    return params, tx, tx.init(params)


def _step_delta(params, tx, opt_state):
    grads = {"w": jnp.ones((4,), jnp.float32)}
    updates, opt_state = tx.update(grads, opt_state, params)
    return float(np.asarray(updates["w"][0])), opt_state


def test_world_scaled_identity_before_any_resize():
    params, tx, opt_state = _setup(lr=0.1)
    d, _ = _step_delta(params, tx, opt_state)
    assert np.isclose(d, -0.1), "wrapper must not perturb the base update"


def test_rescale_grow_scales_update_linearly():
    params, tx, opt_state = _setup(lr=0.1)
    grown = lr_mod.rescale_state(opt_state, 8 / 4)  # 4 -> 8 pods
    d, _ = _step_delta(params, tx, grown)
    assert np.isclose(d, -0.2), d


def test_rescale_shrink_scales_update_linearly():
    params, tx, opt_state = _setup(lr=0.1)
    shrunk = lr_mod.rescale_state(opt_state, 2 / 4)  # 4 -> 2 pods
    d, _ = _step_delta(params, tx, shrunk)
    assert np.isclose(d, -0.05), d


def test_rescale_compounds_and_round_trips():
    params, tx, opt_state = _setup(lr=0.1)
    s = lr_mod.rescale_state(opt_state, 8 / 4)   # 4 -> 8
    s = lr_mod.rescale_state(s, 4 / 8)           # 8 -> 4: back to 1.0
    d, _ = _step_delta(params, tx, s)
    assert np.isclose(d, -0.1), d


def test_scale_state_survives_update_and_noops_unwrapped():
    params, tx, opt_state = _setup(lr=0.1)
    scaled = lr_mod.rescale_state(opt_state, 2.0)
    _d, after = _step_delta(params, tx, scaled)
    d2, _ = _step_delta(params, tx, after)
    assert np.isclose(d2, -0.2), "the scale must persist across steps"
    # a plain (unwrapped) opt_state passes through rescale_state untouched
    plain = optax.sgd(0.1).init(params)
    assert jax.tree.all(jax.tree.map(
        lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)),
        plain, lr_mod.rescale_state(plain, 3.0)))


def test_world_scaled_adam_effective_lr(monkeypatch):
    """Adam's update is proportional to its LR, so the trailing scale is
    an exact effective-LR change there too, inside a jitted step."""
    params = {"w": jnp.ones((4,), jnp.float32)}
    tx = lr_mod.world_scaled(optax.adam(1e-3))
    opt_state = tx.init(params)
    grads = {"w": jnp.full((4,), 0.5, jnp.float32)}

    @jax.jit
    def step(g, s):
        return tx.update(g, s, params)

    base, _ = step(grads, opt_state)
    scaled, _ = step(grads, lr_mod.rescale_state(opt_state, 4.0))
    assert np.allclose(np.asarray(scaled["w"]),
                       4.0 * np.asarray(base["w"]), rtol=1e-5)


def test_trainer_world_lr_rescale_gate(monkeypatch):
    """The trainer helper applies the factor only when the knob is on."""
    from edl_tpu.utils import constants
    from edl_tpu.train.trainer import ElasticTrainer

    params = {"w": jnp.ones((2,), jnp.float32)}
    tx = lr_mod.world_scaled(optax.sgd(0.1))
    state = {"opt": tx.init(params)}

    monkeypatch.setattr(constants, "LR_RESCALE", 0)
    off = ElasticTrainer._world_lr_rescale(object(), state, 4, 8)
    assert float(np.asarray(off["opt"][1].lr_scale)) == 1.0

    monkeypatch.setattr(constants, "LR_RESCALE", 1)
    on = ElasticTrainer._world_lr_rescale(object(), state, 4, 8)
    assert float(np.asarray(on["opt"][1].lr_scale)) == 2.0
    # no-op factors never touch the tree
    same = ElasticTrainer._world_lr_rescale(object(), state, 8, 8)
    assert same is state
