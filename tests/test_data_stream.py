"""Streamed batch delivery (ISSUE 11): framed transport, multi-worker
prefetch, old-peer demotion, stream-protocol failure repair, drain
invariants, and the shared-channel-pool timeout behavior."""

import threading
import time

import pytest

from edl_tpu.data import DistributedReader, PodDataServer, device_put_stream
from edl_tpu.data import distribute_reader as dr_mod
from edl_tpu.data.elastic_input import SPANS_KEY
from edl_tpu.rpc.server import Streaming
from tests.helpers.exactly_once import audit_spans

ALL = sorted(f"f{f}r{r}" for f in range(4) for r in range(10))


@pytest.fixture
def files(tmp_path):
    paths = []
    for f in range(4):
        p = tmp_path / f"part-{f}.txt"
        p.write_text("".join(f"f{f}r{r}\n" for r in range(10)))
        paths.append(str(p))
    return paths


def drain(reader, spans: list | None = None):
    got = []
    for _bid, payload in reader:
        got.extend(payload["records"])
        if spans is not None:
            spans.extend(payload["spans"])
    return got


def test_remote_fetch_rides_the_streamed_path(files):
    """podB produces, podA consumes: the batches must cross the wire
    over get_batch_stream frames (not per-batch RPCs), exactly once."""
    a = PodDataServer("podA", is_leader=True)
    b = PodDataServer("podB")
    stream0 = dr_mod._DELIVERED.labels(path="stream").value
    rpc0 = dr_mod._DELIVERED.labels(path="rpc").value
    try:
        ra = DistributedReader("rs1", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("rs1", "podB", a.endpoint, b, batch_size=4)
        ra.create(files)
        rb.create(files)
        tb = threading.Thread(target=rb._produce, daemon=True)
        tb.start()
        spans: list = []
        got = drain(ra, spans)
        tb.join(10)
        assert sorted(got) == ALL
        audit_spans(spans, 4, 10)
        assert dr_mod._DELIVERED.labels(path="stream").value > stream0
        # nothing fell back to the legacy per-batch path
        assert dr_mod._DELIVERED.labels(path="rpc").value == rpc0
    finally:
        a.stop(); b.stop()


def test_old_peer_demotion_roundtrip(files):
    """A producer without the get_batch_stream handler (an old peer)
    demotes the consumer's pool to per-batch fetch — probed ONCE — and
    every record still arrives exactly once."""
    a = PodDataServer("podA", is_leader=True)
    b = PodDataServer("podB")
    # simulate an old peer: its RPC surface predates the stream handler
    del b._rpc._server.methods["get_batch_stream"]
    demote0 = dr_mod._DEMOTIONS.value
    rpc0 = dr_mod._DELIVERED.labels(path="rpc").value
    try:
        ra = DistributedReader("rs2", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("rs2", "podB", a.endpoint, b, batch_size=4)
        ra.create(files)
        rb.create(files)
        tb = threading.Thread(target=rb._produce, daemon=True)
        tb.start()
        spans: list = []
        got = drain(ra, spans)
        tb.join(10)
        assert sorted(got) == ALL
        audit_spans(spans, 4, 10)
        # probe-once per pool — though workers already mid-flight when
        # the first probe demotes may each pay one probe, so the bound
        # is the worker count, not the batch count
        assert (demote0 + 1 <= dr_mod._DEMOTIONS.value
                <= demote0 + ra._n_workers)
        assert dr_mod._DELIVERED.labels(path="rpc").value > rpc0
    finally:
        a.stop(); b.stop()


@pytest.mark.parametrize("mode", ["short", "mismatch", "garbage"])
def test_stream_protocol_errors_repair_via_requeue(files, mode):
    """Crafted short/mismatched/undecodable frames surface as a typed
    EdlStreamError and the unreceived batches are re-fetched through
    the leader's requeue-repair path — never dropped, never
    double-acked (the audit proves both)."""
    a = PodDataServer("podA", is_leader=True)
    b = PodDataServer("podB")

    real = b.get_batch_stream

    def broken_stream(batch_ids):
        def frames():
            it = real(batch_ids).it
            for i, frame in enumerate(it):
                if mode == "short" and i == len(batch_ids) - 1:
                    return  # ends one frame early
                if mode == "mismatch" and i == 0:
                    frame = dict(frame, batch_id="not-a-batch")
                if mode == "garbage" and i == 0:
                    frame = b"\x00not msgpack\xff"
                yield frame
        return Streaming(frames())

    b._rpc._server.methods["get_batch_stream"] = broken_stream
    err0 = dr_mod._STREAM_ERRORS.value
    try:
        ra = DistributedReader("rs3", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("rs3", "podB", a.endpoint, b, batch_size=4)
        ra.create(files[:2])
        rb.create(files[:2])
        tb = threading.Thread(target=rb._produce, daemon=True)
        tb.start()
        # podB produces its share, then stops producing: the repair
        # spans must be re-produced by podA (fetched from its own
        # cache), or the epoch would never drain
        deadline = time.monotonic() + 10
        while (a.service.reader_status("rs3")["produced"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        spans: list = []
        got = drain(ra, spans)
        tb.join(10)
        assert sorted(got) == sorted(f"f{f}r{r}" for f in range(2)
                                     for r in range(10))
        audit_spans(spans, 2, 10)
        assert dr_mod._STREAM_ERRORS.value > err0
    finally:
        a.stop(); b.stop()


def test_producer_killed_mid_epoch_streamed_exactly_once(files):
    """The streamed path under the chaos contract: a producer dies
    after publishing metas; its batches fail the stream open, conclude
    dead, nack, and its files re-produce — exactly once end to end."""
    a = PodDataServer("podA", is_leader=True)
    b = PodDataServer("podB")
    try:
        rb = DistributedReader("rs4", "podB", a.endpoint, b, batch_size=4)
        rb.create(files[:2])
        tb = threading.Thread(target=rb._produce, daemon=True)
        tb.start()
        deadline = time.monotonic() + 10
        while (a.service.reader_status("rs4")["produced"] < 6
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert a.service.reader_status("rs4")["produced"] == 6
        rb._stop_produce.set()
        tb.join(5)
        b.stop()  # SIGKILL stand-in: the cache endpoint goes dark
        ra = DistributedReader("rs4", "podA", a.endpoint, a, batch_size=4)
        spans: list = []
        got = drain(ra, spans)
        assert sorted(got) == sorted(f"f{f}r{r}" for f in range(2)
                                     for r in range(10))
        audit_spans(spans, 2, 10)
    finally:
        a.stop()


def test_prefetch_drain_leaves_zero_unacked(files):
    """After EdlStopIteration the prefetcher must have drained: no
    held (unacked) batch ids on the reader, none in the leader's
    inflight table, and the fetch workers gone."""
    a = PodDataServer("podA", is_leader=True)
    try:
        ra = DistributedReader("rs5", "podA", a.endpoint, a, batch_size=4)
        ra.create(files)
        got = drain(ra)
        assert sorted(got) == ALL
        with ra._state_lock:
            assert not ra._held
        status = a.service.reader_status("rs5")
        assert all(n == 0 for n in status["inflight"].values()), status
        assert status["acked"] == status["produced"]
        for t in ra._fetch_workers:
            t.join(5)
            assert not t.is_alive()
    finally:
        a.stop()


def test_dead_producer_costs_workers_one_timeout_in_parallel(monkeypatch):
    """Mirror of the rpc/client connect-outside-the-lock regression
    test, at the reader level: concurrent fetch-worker groups against
    one dead producer share an RpcChannelPool with per-connection
    locking, so they all fail in ~one retry cycle, in parallel — not
    N cycles in series."""
    from edl_tpu.rpc import client as client_mod

    delay = 0.2

    def slow_connect(endpoint, timeout):
        time.sleep(delay)
        raise OSError("connect timed out")

    monkeypatch.setattr(client_mod, "_connect", slow_connect)
    a = PodDataServer("podA", is_leader=True)
    try:
        ra = DistributedReader("rs6", "podA", a.endpoint, a, batch_size=4,
                               stream=False, fetch_workers=4)
        ra._closed = True  # skip the inter-attempt sleeps (test only)
        results: list = []

        def worker(i):
            meta = ["podB", "198.51.100.1:9", f"podB:{i}", [[0, 0, 4]]]
            results.append(ra._fetch_group("podB", "198.51.100.1:9", [meta]))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        assert all(r[0][2] == "dead" for r in results), results
        # each group: 3 attempts x 2 dials x 0.2 s = 1.2 s, all four
        # groups in PARALLEL; serialized would be >= 4.8 s
        assert wall < 3.0, f"dead-producer fetches serialized: {wall:.2f}s"
    finally:
        a.stop()


def test_forced_legacy_mode_still_exact(files):
    """EDL_TPU_DATA_PREFETCH_STREAM=0 (the stream=False knob) keeps the
    whole pipeline on per-batch RPCs — still exactly once."""
    a = PodDataServer("podA", is_leader=True)
    b = PodDataServer("podB")
    try:
        ra = DistributedReader("rs7", "podA", a.endpoint, a, batch_size=4,
                               stream=False)
        rb = DistributedReader("rs7", "podB", a.endpoint, b, batch_size=4,
                               stream=False)
        ra.create(files)
        rb.create(files)
        tb = threading.Thread(target=rb._produce, daemon=True)
        tb.start()
        spans: list = []
        got = drain(ra, spans)
        tb.join(10)
        assert sorted(got) == ALL
        audit_spans(spans, 4, 10)
    finally:
        a.stop(); b.stop()


def test_device_put_stream_overlaps_and_keeps_spans_host_side():
    """The H2D overlap stage: batch k+1's put runs while batch k is
    consumed (wall time ~max(puts, consumes), not the sum), spans stay
    host-side, and order is preserved."""
    n, put_s, consume_s = 6, 0.05, 0.05
    put_threads: list = []

    def put(batch):
        put_threads.append(threading.current_thread().name)
        time.sleep(put_s)
        return {k: v for k, v in batch.items()}

    def batches():
        for i in range(n):
            yield {"x": i, SPANS_KEY: [[0, i, i + 1]]}

    t0 = time.monotonic()
    seen = []
    for dev_batch, spans in device_put_stream(batches(), put):
        assert SPANS_KEY not in dev_batch  # split out before the put
        seen.append((dev_batch["x"], spans))
        time.sleep(consume_s)
    wall = time.monotonic() - t0
    assert seen == [(i, [[0, i, i + 1]]) for i in range(n)]
    # staging happened off the consumer thread
    assert all("h2d-stage" in name for name in put_threads)
    # serial would be n*(put+consume) = 0.6s; overlapped ~0.35s
    assert wall < n * (put_s + consume_s) - put_s, wall
