"""Continuous profiling (ISSUE 13): the per-step phase ledger, the
shared FLOP helper, on-demand profile capture + its /profile route,
the rule engine's alert action hooks, and the Perfetto counter-track
export."""

import json
import os
import time
import urllib.request

import pytest

from edl_tpu.obs import context as obs_context
from edl_tpu.obs import dump as obs_dump
from edl_tpu.obs import flops as obs_flops
from edl_tpu.obs import ledger as obs_ledger
from edl_tpu.obs import profile as obs_profile
from edl_tpu.obs import rules as obs_rules
from edl_tpu.obs import trace as obs_trace
from edl_tpu.obs.ledger import PHASE_SECONDS, StepPhaseLedger
from edl_tpu.obs.rules import Rule, RuleEngine
from edl_tpu.obs.tsdb import TSDB


def _phase_sum(phase: str) -> float:
    return PHASE_SECONDS.labels(phase=phase).sum


# -- StepPhaseLedger ---------------------------------------------------------

def test_ledger_nested_credit_is_deducted():
    """An h2d credit recorded inside data_wait must come OUT of
    data_wait — the per-step phase sum never double counts."""
    led = StepPhaseLedger(enabled=True)
    before = {p: _phase_sum(p) for p in obs_ledger.PHASES}
    with led.phase("data_wait"):
        time.sleep(0.02)
        led.add("h2d", 0.015)
    led.step_done(0.05)
    d_data = _phase_sum("data_wait") - before["data_wait"]
    d_h2d = _phase_sum("h2d") - before["h2d"]
    assert d_h2d == pytest.approx(0.015)
    # conservation: data_wait + the deducted credit covers the slept
    # block, and data_wait alone is strictly less than the whole block
    assert d_data + d_h2d >= 0.02
    assert 0.0 <= d_data < 0.02 + 1.0  # bounded (loaded-CI slack)


def test_ledger_nested_phase_deducts_full_child_span():
    led = StepPhaseLedger(enabled=True)
    before = {p: _phase_sum(p) for p in obs_ledger.PHASES}
    with led.phase("hooks"):
        with led.phase("checkpoint"):
            time.sleep(0.03)
            led.add("h2d", 0.01)
    led.step_done(0.05)
    d_hooks = _phase_sum("hooks") - before["hooks"]
    d_ckpt = _phase_sum("checkpoint") - before["checkpoint"]
    d_h2d = _phase_sum("h2d") - before["h2d"]
    assert d_h2d == pytest.approx(0.01)
    assert d_ckpt >= 0.02                     # the sleep minus the credit
    # hooks excludes the child's WHOLE span (sleep included), so it is
    # just the context-manager overhead — effectively zero
    assert d_hooks < 0.01


def test_ledger_coverage_ema_and_gauge():
    led = StepPhaseLedger(enabled=True)
    led.add("compute", 0.8)
    led.step_done(1.0)
    assert led.coverage == pytest.approx(0.8)
    led.add("compute", 1.0)
    led.step_done(1.0)                        # clamped at 1.0
    assert led.coverage == pytest.approx(0.9 * 0.8 + 0.1 * 1.0)


def test_ledger_disabled_is_a_noop():
    led = StepPhaseLedger(enabled=False)
    before = _phase_sum("compute")
    with led.phase("compute"):
        pass
    led.add("h2d", 5.0)
    led.step_done(1.0)
    assert _phase_sum("compute") == before
    assert led.coverage is None


def test_ledger_reset_discards_unobserved_phases():
    """The trainer resets at its FIRST step observation so the compile
    accumulated inside compute is never observed as a step sample."""
    led = StepPhaseLedger(enabled=True)
    before = _phase_sum("compute")
    led.add("compute", 99.0)                  # "the compile"
    led.reset()
    led.add("compute", 0.01)
    led.step_done(0.02)
    assert _phase_sum("compute") - before == pytest.approx(0.01)


def test_ledger_env_knob(monkeypatch):
    monkeypatch.setenv("EDL_TPU_STEP_LEDGER", "0")
    assert StepPhaseLedger().enabled is False
    monkeypatch.delenv("EDL_TPU_STEP_LEDGER")
    assert StepPhaseLedger().enabled is True


def test_ledger_capture_emits_per_step_events(tmp_path):
    path = str(tmp_path / "trace-test.jsonl")
    prev = obs_trace.install(obs_trace.Tracer(path, "test"))
    try:
        led = StepPhaseLedger(enabled=True)
        led.start_capture(30.0)
        assert led.capture_active()
        for i in range(3):
            led.add("compute", 0.01)
            led.step_done(0.012, step=i)
    finally:
        obs_trace.install(prev).close()
    events, bad = obs_dump.read_trace_file(path)
    assert bad == 0
    phases = [e for e in events if e["name"] == "train/step_phases"]
    assert len(phases) == 3
    assert phases[0]["steps"] == 1
    assert phases[0]["counters"]["compute"] == pytest.approx(0.01)
    assert set(phases[0]["counters"]) == set(obs_ledger.PHASES)


def test_ledger_flush_aggregates(tmp_path):
    path = str(tmp_path / "trace-agg.jsonl")
    prev = obs_trace.install(obs_trace.Tracer(path, "test"))
    try:
        led = StepPhaseLedger(enabled=True)
        for i in range(4):
            led.add("compute", 0.01)
            led.step_done(0.02, step=i)
        led.flush(step=4)
    finally:
        obs_trace.install(prev).close()
    events, _ = obs_dump.read_trace_file(path)
    phases = [e for e in events if e["name"] == "train/step_phases"]
    assert len(phases) == 1                   # throttled: one aggregate
    assert phases[0]["steps"] == 4
    # counters are PER-STEP MEANS (same unit as capture events, so one
    # Perfetto counter track stays scale-comparable); dur is the total
    assert phases[0]["counters"]["compute"] == pytest.approx(0.01)
    assert phases[0]["dur"] == pytest.approx(0.08)


# -- obs/flops.py ------------------------------------------------------------

def test_peak_tflops_longest_match_and_env(monkeypatch):
    class Dev:
        def __init__(self, kind):
            self.device_kind = kind

    monkeypatch.delenv("EDL_TPU_PEAK_TFLOPS", raising=False)
    assert obs_flops.peak_tflops(Dev("TPU v5 lite")) == 197.0
    assert obs_flops.peak_tflops(Dev("TPU v5p")) == 459.0
    assert obs_flops.peak_tflops(Dev("weird accelerator")) is None
    monkeypatch.setenv("EDL_TPU_PEAK_TFLOPS", "12.5")
    assert obs_flops.peak_tflops(Dev("weird accelerator")) == 12.5


def test_analytic_lm_flops_matches_hand_formula():
    L, D, M, V, S = 12, 768, 3072, 32_000, 1024
    n_matmul = L * (4 * D * D + 3 * D * M) + D * V
    want = 6 * n_matmul + 6 * L * S * D
    assert obs_flops.analytic_lm_flops_per_token(L, D, M, V, S) == want


def test_xla_cost_flops_on_a_jitted_matmul():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 64), jnp.float32)
    flops = obs_flops.xla_cost_flops(f, a, a)
    # CPU XLA answers with real FLOPs on current jaxlibs; tolerate an
    # analysis-less backend (None) but never a bogus value
    assert flops is None or flops > 0


# -- ProfileCapture + /profile route ----------------------------------------

def test_profile_capture_ledger_fallback_manifest_and_trace(tmp_path):
    trace_path = str(tmp_path / "trace-prof.jsonl")
    prev = obs_trace.install(obs_trace.Tracer(trace_path, "test"))
    led = StepPhaseLedger(enabled=True)
    cap = obs_profile.ProfileCapture("trainer", ledger=led,
                                     out_dir=str(tmp_path))
    ctx = obs_context.new_trace()
    try:
        with obs_context.use(ctx):
            res = cap.trigger(duration_s=0.2, trigger="alert")
        assert res["started"] and res["kind"] == "phase_ledger"
        assert res["trace_id"] == ctx.trace_id
        assert led.capture_active()
        deadline = time.time() + 10
        manifest_path = res["manifest"]
        while time.time() < deadline and not os.path.exists(manifest_path):
            time.sleep(0.05)
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
        # the worker emits the trace event right after the manifest;
        # wait for it before swapping the tracer back
        while time.time() < deadline:
            events, _ = obs_dump.read_trace_file(trace_path)
            if any(e["name"] == "profile/capture" for e in events):
                break
            time.sleep(0.05)
    finally:
        obs_trace.install(prev).close()
    assert manifest["trace_id"] == ctx.trace_id
    assert manifest["trigger"] == "alert"
    assert manifest["kind"] == "phase_ledger"
    events, _ = obs_dump.read_trace_file(trace_path)
    caps = [e for e in events if e["name"] == "profile/capture"]
    assert caps and caps[0]["trace_id"] == ctx.trace_id
    # and the capture joins the trace's merged timeline
    tl = obs_dump.merge_timeline(events, ctx.trace_id)
    assert any(e["name"] == "profile/capture" for e in tl)


def test_profile_capture_busy_guard(tmp_path):
    cap = obs_profile.ProfileCapture("trainer",
                                     ledger=StepPhaseLedger(enabled=True),
                                     out_dir=str(tmp_path))
    first = cap.trigger(duration_s=1.0)
    assert first.get("started")
    second = cap.trigger(duration_s=1.0)
    assert second.get("busy")


def test_profile_jax_stop_failure_does_not_double_sleep(tmp_path,
                                                        monkeypatch):
    """A jax capture that fails only at stop_trace has already slept
    the window; the fallback must not hold the capture slot for a
    second full window."""
    import jax

    monkeypatch.setattr(obs_profile, "_jax_profiler_usable", lambda: True)
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def boom():
        raise RuntimeError("stop failed")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    cap = obs_profile.ProfileCapture("trainer",
                                     ledger=StepPhaseLedger(enabled=True),
                                     out_dir=str(tmp_path))
    t0 = time.monotonic()
    # a 1.5s window so the one-vs-two-sleeps gap (1.5s) dwarfs
    # scheduler jitter: the original 0.6s window left 0.5s of slack
    # and flaked on a loaded box without any real double-sleep
    res = cap.trigger(duration_s=1.5)
    assert res["started"] and res["kind"] == "jax_profiler"
    deadline = time.time() + 15
    while time.time() < deadline and not os.path.exists(res["manifest"]):
        time.sleep(0.05)
    elapsed = time.monotonic() - t0
    with open(res["manifest"], encoding="utf-8") as f:
        manifest = json.load(f)
    # downgraded (stop failed, window already spent) — and finished in
    # ~one window, not two (the double-sleep bug took >= 3.0s)
    assert manifest["kind"] == "manifest_only"
    assert elapsed < 2.5, f"capture slot held {elapsed:.2f}s for a 1.5s window"


def test_profile_route_over_http(tmp_path):
    from edl_tpu.obs.exposition import MetricsServer
    from edl_tpu.obs.metrics import Registry

    led = StepPhaseLedger(enabled=True)
    cap = obs_profile.ProfileCapture("trainer", ledger=led,
                                     out_dir=str(tmp_path))
    obs_profile.install_route(cap)
    srv = MetricsServer(Registry(), host="127.0.0.1").start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/profile?duration_s=0.1",
            timeout=10).read().decode()
        res = json.loads(body)
        assert res.get("started") or res.get("busy")
        # /metrics still serves on the same endpoint
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10
        ).read().decode()
        assert page is not None
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=10)
    finally:
        srv.stop()


# -- alert action hooks ------------------------------------------------------

def test_rule_action_runs_on_firing_transition_only():
    t = TSDB()
    calls = []
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0,
                window=60.0, for_s=0.0, action="profile")
    eng = RuleEngine(t, [rule],
                     actions={"profile":
                              lambda r, g, v: calls.append((r.name, g, v))})
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    assert eng.evaluate(1000.0) != []
    assert calls == [("hot", "", 9.0)]
    t.ingest({("edl_g", ()): 9.0}, 1001.0)
    eng.evaluate(1001.0)                      # still firing: no re-run
    assert len(calls) == 1
    # resolve, then fire again -> a second invocation
    t.ingest({("edl_g", ()): 1.0}, 1002.0)
    eng.evaluate(1002.0)
    t.ingest({("edl_g", ()): 9.0}, 1003.0)
    eng.evaluate(1003.0)
    assert len(calls) == 2


def test_rule_action_without_handler_is_counted_not_fatal():
    t = TSDB()
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0,
                window=60.0, for_s=0.0, action="missing")
    eng = RuleEngine(t, [rule])               # no actions registered
    before = obs_rules._ACTIONS_TOTAL.labels(
        action="missing", outcome="no_handler").value
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    assert eng.evaluate(1000.0) != []
    assert obs_rules._ACTIONS_TOTAL.labels(
        action="missing", outcome="no_handler").value == before + 1


def test_rule_action_error_does_not_stop_alerting():
    t = TSDB()

    def boom(rule, group, value):
        raise RuntimeError("nope")

    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0,
                window=60.0, for_s=0.0, action="profile")
    eng = RuleEngine(t, [rule], actions={"profile": boom})
    before = obs_rules._ACTIONS_TOTAL.labels(
        action="profile", outcome="error").value
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    assert eng.evaluate(1000.0) != []         # still fires
    assert obs_rules._ACTIONS_TOTAL.labels(
        action="profile", outcome="error").value == before + 1


def test_builtin_profile_actions_and_goodput_rule():
    rules = {r.name: r for r in obs_rules.builtin_rules()}
    # the capture action rides alongside the remediation actuators
    # (comma-chained; the engine runs each registered handler)
    assert "profile" in rules["trainer-straggler"].action_names()
    assert "evict" in rules["trainer-straggler"].action_names()
    assert "profile" in rules["gateway-p99-slo"].action_names()
    assert "scale-out" in rules["gateway-p99-slo"].action_names()
    # the postmortem bundle capture is prepended to EVERY builtin rule
    # (evidence is frozen before restart/evict acts on it)
    assert all(r.action_names()[0] == "bundle" for r in rules.values())
    assert rules["trainer-hang"].action_names() == ["bundle", "restart"]
    assert rules["gateway-reject-burn"].action_names() == ["bundle",
                                                           "scale-out"]


def test_builtin_bundle_action_strips_with_env(monkeypatch):
    monkeypatch.setenv("EDL_TPU_OBS_BUNDLE", "0")
    rules = {r.name: r for r in obs_rules.builtin_rules()}
    assert rules["trainer-hang"].action_names() == ["restart"]
    assert all("bundle" not in r.action_names() for r in rules.values())
    gr = rules["goodput-regression"]
    assert gr.metric == "edl_goodput_ratio" and gr.op == "<"


# -- Perfetto counter tracks -------------------------------------------------

def test_perfetto_counter_tracks_from_counters_events():
    events = [
        {"ts": 10.0, "name": "train/step_phases", "dur": 0.5,
         "component": "trainer", "file": "trace-trainer-1.jsonl",
         "steps": 5,
         "counters": {"compute": 0.4, "data_wait": 0.05, "label": "x"}},
        {"ts": 11.0, "name": "goodput/sample", "component": "obs-agg",
         "file": "trace-agg.jsonl",
         "counters": {"goodput_ratio": 0.9, "badput_resize_s": 1.5}},
        {"ts": 12.0, "name": "resize/detect", "component": "launcher",
         "file": "trace-launch.jsonl"},
    ]
    pf = obs_dump.to_perfetto(events)
    counters = [e for e in pf["traceEvents"] if e["ph"] == "C"]
    assert len(counters) == 2
    by_name = {c["name"]: c for c in counters}
    assert by_name["train/step_phases"]["args"] == {
        "compute": 0.4, "data_wait": 0.05}    # non-numeric keys dropped
    assert by_name["goodput/sample"]["args"]["goodput_ratio"] == 0.9
    # the span row still exists alongside its counter sample
    xs = [e for e in pf["traceEvents"] if e.get("ph") == "X"]
    assert any(e["name"] == "train/step_phases" for e in xs)
    json.dumps(pf)                            # stays valid trace JSON


# -- aggregator surface ------------------------------------------------------

def test_healthz_carries_goodput(memkv):
    from edl_tpu.obs.agg import Aggregator

    agg = Aggregator(memkv, "gp-job", scrape_interval=0, cache_s=0.0,
                     include_self=False, enable_actions=False)
    summary = agg.job_summary()
    gp = summary["goodput"]
    assert set(gp) == {"observed_s", "productive_s", "badput", "ratio"}
    assert set(gp["badput"]) == {"resize", "restore", "hang", "idle"}
