"""Flagship LM pretraining example: dp x sp x tp sharded training (and
the ring-attention long-context variant) on the virtual 8-device mesh —
the beyond-parity parallelism capability as a real workload, not just
the dryrun."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "examples", "lm", "train_lm.py")


def run_lm(tmp_path, *args, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["EDL_TPU_DEMO_MARKER"] = str(tmp_path / "marker")
    out = subprocess.run([sys.executable, TRAIN, *args], env=env,
                         cwd=str(tmp_path), capture_output=True, text=True,
                         timeout=timeout)
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads([l for l in (tmp_path / "marker").read_text().splitlines()
                      if l.startswith("done ")][-1][5:])
    return rec, out.stdout


@pytest.mark.slow
def test_lm_learns_on_dp_sp_tp_mesh(tmp_path):
    rec, _ = run_lm(tmp_path, "--epochs", "3", "--steps_per_epoch", "15",
                    "--tp", "2", "--sp", "2")
    assert rec["mesh"]["tp"] == 2 and rec["mesh"]["sp"] == 2, rec
    # sequence structure learned: well under the unigram entropy
    assert rec["val_nll"] < rec["unigram_nll"] - 0.9, rec
    # and monotone-ish improvement
    assert rec["nll_curve"][-1] < rec["nll_curve"][0], rec


@pytest.mark.slow
def test_lm_ring_attention_long_context(tmp_path):
    rec, _ = run_lm(tmp_path, "--epochs", "2", "--steps_per_epoch", "10",
                    "--tp", "1", "--sp", "4", "--attention", "ring")
    assert rec["mesh"]["sp"] == 4, rec
    assert rec["val_nll"] < rec["unigram_nll"], rec


@pytest.mark.slow
def test_lm_pipeline_parallel(tmp_path):
    """Decoder blocks pipelined over pp=4 (GPipe via ops/pipeline.py):
    each shard holds one block's params; the model still learns."""
    rec, _ = run_lm(tmp_path, "--epochs", "3", "--steps_per_epoch", "12",
                    "--pp", "4", "--layers", "4")
    assert rec["mesh"]["pp"] == 4, rec
    assert rec["val_nll"] < rec["unigram_nll"] - 0.4, rec
    assert rec["nll_curve"][-1] < rec["nll_curve"][0], rec


@pytest.mark.slow
def test_lm_pipeline_composes_with_tp_and_fsdp(tmp_path):
    """pp=2 x tp=2 x fsdp=2: the pipeline shard_map is manual over pp
    only, so megatron tensor parallelism and zero-style param sharding
    ride GSPMD inside the stages (round-3 verdict weak #5: --pp forced
    tp=sp=fsdp=1)."""
    rec, _ = run_lm(tmp_path, "--epochs", "3", "--steps_per_epoch", "12",
                    "--pp", "2", "--tp", "2", "--fsdp", "2",
                    "--layers", "4")
    assert rec["mesh"]["pp"] == 2 and rec["mesh"]["tp"] == 2, rec
    assert rec["mesh"]["fsdp"] == 2, rec
    assert rec["val_nll"] < rec["unigram_nll"] - 0.4, rec
    assert rec["nll_curve"][-1] < rec["nll_curve"][0], rec


@pytest.mark.slow
def test_lm_gqa_trains(tmp_path):
    """--kv_heads 2 (grouped-query attention) trains the same workload
    on a dp x tp mesh — the grouped dense path under jit + grad."""
    rec, _ = run_lm(tmp_path, "--epochs", "2", "--steps_per_epoch", "10",
                    "--kv_heads", "2", "--tp", "2")
    assert rec["mesh"]["tp"] == 2, rec
    assert rec["val_nll"] < rec["unigram_nll"], rec
    assert rec["nll_curve"][-1] < rec["nll_curve"][0], rec


@pytest.mark.slow
def test_lm_fsdp_param_sharding(tmp_path):
    """dp x fsdp x tp: zero-style parameter sharding (embed on fsdp via
    the logical rules) trains the same workload."""
    rec, _ = run_lm(tmp_path, "--epochs", "2", "--steps_per_epoch", "10",
                    "--tp", "2", "--sp", "1", "--fsdp", "2")
    assert rec["mesh"]["fsdp"] == 2 and rec["mesh"]["tp"] == 2, rec
    assert rec["val_nll"] < rec["unigram_nll"], rec
