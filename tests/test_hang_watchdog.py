"""Hang watchdog: heartbeat plumbing + launcher in-place restart.

A deadlocked trainer holds its process alive, so exit-code watching
never fires; the watchdog bridges it by restarting the trainers when
the per-step heartbeat goes stale (SURVEY.md §5: the reference had no
equivalent)."""

import os
import subprocess
import sys
import time

import pytest

from edl_tpu.cluster import heartbeat
from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.coord.client import CoordClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "tests", "helpers", "demo_trainer.py")


def test_heartbeat_roundtrip(memkv):
    assert heartbeat.last_beat(memkv, "j", "p") is None
    heartbeat.beat(memkv, "j", "p", now=123.5)
    assert heartbeat.last_beat(memkv, "j", "p") == 123.5
    heartbeat.clear(memkv, "j", "p")
    assert heartbeat.last_beat(memkv, "j", "p") is None


def test_heartbeat_publishes_threshold(memkv):
    heartbeat.beat(memkv, "j", "p", now=10.0, threshold=240.0)
    assert heartbeat.last_beat_info(memkv, "j", "p") == (10.0, 240.0)
    assert heartbeat.last_beat(memkv, "j", "p") == 10.0
    # threshold-less (legacy / explicit-override) beats still parse
    heartbeat.beat(memkv, "j", "p", now=11.0)
    assert heartbeat.last_beat_info(memkv, "j", "p") == (11.0, None)


def test_auto_threshold_shape():
    assert heartbeat.auto_threshold(None) == heartbeat.AUTO_FLOOR
    assert heartbeat.auto_threshold(0.5) == heartbeat.AUTO_FLOOR
    assert heartbeat.auto_threshold(30.0) == 300.0     # 10x EMA past floor


def test_stale_threshold_env_semantics(monkeypatch):
    from edl_tpu.utils import constants
    # auto (default 0): use the published value; none published = off
    monkeypatch.setattr(constants, "HANG_TIMEOUT", 0.0)
    assert heartbeat.stale_threshold(200.0) == 200.0
    assert heartbeat.stale_threshold(None) is None
    # explicit override
    monkeypatch.setattr(constants, "HANG_TIMEOUT", 42.0)
    assert heartbeat.stale_threshold(200.0) == 42.0
    # disabled
    monkeypatch.setattr(constants, "HANG_TIMEOUT", -1.0)
    assert heartbeat.stale_threshold(200.0) is None


def test_trainer_publishes_auto_threshold(memkv):
    """The default-config trainer's beat carries a derived threshold —
    the watchdog engages with zero configuration."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu.cluster.env import TrainerEnv
    from edl_tpu.train import ElasticTrainer, TrainConfig

    tenv = TrainerEnv({"EDL_TPU_JOB_ID": "hb2", "EDL_TPU_POD_ID": "podA",
                       "EDL_TPU_TRAINER_RANK_IN_POD": "0"})

    def loss_fn(params, extra, batch, rng):
        return ((params["w"] * batch["x"] - batch["y"]) ** 2).mean(), (
            extra, {})

    tr = ElasticTrainer(loss_fn,
                        TrainConfig(log_every=0, heartbeat_every=0.001),
                        store=memkv, tenv=tenv)
    state = tr.create_state(
        lambda: ({"w": jnp.ones(())}, None), optax.sgd(0.1))

    def data(_e):
        for _ in range(4):
            yield {"x": np.ones((8,), np.float32),
                   "y": np.full((8,), 3.0, np.float32)}

    tr.fit(state, tr.restore_or_create(
        lambda: ({"w": jnp.ones(())}, None), optax.sgd(0.1))[1],
        data, epochs=1)
    info = heartbeat.last_beat_info(memkv, "hb2", "podA")
    assert info is not None
    ts, thr = info
    # steps are sub-second, so the floor dominates
    assert thr == heartbeat.AUTO_FLOOR


def test_trainer_beats_after_steps(memkv):
    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu.cluster.env import TrainerEnv
    from edl_tpu.train import ElasticTrainer, TrainConfig

    tenv = TrainerEnv({"EDL_TPU_JOB_ID": "hb", "EDL_TPU_POD_ID": "pod0",
                       "EDL_TPU_TRAINER_RANK_IN_POD": "0"})

    def loss_fn(params, extra, batch, rng):
        return ((params["w"] * batch["x"] - batch["y"]) ** 2).mean(), (
            extra, {})

    tr = ElasticTrainer(loss_fn,
                        TrainConfig(log_every=0, heartbeat_every=0.001),
                        store=memkv, tenv=tenv)
    state = tr.create_state(
        lambda: ({"w": jnp.ones(())}, None), optax.sgd(0.1))

    def data(_e):
        for _ in range(3):
            yield {"x": np.ones((8,), np.float32),
                   "y": np.full((8,), 3.0, np.float32)}

    before = time.time()
    tr.fit(state, tr.restore_or_create(
        lambda: ({"w": jnp.ones(())}, None), optax.sgd(0.1))[1],
        data, epochs=1)
    hb = heartbeat.last_beat(memkv, "hb", "pod0")
    assert hb is not None and hb >= before


@pytest.mark.slow
def test_launcher_restarts_hung_trainer(tmp_path, coord_server):
    """Demo trainer beats once then hangs; watchdog restarts it; the
    second run exits cleanly and the job SUCCEEDs."""
    ep = f"127.0.0.1:{coord_server.port}"
    marker = str(tmp_path / "marker")
    env = dict(os.environ)
    env.update({
        "EDL_TPU_TTL": "2",
        "EDL_TPU_GENERATOR_PERIOD": "0.2",
        "EDL_TPU_WATCHER_PERIOD": "0.2",
        "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
        "EDL_TPU_BARRIER_TIMEOUT": "40",
        "EDL_TPU_HANG_TIMEOUT": "2",
        "EDL_TPU_DEMO_HANG_ONCE": "1",
        "EDL_TPU_DEMO_MARKER": marker,
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    log = open(tmp_path / "launcher.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", "hang1", "--coord_endpoints", ep,
         "--nodes_range", "1:1", "--nproc_per_node", "1",
         "--log_dir", str(tmp_path / "log"), DEMO],
        env=env, cwd=str(tmp_path), stdout=log, stderr=subprocess.STDOUT)
    try:
        ret = proc.wait(timeout=120)
    finally:
        log.close()
    assert ret == 0, open(tmp_path / "launcher.log").read().decode()[-2000:]
    starts = open(marker).read().strip().splitlines()
    assert len(starts) == 2, starts       # hung once, restarted once
    client = CoordClient(ep)
    try:
        assert load_job_status(client, "hang1") == Status.SUCCEED
    finally:
        client.close()


@pytest.mark.slow
def test_multipod_coordinated_hang_restart(tmp_path, coord_server):
    """Both pods' trainers hang after one beat; the hang flag coordinates
    a cluster-wide stop-resume (same stage, instant re-barrier); the
    restarted world runs to SUCCEED."""
    ep = f"127.0.0.1:{coord_server.port}"
    base = {
        "EDL_TPU_TTL": "2",
        "EDL_TPU_GENERATOR_PERIOD": "0.2",
        "EDL_TPU_WATCHER_PERIOD": "0.2",
        "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
        "EDL_TPU_BARRIER_TIMEOUT": "40",
        "EDL_TPU_RESIZE_BARRIER_TIMEOUT": "30",
        "EDL_TPU_HANG_TIMEOUT": "2",
        "EDL_TPU_DEMO_HANG_ONCE": "1",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    procs, markers, logs = [], [], []
    for name in ("a", "b"):
        marker = str(tmp_path / f"marker-{name}")
        env = dict(os.environ)
        env.update(base)
        env["EDL_TPU_DEMO_MARKER"] = marker
        log = open(tmp_path / f"launcher-{name}.log", "wb")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.collective.launch",
             "--job_id", "hang2", "--coord_endpoints", ep,
             "--nodes_range", "2:2", "--nproc_per_node", "1",
             "--log_dir", str(tmp_path / f"log-{name}"), DEMO],
            env=env, cwd=str(tmp_path), stdout=log,
            stderr=subprocess.STDOUT))
        markers.append(marker)
        logs.append(log)
    try:
        rets = [p.wait(timeout=150) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:      # a regression must not leak procs
                p.kill()
        for log in logs:
            log.close()
    detail = "".join(open(tmp_path / f"launcher-{n}.log").read()[-1500:]
                     for n in ("a", "b"))
    assert rets == [0, 0], detail
    for marker in markers:
        starts = open(marker).read().strip().splitlines()
        assert len(starts) == 2, (marker, starts)   # hung once, restarted
        assert all("world=2" in s for s in starts)  # same membership
    client = CoordClient(ep)
    try:
        assert load_job_status(client, "hang2") == Status.SUCCEED
    finally:
        client.close()


def test_hang_cap_persists_across_supervise_loops(monkeypatch):
    """The per-stage incident count must survive supervise re-entry
    (coordinated restarts start a fresh loop) and stay per-stage."""
    from edl_tpu.collective import launcher as launcher_mod

    monkeypatch.setattr(launcher_mod.constants, "HANG_MAX_RESTARTS", 2)
    lch = launcher_mod.Launcher.__new__(launcher_mod.Launcher)
    lch._hang_counts = {}
    assert not lch._count_hang("s1")
    assert not lch._count_hang("s1")
    assert lch._count_hang("s1")       # third incident exceeds cap 2
    assert not lch._count_hang("s2")   # stages count independently


def test_hang_flag_honored_with_watchdog_disabled(memkv, monkeypatch):
    """EDL_TPU_HANG_TIMEOUT=-1 disables LOCAL staleness detection only:
    the coordinated hang FLAG (a peer's watchdog, or a remediation-
    ordered restart — controller/remediate.py's multi-pod path) must
    still be polled and acted on, or the alert-driven restart silently
    no-ops exactly in the alerts-do-the-detecting configuration."""
    import threading as _t

    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.env import JobEnv
    from edl_tpu.cluster.status import Status
    from edl_tpu.collective import launcher as launcher_mod
    from tests.test_cluster_model import make_pod

    monkeypatch.setattr(launcher_mod.constants, "HANG_TIMEOUT", -1.0)
    pods = [make_pod("10.6.0.1"), make_pod("10.6.0.2")]
    cluster = Cluster.from_pods(pods)
    lch = launcher_mod.Launcher.__new__(launcher_mod.Launcher)
    lch._store = memkv
    lch._job_env = JobEnv.__new__(JobEnv)
    lch._job_env.job_id = "j-hangflag"
    lch._pod = pods[0]
    lch._procs = []
    lch._period = 0.02
    lch._ttl = 0.2
    lch._hang_counts = {}
    lch._targeted_counts = {}
    lch._hang_incident = None
    lch._preempt_event = _t.Event()
    lch._preempt_stage = None
    lch._preempt_deadline = None

    class _Alive:
        is_stopped = False
    lch._resource_register = _Alive()
    lch._elector = _Alive()
    monkeypatch.setattr(launcher_mod.train_process, "watch_procs",
                        lambda procs: Status.RUNNING)

    from tests.test_relaunch_and_grace import _FakeWatcher
    watcher = _FakeWatcher()

    def flag():
        time.sleep(0.1)
        heartbeat.flag_hang(memkv, "j-hangflag", cluster.stage,
                            "remediation:trainer-hang")
    _t.Thread(target=flag, daemon=True).start()
    # the flagged coordinated restart unwinds the supervise loop (None
    # = take the restart path) even with the local watchdog disabled
    assert lch._supervise(watcher, cluster) is None
    assert lch._hang_incident is not None


def test_hang_flag_roundtrip(memkv):
    assert heartbeat.get_hang(memkv, "j", "s1") is None
    t1 = heartbeat.flag_hang(memkv, "j", "s1", "podA")
    assert heartbeat.get_hang(memkv, "j", "s1") == t1
    assert heartbeat.get_hang(memkv, "j", "s2") is None  # per-stage
    t2 = heartbeat.flag_hang(memkv, "j", "s1", "podB")   # overwrite wins
    assert t2 >= t1
    assert heartbeat.get_hang(memkv, "j", "s1") == t2


def test_preempt_flag_roundtrip(memkv):
    """Stage-scoped preemption flag (cluster/preempt.py) shares the
    hang flag's machinery but its own namespace — the two must never
    read each other's incidents."""
    from edl_tpu.cluster import preempt

    assert preempt.get_preempt(memkv, "j", "s1") is None
    t = preempt.flag_preempt(memkv, "j", "s1", "podA")
    assert preempt.get_preempt(memkv, "j", "s1") == t
    assert preempt.get_preempt(memkv, "j", "s2") is None   # per-stage
    assert heartbeat.get_hang(memkv, "j", "s1") is None    # namespaced
    heartbeat.flag_hang(memkv, "j", "s1", "podA")
    assert preempt.get_preempt(memkv, "j", "s1") == t      # unaffected
