"""Hash-ring balance and monotonicity (reference test_consistent_hash.py:21-80)."""

from collections import Counter

from edl_tpu.coord.consistent_hash import ConsistentHash


def test_balance_and_monotonicity():
    nodes = [f"10.0.0.{i}:900{i}" for i in range(3)]
    ring = ConsistentHash(nodes)
    keys = [f"service-{i}" for i in range(10000)]
    owners = {k: ring.get_node(k) for k in keys}
    counts = Counter(owners.values())
    assert set(counts) == set(nodes)
    # reference asserts >3000/10000 per node on a 3-node ring
    assert min(counts.values()) > 2000

    # removing a node only moves that node's keys
    ring.remove_node(nodes[0])
    for k, old in owners.items():
        new = ring.get_node(k)
        if old != nodes[0]:
            assert new == old
        else:
            assert new in nodes[1:]

    # re-adding restores the original assignment
    ring.add_node(nodes[0])
    assert all(ring.get_node(k) == owners[k] for k in keys)


def test_empty_ring():
    assert ConsistentHash().get_node("x") is None
