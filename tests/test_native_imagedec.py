"""Native batch image decoder (csrc/imagedec.cc) vs the cv2 path."""

import numpy as np
import pytest

from edl_tpu.data import images
from edl_tpu.native import imagedec
from edl_tpu.native.recordio import RecordReader

pytestmark = pytest.mark.skipif(not imagedec.available(),
                                reason="native imagedec not built")


@pytest.fixture(scope="module")
def records(tmp_path_factory):
    d = tmp_path_factory.mktemp("rec")
    paths = images.write_synthetic_imagenet(str(d), n_files=1, per_file=32,
                                            size=96, classes=7)
    r = RecordReader(paths[0])
    recs = list(r)
    r.close()
    return recs


def test_train_batch_format(records):
    imgs, labels, failed = imagedec.decode_batch(records, 64, seed=3,
                                                 train=True, threads=2)
    assert failed == 0
    assert imgs.shape == (32, 64, 64, 3) and imgs.dtype == np.uint8
    assert labels.dtype == np.int32
    assert (labels >= 0).all() and (labels < 7).all()
    # augmentation actually varies between seeds
    imgs2, _, _ = imagedec.decode_batch(records, 64, seed=4, train=True)
    assert (imgs != imgs2).any()


def test_eval_matches_cv2_path(records):
    # labels exact; pixels within JPEG-decoder/resampler tolerance.
    # The striped synthetic images are adversarial for resampling-phase
    # differences (high-frequency edges), so the tight pixel assertion
    # uses a smooth gradient photo; the stripes get a loose bound.
    import cv2
    imgs, labels, failed = imagedec.decode_batch(records, 64, train=False)
    assert failed == 0
    ref = [images.decode_eval(rec, 64, normalize=False) for rec in records]
    ref_imgs = np.stack([x[0] for x in ref])
    ref_labels = np.asarray([x[1] for x in ref], np.int32)
    np.testing.assert_array_equal(labels, ref_labels)
    diff = np.abs(imgs.astype(np.int32) - ref_imgs.astype(np.int32)).mean()
    assert diff < 15.0, f"native eval diverged from cv2: mean |diff| {diff}"

    y, x = np.mgrid[0:300, 0:400]
    smooth = np.stack([(x * 255 / 400), (y * 255 / 300),
                       ((x + y) * 255 / 700)], -1).astype(np.uint8)
    ok, enc = cv2.imencode(".jpg", smooth, [cv2.IMWRITE_JPEG_QUALITY, 95])
    assert ok
    rec = images.encode_sample(enc.tobytes(), 3)
    nat, lab, failed = imagedec.decode_batch([rec], 224, train=False)
    assert failed == 0 and lab[0] == 3
    want = images.decode_eval(rec, 224, normalize=False)[0]
    d = np.abs(nat[0].astype(np.int32) - want.astype(np.int32)).mean()
    assert d < 3.0, f"smooth-image eval diverged: mean |diff| {d}"


def test_bad_record_isolated(records):
    bad = b"\x01\x00\x00\x00not-a-jpeg"
    imgs, labels, failed = imagedec.decode_batch([bad, records[0]], 64,
                                                 train=False)
    assert failed == 1
    assert labels[0] == -1 and labels[1] >= 0
    assert (imgs[0] == 0).all() and (imgs[1] != 0).any()


def test_image_batches_native_path(records, tmp_path):
    paths = images.write_synthetic_imagenet(str(tmp_path), n_files=1,
                                            per_file=24, size=96, classes=5)
    for normalize in (False, True):
        batches = list(images.ImageBatches(paths, 8, image_size=64,
                                           train=True, num_workers=2,
                                           normalize=normalize,
                                           use_native=True))
        assert len(batches) == 3
        b = batches[0]
        assert b["image"].shape == (8, 64, 64, 3)
        assert b["image"].dtype == (np.float32 if normalize else np.uint8)
        assert b["label"].shape == (8,)
