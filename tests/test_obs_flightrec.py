"""Black-box flight recorder + durable obs history + postmortem
bundles: torn-tail truncation of history segments on reload, rollup
downsampling that preserves windowed quantiles exactly, alert-hold
continuity across an aggregator restart, flight-recorder ring eviction
under pressure, partial bundles when a target is unreachable, incident
log rotation, and the fleet watch doorbell."""

import json
import os
import time

from edl_tpu.obs import exposition
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import rules as obs_rules
from edl_tpu.obs.agg import Aggregator
from edl_tpu.obs.bundle import capture_bundle, find_incident
from edl_tpu.obs.dump import read_trace_dir
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.flightrec import FlightRecorder
from edl_tpu.obs.metrics import Registry
from edl_tpu.obs.rules import Rule, RuleEngine
from edl_tpu.obs.tsdb import TSDB, HistoryStore, _SegmentLog


# -- durable history: CRC'd segments + torn-tail truncation ------------------

def test_segment_log_roundtrip_and_torn_tail_truncation(tmp_path):
    d = str(tmp_path / "raw")
    log = _SegmentLog(d, retention_s=600.0, tier="raw")
    for i in range(5):
        assert log.append({"i": i}, now=1000.0 + i)
    log.close()

    # SIGKILL mid-append: a torn half-record lands at the tail
    segs = sorted(os.listdir(d))
    assert len(segs) == 1
    path = os.path.join(d, segs[0])
    clean_size = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x00\x00\x00\x30garbage-that-is-not-a-full-record")

    reopened = _SegmentLog(d, retention_s=600.0, tier="raw")
    recs = reopened.records()
    assert [r["i"] for r in recs] == [0, 1, 2, 3, 4]
    # the torn tail was truncated away: the file is byte-clean again
    assert os.path.getsize(path) == clean_size
    # a second read sees a clean segment (no re-truncation needed)
    assert [r["i"] for r in reopened.records()] == [0, 1, 2, 3, 4]
    reopened.close()


def test_segment_log_corrupt_middle_stops_segment_read(tmp_path):
    d = str(tmp_path / "raw")
    log = _SegmentLog(d, retention_s=600.0, tier="raw")
    for i in range(3):
        log.append({"i": i}, now=1000.0 + i)
    log.close()
    path = os.path.join(d, sorted(os.listdir(d))[0])
    data = bytearray(open(path, "rb").read())
    data[12] ^= 0xFF                    # flip a byte inside record 0
    open(path, "wb").write(bytes(data))
    # everything from the corruption on is dropped — prefix integrity,
    # never a garbage record
    assert _SegmentLog(d, retention_s=600.0, tier="raw").records() == []


def test_history_replay_restores_windowed_reads(tmp_path):
    hs = HistoryStore(str(tmp_path), retention_s=86400.0,
                      raw_retention_s=600.0, rollup_s=30.0)
    t0 = time.time() - 100.0
    for i in range(11):
        hs.append({("edl_r_total", ()): float(i * 10)}, t0 + i * 10)
    hs.close()

    fresh = TSDB(retention_s=600.0)
    n = HistoryStore(str(tmp_path)).replay(fresh)
    assert n == 11
    r = fresh.rate("edl_r_total", 100.0, now=t0 + 100.0)
    assert abs(r[""] - 1.0) < 1e-6      # 10 per 10s, continuous


def test_rollup_downsampling_preserves_windowed_quantiles(tmp_path):
    """Last-value downsampling is EXACT for cumulative histogram
    buckets: a quantile computed from the rollup tier's points equals
    the raw-window quantile on rollup boundaries."""
    hs = HistoryStore(str(tmp_path), retention_s=86400.0,
                      raw_retention_s=600.0, rollup_s=30.0)
    t0 = 1_700_000_000.0

    def buckets_at(n_obs):
        # observations alternate 0.05s and 0.4s: cumulative le-buckets
        return {("edl_q_seconds_bucket", (("le", "0.1"),)):
                    float((n_obs + 1) // 2),
                ("edl_q_seconds_bucket", (("le", "0.5"),)): float(n_obs),
                ("edl_q_seconds_bucket", (("le", "+Inf"),)): float(n_obs)}

    for i in range(121):                        # one scrape/s for 2 min
        hs.append(buckets_at(i), t0 + i)
    hs.close()

    raw = TSDB(retention_s=600.0)
    for i in range(121):
        raw.ingest(buckets_at(i), t0 + i)
    down = TSDB(retention_s=600.0)
    from edl_tpu.obs.tsdb import _decode_scrape
    rollup_recs = [_decode_scrape(r)
                   for r in _SegmentLog(str(tmp_path / "rollup"),
                                        86400.0, "rollup").records()]
    # birth-seed point + one flush per ~30s over 120s
    assert 4 <= len(rollup_recs) <= 6
    for ts, parsed in rollup_recs:
        down.ingest(parsed, ts)

    # window [t0, t0+120]: both ends are rollup points (the seed point
    # carries the birth baseline), so the downsampled increase per
    # cumulative bucket — and thus the quantile — is EXACT
    for q in (0.5, 0.9, 0.99):
        raw_q = raw.quantile_over_window("edl_q_seconds", q, 120.0,
                                         now=t0 + 120.0)
        down_q = down.quantile_over_window("edl_q_seconds", q, 120.0,
                                           now=t0 + 120.0)
        assert raw_q is not None
        assert down_q == raw_q


# -- alert-hold continuity across restart ------------------------------------

def test_engine_state_survives_export_restore():
    rule = Rule(name="hold", kind="gauge", metric="edl_hold_g", op=">", threshold=5.0,
                window=60.0, for_s=30.0)
    t = TSDB(retention_s=600.0)
    eng = RuleEngine(t, [rule])
    t.ingest({("edl_hold_g", ()): 9.0}, 1000.0)
    assert eng.evaluate(now=1000.0) == []       # pending, not firing
    snap = eng.export_state()

    # restart: a NEW engine over a NEW tsdb, holds re-seeded
    t2 = TSDB(retention_s=600.0)
    eng2 = RuleEngine(t2, [rule])
    assert eng2.restore_state(snap) == 1
    t2.ingest({("edl_hold_g", ()): 9.0}, 1040.0)
    fired = eng2.evaluate(now=1040.0)
    assert [a["alert"] for a in fired] == ["hold"]
    # the hold started BEFORE the restart — continuity, not a reset
    assert fired[0]["pending_since"] == 1000.0

    # a fresh engine WITHOUT the snapshot would still be pending
    t3 = TSDB(retention_s=600.0)
    eng3 = RuleEngine(t3, [rule])
    t3.ingest({("edl_hold_g", ()): 9.0}, 1040.0)
    assert eng3.evaluate(now=1040.0) == []


def test_engine_restore_ignores_stale_and_unknown(monkeypatch):
    rule = Rule(name="hold", kind="gauge", metric="edl_hold_g", op=">", threshold=5.0,
                window=60.0, for_s=30.0)
    eng = RuleEngine(TSDB(), [rule])
    assert eng.restore_state(None) == 0
    assert eng.restore_state({}) == 0
    old = {"ts": time.time() - 3600.0,
           "state": [["hold", "", 1.0, None, 9.0]]}
    assert eng.restore_state(old) == 0          # stale snapshot
    other = {"ts": time.time(),
             "state": [["renamed-rule", "", 1.0, None, 9.0],
                       ["hold", "", 1.0, None, 9.0]]}
    assert eng.restore_state(other) == 1        # unknown rule dropped


def test_aggregator_restart_replays_history_and_holds(tmp_path, memkv):
    hist = str(tmp_path / "hist")
    g = obs_metrics.gauge("edl_fr_restart_g", "restart-continuity probe")
    g.set(9.0)
    rule = Rule(name="fr-hold", kind="gauge", metric="edl_fr_restart_g", op=">",
                threshold=5.0, window=120.0, for_s=3600.0)
    agg = Aggregator(memkv, "job-fr", cache_s=0.0, scrape_interval=0,
                     rules=[rule], incident_dir="", enable_actions=False,
                     history_dir=hist)
    t0 = time.time()
    for i in range(4):
        agg.scrape_once(now=t0 - 30.0 + i * 10.0)
    agg.stop_loop()
    assert agg.engine.to_json()["pending"], \
        "hold should be pending before restart"

    agg2 = Aggregator(memkv, "job-fr", cache_s=0.0, scrape_interval=0,
                      rules=[rule], incident_dir="", enable_actions=False,
                      history_dir=hist)
    # windowed reads are continuous: the replayed TSDB already holds the
    # pre-restart samples before any new scrape
    assert agg2.tsdb.latest("edl_fr_restart_g")
    pend = agg2.engine.to_json()["pending"]
    assert [a["alert"] for a in pend] == ["fr-hold"]
    assert abs(pend[0]["pending_since"] - (t0 - 30.0)) < 1e-6
    # the goodput ledger resumed the SAME observation window: ~30s
    # already watched, not a fresh t0
    assert agg2.goodput.summary(t0)["observed_s"] >= 29.0
    agg2.stop_loop()


# -- flight recorder ---------------------------------------------------------

def test_flightrec_ring_evicts_oldest_under_pressure():
    rec = FlightRecorder("test", capacity=16)
    ev_evicted0 = rec._ev_evicted.value
    for i in range(50):
        rec.record_event({"ts": float(i), "name": f"e{i}"})
    snap = rec.snapshot()
    assert len(snap["events"]) == 16
    # oldest dropped, newest kept, order preserved
    assert [e["name"] for e in snap["events"]] == [f"e{i}"
                                                  for i in range(34, 50)]
    assert rec._ev_evicted.value - ev_evicted0 == 34
    assert snap["capacity"] == 16 and snap["pid"] == os.getpid()


def test_flightrec_snapshot_logs_and_scrape_source():
    import logging
    rec = FlightRecorder("test", capacity=32)
    lr = logging.LogRecord("edl_tpu.x", logging.WARNING, "f.py", 7,
                           "boom %d", (3,), None)
    rec.record_log(lr)
    snap = rec.snapshot()
    assert snap["logs"][0]["msg"] == "boom 3"
    assert snap["logs"][0]["level"] == "WARNING"
    # never scraped: metrics fall back to a live registry render
    assert snap["metrics"]["source"] == "live"
    rec.note_scrape("edl_fake_total 1\n")
    snap = rec.snapshot(limit=5)
    assert snap["metrics"]["source"] == "scrape"
    assert snap["metrics"]["text"] == "edl_fake_total 1\n"


def test_trace_tap_feeds_ring_through_null_tracer():
    from edl_tpu.obs import trace as obs_trace
    rec = FlightRecorder("test", capacity=8)
    tracer = obs_trace.NullTracer()
    tracer.emit("quiet/event", x=1)             # no tap: no record built
    obs_trace.add_tap(rec.record_event)
    try:
        tracer.emit("ring/event", x=2)
        with tracer.span("ring/span"):
            pass
    finally:
        obs_trace.remove_tap(rec.record_event)
    names = [e["name"] for e in rec.snapshot()["events"]]
    assert names == ["ring/event", "ring/span"]
    span = rec.snapshot()["events"][1]
    assert "dur" in span                        # ring-only span measured


# -- postmortem bundles ------------------------------------------------------

def _serve_flightrec(rec):
    srv = MetricsServer(Registry(), host="127.0.0.1").start()
    exposition.register_route("/flightrec", rec.route)
    return srv


def test_bundle_partial_when_target_unreachable(tmp_path, memkv):
    rec = FlightRecorder("trainer", capacity=32)
    rec.record_event({"ts": 1.0, "name": "train/step", "trace_id": "tid-1"})
    srv = _serve_flightrec(rec)
    try:
        targets = {
            "live": {"endpoint": f"127.0.0.1:{srv.port}",
                     "component": "trainer"},
            "dead": {"endpoint": "127.0.0.1:9", "component": "trainer"},
        }
        incident = {"id": "abc123", "name": "alert/straggler",
                    "trace_id": "tid-1", "ts": time.time()}
        tsdb = TSDB(retention_s=600.0)
        tsdb.ingest({("edl_b_g", ()): 1.0}, time.time())
        manifest = capture_bundle(
            memkv, "job-b", rule_name="straggler", incident=incident,
            tsdb=tsdb, out_dir=str(tmp_path), timeout=1.0, targets=targets)
    finally:
        exposition._routes.pop("/flightrec", None)
        srv.stop()

    # one unreachable target makes the bundle PARTIAL, never a failure
    assert manifest["outcome"] == "partial"
    assert list(manifest["missing"]) == ["dead"]
    assert manifest["flightrec_rings"] == 1
    assert manifest["trace_id"] == "tid-1"
    bdir = manifest["path"]
    members = set(manifest["members"])
    assert "tsdb-window.json" in members
    assert "coord-state.json" in members        # MemoryKV.dump_state
    assert "incidents-bundle-0.jsonl" in members
    trace_members = [m for m in members if m.startswith("trace-trainer-")]
    assert len(trace_members) == 1
    # the ring replays as a dump-mergeable trace file joined by trace_id
    events, _skipped = read_trace_dir(bdir)
    assert any(e.get("trace_id") == "tid-1" and e["name"] == "train/step"
               for e in events)
    assert any(e["name"] == "alert/straggler" for e in events)
    man = json.load(open(os.path.join(bdir, "manifest.json")))
    assert man["id"] == "abc123"


def test_bundle_reassembles_from_incident_and_history(tmp_path):
    # durable pieces left behind by a dead aggregator
    hist = HistoryStore(str(tmp_path / "hist"), retention_s=86400.0,
                        rollup_s=30.0)
    t0 = time.time()
    hist.append({("edl_b2_g", ()): 7.0}, t0 - 5.0)
    hist.close()
    inc_dir = tmp_path / "incidents"
    inc_dir.mkdir()
    log = obs_rules.IncidentLog(str(inc_dir), "obs-agg", "job-b2")
    rule = Rule(name="late", kind="gauge", metric="edl_b2_g", op=">", threshold=5.0,
                window=60.0)
    rec = log.write("firing", rule, "", 7.0, trace_id="tid-2")

    found = find_incident(rec["id"], [str(inc_dir)])
    assert found is not None and found["trace_id"] == "tid-2"
    manifest = capture_bundle(
        None, "job-b2", rule_name="late", incident=found,
        history=HistoryStore(str(tmp_path / "hist")),
        out_dir=str(tmp_path / "bundles"), targets={}, now=t0,
        source="reassembled")
    assert manifest["outcome"] == "ok" and manifest["source"] == "reassembled"
    window = json.load(open(os.path.join(manifest["path"],
                                         "tsdb-window.json")))
    assert any(s["name"] == "edl_b2_g" for s in window["series"])


# -- incident rotation + rotated files in the merge --------------------------

def test_incident_log_rotates_and_dump_reads_rotated(tmp_path):
    log = obs_rules.IncidentLog(str(tmp_path), "obs-agg", "job-r",
                                max_bytes=600)
    rule = Rule(name="noisy", kind="gauge", metric="edl_n_g", op=">", threshold=0.0,
                window=60.0)
    ids = [log.write("firing", rule, "", 1.0)["id"] for _ in range(12)]
    rotated = [p for p in os.listdir(tmp_path) if p.endswith(".jsonl.1")]
    assert len(rotated) == 1
    live = [p for p in os.listdir(tmp_path)
            if p.startswith("incidents-") and p.endswith(".jsonl")]
    live_ids = {json.loads(ln)["id"]
                for ln in open(os.path.join(tmp_path, live[0]))}
    # the merge view reads live + rotated generations: the timeline
    # holds strictly more than the live file alone
    events, _ = read_trace_dir(str(tmp_path))
    got = {e.get("id") for e in events}
    assert ids[-1] in got
    assert live_ids < got <= set(ids)
    # --incident reassembly finds records in rotated generations too
    assert find_incident(ids[-1], [str(tmp_path)]) is not None


# -- fleet watch doorbell ----------------------------------------------------

def test_fleet_view_watch_doorbell_and_poll_fallback(memkv, monkeypatch):
    from edl_tpu.gateway import fleet
    view = fleet.FleetView(memkv, "job-w", period=30.0)
    try:
        assert view._watch        # MemoryKV has wait(): doorbell mode
        reg = fleet.advertise(memkv, "job-w", "r0",
                              {"endpoint": "h:1"}, ttl=5)
        # a 30s poll period would miss this for half a minute; the
        # doorbell delivers it in well under a second
        deadline = time.monotonic() + 5.0
        while "r0" not in view.replicas():
            assert time.monotonic() < deadline, "watch never woke the view"
            time.sleep(0.02)
        reg.stop()
    finally:
        view.stop()

    monkeypatch.setenv("EDL_TPU_FLEET_WATCH", "0")
    view2 = fleet.FleetView(memkv, "job-w", period=0.05)
    try:
        assert not view2._watch   # env kill-switch: plain polling
        reg = fleet.advertise(memkv, "job-w", "r1",
                              {"endpoint": "h:2"}, ttl=5)
        deadline = time.monotonic() + 5.0
        while "r1" not in view2.replicas():
            assert time.monotonic() < deadline
            time.sleep(0.02)
        reg.stop()
    finally:
        view2.stop()
