"""Delta replication plane (edl_tpu/memstate/delta): chain hashing and
torn-chain detection, freshest-recoverable cut selection, service-side
commit verification, and the end-to-end failover claim — a restore from
base + streamed chains lands PAST the committed checkpoint, survives
the owner pod's death, and every break demotes chain -> peer-full ->
storage, with the recovery record carrying ``restore_source``.

Same in-process strategy as tests/test_memstate.py: pods are
(StateCacheService, RpcServer) pairs over a MemoryKV store on the
8-device virtual CPU mesh.
"""

import functools
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu import memstate
from edl_tpu.cluster.state import State
from edl_tpu.memstate import delta
from edl_tpu.memstate import restore as ms_restore
from edl_tpu.memstate import shards as ms_shards
from edl_tpu.memstate.service import StateCacheService
from edl_tpu.memstate.tee import StateCacheTee
from edl_tpu.rpc import chunks
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer


# -- chain format -------------------------------------------------------------
def _mk_manifest(payload: dict[str, bytes]) -> dict:
    return {k: {"crc": zlib.crc32(v), "nbytes": len(v), "dtype": "uint8",
                "shape": [len(v)], "index": [[0, len(v)]],
                "gshape": [len(v)], "leaf": k}
            for k, v in payload.items()}


def _mk_chain(base_step: int, steps: list[int], payloads=None) -> list[dict]:
    """Well-formed record dicts (manifest-listing shape) for ``steps``."""
    prev, out = delta.anchor_hash(base_step), []
    for i, step in enumerate(steps):
        man = _mk_manifest(payloads[i] if payloads else {"k": b"x" * (i + 1)})
        h = delta.chain_hash(prev, step, i + 1, man)
        out.append({"step": step, "seq": i + 1, "prev": prev, "hash": h,
                    "shards": man, "nproc": 1, "has_meta": True})
        prev = h
    return out


def test_wire_owner_roundtrip_and_reserved_prefix():
    w = delta.wire_owner("pod:a", "3", 7)
    assert delta.parse_wire_owner(w) == ("pod:a", "3", 7)
    # pod ids with colons survive (rsplit), plain owners parse to None
    assert delta.parse_wire_owner("pod-a") is None
    assert delta.parse_wire_owner("~delta:junk") is None


def test_chain_hash_covers_manifest_and_linkage():
    man = _mk_manifest({"k": b"abc"})
    h = delta.chain_hash(delta.anchor_hash(5), 10, 1, man)
    assert h == delta.chain_hash(delta.anchor_hash(5), 10, 1, dict(man))
    assert h != delta.chain_hash(delta.anchor_hash(6), 10, 1, man)
    man2 = _mk_manifest({"k": b"abd"})
    assert h != delta.chain_hash(delta.anchor_hash(5), 10, 1, man2)


def test_intact_prefix_full_and_torn():
    recs = _mk_chain(7, [10, 20, 30])
    assert [r["step"] for r in delta.intact_prefix(7, recs)] == [10, 20, 30]
    # tamper the middle record's manifest: prefix stops BEFORE it
    torn = [dict(r) for r in recs]
    torn[1] = dict(torn[1], shards=_mk_manifest({"k": b"evil"}))
    assert [r["step"] for r in delta.intact_prefix(7, torn)] == [10]
    # a seq hole is a break, not a reorder opportunity
    assert delta.intact_prefix(7, [recs[0], recs[2]]) == [recs[0]]
    # wrong anchor (base mismatch) yields nothing
    assert delta.intact_prefix(8, recs) == []


def _listing(chains: dict) -> dict:
    """cache_delta_manifest() shape from {(owner, src): (base, records)}."""
    return {f"{o}/{s}": {"owner": o, "src": s, "base_step": b, "records": r}
            for (o, s), (b, r) in chains.items()}


def test_plan_freshest_picks_common_cut():
    a = _mk_chain(7, [10, 20, 30])
    b = _mk_chain(7, [10, 20])
    plan = delta.plan_freshest(7, {"pa": _listing({("pa", "0"): (7, a)}),
                                   "pb": _listing({("pb", "0"): (7, b)})})
    # nproc=1 per record but two producers observed -> demoted
    assert plan is None
    a2 = [dict(r, nproc=2) for r in _mk_chain(7, [10, 20, 30])]
    b2 = [dict(r, nproc=2) for r in _mk_chain(7, [10, 20])]
    # rebuild hashes for the nproc field change? nproc is NOT hashed —
    # the cut rule reads it from the record as a claim
    plan = delta.plan_freshest(7, {"pa": _listing({("pa", "0"): (7, a2)}),
                                   "pb": _listing({("pb", "0"): (7, b2)})})
    assert plan is not None and plan["step"] == 20  # pb stops at 20
    assert plan["meta"]  # the step-F sidecar has holders


def test_plan_freshest_torn_chain_demotes_and_max_step_bounds():
    recs = _mk_chain(7, [10, 20, 30])
    listing = {"pa": _listing({("pa", "0"): (7, recs)})}
    assert delta.plan_freshest(7, listing)["step"] == 30
    assert delta.plan_freshest(7, listing, max_step=20)["step"] == 20
    # stale base: chains over another base are invisible
    assert delta.plan_freshest(8, listing) is None
    # torn at seq 2 -> freshest intact is 10
    torn = [recs[0], dict(recs[1], hash="0" * 40), recs[2]]
    assert delta.plan_freshest(
        7, {"pa": _listing({("pa", "0"): (7, torn)})})["step"] == 10


def test_plan_freshest_overlay_takes_latest_record_per_key():
    p1 = {"k1": b"v1-old", "k2": b"v2"}
    p2 = {"k1": b"v1-new"}
    recs = _mk_chain(7, [10, 20], payloads=[p1, p2])
    plan = delta.plan_freshest(7, {"pa": _listing({("pa", "0"): (7, recs)})})
    assert plan["step"] == 20
    # k1 resolves to the seq-2 record's copy, k2 stays at seq 1
    assert plan["overlay"]["k1"][1][0][2] == delta.wire_owner("pa", "0", 2)
    assert plan["overlay"]["k2"][1][0][2] == delta.wire_owner("pa", "0", 1)


# -- service-side commit verification ----------------------------------------
@pytest.fixture
def pod(memkv):
    srv = RpcServer("127.0.0.1", 0)
    svc = StateCacheService(memkv, "job", "pod-a")
    srv.register_instance(svc)
    srv.start()
    reg = memstate.advertise(memkv, "job", "pod-a",
                             f"127.0.0.1:{srv.port}", ttl=30)
    client = RpcClient(f"127.0.0.1:{srv.port}")
    yield svc, srv, client
    client.close()
    reg.stop()
    srv.stop()


def _stage_record(client, owner, src, base, rec, payload):
    wire = delta.wire_owner(owner, src, rec["seq"])
    for key, data in payload.items():
        chunks.push_bytes(
            functools.partial(client.call, "cache_put_chunk", owner=wire,
                              step=rec["step"], key=key), data)
    return client.call(
        "cache_delta_commit", owner=owner, src=src, base_step=base,
        step=rec["step"], seq=rec["seq"], prev_hash=rec["prev"],
        chain_hash=rec["hash"], manifest=rec["shards"], nproc=1,
        meta=b"{}")


def test_delta_commit_links_rejects_and_dedups(pod):
    svc, _srv, client = pod
    pays = [{"k": b"x"}, {"k": b"xy"}, {"k": b"xyz"}]
    recs = _mk_chain(7, [10, 20, 30], payloads=pays)
    assert _stage_record(client, "pod-a", "0", 7, recs[0], pays[0])["ok"]
    # seq hole: record 3 before record 2
    r = _stage_record(client, "pod-a", "0", 7, recs[2], pays[2])
    assert not r["ok"] and r["reason"] == "link"
    assert _stage_record(client, "pod-a", "0", 7, recs[1], pays[1])["ok"]
    # idempotent re-push of a sealed record
    r = _stage_record(client, "pod-a", "0", 7, recs[1], pays[1])
    assert r["ok"] and r.get("dup")
    # a wrong chain hash never lands
    bad = dict(recs[2], hash="0" * 40)
    r = _stage_record(client, "pod-a", "0", 7, bad, pays[2])
    assert not r["ok"] and r["reason"] == "hash"
    # a chain over an OLDER base is stale once this one exists
    old = _mk_chain(5, [6], payloads=[{"k": b"z"}])[0]
    r = _stage_record(client, "pod-a", "0", 5, old, {"k": b"z"})
    assert not r["ok"] and r["reason"] == "stale"
    listing = client.call("cache_delta_manifest")
    assert [x["seq"] for x in listing["pod-a/0"]["records"]] == [1, 2]
    # the sealed records verify end to end as an intact prefix
    assert len(delta.intact_prefix(7, listing["pod-a/0"]["records"])) == 2


def test_delta_commit_payload_crc_verified(pod):
    svc, _srv, client = pod
    rec = _mk_chain(7, [10], payloads=[{"k": b"good"}])[0]
    from edl_tpu.utils.exceptions import EdlInternalError
    with pytest.raises(EdlInternalError):
        _stage_record(client, "pod-a", "0", 7, rec, {"k": b"evil"})
    assert client.call("cache_delta_manifest") == {}


def test_delta_chain_cap_enforced(pod, monkeypatch):
    from edl_tpu.utils import constants
    monkeypatch.setattr(constants, "DELTA_MAX_CHAIN", 2)
    pays = [{"k": bytes([i])} for i in range(3)]
    recs = _mk_chain(7, [10, 20, 30], payloads=pays)
    svc, _srv, client = pod
    for i in range(2):
        assert _stage_record(client, "pod-a", "0", 7, recs[i], pays[i])["ok"]
    r = _stage_record(client, "pod-a", "0", 7, recs[2], pays[2])
    assert not r["ok"] and r["reason"] == "full"


def test_checkpoint_commit_compacts_older_base_chains(pod):
    svc, _srv, client = pod
    pay = {"k": b"v"}
    rec = _mk_chain(7, [10], payloads=[pay])[0]
    assert _stage_record(client, "pod-a", "0", 7, rec, pay)["ok"]
    assert client.call("cache_delta_manifest")
    # a full set committed at step 10 subsumes every chain over base 7
    data = b"d" * 64
    chunks.push_bytes(
        functools.partial(client.call, "cache_put_chunk", owner="pod-a",
                          step=10, key="s"), data)
    manifest = {"s": {"crc": zlib.crc32(data), "nbytes": len(data),
                      "dtype": "uint8", "shape": [64],
                      "index": [[0, 64]], "gshape": [64], "leaf": "s"}}
    assert client.call("cache_commit", owner="pod-a", step=10,
                       manifest=manifest, meta=b"{}")["ok"]
    assert client.call("cache_delta_manifest") == {}
    # and a fresh chain over the dead base is refused as stale
    rec2 = _mk_chain(7, [20], payloads=[pay])[0]
    r = _stage_record(client, "pod-a", "0", 7, rec2, pay)
    assert not r["ok"] and r["reason"] == "stale"


# -- end to end: replicator -> service -> restore -----------------------------
def _two_pods(memkv):
    pods = {}
    for pid in ("pod-a", "pod-b"):
        srv = RpcServer("127.0.0.1", 0)
        svc = StateCacheService(memkv, "job", pid)
        srv.register_instance(svc)
        srv.start()
        reg = memstate.advertise(memkv, "job", pid,
                                 f"127.0.0.1:{srv.port}", ttl=30)
        pods[pid] = (svc, srv, reg)
    return pods


def _teardown(pods):
    for _svc, srv, reg in pods.values():
        reg.stop()
        srv.stop()


def _state_and_abstract():
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    state = {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8), sh),
        "b": jax.device_put(np.linspace(0, 1, 6).astype(np.float32), rep),
        "step": jax.device_put(np.int32(7), rep),
    }
    abstract = {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=rep),
        "b": jax.ShapeDtypeStruct((6,), jnp.float32, sharding=rep),
        "step": jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
    }
    return state, abstract


def _wait_sealed(memkv, step, timeout=30.0):
    deadline = time.monotonic() + timeout
    while memstate.read_committed_step(memkv, "job") != step:
        assert time.monotonic() < deadline, "tee never sealed the step"
        time.sleep(0.02)


def _commit_base(memkv, tmp_path, state):
    """Full set at step 7 through the real tee + checkpoint manager."""
    from edl_tpu.train.checkpoint import CheckpointManager
    tee = StateCacheTee(memkv, "job", "pod-a")
    ck = CheckpointManager(str(tmp_path / "ck"), tee=tee)
    assert ck.save(7, state, State(total_batch_size=32))
    ck.wait()
    _wait_sealed(memkv, 7)
    return ck


def _advance(state, step: int):
    """The post-training state a delta record captures."""
    out = dict(state)
    out["w"] = state["w"] + np.float32(step)
    out["step"] = jax.device_put(np.int32(step), state["step"].sharding)
    return out


def test_delta_restore_beats_committed_base(memkv, tmp_path):
    """Freshest intact chain wins: the restore lands at the delta step,
    not the checkpoint step, and survives the owner pod's death."""
    pods = _two_pods(memkv)
    try:
        state, abstract = _state_and_abstract()
        ck = _commit_base(memkv, tmp_path, state)
        rep = delta.DeltaReplicator(memkv, "job", "pod-a", every=2)
        try:
            rep.rebase(7, state)
            assert not rep.want(7) and not rep.want(9)
            assert rep.want(10)
            s10 = _advance(state, 10)
            rep.stage(10, s10, State(total_batch_size=64))
            s12 = _advance(s10, 12)
            rep.stage(12, s12, State(total_batch_size=64))
            assert rep.flush(30)
        finally:
            rep.close()
        # the probe agrees with the plan: base 7, freshest 12
        assert memstate.probe_freshest(memkv, "job") == (7, 12)
        # replica landed on pod-b for the chain AND the base set
        deadline = time.monotonic() + 30
        while ("pod-a" not in pods["pod-b"][0].cache_manifest()
               or "pod-a/0" not in pods["pod-b"][0].cache_delta_manifest()):
            assert time.monotonic() < deadline, "replication never landed"
            time.sleep(0.02)

        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=7,
                                     delta_step=12)
        assert res is not None
        got, meta_json, info = res
        assert info["step"] == 12
        assert np.array_equal(np.asarray(got["w"]), np.asarray(s12["w"]))
        assert int(np.asarray(got["step"])) == 12
        # the sidecar rides the delta record, not the base
        assert State().from_json(meta_json).total_batch_size == 64
        # the unreachable target is a miss, never a different step
        assert ms_restore.try_restore(memkv, "job", abstract, expect_step=7,
                                      delta_step=14) is None
        # owner death: pod-b's replica chain alone serves the restore
        pods["pod-a"][2].stop()
        pods["pod-a"][1].stop()
        memkv.delete("/edl_tpu/job/memstate/nodes/pod-a")
        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=7,
                                     delta_step=12)
        assert res is not None
        got, _meta, info = res
        assert info["step"] == 12 and info["peers"] == ["pod-b"]
        assert np.array_equal(np.asarray(got["w"]), np.asarray(s12["w"]))
        ck.close()
    finally:
        _teardown({k: v for k, v in pods.items() if k != "pod-a"})


def test_torn_chain_demotes_to_peer_full_then_storage(memkv, tmp_path):
    """The fallback matrix: CRC-broken chain -> delta restore misses;
    the plain peer-full restore still serves the base; with the cache
    gone entirely the storage path remains."""
    pods = _two_pods(memkv)
    try:
        state, abstract = _state_and_abstract()
        ck = _commit_base(memkv, tmp_path, state)
        rep = delta.DeltaReplicator(memkv, "job", "pod-a", every=2)
        try:
            rep.rebase(7, state)
            rep.stage(10, _advance(state, 10), State())
            assert rep.flush(30)
        finally:
            rep.close()
        committed, freshest = memstate.probe_freshest(memkv, "job")
        assert (committed, freshest) == (7, 10)
        # tear the chain on EVERY holder (hash no longer matches)
        for svc, _srv, _reg in pods.values():
            for ch in svc._chains.values():
                for rec in ch.records:
                    rec.manifest = {k: dict(v, crc=(int(v["crc"]) ^ 1))
                                    for k, v in rec.manifest.items()}
        assert memstate.probe_freshest(memkv, "job") == (7, None)
        assert ms_restore.try_restore(memkv, "job", abstract, expect_step=7,
                                      delta_step=10) is None
        # chain -> peer-full: the base still restores at the committed step
        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=7)
        assert res is not None and res[2]["step"] == 7
        assert np.array_equal(np.asarray(res[0]["w"]), np.asarray(state["w"]))
        # peer-full -> storage: all adverts gone, Orbax still has step 7
        for pid in list(pods):
            pods[pid][2].stop()
            memkv.delete(f"/edl_tpu/job/memstate/nodes/{pid}")
        assert ms_restore.try_restore(memkv, "job", abstract,
                                      expect_step=7) is None
        stored = ck.restore(abstract)
        assert stored is not None
        assert np.array_equal(np.asarray(stored[0]["w"]),
                              np.asarray(state["w"]))
        ck.close()
    finally:
        _teardown(pods)


def test_replicator_diffs_only_changed_shards(memkv, tmp_path):
    """Record 2 carries only the keys whose CRC changed since record 1
    (the bytes/step vs full-shard win the bench section measures)."""
    pods = _two_pods(memkv)
    try:
        state, _abstract = _state_and_abstract()
        _commit_base(memkv, tmp_path, state).close()
        rep = delta.DeltaReplicator(memkv, "job", "pod-a", every=1)
        try:
            rep.rebase(7, state)
            s8 = _advance(state, 8)  # w + step change; b does not
            rep.stage(8, s8, State())
            s9 = dict(s8)            # ONLY step changes in record 2
            s9["step"] = jax.device_put(np.int32(9), s8["step"].sharding)
            rep.stage(9, s9, State())
            assert rep.flush(30)
        finally:
            rep.close()
        listing = pods["pod-a"][0].cache_delta_manifest()
        recs = listing["pod-a/0"]["records"]
        assert [r["seq"] for r in recs] == [1, 2]
        leaves1 = {v["leaf"] for v in recs[0]["shards"].values()}
        leaves2 = {v["leaf"] for v in recs[1]["shards"].values()}
        assert "['b']" not in leaves1 and "['w']" in leaves1
        assert leaves2 == {"['step']"}
    finally:
        _teardown(pods)


def test_replicator_cap_saturates_staging(memkv, tmp_path):
    pods = _two_pods(memkv)
    try:
        state, _abstract = _state_and_abstract()
        _commit_base(memkv, tmp_path, state).close()
        rep = delta.DeltaReplicator(memkv, "job", "pod-a", every=1,
                                    max_chain=2)
        try:
            rep.rebase(7, state)
            assert rep.want(8)
            rep.stage(8, _advance(state, 8), State())
            assert rep.want(9)
            rep.stage(9, _advance(state, 9), State())
            assert not rep.want(10)  # saturated until the next rebase
            assert rep.flush(30)
            rep.rebase(10, _advance(state, 10))
            assert rep.want(11)
        finally:
            rep.close()
    finally:
        _teardown(pods)


# -- recovery record carries restore_source=delta ----------------------------
def test_recovery_record_restore_source_delta(memkv):
    from edl_tpu.cluster.recovery import (
        summarize_recovery, write_launcher_half, write_trainer_half,
    )
    write_launcher_half(memkv, "j", "stg", "p1",
                        {"detect": 10.0, "killed": 11.0, "barrier": 12.0,
                         "spawn": 13.0})
    write_trainer_half(memkv, "j", "stg", "p1", restored=15.0,
                       first_step=16.0, restore_source="delta")
    [entry] = summarize_recovery(memkv, "j")
    assert entry["restore_source"] == "delta"
    # any pod demoted to storage downgrades the stage's source
    write_trainer_half(memkv, "j", "stg", "p2", restored=15.5,
                       first_step=16.5, restore_source="storage")
    [entry] = summarize_recovery(memkv, "j")
    assert entry["restore_source"] == "storage"
