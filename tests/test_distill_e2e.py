"""Distillation end-to-end with real models: a trained TPU teacher
served over the wire through a live discovery server measurably
improves a student trained on noisy labels — the README.md:83-85 effect
at toy scale — plus the DistillReader QPS probe.

Reference flow: example/distill/mnist_distill/train_with_fleet.py:1-300.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples", "distill"))


@pytest.mark.slow
def test_distilled_student_beats_noisy_baseline(tmp_path):
    import train_mnist_distill as ex

    summary = ex.main([
        "--role", "local", "--classes", "6", "--train_n", "256",
        "--label_noise", "0.7", "--student_epochs", "20",
        "--out", str(tmp_path / "summary.json"),
    ])
    # the teacher masters the clean task ...
    assert summary["teacher_acc"] > 0.95, summary
    # ... and transfers it through the service: the distilled student
    # recovers most of the noise-destroyed accuracy
    assert summary["distill_acc"] > 0.9, summary
    assert summary["gain"] >= 0.05, summary


def test_qps_probe_reports_throughput():
    from qps_tool import run_probe

    out = run_probe(nop=True, batches=120, batch_size=16, warmup=10)
    assert out["metric"] == "distill_reader_qps"
    assert out["value"] > 0, out
    assert out["unit"] == "samples/s"
