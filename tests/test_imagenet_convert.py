"""ImageNet tree -> recordio converter
(examples/collective/imagenet_to_recordio.py): real JPEGs in a class
tree, deterministic shard membership, resumability, and that the
output feeds the training pipeline unchanged."""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples", "collective"))

from imagenet_to_recordio import convert, shard_of  # noqa: E402

from edl_tpu.data import images  # noqa: E402
from edl_tpu.native.recordio import RecordReader  # noqa: E402


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    """3 wnid classes x 5 real JPEGs, varied sizes like a camera dump."""
    import cv2
    root = tmp_path_factory.mktemp("imagenet") / "train"
    rng = np.random.default_rng(0)
    wnids = ["n01440764", "n01443537", "n02102040"]
    for ci, wnid in enumerate(wnids):
        d = root / wnid
        d.mkdir(parents=True)
        for i in range(5):
            h, w = int(rng.integers(80, 200)), int(rng.integers(80, 200))
            img = np.full((h, w, 3), 40 * ci, np.uint8)
            img += rng.integers(0, 40, img.shape).astype(np.uint8)
            ok, enc = cv2.imencode(".jpg", img)
            assert ok
            (d / f"img_{i}.JPEG").write_bytes(enc.tobytes())
    return str(root), wnids


def _read_all(paths):
    out = []
    for p in sorted(paths):
        r = RecordReader(p)
        for rec in r:
            jpg, label = images.decode_sample(rec)
            out.append((len(jpg), label))
        r.close()
    return out


def test_convert_roundtrip(tree, tmp_path):
    src, wnids = tree
    out = str(tmp_path / "rec")
    written = convert(src, out, "train", shards=4, verbose=False)
    assert len(written) <= 4 and written
    samples = _read_all(written)
    assert len(samples) == 15
    # labels are sorted-wnid indices 0..2, 5 each
    labels = sorted(lab for _, lab in samples)
    assert labels == sorted([0] * 5 + [1] * 5 + [2] * 5)
    # class mapping file written
    classes = open(os.path.join(out, "train-classes.txt")).read().split()
    assert classes == sorted(wnids)


def test_convert_resumable(tree, tmp_path):
    src, _ = tree
    out = str(tmp_path / "rec")
    first = convert(src, out, "train", shards=4, verbose=False)
    before = _read_all(first)
    # wipe one shard: re-run must rewrite ONLY it, identically
    victim = first[0]
    os.unlink(victim)
    second = convert(src, out, "train", shards=4, verbose=False)
    assert second == [victim]
    assert sorted(_read_all(first)) == sorted(before)
    # fully complete -> no-op
    assert convert(src, out, "train", shards=4, verbose=False) == []


def test_more_shards_than_samples_still_completes(tree, tmp_path):
    # empty shards must finalize too, or re-runs re-stream forever
    src, _ = tree
    out = str(tmp_path / "rec")
    written = convert(src, out, "train", shards=64, verbose=False)
    assert len(written) == 64
    assert len(_read_all(written)) == 15
    assert convert(src, out, "train", shards=64, verbose=False) == []


def test_shard_membership_stable(tree):
    src, _ = tree
    # membership is a pure function of relpath: resuming can't shuffle
    assert shard_of("n01440764/img_0.JPEG", 8) == shard_of(
        "n01440764/img_0.JPEG", 8)


def test_output_feeds_training_pipeline(tree, tmp_path):
    src, _ = tree
    out = str(tmp_path / "rec")
    convert(src, out, "train", shards=2, verbose=False)
    import glob
    paths = sorted(glob.glob(os.path.join(out, "train-*.rec")))
    batches = list(images.ImageBatches(paths, 4, image_size=64, train=True,
                                       num_workers=2, drop_remainder=False))
    n = sum(len(b["label"]) for b in batches)
    assert n == 15
    assert batches[0]["image"].shape[1:] == (64, 64, 3)
    assert all(0 <= int(l) < 3 for b in batches for l in b["label"])
