"""Train engine: jitted DP/FSDP steps, checkpoint/resume, LR rules.

The linear-regression flow is the reference's fit_a_line smoke workload
(example/fit_a_line) run TPU-natively on the 8-device CPU mesh.
"""

import numpy as np
import optax
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from edl_tpu.parallel import MeshSpec, ShardingRules
from edl_tpu.train import (
    CheckpointManager, ElasticTrainer, TrainConfig, TrainState,
    cosine_warmup, piecewise_decay, scale_lr_for_batch,
)
from edl_tpu.train.state import abstract_like

RNG = np.random.default_rng(0)
W_TRUE = RNG.normal(size=(13, 1)).astype(np.float32)


def make_batches(n_batches=8, bs=16):
    for _ in range(n_batches):
        x = RNG.normal(size=(bs, 13)).astype(np.float32)
        y = x @ W_TRUE + 0.01 * RNG.normal(size=(bs, 1)).astype(np.float32)
        yield {"x": x, "y": y}


def linear_loss(params, extra, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    loss = jnp.mean((pred - batch["y"]) ** 2)
    return loss, (extra, {"mse": loss})


def init_linear():
    return {"w": jnp.zeros((13, 1)), "b": jnp.zeros((1,))}, None


def make_trainer(tmp_path=None, spec=None, **cfg_kw):
    cfg = TrainConfig(mesh_spec=spec or MeshSpec(),
                      checkpoint_dir=str(tmp_path) if tmp_path else "",
                      log_every=0, **cfg_kw)
    return ElasticTrainer(linear_loss, cfg)


def test_fit_linear_regression_converges():
    tr = make_trainer()
    state = tr.create_state(init_linear, optax.sgd(0.1))
    state, meta = tr.fit(state, __import__("edl_tpu.cluster.state", fromlist=["State"]).State(),
                         lambda e: make_batches(30), epochs=2)
    w = np.asarray(state.params["w"])
    assert np.allclose(w, W_TRUE, atol=0.05)
    assert meta.next_epoch == 2
    assert len(meta.epochs) == 2 and meta.epochs[0].world_size == 8


def test_checkpoint_resume(tmp_path):
    tr = make_trainer(tmp_path)
    state, meta = tr.restore_or_create(init_linear, optax.sgd(0.1))
    assert meta.next_epoch == 0
    state, meta = tr.fit(state, meta, lambda e: make_batches(5), epochs=1)
    tr.ckpt.close()

    tr2 = make_trainer(tmp_path)
    state2, meta2 = tr2.restore_or_create(init_linear, optax.sgd(0.1))
    assert meta2.next_epoch == 1
    assert int(state2.step) == 5
    np.testing.assert_array_equal(np.asarray(state2.params["w"]),
                                  np.asarray(state.params["w"]))
    # resume continues into epoch 1 only
    state2, meta2 = tr2.fit(state2, meta2, lambda e: make_batches(5), epochs=2)
    assert int(state2.step) == 10
    assert [e.epoch_no for e in meta2.epochs] == [0, 1]
    tr2.ckpt.close()


def test_adjust_registry_fires_on_world_change(tmp_path):
    tr = make_trainer(tmp_path)
    state, meta = tr.restore_or_create(init_linear, optax.sgd(0.1))
    state, meta = tr.fit(state, meta, lambda e: make_batches(3), epochs=1)
    tr.ckpt.close()

    # resize: 8 -> 4 devices
    calls = []
    cfg = TrainConfig(mesh_spec=MeshSpec(dp=4), checkpoint_dir=str(tmp_path),
                      log_every=0)
    tr2 = ElasticTrainer(linear_loss, cfg, devices=jax.devices()[:4])
    tr2.adjust.register(lambda old, new, st: calls.append((old, new)))
    state2, meta2 = tr2.restore_or_create(init_linear, optax.sgd(0.1))
    assert calls == [(8, 4)]
    tr2.ckpt.close()


def test_fsdp_shards_params_and_momentum():
    spec = MeshSpec(dp=1, fsdp=8)
    cfg = TrainConfig(mesh_spec=spec, log_every=0)
    tr = ElasticTrainer(linear_loss, cfg)

    def init_big():
        return {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}, None

    logical = {"w": ("embed", None), "b": (None,)}
    state = tr.create_state(init_big, optax.adam(1e-3), param_logical=logical)
    assert state.params["w"].sharding.spec == P("fsdp")
    # optimizer momentum inherited the sharding through propagation
    mu_w = state.opt_state[0].mu["w"]
    assert mu_w.sharding.spec == P("fsdp")
    # and the step still runs
    batch = {"x": np.ones((8, 16), np.float32), "y": np.ones((8, 8), np.float32)}

    def loss(params, extra, b, rng):
        pred = b["x"] @ params["w"] + params["b"]
        l = jnp.mean((pred - b["y"]) ** 2)
        return l, (extra, {})
    tr2 = ElasticTrainer(loss, cfg)
    gb = __import__("edl_tpu.parallel.sharding", fromlist=["shard_host_batch"]
                    ).shard_host_batch(batch, tr.mesh)
    state2, metrics = tr2.step_fn(state, gb, jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))


def test_lr_schedules():
    assert scale_lr_for_batch(0.1, 1024) == pytest.approx(0.4)
    s = cosine_warmup(0.4, total_steps=100, warmup_steps=10)
    assert float(s(0)) == pytest.approx(0.0)
    assert float(s(10)) == pytest.approx(0.4)
    assert float(s(100)) < 0.01
    p = piecewise_decay(0.4, [30, 60], gamma=0.1, warmup_steps=5)
    assert float(p(5)) == pytest.approx(0.4)
    assert float(p(31)) == pytest.approx(0.04)
    assert float(p(61)) == pytest.approx(0.004)


def test_evaluate_masks_ragged_batches():
    """Per-example metrics over batches not divisible by the 8-way mesh:
    padding must be masked out exactly and jit compiled once."""
    tr = make_trainer()
    state = tr.create_state(init_linear, optax.sgd(0.1))

    def metric_fn(params, extra, batch):
        return {"v": batch["x"][:, 0]}

    vals = [np.arange(10, dtype=np.float32), np.arange(3, dtype=np.float32)]
    batches = [{"x": np.stack([v] * 13, axis=1)} for v in vals]
    out = tr.evaluate(state, batches, metric_fn)
    expect = float(np.concatenate(vals).mean())
    assert abs(out["v"] - expect) < 1e-6
    # second call reuses the cached jitted step (no retrace)
    out2 = tr.evaluate(state, batches, metric_fn)
    assert out2 == out
    assert len(tr._eval_cache) == 1


@pytest.mark.slow
def test_evaluate_uneven_batches_two_processes(tmp_path):
    """evaluate() must not hang when hosts yield different batch counts
    (per-batch has-next agreement; round-2 verdict weak #4).  Rank 0
    feeds 3 batches, rank 1 feeds 1; both must agree on the weighted
    mean over the 16 real rows."""
    import subprocess
    import sys
    import os as _os

    from edl_tpu.utils.network import find_free_port

    port = find_free_port()
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    script = _os.path.join(repo, "tests", "helpers", "eval_uneven.py")
    env = dict(_os.environ)
    env["PYTHONPATH"] = repo + _os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, script, str(r), str(port)],
                              stdout=subprocess.PIPE, text=True, env=env)
             for r in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        assert p.returncode == 0, out
        outs.append(out)
    import json as _json
    results = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("EVAL_RESULT")][0]
        results.append(_json.loads(line.split(" ", 1)[1]))
    # expected: mean over rank0's 3 batches (12 rows) + rank1's 1 (4 rows)
    vals = [0 * 100 + b * 10 + i for b in range(3) for i in range(4)] + \
           [1 * 100 + 0 * 10 + i for i in range(4)]
    expected = sum(vals) / len(vals)
    for r in results:
        assert abs(r["mean_x"] - expected) < 1e-3, (results, expected)


def test_maybe_preempt_unit(memkv, monkeypatch):
    """Preempt check in isolation (single-process: WALL-CLOCK cadence,
    ADVICE r5): the first step checks, a step inside the cadence
    window never reads the store, and a due check with the flag
    visible checkpoints-and-exits PREEMPT_EXIT_CODE."""
    from edl_tpu.cluster import preempt
    from edl_tpu.cluster.env import TrainerEnv
    from edl_tpu.utils import constants

    monkeypatch.setenv("EDL_TPU_JOB_ID", "pj")
    monkeypatch.setenv("EDL_TPU_POD_ID", "pod1")
    monkeypatch.setenv("EDL_TPU_CLUSTER_STAGE", "stg")
    tenv = TrainerEnv()
    tr = ElasticTrainer(lambda *a: None, TrainConfig(log_every=0),
                        store=memkv, tenv=tenv)
    exits = []
    monkeypatch.setattr("os._exit", lambda code: exits.append(code))

    tr._maybe_preempt(None, None, 1)   # first call checks; no flag yet
    assert exits == []
    preempt.flag_preempt(memkv, "pj", "stg", "pod2")
    tr._maybe_preempt(None, None, 2)   # inside the window: no store read
    assert exits == []
    # force the cadence window to elapse without sleeping through it
    tr._preempt_last_check_t -= constants.PREEMPT_CHECK_SECONDS + 1
    tr._maybe_preempt(None, None, 3)   # due + flagged: exit
    assert exits == [constants.PREEMPT_EXIT_CODE]
