"""Generation with tensor-parallel-sharded params on a mesh.

Multi-chip serving: the decode model's unrolled params carry the same
logical axes as training, so sharding them over ``tp`` and jitting
``generate`` lets XLA insert the collectives — tokens must match the
replicated single-device run exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.generate import generate, shard_split_params
from edl_tpu.models.transformer import (
    TransformerConfig, TransformerLM,
)
from edl_tpu.parallel import MeshSpec, build_mesh

CFG = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                        num_heads=4, mlp_dim=64, max_len=32,
                        dtype=jnp.float32, attention_impl="dense",
                        remat=False)


def test_tp_sharded_generation_matches_replicated():
    model = TransformerLM(CFG)
    ids = jnp.zeros((2, 4), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (2, 6)), jnp.int32)

    want = generate(CFG, params, prompt, 8, temperature=0)

    mesh = build_mesh(MeshSpec(dp=-1, tp=2))
    sharded = shard_split_params(params, mesh, CFG.num_layers)
    # spot-check an actually-sharded leaf (mlp kernel split over tp)
    k = sharded["layer_0"]["mlp_in"]["kernel"]
    assert k.addressable_shards[0].data.shape == (32, 32)  # mlp 64 / tp 2
    got = jax.jit(lambda p, i: generate(CFG, p, i, 8, temperature=0))(
        sharded, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
