"""Generation with tensor-parallel-sharded params on a mesh.

Multi-chip serving: the decode model's unrolled params carry the same
logical axes as training, so sharding them over ``tp`` and jitting
``generate`` lets XLA insert the collectives — tokens must match the
replicated single-device run exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.generate import _split_layer_params, generate
from edl_tpu.models.transformer import (
    TransformerConfig, TransformerLM,
)
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.parallel.sharding import ShardingRules, tree_shardings

CFG = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                        num_heads=4, mlp_dim=64, max_len=32,
                        dtype=jnp.float32, attention_impl="dense",
                        remat=False)


def _shard_split_params(params, mesh, rules, num_layers):
    """tp-shard the per-layer split params by their logical axes."""
    from edl_tpu.models import transformer as tf_mod
    from edl_tpu.models.logical import logical_axes_from_paths

    logical = logical_axes_from_paths(params, tf_mod.LOGICAL_RULES)
    # per-layer modules lose the leading "layers" stacking axis
    is_axes = lambda x: isinstance(x, tuple) and all(  # noqa: E731
        a is None or isinstance(a, str) for a in x)
    per_layer = jax.tree.map(lambda ax: ax[1:], logical["layers"],
                             is_leaf=is_axes)
    split_logical = {k: v for k, v in logical.items() if k != "layers"}
    for i in range(num_layers):
        split_logical[f"layer_{i}"] = per_layer
    split = _split_layer_params(params, num_layers)
    return jax.device_put(split, tree_shardings(split_logical, mesh, rules))


def test_tp_sharded_generation_matches_replicated():
    model = TransformerLM(CFG)
    ids = jnp.zeros((2, 4), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (2, 6)), jnp.int32)

    want = generate(CFG, params, prompt, 8, temperature=0)

    mesh = build_mesh(MeshSpec(dp=-1, tp=2))
    rules = ShardingRules()
    sharded = _shard_split_params(params, mesh, rules, CFG.num_layers)
    # spot-check an actually-sharded leaf (mlp kernel split over tp)
    k = sharded["layer_0"]["mlp_in"]["kernel"]
    assert k.addressable_shards[0].data.shape == (32, 32)  # mlp 64 / tp 2
    got = jax.jit(lambda p, i: generate(CFG, p, i, 8, temperature=0))(
        sharded, prompt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
