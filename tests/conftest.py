"""Test env: force an 8-device virtual CPU mesh before jax imports.

Multi-chip sharding is validated on a host-platform device mesh
(SURVEY.md §7 / driver contract); the real-TPU path is exercised by
bench.py, not the unit suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the ambient axon/TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize imports jax at interpreter start, so the env
# var alone is too late — force the platform through the config too.
import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from edl_tpu.coord.memory import MemoryKV


@pytest.fixture
def memkv():
    kv = MemoryKV(sweep_period=0.1)
    yield kv
    kv.close()


@pytest.fixture
def coord_server():
    from edl_tpu.coord.server import start_server
    server = start_server("127.0.0.1", 0)
    yield server
    server.stop()


@pytest.fixture
def coord_client(coord_server):
    from edl_tpu.coord.client import CoordClient
    client = CoordClient(f"127.0.0.1:{coord_server.port}")
    yield client
    client.close()
