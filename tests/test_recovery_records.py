"""Recovery-time record merging (cluster/recovery.py): launcher and
trainer halves join per stage, phases compute correctly, ordering is
chronological."""

import json

from edl_tpu.cluster import paths
from edl_tpu.cluster.recovery import load_recovery_records, summarize_recovery
from edl_tpu.utils import constants


def put(kv, job, stage, role, pod, times):
    kv.put(paths.key(job, constants.ETCD_RECOVERY, f"{stage}/{role}/{pod}"),
           json.dumps(times).encode())


def test_merge_and_breakdown(memkv):
    t0 = 1000.0
    put(memkv, "j", "s1", "launcher", "podA",
        {"detect": t0, "killed": t0 + 2, "barrier": t0 + 2.5,
         "spawn": t0 + 3})
    put(memkv, "j", "s1", "trainer", "podA",
        {"restored": t0 + 8, "first_step": t0 + 9.5})
    # a second, later resize with no trainer half yet
    put(memkv, "j", "s2", "launcher", "podA",
        {"detect": t0 + 100, "killed": t0 + 101, "barrier": t0 + 101.2,
         "spawn": t0 + 101.5})

    recs = load_recovery_records(memkv, "j")
    assert set(recs) == {"s1", "s2"}

    stages = summarize_recovery(memkv, "j", kill_time=t0 - 1.5)
    assert [s["stage"] for s in stages] == ["s1", "s2"]  # chronological
    s1 = stages[0]
    assert s1["detect_to_kill"] == 2.0
    assert s1["kill_to_barrier"] == 0.5
    assert s1["barrier_to_spawn"] == 0.5
    assert s1["spawn_to_restored"] == 5.0
    assert s1["restored_to_first_step"] == 1.5
    assert s1["total"] == 9.5
    assert s1["kill_to_detect"] == 1.5
    assert s1["total_from_kill"] == 11.0
    # incomplete stage carries launcher phases only
    assert "total" not in stages[1]


def test_launcher_half_only(memkv):
    """A resize whose trainer half never landed (job completed first,
    trainer died before its first step) still reports the launcher
    phases — and no fabricated trainer phases or total."""
    put(memkv, "jp", "s1", "launcher", "podA",
        {"detect": 1.0, "killed": 2.0, "barrier": 2.5, "spawn": 3.0})
    (s,) = summarize_recovery(memkv, "jp")
    assert s["detect_to_kill"] == 1.0
    assert s["kill_to_barrier"] == 0.5
    assert s["barrier_to_spawn"] == 0.5
    for key in ("spawn_to_restored", "restored_to_first_step", "total",
                "total_from_kill"):
        assert key not in s
    # kill_time only decorates COMPLETE records
    (s,) = summarize_recovery(memkv, "jp", kill_time=0.5)
    assert "kill_to_detect" not in s and "total_from_kill" not in s


def test_trainer_half_only_is_skipped(memkv):
    """A trainer half with no launcher half has no detect anchor: the
    summary skips the stage (no crash, no partial garbage) while the
    raw record stays loadable for debugging."""
    put(memkv, "jt", "s1", "trainer", "podA",
        {"restored": 5.0, "first_step": 6.0})
    assert summarize_recovery(memkv, "jt") == []
    recs = load_recovery_records(memkv, "jt")
    assert recs["s1"]["trainer"]["podA"]["first_step"] == 6.0


def test_mixed_partial_and_complete_stages(memkv):
    """One complete stage + one trainer-only stage: the complete stage
    summarizes normally; the orphan half can't corrupt the merge."""
    put(memkv, "jm", "s1", "launcher", "podA",
        {"detect": 10.0, "killed": 11.0, "barrier": 11.5, "spawn": 12.0})
    put(memkv, "jm", "s1", "trainer", "podA",
        {"restored": 14.0, "first_step": 15.0})
    put(memkv, "jm", "s2", "trainer", "podA",
        {"restored": 99.0, "first_step": 100.0})
    stages = summarize_recovery(memkv, "jm")
    assert [s["stage"] for s in stages] == ["s1"]
    assert stages[0]["total"] == 5.0


def test_earliest_detector_and_last_finisher_win(memkv):
    t0 = 50.0
    put(memkv, "j2", "s", "launcher", "podB",
        {"detect": t0 + 1, "killed": t0 + 2, "barrier": t0 + 3,
         "spawn": t0 + 4})
    put(memkv, "j2", "s", "launcher", "podA",  # detected FIRST
        {"detect": t0, "killed": t0 + 1, "barrier": t0 + 3, "spawn": t0 + 4})
    put(memkv, "j2", "s", "trainer", "podA",
        {"restored": t0 + 6, "first_step": t0 + 7})
    put(memkv, "j2", "s", "trainer", "podB",  # finished LAST
        {"restored": t0 + 6, "first_step": t0 + 9})
    s = summarize_recovery(memkv, "j2")[0]
    assert s["detect_at"] == t0
    assert s["total"] == 9.0  # earliest detect -> last first_step
