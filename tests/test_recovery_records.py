"""Recovery-time record merging (cluster/recovery.py): launcher and
trainer halves join per stage, phases compute correctly, ordering is
chronological."""

import json

from edl_tpu.cluster import paths
from edl_tpu.cluster.recovery import load_recovery_records, summarize_recovery
from edl_tpu.utils import constants


def put(kv, job, stage, role, pod, times):
    kv.put(paths.key(job, constants.ETCD_RECOVERY, f"{stage}/{role}/{pod}"),
           json.dumps(times).encode())


def test_merge_and_breakdown(memkv):
    t0 = 1000.0
    put(memkv, "j", "s1", "launcher", "podA",
        {"detect": t0, "killed": t0 + 2, "barrier": t0 + 2.5,
         "spawn": t0 + 3})
    put(memkv, "j", "s1", "trainer", "podA",
        {"restored": t0 + 8, "first_step": t0 + 9.5})
    # a second, later resize with no trainer half yet
    put(memkv, "j", "s2", "launcher", "podA",
        {"detect": t0 + 100, "killed": t0 + 101, "barrier": t0 + 101.2,
         "spawn": t0 + 101.5})

    recs = load_recovery_records(memkv, "j")
    assert set(recs) == {"s1", "s2"}

    stages = summarize_recovery(memkv, "j", kill_time=t0 - 1.5)
    assert [s["stage"] for s in stages] == ["s1", "s2"]  # chronological
    s1 = stages[0]
    assert s1["detect_to_kill"] == 2.0
    assert s1["kill_to_barrier"] == 0.5
    assert s1["barrier_to_spawn"] == 0.5
    assert s1["spawn_to_restored"] == 5.0
    assert s1["restored_to_first_step"] == 1.5
    assert s1["total"] == 9.5
    assert s1["kill_to_detect"] == 1.5
    assert s1["total_from_kill"] == 11.0
    # incomplete stage carries launcher phases only
    assert "total" not in stages[1]


def test_earliest_detector_and_last_finisher_win(memkv):
    t0 = 50.0
    put(memkv, "j2", "s", "launcher", "podB",
        {"detect": t0 + 1, "killed": t0 + 2, "barrier": t0 + 3,
         "spawn": t0 + 4})
    put(memkv, "j2", "s", "launcher", "podA",  # detected FIRST
        {"detect": t0, "killed": t0 + 1, "barrier": t0 + 3, "spawn": t0 + 4})
    put(memkv, "j2", "s", "trainer", "podA",
        {"restored": t0 + 6, "first_step": t0 + 7})
    put(memkv, "j2", "s", "trainer", "podB",  # finished LAST
        {"restored": t0 + 6, "first_step": t0 + 9})
    s = summarize_recovery(memkv, "j2")[0]
    assert s["detect_at"] == t0
    assert s["total"] == 9.0  # earliest detect -> last first_step
