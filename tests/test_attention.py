"""Attention dispatch and numerics (edl_tpu/ops/attention.py).

The pallas kernels (splash/flash) only exist on TPU; CPU covers the
dense path plus the dispatch decisions themselves.  TPU-only parity
tests are gated on the platform so the same file runs everywhere.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.attention import (
    _splash_ok, dense_attention, dot_product_attention,
)


def _ref_attention(q, k, v, causal):
    """O(L^2) numpy reference, f64 softmax."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    logits = np.einsum("bqhd,bkhd->bhqk", np.float64(q), np.float64(k))
    logits *= D ** -0.5
    if causal:
        mask = np.tril(np.ones((Lq, Lk), bool), k=Lk - Lq)
        logits = np.where(mask[None, None], logits, -np.inf)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", w, np.float64(v))


@pytest.mark.parametrize("causal", [False, True])
def test_dense_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 16, 2, 8)), jnp.float32)
               for _ in range(3))
    out = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, causal),
                               atol=1e-5)


def test_dense_grouped_kv_matches_repeat():
    # GQA: grouped einsum == explicit kv-head repetition
    rng = np.random.default_rng(3)
    H, Hk = 6, 2
    q = jnp.asarray(rng.normal(size=(2, 16, H, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 16, Hk, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 16, Hk, 8)), jnp.float32)
    grouped = dense_attention(q, k, v, causal=True)
    repeated = dense_attention(q, jnp.repeat(k, H // Hk, axis=2),
                               jnp.repeat(v, H // Hk, axis=2), causal=True)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(repeated),
                               atol=1e-5)


def test_dense_grouped_kv_batched_mask():
    # a [B, 1, Lq, Lk] mask must broadcast identically in the GQA and
    # MHA branches (it used to meet 5-D grouped logits: shape error, or
    # silent mis-masking when B == Hk)
    rng = np.random.default_rng(5)
    B, L, H, Hk = 2, 8, 4, 2      # B == Hk: the silent mis-mask case
    q = jnp.asarray(rng.normal(size=(B, L, H, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, Hk, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, Hk, 8)), jnp.float32)
    mask = jnp.asarray(rng.random((B, 1, L, L)) > 0.3)
    mask = mask | jnp.eye(L, dtype=bool)          # keep rows non-empty
    grouped = dense_attention(q, k, v, mask=mask)
    repeated = dense_attention(q, jnp.repeat(k, H // Hk, axis=2),
                               jnp.repeat(v, H // Hk, axis=2), mask=mask)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(repeated),
                               atol=1e-5)


def test_auto_on_cpu_is_dense():
    # no pallas kernels off-TPU: auto must resolve to dense and agree
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.float32)
               for _ in range(3))
    a = dot_product_attention(q, k, v, causal=True, impl="auto")
    d = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(d), atol=1e-6)


def test_splash_gate_shapes():
    def qk(L, D, Lk=None):
        q = jnp.zeros((1, L, 2, D))
        k = jnp.zeros((1, Lk if Lk else L, 2, D))
        return q, k

    assert _splash_ok(*qk(1024, 128), causal=True)
    assert _splash_ok(*qk(256, 64), causal=True)
    assert not _splash_ok(*qk(1024, 128), causal=False)   # causal-only
    assert not _splash_ok(*qk(100, 128), causal=True)     # L % 128
    assert not _splash_ok(*qk(1024, 80), causal=True)     # D % 64
    assert not _splash_ok(*qk(1024, 128, Lk=512), causal=True)  # cross-attn


def test_splash_rejects_non_causal():
    q = jnp.zeros((1, 128, 2, 64))
    with pytest.raises(ValueError, match="causal-only"):
        dot_product_attention(q, q, q, causal=False, impl="splash")


@pytest.mark.skipif(jax.devices()[0].platform != "tpu",
                    reason="pallas TPU kernels")
def test_splash_under_remat_scan():
    """Regression: the memoised splash kernel must not capture tracers
    when first built inside flax's nn.remat-under-nn.scan trace — the
    cached kernel poisoned every later trace (UnexpectedTracerError)
    until construction was moved under ensure_compile_time_eval."""
    from edl_tpu.models import TransformerConfig, TransformerLM
    from edl_tpu.models.transformer import lm_loss

    from edl_tpu.ops.attention import _splash_kernel
    _splash_kernel.cache_clear()   # force a fresh IN-TRACE kernel build

    cfg = TransformerConfig(vocab_size=128, num_layers=2, embed_dim=256,
                            num_heads=2, mlp_dim=256, max_len=256,
                            remat=True)
    model = TransformerLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 257)),
                      jnp.int32)
    params = model.init(jax.random.key(0), ids[:1, :8])["params"]

    def loss(p):
        return lm_loss(model.apply({"params": p}, ids[:, :-1]), ids[:, 1:])

    g = jax.jit(jax.grad(loss))(params)
    assert np.isfinite(float(jax.tree.leaves(g)[0].astype(jnp.float32).sum()))


@pytest.mark.skipif(jax.devices()[0].platform != "tpu",
                    reason="pallas TPU kernels")
def test_splash_matches_dense_on_tpu():
    rng = np.random.default_rng(2)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 256, 2, 128)), jnp.bfloat16)
               for _ in range(3))
    s = dot_product_attention(q, k, v, causal=True, impl="splash")
    d = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.float32(s), np.float32(d),
                               atol=2e-2, rtol=2e-2)
