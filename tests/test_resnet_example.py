"""The headline workload under the elastic launcher: 2-pod collective
ResNet training on the synthetic image dataset with per-epoch eval,
benchmark dump, and a real 2-process jax.distributed world.

Parity target: example/collective/resnet50/train_with_fleet.py run by
the reference launcher (test_launch.sh two-pod strategy, SURVEY.md §4).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.coord.client import CoordClient
from tests.test_launch_integration import FAST, finish

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "examples", "collective", "train_resnet.py")


def spawn(job_id, coord_ep, tmp, name, data_dir, bench, extra_env=None,
          extra_args=(), nodes_range="2:2", ckpt_dir=None):
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["EDL_TPU_DEMO_MARKER"] = os.path.join(tmp, f"marker-{name}")
    env.update(extra_env or {})
    log = open(os.path.join(tmp, f"launcher-{name}.log"), "wb")
    ckpt = (["--checkpoint_dir", ckpt_dir] if ckpt_dir else [])
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", job_id, "--coord_endpoints", coord_ep,
         "--nodes_range", nodes_range, "--nproc_per_node", "1"] + ckpt + [
         "--log_dir", os.path.join(tmp, f"log-{name}"), TRAIN, "--",
         "--synthetic", "4", "--synthetic_per_file", "48",
         "--synthetic_files", "2", "--data_dir", data_dir,
         "--model", "resnet18", "--width", "16", "--image_size", "32",
         "--epochs", "2", "--batch_size", "8", "--steps_per_epoch", "4",
         "--base_lr", "0.05", "--warmup_epochs", "0",
         "--num_workers", "2", "--bench_dump", bench] + list(extra_args),
        env=env, cwd=tmp, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    return proc


@pytest.mark.slow
def test_two_pod_resnet_collective(coord_server, tmp_path):
    ep = f"127.0.0.1:{coord_server.port}"
    tmp = str(tmp_path)
    data = os.path.join(tmp, "data")
    bench = os.path.join(tmp, "bench.json")
    pa = spawn("rn-e2e", ep, tmp, "a", data, bench)
    pb = spawn("rn-e2e", ep, tmp, "b", data, bench)
    assert finish(pa, 420) == 0, _logs(tmp)
    assert finish(pb, 420) == 0, _logs(tmp)

    client = CoordClient(ep)
    assert load_job_status(client, "rn-e2e") == Status.SUCCEED
    client.close()

    # both ranks trained in one world and recorded both epochs
    for name in ("a", "b"):
        marker = (tmp_path / f"marker-{name}").read_text()
        assert "world=2" in marker and "epochs=[0, 1]" in marker, marker

    # rank 0 dumped the per-epoch benchmark with eval metrics
    dump = json.load(open(bench))
    assert dump["world"] == 2 and dump["global_batch"] == 16
    assert len(dump["epochs"]) == 2
    assert all("val_top1" in e and "img_s" in e for e in dump["epochs"])


@pytest.mark.slow
def test_two_pod_resnet_data_service(coord_server, tmp_path):
    """The headline workload fed by the distributed DataService
    (--data_service): dynamic file handout + masked ragged tail under a
    real 2-process world (VERDICT r2 #1 integration)."""
    ep = f"127.0.0.1:{coord_server.port}"
    tmp = str(tmp_path)
    data = os.path.join(tmp, "data")
    bench = os.path.join(tmp, "bench.json")
    # no steps_per_epoch cap: the epoch ends by the has-next agreement
    args = ("--data_service", "--steps_per_epoch", "0")
    pa = spawn("rn-ds", ep, tmp, "a", data, bench, extra_args=args)
    pb = spawn("rn-ds", ep, tmp, "b", data, bench, extra_args=args)
    assert finish(pa, 420) == 0, _logs(tmp)
    assert finish(pb, 420) == 0, _logs(tmp)

    client = CoordClient(ep)
    assert load_job_status(client, "rn-ds") == Status.SUCCEED
    client.close()

    for name in ("a", "b"):
        marker = (tmp_path / f"marker-{name}").read_text()
        assert "world=2" in marker and "epochs=[0, 1]" in marker, marker
    dump = json.load(open(bench))
    # 2 files x 48 records over global batch 16 = 6 steps/epoch, all
    # records trained (the img_s accounting sees the full epoch)
    assert len(dump["epochs"]) == 2
    assert all("val_top1" in e for e in dump["epochs"])


@pytest.mark.slow
def test_resnet_data_service_survives_mid_epoch_kill(coord_server, tmp_path):
    """The headline workload + DataService under a hard mid-epoch pod
    kill: the survivor stop-resumes SOLO, re-enters the SAME epoch from
    the checkpointed record spans, and finishes the job."""
    import re
    import time

    from tests.helpers.harness import kill_tree

    ep = f"127.0.0.1:{coord_server.port}"
    tmp = str(tmp_path)
    data = os.path.join(tmp, "data")
    bench = os.path.join(tmp, "bench.json")
    # 16 paced steps/epoch (~4s), eval off so inter-epoch gaps are tiny
    # (a kill shortly after epoch 0's record lands inside epoch 1), and
    # mid-epoch saves every 4 steps so the resume carries record spans
    args = ("--data_service", "--steps_per_epoch", "0", "--epochs", "3",
            "--synthetic_per_file", "128", "--no-eval",
            "--save_every_steps", "4")
    env = {"EDL_TPU_DEMO_STEP_SLEEP": "0.25"}
    ckpt = os.path.join(tmp, "ckpt")
    pa = spawn("rn-kill", ep, tmp, "a", data, bench, extra_env=env,
               extra_args=args, nodes_range="1:2", ckpt_dir=ckpt)
    pb = spawn("rn-kill", ep, tmp, "b", data, bench, extra_env=env,
               extra_args=args, nodes_range="1:2", ckpt_dir=ckpt)
    # wait for epoch 0's bench record (the example prints one JSON line
    # per epoch; trainer INFO logs are not configured in subprocesses)
    la = os.path.join(tmp, "launcher-a.log")
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if os.path.exists(la) and re.search(
                r'"epoch": 0,', open(la, errors="replace").read()):
            break
        time.sleep(0.25)
    else:
        raise AssertionError("epoch 0 never completed: " + _logs(tmp)[-3000:])
    # kill once training is demonstrably INSIDE epoch 1: a mid-epoch
    # save (every 4 steps) past epoch 0's 16 steps has committed — a
    # condition, where the old fixed 2 s meant 0-8 steps depending on
    # host load
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        steps = [int(d) for d in (os.listdir(ckpt)
                                  if os.path.isdir(ckpt) else [])
                 if d.isdigit()]
        if steps and max(steps) > 16:
            break
        time.sleep(0.25)
    else:
        raise AssertionError("no mid-epoch-1 checkpoint appeared: "
                             + _logs(tmp)[-3000:])
    kill_tree(pb)
    assert finish(pa, 420) == 0, _logs(tmp)[-4000:]
    try:
        finish(pb, 10)
    except Exception:  # noqa: BLE001 — B was SIGKILLed
        pass

    client = CoordClient(ep)
    assert load_job_status(client, "rn-kill") == Status.SUCCEED
    client.close()
    marker = (tmp_path / "marker-a").read_text()
    assert "epochs=[0, 1, 2]" in marker, marker
    assert "world=1" in marker, marker  # the job really shrank
    text = open(la, errors="replace").read()
    resumes = re.findall(
        r"resume_epoch=(\d+) in_epoch=(-?\d+) resumed_spans=(\d+)", text)
    assert len(resumes) >= 2, text[-2000:]
    # the post-kill restart resumed from a committed checkpoint WITH its
    # data-checkpoint spans — never a cold start.  Whether the resume is
    # mid-epoch (in_epoch >= 0) or at an epoch boundary depends on which
    # async save had committed when the kill landed; the deterministic
    # mid-epoch exactly-once case is pinned by tests/test_data_plane_e2e
    assert any((int(e) >= 1 or int(ie) >= 0) and int(sp) > 0
               for e, ie, sp in resumes[1:]), resumes


def _logs(tmp):
    out = []
    for root, _, files in os.walk(tmp):
        for f in files:
            if f.endswith(".log") or f.startswith("workerlog"):
                p = os.path.join(root, f)
                out.append(f"==== {p} ====\n" + open(p, errors="replace").read())
    return "\n".join(out)
