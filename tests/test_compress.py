"""DGC gradient compression (train/compress.py): sparsity, residual
accumulation (nothing is lost, only delayed), and convergence when
chained into an optimizer."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.train.compress import dgc


def test_topk_sparsity_and_residual_carry():
    tx = dgc(sparsity=0.9, momentum=0.0, min_size=1)
    g = jnp.asarray(np.linspace(1.0, 100.0, 100), jnp.float32)
    state = tx.init(g)
    send, state = tx.update(g, state)
    # ~10% largest entries sent, the rest carried as residual
    assert int((send != 0).sum()) <= 15
    assert float(jnp.abs(send + state.residual - g).max()) < 1e-5

    # a small gradient repeatedly below the cut accumulates until sent
    tiny = jnp.zeros(100).at[0].set(0.5)
    total_sent0 = 0.0
    for _ in range(30):
        send, state = tx.update(tiny, state)
        total_sent0 += float(send[0])
    assert total_sent0 > 0.0  # eventually transmitted, not dropped


def test_small_leaves_pass_dense():
    tx = dgc(sparsity=0.99, min_size=10)
    g = {"bias": jnp.ones(4),
         "kernel": jnp.asarray(np.linspace(0.001, 1.0, 1000), jnp.float32)}
    state = tx.init(g)
    send, _ = tx.update(g, state)
    assert float(jnp.abs(send["bias"] - g["bias"]).max()) == 0.0
    assert int((send["kernel"] != 0).sum()) < 1000


def test_converges_chained_with_sgd():
    rng = np.random.default_rng(0)
    w_true = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(256, 32)), jnp.float32)
    y = x @ w_true

    tx = optax.chain(dgc(sparsity=0.75, momentum=0.9, min_size=1),
                     optax.sgd(0.05))
    params = jnp.zeros(32)
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        def loss(w):
            return ((x @ w - y) ** 2).mean()
        g = jax.grad(loss)(params)
        upd, state = tx.update(g, state)
        return optax.apply_updates(params, upd), state

    for _ in range(300):
        params, state = step(params, state)
    err = float(jnp.abs(params - w_true).max())
    assert err < 0.05, err


def test_arbitrary_pytree_structure():
    """optax transforms must handle any pytree — including ones that
    contain tuples, which a naive is_leaf=isinstance(tuple) unzip would
    confuse with the per-leaf result triples."""
    tx = dgc(sparsity=0.5, min_size=1)
    params = (jnp.ones(10), {"b": jnp.ones(5)})
    state = tx.init(params)
    send, state = tx.update(params, state)
    assert jax.tree.structure(send) == jax.tree.structure(params)
    assert send[1]["b"].shape == (5,)
