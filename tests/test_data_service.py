"""Distributed data service: exactly-once delivery, work stealing,
resume-by-checkpoint, dead-consumer requeue."""

import threading

import pytest

from edl_tpu.cluster.state import DataCheckpoint
from edl_tpu.data import DistributedReader, PodDataServer
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils.exceptions import EdlStopIteration


@pytest.fixture
def files(tmp_path):
    paths = []
    for f in range(4):
        p = tmp_path / f"part-{f}.txt"
        p.write_text("".join(f"f{f}r{r}\n" for r in range(10)))
        paths.append(str(p))
    return paths


def make_pod(pod_id, leader=False):
    return PodDataServer(pod_id, is_leader=leader)


def test_two_pods_exactly_once(files):
    a = make_pod("podA", leader=True)
    b = make_pod("podB")
    a.service.create_reader("r1", ["podA", "podB"], files)
    try:
        ra = DistributedReader("r1", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("r1", "podB", a.endpoint, b, batch_size=4)
        got = {"podA": [], "podB": []}

        def consume(r, key):
            for _, records in r:
                got[key].extend(records)

        ta = threading.Thread(target=consume, args=(ra, "podA"))
        tb = threading.Thread(target=consume, args=(rb, "podB"))
        ta.start(); tb.start(); ta.join(20); tb.join(20)
        assert not ta.is_alive() and not tb.is_alive()
        all_records = got["podA"] + got["podB"]
        # exactly-once across both consumers, whatever the steal split
        assert sorted(all_records) == sorted(
            f"f{f}r{r}" for f in range(4) for r in range(10))
    finally:
        a.stop(); b.stop()


def test_remote_fetch_of_peer_batches(files):
    """podB only produces; podA consumes everything — podB's batches
    must arrive over podB's data-server RPC."""
    a = make_pod("podA", leader=True)
    b = make_pod("podB")
    a.service.create_reader("rr", ["podA", "podB"], files)
    try:
        ra = DistributedReader("rr", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("rr", "podB", a.endpoint, b, batch_size=4)
        tb = threading.Thread(target=rb._produce)
        tb.start()
        got = []
        for _, records in ra:
            got.extend(records)
        tb.join(10)
        assert sorted(got) == sorted(
            f"f{f}r{r}" for f in range(4) for r in range(10))
    finally:
        a.stop(); b.stop()


def test_checkpoint_resume_skips_processed(files):
    a = make_pod("podA", leader=True)
    a.service.create_reader("r2", ["podA"], files)
    try:
        ra = DistributedReader("r2", "podA", a.endpoint, a, batch_size=4)
        consumed = []
        for _, records in ra:
            consumed.extend(records)
            if len(consumed) >= 12:
                break
        ckpt_json = ra.checkpoint.to_json()
    finally:
        a.stop()

    # resume with the checkpoint: only unprocessed records appear
    a2 = make_pod("podA", leader=True)
    a2.service.create_reader("r2", ["podA"], files)
    try:
        ckpt = DataCheckpoint().from_json(ckpt_json)
        ra2 = DistributedReader("r2", "podA", a2.endpoint, a2, batch_size=4,
                                checkpoint=ckpt)
        rest = []
        for _, records in ra2:
            rest.extend(records)
        assert not (set(consumed) & set(rest))
        assert sorted(consumed + rest) == sorted(
            f"f{f}r{r}" for f in range(4) for r in range(10))
    finally:
        a2.stop()


def test_requeue_dead_consumer(files):
    a = make_pod("podA", leader=True)
    a.service.create_reader("r3", ["podA"], files[:1])
    try:
        svc = a.service
        svc.report_batch_meta("r3", "podA", a.endpoint, ["podA:0", "podA:1"])
        # podB grabs both batches then dies without consuming
        svc.get_batch_meta("r3", "podB", n=2)
        assert svc.get_batch_meta("r3", "podA", n=2)["metas"] == []
        svc.requeue_pod("r3", "podB")
        metas = svc.get_batch_meta("r3", "podA", n=2)["metas"]
        assert [m[2] for m in metas] == ["podA:0", "podA:1"]
    finally:
        a.stop()


def test_spans_correct_across_file_boundaries(files):
    """A batch spanning a file boundary must checkpoint per-file spans
    with per-file offsets (regression: begin must reset per file)."""
    a = make_pod("podA", leader=True)
    # batch_size 16 over 10-record files forces every batch to span files
    a.service.create_reader("rs", ["podA"], files)
    try:
        ra = DistributedReader("rs", "podA", a.endpoint, a, batch_size=16)
        for _, _records in ra:
            pass
        ckpt = ra.checkpoint
        for f in range(4):
            for r in range(10):
                assert ckpt.is_processed(f, r), (f, r, ckpt.to_dict())
        for pr in ckpt.processed:
            assert 0 <= pr.begin < pr.end <= 10
    finally:
        a.stop()


def test_producer_error_surfaces_to_consumer(files, tmp_path):
    a = make_pod("podA", leader=True)
    missing = str(tmp_path / "nope.txt")
    a.service.create_reader("re", ["podA"], files[:1] + [missing])
    try:
        ra = DistributedReader("re", "podA", a.endpoint, a, batch_size=4)
        with pytest.raises(FileNotFoundError):
            for _ in ra:
                pass
    finally:
        a.stop()


def test_data_end_raises_typed_error(files):
    a = make_pod("podA", leader=True)
    a.service.create_reader("r4", ["podA"], files[:1])
    try:
        client = RpcClient(a.endpoint)
        a.service.reach_data_end("r4", "podA")
        with pytest.raises(EdlStopIteration):
            client.call("get_batch_meta", reader="r4", pod_id="podA", n=1)
        client.close()
    finally:
        a.stop()
