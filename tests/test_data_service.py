"""Distributed data service: exactly-once delivery, file-level work
stealing, resume-by-checkpoint, dead-pod re-production (minus consumed
spans), and span bookkeeping."""

import threading
import time

import pytest

from edl_tpu.cluster.state import DataCheckpoint
from edl_tpu.data import DistributedReader, PodDataServer
from edl_tpu.data.data_server import merge_span
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils.exceptions import EdlDataError, EdlStopIteration
from tests.helpers.exactly_once import audit_spans

ALL = sorted(f"f{f}r{r}" for f in range(4) for r in range(10))


@pytest.fixture
def files(tmp_path):
    paths = []
    for f in range(4):
        p = tmp_path / f"part-{f}.txt"
        p.write_text("".join(f"f{f}r{r}\n" for r in range(10)))
        paths.append(str(p))
    return paths


def make_pod(pod_id, leader=False):
    return PodDataServer(pod_id, is_leader=leader)


def drain(reader, spans: list | None = None):
    got = []
    for _bid, payload in reader:
        got.extend(payload["records"])
        if spans is not None:
            spans.extend(payload["spans"])
    return got


def test_merge_span():
    spans = []
    merge_span(spans, 5, 8)
    merge_span(spans, 0, 2)
    assert spans == [[0, 2], [5, 8]]
    merge_span(spans, 2, 4)  # adjacent-left merge
    assert spans == [[0, 4], [5, 8]]
    merge_span(spans, 4, 5)  # bridges the gap
    assert spans == [[0, 8]]
    merge_span(spans, 3, 6)  # contained
    assert spans == [[0, 8]]
    merge_span(spans, 10, 12)
    merge_span(spans, 7, 11)  # overlaps both sides
    assert spans == [[0, 12]]


def test_two_pods_exactly_once(files):
    a = make_pod("podA", leader=True)
    b = make_pod("podB")
    try:
        ra = DistributedReader("r1", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("r1", "podB", a.endpoint, b, batch_size=4)
        ra.create(files)
        rb.create(files)
        got = {"podA": [], "podB": []}
        spans = {"podA": [], "podB": []}

        def consume(r, key):
            got[key].extend(drain(r, spans[key]))

        ta = threading.Thread(target=consume, args=(ra, "podA"))
        tb = threading.Thread(target=consume, args=(rb, "podB"))
        ta.start(); tb.start(); ta.join(20); tb.join(20)
        assert not ta.is_alive() and not tb.is_alive()
        # exactly-once across both consumers, whatever the steal split:
        # the raw span log proves full coverage AND zero overlap
        assert sorted(got["podA"] + got["podB"]) == ALL
        audit_spans(spans["podA"] + spans["podB"], 4, 10)
    finally:
        a.stop(); b.stop()


def test_remote_fetch_of_peer_batches(files):
    """podB only produces; podA consumes everything — podB's batches
    must arrive over podB's data-server RPC."""
    a = make_pod("podA", leader=True)
    b = make_pod("podB")
    try:
        ra = DistributedReader("rr", "podA", a.endpoint, a, batch_size=4)
        rb = DistributedReader("rr", "podB", a.endpoint, b, batch_size=4)
        ra.create(files)
        rb.create(files)
        tb = threading.Thread(target=rb._produce)
        tb.start()
        got = drain(ra)
        tb.join(10)
        assert sorted(got) == ALL
    finally:
        a.stop(); b.stop()


def test_checkpoint_resume_skips_processed(files):
    a = make_pod("podA", leader=True)
    try:
        ra = DistributedReader("r2", "podA", a.endpoint, a, batch_size=4)
        ra.create(files)
        consumed = []
        for _bid, payload in ra:
            consumed.extend(payload["records"])
            if len(consumed) >= 12:
                break
        ckpt_json = ra.checkpoint.to_json()
    finally:
        a.stop()

    # resume with the checkpoint (a new generation, as after stop-resume):
    # only unprocessed records appear
    a2 = make_pod("podA", leader=True)
    try:
        ckpt = DataCheckpoint().from_json(ckpt_json)
        ra2 = DistributedReader("r2@gen2", "podA", a2.endpoint, a2,
                                batch_size=4, checkpoint=ckpt)
        ra2.create(files)
        rest = drain(ra2)
        assert not (set(consumed) & set(rest))
        assert sorted(consumed + rest) == ALL
    finally:
        a2.stop()


def test_dead_consumer_requeues_inflight(files):
    """Metas handed to a consumer that dies return to the pool."""
    a = make_pod("podA", leader=True)
    try:
        svc = a.service
        svc.create_reader("r3", files[:1])
        svc.report_batch_meta("r3", "podA", a.endpoint,
                              [["podA:0", [[0, 0, 4]]], ["podA:1", [[0, 4, 8]]]])
        # podB grabs both batches then dies without consuming
        svc.get_batch_meta("r3", "podB", n=2)
        assert svc.get_batch_meta("r3", "podA", n=2)["metas"] == []
        svc.mark_pod_dead("podB")
        metas = svc.get_batch_meta("r3", "podA", n=2)["metas"]
        assert [m[2] for m in metas] == ["podA:0", "podA:1"]
    finally:
        a.stop()


def test_dead_producer_requeues_files_minus_consumed(files):
    """The round-2 verdict gap: batches *produced* by a dead pod must
    not be lost — their files re-produce, minus already-consumed spans."""
    a = make_pod("podA", leader=True)
    try:
        svc = a.service
        svc.create_reader("r4", files[:1])
        # dead-to-be producer podB claims file 0 and produces 3 batches
        assert svc.next_file("r4", "podB")["file"] == [0, files[0]]
        svc.report_batch_meta(
            "r4", "podB", "127.0.0.1:1",  # dead endpoint
            [["podB:0", [[0, 0, 4]]], ["podB:1", [[0, 4, 8]]],
             ["podB:2", [[0, 8, 10]]]])
        svc.file_done("r4", "podB", 0)
        # podA consumes + acks the first batch...
        metas = svc.get_batch_meta("r4", "podA", n=1)["metas"]
        assert metas[0][2] == "podB:0"
        svc.get_batch_meta("r4", "podA", n=0, ack_ids=["podB:0"])
        # ...then podB dies: its queued batches drop, file 0 requeues
        svc.mark_pod_dead("podB")
        nxt = svc.next_file("r4", "podA")
        assert nxt["file"] == [0, files[0]]
        assert nxt["skip"] == [[0, 4]]  # consumed span excluded
    finally:
        a.stop()


def test_nack_reproduces_via_live_producer(files):
    """End-to-end: producer dies after reporting metas; the consumer's
    fetch fails, nacks, and a surviving producer re-produces the file —
    every record still arrives exactly once."""
    a = make_pod("podA", leader=True)
    b = make_pod("podB")
    try:
        rb = DistributedReader("r5", "podB", a.endpoint, b, batch_size=4)
        rb.create(files[:2])
        tb = threading.Thread(target=rb._produce, daemon=True)
        tb.start()  # podB produces both files...
        deadline = time.monotonic() + 10
        while (a.service.reader_status("r5")["produced"] < 6
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert a.service.reader_status("r5")["produced"] == 6
        rb._stop_produce.set()
        tb.join(5)
        b.stop()  # ...and dies; its cache is unreachable
        ra = DistributedReader("r5", "podA", a.endpoint, a, batch_size=4)
        got = drain(ra)
        assert sorted(got) == sorted(f"f{f}r{r}" for f in range(2)
                                     for r in range(10))
    finally:
        a.stop()


def test_cache_eviction_repairs_without_killing_producer(files):
    """A live producer evicting a batch under cache pressure must NOT be
    declared dead (advisor r3): the consumer nacks with
    producer_dead=False and only the lost spans re-produce — every
    record still arrives exactly once, nothing double-produces."""
    a = PodDataServer("podA", is_leader=True, cache_cap=2)
    try:
        ra = DistributedReader("rv", "podA", a.endpoint, a, batch_size=4)
        ra._backpressure = 10_000  # defeat throttling to force eviction
        ra.create(files[:1])
        spans: list = []
        got = drain(ra, spans)  # 3 batches published, cache keeps 2: one miss
        assert sorted(got) == sorted(f"f0r{r}" for r in range(10))
        assert len(got) == 10  # exactly once — no double production
        audit_spans(spans, 1, 10)
    finally:
        a.stop()


def test_spans_cover_every_record(files):
    a = make_pod("podA", leader=True)
    try:
        ra = DistributedReader("rs", "podA", a.endpoint, a, batch_size=16)
        ra.create(files)
        for _ in ra:
            pass
        ckpt = ra.checkpoint
        for f in range(4):
            for r in range(10):
                assert ckpt.is_processed(f, r), (f, r, ckpt.to_dict())
        for pr in ckpt.processed:
            assert 0 <= pr.begin < pr.end <= 10
    finally:
        a.stop()


def test_producer_error_fails_all_consumers(files, tmp_path):
    """An unreadable file fails the generation for EVERY consumer (the
    reference surfaced producer errors only on the producing pod)."""
    a = make_pod("podA", leader=True)
    missing = str(tmp_path / "nope.txt")
    try:
        ra = DistributedReader("re", "podA", a.endpoint, a, batch_size=4)
        ra.create(files[:1] + [missing])
        with pytest.raises((FileNotFoundError, EdlDataError)):
            for _ in ra:
                pass
        # a second consumer sees the typed error too
        client = RpcClient(a.endpoint)
        with pytest.raises(EdlDataError):
            client.call("get_batch_meta", reader="re", pod_id="podC", n=1)
        client.close()
    finally:
        a.stop()


def test_drained_raises_typed_stop(files):
    a = make_pod("podA", leader=True)
    try:
        svc = a.service
        svc.create_reader("r6", [])
        client = RpcClient(a.endpoint)
        with pytest.raises(EdlStopIteration):
            client.call("get_batch_meta", reader="r6", pod_id="podA", n=1)
        client.close()
    finally:
        a.stop()


def test_producer_coalesces_meta_reports(files):
    """Producer-side batched report_batch_meta (ROADMAP item 3
    leftover): metas ride the leader wire in chunks — far fewer
    non-empty RPCs than batches — and delivery stays exactly-once."""
    a = make_pod("podA", leader=True)
    try:
        ra = DistributedReader("rcoal", "podA", a.endpoint, a, batch_size=4,
                               produce_meta_batch=4)
        calls: list[int] = []
        orig = ra._leader.call

        def counted(method, **kw):
            if method == "report_batch_meta":
                calls.append(len(kw.get("batches") or []))
            return orig(method, **kw)

        ra._leader.call = counted
        ra.create(files)
        spans: list = []
        got = drain(ra, spans)
        assert sorted(got) == ALL
        audit_spans(spans, 4, per_file=10)
        reports = [c for c in calls if c > 0]
        # 4 files x 10 records / bs 4 = 12 batches; coalescing must
        # beat call-per-batch, and no report may exceed the chunk size
        assert sum(reports) == 12
        assert len(reports) < 12, f"not coalesced: {reports}"
        assert max(reports) > 1 and max(reports) <= 4, reports
    finally:
        a.stop()


def test_generation_gc(files):
    a = make_pod("podA", leader=True)
    try:
        svc = a.service
        svc.create_reader("train@e0@s1", files)
        svc.create_reader("other@e0@s1", files)
        svc.create_reader("train@e1@s1", files)  # GCs train@e0@s1
        with pytest.raises(Exception):
            svc.reader_status("train@e0@s1")
        assert svc.reader_status("train@e1@s1")["files"] == 4
        assert svc.reader_status("other@e0@s1")["files"] == 4
    finally:
        a.stop()
