"""Image pipeline units: codec, augment geometry, batching, file shards."""

import numpy as np
import pytest

from edl_tpu.data import images


def test_sample_codec_roundtrip():
    payload = b"\xff\xd8jpegish"
    rec = images.encode_sample(payload, 123)
    img, label = images.decode_sample(rec)
    assert (img, label) == (payload, 123)


def test_synthetic_batches_and_shapes(tmp_path):
    paths = images.write_synthetic_imagenet(str(tmp_path), n_files=2,
                                            per_file=24, size=40, classes=3)
    batches = list(images.ImageBatches(paths, 8, image_size=32, train=True,
                                       seed=0, num_workers=2))
    assert len(batches) == 6  # 48 samples / 8
    for b in batches:
        assert b["image"].shape == (8, 32, 32, 3)
        assert b["image"].dtype == np.float32
        assert b["label"].dtype == np.int32
        assert set(np.unique(b["label"])) <= {0, 1, 2}


def test_eval_pipeline_keeps_remainder(tmp_path):
    paths = images.write_synthetic_imagenet(str(tmp_path), n_files=1,
                                            per_file=10, size=40, classes=2)
    batches = list(images.ImageBatches(paths, 4, image_size=32, train=False,
                                       drop_remainder=False))
    assert [len(b["label"]) for b in batches] == [4, 4, 2]
    # eval transform is deterministic: two runs agree exactly
    again = list(images.ImageBatches(paths, 4, image_size=32, train=False,
                                     drop_remainder=False))
    np.testing.assert_array_equal(batches[0]["image"], again[0]["image"])


def test_train_shuffle_differs_by_seed(tmp_path):
    paths = images.write_synthetic_imagenet(str(tmp_path), n_files=1,
                                            per_file=64, size=40, classes=4)
    a = next(iter(images.ImageBatches(paths, 16, image_size=32, seed=1)))
    b = next(iter(images.ImageBatches(paths, 16, image_size=32, seed=2)))
    assert not np.array_equal(a["label"], b["label"]) or \
        not np.array_equal(a["image"], b["image"])


def test_augment_geometry():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (60, 80, 3), np.uint8)
    out = images.random_resized_crop(img, 32, rng)
    assert out.shape == (32, 32, 3)
    out = images.center_crop_resize(img, 32)
    assert out.shape == (32, 32, 3)


def test_corrupt_record_surfaces_in_consumer(tmp_path):
    from edl_tpu.native.recordio import write_records
    p = str(tmp_path / "bad.rec")
    write_records(p, [images.encode_sample(b"notajpeg", 0)])
    with pytest.raises(Exception):
        list(images.ImageBatches([p], 1, image_size=32, train=False,
                                 drop_remainder=False))


def test_shard_files_covers_all_and_never_empty():
    paths = [f"f{i}" for i in range(5)]
    shards = [images.shard_files(paths, r, 3) for r in range(3)]
    assert sorted(sum(shards, [])) == sorted(paths)
    # more shards than files: every shard still gets one
    for r in range(8):
        assert images.shard_files(paths, r, 8)


def test_shuffled_stream_is_deterministic(tmp_path):
    """Same (files, seed) -> identical batch stream, run after run: the
    native shuffle window must wait for a FULL buffer before sampling,
    or thread timing changes the order despite the seed (the root cause
    of run-to-run training variance found in round 3)."""
    import hashlib

    from edl_tpu.data import images as im

    paths = im.write_synthetic_imagenet(str(tmp_path), n_files=2,
                                        per_file=40, size=24, classes=3)
    digests = []
    for _trial in range(3):
        h = hashlib.sha1()
        # shuffle_buffer SMALLER than the dataset: the steady-state
        # full-window sampling path must run, not just the EOF drain
        for b in im.ImageBatches(paths, 8, image_size=24, train=True,
                                 seed=5, num_workers=4, shuffle_buffer=16):
            h.update(b["image"].tobytes())
            h.update(b["label"].tobytes())
        digests.append(h.hexdigest())
    assert len(set(digests)) == 1, digests
