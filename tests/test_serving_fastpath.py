"""Serving fast path (ISSUE 20): mesh-sharded paged KV, chunked
prefill, speculative decoding.

Every fast-path feature is an OPTIMIZATION over the same contract the
base engine proves — greedy outputs bit-identical to
``models.generate`` — so every test here is a parity test first and a
mechanism test second: the stats must prove the fast path actually
engaged (prefix hits, chunk counts, accepted drafts), and the tokens
must prove it changed nothing.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edl_tpu.models import TransformerConfig, TransformerLM
from edl_tpu.models.generate import generate
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def small():
    cfg = TransformerConfig(vocab_size=97, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    return cfg, params


@pytest.fixture(scope="module")
def tp2():
    return build_mesh(MeshSpec(dp=-1, tp=2))


def _engine(cfg, params, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("temperature", 0.0)
    kw.setdefault("steps_per_sync", 2)
    kw.setdefault("kv_block", 4)
    kw.setdefault("kv_pool_blocks", 64)
    return ContinuousBatcher(cfg, params, **kw)


def _want(cfg, params, p, n):
    return np.asarray(generate(cfg, params, jnp.asarray(p[None]), n,
                               temperature=0.0))[0]


# -- mesh-sharded paged pool ----------------------------------------------


def test_mesh_pool_migration_roundtrip(small, tp2):
    """Commit → drain → export on one tp=2 engine, import into a fresh
    tp=2 engine: the sharded pool's export gathers to host layout, the
    import re-shards, and the migrated session's next turn resumes warm
    and bit-exact."""
    cfg, params = small
    p1 = np.asarray([7, 11, 13, 5, 9, 2, 8, 3], np.int32)
    eng_a = _engine(cfg, params, slots=2, mesh=tp2)
    try:
        out1 = eng_a.submit(p1, 8, session="s").result(120)
        np.testing.assert_array_equal(out1, _want(cfg, params, p1, 8))
        conv = np.concatenate([p1, out1])
        assert eng_a.drain(timeout=30)
        exported = eng_a.export_sessions()
        assert [e[0] for e in exported] == ["s"]
        _, tokens, meta, blob = exported[0]
        assert tokens == list(map(int, conv[:len(tokens)]))
    finally:
        eng_a.stop()

    eng_b = _engine(cfg, params, slots=2, mesh=tp2)
    try:
        assert eng_b.import_session("s", tokens, meta, blob) > 0
        p2 = np.concatenate([conv, np.asarray([4, 1], np.int32)])
        out2 = eng_b.generate(p2, 6, timeout=120)
        np.testing.assert_array_equal(out2, _want(cfg, params, p2, 6))
        stats = eng_b.stats()
        assert stats["kv_prefix_hits"] == 1, stats
        assert stats["kv_prefill_tokens_skipped"] == len(tokens), stats
    finally:
        eng_b.stop()


def test_mesh_paged_matches_unpaged(small, tp2):
    """The tentpole gate: one workload (shared prefixes, an unrelated
    prompt, commits in play) through a tp=2 paged engine and a tp=2
    unpaged engine — byte-identical.  (Single-device paged parity vs
    the same generate() oracle lives in test_serving_kv.py, closing
    the three-way triangle without a third engine compile.)"""
    cfg, params = small
    rng = np.random.default_rng(5)
    prefix = rng.integers(1, 97, (9,)).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.integers(1, 97, (n,)).astype(np.int32)])
               for n in (2, 6, 3)]
    prompts += [rng.integers(1, 97, (5,)).astype(np.int32)]
    news = [5, 7, 4, 6]

    def run(**kw):
        eng = _engine(cfg, params, slots=2, prefill_buckets=(16,), **kw)
        try:
            return [eng.generate(p, n, timeout=120)
                    for p, n in zip(prompts, news)]
        finally:
            eng.stop()

    mesh_paged = run(mesh=tp2)
    mesh_unpaged = run(mesh=tp2, kv_block=0)
    for p, n, a, b in zip(prompts, news, mesh_paged, mesh_unpaged):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, _want(cfg, params, p, n))


# -- chunked prefill ------------------------------------------------------


def test_chunked_prefill_bit_exact_and_counted(small):
    """Prompts past ``prefill_chunk`` split into cache-aligned chunks;
    outputs identical to the unchunked engine and to generate(), and
    the chunk counters prove the split happened."""
    cfg, params = small
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, 97, (n,)).astype(np.int32)
               for n in (40, 23, 6)]          # 5 + 3 + 0 chunk dispatches

    eng = _engine(cfg, params, prefill_chunk=8, prefill_buckets=(8,))
    try:
        chunked = [eng.generate(p, 5, timeout=120) for p in prompts]
        st = eng.stats()
    finally:
        eng.stop()
    # generate() is the same oracle the unchunked engine is gated
    # against, so chunked == generate() closes chunked == unchunked
    for p, a in zip(prompts, chunked):
        np.testing.assert_array_equal(a, _want(cfg, params, p, 5))
    assert st["chunked_admissions"] == 2, st
    assert st["prefill_chunks"] == 8, st    # 40 -> 5 of 8, 23 -> 3 of 8


def test_chunked_prefill_does_not_starve_decode(small):
    """The point of chunking: a live decode keeps ticking while a long
    admission prefills.  The short request (2 tokens left) must finish
    while the long one (5 chunks + 24 decode ticks) is still in
    flight — and both stay bit-exact."""
    cfg, params = small
    rng = np.random.default_rng(8)
    short = rng.integers(1, 97, (6,)).astype(np.int32)
    long = rng.integers(1, 97, (40,)).astype(np.int32)
    eng = _engine(cfg, params, prefill_chunk=8, steps_per_sync=1)
    try:
        f_short = eng.submit(short, 8)
        time.sleep(0.3)                       # short is live and decoding
        f_long = eng.submit(long, 24)
        out_short = f_short.result(120)
        long_done_at_short_finish = f_long.done()
        out_long = f_long.result(120)
        stats = eng.stats()
    finally:
        eng.stop()
    np.testing.assert_array_equal(out_short, _want(cfg, params, short, 8))
    np.testing.assert_array_equal(out_long, _want(cfg, params, long, 24))
    assert not long_done_at_short_finish
    assert stats["prefill_chunks"] >= 4, stats
    assert stats["prefill_stall_s"] >= 0.0


# -- speculative decoding -------------------------------------------------


def _spec_engine(cfg, params, draft_params, k, **kw):
    return _engine(cfg, params, spec_k=k, draft_cfg=cfg,
                   draft_params=draft_params, **kw)


def test_spec_self_draft_parity_and_accept_rate(small):
    """Draft == target: every proposal must verify, so the accept rate
    is ~1.0 — and the outputs are still bit-identical to generate()
    (greedy acceptance never emits an unverified token)."""
    cfg, params = small
    rng = np.random.default_rng(9)
    prompts = [rng.integers(1, 97, (int(n),)).astype(np.int32)
               for n in rng.integers(3, 14, (6,))]
    eng = _spec_engine(cfg, params, params, k=3, prefill_buckets=(16,))
    try:
        outs = [eng.generate(p, 7, timeout=120) for p in prompts]
        stats = eng.stats()
    finally:
        eng.stop()
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _want(cfg, params, p, 7))
    assert stats["spec_k"] == 3
    assert stats["spec_proposed"] > 0
    assert stats["spec_accept_rate"] > 0.9, stats


@pytest.mark.slow
def test_spec_adversarial_draft_still_bit_exact(small):
    """A randomly-initialized draft proposes garbage: near-everything
    is rejected, the engine degrades to ~1 verified token per round,
    and the outputs STILL match generate() exactly."""
    cfg, params = small
    bad_draft = TransformerLM(cfg).init(
        jax.random.key(99), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(10)
    prompts = [rng.integers(1, 97, (int(n),)).astype(np.int32)
               for n in rng.integers(3, 12, (6,))]
    eng = _spec_engine(cfg, params, bad_draft, k=3)
    try:
        outs = [eng.generate(p, 8, timeout=120) for p in prompts]
        stats = eng.stats()
    finally:
        eng.stop()
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _want(cfg, params, p, 8))
    assert stats["spec_proposed"] > 0
    assert stats["spec_accept_rate"] < 0.9, stats


@pytest.mark.slow
def test_spec_k1_parity(small):
    cfg, params = small
    rng = np.random.default_rng(12)
    prompts = [rng.integers(1, 97, (int(n),)).astype(np.int32)
               for n in rng.integers(3, 10, (4,))]
    eng = _spec_engine(cfg, params, params, k=1)
    try:
        outs = [eng.generate(p, 7, timeout=120) for p in prompts]
    finally:
        eng.stop()
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _want(cfg, params, p, 7))


def test_spec_eos_mid_draft_truncates(small):
    """EOS landing inside an accepted draft burst: the finish pass
    consumes round tokens in order and stops AT the eos — no trailing
    speculated tokens leak into the output."""
    cfg, params = small
    p = np.asarray([5, 9, 2], np.int32)
    ref = _want(cfg, params, p, 8)
    eos = int(ref[1])     # greedy's 2nd token: dies mid-burst at k=3
    eng = _spec_engine(cfg, params, params, k=3, eos_id=eos,
                       prefill_buckets=(8,))
    try:
        out = eng.generate(p, 8, timeout=120)
    finally:
        eng.stop()
    assert list(out) == list(ref[:2])


def test_spec_validation(small):
    cfg, params = small
    with pytest.raises(ValueError, match="draft"):
        _engine(cfg, params, spec_k=2)
    with pytest.raises(ValueError, match="greedy"):
        _spec_engine(cfg, params, params, k=2, temperature=0.7)


# -- the full stack at once -----------------------------------------------


@pytest.mark.slow
def test_mesh_chunk_spec_combined_parity(small, tp2):
    """Everything on together — tp=2 mesh, sharded paged pool, chunked
    prefill, self-draft speculation — over shared-prefix traffic with a
    long admission: bit-exact, chunks counted, drafts accepted, prefix
    reused."""
    cfg, params = small
    rng = np.random.default_rng(13)
    prefix = rng.integers(1, 97, (12,)).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.integers(1, 97, (n,)).astype(np.int32)])
               for n in (3, 6)]
    prompts.append(rng.integers(1, 97, (40,)).astype(np.int32))
    eng = _engine(cfg, params, slots=2, mesh=tp2, prefill_chunk=16,
                  spec_k=2, draft_cfg=cfg, draft_params=params)
    try:
        outs = [eng.generate(p, 8, timeout=180) for p in prompts]
        stats = eng.stats()
    finally:
        eng.stop()
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _want(cfg, params, p, 8))
    assert stats["kv_prefix_hits"] >= 1, stats
    assert stats["prefill_chunks"] >= 2, stats
    assert stats["spec_accept_rate"] > 0.9, stats
