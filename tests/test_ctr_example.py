"""CTR Wide&Deep example (reference example/ctr): ep-sharded embedding
tables over the virtual device mesh, trained to a real AUC against a
known ground-truth click model, standalone and under the launcher."""

import json
import os
import subprocess
import sys

import pytest

from tests.test_launch_integration import FAST, finish

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "examples", "ctr", "train_wide_deep.py")


@pytest.mark.slow
def test_wide_deep_standalone_reaches_auc(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["EDL_TPU_DEMO_MARKER"] = str(tmp_path / "marker")
    # 8 virtual devices from the ambient XLA_FLAGS: mesh ep=2 x dp=4
    out = subprocess.run(
        [sys.executable, TRAIN, "--epochs", "2", "--steps_per_epoch", "40",
         "--batch_size", "128", "--vocab", "100", "--lr", "0.01"],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "'ep': 2" in out.stdout, out.stdout  # tables really sharded
    rec = json.loads([l for l in (tmp_path / "marker").read_text().splitlines()
                      if l.startswith("done ")][-1][5:])
    assert rec["auc"] >= 0.8, rec


@pytest.mark.slow
def test_wide_deep_under_launcher(coord_server, tmp_path):
    ep = f"127.0.0.1:{coord_server.port}"
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["EDL_TPU_DEMO_MARKER"] = str(tmp_path / "marker")
    log = open(tmp_path / "launcher.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", "ctr", "--coord_endpoints", ep,
         "--nodes_range", "1:1", "--nproc_per_node", "1",
         "--checkpoint_dir", str(tmp_path / "ckpt"),
         "--log_dir", str(tmp_path / "log"), TRAIN, "--",
         "--epochs", "2", "--steps_per_epoch", "40", "--batch_size", "128",
         "--vocab", "100", "--lr", "0.01"],
        env=env, cwd=str(tmp_path), stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    assert finish(proc, 420) == 0, \
        (tmp_path / "launcher.log").read_text(errors="replace")[-3000:]
    rec = json.loads([l for l in (tmp_path / "marker").read_text().splitlines()
                      if l.startswith("done ")][-1][5:])
    assert rec["auc"] >= 0.75, rec
