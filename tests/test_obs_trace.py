"""JSONL tracer + the unified resize-record write path: the store
record (read back by summarize_recovery), the trace events, and the
resize-phase histogram all derive from the same times dict
(cluster/recovery.py), and the dump CLI reproduces summarize_recovery
verbatim."""

import json

from edl_tpu.cluster import recovery
from edl_tpu.obs import trace as obs_trace
from edl_tpu.obs.dump import job_report, render_report

PHASES = ("detect_to_kill", "kill_to_barrier", "barrier_to_spawn",
          "restored_to_first_step")


def _read_events(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


def test_tracer_emit_and_span(tmp_path):
    path = tmp_path / "t.jsonl"
    tr = obs_trace.Tracer(str(path), component="unit")
    tr.emit("hello", at=12.0, stage="s1")
    with tr.span("work", k=1):
        pass
    tr.close()
    first, second = _read_events(path)
    assert first == {"ts": 12.0, "name": "hello", "component": "unit",
                     "stage": "s1"}
    assert second["name"] == "work" and second["k"] == 1
    assert second["dur"] >= 0  # monotonic span duration


def test_span_emits_on_exception(tmp_path):
    path = tmp_path / "t.jsonl"
    tr = obs_trace.Tracer(str(path))
    try:
        with tr.span("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    tr.close()
    (event,) = _read_events(path)
    assert event["name"] == "boom" and "dur" in event


def test_configure_from_env_idempotent(tmp_path, monkeypatch):
    monkeypatch.setenv("EDL_TPU_TRACE_DIR", str(tmp_path))
    tr = obs_trace.configure_from_env("unit")
    try:
        assert tr is obs_trace.get_tracer()
        assert obs_trace.configure_from_env("unit") is tr
        obs_trace.emit("e1", at=1.0)
        (trace_file,) = tmp_path.glob("trace-unit-*.jsonl")
        (event,) = _read_events(trace_file)
        assert event["name"] == "e1" and event["component"] == "unit"
    finally:
        tr.close()
        obs_trace._tracer = obs_trace.NullTracer()


def test_unified_halves_store_trace_and_histogram_agree(memkv, tmp_path):
    tr = obs_trace.configure(str(tmp_path / "trace.jsonl"), "unit")
    hist = recovery.RESIZE_PHASE_SECONDS
    before = {ph: hist.labels(phase=ph).count for ph in PHASES}
    t0 = 1000.0
    try:
        recovery.write_launcher_half(
            memkv, "j", "s1", "podA",
            {"detect": t0, "killed": t0 + 2, "barrier": t0 + 2.5,
             "spawn": t0 + 3})
        recovery.write_trainer_half(memkv, "j", "s1", "podA",
                                    restored=t0 + 8, first_step=t0 + 9.5)
    finally:
        obs_trace._tracer = obs_trace.NullTracer()
        tr.close()

    # the store record, read back through the one read path
    (stage,) = recovery.summarize_recovery(memkv, "j")
    assert stage["detect_to_kill"] == 2.0
    assert stage["kill_to_barrier"] == 0.5
    assert stage["barrier_to_spawn"] == 0.5
    assert stage["restored_to_first_step"] == 1.5
    assert stage["total"] == 9.5

    # the trace events carry the SAME per-phase durations (same dict)
    events = {e["name"]: e
              for e in _read_events(tmp_path / "trace.jsonl")}
    for phase in PHASES:
        assert events[f"resize/{phase}"]["dur"] == stage[phase]
        assert events[f"resize/{phase}"]["stage"] == "s1"

    # and the per-phase histogram observed each phase exactly once
    after = {ph: hist.labels(phase=ph).count for ph in PHASES}
    assert after == {ph: before[ph] + 1 for ph in PHASES}


def test_dump_reproduces_summarize_recovery(memkv):
    t0 = 50.0
    recovery.write_launcher_half(
        memkv, "jd", "s1", "podA",
        {"detect": t0, "killed": t0 + 2, "barrier": t0 + 2.5,
         "spawn": t0 + 3})
    recovery.write_trainer_half(memkv, "jd", "s1", "podA",
                                restored=t0 + 8, first_step=t0 + 9.5)
    # a later, in-flight resize: launcher half only
    recovery.write_launcher_half(
        memkv, "jd", "s2", "podA",
        {"detect": t0 + 100, "killed": t0 + 101, "barrier": t0 + 101.25,
         "spawn": t0 + 101.5})

    report = job_report(memkv, "jd")
    # the dump's per-phase totals ARE summarize_recovery's — one read
    # path, zero chance of drift
    assert report["resizes"] == recovery.summarize_recovery(memkv, "jd")
    assert report["job"]["resizes"] == 2
    # the newest resize (s2) is still in flight, so the collector cell
    # is empty; the completed s1 carries the full breakdown
    assert report["job"]["last_recovery_sec"] == ""
    assert report["resizes"][0]["total"] == 9.5

    text = render_report(report)
    assert "resize s1" in text and "resize s2" in text
    assert "[launcher half only]" in text  # s2 is visibly incomplete
    assert "total" in text and "9.500s" in text
    assert "restored_to_first_step" in text and "1.500s" in text


def test_dump_empty_job(memkv):
    report = job_report(memkv, "ghost")
    assert report["resizes"] == []
    text = render_report(report)
    assert "ghost" in text and "no resize records" in text
