"""JSONL tracer + the unified resize-record write path: the store
record (read back by summarize_recovery), the trace events, and the
resize-phase histogram all derive from the same times dict
(cluster/recovery.py), and the dump CLI reproduces summarize_recovery
verbatim."""

import json

from edl_tpu.cluster import recovery
from edl_tpu.obs import trace as obs_trace
from edl_tpu.obs.dump import job_report, render_report

PHASES = ("detect_to_kill", "kill_to_barrier", "barrier_to_spawn",
          "restored_to_first_step")


def _read_events(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


def test_tracer_emit_and_span(tmp_path):
    path = tmp_path / "t.jsonl"
    tr = obs_trace.Tracer(str(path), component="unit")
    tr.emit("hello", at=12.0, stage="s1")
    with tr.span("work", k=1):
        pass
    tr.close()
    first, second = _read_events(path)
    assert first == {"ts": 12.0, "name": "hello", "component": "unit",
                     "stage": "s1"}
    assert second["name"] == "work" and second["k"] == 1
    assert second["dur"] >= 0  # monotonic span duration


def test_span_emits_on_exception(tmp_path):
    path = tmp_path / "t.jsonl"
    tr = obs_trace.Tracer(str(path))
    try:
        with tr.span("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    tr.close()
    (event,) = _read_events(path)
    assert event["name"] == "boom" and "dur" in event


def test_configure_from_env_idempotent(tmp_path, monkeypatch):
    monkeypatch.setenv("EDL_TPU_TRACE_DIR", str(tmp_path))
    tr = obs_trace.configure_from_env("unit")
    try:
        assert tr is obs_trace.get_tracer()
        assert obs_trace.configure_from_env("unit") is tr
        obs_trace.emit("e1", at=1.0)
        (trace_file,) = tmp_path.glob("trace-unit-*.jsonl")
        (event,) = _read_events(trace_file)
        assert event["name"] == "e1" and event["component"] == "unit"
    finally:
        tr.close()
        obs_trace._tracer = obs_trace.NullTracer()


def test_unified_halves_store_trace_and_histogram_agree(memkv, tmp_path):
    tr = obs_trace.configure(str(tmp_path / "trace.jsonl"), "unit")
    hist = recovery.RESIZE_PHASE_SECONDS
    before = {ph: hist.labels(phase=ph).count for ph in PHASES}
    t0 = 1000.0
    try:
        recovery.write_launcher_half(
            memkv, "j", "s1", "podA",
            {"detect": t0, "killed": t0 + 2, "barrier": t0 + 2.5,
             "spawn": t0 + 3})
        recovery.write_trainer_half(memkv, "j", "s1", "podA",
                                    restored=t0 + 8, first_step=t0 + 9.5)
    finally:
        obs_trace._tracer = obs_trace.NullTracer()
        tr.close()

    # the store record, read back through the one read path
    (stage,) = recovery.summarize_recovery(memkv, "j")
    assert stage["detect_to_kill"] == 2.0
    assert stage["kill_to_barrier"] == 0.5
    assert stage["barrier_to_spawn"] == 0.5
    assert stage["restored_to_first_step"] == 1.5
    assert stage["total"] == 9.5

    # the trace events carry the SAME per-phase durations (same dict)
    events = {e["name"]: e
              for e in _read_events(tmp_path / "trace.jsonl")}
    for phase in PHASES:
        assert events[f"resize/{phase}"]["dur"] == stage[phase]
        assert events[f"resize/{phase}"]["stage"] == "s1"

    # and the per-phase histogram observed each phase exactly once
    after = {ph: hist.labels(phase=ph).count for ph in PHASES}
    assert after == {ph: before[ph] + 1 for ph in PHASES}


def test_dump_reproduces_summarize_recovery(memkv):
    t0 = 50.0
    recovery.write_launcher_half(
        memkv, "jd", "s1", "podA",
        {"detect": t0, "killed": t0 + 2, "barrier": t0 + 2.5,
         "spawn": t0 + 3})
    recovery.write_trainer_half(memkv, "jd", "s1", "podA",
                                restored=t0 + 8, first_step=t0 + 9.5)
    # a later, in-flight resize: launcher half only
    recovery.write_launcher_half(
        memkv, "jd", "s2", "podA",
        {"detect": t0 + 100, "killed": t0 + 101, "barrier": t0 + 101.25,
         "spawn": t0 + 101.5})

    report = job_report(memkv, "jd")
    # the dump's per-phase totals ARE summarize_recovery's — one read
    # path, zero chance of drift
    assert report["resizes"] == recovery.summarize_recovery(memkv, "jd")
    assert report["job"]["resizes"] == 2
    # the newest resize (s2) is still in flight, so the collector cell
    # is empty; the completed s1 carries the full breakdown
    assert report["job"]["last_recovery_sec"] == ""
    assert report["resizes"][0]["total"] == 9.5

    text = render_report(report)
    assert "resize s1" in text and "resize s2" in text
    assert "[launcher half only]" in text  # s2 is visibly incomplete
    assert "total" in text and "9.500s" in text
    assert "restored_to_first_step" in text and "1.500s" in text


def test_dump_empty_job(memkv):
    report = job_report(memkv, "ghost")
    assert report["resizes"] == []
    text = render_report(report)
    assert "ghost" in text and "no resize records" in text


# -- trace-file growth cap (EDL_TPU_TRACE_MAX_MB) ----------------------------

def test_tracer_rotates_at_cap(tmp_path):
    from edl_tpu.obs.trace import _ROTATIONS_TOTAL

    path = tmp_path / "t.jsonl"
    tr = obs_trace.Tracer(str(path), "unit", max_bytes=2048)
    rotations0 = _ROTATIONS_TOTAL.value
    for i in range(200):
        tr.emit("spin", at=float(i), i=i)
    tr.close()
    assert _ROTATIONS_TOTAL.value > rotations0, "cap never triggered"
    rotated = tmp_path / "t.jsonl.1"
    assert rotated.exists(), "rotation must keep one previous generation"
    # on-disk footprint stays bounded: live file + one rotated generation
    assert path.stat().st_size <= 2048
    assert rotated.stat().st_size <= 2048
    # both generations remain valid JSONL, newest events in the live file
    live = _read_events(path)
    old = _read_events(rotated)
    assert live and old
    assert live[-1]["i"] == 199
    assert old[-1]["i"] == live[0]["i"] - 1  # no event lost at the seam


def test_tracer_counts_dropped_events_on_write_failure(tmp_path):
    from edl_tpu.obs.trace import _DROPPED_TOTAL

    tr = obs_trace.Tracer(str(tmp_path / "t.jsonl"), "unit")
    dropped0 = _DROPPED_TOTAL.labels(reason="write").value
    tr._f.close()  # simulate the fd dying under the tracer (full disk)
    tr.emit("lost", at=1.0)
    assert _DROPPED_TOTAL.labels(reason="write").value == dropped0 + 1


# -- merged timelines + Perfetto export (edl-obs-dump --merge) ---------------

def _write_trace(path, events, truncate_last=False):
    lines = [json.dumps(e) for e in events]
    text = "\n".join(lines) + "\n"
    if truncate_last:
        text = text[:-len(lines[-1]) // 2]  # concurrent writer mid-append
    path.write_text(text)


def test_read_trace_dir_skips_and_counts_truncated_lines(tmp_path):
    from edl_tpu.obs.dump import read_trace_dir

    _write_trace(tmp_path / "trace-a-1.jsonl",
                 [{"ts": 1.0, "name": "x", "component": "a"},
                  {"ts": 2.0, "name": "y", "component": "a"}],
                 truncate_last=True)
    _write_trace(tmp_path / "trace-b-2.jsonl",
                 [{"ts": 1.5, "name": "z", "component": "b"}])
    events, skipped = read_trace_dir(str(tmp_path))
    assert skipped == 1, "the torn final line must be counted, not fatal"
    assert {e["name"] for e in events} == {"x", "z"}
    assert all("file" in e for e in events)


def test_read_trace_dir_folds_rotated_generation(tmp_path):
    from edl_tpu.obs.dump import read_trace_dir

    _write_trace(tmp_path / "trace-a-1.jsonl",
                 [{"ts": 2.0, "name": "new", "component": "a"}])
    _write_trace(tmp_path / "trace-a-1.jsonl.1",
                 [{"ts": 1.0, "name": "old", "component": "a"}])
    events, skipped = read_trace_dir(str(tmp_path))
    assert skipped == 0 and len(events) == 2
    # one process, not two: the rotated generation folds into its live file
    assert {e["file"] for e in events} == {"trace-a-1.jsonl"}


def test_merge_timeline_filters_and_orders(tmp_path):
    from edl_tpu.obs.dump import merge_timeline, read_trace_dir

    _write_trace(tmp_path / "trace-gw-1.jsonl",
                 [{"ts": 10.0, "name": "gateway/request", "trace_id": "T1",
                   "component": "gateway", "dur": 0.5}])
    _write_trace(tmp_path / "trace-rep-2.jsonl",
                 [{"ts": 10.2, "name": "serving/submit", "trace_id": "T1",
                   "component": "replica"},
                  {"ts": 9.0, "name": "other", "trace_id": "T2",
                   "component": "replica"}])
    events, _ = read_trace_dir(str(tmp_path))
    tl = merge_timeline(events, "T1")
    assert [e["name"] for e in tl] == ["gateway/request", "serving/submit"]
    assert {e["component"] for e in tl} == {"gateway", "replica"}
    assert merge_timeline(events)[0]["trace_id"] == "T2"  # global sort by ts


def test_perfetto_export_shape(tmp_path):
    from edl_tpu.obs.dump import to_perfetto

    events = [
        {"ts": 5.0, "name": "resize/detect", "component": "launcher",
         "trace_id": "T", "file": "trace-launcher-1.jsonl"},
        {"ts": 5.1, "name": "train/restore", "component": "trainer",
         "dur": 0.25, "trace_id": "T", "step": 7,
         "file": "trace-trainer-2.jsonl"},
    ]
    pf = to_perfetto(events)
    # valid JSON end to end (what Perfetto actually loads)
    pf = json.loads(json.dumps(pf))
    evs = pf["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(metas) == 2, "one process row per source file"
    spans = [e for e in evs if e["ph"] == "X"]
    (span,) = spans
    assert span["ts"] == 5.1e6 and span["dur"] == 0.25e6  # microseconds
    assert span["args"]["step"] == 7
    instants = [e for e in evs if e["ph"] == "i"]
    assert instants and instants[0]["name"] == "resize/detect"


def test_dump_merge_cli(tmp_path, capsys):
    from edl_tpu.obs import dump as obs_dump

    _write_trace(tmp_path / "trace-a-1.jsonl",
                 [{"ts": 1.0, "name": "a/one", "trace_id": "T",
                   "component": "a", "dur": 0.1},
                  {"ts": 2.0, "name": "bad"}])
    (tmp_path / "trace-a-1.jsonl").write_text(
        (tmp_path / "trace-a-1.jsonl").read_text() + '{"torn')
    out_json = tmp_path / "out.json"
    rc = obs_dump.main(["--merge", "--trace_dir", str(tmp_path),
                        "--trace", "T", "--perfetto", str(out_json)])
    assert rc == 0
    captured = capsys.readouterr()
    assert "a/one" in captured.out
    assert "skipped 1 malformed" in captured.err
    pf = json.loads(out_json.read_text())
    assert any(e.get("name") == "a/one" for e in pf["traceEvents"])
