"""Metrics collector (edl_tpu/obs/collector.py): store-sourced CSV rows
+ job-phase accounting."""

import json

from edl_tpu.cluster import paths
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.pod import Pod
from edl_tpu.cluster.status import Status, save_job_status, save_pod_status
from edl_tpu.cluster.train_status import TrainStatus, save_train_status
from edl_tpu.obs.collector import FIELDS, JobPhases, collect_row
from edl_tpu.utils import constants


def _seed_job(kv, job="j1"):
    pods = [Pod(pod_id=f"p{i}", port=7000 + i) for i in range(2)]
    for p in pods:
        p.make_trainers(2, [7100, 7101])
    cluster = Cluster.from_pods(pods)
    kv.put(paths.key(job, constants.ETCD_CLUSTER, "cluster"),
           cluster.to_json().encode())
    for p in pods:
        kv.put(paths.key(job, constants.ETCD_POD_RESOURCE, p.pod_id),
               p.to_json().encode())
        save_pod_status(kv, job, p.pod_id, Status.RUNNING)
        save_train_status(kv, job, p.pod_id, TrainStatus.RUNNING)
    save_job_status(kv, job, Status.RUNNING)
    return cluster


def test_collect_row_running_job(memkv):
    cluster = _seed_job(memkv)
    row = collect_row(memkv, "j1", now=100.0)
    assert list(row) == FIELDS
    assert row["job_status"] == Status.RUNNING.value
    assert row["stage"] == cluster.stage[:8]
    assert row["live_pods"] == 2 and row["cluster_pods"] == 2
    assert row["world_size"] == 4 and row["pods_running"] == 2
    assert row["train_status"] == f"{TrainStatus.RUNNING.value}:2"
    assert row["resizes"] == 0 and row["last_recovery_sec"] == ""


def test_collect_row_empty_store(memkv):
    row = collect_row(memkv, "ghost")
    assert row["job_status"] == "N/A"
    assert row["cluster_pods"] == 0 and row["world_size"] == 0


def test_collect_row_includes_recovery(memkv):
    _seed_job(memkv)
    stage = "s1"
    memkv.put(paths.key("j1", constants.ETCD_RECOVERY,
                        f"{stage}/launcher/p0"),
              json.dumps({"detect": 10.0, "killed": 10.5, "barrier": 11.0,
                          "spawn": 11.2}).encode())
    memkv.put(paths.key("j1", constants.ETCD_RECOVERY,
                        f"{stage}/trainer/p0"),
              json.dumps({"restored": 14.0, "first_step": 15.5}).encode())
    row = collect_row(memkv, "j1")
    assert row["resizes"] == 1
    assert row["last_recovery_sec"] == 5.5  # 15.5 - 10.0


def test_job_phases_accounting():
    ph = JobPhases()
    ph.observe({"job_id": "a", "ts": 1.0, "job_status": "N/A",
                "pods_running": 0})
    ph.observe({"job_id": "a", "ts": 4.0,
                "job_status": Status.RUNNING.value, "pods_running": 2})
    ph.observe({"job_id": "a", "ts": 10.0,
                "job_status": Status.SUCCEED.value, "pods_running": 0})
    (s,) = ph.summary()
    assert s == {"job_id": "a", "status": Status.SUCCEED.value,
                 "pending_sec": 3.0, "run_sec": 6.0}
