"""Rule engine: declarative specs, for: hold semantics, the builtin
ruleset's signals, incident records and their trace join."""

import json

import pytest

from edl_tpu.obs import dump as obs_dump
from edl_tpu.obs import rules as obs_rules
from edl_tpu.obs.metrics import REGISTRY, Registry, parse_exposition
from edl_tpu.obs.rules import (
    IncidentLog, Rule, RuleEngine, builtin_rules, load_rules, rule_from_dict,
)
from edl_tpu.obs.tsdb import TSDB


def _feed(t, name, values, labels=(), t0=1000.0, dt=1.0):
    for i, v in enumerate(values):
        t.ingest({(name, labels): float(v)}, t0 + i * dt)
    return t0 + (len(values) - 1) * dt


# -- spec parsing ------------------------------------------------------------

def test_rule_from_dict_and_for_alias():
    r = rule_from_dict({"name": "x", "kind": "rate", "metric": "m_total",
                        "for": 30, "threshold": 2, "severity": "critical"})
    assert r.for_s == 30.0 and r.threshold == 2 and r.severity == "critical"
    with pytest.raises(ValueError, match="unknown keys"):
        rule_from_dict({"name": "x", "kind": "rate", "metric": "m",
                        "nope": 1})
    with pytest.raises(ValueError, match="unknown kind"):
        Rule("x", kind="magic", metric="m")
    with pytest.raises(ValueError, match="unknown op"):
        Rule("x", kind="rate", metric="m", op="!=")


def test_load_rules_env_overrides_builtin(monkeypatch):
    override = [{"name": "trainer-hang", "kind": "stalled",
                 "metric": "edl_train_step_seconds_count",
                 "op": "<=", "threshold": 0.0, "window": 5, "for": 1},
                {"name": "custom", "kind": "gauge", "metric": "edl_g",
                 "threshold": 9}]
    monkeypatch.setenv("EDL_TPU_ALERT_RULES", json.dumps(override))
    rules = {r.name: r for r in load_rules()}
    assert rules["trainer-hang"].window == 5.0      # builtin replaced
    assert "custom" in rules
    assert "gateway-p99-slo" in rules               # other builtins kept

    monkeypatch.setenv("EDL_TPU_ALERT_BUILTIN", "0")
    only = {r.name for r in load_rules()}
    assert only == {"trainer-hang", "custom"}


def test_load_rules_from_file_and_malformed(tmp_path, monkeypatch):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([{"name": "filerule", "kind": "gauge",
                              "metric": "edl_g", "threshold": 1}]))
    monkeypatch.setenv("EDL_TPU_ALERT_RULES", str(p))
    assert any(r.name == "filerule" for r in load_rules())
    # malformed config is skipped, never fatal — builtins survive
    monkeypatch.setenv("EDL_TPU_ALERT_RULES", "[{broken json")
    assert {r.name for r in load_rules()} == {r.name
                                              for r in builtin_rules()}


def test_alert_scale_shrinks_builtin_windows(monkeypatch):
    base = {r.name: r for r in builtin_rules()}
    monkeypatch.setenv("EDL_TPU_ALERT_SCALE", "0.1")
    scaled = {r.name: r for r in builtin_rules()}
    assert scaled["trainer-hang"].window == pytest.approx(
        base["trainer-hang"].window * 0.1)
    assert scaled["trainer-hang"].for_s == pytest.approx(
        base["trainer-hang"].for_s * 0.1)


# -- state machine: pending -> firing -> resolved ----------------------------

def test_gauge_rule_for_hold_and_resolve():
    t = TSDB()
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0,
                window=60.0, for_s=10.0)
    eng = RuleEngine(t, [rule])
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    assert eng.evaluate(1000.0) == []               # pending, not firing
    pend = eng.to_json()["pending"]
    assert [a["alert"] for a in pend] == ["hot"]
    t.ingest({("edl_g", ()): 9.0}, 1009.0)
    assert eng.evaluate(1009.0) == []               # still inside for:
    t.ingest({("edl_g", ()): 9.0}, 1011.0)
    firing = eng.evaluate(1011.0)
    assert [a["alert"] for a in firing] == ["hot"]
    assert firing[0]["value"] == 9.0
    # condition clears -> resolved
    t.ingest({("edl_g", ()): 1.0}, 1012.0)
    assert eng.evaluate(1012.0) == []


def test_hold_interrupted_resets_pending():
    t = TSDB()
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0,
                window=60.0, for_s=10.0)
    eng = RuleEngine(t, [rule])
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    eng.evaluate(1000.0)
    t.ingest({("edl_g", ()): 1.0}, 1005.0)          # dips below mid-hold
    eng.evaluate(1005.0)
    t.ingest({("edl_g", ()): 9.0}, 1008.0)
    assert eng.evaluate(1008.0) == []               # hold restarted HERE
    # had the original 1000.0 hold survived the dip, this would fire
    assert eng.evaluate(1012.0) == []
    t.ingest({("edl_g", ()): 9.0}, 1018.0)
    assert eng.evaluate(1018.5) != []               # 1008 + for_s elapsed


def test_on_change_gauge_resolves_after_value_stops_changing():
    # the MTTR builtins: an event-style gauge ("last outage took Ns")
    # re-exported verbatim every scrape must NOT keep the alert latched
    # forever — staleness is measured from the value's last CHANGE
    t = TSDB()
    rule = Rule("mttr", kind="gauge", metric="edl_outage_s", op=">",
                threshold=5.0, window=10.0, on_change=True)
    eng = RuleEngine(t, [rule])
    t.ingest({("edl_outage_s", ()): 11.0}, 1000.0)   # slow outage observed
    assert [a["alert"] for a in eng.evaluate(1000.0)] == ["mttr"]
    for i in range(1, 20):                           # re-scraped, unchanged
        t.ingest({("edl_outage_s", ()): 11.0}, 1000.0 + i)
    assert [a["alert"]
            for a in eng.evaluate(1009.0)] == ["mttr"]  # inside window
    assert eng.evaluate(1019.0) == []                # aged out: resolved
    # a NEW slow outage re-fires
    t.ingest({("edl_outage_s", ()): 12.0}, 1020.0)
    assert [a["alert"] for a in eng.evaluate(1020.0)] == ["mttr"]
    # without on_change the same series would have stayed latched
    latched = Rule("latched", kind="gauge", metric="edl_outage_s", op=">",
                   threshold=5.0, window=10.0)
    eng2 = RuleEngine(t, [latched])
    assert [a["alert"] for a in eng2.evaluate(1030.0)] == ["latched"]


def test_stalled_rule_unknown_on_fresh_job_fires_on_stall():
    t = TSDB()
    rule = Rule("hang", kind="stalled", metric="edl_steps_total",
                op="<=", threshold=0.0, window=8.0, for_s=0.0,
                match={"component": "trainer"})
    eng = RuleEngine(t, [rule])
    lab = (("component", "trainer"),)
    t.ingest({("edl_steps_total", lab): 5.0}, 1000.0)
    assert eng.evaluate(1000.0) == []               # no history: unknown
    now = _feed(t, "edl_steps_total", range(10), labels=lab)
    assert eng.evaluate(now) == []                  # progressing
    for i in range(10):                             # counter freezes
        t.ingest({("edl_steps_total", lab): 9.0}, now + 1 + i)
    assert [a["alert"] for a in eng.evaluate(now + 10)] == ["hang"]


def test_outlier_rule_fires_per_instance():
    t = TSDB()
    rule = Rule("straggler", kind="outlier", metric="edl_step_seconds",
                by="instance", op=">", threshold=2.0, window=10.0,
                min_series=3)
    eng = RuleEngine(t, [rule])
    for i in range(5):
        page = {}
        for inst, step in (("a", 0.1), ("b", 0.1), ("c", 0.5)):
            page[("edl_step_seconds_sum",
                  (("instance", inst),))] = step * i
            page[("edl_step_seconds_count",
                  (("instance", inst),))] = float(i)
        t.ingest(page, 1000.0 + i)
    firing = eng.evaluate(1004.0)
    assert len(firing) == 1
    assert firing[0]["instance"] == "c"
    assert firing[0]["value"] == pytest.approx(5.0)  # 0.5 / median 0.1


def test_outlier_needs_min_series():
    t = TSDB()
    rule = Rule("straggler", kind="outlier", metric="edl_step_seconds",
                by="instance", threshold=2.0, window=10.0, min_series=3)
    eng = RuleEngine(t, [rule])
    for i in range(5):
        t.ingest({("edl_step_seconds_sum", (("instance", "a"),)): 0.5 * i,
                  ("edl_step_seconds_count", (("instance", "a"),)): float(i)},
                 1000.0 + i)
    assert eng.evaluate(1004.0) == []   # one series is not a fleet


def test_quantile_rule():
    t = TSDB()
    rule = Rule("slo", kind="quantile", metric="edl_lat_seconds", q=0.99,
                op=">", threshold=0.5, window=10.0)
    eng = RuleEngine(t, [rule])
    fast, slow, inf = ((("le", "0.1"),), (("le", "1.0"),), (("le", "+Inf"),))
    t.ingest({("edl_lat_seconds_bucket", fast): 100.0,
              ("edl_lat_seconds_bucket", slow): 100.0,
              ("edl_lat_seconds_bucket", inf): 100.0}, 1000.0)
    # window traffic lands entirely in (0.1, 1.0]: windowed p99 ~0.99
    t.ingest({("edl_lat_seconds_bucket", fast): 100.0,
              ("edl_lat_seconds_bucket", slow): 200.0,
              ("edl_lat_seconds_bucket", inf): 200.0}, 1005.0)
    firing = eng.evaluate(1005.0)
    assert [a["alert"] for a in firing] == ["slo"]
    assert firing[0]["value"] > 0.5


def test_vanished_group_resolves():
    t = TSDB(retention_s=5.0)
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=1.0,
                window=5.0, by="instance")
    eng = RuleEngine(t, [rule])
    t.ingest({("edl_g", (("instance", "a"),)): 9.0}, 1000.0)
    assert [a.get("instance") for a in eng.evaluate(1000.0)] == ["a"]
    # instance dies; its series ages out -> the alert resolves
    t.ingest({("edl_other", ()): 1.0}, 1030.0)
    assert eng.evaluate(1030.0) == []


def test_recording_rule_publishes_gauge():
    t = TSDB()
    rule = Rule("steps", kind="rate", metric="edl_steps_total",
                op=">", threshold=1e9, window=4.0,
                record="steps_per_s")
    eng = RuleEngine(t, [rule])
    now = _feed(t, "edl_steps_total", [0, 10, 20, 30, 40])
    eng.evaluate(now)
    g = REGISTRY.get("edl_alerts_recorded")
    assert g.labels(rule="steps_per_s", series="").value == pytest.approx(10.0)


# -- incidents: one write path, trace-joinable -------------------------------

def test_incident_log_written_and_joins_trace(tmp_path):
    t = TSDB()
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0,
                window=60.0, severity="critical", summary="too hot")
    log = IncidentLog(str(tmp_path), component="obs-agg", job_id="j")
    eng = RuleEngine(t, [rule], incident_log=log,
                     trace_provider=lambda: "feedc0de" * 4)
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    eng.evaluate(1000.0)
    t.ingest({("edl_g", ()): 0.0}, 1001.0)
    eng.evaluate(1001.0)

    with open(log.path, encoding="utf-8") as f:
        recs = [json.loads(line) for line in f]
    assert [r["state"] for r in recs] == ["firing", "resolved"]
    assert recs[0]["name"] == "alert/hot"
    assert recs[0]["trace_id"] == "feedc0de" * 4
    assert recs[0]["severity"] == "critical"
    assert recs[0]["job"] == "j"

    # the dump CLI's merge mode reads incidents next to trace files and
    # lands the alert inside that trace's causal timeline
    events, skipped = obs_dump.read_trace_dir(str(tmp_path))
    assert skipped == 0
    tl = obs_dump.merge_timeline(events, "feedc0de" * 4)
    assert [e["name"] for e in tl] == ["alert/hot", "alert/hot"]


def test_incident_trace_provider_failure_is_not_fatal(tmp_path):
    t = TSDB()
    rule = Rule("hot", kind="gauge", metric="edl_g", op=">", threshold=5.0)

    def boom():
        raise RuntimeError("store down")

    eng = RuleEngine(t, [rule],
                     incident_log=IncidentLog(str(tmp_path)),
                     trace_provider=boom)
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    assert [a["alert"] for a in eng.evaluate(1000.0)] == ["hot"]
    with open(eng.incidents.path, encoding="utf-8") as f:
        (rec,) = [json.loads(line) for line in f]
    assert "trace_id" not in rec


def test_firing_gauge_exported():
    t = TSDB()
    rule = Rule("gaugetest-hot", kind="gauge", metric="edl_g", op=">",
                threshold=5.0, severity="warning")
    eng = RuleEngine(t, [rule])
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    eng.evaluate(1000.0)
    parsed = parse_exposition(REGISTRY.render())
    assert parsed[("edl_alerts_firing",
                   (("alert", "gaugetest-hot"),
                    ("severity", "warning")))] == 1.0
    t.ingest({("edl_g", ()): 0.0}, 1001.0)
    eng.evaluate(1001.0)
    parsed = parse_exposition(REGISTRY.render())
    assert parsed[("edl_alerts_firing",
                   (("alert", "gaugetest-hot"),
                    ("severity", "warning")))] == 0.0


def test_bad_rule_does_not_kill_the_pass():
    t = TSDB()
    good = Rule("ok", kind="gauge", metric="edl_g", op=">", threshold=5.0)
    bad = Rule("bad", kind="gauge", metric="edl_g")
    bad.kind = "exploded"            # corrupt post-construction
    eng = RuleEngine(t, [bad, good])
    t.ingest({("edl_g", ()): 9.0}, 1000.0)
    assert [a["alert"] for a in eng.evaluate(1000.0)] == ["ok"]


# -- builtins sanity ---------------------------------------------------------

def test_builtin_ruleset_covers_the_repo_signals():
    names = {r.name for r in builtin_rules()}
    assert {"trainer-hang", "trainer-straggler", "data-starvation",
            "coord-mttr-regression", "data-leader-mttr-regression",
            "gateway-p99-slo", "gateway-reject-burn",
            "hang-restarts"} <= names
    for r in builtin_rules():
        assert r.kind in obs_rules.KINDS
        assert r.severity in ("warning", "critical")
