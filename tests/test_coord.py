"""Coordination store: KV semantics, leases, transactions, watches,
TTL-leased registration — over the in-process engine, the Python TCP
server, AND the native C++ daemon (csrc/coordd.cc), proving the
KVStore interface is pluggable (the reference ran these against a real
etcd; etcd_test.sh)."""

import subprocess
import time

import pytest

from edl_tpu.coord.memory import MemoryKV
from edl_tpu.coord.register import Register
from edl_tpu.utils.exceptions import EdlRegisterError


@pytest.fixture(scope="session")
def coordd_binary():
    from edl_tpu.native.build import ensure_coordd
    path = ensure_coordd()
    if path is None:
        pytest.skip("g++ unavailable; coordd not built")
    return path


@pytest.fixture
def coordd_client(coordd_binary):
    proc = subprocess.Popen([coordd_binary, "--host", "127.0.0.1",
                             "--port", "0"],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()  # "COORDD LISTENING <port>"
        port = int(line.split()[-1])
        from edl_tpu.coord.client import CoordClient
        client = CoordClient(f"127.0.0.1:{port}")
        yield client
        client.close()
    finally:
        proc.kill()
        proc.wait()


@pytest.fixture(params=["memory", "tcp", "native"])
def kv(request):
    if request.param == "memory":
        return request.getfixturevalue("memkv")
    if request.param == "tcp":
        return request.getfixturevalue("coord_client")
    return request.getfixturevalue("coordd_client")


def test_put_get_delete(kv):
    rev1 = kv.put("/a/b", b"1")
    rev2 = kv.put("/a/c", b"2")
    assert rev2 > rev1
    assert kv.get("/a/b").value == b"1"
    assert kv.get("/missing") is None
    recs, rev = kv.get_prefix("/a/")
    assert [r.key for r in recs] == ["/a/b", "/a/c"]
    assert rev >= rev2
    assert kv.delete("/a/b") is True
    assert kv.delete("/a/b") is False
    assert kv.delete_prefix("/a/") == 1
    assert kv.get_prefix("/a/")[0] == []


def test_lease_expiry_removes_keys(kv):
    lid = kv.lease_grant(0.4)
    kv.put("/lease/k", b"v", lid)
    assert kv.get("/lease/k") is not None
    time.sleep(1.0)
    assert kv.get("/lease/k") is None
    assert kv.lease_keepalive(lid) is False


def test_lease_keepalive_extends(kv):
    lid = kv.lease_grant(0.6)
    kv.put("/ka/k", b"v", lid)
    for _ in range(4):
        time.sleep(0.25)
        assert kv.lease_keepalive(lid) is True
    assert kv.get("/ka/k") is not None
    kv.lease_revoke(lid)
    assert kv.get("/ka/k") is None


def test_put_if_absent_leader_semantics(kv):
    l1 = kv.lease_grant(5)
    l2 = kv.lease_grant(5)
    assert kv.put_if_absent("/rank/0", b"pod-A", l1) is True
    # loser
    assert kv.put_if_absent("/rank/0", b"pod-B", l2) is False
    # idempotent re-seize by the holder (same value, same lease)
    assert kv.put_if_absent("/rank/0", b"pod-A", l1) is True
    # holder dies -> seat free
    kv.lease_revoke(l1)
    assert kv.put_if_absent("/rank/0", b"pod-B", l2) is True


def test_put_if_equals_guarded_write(kv):
    kv.put("/rank/0", b"leader-A")
    assert kv.put_if_equals("/rank/0", b"leader-A", "/cluster", b"c1") is True
    assert kv.get("/cluster").value == b"c1"
    assert kv.put_if_equals("/rank/0", b"leader-B", "/cluster", b"c2") is False
    assert kv.get("/cluster").value == b"c1"


def test_wait_sees_puts_and_deletes(kv):
    _, rev = kv.get_prefix("/w/")
    kv.put("/w/a", b"1")
    kv.delete("/w/a")
    res = kv.wait("/w/", rev, timeout=2.0)
    assert [e.type for e in res.events] == ["put", "delete"]
    # no further events -> timeout path returns empty
    res2 = kv.wait("/w/", res.revision, timeout=0.2)
    assert res2.events == []


def test_watch_prefix_callback(kv):
    seen = []
    watcher = kv.watch_prefix("/svc/", lambda evs: seen.extend(evs), period=0.5)
    time.sleep(0.2)
    kv.put("/svc/n1", b"x")
    deadline = time.time() + 5
    while not seen and time.time() < deadline:
        time.sleep(0.05)
    watcher.stop()
    assert seen and seen[0].record.key == "/svc/n1"


def test_register_keeps_key_alive_then_ttl_failover(kv):
    reg = Register(kv, "/root/job/resource/p0", b"pod0", ttl=0.6)
    time.sleep(1.5)  # several TTLs: heartbeat must keep it alive
    assert kv.get("/root/job/resource/p0").value == b"pod0"
    assert not reg.is_stopped
    # simulate pod death the way the reference's leader test does:
    # stop refreshing, lease expires, key vanishes
    reg.stop_heartbeat_only()
    time.sleep(1.2)
    assert kv.get("/root/job/resource/p0") is None


def test_exclusive_register_conflict(kv):
    reg = Register(kv, "/x/rank/0", b"A", ttl=2.0, exclusive=True)
    with pytest.raises(EdlRegisterError):
        Register(kv, "/x/rank/0", b"B", ttl=2.0, exclusive=True)
    reg.stop()
    reg2 = Register(kv, "/x/rank/0", b"B", ttl=2.0, exclusive=True)
    reg2.stop()


def test_exclusive_register_stops_on_lost_seat(memkv):
    """A deposed exclusive holder must stop immediately (leader election
    depends on prompt on-lose), never silently re-seize."""
    reg = Register(memkv, "/seat/0", b"A", ttl=0.6, exclusive=True)
    memkv.lease_revoke(reg._lease_id)  # simulate expiry + takeover window
    memkv.put("/seat/0", b"B")         # usurper
    deadline = time.time() + 5
    while not reg.is_stopped and time.time() < deadline:
        time.sleep(0.05)
    assert reg.is_stopped and reg.error is not None
    assert memkv.get("/seat/0").value == b"B"  # usurper untouched


def test_wait_compaction_snapshot(memkv):
    # blow past the event-log capacity; an old revision must get a snapshot
    memkv.put("/c/live", b"v")
    for i in range(5000):
        memkv.put("/junk/k", str(i).encode())
    res = memkv.wait("/c/", 0, timeout=0.5)
    assert any(e.record.key == "/c/live" for e in res.events)
