"""Model zoo: init/forward shapes, grad steps, sharded embedding tables."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from edl_tpu.models import (
    BowClassifier, CnnClassifier, LinearRegression, MnistCNN, ResNet18,
    ResNet50, ResNet50vd, TextTransformer, TransformerConfig, TransformerLM,
    VGG16, WideDeep, logical_axes_from_paths,
)
from edl_tpu.models.transformer import LOGICAL_RULES, lm_loss
from edl_tpu.models import wide_deep as wd_mod
from edl_tpu.parallel import MeshSpec, ShardingRules
from edl_tpu.train import ElasticTrainer, TrainConfig

KEY = jax.random.key(0)


def test_linear_forward():
    m = LinearRegression()
    params = m.init(KEY, jnp.ones((2, 13)))
    out = m.apply(params, jnp.ones((2, 13)))
    assert out.shape == (2, 1)


def test_mnist_cnn_forward():
    m = MnistCNN()
    x = jnp.ones((2, 28, 28, 1))
    params = m.init(KEY, x)
    assert m.apply(params, x).shape == (2, 10)


@pytest.mark.parametrize("ctor,extra_stem", [(ResNet18, False),
                                             (ResNet50, False),
                                             (ResNet50vd, True)])
def test_resnet_forward(ctor, extra_stem):
    m = ctor(num_classes=10)
    x = jnp.ones((1, 32, 32, 3))
    variables = m.init(KEY, x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (1, 10)
    assert out.dtype == jnp.float32
    assert ("stem1" in variables["params"]) == extra_stem
    # train mode returns updated batch stats
    out, mutated = m.apply(variables, x, train=True, mutable=["batch_stats"])
    assert "batch_stats" in mutated


def test_vgg_forward():
    m = VGG16(num_classes=7)
    x = jnp.ones((1, 32, 32, 3))
    variables = m.init(KEY, x, train=False)
    assert m.apply(variables, x, train=False).shape == (1, 7)


def test_text_models_forward():
    ids = jnp.ones((2, 16), jnp.int32)
    mask = jnp.ones((2, 16))
    for m in (BowClassifier(vocab_size=100), CnnClassifier(vocab_size=100),
              TextTransformer(vocab_size=100, num_layers=2, embed_dim=32,
                              num_heads=2, mlp_dim=64, max_len=32)):
        params = m.init(KEY, ids, mask)
        assert m.apply(params, ids, mask).shape == (2, 2)


def test_wide_deep_sharded_tables():
    mesh_spec = MeshSpec(dp=2, ep=4)
    model = WideDeep(vocab_sizes=(1000, 1000, 500), dense_features=4,
                     embed_dim=8, hidden=(16,))
    dense = np.ones((8, 4), np.float32)
    sparse = np.zeros((8, 3), np.int64)

    def loss_fn(params, extra, batch, rng):
        logit = model.apply({"params": params}, batch["dense"], batch["sparse"])
        labels = batch["y"]
        l = optax.sigmoid_binary_cross_entropy(logit, labels).mean()
        return l, (extra, {})

    tr = ElasticTrainer(loss_fn, TrainConfig(mesh_spec=mesh_spec, log_every=0))

    def init():
        v = model.init(KEY, jnp.asarray(dense), jnp.asarray(sparse))
        return v["params"], None

    logical = lambda params: logical_axes_from_paths(params, wd_mod.LOGICAL_RULES)
    params_shape = jax.eval_shape(lambda: init()[0])
    state = tr.create_state(init, optax.adam(1e-3),
                            param_logical=logical(params_shape))
    # embedding tables sharded over ep on the vocab dim
    assert state.params["embed_0"]["embedding"].sharding.spec[0] == "ep"
    from edl_tpu.parallel.sharding import shard_host_batch
    batch = shard_host_batch({"dense": dense, "sparse": sparse,
                              "y": np.ones((8,), np.float32)}, tr.mesh)
    state2, metrics = tr.step_fn(state, batch, KEY)
    assert np.isfinite(float(metrics["loss"]))


def test_transformer_lm_trains_and_rules_cover_params():
    cfg = TransformerConfig(vocab_size=128, num_layers=2, embed_dim=64,
                            num_heads=4, mlp_dim=128, max_len=32,
                            dtype=jnp.float32, attention_impl="dense",
                            remat=False)
    model = TransformerLM(cfg)
    ids = jax.random.randint(KEY, (2, 16), 0, 128)
    variables = model.init(KEY, ids)
    logits = model.apply(variables, ids)
    assert logits.shape == (2, 16, 128)

    # scanned layers: params have a leading layers dim
    qkv = variables["params"]["layers"]["attn_qkv"]["kernel"]
    assert qkv.shape[0] == 2

    logical = logical_axes_from_paths(variables["params"], LOGICAL_RULES)
    flat = jax.tree.leaves(logical, is_leaf=lambda x: isinstance(x, tuple))
    assert all(isinstance(t, tuple) for t in flat)

    # a couple of SGD steps reduce loss
    params = variables["params"]
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def lf(p):
            logits = model.apply({"params": p}, ids[:, :-1])
            return lm_loss(logits, ids[:, 1:])
        loss, grads = jax.value_and_grad(lf)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    params, opt, l0 = step(params, opt)
    for _ in range(5):
        params, opt, l = step(params, opt)
    assert float(l) < float(l0)


def test_transformer_tp_sharding_end_to_end():
    """TP+DP mesh: logits match the single-device model."""
    cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=16,
                            dtype=jnp.float32, attention_impl="dense",
                            remat=False)
    model = TransformerLM(cfg)
    ids = jax.random.randint(KEY, (4, 16), 0, 64)
    variables = model.init(KEY, ids)
    expected = model.apply(variables, ids)

    from edl_tpu.parallel import build_mesh, logical_sharding
    from edl_tpu.parallel.sharding import ShardingRules, shard_host_batch
    mesh = build_mesh(MeshSpec(dp=2, tp=4))
    rules = ShardingRules()
    logical = logical_axes_from_paths(variables["params"], LOGICAL_RULES)
    params = jax.tree.map(
        lambda x, ax: jax.device_put(x, logical_sharding(ax, mesh, rules)),
        variables["params"], logical)
    gids = shard_host_batch({"ids": np.asarray(ids)}, mesh, rules)["ids"]
    out = jax.jit(lambda p, i: model.apply({"params": p}, i))(params, gids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-3, atol=2e-3)


def test_auto_layout_decisions():
    """The shipped defaults must BE the fast configuration: unroll
    shallow stacks, remat only when the batch misses HBM (calibrated on
    measured v5e runs — the flagship trains un-remat'd at bs 8 and OOMs
    at bs 16 on 16 GB)."""
    from edl_tpu.models.transformer import TransformerConfig, auto_layout

    flag = TransformerConfig()          # 12L x 768, seq 1024
    bs8 = auto_layout(flag, 8, 1024, hbm_bytes=16.6e9)
    assert bs8.remat is False and bs8.scan_layers is False
    bs16 = auto_layout(flag, 16, 1024, hbm_bytes=16.6e9)
    assert bs16.remat is True
    deep = auto_layout(TransformerConfig(num_layers=48), 8, 1024,
                       hbm_bytes=16.6e9)
    assert deep.scan_layers is True and deep.remat is True
