"""TeacherServer unit behavior: request coalescing (concurrent students
share forward passes), stats accounting, per-request result slicing,
and clean shutdown under late requests."""

import threading

import numpy as np
import pytest

from edl_tpu.distill.predict_client import TeacherClient
from edl_tpu.distill.teacher import TeacherServer


def slow_identity_predict(delay=0.05):
    import time

    def predict(feed):
        time.sleep(delay)  # hold the inference thread so requests pile up
        x = feed["x"]
        return {"out": x * 2.0}
    return predict


def test_concurrent_requests_coalesce_and_slice_correctly():
    server = TeacherServer(slow_identity_predict(), buckets=(4, 8, 16, 32),
                           coalesce_wait_ms=20.0)
    try:
        results = {}

        def call(i):
            client = TeacherClient(server.endpoint, ["out"])
            x = np.full((4, 2), float(i), np.float32)
            results[i] = client.predict({"x": x})["out"]
            client.close()

        threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for i in range(6):
            assert results[i].shape == (4, 2)
            assert float(results[i][0, 0]) == 2.0 * i  # right rows came back
        stats = server.stats()
        assert stats["requests"] == 6 and stats["rows"] == 24
        # coalescing shared passes: fewer forwards than requests
        assert stats["forward_passes"] < 6, stats
        assert stats["rows_per_s"] > 0
    finally:
        server.stop()


def test_mixed_shapes_do_not_coalesce():
    """Drive the mixed-signature split in _infer DIRECTLY (timing-based
    coalescing can't be forced from the wire deterministically): two
    requests with different row widths must be served separately, each
    getting its own rows back."""
    from edl_tpu.distill.teacher import _Request

    server = TeacherServer(slow_identity_predict(0.0), buckets=(4, 8))
    try:
        a = _Request({"x": np.ones((4, 2), np.float32)}, ["out"], 4)
        b = _Request({"x": np.full((4, 3), 3.0, np.float32)}, ["out"], 4)
        results = server._infer([a, b])  # mixed widths: the split path
        assert results[0]["out"].shape == (4, 2)
        assert results[1]["out"].shape == (4, 3)
        assert float(results[1]["out"][0, 0]) == 6.0
        # two separate forward passes, one per signature
        assert server.stats()["forward_passes"] == 2
    finally:
        server.stop()


def test_stop_rejects_new_requests():
    server = TeacherServer(slow_identity_predict(0.0))
    server.stop()
    client = TeacherClient(server.endpoint, ["out"], retries=1,
                           timeout=2.0, first_timeout=2.0)
    with pytest.raises(ConnectionError):
        client.predict({"x": np.ones((2, 2), np.float32)})
    client.close()
