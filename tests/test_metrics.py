"""Metrics registry (edl_tpu/obs/metrics.py): thread-safe increments,
label handling, byte-exact Prometheus text exposition (and parsing it
back), and the stdlib /metrics HTTP endpoint."""

import threading
import urllib.error
import urllib.request

import pytest

from edl_tpu.obs.exposition import CONTENT_TYPE, MetricsServer
from edl_tpu.obs.metrics import Registry, parse_exposition


def test_concurrent_increments_from_threads():
    r = Registry()
    c = r.counter("ops_total", "ops", ("worker",))
    h = r.histogram("lat_seconds", "lat", buckets=(0.5,))
    g = r.gauge("depth", "depth")
    n, nthreads = 1000, 8

    def work(i):
        child = c.labels(worker=str(i % 2))
        for _ in range(n):
            child.inc()
            h.observe(0.1)
            g.inc()

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.labels(worker="0").value == n * nthreads / 2
    assert c.labels(worker="1").value == n * nthreads / 2
    assert h.count == n * nthreads
    assert abs(h.sum - 0.1 * n * nthreads) < 1e-6
    assert g.value == n * nthreads


def test_label_handling():
    r = Registry()
    c = r.counter("x_total", "x", ("a", "b"))
    c.labels("1", "2").inc()
    c.labels(b="2", a="1").inc()  # kwargs in any order: same child
    assert c.labels("1", "2").value == 2.0
    with pytest.raises(ValueError):
        c.labels("1")  # wrong arity
    with pytest.raises(ValueError):
        c.labels(a="1", z="2")  # unknown label
    with pytest.raises(ValueError):
        c.inc()  # labeled metric used without labels
    with pytest.raises(ValueError):
        c.labels("1", "2").inc(-1)  # counters only go up
    # get-or-create: identical spec returns the same instrument;
    # a different spec (labels or kind) is a registration error
    assert r.counter("x_total", "x", ("a", "b")) is c
    with pytest.raises(ValueError):
        r.counter("x_total", "x", ("a",))
    with pytest.raises(ValueError):
        r.gauge("x_total")


def test_exposition_byte_exact_and_parse_back():
    r = Registry()
    c = r.counter("edl_ops_total", "Operations served", ("op",))
    c.labels(op="get").inc(3)
    c.labels(op='we"ird\n').inc()
    r.gauge("edl_depth", "Queue depth").set(2.5)
    h = r.histogram("edl_lat_seconds", "Latency", buckets=(0.1, 1.0))
    h.observe(0.25)
    h.observe(0.5)
    h.observe(7.0)
    expected = (
        '# HELP edl_depth Queue depth\n'
        '# TYPE edl_depth gauge\n'
        'edl_depth 2.5\n'
        '# HELP edl_lat_seconds Latency\n'
        '# TYPE edl_lat_seconds histogram\n'
        'edl_lat_seconds_bucket{le="0.1"} 0.0\n'
        'edl_lat_seconds_bucket{le="1.0"} 2.0\n'
        'edl_lat_seconds_bucket{le="+Inf"} 3.0\n'
        'edl_lat_seconds_sum 7.75\n'
        'edl_lat_seconds_count 3.0\n'
        '# HELP edl_ops_total Operations served\n'
        '# TYPE edl_ops_total counter\n'
        'edl_ops_total{op="get"} 3.0\n'
        'edl_ops_total{op="we\\"ird\\n"} 1.0\n'
    )
    assert r.render() == expected

    parsed = parse_exposition(r.render())
    assert parsed[("edl_ops_total", (("op", "get"),))] == 3.0
    assert parsed[("edl_ops_total", (("op", 'we"ird\n'),))] == 1.0
    assert parsed[("edl_depth", ())] == 2.5
    assert parsed[("edl_lat_seconds_bucket", (("le", "+Inf"),))] == 3.0
    assert parsed[("edl_lat_seconds_count", ())] == 3.0
    assert parsed[("edl_lat_seconds_sum", ())] == 7.75


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_exposition("this is not { prometheus\n")


def test_histogram_gets_inf_bucket_and_labeled_children():
    r = Registry()
    h = r.histogram("h_seconds", "h", ("phase",), buckets=(1.0,))
    assert h.buckets[-1] == float("inf")
    h.labels(phase="a").observe(0.5)
    h.labels(phase="b").observe(2.0)
    parsed = parse_exposition(r.render())
    assert parsed[("h_seconds_bucket",
                   (("le", "1.0"), ("phase", "a")))] == 1.0
    assert parsed[("h_seconds_bucket",
                   (("le", "1.0"), ("phase", "b")))] == 0.0
    assert parsed[("h_seconds_count", (("phase", "b"),))] == 1.0


def test_metrics_http_endpoint():
    r = Registry()
    r.counter("up_total", "process up").inc()
    srv = MetricsServer(r, host="127.0.0.1", port=0).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            text = resp.read().decode()
        assert parse_exposition(text)[("up_total", ())] == 1.0
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5)
    finally:
        srv.stop()


def test_serve_from_env_writes_addr_file(tmp_path, monkeypatch):
    from edl_tpu.obs import exposition

    monkeypatch.setattr(exposition, "_server", None)
    monkeypatch.setenv("EDL_TPU_METRICS_PORT", "0")
    monkeypatch.setenv("EDL_TPU_METRICS_DIR", str(tmp_path))
    srv = exposition.serve_from_env("unit", Registry())
    try:
        assert srv is not None
        # idempotent: a second call returns the same server
        assert exposition.serve_from_env("unit") is srv
        (addr_file,) = tmp_path.glob("metrics-unit-*.addr")
        addr = addr_file.read_text().strip()
        assert addr.endswith(f":{srv.port}")
    finally:
        srv.stop()


def test_serve_from_env_disabled_without_env(monkeypatch):
    from edl_tpu.obs import exposition

    monkeypatch.setattr(exposition, "_server", None)
    monkeypatch.delenv("EDL_TPU_METRICS_PORT", raising=False)
    assert exposition.serve_from_env("unit") is None
