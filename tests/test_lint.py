"""edl-lint: fixture-driven check tests + baseline ratchet + repo smoke.

One minimal positive and negative fixture per check (the contract
doc/lint.md promises), the ratchet semantics (new finding fails, waived
finding passes, fixed finding flags the stale waiver), and a smoke run
over the real package asserting zero non-baselined findings — the same
gate scripts/ci.sh runs.
"""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from edl_tpu.lint import baseline as baseline_mod
from edl_tpu.lint import engine

REPO_ROOT = Path(__file__).resolve().parents[1]


def make_project(tmp_path: Path, files: dict[str, str],
                 docs: dict[str, str] | None = None) -> Path:
    """Write a throwaway mini-package under ``tmp_path/edl_tpu``."""
    for rel, text in files.items():
        p = tmp_path / "edl_tpu" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text), encoding="utf-8")
    for rel, text in (docs or {}).items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text), encoding="utf-8")
    return tmp_path


def run_checks(root: Path, *checks: str) -> list[engine.Finding]:
    return engine.run(root, checks=list(checks))


# -- blocking-under-lock -----------------------------------------------------
def test_blocking_under_lock_positive(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading, time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def bad_sleep(self):
                with self._lock:
                    time.sleep(1.0)

            def bad_rpc(self, client):
                with self._lock:
                    client.call("op")

            def bad_acquire_span(self, client):
                self._lock.acquire()
                client.call("op")
                self._lock.release()
    """})
    found = run_checks(root, "blocking-under-lock")
    msgs = [f.message for f in found]
    assert len(found) == 3, msgs
    assert any("time.sleep" in m for m in msgs)
    assert sum("client.call" in m for m in msgs) == 2


def test_blocking_under_lock_negative(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading, time

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def snapshot_then_call(self, client):
                with self._lock:
                    payload = dict(x=1)
                time.sleep(0.1)          # outside: fine
                client.call("op", **payload)

            def after_release(self, client):
                self._lock.acquire()
                self._lock.release()
                client.call("op")

            def cond_wait_is_fine(self):
                with self._cond:
                    self._cond.wait(0.1)  # releases the lock: idiomatic

            def nested_def_runs_later(self):
                with self._lock:
                    def gen():
                        time.sleep(1.0)   # executes OUTSIDE the lock
                    return gen
    """})
    assert run_checks(root, "blocking-under-lock") == []


def test_blocking_under_lock_transitive_ctor(tmp_path):
    # the BalanceTable.service() bug shape: a constructor that does
    # store I/O, called while holding the table lock
    root = make_project(tmp_path, {"svc.py": """
        import threading

        class Watcher:
            def __init__(self, store):
                self._recs = store.get_prefix("/x")

        class Table:
            def __init__(self, store):
                self._lock = threading.Lock()
                self._store = store
                self._w = None

            def bad(self):
                with self._lock:
                    self._w = Watcher(self._store)

            def helper(self):
                self._store.put("/k", b"v")

            def bad_self_call(self):
                with self._lock:
                    self.helper()
    """})
    found = run_checks(root, "blocking-under-lock")
    assert len(found) == 2, [f.message for f in found]
    assert any("Watcher(...)" in f.message and "get_prefix" in f.message
               for f in found)
    assert any("self.helper()" in f.message and "put" in f.message
               for f in found)


def test_blocking_under_lock_inline_waiver(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading

        class FileLog:
            def __init__(self, f):
                self._lock = threading.Lock()
                self._f = f

            def emit(self, line):
                # edl-lint: disable=blocking-under-lock — file lock:
                # serializing this write is the lock's purpose
                with self._lock:
                    self._f.write(line)
    """})
    assert run_checks(root, "blocking-under-lock") == []


# -- lock-order --------------------------------------------------------------
def test_lock_order_cycle_positive(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def f(self):
                with self._a_lock:
                    with self._b_lock:
                        return 1

            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        return 2
    """})
    found = run_checks(root, "lock-order")
    assert len(found) == 1, [f.message for f in found]
    assert "cycle" in found[0].message


def test_lock_order_reacquire_via_self_call(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading

        class Bad:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    return 1

        class FineRLock:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    return 1
    """})
    found = run_checks(root, "lock-order")
    assert len(found) == 1, [f.message for f in found]
    assert "non-reentrant" in found[0].message
    assert found[0].context.startswith("Bad.")


def test_lock_order_consistent_negative(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def f(self):
                with self._a_lock:
                    with self._b_lock:
                        return 1

            def g(self):
                with self._a_lock:
                    with self._b_lock:
                        return 2
    """})
    assert run_checks(root, "lock-order") == []


# -- wire-error --------------------------------------------------------------
def test_wire_error_handler_raise_positive(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        class Service:
            def __init__(self, server):
                server.register("op", self._op)

            def _op(self):
                self._validate()

            def _validate(self):
                raise ValueError("untyped across the wire")
    """})
    found = [f for f in run_checks(root, "wire-error")
             if "raise" in f.message]
    assert len(found) == 1
    assert "ValueError" in found[0].message
    assert "Service._op" in found[0].message


def test_wire_error_register_instance_cross_module(tmp_path):
    # class registered in ANOTHER module: its public methods are wire
    # surface; private helpers only through reachability
    root = make_project(tmp_path, {
        "cache.py": """
            class CacheService:
                def cache_get(self, key):
                    raise KeyError(key)

                def _internal(self):
                    raise RuntimeError("not wire surface by itself")
        """,
        "wiring.py": """
            from edl_tpu.cache import CacheService

            def wire(server, store):
                svc = CacheService()
                server.register_instance(svc)
        """})
    found = [f for f in run_checks(root, "wire-error")
             if "raise" in f.message]
    assert len(found) == 1, [f.message for f in found]
    assert "KeyError" in found[0].message


def test_wire_error_typed_raise_negative(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        from edl_tpu.utils.exceptions import EdlDataError

        class Service:
            def __init__(self, server):
                server.register("op", self._op)

            def _op(self):
                raise EdlDataError("typed: crosses the wire as itself")
    """})
    assert [f for f in run_checks(root, "wire-error")
            if "raise" in f.message] == []


def test_wire_error_swallow(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import logging

        def swallows():
            try:
                risky()
            except Exception:
                pass

        def logs():
            try:
                risky()
            except Exception:
                logging.getLogger(__name__).warning("risky failed")

        def reraises():
            try:
                risky()
            except Exception:
                raise

        def narrow_is_fine():
            try:
                risky()
            except OSError:
                pass
    """})
    found = [f for f in run_checks(root, "wire-error")
             if "swallows" in f.message]
    assert len(found) == 1
    assert found[0].context == "swallows"


# -- clock -------------------------------------------------------------------
def test_clock_positive(tmp_path):
    root = make_project(tmp_path, {
        "svc.py": """
            import time

            def bad_deadline(t0):
                return time.time() - t0

            def bad_compare(deadline):
                return time.time() > deadline
        """,
        "coord/wal.py": """
            from datetime import datetime

            def bad_now():
                return datetime.now()
        """})
    found = run_checks(root, "clock")
    assert len(found) == 3, [f.message for f in found]
    assert any("replay" in f.message for f in found)


def test_clock_negative(tmp_path):
    root = make_project(tmp_path, {
        "svc.py": """
            import time
            from datetime import datetime, timezone

            def timestamp_is_fine():
                return {"ts": time.time()}

            def monotonic_is_fine(t0):
                return time.monotonic() - t0
        """,
        "coord/wal.py": """
            from datetime import datetime, timezone

            def tz_aware_is_fine():
                return datetime.now(timezone.utc)
        """})
    assert run_checks(root, "clock") == []


# -- thread-hygiene ----------------------------------------------------------
def test_thread_hygiene_positive(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading

        def fire_and_forget(fn):
            threading.Thread(target=fn).start()
    """})
    found = run_checks(root, "thread-hygiene")
    assert len(found) == 1
    assert "daemon" in found[0].message


def test_thread_hygiene_negative(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import threading

        class S:
            def start(self, fn):
                self._t = threading.Thread(target=fn)   # joined in stop()
                self._t.start()

            def stop(self):
                self._t.join(timeout=5.0)

        def daemonized(fn):
            threading.Thread(target=fn, daemon=True).start()

        def local_joined(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
    """})
    assert run_checks(root, "thread-hygiene") == []


# -- knob-drift --------------------------------------------------------------
_KNOB_DOCS = {"README.md": "# x\n", "doc/usage.md": """
    `EDL_TPU_DOCUMENTED` is a knob.  The `EDL_TPU_FAMILY_*` knobs are
    a documented family.  `EDL_TPU_GONE` no longer exists.
"""}


def test_knob_drift(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        import os

        A = os.environ.get("EDL_TPU_DOCUMENTED", "")
        B = os.environ.get("EDL_TPU_UNDOCUMENTED", "")
        C = os.environ.get("EDL_TPU_FAMILY_MEMBER", "")
    """}, docs=_KNOB_DOCS)
    found = run_checks(root, "knob-drift")
    assert len(found) == 2, [f.message for f in found]
    undoc = [f for f in found if "EDL_TPU_UNDOCUMENTED" in f.message]
    stale = [f for f in found if "EDL_TPU_GONE" in f.message]
    assert undoc and undoc[0].path == "edl_tpu/svc.py"
    assert stale and stale[0].path == "doc/usage.md"


def test_knob_drift_docstring_mention_not_a_read(tmp_path):
    root = make_project(tmp_path, {"svc.py": '''
        """This docstring explains `EDL_TPU_ONLY_IN_DOCSTRING` history."""
        import os
        A = os.environ.get("EDL_TPU_DOCUMENTED", "")
    '''}, docs=_KNOB_DOCS)
    found = run_checks(root, "knob-drift")
    assert [f for f in found if "ONLY_IN_DOCSTRING" in f.message] == []


# -- metric-drift ------------------------------------------------------------
def test_metric_drift(tmp_path):
    root = make_project(tmp_path, {"svc.py": """
        from edl_tpu.obs import metrics as obs_metrics

        _A = obs_metrics.counter("edl_documented_total", "doc'd")
        _B = obs_metrics.gauge("edl_undocumented_bytes", "not doc'd")
        _H = obs_metrics.histogram("edl_latency_seconds", "doc'd by suffix")
    """}, docs={"doc/observability.md": """
        | `edl_documented_total` | counter |
        | `edl_latency_seconds_bucket` | histogram series |
        | `edl_vanished_total` | counter |
    """})
    found = run_checks(root, "metric-drift")
    assert len(found) == 2, [f.message for f in found]
    assert any("edl_undocumented_bytes" in f.message
               and f.path == "edl_tpu/svc.py" for f in found)
    assert any("edl_vanished_total" in f.message
               and f.path == "doc/observability.md" for f in found)


# -- baseline ratchet --------------------------------------------------------
_RATCHET_SRC = """
    import threading, time

    class S:
        def __init__(self):
            self._lock = threading.Lock()

        def bad(self):
            with self._lock:
                time.sleep(1.0)
"""


def test_baseline_waives_and_ratchets(tmp_path):
    root = make_project(tmp_path, {"svc.py": _RATCHET_SRC})
    found = run_checks(root, "blocking-under-lock")
    assert len(found) == 1
    bl = tmp_path / "lint_baseline.json"
    baseline_mod.save(bl, found)

    # waived finding passes
    new, stale, waived = baseline_mod.compare(
        run_checks(root, "blocking-under-lock"), baseline_mod.load(bl))
    assert not new and not stale and len(waived) == 1

    # a SECOND instance of the same defect in the same function is NEW
    # (occurrence index), even though the first is waived
    (tmp_path / "edl_tpu" / "svc.py").write_text(textwrap.dedent("""
        import threading, time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)
                    time.sleep(2.0)
    """), encoding="utf-8")
    new, stale, waived = baseline_mod.compare(
        run_checks(root, "blocking-under-lock"), baseline_mod.load(bl))
    assert len(new) == 1 and len(waived) == 1 and not stale
    assert new[0][0].endswith("#1")

    # fixing the defect turns the waiver STALE — the ratchet only
    # tightens: the key must be removed, it can't silently linger
    (tmp_path / "edl_tpu" / "svc.py").write_text(textwrap.dedent("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                return None
    """), encoding="utf-8")
    new, stale, waived = baseline_mod.compare(
        run_checks(root, "blocking-under-lock"), baseline_mod.load(bl))
    assert not new and not waived and len(stale) == 1


def test_baseline_keys_are_line_free(tmp_path):
    root = make_project(tmp_path, {"svc.py": _RATCHET_SRC})
    bl = tmp_path / "lint_baseline.json"
    baseline_mod.save(bl, run_checks(root, "blocking-under-lock"))
    # shift the finding by 30 lines: the waiver must still match
    src = (tmp_path / "edl_tpu" / "svc.py").read_text(encoding="utf-8")
    (tmp_path / "edl_tpu" / "svc.py").write_text(
        "# pad\n" * 30 + src, encoding="utf-8")
    new, stale, waived = baseline_mod.compare(
        run_checks(root, "blocking-under-lock"), baseline_mod.load(bl))
    assert not new and not stale and len(waived) == 1


def test_cli_exit_codes(tmp_path, capsys):
    from edl_tpu.lint.cli import main

    root = make_project(tmp_path, {"svc.py": _RATCHET_SRC})
    # no baseline file: the finding is new -> fail
    assert main(["--root", str(root),
                 "--checks", "blocking-under-lock"]) == 1
    assert main(["--root", str(root), "--checks", "blocking-under-lock",
                 "--update-baseline"]) == 0
    assert main(["--root", str(root),
                 "--checks", "blocking-under-lock"]) == 0
    capsys.readouterr()  # drop text output; --json shape checked next
    assert main(["--root", str(root), "--checks", "blocking-under-lock",
                 "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True and len(payload["waived"]) == 1
    assert main(["--root", str(root), "--checks", "bogus-check"]) == 2


def test_cli_reintroduced_fixed_pattern_fails(tmp_path):
    """The acceptance drill: a known-fixed blocking-under-lock shape
    (a coord put inside a generation/service lock) re-introduced into a
    clean tree makes the gate exit non-zero."""
    from edl_tpu.lint.cli import main

    root = make_project(tmp_path, {"data_server.py": """
        import threading

        class DataService:
            def __init__(self, store):
                self._gen_lock = threading.Lock()
                self._store = store

            def report(self, key, val):
                with self._gen_lock:
                    self._store.put(key, val)
    """})
    (root / "lint_baseline.json").write_text(
        json.dumps({"version": 1, "waivers": {}}), encoding="utf-8")
    assert main(["--root", str(root),
                 "--checks", "blocking-under-lock"]) == 1


def test_blocking_under_lock_inside_match(tmp_path):
    # review regression: match-case bodies are lock-scoped too
    root = make_project(tmp_path, {"svc.py": """
        import threading, time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def dispatch(self, cmd, client):
                match cmd:
                    case "bad":
                        with self._lock:
                            client.call("op")
                    case "nested":
                        with self._lock:
                            match cmd:
                                case _:
                                    time.sleep(1.0)
    """})
    found = run_checks(root, "blocking-under-lock")
    assert len(found) == 2, [f.message for f in found]


def test_partial_update_baseline_preserves_other_checks(tmp_path):
    """Review regression: `--checks X --update-baseline` must not drop
    the other checks' waivers from the grandfather list."""
    from edl_tpu.lint.cli import main

    root = make_project(tmp_path, {"svc.py": """
        import threading, time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)

        def swallows():
            try:
                bad()
            except Exception:
                pass
    """}, docs={"doc/observability.md": "# empty catalog\n"})
    assert main(["--root", str(root), "--update-baseline"]) == 0
    bl = baseline_mod.load(root / baseline_mod.BASELINE_NAME)
    assert set(bl) == {"blocking-under-lock", "wire-error"}
    # partial rewrite of ONE check keeps the other's waivers
    assert main(["--root", str(root), "--checks", "wire-error",
                 "--update-baseline"]) == 0
    bl2 = baseline_mod.load(root / baseline_mod.BASELINE_NAME)
    assert bl2 == bl
    # and the full gate still passes afterwards
    assert main(["--root", str(root)]) == 0


def test_check_registration_without_doc():
    from edl_tpu.lint.engine import CHECKS, CHECK_DOC, check

    @check("dummy-docless")
    def dummy(project):
        return []

    try:
        assert CHECK_DOC["dummy-docless"] == "dummy-docless"
        assert engine.check_ids()[-1] == "dummy-docless"
    finally:
        CHECKS.pop("dummy-docless", None)
        CHECK_DOC.pop("dummy-docless", None)


# -- smoke over the real repo ------------------------------------------------
def test_repo_lint_clean_against_baseline():
    """The CI gate, as a test: zero non-baselined findings and zero
    stale waivers over the real package with the committed baseline."""
    findings = engine.run(REPO_ROOT)
    waivers = baseline_mod.load(REPO_ROOT / baseline_mod.BASELINE_NAME)
    new, stale, _waived = baseline_mod.compare(findings, waivers)
    assert not new, "new lint findings:\n" + "\n".join(
        f.render() for _k, f in new)
    assert not stale, f"stale waivers (fixed findings — remove): {stale}"


def test_repo_knob_and_metric_catalogs_green():
    """Satellite contract: the drift checks pass with NO waivers."""
    findings = engine.run(REPO_ROOT, checks=["knob-drift", "metric-drift"])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_list_checks_names_all_seven():
    ids = engine.check_ids()
    assert ids == ["blocking-under-lock", "lock-order", "wire-error",
                   "clock", "thread-hygiene", "knob-drift", "metric-drift"]


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
