"""Reusable exactly-once audit over record spans.

The data plane's whole contract is one sentence — *the union of
trained spans equals the file set, and no record trains twice* — so
every test that claims it should assert it the same way.  Two entry
points:

- :func:`audit_spans` takes the RAW span log (one entry per trained
  batch, unmerged, possibly from many pods) and proves both halves:
  full coverage AND zero overlap.  Overlap is only detectable on raw
  logs — merged checkpoint spans absorb duplicates silently.
- :func:`audit_union` takes already-merged spans (a DataCheckpoint's
  ``processed`` list, the sidecar's per-epoch log) and proves coverage;
  it is the right check where only the merged record survives.

Both return a small stats dict so smokes can publish the counts
(``records_total`` / ``records_exactly_once`` / duplicates) into their
artifacts.
"""

from __future__ import annotations

from collections import Counter


def span_counts(spans) -> Counter:
    """(file_idx, record_no) -> times covered, from raw [f, b, e) spans."""
    counts: Counter = Counter()
    for file_idx, begin, end in spans:
        for record_no in range(int(begin), int(end)):
            counts[(int(file_idx), record_no)] += 1
    return counts


def audit_spans(spans, files: "dict[int, int] | int", per_file: int | None = None,
                allow_duplicates_of=None) -> dict:
    """Assert exactly-once delivery from a RAW (unmerged) span log.

    ``files`` is either ``{file_idx: record_count}`` or a file count
    (with ``per_file`` records each).  ``allow_duplicates_of`` — an
    iterable of ``(file_idx, record_no)`` — whitelists records that may
    legitimately appear twice: the consumed-but-unacked window of a
    SIGKILLed consumer (the documented at-least-once caveat).  Any
    duplicate outside the whitelist, and any gap, fails."""
    if isinstance(files, int):
        assert per_file is not None, "per_file required with a file count"
        files = {f: per_file for f in range(files)}
    expected = {(f, r) for f, n in files.items() for r in range(n)}
    counts = span_counts(spans)
    unexpected = sorted(set(counts) - expected)
    assert not unexpected, f"records outside the file set: {unexpected[:10]}"
    missing = sorted(expected - set(counts))
    assert not missing, (
        f"{len(missing)} records never trained (silent drop), e.g. "
        f"{missing[:10]}")
    allowed = set(allow_duplicates_of or ())
    dups = {k: c for k, c in counts.items() if c > 1}
    bad = sorted(set(dups) - allowed)
    assert not bad, (
        f"{len(bad)} records trained more than once outside the allowed "
        f"set, e.g. {[(k, dups[k]) for k in bad[:10]]}")
    return {
        "records_total": len(expected),
        "records_exactly_once": sum(1 for c in counts.values() if c == 1),
        "records_duplicated": len(dups),
        "max_multiplicity": max(counts.values(), default=0),
    }


def audit_union(spans, files: "dict[int, int] | int",
                per_file: int | None = None) -> dict:
    """Assert full coverage from MERGED spans: per file, the merged
    disjoint spans must be exactly ``[[0, n)]`` — a gap cannot produce
    that, and (because the input is already merged) duplicates are not
    observable here."""
    from edl_tpu.utils.spans import merge_span

    if isinstance(files, int):
        assert per_file is not None, "per_file required with a file count"
        files = {f: per_file for f in range(files)}
    merged: dict[int, list[list[int]]] = {}
    for file_idx, begin, end in spans:
        merge_span(merged.setdefault(int(file_idx), []), int(begin), int(end))
    for file_idx, n in files.items():
        assert merged.get(file_idx) == [[0, n]], (
            f"file {file_idx}: union {merged.get(file_idx)} != [[0, {n}]]")
    extra = sorted(set(merged) - set(files))
    assert not extra, f"spans for unknown files: {extra}"
    return {"records_total": sum(files.values()), "files": len(files)}
