"""Two-process ElasticTrainer.evaluate() with UNEVEN per-host batch
counts — would hang in an unmatched collective before the per-batch
has-next agreement (round-2 verdict weak #4).

Usage: eval_uneven.py <rank> <coordinator_port>
Prints ``EVAL_RESULT <json>`` on success; both ranks must agree.
"""

import json
import os
import sys


def main() -> None:
    rank, port = int(sys.argv[1]), sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = ""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2,
                               process_id=rank)
    assert jax.process_count() == 2

    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu.parallel import MeshSpec
    from edl_tpu.train import ElasticTrainer, TrainConfig

    def loss_fn(params, extra, batch, rng):
        return (params["w"] * batch["x"]).mean(), (extra, {})

    trainer = ElasticTrainer(loss_fn, TrainConfig(mesh_spec=MeshSpec()))
    state = trainer.create_state(lambda: ({"w": jnp.ones(())}, None),
                                 optax.sgd(0.1))

    def metric_fn(params, extra, batch):
        return {"mean_x": batch["x"][:, 0]}

    n_batches = 3 if rank == 0 else 1  # deliberately uneven

    def batches():
        for b in range(n_batches):
            x = np.asarray([[rank * 100 + b * 10 + i] for i in range(4)],
                           np.float32)
            yield {"x": x}

    result = trainer.evaluate(state, batches(), metric_fn)
    print("EVAL_RESULT", json.dumps({k: round(v, 4)
                                     for k, v in result.items()}), flush=True)


if __name__ == "__main__":
    main()
