"""Inert demo trainer for launcher integration tests.

Reference: python/edl/tests/unittests/launch_demo.py — reads the env
ABI, optionally sleeps, exits with an injected code
(``EDL_TPU_DEMO_EXIT_CODE``).  Also appends one line per start to
``EDL_TPU_DEMO_MARKER`` so tests can count restarts, and can sleep
longer while solo (``EDL_TPU_DEMO_SLEEP_SOLO``) so elastic-resize tests
get a stable join window.
"""

import os
import sys
import time

from edl_tpu.cluster.env import TrainerEnv


def main():
    te = TrainerEnv()
    marker = os.environ.get("EDL_TPU_DEMO_MARKER", "")
    if marker:
        with open(marker, "a") as f:
            f.write(f"start world={te.world_size} rank={te.global_rank} "
                    f"stage={te.cluster_stage}\n")
    print(f"demo trainer rank={te.global_rank}/{te.world_size} "
          f"pod={te.pod_id[:8]} stage={te.cluster_stage[:8]}", flush=True)

    if os.environ.get("EDL_TPU_DEMO_HANG_ONCE") and marker:
        # hang-watchdog fixture: on the FIRST start, write one liveness
        # beat then go silent (a deadlocked trainer); on restart, exit
        # normally — the launcher's watchdog must bridge the two
        with open(marker) as f:
            starts = sum(1 for _ in f)
        if starts == 1:
            from edl_tpu.cluster import heartbeat
            from edl_tpu.coord.client import connect

            store = connect(te.coord_endpoints)
            heartbeat.beat(store, te.job_id, te.pod_id)
            print("demo trainer hanging after one beat", flush=True)
            time.sleep(600)

    sleep = float(os.environ.get("EDL_TPU_DEMO_SLEEP", "1"))
    if te.world_size <= 1:
        sleep = float(os.environ.get("EDL_TPU_DEMO_SLEEP_SOLO", sleep))
    time.sleep(sleep)

    code = int(os.environ.get("EDL_TPU_DEMO_EXIT_CODE", "0"))
    print(f"demo trainer rank={te.global_rank} exiting {code}", flush=True)
    sys.exit(code)


if __name__ == "__main__":
    main()
