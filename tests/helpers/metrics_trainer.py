"""Instrumented inert trainer for the remediation smoke.

Runs as a REAL launcher's training script (reads the TrainerEnv ABI,
like demo_trainer.py) but behaves like a live trainer as far as the
observability loop is concerned:

- serves a /metrics endpoint with a live ``edl_train_step_seconds``
  histogram and a TTL-leased obs advert carrying the POD id (so the
  remediation dispatcher can map an alerting instance back to the pod
  it must act on);
- writes per-step liveness beats with a small published threshold;
- steps every ``EDL_TPU_SMOKE_STEP_S`` seconds (the straggler fixture
  sets a slower pace on one pod);
- STALLS — stops stepping AND beating, process alive, exactly like a
  wedged collective — while ``EDL_TPU_SMOKE_STALL_FILE`` exists;
- polls the stage preempt flag and exits ``PREEMPT_EXIT_CODE`` after a
  token "checkpoint", logging the per-pod eviction reason, exactly
  like the real trainer's non-delta preemption flow;
- appends one line per start to ``EDL_TPU_DEMO_MARKER`` so the smoke
  can count in-place restarts.

It never exits on its own — the smoke ends the jobs by killing the
launchers (or evicting the pods).
"""

import os
import sys
import time

from edl_tpu.cluster import heartbeat, preempt
from edl_tpu.cluster.env import TrainerEnv
from edl_tpu.coord.client import connect
from edl_tpu.obs import advert as obs_advert
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.metrics import Registry
from edl_tpu.utils import constants


def main() -> None:
    te = TrainerEnv()
    marker = os.environ.get("EDL_TPU_DEMO_MARKER", "")
    if marker:
        with open(marker, "a") as f:
            f.write(f"start pod={te.pod_id} stage={te.cluster_stage}\n")
    step_s = float(os.environ.get("EDL_TPU_SMOKE_STEP_S", "0.05"))
    stall_file = os.environ.get("EDL_TPU_SMOKE_STALL_FILE", "")
    threshold = float(os.environ.get("EDL_TPU_SMOKE_BEAT_THRESHOLD", "3"))

    reg = Registry()
    steps = reg.histogram("edl_train_step_seconds", "per-step wall time")
    srv = MetricsServer(reg, host="127.0.0.1").start()
    store = connect(te.coord_endpoints)
    handle = obs_advert.advertise_metrics(
        store, te.job_id, "trainer", srv.endpoint,
        name=f"trainer-{te.pod_id[:8]}-{os.getpid()}",
        extra={"pod": te.pod_id})
    print(f"metrics trainer up pod={te.pod_id[:8]} "
          f"stage={te.cluster_stage[:8]} metrics={srv.endpoint} "
          f"step_s={step_s}", flush=True)

    last_beat = 0.0
    last_poll = 0.0
    while True:
        stalled = stall_file and os.path.exists(stall_file)
        if not stalled:
            time.sleep(step_s)
            steps.observe(step_s)
            now = time.monotonic()
            if now - last_beat > min(1.0, threshold / 3.0):
                last_beat = now
                try:
                    heartbeat.beat(store, te.job_id, te.pod_id,
                                   threshold=threshold)
                except Exception as e:  # noqa: BLE001 — a blip is not fatal
                    print(f"beat failed: {e}", flush=True)
        else:
            time.sleep(0.2)     # wedged: no steps, no beats
        now = time.monotonic()
        if now - last_poll > 0.5:
            last_poll = now
            try:
                flagged = preempt.get_preempt(store, te.job_id,
                                              te.cluster_stage)
            except Exception:  # noqa: BLE001 — a blip is not a preempt
                flagged = None
            if flagged is not None:
                # token "checkpoint at the agreed step", then the
                # non-delta flow: every pod's trainers exit together
                time.sleep(0.1)
                reason = "peer-preempt"
                try:
                    info = preempt.pod_preempt_info(
                        store, te.job_id, te.cluster_stage, te.pod_id)
                    if info is not None:
                        reason = info[1]
                except Exception as e:  # noqa: BLE001 — best-effort
                    print(f"reason read failed: {e}", flush=True)
                print(f"preempt: exiting {constants.PREEMPT_EXIT_CODE} "
                      f"(reason={reason})", flush=True)
                handle.stop()
                sys.stdout.flush()
                os._exit(constants.PREEMPT_EXIT_CODE)


if __name__ == "__main__":
    main()
