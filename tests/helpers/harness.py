"""Shared bits for multi-process launcher tests."""

from __future__ import annotations

import signal

import psutil


def kill_tree(proc) -> None:
    """SIGKILL a subprocess and its whole child tree (launcher + trainer
    + data servers) — the hard-failure injection used by the elastic
    e2e tests."""
    try:
        parent = psutil.Process(proc.pid)
        victims = parent.children(recursive=True) + [parent]
    except psutil.NoSuchProcess:
        return
    for p in victims:
        try:
            p.send_signal(signal.SIGKILL)
        except psutil.NoSuchProcess:
            pass
