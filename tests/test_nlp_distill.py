"""NLP distillation (reference example/distill/nlp/*): transformer
teacher served over the wire → BOW/CNN student with KL-temperature
loss; the distilled student must beat the asymmetric-noise baseline."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE_DIR = os.path.join(REPO, "examples", "distill")


def run(student):
    sys.path.insert(0, EXAMPLE_DIR)
    try:
        from train_nlp_distill import main
    finally:
        sys.path.pop(0)
    return main(["--role", "local", "--student", student])


@pytest.mark.slow
@pytest.mark.parametrize("student", ["bow", "cnn"])
def test_nlp_distill_beats_noisy_baseline(student):
    summary = run(student)
    assert summary["teacher_acc"] >= 0.9, summary
    assert summary["distill_acc"] >= 0.8, summary
    assert summary["gain"] >= 0.2, summary
    assert summary["teacher_rows"] > 0 and summary["teacher_rows_per_s"] > 0
