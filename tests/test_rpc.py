"""RPC layer: round-trip, typed errors across the wire, binary payloads,
concurrent clients."""

import threading

import numpy as np
import pytest

from edl_tpu.rpc import RpcClient, RpcServer
from edl_tpu.utils.exceptions import EdlBarrierError, EdlInternalError


@pytest.fixture
def server():
    s = RpcServer("127.0.0.1", 0)
    s.register("echo", lambda **kw: kw)
    s.register("add", lambda a, b: {"sum": a + b})

    def barrier_not_ready():
        raise EdlBarrierError("3 of 4 pods arrived")

    def crash():
        raise RuntimeError("unexpected")

    s.register("nope", barrier_not_ready)
    s.register("crash", crash)
    s.start()
    yield s
    s.stop()


def test_roundtrip_and_errors(server):
    with RpcClient(f"127.0.0.1:{server.port}") as c:
        assert c.call("add", a=2, b=3)["sum"] == 5
        with pytest.raises(EdlBarrierError, match="3 of 4"):
            c.call("nope")
        with pytest.raises(EdlInternalError, match="unexpected"):
            c.call("crash")
        with pytest.raises(EdlInternalError, match="no such method"):
            c.call("missing_method")
        # connection still usable after typed errors
        assert c.call("add", a=1, b=1)["sum"] == 2


def test_binary_payload(server):
    arr = np.arange(1 << 16, dtype=np.float32)
    with RpcClient(f"127.0.0.1:{server.port}") as c:
        out = c.call("echo", blob=arr.tobytes(), shape=list(arr.shape))
    back = np.frombuffer(out["blob"], dtype=np.float32)
    assert back.shape == (1 << 16,) and np.array_equal(back, arr)


def test_concurrent_clients(server):
    errs = []

    def worker(i):
        try:
            with RpcClient(f"127.0.0.1:{server.port}") as c:
                for j in range(20):
                    assert c.call("add", a=i, b=j)["sum"] == i + j
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
