"""KV-cache generation == full-recompute generation, plus sampling knobs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models.generate import generate
from edl_tpu.models.transformer import TransformerConfig, TransformerLM

CFG = TransformerConfig(vocab_size=61, num_layers=2, embed_dim=32,
                        num_heads=4, mlp_dim=64, max_len=32,
                        dtype=jnp.float32, attention_impl="dense",
                        remat=False)


def _model_and_params(cfg=CFG, seed=0):
    model = TransformerLM(cfg)
    ids = jnp.zeros((2, 4), jnp.int32)
    params = model.init(jax.random.key(seed), ids)["params"]
    return model, params


def _greedy_full_recompute(model, params, prompt, n):
    """Reference path: re-run the whole prefix for every token."""
    ids = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, ids)
        nxt = logits[:, -1].argmax(-1).astype(jnp.int32)
        out.append(nxt)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_greedy_matches_full_recompute():
    model, params = _model_and_params()
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 61, (2, 5)), jnp.int32)
    want = _greedy_full_recompute(model, params, prompt, 8)
    got = generate(CFG, params, prompt, 8, temperature=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_gqa_cached_greedy_matches_full_recompute(kv_heads):
    """GQA/MQA decode (grouped cache, H/Hk-smaller) must be exact vs
    the training forward — the training path repeats kv heads, the
    decode path groups queries; both must implement the same map."""
    import dataclasses
    cfg = dataclasses.replace(CFG, num_kv_heads=kv_heads)
    model, params = _model_and_params(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 61, (2, 5)), jnp.int32)
    want = _greedy_full_recompute(model, params, prompt, 8)
    got = generate(cfg, params, prompt, 8, temperature=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gqa_cache_is_smaller():
    import dataclasses
    cfg = dataclasses.replace(CFG, num_kv_heads=1, decode=True)
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((2, 1), jnp.int32),
                           positions=jnp.zeros((2, 1), jnp.int32))
    ck = variables["cache"]["layer_0"]["cached_key"]
    # MQA: one kv head instead of 4 -> cache 4x smaller
    assert ck.shape == (2, 1, CFG.head_dim, CFG.max_len)


def test_generate_single_token_and_jit():
    _, params = _model_and_params()
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    got = jax.jit(lambda p, ids: generate(CFG, p, ids, 1, temperature=0))(
        params, prompt)
    assert got.shape == (1, 1)
    model = TransformerLM(CFG)
    want = model.apply({"params": params}, prompt)[:, -1].argmax(-1)
    assert int(got[0, 0]) == int(want[0])


def test_sampling_deterministic_under_rng():
    _, params = _model_and_params()
    prompt = jnp.asarray([[4, 5]], jnp.int32)
    a = generate(CFG, params, prompt, 6, rng=jax.random.key(3),
                 temperature=0.8, top_k=10)
    b = generate(CFG, params, prompt, 6, rng=jax.random.key(3),
                 temperature=0.8, top_k=10)
    c = generate(CFG, params, prompt, 6, rng=jax.random.key(4),
                 temperature=0.8, top_k=10)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 6)
    assert (np.asarray(a) != np.asarray(c)).any()  # rng actually matters
    assert np.asarray(a).max() < 61 and np.asarray(a).min() >= 0


def test_overflow_guard():
    _, params = _model_and_params()
    prompt = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generate(CFG, params, prompt, 10)


def test_bad_args_rejected():
    _, params = _model_and_params()
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(CFG, params, prompt, 0)


def test_moe_cached_greedy_matches_full_recompute():
    """With ample training capacity (nothing dropped), per-token decode
    routing must reproduce the capacity-based training forward."""
    import dataclasses

    cfg = dataclasses.replace(CFG, moe_experts=4, moe_top_k=2,
                              moe_capacity=4.0)
    model, params = _model_and_params(cfg, seed=4)
    prompt = jnp.asarray(
        np.random.default_rng(9).integers(0, 61, (2, 5)), jnp.int32)
    want = _greedy_full_recompute(model, params, prompt, 6)
    got = generate(cfg, params, prompt, 6, temperature=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cached_greedy_matches_full_recompute_bf16():
    """The precision recipe (input-dtype matmuls, f32 softmax) must keep
    cached decode token-identical to the full-prefix forward in bf16."""
    import dataclasses

    cfg = dataclasses.replace(CFG, dtype=jnp.bfloat16)
    model, params = _model_and_params(cfg, seed=2)
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, 61, (2, 6)), jnp.int32)
    want = _greedy_full_recompute(model, params, prompt, 6)
    got = generate(cfg, params, prompt, 6, temperature=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_moe_cached_greedy_matches_full_recompute_bf16():
    """bf16 MoE: gather-path decode must stay token-identical to the
    capacity-path training forward (ample capacity, nothing dropped)."""
    import dataclasses

    cfg = dataclasses.replace(CFG, dtype=jnp.bfloat16, moe_experts=4,
                              moe_top_k=2, moe_capacity=4.0)
    model, params = _model_and_params(cfg, seed=5)
    prompt = jnp.asarray(
        np.random.default_rng(11).integers(0, 61, (2, 5)), jnp.int32)
    want = _greedy_full_recompute(model, params, prompt, 6)
    got = generate(cfg, params, prompt, 6, temperature=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_top_p_sampling():
    """top_p -> tokens restricted to the nucleus; p->1 behaves like
    plain temperature sampling; p tiny behaves like greedy."""
    _, params = _model_and_params()
    prompt = jnp.asarray([[4, 5, 6]], jnp.int32)
    # a tiny nucleus keeps only the top token -> must equal greedy
    greedy = generate(CFG, params, prompt, 6, temperature=0)
    nucleus = generate(CFG, params, prompt, 6, rng=jax.random.key(0),
                       temperature=0.7, top_p=1e-9)
    np.testing.assert_array_equal(np.asarray(nucleus), np.asarray(greedy))
    # p=1 keeps everything: deterministic under a fixed rng, in range
    full = generate(CFG, params, prompt, 6, rng=jax.random.key(1),
                    temperature=0.9, top_p=1.0)
    again = generate(CFG, params, prompt, 6, rng=jax.random.key(1),
                     temperature=0.9, top_p=1.0)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(again))
    assert np.asarray(full).max() < CFG.vocab_size
    with pytest.raises(ValueError, match="top_p"):
        generate(CFG, params, prompt, 2, top_p=1.5)
