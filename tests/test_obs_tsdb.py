"""Ring-buffer TSDB: retention, counter-reset-aware rates, windowed
histogram quantiles, and the edge cases the rule engine leans on."""

import math

import pytest

from edl_tpu.obs.metrics import Registry, parse_exposition
from edl_tpu.obs.tsdb import TSDB, quantile_from_buckets


def _scrape(build):
    reg = Registry()
    build(reg)
    return parse_exposition(reg.render())


def _feed_counter(t, values, t0=1000.0, dt=1.0, name="edl_c_total"):
    for i, v in enumerate(values):
        t.ingest({(name, ()): float(v)}, t0 + i * dt)
    return t0 + (len(values) - 1) * dt


# -- ingestion / retention ---------------------------------------------------

def test_ingest_latest_and_retention():
    t = TSDB(retention_s=10.0)
    _feed_counter(t, range(20), t0=0.0)  # ts 0..19
    ((labels, ts, v),) = t.latest("edl_c_total")
    assert (labels, ts, v) == ((), 19.0, 19.0)
    # points older than retention were pruned on ingest
    inc = t.increase("edl_c_total", window=100.0, now=19.0)
    assert inc[""][1] <= 10.0 + 1e-9     # covered at most the retention

    # a series that stops being scraped is evicted after one window
    t.ingest({("edl_other", ()): 1.0}, 20.0)
    for i in range(12):
        t.ingest({("edl_c_total", ()): 30.0 + i}, 21.0 + i)
    assert t.latest("edl_other") == []
    assert t.series_count("edl_other") == 0


def test_max_points_ring():
    t = TSDB(retention_s=1e9, max_points=8)
    _feed_counter(t, range(100), t0=0.0)
    ((_, ts, v),) = t.latest("edl_c_total")
    assert v == 99.0
    inc = t.increase("edl_c_total", window=1e9, now=99.0)
    assert inc[""][0] == pytest.approx(7.0)  # only the ring's 8 points


def test_max_series_cap():
    t = TSDB(max_series=3)
    for i in range(10):
        t.ingest({(f"edl_s{i}", ()): 1.0}, 100.0)
    assert sum(t.series_count(f"edl_s{i}") for i in range(10)) == 3


# -- counter-reset-aware increase/rate ---------------------------------------

def test_increase_simple_and_rate():
    t = TSDB()
    now = _feed_counter(t, [0, 10, 20, 30, 40])
    assert t.increase("edl_c_total", 4.0, now=now)[""][0] == pytest.approx(40)
    assert t.rate("edl_c_total", 4.0, now=now)[""] == pytest.approx(10.0)


def test_increase_counter_reset_between_scrapes():
    # 0,10,20 then the process restarts: 5,15 — PromQL semantics: the
    # reset counts from zero, total increase 20 + 5 + 10 = 35
    t = TSDB()
    now = _feed_counter(t, [0, 10, 20, 5, 15])
    assert t.increase("edl_c_total", 4.0, now=now)[""][0] == pytest.approx(35)
    # and the rate can never go negative
    assert t.rate("edl_c_total", 4.0, now=now)[""] > 0


def test_rate_insufficient_coverage_is_unknown():
    t = TSDB()
    t.ingest({("edl_c_total", ()): 5.0}, 1000.0)
    t.ingest({("edl_c_total", ()): 6.0}, 1001.0)
    # 1s of history cannot answer a 60s window: unknown, NOT zero —
    # the hang rule must not fire on a just-started job
    assert t.rate("edl_c_total", 60.0, now=1001.0) == {}
    # but a covered window answers
    assert t.rate("edl_c_total", 1.2, now=1001.0)[""] == pytest.approx(1.0)


def test_rate_grouped_by_label():
    t = TSDB()
    for i in range(5):
        t.ingest({("edl_c_total", (("instance", "a"),)): float(i * 2),
                  ("edl_c_total", (("instance", "b"),)): float(i * 6)},
                 1000.0 + i)
    r = t.rate("edl_c_total", 4.0, now=1004.0, by="instance")
    assert r["a"] == pytest.approx(2.0)
    assert r["b"] == pytest.approx(6.0)
    # ungrouped: one summed series
    total = t.rate("edl_c_total", 4.0, now=1004.0)
    assert total[""] == pytest.approx(8.0)


def test_stalled_counter_rates_zero_not_unknown():
    t = TSDB()
    now = _feed_counter(t, [50] * 10)   # scrapes continue, value frozen
    assert t.rate("edl_c_total", 8.0, now=now)[""] == 0.0


# -- windowed histogram quantiles --------------------------------------------

def _hist_scrape(observations, buckets=(0.1, 1.0)):
    return _scrape(lambda r: [r.histogram("edl_h_seconds", "h",
                                          buckets=buckets).observe(o)
                              for o in observations])


def test_windowed_quantile_tracks_the_window_not_the_lifetime():
    t = TSDB()
    # first era: all fast (0.05s) — baseline scrape at t=0
    t.ingest(_hist_scrape([0.05] * 100), 1000.0)
    # second era: all slow (0.5s) land between the next scrapes
    t.ingest(_hist_scrape([0.05] * 100 + [0.5] * 50), 1010.0)
    t.ingest(_hist_scrape([0.05] * 100 + [0.5] * 100), 1020.0)
    # lifetime p50 is still 'fast' (150/200 obs <= 0.1) but the WINDOW
    # saw only slow traffic
    q = t.quantile_over_window("edl_h_seconds", 0.50, window=25.0,
                               now=1020.0)
    assert q is not None and q > 0.1
    # empty window: None (caller falls back to lifetime, marked)
    assert t.quantile_over_window("edl_h_seconds", 0.5, window=25.0,
                                  now=2000.0) is None


def test_window_buckets_sum_across_instances_and_survive_reset():
    t = TSDB()
    page_a = {("edl_h_seconds_bucket", (("instance", "a"), ("le", "0.1"))): 4.0,
              ("edl_h_seconds_bucket", (("instance", "a"), ("le", "+Inf"))): 6.0}
    page_b = {("edl_h_seconds_bucket", (("instance", "b"), ("le", "0.1"))): 10.0,
              ("edl_h_seconds_bucket", (("instance", "b"), ("le", "+Inf"))): 10.0}
    t.ingest({**page_a, **page_b}, 1000.0)
    grown = {k: v + 2.0 for k, v in page_a.items()}
    # instance b RESTARTED: cumulative counts fell back to ~0 then grew
    reset_b = {k: 1.0 for k in page_b}
    t.ingest({**grown, **reset_b}, 1005.0)
    w = t.window_buckets("edl_h_seconds", window=4.0, now=1005.0)
    # a contributed +2 per bucket; b's reset contributes its post-reset
    # absolute (1.0) — never a negative count
    assert w[0.1] == pytest.approx(3.0)
    assert w[math.inf] == pytest.approx(3.0)
    assert all(v >= 0 for v in w.values())


def test_mean_over_window_by_instance():
    t = TSDB()
    for i in range(4):
        t.ingest({
            ("edl_h_seconds_sum", (("instance", "a"),)): 0.1 * i,
            ("edl_h_seconds_count", (("instance", "a"),)): float(i),
            ("edl_h_seconds_sum", (("instance", "b"),)): 0.5 * i,
            ("edl_h_seconds_count", (("instance", "b"),)): float(i),
        }, 1000.0 + i)
    means = t.mean_over_window("edl_h_seconds", 3.0, now=1003.0,
                               by="instance")
    assert means["a"] == pytest.approx(0.1)
    assert means["b"] == pytest.approx(0.5)


# -- quantile_from_buckets edge cases (satellite) ----------------------------

def test_quantile_single_bucket_only_inf():
    # a histogram whose only bucket is +Inf carries no magnitude
    # information: the estimate collapses to the 0.0 floor, not a crash
    assert quantile_from_buckets({math.inf: 10.0}, 0.5) == 0.0
    assert quantile_from_buckets({math.inf: 10.0}, 0.99) == 0.0


def test_quantile_all_observations_in_inf_bucket():
    # every observation beyond the last finite bound: the classic
    # histogram_quantile answer is that bound
    b = {0.1: 0.0, 1.0: 0.0, math.inf: 50.0}
    assert quantile_from_buckets(b, 0.5) == pytest.approx(1.0)


def test_quantile_single_finite_bucket():
    b = {0.5: 7.0, math.inf: 7.0}
    q = quantile_from_buckets(b, 0.5)
    assert q is not None and 0.0 <= q <= 0.5


def test_quantile_empty_and_zero():
    assert quantile_from_buckets({}, 0.5) is None
    assert quantile_from_buckets({0.1: 0.0, math.inf: 0.0}, 0.5) is None


def test_windowed_quantile_counter_reset_between_scrapes():
    t = TSDB()
    t.ingest(_hist_scrape([0.05] * 40), 1000.0)
    # restart: fresh histogram, only 10 slow observations since boot
    t.ingest(_hist_scrape([0.5] * 10), 1010.0)
    q = t.quantile_over_window("edl_h_seconds", 0.5, window=15.0, now=1010.0)
    # the reset era contributes its absolute post-reset counts: all slow
    assert q is not None and q > 0.1
