"""In-memory peer checkpoint cache (edl_tpu/memstate): ring replica
placement, chunked shard RPC, CRC rejection, tee + cache-first restore
bit-identity, staleness/eviction fallbacks, and the recovery-record
``restore_source`` field.

Everything runs in-process on the 8-device virtual CPU mesh: pods are
(StateCacheService, RpcServer) pairs over a MemoryKV coordination
store — the launcher-integration strategy, without subprocesses.
"""

import functools
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu import memstate
from edl_tpu.cluster.state import State
from edl_tpu.memstate import placement
from edl_tpu.memstate import restore as ms_restore
from edl_tpu.memstate.service import StateCacheService
from edl_tpu.memstate.tee import StateCacheTee
from edl_tpu.rpc import chunks
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils.exceptions import EdlInternalError


# -- ring replica placement ---------------------------------------------------
def test_replica_placement_deterministic_and_never_self():
    pods = [f"pod-{i}" for i in range(6)]
    for owner in pods:
        r = placement.replica_for(owner, pods)
        assert r in pods and r != owner
        # pure function of the pod set: same answer on every caller
        assert r == placement.replica_for(owner, list(reversed(pods)))


def test_replica_placement_single_pod_and_two_pods():
    assert placement.replica_for("a", ["a"]) is None
    # two pods always pick each other — the 2-pod kill-one e2e relies
    # on exactly this
    assert placement.replica_for("a", ["a", "b"]) == "b"
    assert placement.replica_for("b", ["a", "b"]) == "a"


def test_replica_placement_stable_under_unrelated_change():
    """Consistent hashing: removing one pod must not re-home every
    other owner's replica (the rank-neighbor scheme would)."""
    pods = [f"pod-{i}" for i in range(10)]
    before = {o: placement.replica_for(o, pods) for o in pods}
    gone = "pod-7"
    after = {o: placement.replica_for(o, [p for p in pods if p != gone])
             for o in pods if o != gone}
    moved = [o for o in after if before[o] != after[o] and before[o] != gone]
    # owners whose replica was NOT the removed pod mostly keep it
    assert len(moved) <= 3, (moved, before, after)


# -- service + chunked RPC ----------------------------------------------------
@pytest.fixture
def pod(memkv):
    """One live cache pod: (service, server, client)."""
    srv = RpcServer("127.0.0.1", 0)
    svc = StateCacheService(memkv, "job", "pod-a")
    srv.register_instance(svc)
    srv.start()
    reg = memstate.advertise(memkv, "job", "pod-a",
                             f"127.0.0.1:{srv.port}", ttl=30)
    client = RpcClient(f"127.0.0.1:{srv.port}")
    yield svc, srv, client
    client.close()
    reg.stop()
    srv.stop()


def _push_shard(client, owner, step, key, data, chunk=1 << 16):
    n = chunks.push_bytes(
        functools.partial(client.call, "cache_put_chunk",
                          owner=owner, step=step, key=key),
        data, chunk_bytes=chunk)
    return n, {key: {"crc": zlib.crc32(data), "nbytes": len(data),
                     "dtype": "uint8", "shape": [len(data)],
                     "index": [[0, len(data)]], "gshape": [len(data)],
                     "leaf": key}}


def test_chunked_shard_roundtrip(pod):
    svc, srv, client = pod
    data = np.random.default_rng(0).bytes(3 * (1 << 20) + 17)  # ~3 MB
    n, manifest = _push_shard(client, "pod-a", 5, "['w']@0:N", data)
    assert n == -(-len(data) // (1 << 16))  # really went in chunks
    assert client.call("cache_commit", owner="pod-a", step=5,
                       manifest=manifest, meta=b"{}")["ok"]
    got = chunks.fetch_bytes(
        functools.partial(client.call, "cache_fetch",
                          owner="pod-a", key="['w']@0:N"),
        len(data), chunk_bytes=1 << 16)
    assert got == data
    listing = client.call("cache_manifest")
    assert listing["pod-a"]["step"] == 5
    assert listing["pod-a"]["has_meta"]


def test_chunk_sequence_violation_rejected(pod):
    svc, srv, client = pod
    client.call("cache_put_chunk", owner="pod-a", step=1, key="k",
                seq=0, data=b"xx", eof=False)
    with pytest.raises(EdlInternalError):
        client.call("cache_put_chunk", owner="pod-a", step=1, key="k",
                    seq=5, data=b"yy", eof=True)  # hole in the stream


def test_commit_rejects_bad_crc(pod):
    svc, srv, client = pod
    data = b"a" * 1024
    _, manifest = _push_shard(client, "pod-a", 2, "k", data)
    manifest["k"]["crc"] = 123  # wrong
    with pytest.raises(EdlInternalError):
        client.call("cache_commit", owner="pod-a", step=2,
                    manifest=manifest, meta=None)
    # the poisoned staging is dropped; nothing committed
    assert client.call("cache_manifest") == {}


def test_memory_cap_rejects_push(memkv):
    srv = RpcServer("127.0.0.1", 0)
    svc = StateCacheService(memkv, "job", "pod-cap", max_bytes=64)
    srv.register_instance(svc)
    srv.start()
    try:
        client = RpcClient(f"127.0.0.1:{srv.port}")
        with pytest.raises(EdlInternalError):
            client.call("cache_put_chunk", owner="pod-cap", step=1, key="k",
                        seq=0, data=b"z" * 128, eof=True)
        client.close()
    finally:
        srv.stop()


def test_memory_cap_allows_superseding_step(memkv):
    """A cap between 1x and 2x the working set must not deadlock: the
    owner's committed step N set is superseded by step N+1's staging,
    so it does not count against the cap; commit evicts it."""
    srv = RpcServer("127.0.0.1", 0)
    svc = StateCacheService(memkv, "job", "pod-cap2", max_bytes=48)
    srv.register_instance(svc)
    srv.start()
    try:
        client = RpcClient(f"127.0.0.1:{srv.port}")
        data = b"a" * 40  # ~0.83x of the cap: two sets never co-fit
        for step in (1, 2):
            _, manifest = _push_shard(client, "pod-cap2", step, "k", data)
            assert client.call("cache_commit", owner="pod-cap2", step=step,
                               manifest=manifest, meta=b"{}")["ok"]
        listing = client.call("cache_manifest")
        assert listing["pod-cap2"]["step"] == 2  # replaced, not wedged
        client.close()
    finally:
        srv.stop()


# -- tee + cache-first restore ------------------------------------------------
def _two_pods(memkv):
    pods = {}
    for pid in ("pod-a", "pod-b"):
        srv = RpcServer("127.0.0.1", 0)
        svc = StateCacheService(memkv, "job", pid)
        srv.register_instance(svc)
        srv.start()
        reg = memstate.advertise(memkv, "job", pid,
                                 f"127.0.0.1:{srv.port}", ttl=30)
        pods[pid] = (svc, srv, reg)
    return pods


def _teardown(pods):
    for svc, srv, reg in pods.values():
        reg.stop()
        srv.stop()


def _wait_sealed(memkv, step, timeout=30.0):
    deadline = time.monotonic() + timeout
    while memstate.read_committed_step(memkv, "job") != step:
        assert time.monotonic() < deadline, "tee never sealed the step"
        time.sleep(0.02)


def _state_and_abstract():
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    state = {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8), sh),
        "b": jax.device_put(np.linspace(0, 1, 6).astype(np.float32), rep),
        "step": jax.device_put(np.int32(7), rep),
    }
    # restore target RESHARDED: w replicated, b dp-sharded (pad to 8?
    # 6 doesn't divide 4 -> keep replicated), proving old/new meshes
    # need not agree
    abstract = {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=rep),
        "b": jax.ShapeDtypeStruct((6,), jnp.float32, sharding=rep),
        "step": jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
    }
    return state, abstract


def test_tee_restore_bit_identical_and_resharded(memkv, tmp_path):
    from edl_tpu.train.checkpoint import CheckpointManager
    pods = _two_pods(memkv)
    try:
        state, abstract = _state_and_abstract()
        tee = StateCacheTee(memkv, "job", "pod-a")
        ck = CheckpointManager(str(tmp_path / "ck"), tee=tee)
        assert ck.save(7, state, State(total_batch_size=32))
        ck.wait()
        _wait_sealed(memkv, 7)

        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=7)
        assert res is not None, "expected a cache hit"
        got, meta_json, info = res
        assert info["step"] == 7 and info["shards"] >= 3
        for k in state:
            assert np.array_equal(np.asarray(got[k]), np.asarray(state[k])), k
        assert got["w"].sharding == abstract["w"].sharding  # resharded
        assert State().from_json(meta_json).total_batch_size == 32
        # the cache path and the storage path agree bit for bit
        stored = ck.restore(abstract)
        assert stored is not None
        ms_restore.assert_bit_identical(got, stored[0])
        ck.close()
    finally:
        _teardown(pods)


def test_restore_survives_owner_pod_loss(memkv, tmp_path):
    """The 2-pod kill-one scenario: pod-a saves, dies; its ring replica
    on pod-b alone serves the restore."""
    from edl_tpu.train.checkpoint import CheckpointManager
    pods = _two_pods(memkv)
    try:
        state, abstract = _state_and_abstract()
        tee = StateCacheTee(memkv, "job", "pod-a")
        ck = CheckpointManager(str(tmp_path / "ck"), tee=tee)
        assert ck.save(7, state, State())
        ck.wait()
        _wait_sealed(memkv, 7)
        # replication to pod-b is async: wait for its copy
        deadline = time.monotonic() + 30
        while "pod-a" not in pods["pod-b"][0].cache_manifest():
            assert time.monotonic() < deadline, "replica never landed"
            time.sleep(0.02)
        # kill pod-a: server down, advert gone
        pods["pod-a"][2].stop()
        pods["pod-a"][1].stop()
        memkv.delete("/edl_tpu/job/memstate/nodes/pod-a")

        res = ms_restore.try_restore(memkv, "job", abstract, expect_step=7)
        assert res is not None, "replica on pod-b should serve the restore"
        got, _meta, info = res
        assert info["peers"] == ["pod-b"]
        assert np.array_equal(np.asarray(got["w"]), np.asarray(state["w"]))
        ck.close()
    finally:
        _teardown({k: v for k, v in pods.items() if k != "pod-a"})


def test_restore_checksum_rejection_falls_back(memkv, tmp_path):
    from edl_tpu.train.checkpoint import CheckpointManager
    pods = _two_pods(memkv)
    try:
        state, abstract = _state_and_abstract()
        tee = StateCacheTee(memkv, "job", "pod-a")
        ck = CheckpointManager(str(tmp_path / "ck"), tee=tee)
        assert ck.save(7, state, State())
        ck.wait()
        _wait_sealed(memkv, 7)
        deadline = time.monotonic() + 30
        while "pod-a" not in pods["pod-b"][0].cache_manifest():
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # corrupt EVERY copy of one shard, owner and replica alike
        for svc, _srv, _reg in pods.values():
            sset = svc._sets["pod-a"]
            for key in list(sset.shards):
                if "w" in key:
                    sset.shards[key] = b"\x00" * len(sset.shards[key])
        assert ms_restore.try_restore(memkv, "job", abstract,
                                      expect_step=7) is None
        # ...but the storage path still restores fine (the fallback)
        stored = ck.restore(abstract)
        assert stored is not None
        assert np.array_equal(np.asarray(stored[0]["w"]),
                              np.asarray(state["w"]))
        ck.close()
    finally:
        _teardown(pods)


def test_restore_refuses_stale_and_missing_record(memkv, tmp_path):
    from edl_tpu.train.checkpoint import CheckpointManager
    pods = _two_pods(memkv)
    try:
        state, abstract = _state_and_abstract()
        # no committed record at all -> miss
        assert ms_restore.try_restore(memkv, "job", abstract,
                                      expect_step=1) is None
        tee = StateCacheTee(memkv, "job", "pod-a")
        ck = CheckpointManager(str(tmp_path / "ck"), tee=tee)
        assert ck.save(7, state, State())
        ck.wait()
        _wait_sealed(memkv, 7)
        # storage moved on (step 9) but the cache still holds 7 -> stale
        assert ms_restore.try_restore(memkv, "job", abstract,
                                      expect_step=9) is None
        ck.close()
    finally:
        _teardown(pods)


# -- recovery record carries the source ---------------------------------------
def test_trainer_half_records_restore_source(memkv):
    from edl_tpu.cluster.recovery import (
        summarize_recovery, write_launcher_half, write_trainer_half,
    )
    write_launcher_half(memkv, "j", "stg", "p1",
                        {"detect": 10.0, "killed": 11.0, "barrier": 12.0,
                         "spawn": 13.0})
    write_trainer_half(memkv, "j", "stg", "p1", restored=15.0,
                       first_step=16.0, restore_source="peer")
    [entry] = summarize_recovery(memkv, "j")
    assert entry["restore_source"] == "peer"
    # one pod falling back to storage downgrades the stage's source
    write_trainer_half(memkv, "j", "stg", "p2", restored=15.5,
                       first_step=16.5, restore_source="storage")
    [entry] = summarize_recovery(memkv, "j")
    assert entry["restore_source"] == "storage"
