"""Regression tests for rerun-after-failure cleanup and fail-grace.

Covers the findings: stale unleased pod_status records disabling
scale-out on a job_id rerun, and collateral trainer crashes failing the
job before the membership change arrives.
"""

import time

from edl_tpu.cluster import paths
from edl_tpu.cluster.status import (
    Status, load_job_status, load_pods_status, save_job_status, save_pod_status,
)
from edl_tpu.collective.launch import clear_stale_job_tables
from edl_tpu.collective.resource import load_resource_pods, register_pod
from edl_tpu.utils import constants
from tests.test_cluster_model import make_pod
from tests.test_elastic_control import wait_for

JOB = "job-rerun"


def test_clear_stale_tables_on_dead_job(memkv):
    # dead run left unleased records behind
    save_pod_status(memkv, JOB, "deadpod", Status.SUCCEED)
    save_job_status(memkv, JOB, Status.FAILED)
    memkv.put(paths.key(JOB, constants.ETCD_CLUSTER, "cluster"), b"{}")
    memkv.put(paths.key(JOB, constants.ETCD_STATE, "state"), b"keepme")

    clear_stale_job_tables(memkv, JOB)
    assert load_pods_status(memkv, JOB) == {}
    assert load_job_status(memkv, JOB) is None
    # state (data checkpoint) survives for resume
    assert memkv.get(paths.key(JOB, constants.ETCD_STATE, "state")).value == b"keepme"


def test_clear_skipped_while_job_live(memkv):
    # a provisionally-FAILED flag with live pods = elastically recovering
    # run; a relaunching pod must not wipe its records
    pod = make_pod()
    reg = register_pod(memkv, JOB, pod, ttl=5.0)
    assert wait_for(lambda: pod.pod_id in load_resource_pods(memkv, JOB))
    save_pod_status(memkv, JOB, pod.pod_id, Status.RUNNING)
    save_job_status(memkv, JOB, Status.FAILED)

    clear_stale_job_tables(memkv, JOB)  # we are a scale-out joiner: no-op
    assert load_pods_status(memkv, JOB) == {pod.pod_id: Status.RUNNING}
    assert load_job_status(memkv, JOB) == Status.FAILED
    reg.stop()


def test_clear_noop_on_fresh_job(memkv):
    # no FAILED flag → never clean (simultaneous fresh launch is safe)
    save_pod_status(memkv, JOB, "earlybird", Status.INITIAL)
    clear_stale_job_tables(memkv, JOB)
    assert load_pods_status(memkv, JOB) == {"earlybird": Status.INITIAL}


def test_clear_claimed_once(memkv):
    save_pod_status(memkv, JOB, "deadpod", Status.SUCCEED)
    save_job_status(memkv, JOB, Status.FAILED)
    clear_stale_job_tables(memkv, JOB)        # claims + cleans
    save_pod_status(memkv, JOB, "newpod", Status.INITIAL)
    clear_stale_job_tables(memkv, JOB)        # no flag → no-op
    assert load_pods_status(memkv, JOB) == {"newpod": Status.INITIAL}


class _FakeWatcher:
    def __init__(self):
        self.changed = False

    def stop(self):
        pass


def test_supervise_grace_turns_peer_crash_into_resize(monkeypatch):
    """A local FAILED followed by a membership change inside the grace
    window must return None (resize), not FAILED."""
    from edl_tpu.collective import launcher as launcher_mod

    monkeypatch.setattr(launcher_mod.constants, "FAIL_GRACE", 0.3)
    lch = launcher_mod.Launcher.__new__(launcher_mod.Launcher)
    lch._procs = []
    lch._period = 0.02
    lch._ttl = 0.2
    import threading as _t
    lch._preempt_event = _t.Event()
    lch._preempt_stage = None
    lch._preempt_deadline = None

    class _Alive:
        is_stopped = False
    lch._resource_register = _Alive()
    lch._elector = _Alive()

    monkeypatch.setattr(launcher_mod.train_process, "watch_procs",
                        lambda procs: Status.FAILED)
    watcher = _FakeWatcher()

    # membership change arrives 0.1 s after the crash
    def flip():
        time.sleep(0.1)
        watcher.changed = True
    import threading
    threading.Thread(target=flip, daemon=True).start()
    assert lch._supervise(watcher, None) is None

    # no membership change → grace expires → FAILED
    watcher2 = _FakeWatcher()
    start = time.monotonic()
    assert lch._supervise(watcher2, None) == Status.FAILED
    assert time.monotonic() - start >= lch._fail_grace()
