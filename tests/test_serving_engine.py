"""Continuous batching engine (edl_tpu/serving/engine.py).

The load-bearing property is slot independence: a request decoded
while other slots churn must match the same request decoded alone.
Greedy sampling makes that exact, so parity against
models/generate.generate() is the core assertion.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edl_tpu.models import TransformerConfig, TransformerLM
from edl_tpu.models.generate import generate
from edl_tpu.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def small():
    cfg = TransformerConfig(vocab_size=97, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    return cfg, params


def _engine(cfg, params, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("temperature", 0.0)
    kw.setdefault("steps_per_sync", 4)
    return ContinuousBatcher(cfg, params, **kw)


def test_greedy_parity_vs_generate(small):
    cfg, params = small
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 97, (n,)).astype(np.int32)
               for n in (3, 7, 12, 5, 9, 16, 2)]
    news = [6, 3, 9, 12, 1, 5, 8]
    eng = _engine(cfg, params)
    try:
        futs = [eng.submit(p, n) for p, n in zip(prompts, news)]
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.stop()
    for p, n, out in zip(prompts, news, got):
        want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), n,
                                   temperature=0.0))[0]
        np.testing.assert_array_equal(out, want)


def test_queue_deeper_than_slots(small):
    # more requests than slots: every future resolves, slots recycle
    cfg, params = small
    rng = np.random.default_rng(1)
    eng = _engine(cfg, params, slots=2)
    try:
        futs = [eng.submit(rng.integers(1, 97, (4,)).astype(np.int32), 5)
                for _ in range(9)]
        outs = [f.result(timeout=120) for f in futs]
        stats = eng.stats()
    finally:
        eng.stop()
    assert all(len(o) == 5 for o in outs)
    assert stats["requests_done"] == 9
    assert stats["queue_depth"] == 0
    assert 0.0 < stats["slot_utilization"] <= 1.0
    assert stats["moe_prefill_drops"] == 0     # dense config never drops


def test_engine_counts_moe_prefill_drops():
    """Continuous-batching prefill surfaces MoE capacity overflow."""
    import dataclasses

    cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32,
                            moe_experts=4, moe_top_k=2, moe_capacity=0.05)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    prompt = np.random.default_rng(3).integers(1, 64, (16,)).astype(np.int32)
    eng = _engine(cfg, params, slots=1)
    try:
        out = eng.generate(prompt, 3, timeout=120)
        assert len(out) == 3
        starved = eng.stats()["moe_prefill_drops"]
    finally:
        eng.stop()
    assert starved > 0, "starved capacity_factor must report drops"

    ample = dataclasses.replace(cfg, moe_capacity=4.0)
    eng2 = _engine(ample, params, slots=1)
    try:
        eng2.generate(prompt, 3, timeout=120)
        assert eng2.stats()["moe_prefill_drops"] == 0
    finally:
        eng2.stop()


def test_tp_sharded_engine_greedy_parity(small):
    """Continuous batching on a tp=2 mesh: params + KV cache sharded,
    slot logic unchanged, tokens match the unsharded engine exactly —
    the serving path for models bigger than one chip's HBM."""
    from edl_tpu.parallel import MeshSpec, build_mesh

    cfg, params = small
    mesh = build_mesh(MeshSpec(dp=-1, tp=2))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 97, (n,)).astype(np.int32)
               for n in (3, 9, 14, 6)]
    news = [7, 4, 10, 6]
    eng = _engine(cfg, params, mesh=mesh)
    try:
        # spot-check the params actually shard (mlp kernel over tp)
        k = eng._params["layer_0"]["mlp_in"]["kernel"]
        assert k.sharding.is_fully_replicated is False
        futs = [eng.submit(p, n) for p, n in zip(prompts, news)]
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.stop()
    for p, n, out in zip(prompts, news, got):
        want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), n,
                                   temperature=0.0))[0]
        np.testing.assert_array_equal(out, want)


def test_tp_sharded_jit_teacher_matches():
    """TeacherServer's model wrapper on a tp mesh: sharded forward
    logits match the replicated forward bit-for-bit shape/value-wise."""
    from edl_tpu.distill.teacher import jit_teacher
    from edl_tpu.models.transformer import LOGICAL_RULES
    from edl_tpu.parallel import MeshSpec, build_mesh

    cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=32,
                            remat=False, dtype=jnp.float32)
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))
    ids = np.random.default_rng(2).integers(0, 64, (2, 8)).astype(np.int32)

    plain = jit_teacher(model.apply, variables)({"ids": ids})["logits"]
    mesh = build_mesh(MeshSpec(dp=-1, tp=2))
    sharded = jit_teacher(model.apply, variables, mesh=mesh,
                          logical_rules=LOGICAL_RULES)({"ids": ids})["logits"]
    np.testing.assert_allclose(sharded, plain, atol=1e-5)


def test_moe_engine_greedy_parity():
    """MoE greedy parity engine-vs-generate: the padded prefill masks
    pad positions out of routing, so a prompt shorter than its bucket
    matches generate() on the unpadded prompt (ample capacity — see
    compute_routing's valid test for the tight-capacity invariant)."""
    cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32,
                            moe_experts=4, moe_top_k=2, moe_capacity=4.0)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 64, (n,)).astype(np.int32) for n in (3, 7, 13)]
    eng = _engine(cfg, params, slots=2)
    try:
        futs = [eng.submit(p, 6) for p in prompts]
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.stop()
    for p, out in zip(prompts, got):
        want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 6,
                                   temperature=0.0))[0]
        np.testing.assert_array_equal(out, want)


def test_gqa_engine_greedy_parity(small):
    """Continuous batching over a GQA model: grouped decode cache per
    slot still matches isolated generate() exactly."""
    import dataclasses

    cfg = dataclasses.replace(small[0], num_kv_heads=2)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 97, (n,)).astype(np.int32)
               for n in (3, 8, 12, 5)]
    eng = _engine(cfg, params, slots=2)
    try:
        futs = [eng.submit(p, 6) for p in prompts]
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.stop()
    for p, out in zip(prompts, got):
        want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 6,
                                   temperature=0.0))[0]
        np.testing.assert_array_equal(out, want)


def test_eos_truncates(small):
    cfg, params = small
    # eos = whatever greedy emits second -> output must stop there
    p = np.asarray([5, 9, 2], np.int32)
    ref = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 8,
                              temperature=0.0))[0]
    eos = int(ref[1])
    eng = _engine(cfg, params, eos_id=eos)
    try:
        out = eng.generate(p, 8, timeout=120)
    finally:
        eng.stop()
    assert list(out) == list(ref[:2])


def test_submit_validation(small):
    cfg, params = small
    eng = _engine(cfg, params)
    try:
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="room"):
            eng.submit(np.zeros((64,), np.int32), 1)   # no room to generate
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(np.zeros((16,), np.int32), 60)  # 16 + 60 > 64
    finally:
        eng.stop()


def test_prompt_longer_than_configured_buckets(small):
    """The prompt cap is the CACHE, not the bucket list: buckets extend
    by doubling to cache_len, so a 17-token prompt serves fine with
    configured buckets (8, 16) and a 64 cache — greedy parity holds."""
    cfg, params = small
    p = np.random.default_rng(9).integers(1, 97, (17,)).astype(np.int32)
    eng = _engine(cfg, params)
    try:
        assert eng.stats()["max_prompt_len"] == 63
        out = eng.generate(p, 5, timeout=120)
    finally:
        eng.stop()
    want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 5,
                               temperature=0.0))[0]
    np.testing.assert_array_equal(out, want)


@pytest.mark.slow
def test_600_token_prompt_1024_cache():
    """VERDICT r4 #4's acceptance case: a 1024-cache engine must accept
    a 600-token prompt with the DEFAULT bucket list (max 512)."""
    cfg = TransformerConfig(vocab_size=61, num_layers=1, embed_dim=16,
                            num_heads=2, mlp_dim=32, max_len=1024,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    p = np.random.default_rng(4).integers(1, 61, (600,)).astype(np.int32)
    eng = ContinuousBatcher(cfg, params, slots=2, temperature=0.0,
                            steps_per_sync=4)
    try:
        out = eng.generate(p, 6, timeout=300)
    finally:
        eng.stop()
    want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 6,
                               temperature=0.0))[0]
    np.testing.assert_array_equal(out, want)


def test_mixed_load_decode_not_starved(small):
    """Decode lanes advance no matter how fast new requests arrive: two
    long generations run to completion while a queue of short arrivals
    churns through the remaining slot.  Starvation is gated on the
    engine's OWN scheduler accounting — the longs' completion proves
    liveness, ``requests_done`` proves the churn was real, and the
    wall-clock ratio is a wide LOAD-TOLERANT backstop only (ISSUE 13
    deflake: the old 2x bound tripped under the full tier-1 suite on a
    1-core box purely from host scheduler jitter; the >=0.8
    device-class ratio is measured on real hardware by bench.py's
    engine section, not here)."""
    import time as _t

    cfg, params = small
    LONG, SHORT = 40, 4

    def run(churn: int) -> float:
        eng = _engine(cfg, params, slots=3, steps_per_sync=4)
        try:
            t0 = _t.monotonic()
            longs = [eng.submit(np.asarray([7, 11, 13], np.int32), LONG)
                     for _ in range(2)]
            shorts = [eng.submit(np.asarray([5, 9], np.int32), SHORT)
                      for _ in range(churn)]
            for f in longs:
                f.result(timeout=300)
            dt = _t.monotonic() - t0
            for f in shorts:
                f.result(timeout=300)
            stats = eng.stats()
        finally:
            eng.stop()
        if churn:
            # shorts prefill while the longs decode: stall is accounted
            # (no assertion on the quiet run — whether its two submits
            # land in one idle-engine prefill group is a thread race)
            assert stats["prefill_stall_s"] > 0.0
            assert stats["requests_done"] == 2 + churn
        return dt

    quiet = run(churn=0)
    busy = run(churn=12)
    # backstop, not the starvation oracle: a starved decode lane would
    # take ~churn/slots times longer (the longs would queue behind every
    # short), so 4x + a flat 8s scheduler allowance cleanly separates
    # "starved" from "loaded CI host" without flaking under tier-1
    assert busy <= max(4.0 * quiet, quiet + 8.0), (
        f"long decodes starved by arrivals: quiet {quiet:.2f}s vs "
        f"busy {busy:.2f}s")


def test_warm_then_serve(small):
    """warm() pre-compiles the step + every prefill/insert sub-batch
    without touching live state: the engine must serve identically
    afterwards (greedy parity), and the ladder must scale with slots."""
    cfg, params = small
    eng = _engine(cfg, params, slots=3)
    try:
        assert eng.PREFILL_KS == (2, 1)   # ladder filtered by slots
        eng.warm(7)
        p = np.random.default_rng(21).integers(1, 97, (7,)).astype(np.int32)
        out = eng.generate(p, 5, timeout=120)
    finally:
        eng.stop()
    want = np.asarray(generate(cfg, params, jnp.asarray(p[None]), 5,
                               temperature=0.0))[0]
    np.testing.assert_array_equal(out, want)


def test_warm_mid_traffic_fails_loudly(small):
    """warm() shares the donated pool cache with the engine thread, so
    calling it with requests in flight must raise, not race (ISSUE 2
    satellite): occupied slots or queued work both refuse."""
    cfg, params = small
    eng = _engine(cfg, params, slots=2)
    try:
        fut = eng.submit(np.asarray([3, 1, 4], np.int32), 8)
        # wait until the request occupies a slot (not the queue->pending
        # handoff instant) so the guard trips on a deterministic state
        deadline = time.monotonic() + 120
        while not eng.stats()["active_slots"]:
            assert time.monotonic() < deadline, "request never admitted"
            time.sleep(0.01)
        with pytest.raises(RuntimeError, match="in flight"):
            eng.warm(7)
        fut.result(timeout=120)   # the live request still completes
        # drained again: warm() is legal once traffic is gone
        eng.warm(7)
    finally:
        eng.stop()


def test_stop_fails_pending(small):
    cfg, params = small
    eng = _engine(cfg, params, slots=1)
    futs = [eng.submit(np.asarray([3, 4], np.int32), 30) for _ in range(4)]
    eng.stop()
    # all futures resolve one way or the other — none hang
    done = sum(1 for f in futs if f.done())
    assert done == 4


def test_drain_completes_queued_and_inflight(small):
    """drain() is the graceful replica-removal path: admission stops,
    but every queued + in-flight request runs to completion — where
    stop() (the hard path above) FAILS them."""
    import threading

    cfg, params = small
    eng = _engine(cfg, params, slots=1)   # 1 slot: most requests queued
    futs = [eng.submit(np.asarray([3, 4], np.int32), 8) for _ in range(5)]
    drained = []
    t = threading.Thread(target=lambda: drained.append(eng.drain()))
    t.start()
    # the draining flag is up before completion: new submits refuse
    deadline = time.monotonic() + 120
    while not eng.stats()["draining"]:
        assert time.monotonic() < deadline, "drain flag never observed"
        time.sleep(0.005)
    with pytest.raises(RuntimeError, match="draining|stopping"):
        eng.submit(np.asarray([5], np.int32), 4)
    t.join(timeout=120)
    assert drained == [True]
    for f in futs:
        out = f.result(timeout=1)         # resolved, with real tokens
        assert len(out) == 8


def test_drain_timeout_falls_back_to_hard_stop(small):
    cfg, params = small
    eng = _engine(cfg, params, slots=1)
    futs = [eng.submit(np.asarray([3, 4], np.int32), 40) for _ in range(3)]
    assert eng.drain(timeout=0.0) is False   # deadline already passed
    # hard-stop fallback: every future resolves (with an error), none hang
    for f in futs:
        assert f.done()
    assert sum(1 for f in futs if f.exception() is not None) >= 1
