"""Paged KV cache + prefix reuse (edl_tpu/serving/kv_cache.py, engine
integration).

The load-bearing property is the same one the engine already proves for
slot independence, extended to chain reuse: a request admitted FROM a
cached prefix must emit bit-identical tokens to the same request
prefilled from scratch (greedy sampling makes that exact).  Everything
else — commit, eviction, session pinning, export/import — must never
bend that invariant.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edl_tpu.models import TransformerConfig, TransformerLM
from edl_tpu.models.generate import generate
from edl_tpu.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def small():
    cfg = TransformerConfig(vocab_size=97, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    return cfg, params


def _engine(cfg, params, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("temperature", 0.0)
    kw.setdefault("steps_per_sync", 4)
    kw.setdefault("kv_block", 4)
    kw.setdefault("kv_pool_blocks", 64)
    return ContinuousBatcher(cfg, params, **kw)


def _want(cfg, params, p, n):
    return np.asarray(generate(cfg, params, jnp.asarray(p[None]), n,
                               temperature=0.0))[0]


def test_paged_engine_greedy_parity_and_prefix_hits(small):
    """Shared-prefix traffic: the first request commits the chain, the
    rest resume from it — every output bit-identical to generate(), and
    the stats prove the reuse actually happened."""
    cfg, params = small
    rng = np.random.default_rng(0)
    prefix = rng.integers(1, 97, (12,)).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.integers(1, 97, (n,)).astype(np.int32)])
               for n in (3, 7, 2, 5)]
    eng = _engine(cfg, params)
    try:
        # serialized: each request commits before the next matches (a
        # burst would cold-prefill concurrently — still correct, but
        # this test is about the hit path)
        outs = [eng.generate(p, 6, timeout=120) for p in prompts]
        stats = eng.stats()
    finally:
        eng.stop()
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _want(cfg, params, p, 6))
    assert stats["kv_prefix_hits"] >= len(prompts) - 1, stats
    assert stats["kv_prefill_tokens_skipped"] >= (len(prompts) - 1) * 12, \
        stats
    assert stats["kv_blocks_used"] > 0


def test_paged_matches_unpaged_engine_bit_exact(small):
    """The acceptance gate: the SAME workload through a paged and an
    unpaged engine yields byte-identical outputs (mixed hits, misses,
    bursts)."""
    cfg, params = small
    rng = np.random.default_rng(1)
    prefix = rng.integers(1, 97, (9,)).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.integers(1, 97, (n,)).astype(np.int32)])
               for n in (2, 6, 3)]
    prompts += [rng.integers(1, 97, (5,)).astype(np.int32)]  # unrelated
    news = [5, 7, 4, 6]

    def run(**kw):
        eng = _engine(cfg, params, **kw)
        try:
            return [eng.generate(p, n, timeout=120)
                    for p, n in zip(prompts, news)]
        finally:
            eng.stop()

    paged = run()
    unpaged = run(kv_block=0)
    for a, b in zip(paged, unpaged):
        np.testing.assert_array_equal(a, b)


def test_cow_divergence_never_corrupts_sibling_chain(small):
    """Two sessions share a prefix chain, then diverge: committed
    blocks are immutable (divergence writes NEW blocks under new chain
    keys), so each sibling's continuation stays bit-identical to a
    fresh-cache run no matter how the other mutates its own line."""
    cfg, params = small
    rng = np.random.default_rng(2)
    shared = rng.integers(1, 97, (10,)).astype(np.int32)
    eng = _engine(cfg, params)
    try:
        p_a = np.concatenate([shared, np.asarray([3, 1, 4], np.int32)])
        p_b = np.concatenate([shared, np.asarray([2, 7], np.int32)])
        out_a = eng.submit(p_a, 8, session="a").result(120)
        out_b = eng.submit(p_b, 8, session="b").result(120)
        # second turns, interleaved: each extends ITS OWN divergent line
        p_a2 = np.concatenate([p_a, out_a, np.asarray([5], np.int32)])
        p_b2 = np.concatenate([p_b, out_b, np.asarray([9, 6], np.int32)])
        out_a2 = eng.submit(p_a2, 6, session="a").result(120)
        out_b2 = eng.submit(p_b2, 6, session="b").result(120)
        stats = eng.stats()
    finally:
        eng.stop()
    for p, n, out in ((p_a, 8, out_a), (p_b, 8, out_b),
                      (p_a2, 6, out_a2), (p_b2, 6, out_b2)):
        np.testing.assert_array_equal(out, _want(cfg, params, p, n))
    assert stats["kv_sessions"] == 2
    assert stats["kv_prefix_hits"] >= 2   # both second turns resumed


def test_near_max_len_reuse_shortens_chain_not_cache(small):
    """A prompt near max_len whose matched chain + bucketed suffix
    would overhang the cache must shorten the chain (the cache write is
    a CLAMPED dynamic_update_slice — an overhanging slab would silently
    shift backwards over the gathered prefix and poison the pool at
    commit).  Both the overhanging request and a later sibling reusing
    the same chain stay bit-exact."""
    cfg, params = small          # max_len=64, kv_block=4 via _engine
    rng = np.random.default_rng(4)
    p_a = rng.integers(1, 97, (60,)).astype(np.int32)
    # shares 52 tokens (13 blocks) with p_a; suffix of 9 buckets to 16,
    # so 52 + 16 > 64 forces the guard to pop down to a 48-token prefix
    p_b = np.concatenate([p_a[:52],
                          rng.integers(1, 97, (9,)).astype(np.int32)])
    # fits exactly (56 + bucket(2)=8 == 64): proves p_b's admission did
    # not corrupt the committed chain it partially reused
    p_c = np.concatenate([p_a[:56],
                          rng.integers(1, 97, (2,)).astype(np.int32)])
    eng = _engine(cfg, params)
    try:
        out_a = eng.generate(p_a, 4, timeout=120)
        out_b = eng.generate(p_b, 3, timeout=120)
        out_c = eng.generate(p_c, 3, timeout=120)
        stats = eng.stats()
    finally:
        eng.stop()
    np.testing.assert_array_equal(out_a, _want(cfg, params, p_a, 4))
    np.testing.assert_array_equal(out_b, _want(cfg, params, p_b, 3))
    np.testing.assert_array_equal(out_c, _want(cfg, params, p_c, 3))
    assert stats["kv_prefix_hits"] >= 2, stats


def test_eviction_under_pressure_keeps_parity(small):
    """A pool far too small for the traffic must evict (or skip
    commits) — never corrupt: every output still greedy-exact."""
    cfg, params = small
    rng = np.random.default_rng(3)
    eng = _engine(cfg, params, slots=2, kv_pool_blocks=9)
    try:
        for _ in range(10):
            p = rng.integers(1, 97,
                             (int(rng.integers(6, 14)),)).astype(np.int32)
            out = eng.generate(p, 5, timeout=120)
            np.testing.assert_array_equal(out, _want(cfg, params, p, 5))
        stats = eng.stats()
    finally:
        eng.stop()
    assert stats["kv_evictions"] > 0 or stats["kv_commit_skips"] > 0, stats
    assert stats["kv_blocks_free"] >= 0


def test_export_import_roundtrip_resumes_warm(small):
    """The migration primitive: a pinned chain exported after drain()
    imports into a second engine, and the session's next turn there
    skips the prefix prefill — bit-identical output."""
    cfg, params = small
    p1 = np.asarray([7, 11, 13, 5, 9, 2, 8], np.int32)
    eng_a = _engine(cfg, params)
    conv = None
    try:
        out1 = eng_a.submit(p1, 8, session="s").result(120)
        np.testing.assert_array_equal(out1, _want(cfg, params, p1, 8))
        conv = np.concatenate([p1, out1])
        assert eng_a.drain(timeout=30)
        exported = eng_a.export_sessions()
        assert [e[0] for e in exported] == ["s"]
        _, tokens, meta, blob = exported[0]
        # the chain covers full blocks of prompt + emitted[:-1]
        assert tokens == list(map(int, conv[:len(tokens)]))
    finally:
        eng_a.stop()

    eng_b = _engine(cfg, params)
    try:
        assert eng_b.import_session("s", tokens, meta, blob) > 0
        assert eng_b.stats()["kv_sessions"] == 1
        p2 = np.concatenate([conv, np.asarray([4, 1], np.int32)])
        out2 = eng_b.generate(p2, 6, timeout=120)
        np.testing.assert_array_equal(out2, _want(cfg, params, p2, 6))
        stats = eng_b.stats()
        assert stats["kv_prefix_hits"] == 1, stats
        assert stats["kv_prefill_tokens_skipped"] == len(tokens), stats
    finally:
        eng_b.stop()


def test_import_refused_without_paging(small):
    cfg, params = small
    eng = _engine(cfg, params, kv_block=0)
    try:
        with pytest.raises(RuntimeError, match="disabled"):
            eng.import_session("s", [1, 2, 3, 4], {"block": 4, "n": 1,
                                                   "layers": [],
                                                   "layout": {}}, b"")
    finally:
        eng.stop()


def test_reuse_off_still_commits_for_migration(small):
    """prefix_reuse=False: admissions always cold-prefill (misses only)
    but chains still commit + pin, so drain migration keeps working."""
    cfg, params = small
    eng = _engine(cfg, params, prefix_reuse=False)
    try:
        p = np.asarray([5, 9, 2, 7, 1], np.int32)
        eng.submit(p, 6, session="s").result(120)
        p2 = np.concatenate([p, np.asarray([3], np.int32)])
        out = eng.generate(p2, 4, timeout=120)
        np.testing.assert_array_equal(out, _want(cfg, params, p2, 4))
        stats = eng.stats()
    finally:
        eng.stop()
    assert stats["kv_prefix_hits"] == 0
    assert stats["kv_sessions"] == 1
    assert stats["kv_blocks_used"] > 0


def test_mesh_engine_accepts_paging_bit_exact(small):
    """ISSUE 20 flipped the old refusal: a tp>1 engine now pages by
    sharding the pool over the head axis (one shared host trie, every
    pool op lifted through shard_map) — shared-prefix traffic on a mesh
    engine must hit the trie AND stay bit-identical to generate()."""
    from edl_tpu.parallel import MeshSpec, build_mesh

    cfg, params = small
    mesh = build_mesh(MeshSpec(dp=-1, tp=2))
    rng = np.random.default_rng(7)
    prefix = rng.integers(1, 97, (12,)).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.integers(1, 97, (n,)).astype(np.int32)])
               for n in (3, 6, 2)]
    eng = _engine(cfg, params, slots=2, mesh=mesh)
    try:
        outs = [eng.generate(p, 5, timeout=120) for p in prompts]
        stats = eng.stats()
    finally:
        eng.stop()
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _want(cfg, params, p, 5))
    assert stats["kv_prefix_hits"] >= len(prompts) - 1, stats
