"""Elastic *training* end-to-end: real launchers, real JAX trainers,
real checkpoints, a live mid-run join with stop-resume.

This is SURVEY.md §7 step 4 (elastic resize proof) as a test: pod A
trains solo, pod B joins mid-run, A's trainer is killed and restarted
in a 2-host world, resumes from the Orbax checkpoint at the next epoch,
and the epoch history records both world sizes.
"""

import os
import re
import subprocess
import sys
import time
import urllib.request

import pytest

from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.coord.client import CoordClient
from tests.test_launch_integration import FAST, finish

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "examples", "collective", "train_linear.py")


def spawn(job_id, coord_ep, tmp, name, ckpt_dir, extra_env=None,
          epochs="10", steps="4"):
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["EDL_TPU_DEMO_STEP_SLEEP"] = "0.25"
    env["EDL_TPU_DEMO_MARKER"] = os.path.join(tmp, f"marker-{name}")
    env.update(extra_env or {})
    log = open(os.path.join(tmp, f"launcher-{name}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", job_id, "--coord_endpoints", coord_ep,
         "--nodes_range", "1:2", "--nproc_per_node", "1",
         "--checkpoint_dir", ckpt_dir,
         "--log_dir", os.path.join(tmp, f"log-{name}"), TRAIN,
         "--", "--epochs", epochs, "--steps_per_epoch", steps],
        env=env, cwd=tmp, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    return proc


@pytest.mark.slow
def test_sigterm_preemption_checkpoint(coord_server, tmp_path):
    """SIGTERM a 2-pod world mid-run: the signalled pod's launcher
    flags preemption, BOTH trainers checkpoint at an agreed step and
    exit PREEMPT_EXIT_CODE, the signalled pod departs DESCALED (exit
    0), and the survivor stop-resumes SOLO from the preemption-point
    checkpoint — epochs complete exactly once (VERDICT r4 #8)."""
    import signal as _signal

    ep = f"127.0.0.1:{coord_server.port}"
    ckpt = str(tmp_path / "ckpt")
    env = {"EDL_TPU_PREEMPT_CHECK_STEPS": "2"}
    pa = spawn("preempt-e2e", ep, str(tmp_path), "a", ckpt, extra_env=env,
               epochs="8", steps="4")
    pb = spawn("preempt-e2e", ep, str(tmp_path), "b", ckpt, extra_env=env,
               epochs="8", steps="4")
    # wait for the 2-pod world to commit its first epoch checkpoint
    deadline = time.time() + 240
    while time.time() < deadline:
        done = [d for d in (os.listdir(ckpt) if os.path.isdir(ckpt) else [])
                if d.isdigit()]
        if done:
            break
        assert pa.poll() is None and pb.poll() is None, "pod died in warmup"
        time.sleep(0.25)
    else:
        raise AssertionError("no checkpoint committed before preemption")

    pb.send_signal(_signal.SIGTERM)
    assert finish(pb, 240) == 0, "preempted pod must exit cleanly (DESCALED)"
    assert finish(pa, 300) == 0

    client = CoordClient(ep)
    assert load_job_status(client, "preempt-e2e") == Status.SUCCEED
    client.close()

    lb = (tmp_path / "launcher-b.log").read_bytes().decode(errors="replace")
    assert "flagging preemption" in lb, lb[-2000:]
    assert "preemption checkpoint complete; departing" in lb, lb[-2000:]
    # both worlds' trainers took the coordinated preemption checkpoint
    m = re.search(r"preemption flagged: checkpointing at step (\d+)", lb)
    assert m, lb[-3000:]
    preempt_step = int(m.group(1))
    la = (tmp_path / "launcher-a.log").read_bytes().decode(errors="replace")
    assert "peer preempted; waiting for the shrunk cluster" in la, la[-2000:]
    # the survivor's restarted trainer resumed from the preemption-point
    # checkpoint: its resume epoch is the epoch the preempt step sat in
    # (4 steps/epoch; later epoch checkpoints GC the step dir itself)
    resumes = [int(x) for x in re.findall(r"resume_epoch=(\d+)", la)]
    assert len(resumes) >= 2, la[-2000:]
    # a preemption at an epoch-BOUNDARY step (step % 4 == 0) saves with
    # in_epoch still pointing at the just-finished epoch, so the resume
    # epoch is (step-1)//4 there and step//4 mid-epoch
    assert resumes[1] in (preempt_step // 4, (preempt_step - 1) // 4), (
        resumes, preempt_step)
    # the survivor finished the full epoch set exactly once, world=1
    marker_a = (tmp_path / "marker-a").read_text()
    done_lines = [l for l in marker_a.splitlines() if l.startswith("done")]
    assert done_lines, marker_a
    m = re.search(r"world=(\d+) epochs=\[([0-9, ]+)\]", done_lines[-1])
    assert m and m.group(1) == "1", marker_a
    assert [int(x) for x in m.group(2).split(",")] == list(range(8))


def _poll_metrics_endpoints(mdir, procs, want, deadline_s=240):
    """Scrape every addr file in ``mdir`` until all ``want`` series have
    nonzero counts (or every proc exits).  Returns the set seen."""
    from edl_tpu.obs.metrics import parse_exposition

    seen: set[str] = set()
    deadline = time.time() + deadline_s
    while time.time() < deadline and not want <= seen:
        for f in mdir.glob("metrics-*.addr"):
            addr = f.read_text().strip()
            try:
                with urllib.request.urlopen(f"http://{addr}/metrics",
                                            timeout=5) as resp:
                    text = resp.read().decode()
            except OSError:
                continue  # that process restarted/exited; others carry on
            samples = parse_exposition(text)  # raises if page is invalid
            for (name, _labels), value in samples.items():
                if name in want and value > 0:
                    seen.add(name)
        if all(p.poll() is not None for p in procs):
            break
        time.sleep(1.0)
    return seen


def _wait_for_checkpoints(ckpt, procs, n, deadline_s=180):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        done = [d for d in (os.listdir(ckpt) if os.path.isdir(ckpt) else [])
                if d.isdigit()]
        if len(done) >= n:
            return
        for p in procs:
            assert p.poll() is None, "pod died during warmup"
        time.sleep(0.25)
    raise AssertionError(f"never committed {n} epoch checkpoints")


def _complete_stages(ep, job):
    from edl_tpu.cluster.recovery import summarize_recovery
    client = CoordClient(ep)
    try:
        return [s for s in summarize_recovery(client, job) if "total" in s]
    finally:
        client.close()


@pytest.mark.slow
def test_peer_cache_restore_after_resize(coord_server, tmp_path):
    """ISSUE 2 acceptance: a mid-run join resizes the world; the
    restarted trainers restore from the surviving launcher's in-RAM
    cache (recovery record ``restore_source=peer``), and the restored
    state is verified bit-identical to the storage path in situ
    (EDL_TPU_MEMSTATE_VERIFY=1 restores BOTH and asserts equality
    inside the trainer)."""
    ep = f"127.0.0.1:{coord_server.port}"
    ckpt = str(tmp_path / "ckpt")
    env = {"EDL_TPU_MEMSTATE_VERIFY": "1"}
    pa = spawn("memstate-e2e", ep, str(tmp_path), "a", ckpt, extra_env=env)
    _wait_for_checkpoints(ckpt, [pa], 2)
    pb = spawn("memstate-e2e", ep, str(tmp_path), "b", ckpt, extra_env=env)
    assert finish(pa, 240) == 0
    assert finish(pb, 240) == 0

    client = CoordClient(ep)
    assert load_job_status(client, "memstate-e2e") == Status.SUCCEED
    client.close()
    complete = _complete_stages(ep, "memstate-e2e")
    assert complete, "no complete resize record"
    assert complete[-1]["restore_source"] == "peer", complete
    # the trainers logged the in-situ bit-identity proof (cache restore
    # AND storage restore of the same step compared leaf by leaf)
    la = (tmp_path / "launcher-a.log").read_bytes().decode(errors="replace")
    assert "restore_source=peer" in la, la[-3000:]
    assert "verified bit-identical to storage" in la, la[-3000:]
    # the full epoch set still completed exactly once, world=2
    marker_a = (tmp_path / "marker-a").read_text()
    done = [l for l in marker_a.splitlines() if l.startswith("done")]
    m = re.search(r"world=(\d+) epochs=\[([0-9, ]+)\]", done[-1])
    assert m and m.group(1) == "2", marker_a
    assert [int(x) for x in m.group(2).split(",")] == list(range(10))


@pytest.mark.slow
def test_peer_cache_miss_falls_back_to_storage(coord_server, tmp_path):
    """Forced cache miss: a 1-byte cache cap rejects every shard push
    (eviction-class miss — the set never seals, no committed record),
    so the post-resize restore must fall back to Orbax storage and the
    recovery record says ``restore_source=storage``.  Same resize
    choreography as the peer-restore test; only the cache differs."""
    ep = f"127.0.0.1:{coord_server.port}"
    ckpt = str(tmp_path / "ckpt")
    env = {"EDL_TPU_MEMSTATE_MAX_BYTES": "1"}
    pa = spawn("miss-e2e", ep, str(tmp_path), "a", ckpt, extra_env=env)
    _wait_for_checkpoints(ckpt, [pa], 2)
    pb = spawn("miss-e2e", ep, str(tmp_path), "b", ckpt, extra_env=env)
    assert finish(pa, 240) == 0
    assert finish(pb, 240) == 0

    client = CoordClient(ep)
    assert load_job_status(client, "miss-e2e") == Status.SUCCEED
    client.close()
    complete = _complete_stages(ep, "miss-e2e")
    assert complete, "no complete resize record"
    assert complete[-1]["restore_source"] == "storage", complete
    la = (tmp_path / "launcher-a.log").read_bytes().decode(errors="replace")
    assert "restore_source=peer" not in la
    marker_a = (tmp_path / "marker-a").read_text()
    done = [l for l in marker_a.splitlines() if l.startswith("done")]
    assert done and "world=2" in done[-1], marker_a


@pytest.mark.slow
def test_elastic_join_resumes_training(coord_server, tmp_path):
    ep = f"127.0.0.1:{coord_server.port}"
    ckpt = str(tmp_path / "ckpt")
    mdir = tmp_path / "metrics"
    mdir.mkdir()
    # every process (launchers + trainers) serves /metrics on a free
    # port and advertises it via an addr file (doc/observability.md)
    obs_env = {"EDL_TPU_METRICS_PORT": "0", "EDL_TPU_METRICS_DIR": str(mdir)}
    pa = spawn("train-e2e", ep, str(tmp_path), "a", ckpt, extra_env=obs_env)
    # condition, not a fixed sleep (a loaded host made 12 s mean
    # anything from 1 to 6 epochs): B joins once A has COMMITTED at
    # least two epoch checkpoints solo
    deadline = time.time() + 180
    while time.time() < deadline:
        done = [d for d in (os.listdir(ckpt) if os.path.isdir(ckpt) else [])
                if d.isdigit()]
        if len(done) >= 2:
            break
        assert pa.poll() is None, "pod A died during solo warmup"
        time.sleep(0.25)
    else:
        raise AssertionError("pod A never committed 2 epoch checkpoints")
    pb = spawn("train-e2e", ep, str(tmp_path), "b", ckpt, extra_env=obs_env)
    # while the job runs, the live /metrics endpoints must serve valid
    # Prometheus text; after the resize the step-latency histogram (any
    # trainer) and the resize-phase histogram (the launchers) both have
    # samples.  _count series prove real observations, not just TYPE
    # lines.
    want = {"edl_train_step_seconds_count", "edl_resize_phase_seconds_count"}
    seen = _poll_metrics_endpoints(mdir, [pa, pb], want)
    assert want <= seen, f"missing live metrics series: {want - seen}"
    assert finish(pa, 240) == 0
    assert finish(pb, 240) == 0

    client = CoordClient(ep)
    assert load_job_status(client, "train-e2e") == Status.SUCCEED
    # the resize left a full recovery-time record (the north-star
    # metric): launcher phases + trainer restore/first-step merged.
    # Only COMPLETE records count — a stage whose trainer half never
    # landed (e.g. a second resize racing job completion) is legitimate
    # mid-flight state, not the record under test
    from edl_tpu.cluster.recovery import summarize_recovery
    stages = summarize_recovery(client, "train-e2e")
    complete = [s for s in stages if "total" in s]
    assert complete, stages
    assert 0 < complete[-1]["total"] < 300, stages
    print("recovery breakdown:", complete[-1])
    # the obs dump reproduces the same per-phase totals for the
    # completed resize — one read path over one write path
    from edl_tpu.obs.dump import job_report, render_report
    report = job_report(client, "train-e2e")
    assert [s for s in report["resizes"] if "total" in s] == complete
    assert "restored_to_first_step" in render_report(report)
    client.close()

    marker_a = (tmp_path / "marker-a").read_text()
    done = [l for l in marker_a.splitlines() if l.startswith("done")]
    assert done, marker_a
    # the finishing run saw world=2 and a full epoch set 0..9
    m = re.search(r"world=(\d+) epochs=\[([0-9, ]+)\] w_err=([0-9.]+)", done[-1])
    assert m, marker_a
    assert m.group(1) == "2"
    assert [int(x) for x in m.group(2).split(",")] == list(range(10))
    assert float(m.group(3)) < 0.05  # actually learned
    # log shows a resume from a nonzero epoch after the resize restart
    la = (tmp_path / "launcher-a.log").read_bytes().decode(errors="replace")
    resumes = re.findall(r"resume_epoch=(\d+)", la)
    assert len(resumes) >= 2 and any(int(r) > 0 for r in resumes[1:]), resumes
