"""Distill orchestration: teacher fleets as elastic serving jobs.

Covers the ROADMAP item 4 subsystem end to end at unit scale:
balance-table churn under teacher SIGKILL (TTL-failover, no student
stuck on a dead endpoint), assignment versions advancing only on real
membership change, the DistillFleet routed view (filtering, least-
loaded pick, quarantine, failover retry, latency hedge), StudentFeed
backlog accounting + durable records, the DistillAutoscaler's
grow/hold/decay ladder, and the controller's advert-backed distill
job view.  The full three-job arbitration story is the chaos smoke
(scripts/distill_chaos_smoke.py); this file is the fast CI floor.
"""

import json
import threading
import time

import numpy as np
import pytest

from edl_tpu.cluster import scale
from edl_tpu.controller.autoscale import DistillAutoscaler
from edl_tpu.coord.register import Register
from edl_tpu.coord.session import CoordSession
from edl_tpu.distill import reader as reader_mod
from edl_tpu.distill.backlog import StudentFeed
from edl_tpu.distill.balance import Service, server_key, service_prefix
from edl_tpu.distill.fleet import DISTILL_SERVICE_CLASS, DistillFleet, \
    TeacherReplica
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher import TeacherServer
from edl_tpu.gateway import fleet as gw_fleet


def _wait_until(cond, timeout: float, period: float = 0.05) -> float:
    """Poll ``cond`` until true; returns elapsed seconds (fails the
    test on timeout so callers can assert on the latency)."""
    t0 = time.monotonic()
    deadline = t0 + timeout
    while not cond():
        assert time.monotonic() < deadline, "condition not met in time"
        time.sleep(period)
    return time.monotonic() - t0


def sample_list_gen(n_batches=8, bs=4, dim=3):
    def gen():
        for b in range(n_batches):
            yield [(np.full((dim,), b * bs + i, np.float32), b * bs + i)
                   for i in range(bs)]
    return gen


# -- balance-table churn (satellite: SIGKILL rebalance + version pin) --------

def test_teacher_sigkill_rebalances_within_ttl(memkv):
    """A teacher whose keepalive dies mid-assignment (SIGKILL from the
    store's point of view) is rebalanced away within TTL + grace; the
    surviving client is never left holding only the dead endpoint."""
    ttl = 1.0
    regs = {ep: Register(memkv, server_key("churn", ep), ep.encode(),
                         ttl=ttl) for ep in ("t-dead:1", "t-live:2")}
    svc = Service("churn", memkv, period=0.1)
    try:
        svc.add_client("student", require_num=2)
        svc._refresh_servers()
        _, servers = svc.get_servers("student", -1)
        assert set(servers) == {"t-dead:1", "t-live:2"}

        regs["t-dead:1"].stop_heartbeat_only()   # SIGKILL, as seen by the store
        t0 = time.monotonic()

        def rebalanced():
            _, s = svc.get_servers("student", -1)
            return s is not None and set(s) == {"t-live:2"}
        _wait_until(rebalanced, timeout=ttl + 2.0)
        # within TTL + sweep + watch-poll grace, not eventually-someday
        assert time.monotonic() - t0 <= ttl + 2.0
        # the client's final assignment holds no dead endpoint
        _, final = svc.get_servers("student", -1)
        assert final is None or "t-dead:1" not in final
    finally:
        svc.close()
        for r in regs.values():
            r.stop()


def test_assignment_version_only_advances_on_membership_change(memkv):
    """Advert VALUE refreshes (the new stats payload republished every
    advert period) fire watch events but must not bump assignment
    versions — only real membership change does."""
    for ep in ("a:1", "b:2"):
        memkv.put(server_key("verpin", ep), b"v0")
    svc = Service("verpin", memkv, period=0.05)
    try:
        svc.add_client("c1", require_num=2)
        svc._refresh_servers()
        v0, servers = svc.get_servers("c1", -1)
        assert set(servers) == {"a:1", "b:2"}
        # stats refresh: same keys, new values, several rounds
        for round_ in range(3):
            for ep in ("a:1", "b:2"):
                memkv.put(server_key("verpin", ep),
                          json.dumps({"endpoint": ep,
                                      "rows": round_}).encode())
            svc._refresh_servers()
        v1, servers = svc.get_servers("c1", v0)
        assert v1 == v0 and servers is None   # nothing changed for the client
        # real membership change: one teacher gone
        memkv.delete(server_key("verpin", "b:2"))
        svc._refresh_servers()
        v2, servers = svc.get_servers("c1", v0)
        assert v2 > v0 and servers == ["a:1"]
    finally:
        svc.close()


def test_refresh_servers_store_blip_keeps_stale_view(memkv, monkeypatch):
    """A coord blip during the watch callback defers the rebalance
    round (stale teacher set kept) instead of dropping teachers."""
    memkv.put(server_key("blip", "t:1"), b"t")
    svc = Service("blip", memkv, period=10.0)
    try:
        svc.add_client("c", require_num=1)
        svc._refresh_servers()
        _, servers = svc.get_servers("c", -1)
        assert servers == ["t:1"]

        def boom(prefix):
            raise ConnectionError("coord away")
        monkeypatch.setattr(memkv, "get_prefix", boom)
        svc._refresh_servers()                 # must not raise, must not wipe
        v, servers = svc.get_servers("c", -1)
        assert servers == ["t:1"]
    finally:
        svc.close()


# -- teacher adverts on one shared session -----------------------------------

def test_teacher_advert_rides_shared_session(memkv):
    server = TeacherServer(lambda feed: {"p": feed["x"]}, port=0)
    session = CoordSession(memkv, ttl=1.0, name="test-teacher")
    try:
        server.register(memkv, "shared-svc", session=session,
                        advert_period=60.0)
        rec = memkv.get(server_key("shared-svc", server.endpoint))
        assert rec is not None
        stats = json.loads(rec.value.decode())
        # the advert value is the live stats payload
        assert stats["endpoint"] == server.endpoint
        assert "queue_depth" in stats and "rows_per_s" in stats
        # the advert rides the SHARED lease: abandoning the session's
        # keepalive (a SIGKILLed process) TTL-expires the advert
        session.abandon()
        _wait_until(
            lambda: memkv.get(server_key("shared-svc",
                                         server.endpoint)) is None,
            timeout=3.0)
    finally:
        server.stop()
        session.close()


def test_teacher_replica_dual_advert_one_lease(memkv):
    """TeacherReplica advertises in BOTH tables on one session: one
    abandoned keepalive expires the serving advert and the balance
    advert together (the one-lease-per-process idiom)."""
    server = TeacherServer(lambda feed: {"p": feed["x"]}, port=0)
    replica = TeacherReplica(memkv, "teachjob", server, "dual-svc",
                             ttl=1.0, advert_period=60.0)
    try:
        reps = gw_fleet.list_replicas(memkv, "teachjob")
        assert replica.replica_id in reps
        payload = reps[replica.replica_id]
        assert payload["service_class"] == DISTILL_SERVICE_CLASS
        assert payload["endpoint"] == server.endpoint
        assert memkv.get(server_key("dual-svc", server.endpoint)) is not None

        replica._halt.set()                    # silence refresh loops, then
        server._advert_halt.set()              # kill the keepalive: SIGKILL
        replica._coord_session.abandon()
        _wait_until(
            lambda: not gw_fleet.list_replicas(memkv, "teachjob")
            and memkv.get(server_key("dual-svc", server.endpoint)) is None,
            timeout=3.0)
    finally:
        try:
            replica.stop()
        except Exception:
            pass


# -- DistillFleet routing ----------------------------------------------------

def _advert(memkv, job, rid, ep, queue_depth=0, service="svc",
            service_class=DISTILL_SERVICE_CLASS, draining=False, ttl=5.0):
    return gw_fleet.advertise(
        memkv, job, rid,
        {"endpoint": ep, "service": service, "service_class": service_class,
         "queue_depth": queue_depth, "draining": draining}, ttl=ttl)


def test_fleet_filters_and_picks_least_loaded(memkv):
    regs = [
        _advert(memkv, "fl", "t1", "t1:1", queue_depth=4),
        _advert(memkv, "fl", "t2", "t2:2", queue_depth=1),
        _advert(memkv, "fl", "lm", "lm:3", service_class="lm"),
        _advert(memkv, "fl", "t3", "t3:4", queue_depth=0, draining=True),
    ]
    fleet = DistillFleet(memkv, "fl", period=0.05)
    try:
        assert fleet.wait_for(2, timeout=3.0)
        teachers = fleet.teachers()
        # the LM replica and the draining teacher are filtered out
        assert set(teachers) == {"t1", "t2"}
        assert fleet.pick() == "t2:2"          # least advertised queue
        fleet.drop("t2:2")                     # transport failure observed
        assert fleet.pick() == "t1:1"          # quarantined endpoint skipped
        assert fleet.endpoints() == ["t1:1"]
    finally:
        fleet.stop()
        for r in regs:
            r.stop()


def test_fleet_routed_predict_fails_over(memkv):
    regs = [_advert(memkv, "fo", "t1", "dead:1", queue_depth=0),
            _advert(memkv, "fo", "t2", "live:2", queue_depth=3)]
    fleet = DistillFleet(memkv, "fo", period=0.05)

    class _Client:
        def __init__(self, ep):
            self.ep = ep

        def predict(self, feed):
            if self.ep == "dead:1":
                raise ConnectionError("teacher gone")
            return {"from": self.ep}

        def close(self):
            pass

    try:
        assert fleet.wait_for(2, timeout=3.0)
        out = fleet.predict({"x": 1}, ["from"], retries=2,
                            client_factory=_Client)
        assert out == {"from": "live:2"}       # death cost one retry, not the call
        assert "dead:1" not in fleet.endpoints()   # quarantined
    finally:
        fleet.stop()
        for r in regs:
            r.stop()


def test_fleet_hedged_predict_backup_wins(memkv):
    regs = [_advert(memkv, "hg", "t1", "slow:1", queue_depth=0),
            _advert(memkv, "hg", "t2", "fast:2", queue_depth=1)]
    fleet = DistillFleet(memkv, "hg", period=0.05)

    class _Client:
        def __init__(self, ep):
            self.ep = ep

        def predict(self, feed):
            if self.ep == "slow:1":
                time.sleep(1.5)
            return {"from": self.ep}

        def close(self):
            pass

    try:
        assert fleet.wait_for(2, timeout=3.0)
        t0 = time.monotonic()
        out = fleet.predict({"x": 1}, ["from"], hedge_after_s=0.05,
                            client_factory=_Client)
        # primary (least queue = slow:1) stalls; the hedge answers first
        assert out == {"from": "fast:2"}
        assert time.monotonic() - t0 < 1.0
    finally:
        fleet.stop()
        for r in regs:
            r.stop()


# -- the fleet-backed student, teacher SIGKILL mid-epoch ---------------------

def test_student_survives_teacher_sigkill_exactly_once(memkv):
    """Two live TeacherReplicas behind a DistillFleet feeding a real
    DistillReader; one teacher SIGKILLs mid-epoch.  The pool requeues
    its in-flight task onto the survivor: every row arrives exactly
    once, in order — teacher death costs a retry, not a batch."""
    def predict_fn(feed):
        time.sleep(0.02)                       # slow enough to die mid-epoch
        return {"prediction": feed["x"] * 2.0}

    replicas = [
        TeacherReplica(memkv, "ek", TeacherServer(predict_fn, port=0),
                       f"ek-svc", replica_id=f"t{i}", ttl=1.0,
                       advert_period=0.2)
        for i in range(2)]
    fleet = DistillFleet(memkv, "ek", period=0.1)
    assert fleet.wait_for(2, timeout=5.0)

    n_batches, bs = 20, 3
    dr = DistillReader(ins=["x", "idx"], predicts=["prediction"],
                       feeds=["x"], teacher_batch_size=bs)
    dr.set_sample_list_generator(sample_list_gen(n_batches, bs))
    dr.set_servers_fn(fleet.endpoints_fn())
    dr._pool_kw = {"manage_period": 0.2, "no_teacher_timeout": 20.0}

    victim = replicas[0]
    batches = []
    try:
        for i, batch in enumerate(dr()):
            batches.append(batch)
            if i == 2:                         # SIGKILL mid-epoch
                victim._halt.set()
                victim.server._advert_halt.set()
                victim.server._rpc.stop()
                victim._coord_session.abandon()
        assert len(batches) == n_batches
        ids = np.concatenate([b[1] for b in batches])
        np.testing.assert_array_equal(ids, np.arange(n_batches * bs))
        preds = np.concatenate([b[2] for b in batches])
        np.testing.assert_allclose(preds[:, 0], np.arange(n_batches * bs) * 2.0)
    finally:
        for r in replicas:
            try:
                r.stop()
            except Exception:
                pass


# -- StudentFeed backlog signal ----------------------------------------------

def test_student_feed_accounting_and_cleanup(memkv, monkeypatch):
    monkeypatch.setattr(reader_mod, "_NOP_PREDICT_TEST", True)
    n_batches, bs = 8, 4
    dr = DistillReader(ins=["x", "idx"], predicts=["prediction"],
                       feeds=["x"], teacher_batch_size=3)
    dr.set_fixed_teacher("t1", "t2")
    dr.set_sample_list_generator(sample_list_gen(n_batches, bs))
    dr._pool_kw = {"manage_period": 0.05}
    feed = StudentFeed(memkv, "teachjob", dr, student_id="s1", period=0.05)
    batches = list(feed)
    assert len(batches) == n_batches
    assert feed.submitted_rows == feed.consumed_rows == n_batches * bs
    assert feed.backlog_rows() == 0
    # stop() clears the durable record — a finished student's backlog
    # does not linger for the autoscaler
    assert scale.load_backlogs(memkv, "teachjob") == {}


def test_student_feed_publishes_backlog_record(memkv, monkeypatch):
    monkeypatch.setattr(reader_mod, "_NOP_PREDICT_TEST", True)
    dr = DistillReader(ins=["x", "idx"], predicts=["prediction"],
                       feeds=["x"])
    dr.set_fixed_teacher("t1")
    dr.set_sample_list_generator(sample_list_gen(2, 2))
    feed = StudentFeed(memkv, "teachjob", dr, student_id="s2", period=60.0)
    # simulate a stream mid-flight: 30 rows in, 10 back
    feed.submitted_rows, feed.consumed_rows = 30, 10
    feed._publish_once(now=100.0)
    recs = scale.load_backlogs(memkv, "teachjob")
    assert recs["s2"]["queued_rows"] == 20
    assert recs["s2"]["rows_per_s"] == 0.0     # no rate observed yet
    # one second later the teachers delivered 20 more rows
    feed.consumed_rows = 30
    feed._publish_once(now=101.0)
    recs = scale.load_backlogs(memkv, "teachjob")
    assert recs["s2"]["queued_rows"] == 0
    assert recs["s2"]["rows_per_s"] == pytest.approx(20.0)
    assert feed.observed_rows_per_s() == pytest.approx(20.0)


def test_load_backlogs_skips_torn_records(memkv):
    scale.save_backlog(memkv, "tj", "good", 5, 1.0)
    from edl_tpu.cluster import paths
    from edl_tpu.utils import constants
    memkv.put(paths.key("tj", constants.ETCD_SCALE, "backlog/torn"),
              b"{not json")
    recs = scale.load_backlogs(memkv, "tj")
    assert set(recs) == {"good"}
    assert recs["good"]["queued_rows"] == 5


# -- DistillAutoscaler -------------------------------------------------------

def test_autoscaler_grow_hold_decay_ladder(memkv):
    a = DistillAutoscaler(memkv, step=1, grow_s=5.0, hold_s=10.0,
                          quiet_s=30.0, demand_ttl=120.0)
    scale.save_backlog(memkv, "tj", "s1", 100, 1.0)   # 100s of backlog
    # above the grow threshold but not yet held: no step
    assert a.desired("tj", 1, 3, 1, now=0.0) == 1
    assert a.desired("tj", 1, 3, 1, now=5.0) == 1
    # held for the full window: one step, and the window re-arms
    assert a.desired("tj", 1, 3, 1, now=10.0) == 2
    assert a.desired("tj", 1, 3, 2, now=15.0) == 2    # re-armed at t=10
    assert a.desired("tj", 1, 3, 2, now=20.0) == 3    # second held window
    assert a.desired("tj", 1, 3, 3, now=30.0) == 3    # clamped at max
    # backlog drains to zero: quiet clock runs, one step per window
    scale.save_backlog(memkv, "tj", "s1", 0, 10.0)
    assert a.desired("tj", 1, 3, 3, now=40.0) == 3    # quiet < 30s
    assert a.desired("tj", 1, 3, 3, now=61.0) == 2    # first quiet window
    assert a.desired("tj", 1, 3, 2, now=92.0) == 1    # second
    assert a.desired("tj", 1, 3, 1, now=123.0) == 1   # floored at min
    a2 = DistillAutoscaler(memkv, step=1, grow_s=5.0, hold_s=0.0,
                           quiet_s=30.0)
    # small-but-nonzero backlog refreshes the quiet clock, never grows
    scale.save_backlog(memkv, "tj2", "s1", 3, 1.0)    # 3s < grow 5s
    assert a2.desired("tj2", 1, 3, 2, now=0.0) == 2
    assert a2.desired("tj2", 1, 3, 2, now=100.0) == 2


def test_autoscaler_ignores_stale_backlog(memkv):
    from edl_tpu.cluster import paths
    from edl_tpu.utils import constants
    a = DistillAutoscaler(memkv, step=1, grow_s=1.0, hold_s=0.0,
                          quiet_s=5.0, demand_ttl=60.0)
    memkv.put(paths.key("stale", constants.ETCD_SCALE, "backlog/dead"),
              json.dumps({"queued_rows": 1000, "rows_per_s": 1.0,
                          "at": time.time() - 999.0}).encode())
    assert a.backlog_seconds("stale") is None
    # a dead student's huge last backlog never grows the fleet, and the
    # target decays on quiet down to min
    assert a.desired("stale", 1, 3, 3, now=0.0) == 3
    assert a.desired("stale", 1, 3, 3, now=6.0) == 2
    assert a.desired("stale", 1, 3, 2, now=12.0) == 1


# -- controller integration --------------------------------------------------

def test_controller_job_view_counts_fleet_adverts(memkv):
    from edl_tpu.controller.controller import Controller
    scale.save_nodes_range(memkv, "teach", 1, 3)
    scale.save_job_spec(memkv, "teach", kind="distill", fleet=True)
    regs = [_advert(memkv, "teach", f"t{i}", f"t{i}:1") for i in range(2)]
    scale.save_backlog(memkv, "teach", "s1", 500, 1.0)
    ctrl = Controller(
        memkv, job_ids=["teach"],
        distill_autoscaler=DistillAutoscaler(memkv, step=1, grow_s=1.0,
                                             hold_s=0.0, quiet_s=60.0))
    try:
        view = ctrl.job_view("teach")
        assert view is not None
        assert view.kind == "distill" and view.priority == 50
        assert view.current_nodes == 2         # counted from live adverts
        assert view.demand == 3                # backlog held: current + step
    finally:
        for r in regs:
            r.stop()
