"""Data-plane fault tolerance: journal replay on a successor leader,
idempotent RPC replay, reader retry/reattach, rebuild grace, the
registry watch, and the bounded reader shutdown."""

import threading
import time

import pytest

from edl_tpu.cluster.state import DataCheckpoint
from edl_tpu.data import DistributedReader, PodDataServer
from edl_tpu.data.data_server import DataService
from edl_tpu.data.journal import DataJournal
from edl_tpu.data.resilient import CallAborted, ResilientDataClient
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import faultinject
from edl_tpu.utils.exceptions import (
    EdlCoordError,
    EdlReaderGoneError,
    EdlStopIteration,
)
from tests.helpers.exactly_once import audit_spans, audit_union

ALL = sorted(f"f{f}r{r}" for f in range(4) for r in range(10))


@pytest.fixture
def files(tmp_path):
    paths = []
    for f in range(4):
        p = tmp_path / f"part-{f}.txt"
        p.write_text("".join(f"f{f}r{r}\n" for r in range(10)))
        paths.append(str(p))
    return paths


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    faultinject.configure(None)


def serve(service: DataService) -> tuple[RpcServer, str]:
    srv = RpcServer("127.0.0.1", 0)
    srv.register_instance(service)
    srv.start()
    return srv, f"127.0.0.1:{srv.port}"


# -- the audit helper itself ------------------------------------------------

def test_audit_spans_detects_overlap_and_gap():
    ok = audit_spans([[0, 0, 5], [0, 5, 10]], {0: 10})
    assert ok["records_exactly_once"] == 10
    with pytest.raises(AssertionError, match="more than once"):
        audit_spans([[0, 0, 6], [0, 5, 10]], {0: 10})
    with pytest.raises(AssertionError, match="never trained"):
        audit_spans([[0, 0, 9]], {0: 10})
    # the consumer-death whitelist tolerates listed duplicates only
    stats = audit_spans([[0, 0, 6], [0, 5, 10]], {0: 10},
                        allow_duplicates_of={(0, 5)})
    assert stats["records_duplicated"] == 1
    audit_union([[0, 3, 10], [0, 0, 5]], {0: 10})
    with pytest.raises(AssertionError):
        audit_union([[0, 0, 9]], {0: 10})


# -- journal replay ---------------------------------------------------------

def test_journal_rebuild_minus_consumed(memkv, files):
    journal = DataJournal(memkv, "j1")
    a = DataService(journal=journal, rebuild_grace=0.0)
    a.create_reader("r@e0@s1", files, consumed=[[3, 0, 4]])
    assert a.next_file("r@e0@s1", "podA")["file"] == [0, files[0]]
    a.report_batch_meta("r@e0@s1", "podA", "127.0.0.1:1",
                        [["podA:0", [[0, 0, 4]]], ["podA:1", [[0, 4, 8]]]])
    a.file_done("r@e0@s1", "podA", 0)
    # consume + ack the first batch
    got = a.get_batch_meta("r@e0@s1", "podB", n=1)["metas"]
    assert got[0][2] == "podA:0"
    a.get_batch_meta("r@e0@s1", "podB", n=0, ack_ids=["podA:0"])

    # successor leader: same journal, fresh service — lazy rebuild
    b = DataService(journal=journal, rebuild_grace=0.0)
    st = b.reader_status("r@e0@s1")
    assert st["files"] == 4
    assert st["done"] == [0]
    assert st["consumed"]["0"] == [[0, 4]]       # the ack survived
    assert st["consumed"]["3"] == [[0, 4]]       # the restored checkpoint
    assert st["parked"] == 1                     # podA:1 awaits its consumer
    # grants resume (grace 0) and skip the consumed spans
    nxt = b.next_file("r@e0@s1", "podC")
    assert nxt["file"][0] in (1, 2, 3)
    if nxt["file"][0] == 3:
        assert nxt["skip"] == [[0, 4]]


def test_idempotent_report_ack_and_grant(memkv, files):
    journal = DataJournal(memkv, "j2")
    svc = DataService(journal=journal)
    svc.create_reader("r", files[:1])
    # a retried next_file returns the SAME assignment, not a second file
    first = svc.next_file("r", "podA")["file"]
    assert svc.next_file("r", "podA")["file"] == first
    # a replayed report must not double-queue
    batches = [["podA:0", [[0, 0, 4]]]]
    svc.report_batch_meta("r", "podA", "ep", batches)
    svc.report_batch_meta("r", "podA", "ep", batches)
    assert svc.reader_status("r")["produced"] == 1
    # a replayed ack must not double-count
    svc.get_batch_meta("r", "podB", n=1)
    svc.get_batch_meta("r", "podB", n=0, ack_ids=["podA:0"])
    svc.get_batch_meta("r", "podB", n=0, ack_ids=["podA:0"])
    st = svc.reader_status("r")
    assert st["acked"] == 1 and st["consumed"]["0"] == [[0, 4]]


def test_ack_replay_lands_on_rebuilt_leader(memkv, files):
    """A consumer that fetched from the OLD leader acks on the NEW one:
    the parked meta resolves the ack (keyed by (reader, batch_id)), and
    an acked batch can never be handed out again after a second crash
    (the journal tombstone keeps the dedup alive)."""
    journal = DataJournal(memkv, "j3")
    a = DataService(journal=journal)
    a.create_reader("r", files[:1])
    a.next_file("r", "podA")
    a.report_batch_meta("r", "podA", "ep", [["podA:0", [[0, 0, 4]]]])
    a.get_batch_meta("r", "podB", n=1)  # handed out, never acked on A

    b = DataService(journal=journal, rebuild_grace=10.0)
    b.get_batch_meta("r", "podB", n=0, ack_ids=["podA:0"])  # parked -> acked
    st = b.reader_status("r")
    assert st["parked"] == 0 and st["consumed"]["0"] == [[0, 4]]

    c = DataService(journal=journal, rebuild_grace=0.0)
    st = c.reader_status("r")
    assert st["parked"] == 0 and st["acked"] == 1
    # a stale report replay of the acked batch must not resurrect it
    c.report_batch_meta("r", "podA", "ep", [["podA:0", [[0, 0, 4]]]])
    assert c.reader_status("r")["queued"] == 0


def test_rebuild_grace_parks_then_releases(memkv, files):
    journal = DataJournal(memkv, "j4")
    a = DataService(journal=journal)
    a.create_reader("r", files[:1])
    a.next_file("r", "podA")
    a.report_batch_meta("r", "podA", "ep", [["podA:0", [[0, 0, 4]]]])
    a.get_batch_meta("r", "podX", n=1)  # podX holds it, unacked

    b = DataService(journal=journal, rebuild_grace=0.6)
    # during the grace neither parked metas nor new grants go out
    assert b.get_batch_meta("r", "podY", n=4)["metas"] == []
    assert b.next_file("r", "podY")["file"] is None
    time.sleep(0.7)
    # past the grace the unclaimed meta is released to any consumer
    metas = b.get_batch_meta("r", "podY", n=4)["metas"]
    assert [m[2] for m in metas] == ["podA:0"]
    # the file stays with its journaled owner (podA may still be mid-
    # production); the idempotent grant hands IT the same file back
    assert b.next_file("r", "podY")["file"] is None
    assert b.next_file("r", "podA")["file"][0] == 0


def test_reattach_restores_held_and_producer(memkv, files):
    journal = DataJournal(memkv, "j5")
    a = DataService(journal=journal)
    a.create_reader("r", files[:2])
    assert a.next_file("r", "podA")["file"][0] == 0
    a.report_batch_meta("r", "podA", "ep", [["podA:0", [[0, 0, 4]]]])
    a.get_batch_meta("r", "podB", n=1)

    b = DataService(journal=journal, rebuild_grace=30.0)
    resp = b.reattach_reader("r", "podB", held=["podA:0", "ghost"])
    assert resp["drop"] == ["ghost"]            # unknown: reader forgets it
    # podB's held batch is back in ITS inflight: the ack works
    b.get_batch_meta("r", "podB", n=0, ack_ids=["podA:0"])
    assert b.reader_status("r")["consumed"]["0"] == [[0, 4]]
    # the producer re-asserts its in-flight grant and keeps the file
    resp = b.reattach_reader("r", "podA", producing=[0, None])
    assert not resp["abandon_file"]
    assert b.next_file("r", "podA")["file"][0] == 0   # same grant back

    # a producer whose journaled grant it never heard of (lost response)
    # gets the file re-pended; one it FINISHED (lost file_done) is done
    c = DataService(journal=journal, rebuild_grace=0.0)
    c.reattach_reader("r", "podA", producing=None, finished=[0])
    st = c.reader_status("r")
    assert 0 in st["done"] and st["owned"] == 0


def test_reattach_reseeds_on_torn_journal(memkv, files):
    """No (or torn) journal on the successor: readers re-seed the
    generation from their own checkpoint + claimed spans — the clean
    fallback onto the stop-resume contract — and the epoch still
    drains exactly once."""
    svc = DataService(journal=None, rebuild_grace=0.2)
    with pytest.raises(EdlReaderGoneError):
        svc.get_batch_meta("r", "podA", n=1)
    svc.reattach_reader("r", "podA", files=files[:1],
                        consumed=[[0, 0, 4]], held=["stale:0"])
    # the unknown held id was dropped; its spans ride consumed
    st = svc.reader_status("r")
    assert st["consumed"]["0"] == [[0, 4]]
    time.sleep(0.25)
    nxt = svc.next_file("r", "podA")
    assert nxt["file"] == [0, files[0]] and nxt["skip"] == [[0, 4]]


def test_rebuild_pends_repairs_behind_live_whole_file_owner(memkv, files):
    """A journaled repair entry for a file with a live whole-file owner
    must survive the rebuild (the owner's skip says it is NOT emitting
    those records) — dropping it would silently lose the records."""
    journal = DataJournal(memkv, "jr1")
    a = DataService(journal=journal)
    a.create_reader("r", files[:1])
    # podB owns file 0 whole with records 0-4 in its skip (live batch)
    a.next_file("r", "podX")
    a.report_batch_meta("r", "podX", "epX", [["podX:0", [[0, 0, 4]]]])
    a.get_batch_meta("r", "podA", n=1)
    a.mark_pod_dead("podX")
    assert a.next_file("r", "podB")["skip"] == [[0, 4]]
    # the live batch dies too: its spans become a journaled repair
    a.nack_batches("r", "podA", ["podX:0"], producer_dead=True)
    assert a.reader_status("r")["pending"] == 1
    # successor rebuild: the repair must re-pend even though podB's
    # whole-file grant is restored; it is granted once podB finishes
    b = DataService(journal=journal, rebuild_grace=0.0)
    st = b.reader_status("r")
    assert st["owned"] == 1 and st["pending"] == 1, st
    b.file_done("r", "podB", 0)
    rep = b.next_file("r", "podC")
    assert rep["file"][0] == 0 and rep["only"] == [[0, 4]], rep


def test_reattach_keeps_queued_full_pass(files):
    """A (possibly spurious) reattach re-asserting a REPAIR grant must
    not purge pending full-pass work for the same file — only entries
    duplicating the grant's own type are absorbed."""
    svc = DataService()
    svc.create_reader("r", files[:1])  # pending: [0, None]
    svc.reattach_reader("r", "podC", producing=[0, [[0, 4]], 0])
    assert svc.reader_status("r")["pending"] == 1  # full pass survives
    svc.reattach_reader("r", "podC", producing=[0, [[0, 4]], 0])
    assert svc.reader_status("r")["pending"] == 1  # idempotent
    # whereas re-asserting the WHOLE-file grant absorbs its own entry
    svc2 = DataService()
    svc2.create_reader("r2", files[:1])
    svc2.reattach_reader("r2", "podB", producing=[0, None, 0])
    assert svc2.reader_status("r2")["pending"] == 0


def test_reseed_repairs_in_flight_file_behind_position(files):
    """No journal: the successor re-seeds from reattaches.  A producer
    mid-file re-asserts its grant WITH its position — the records
    behind it (published to the dead leader, metas lost) re-pend as a
    repair instead of silently never training."""
    svc = DataService(journal=None, rebuild_grace=0.0)
    with pytest.raises(EdlReaderGoneError):
        svc.next_file("r", "podA")
    # producer was at record 8 of file 0; consumer had claimed [0,4)
    svc.reattach_reader("r", "podA", files=files[:1],
                        consumed=[[0, 0, 4]], producing=[0, None, 8])
    time.sleep(0.05)
    st = svc.reader_status("r")
    assert st["owned"] == 1 and st["pending"] == 1, st
    # the repair waits for podA's grant to close (single owner slot)
    assert svc.next_file("r", "podB")["file"] is None
    svc.file_done("r", "podA", 0)
    rep = svc.next_file("r", "podB")
    # the repair covers the lost window [0,8); its grant-time skip
    # excludes the consumed [0,4), so only [4,8) re-produces
    assert rep["file"][0] == 0 and rep["only"] == [[0, 8]], rep
    assert rep["skip"] == [[0, 4]], rep


def test_grant_skip_covers_live_batches_and_nack_repairs(files):
    """The chaos-smoke race, pinned: a dead pod's whole-file requeue
    lands while batches covering the same records sit unacked in a
    survivor's inflight.  The re-grant skip must cover LIVE batches
    (not just acked spans) — re-producing them would train them twice
    — and if such a live batch later nacks dead, exactly its skipped
    spans re-pend as a repair (no drop either)."""
    svc = DataService()
    svc.create_reader("r", files[:1])
    svc.next_file("r", "podX")
    svc.report_batch_meta("r", "podX", "epX", [["podX:0", [[0, 0, 4]]]])
    svc.get_batch_meta("r", "podA", n=1)   # podA holds podX:0, unacked
    svc.mark_pod_dead("podX")
    nxt = svc.next_file("r", "podB")       # file 0 re-granted to podB
    assert nxt["file"][0] == 0
    assert nxt["skip"] == [[0, 4]], nxt    # live-held records skipped
    # the retried grant carries the IDENTICAL skip
    assert svc.next_file("r", "podB")["skip"] == [[0, 4]]
    # podA now nacks podX:0 (dead cache): records 0-4 were in podB's
    # skip, so they re-pend as a repair — podB keeps its grant
    svc.nack_batches("r", "podA", ["podX:0"], producer_dead=True)
    st = svc.reader_status("r")
    assert st["owned"] == 1 and st["pending"] == 1, st
    # the repair waits while podB's grant is open (single owner slot)
    assert svc.next_file("r", "podC")["file"] is None
    svc.file_done("r", "podB", 0)
    rep = svc.next_file("r", "podC")
    assert rep["file"][0] == 0 and rep["only"] == [[0, 4]], rep


def test_get_batch_meta_replay_returns_same_metas(files):
    """A retried get_batch_meta (same req_id) whose first response was
    lost on the wire must receive the SAME metas back — otherwise they
    strand in the pod's inflight with no consumer aware of them and
    the epoch never drains."""
    svc = DataService()
    svc.create_reader("r", files[:1])
    svc.next_file("r", "podA")
    svc.report_batch_meta("r", "podA", "ep",
                          [["podA:0", [[0, 0, 4]]], ["podA:1", [[0, 4, 8]]]])
    first = svc.get_batch_meta("r", "podB", n=2, req_id=1)["metas"]
    assert [m[2] for m in first] == ["podA:0", "podA:1"]
    replay = svc.get_batch_meta("r", "podB", n=2, req_id=1)["metas"]
    assert replay == first
    # a replay that also carries acks re-delivers only the unacked rest
    replay = svc.get_batch_meta("r", "podB", n=2, ack_ids=["podA:0"],
                                req_id=1)["metas"]
    assert [m[2] for m in replay] == ["podA:1"]
    assert svc.reader_status("r")["consumed"]["0"] == [[0, 4]]


def test_requeue_keeps_live_owner_journaled(memkv, files):
    """A nack for a file whose full production is already in progress
    on a LIVE pod must not delete that owner's journal record — a
    rebuilt successor would double-grant the file (two producers
    emitting overlapping spans = records trained twice)."""
    journal = DataJournal(memkv, "j9")
    a = DataService(journal=journal)
    a.create_reader("r", files[:1])
    # dead producer podX reported a batch, then its file re-pended and
    # was re-granted WHOLE to live podB
    a.next_file("r", "podX")
    a.report_batch_meta("r", "podX", "epX", [["podX:0", [[0, 0, 4]]]])
    a.get_batch_meta("r", "podC", n=1)          # podC holds podX:0
    a.mark_pod_dead("podX")
    assert a.next_file("r", "podB")["file"][0] == 0  # re-granted to podB
    # a late nack of podX's batch must leave podB's grant journaled
    # (the nacked records, being in podB's skip, re-pend as a repair)
    a.nack_batches("r", "podC", ["podX:0"], producer_dead=True)
    b = DataService(journal=journal, rebuild_grace=0.0)
    st = b.reader_status("r")
    assert st["owned"] == 1 and st["pending"] == 1, st
    assert b.next_file("r", "podB")["file"][0] == 0  # still podB's


def test_gcd_generation_fails_fast(files):
    """A straggler addressing a GC'd (superseded) generation must get
    a hard error — not resurrect the dead epoch through the reattach
    re-seed fallback."""
    from edl_tpu.utils.exceptions import EdlDataError

    svc = DataService()
    svc.create_reader("t@e0@s", files[:1])
    svc.create_reader("t@e1@s", files[:1])  # GCs t@e0@s
    with pytest.raises(EdlDataError, match="superseded"):
        svc.get_batch_meta("t@e0@s", "podA", n=1)
    with pytest.raises(EdlDataError, match="superseded"):
        svc.reattach_reader("t@e0@s", "podA", files=files[:1])
    with pytest.raises(EdlDataError, match="superseded"):
        svc.create_reader("t@e0@s", files[:1])


def test_gcd_tombstone_survives_failover(memkv, files):
    """The GC tombstone is durable: a SUCCESSOR leader also refuses a
    straggler's reattach for a superseded generation (in-memory
    _dead_readers alone would not survive the failover)."""
    from edl_tpu.utils.exceptions import EdlDataError

    journal = DataJournal(memkv, "jt")
    a = DataService(journal=journal)
    a.create_reader("t@e0@s", files[:1])
    a.create_reader("t@e1@s", files[:1])  # GCs t@e0@s + journals "dead"
    b = DataService(journal=journal, rebuild_grace=0.0)  # fresh successor
    with pytest.raises(EdlDataError, match="superseded"):
        b.reattach_reader("t@e0@s", "podB", files=files[:1])
    assert b.reader_status("t@e1@s")["files"] == 1  # live gen rebuilds


def test_pod_death_event_rebuilds_lazily(memkv, files):
    """A registry-expiry event naming a generation the successor has
    not served yet must force the journal rebuild and requeue the dead
    pod's grants — the advert delete never fires twice."""
    journal = DataJournal(memkv, "jl")
    a = DataService(journal=journal)
    a.create_reader("r", files[:1])
    a.next_file("r", "podX")
    b = DataService(journal=journal, rebuild_grace=0.0)  # nothing served
    b.mark_pod_dead("podX", reader="r")  # the expiry event
    st = b.reader_status("r")
    assert st["owned"] == 0 and st["pending"] == 1, st


def test_reconcile_requeues_pods_with_no_advert(memkv, files):
    """A successor leader reconciles journal-restored grants against
    the live registry: a pod that died BEFORE the successor's watch
    started (no delete event will ever fire) must not pin its files."""
    journal = DataJournal(memkv, "jr2")
    a = DataService(journal=journal)
    a.create_reader("r", files[:2])
    a.next_file("r", "podX")                       # podX owns file 0
    b = DataService(journal=journal, rebuild_grace=0.0)
    assert b.reconcile_pods("r", ["podY"])["dead"] == ["podX"]
    st = b.reader_status("r")
    assert st["owned"] == 0 and st["pending"] == 2, st


# -- reader-side resilience --------------------------------------------------

def test_reader_survives_transient_faults(files):
    """Injected transport errors below the retry deadline cause ZERO
    reader failures — retries are visible in metrics, not exceptions
    (the acceptance criterion for a transient leader blip)."""
    from edl_tpu.data.resilient import _RETRIES

    a = PodDataServer("podA", is_leader=True)
    faultinject.configure(
        "client:get_batch_meta:error:0.3;client:next_file:error:0.3;"
        "client:report_batch_meta:error:0.3", seed=7)
    before = sum(_RETRIES.labels(op=op).value
                 for op in ("get_batch_meta", "next_file",
                            "report_batch_meta"))
    try:
        ra = DistributedReader("rf", "podA", a.endpoint, a, batch_size=4)
        ra.create(files)
        spans = []
        got = []
        for _bid, payload in ra:
            got.extend(payload["records"])
            spans.extend(payload["spans"])
        assert sorted(got) == ALL
        audit_spans(spans, 4, 10)
        retried = sum(_RETRIES.labels(op=op).value
                      for op in ("get_batch_meta", "next_file",
                                 "report_batch_meta")) - before
        assert retried > 0, "a 30% fault rate must have exercised retries"
    finally:
        faultinject.configure(None)
        a.stop()


def test_reader_reattaches_across_leader_restart(memkv, files):
    """SIGKILL-equivalent: the leader server dies mid-epoch; a
    successor (same journal) comes up on a DIFFERENT endpoint; the
    reader re-resolves, reattaches, and finishes the epoch with every
    record delivered exactly once."""
    journal = DataJournal(memkv, "j6")
    cache = PodDataServer("podA")
    srv1, ep1 = serve(DataService(journal=journal, rebuild_grace=1.0))
    endpoint = {"ep": ep1}
    srv2 = None
    try:
        ra = DistributedReader("rk", "podA", lambda: endpoint["ep"], cache,
                               batch_size=4, retry_deadline=30.0)
        ra.create(files)
        got, spans = [], []
        it = iter(ra)
        for _ in range(3):
            _bid, payload = next(it)
            got.extend(payload["records"])
            spans.extend(payload["spans"])
        # kill the leader mid-epoch, seat a successor elsewhere
        srv1.stop()
        srv2, ep2 = serve(DataService(journal=journal, rebuild_grace=1.0))
        endpoint["ep"] = ep2
        for _bid, payload in it:
            got.extend(payload["records"])
            spans.extend(payload["spans"])
        assert sorted(got) == ALL
        audit_spans(spans, 4, 10)
    finally:
        cache.stop()
        for s in (srv1, srv2):
            if s is not None:
                s.stop()


def test_resilient_client_raises_after_budget():
    client = ResilientDataClient("127.0.0.1:1", timeout=0.2,
                                 retry_deadline=0.8)
    t0 = time.monotonic()
    with pytest.raises(EdlCoordError):
        client.call("reader_status", reader="x")
    assert time.monotonic() - t0 < 10.0
    client.close()


def test_call_aborted_after_reattach_abandon():
    """The coalesced-meta exactly-once guard: a leader failover mid-
    report triggers a reattach on the retry, and when that reattach
    learns the file was re-granted elsewhere (the producer's abandon
    flag), the retried report must NOT be replayed on the successor —
    its spans now belong to the new owner.  call() raises CallAborted
    before delivering."""
    delivered = []
    srv = RpcServer("127.0.0.1", 0)
    srv.register("report_batch_meta",
                 lambda **kw: (delivered.append(kw), {"backlog": 0})[1])
    srv.start()
    abandoned = threading.Event()
    eps = iter(["127.0.0.1:1", f"127.0.0.1:{srv.port}"])
    last = {"ep": "127.0.0.1:1"}

    def resolver():
        last["ep"] = next(eps, last["ep"])
        return last["ep"]

    client = ResilientDataClient(
        resolver, timeout=0.5, retry_deadline=10.0,
        on_reattach=lambda raw_call: abandoned.set(),  # = abandon_file
        name="abort-test")
    try:
        with pytest.raises(CallAborted):
            client.call("report_batch_meta", reader="r", pod_id="p",
                        endpoint="e", batches=[["b0", [[0, 0, 4]]]],
                        _abort_if=abandoned.is_set)
        assert delivered == []   # the successor never saw the report
    finally:
        client.close()
        srv.stop()


def test_close_bounds_stuck_producer(files, caplog):
    """A producer blocked in an in-flight leader call must not leak
    past close(): the stop flag + capped call budget unwind it, and a
    truly wedged thread is logged, not silently abandoned."""
    srv = RpcServer("127.0.0.1", 0)
    release = threading.Event()

    def slow_next_file(reader, pod_id):
        release.wait(30.0)  # a leader that never answers in time
        return {"file": None, "skip": [], "eof": True}

    svc = DataService()
    svc.create_reader("rc", files[:1])
    srv.register_instance(svc)
    srv.register("next_file", slow_next_file)  # shadow with the stall
    srv.start()
    cache = PodDataServer("podA")
    try:
        ra = DistributedReader("rc", "podA", f"127.0.0.1:{srv.port}", cache,
                               batch_size=4)
        ra._files = files[:1]
        ra._producer = threading.Thread(target=ra._produce, daemon=True)
        ra._producer.start()
        time.sleep(0.3)  # the producer is now blocked inside next_file
        t0 = time.monotonic()
        ra.close(deadline=1.0)
        took = time.monotonic() - t0
        assert took < 5.0, f"close() blocked {took:.1f}s on a stuck producer"
    finally:
        release.set()
        cache.stop()
        srv.stop()


# -- registry watch ----------------------------------------------------------

def test_wait_dist_readers_watch_reacts_fast(memkv):
    from edl_tpu.data import register_reader, wait_dist_readers

    reg_a = register_reader(memkv, "jw", "r", "podA", "epA")
    done = {}

    def waiter():
        t0 = time.monotonic()
        done["got"] = wait_dist_readers(memkv, "jw", "r", ["podA", "podB"],
                                        timeout=10.0)
        done["took"] = time.monotonic() - t0

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.4)
    reg_b = register_reader(memkv, "jw", "r", "podB", "epB")
    t.join(5.0)
    assert not t.is_alive() and done["got"] == {"podA": "epA", "podB": "epB"}
    # the watch must react well inside a poll tick of the old 0.2s loop
    assert done["took"] < 2.0, done
    reg_a.stop(), reg_b.stop()


def test_wait_dist_readers_falls_back_to_polling(memkv):
    from edl_tpu.data import register_reader, wait_dist_readers

    class NoWatch:
        """Store whose watch path is broken (old server)."""

        def __init__(self, kv):
            self._kv = kv

        def get_prefix(self, prefix):
            return self._kv.get_prefix(prefix)

        def wait(self, prefix, since_revision, timeout):
            raise NotImplementedError("old server")

    reg = register_reader(memkv, "jp", "r", "podA", "epA")
    got = wait_dist_readers(NoWatch(memkv), "jp", "r", ["podA"], timeout=5.0)
    assert got == {"podA": "epA"}
    reg.stop()


def test_wait_dist_readers_timeout(memkv):
    from edl_tpu.data import wait_dist_readers
    from edl_tpu.utils.exceptions import EdlDataError

    t0 = time.monotonic()
    with pytest.raises(EdlDataError):
        wait_dist_readers(memkv, "jt", "r", ["ghost"], timeout=0.6)
    assert time.monotonic() - t0 < 5.0


# -- end-of-data across rebuild ----------------------------------------------

def test_drain_completes_on_successor(memkv, files):
    """The generation drains to EdlStopIteration on the successor: done
    files stay done, parked work resolves, and eof gates on the grace."""
    journal = DataJournal(memkv, "j8")
    a = DataService(journal=journal)
    a.create_reader("r", files[:1])
    a.next_file("r", "podA")
    a.report_batch_meta("r", "podA", "ep", [["podA:0", [[0, 0, 10]]]])
    a.file_done("r", "podA", 0)
    b = DataService(journal=journal, rebuild_grace=0.2)
    b.get_batch_meta("r", "podA", n=0, ack_ids=["podA:0"])  # ack from parked
    time.sleep(0.25)
    with pytest.raises(EdlStopIteration):
        b.get_batch_meta("r", "podA", n=1)
    assert b.next_file("r", "podA")["eof"] is True
