"""Multi-process serving failover e2e (slow tier).

Drives scripts/gateway_smoke.py — the canonical harness: two replica
PROCESSES against a real coordination server, greedy parity through the
gateway, a deterministic SIGSTOP-induced hedge, a SIGKILL under
sustained load with zero lost accepted requests, saturation rejects,
and the edl_gateway_*/edl_serving_* metrics + route/hedge/retry trace
spans.  One harness for CI and the suite so the acceptance proof can't
drift from what CI runs.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_gateway_sigkill_failover_e2e(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               EDL_TPU_METRICS_PORT="0",
               EDL_TPU_TRACE_DIR=str(tmp_path / "trace"))
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gateway_smoke.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=580)
    assert out.returncode == 0, out.stdout[-4000:]
    assert "gateway smoke OK" in out.stdout
    assert "SIGKILL under load" in out.stdout
