"""Mesh + logical sharding tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from edl_tpu.parallel import (
    MeshSpec, ShardingRules, build_mesh, default_mesh,
    logical_sharding, logical_constraint, shard_host_batch,
)
from edl_tpu.parallel.mesh import batch_divisor


def test_default_mesh_all_dp():
    mesh = default_mesh()
    assert mesh.shape["dp"] == 8
    assert all(mesh.shape[a] == 1 for a in mesh.axis_names if a != "dp")


def test_spec_resolve_wildcard():
    assert MeshSpec(tp=2).resolve(8)["dp"] == 4
    assert MeshSpec(dp=2, tp=2, sp=2).resolve(8)["dp"] == 2
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=3).resolve(8)


def test_build_mesh_multi_axis():
    mesh = build_mesh(MeshSpec(dp=2, tp=2, sp=2))
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2 and mesh.shape["sp"] == 2
    assert batch_divisor(mesh) == 2


def test_logical_sharding_drops_size1_axes():
    mesh = build_mesh(MeshSpec(dp=4, tp=2))
    s = logical_sharding(("batch", "embed", "mlp"), mesh)
    # fsdp has size 1 → batch maps to dp only; embed (fsdp) replicated.
    assert s.spec == P("dp", None, "tp")


def test_logical_sharding_tuple_axes():
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    s = logical_sharding(("batch", None, "mlp"), mesh)
    assert s.spec == P(("dp", "fsdp"), None, "tp")


def test_no_mesh_axis_reuse_within_spec():
    mesh = build_mesh(MeshSpec(dp=4, tp=2))
    rules = ShardingRules().updated(rows="tp", cols="tp")
    s = logical_sharding(("rows", "cols"), mesh, rules)
    # tp may appear only once per spec; second use is replicated.
    assert s.spec == P("tp")


def test_shard_host_batch_and_constraint():
    mesh = build_mesh(MeshSpec(dp=8))
    batch = {"x": np.ones((16, 4), np.float32), "y": np.arange(16)}
    global_batch = shard_host_batch(batch, mesh)
    assert global_batch["x"].sharding.spec == P("dp")

    @jax.jit
    def f(b):
        h = logical_constraint(b["x"] * 2, ("batch", None), mesh)
        return h.sum() + b["y"].sum()

    assert float(f(global_batch)) == 16 * 4 * 2 + np.arange(16).sum()


def test_matmul_psum_over_tp_mesh():
    # A tp-sharded matmul must reduce over ICI: result matches single-device.
    mesh = build_mesh(MeshSpec(dp=1, tp=8))
    rules = ShardingRules()
    x = np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(16, 32)).astype(np.float32)
    xs = jax.device_put(x, logical_sharding((None, "mlp"), mesh, rules))
    ws = jax.device_put(w, logical_sharding(("mlp", None), mesh, rules))
    out = jax.jit(lambda a, b: a @ b)(xs, ws)
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-4, atol=1e-4)


def test_hybrid_mesh_dcn_groups():
    """dcn_dp spreads replica groups across slices; dp = dcn x inner dp.
    On CPU there are no slice indices, so this exercises the slice-major
    reshape fallback; the resulting mesh must still run a sharded step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from edl_tpu.parallel.mesh import MeshSpec, batch_divisor, build_mesh
    from edl_tpu.parallel.sharding import shard_host_batch

    mesh = build_mesh(MeshSpec(dp=-1, tp=2, dcn_dp=2))
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    assert batch_divisor(mesh) == 4
    g = shard_host_batch({"x": np.ones((8, 4), np.float32)}, mesh)
    out = jax.jit(lambda b: b["x"].sum())(g)
    assert float(out) == 32.0


def test_hybrid_mesh_auto_single_slice():
    from edl_tpu.parallel.mesh import MeshSpec, build_mesh, n_slices
    import jax

    assert n_slices(jax.devices()) == 1  # CPU: no slice_index attr
    mesh = build_mesh(MeshSpec(dp=-1, dcn_dp=0))  # auto -> 1 group
    assert mesh.shape["dp"] == 8


def test_hybrid_mesh_bad_group_count():
    import pytest
    from edl_tpu.parallel.mesh import MeshSpec, build_mesh

    with pytest.raises(ValueError, match="DCN groups"):
        build_mesh(MeshSpec(dp=-1, dcn_dp=3))
