"""Job-level aggregator: coord-store discovery of /metrics endpoints,
merged exposition that stays byte-parseable when processes export the
same metric with different label sets, and the /healthz job summary."""

import json
import math
import urllib.request

import pytest

from edl_tpu.obs import advert
from edl_tpu.obs.agg import (
    Aggregator, AggregatorServer, merge_expositions, quantile_from_buckets,
)
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.metrics import Registry, parse_exposition


def _page(build):
    reg = Registry()
    build(reg)
    return reg.render()


# -- merge_expositions -------------------------------------------------------

def test_merge_adds_labels_and_stays_parseable():
    a = _page(lambda r: r.counter("edl_x_total", "x", ("op",))
              .labels(op="get").inc(3))
    b = _page(lambda r: r.counter("edl_x_total", "x", ("op",))
              .labels(op="put").inc(5))
    merged = merge_expositions([({"component": "c1", "instance": "h:1"}, a),
                                ({"component": "c2", "instance": "h:2"}, b)])
    parsed = parse_exposition(merged)   # raises on any malformed line
    assert parsed[("edl_x_total", (("component", "c1"), ("instance", "h:1"),
                                   ("op", "get")))] == 3.0
    assert parsed[("edl_x_total", (("component", "c2"), ("instance", "h:2"),
                                   ("op", "put")))] == 5.0


def test_merge_dedupes_help_type_across_conflicting_label_sets():
    # the satellite case: same metric NAME, different label sets — the
    # merged page must carry exactly one HELP and one TYPE per family
    a = _page(lambda r: r.gauge("edl_shared", "from a", ("role",))
              .labels(role="x").set(1))
    b = _page(lambda r: r.gauge("edl_shared", "from b").set(2))
    merged = merge_expositions([({"component": "a", "instance": "h:1"}, a),
                                ({"component": "b", "instance": "h:2"}, b)])
    assert merged.count("# TYPE edl_shared gauge") == 1
    assert merged.count("# HELP edl_shared") == 1
    parsed = parse_exposition(merged)
    keys = [k for k in parsed if k[0] == "edl_shared"]
    assert len(keys) == 2   # both processes' samples survive, disambiguated


def test_merge_histograms_group_under_one_family():
    def build(r):
        r.histogram("edl_h_seconds", "h", buckets=(0.1, 1.0)).observe(0.05)

    merged = merge_expositions(
        [({"component": "c", "instance": f"h:{i}"}, _page(build))
         for i in (1, 2)])
    # family header once, then both instances' bucket/sum/count samples
    assert merged.count("# TYPE edl_h_seconds histogram") == 1
    parsed = parse_exposition(merged)
    buckets = [k for k in parsed if k[0] == "edl_h_seconds_bucket"]
    assert len(buckets) == 6    # 3 le-buckets x 2 instances
    # an existing label (le) is never clobbered by the injected ones
    assert all(dict(labels).get("le") for _, labels in buckets)


def test_merge_empty_and_label_escaping():
    assert merge_expositions([]) == ""
    a = _page(lambda r: r.counter("edl_e_total", "e", ("p",))
              .labels(p='we"ird\\').inc())
    merged = merge_expositions([({"component": "c", "instance": "h:1"}, a)])
    parsed = parse_exposition(merged)
    ((_, labels),) = [k for k in parsed if k[0] == "edl_e_total"]
    assert dict(labels)["p"] == 'we"ird\\'


# -- quantiles from merged histograms ----------------------------------------

def test_quantile_from_buckets():
    buckets = {0.1: 50.0, 1.0: 90.0, math.inf: 100.0}
    assert quantile_from_buckets(buckets, 0.5) == pytest.approx(0.1)
    # p90 sits exactly at the 1.0 bound
    assert quantile_from_buckets(buckets, 0.9) == pytest.approx(1.0)
    # tail beyond the last finite bound resolves to that bound
    assert quantile_from_buckets(buckets, 0.99) == pytest.approx(1.0)
    assert quantile_from_buckets({}, 0.5) is None
    assert quantile_from_buckets({math.inf: 0.0}, 0.5) is None


# -- end to end over real HTTP + a real store --------------------------------

@pytest.fixture
def fleet(memkv):
    servers, regs = [], []

    def spawn(component: str, build) -> MetricsServer:
        reg = Registry()
        build(reg)
        srv = MetricsServer(reg, host="127.0.0.1").start()
        servers.append(srv)
        regs.append(advert.advertise_metrics(
            memkv, "job", component, srv.endpoint,
            name=f"{component}-{srv.port}", ttl=30))
        return srv

    yield spawn
    for r in regs:
        r.stop()
    for s in servers:
        s.stop()


def test_aggregator_merges_live_targets(memkv, fleet):
    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc(7))
    fleet("replica", lambda r: r.gauge("edl_r", "r").set(3))
    agg = Aggregator(memkv, "job", cache_s=0.0)
    merged, info = agg.collect()
    assert len(info["targets"]) == 2 and not info["errors"]
    parsed = parse_exposition(merged)
    by_component = {dict(labels).get("component")
                    for (name, labels) in parsed
                    if name in ("edl_t_total", "edl_r")}
    assert by_component == {"trainer", "replica"}
    # the aggregator's own registry rides along
    assert any(name == "edl_obs_agg_targets" for name, _ in parsed)


def test_aggregator_tolerates_dead_target(memkv, fleet):
    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc())
    reg = advert.advertise_metrics(memkv, "job", "ghost",
                                   "127.0.0.1:1", name="ghost-1", ttl=30)
    try:
        agg = Aggregator(memkv, "job", scrape_timeout=0.5, cache_s=0.0)
        merged, info = agg.collect()
        assert "ghost-1" in info["errors"]
        parsed = parse_exposition(merged)   # live page still parseable
        assert any(name == "edl_t_total" for name, _ in parsed)
    finally:
        reg.stop()


def test_aggregator_server_metrics_and_healthz(memkv, fleet):
    from edl_tpu.cluster import recovery

    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc())
    fleet("gateway", lambda r: r.histogram(
        "edl_gateway_request_seconds", "lat",
        buckets=(0.1, 1.0)).observe(0.05))
    recovery.write_launcher_half(
        memkv, "job", "s1", "podA",
        {"detect": 100.0, "killed": 101.0, "barrier": 101.5, "spawn": 102.0})
    # include_self=False: under the full suite this process's registry
    # already holds gateway histograms from other tests — the healthz
    # numbers here must come from the fleet pages alone
    srv = AggregatorServer(memkv, "job", host="127.0.0.1",
                           cache_s=0.0, include_self=False).start()
    try:
        page = urllib.request.urlopen(
            f"http://{srv.endpoint}/metrics", timeout=10).read().decode()
        parse_exposition(page)
        assert 'component="gateway"' in page
        health = json.loads(urllib.request.urlopen(
            f"http://{srv.endpoint}/healthz", timeout=10).read().decode())
        assert health["live_targets"] == 2
        assert health["components"] == {"trainer": 1, "gateway": 1}
        assert health["resizes"] == 1
        assert health["last_resize"]["stage"] == "s1"
        assert health["gateway"]["requests"] == 1.0
        assert health["gateway"]["p99_s"] is not None
    finally:
        srv.stop()
