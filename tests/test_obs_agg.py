"""Job-level aggregator: coord-store discovery of /metrics endpoints,
merged exposition that stays byte-parseable when processes export the
same metric with different label sets, the /healthz job summary
(windowed quantiles + robustness headlines), the scrape loop feeding
the TSDB/rule engine, and /alerts."""

import json
import math
import socket
import time
import urllib.request

import pytest

from edl_tpu.obs import advert
from edl_tpu.obs.agg import (
    Aggregator, AggregatorServer, merge_expositions, quantile_from_buckets,
)
from edl_tpu.obs.exposition import MetricsServer
from edl_tpu.obs.metrics import Registry, parse_exposition


def _page(build):
    reg = Registry()
    build(reg)
    return reg.render()


# -- merge_expositions -------------------------------------------------------

def test_merge_adds_labels_and_stays_parseable():
    a = _page(lambda r: r.counter("edl_x_total", "x", ("op",))
              .labels(op="get").inc(3))
    b = _page(lambda r: r.counter("edl_x_total", "x", ("op",))
              .labels(op="put").inc(5))
    merged = merge_expositions([({"component": "c1", "instance": "h:1"}, a),
                                ({"component": "c2", "instance": "h:2"}, b)])
    parsed = parse_exposition(merged)   # raises on any malformed line
    assert parsed[("edl_x_total", (("component", "c1"), ("instance", "h:1"),
                                   ("op", "get")))] == 3.0
    assert parsed[("edl_x_total", (("component", "c2"), ("instance", "h:2"),
                                   ("op", "put")))] == 5.0


def test_merge_dedupes_help_type_across_conflicting_label_sets():
    # the satellite case: same metric NAME, different label sets — the
    # merged page must carry exactly one HELP and one TYPE per family
    a = _page(lambda r: r.gauge("edl_shared", "from a", ("role",))
              .labels(role="x").set(1))
    b = _page(lambda r: r.gauge("edl_shared", "from b").set(2))
    merged = merge_expositions([({"component": "a", "instance": "h:1"}, a),
                                ({"component": "b", "instance": "h:2"}, b)])
    assert merged.count("# TYPE edl_shared gauge") == 1
    assert merged.count("# HELP edl_shared") == 1
    parsed = parse_exposition(merged)
    keys = [k for k in parsed if k[0] == "edl_shared"]
    assert len(keys) == 2   # both processes' samples survive, disambiguated


def test_merge_histograms_group_under_one_family():
    def build(r):
        r.histogram("edl_h_seconds", "h", buckets=(0.1, 1.0)).observe(0.05)

    merged = merge_expositions(
        [({"component": "c", "instance": f"h:{i}"}, _page(build))
         for i in (1, 2)])
    # family header once, then both instances' bucket/sum/count samples
    assert merged.count("# TYPE edl_h_seconds histogram") == 1
    parsed = parse_exposition(merged)
    buckets = [k for k in parsed if k[0] == "edl_h_seconds_bucket"]
    assert len(buckets) == 6    # 3 le-buckets x 2 instances
    # an existing label (le) is never clobbered by the injected ones
    assert all(dict(labels).get("le") for _, labels in buckets)


def test_merge_empty_and_label_escaping():
    assert merge_expositions([]) == ""
    a = _page(lambda r: r.counter("edl_e_total", "e", ("p",))
              .labels(p='we"ird\\').inc())
    merged = merge_expositions([({"component": "c", "instance": "h:1"}, a)])
    parsed = parse_exposition(merged)
    ((_, labels),) = [k for k in parsed if k[0] == "edl_e_total"]
    assert dict(labels)["p"] == 'we"ird\\'


# -- quantiles from merged histograms ----------------------------------------

def test_quantile_from_buckets():
    buckets = {0.1: 50.0, 1.0: 90.0, math.inf: 100.0}
    assert quantile_from_buckets(buckets, 0.5) == pytest.approx(0.1)
    # p90 sits exactly at the 1.0 bound
    assert quantile_from_buckets(buckets, 0.9) == pytest.approx(1.0)
    # tail beyond the last finite bound resolves to that bound
    assert quantile_from_buckets(buckets, 0.99) == pytest.approx(1.0)
    assert quantile_from_buckets({}, 0.5) is None
    assert quantile_from_buckets({math.inf: 0.0}, 0.5) is None


# -- end to end over real HTTP + a real store --------------------------------

@pytest.fixture
def fleet(memkv):
    servers, regs = [], []

    def spawn(component: str, build) -> MetricsServer:
        reg = Registry()
        build(reg)
        srv = MetricsServer(reg, host="127.0.0.1").start()
        servers.append(srv)
        regs.append(advert.advertise_metrics(
            memkv, "job", component, srv.endpoint,
            name=f"{component}-{srv.port}", ttl=30))
        return srv

    yield spawn
    for r in regs:
        r.stop()
    for s in servers:
        s.stop()


def test_aggregator_merges_live_targets(memkv, fleet):
    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc(7))
    fleet("replica", lambda r: r.gauge("edl_r", "r").set(3))
    agg = Aggregator(memkv, "job", cache_s=0.0)
    merged, info = agg.collect()
    assert len(info["targets"]) == 2 and not info["errors"]
    parsed = parse_exposition(merged)
    by_component = {dict(labels).get("component")
                    for (name, labels) in parsed
                    if name in ("edl_t_total", "edl_r")}
    assert by_component == {"trainer", "replica"}
    # the aggregator's own registry rides along
    assert any(name == "edl_obs_agg_targets" for name, _ in parsed)


def test_aggregator_tolerates_dead_target(memkv, fleet):
    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc())
    reg = advert.advertise_metrics(memkv, "job", "ghost",
                                   "127.0.0.1:1", name="ghost-1", ttl=30)
    try:
        agg = Aggregator(memkv, "job", scrape_timeout=0.5, cache_s=0.0)
        merged, info = agg.collect()
        assert "ghost-1" in info["errors"]
        parsed = parse_exposition(merged)   # live page still parseable
        assert any(name == "edl_t_total" for name, _ in parsed)
    finally:
        reg.stop()


def test_merge_stays_parseable_when_help_text_changes_mid_run():
    # satellite: a target rewriting its HELP string between scrapes (a
    # redeploy with new wording) must not break parseability or dupe
    # the family header on either scrape's merged page
    def page(help_text):
        return _page(lambda r: r.gauge("edl_flip", help_text).set(1))

    for help_text in ("old wording", "new wording"):
        merged = merge_expositions(
            [({"component": "a", "instance": "h:1"}, page("old wording")),
             ({"component": "b", "instance": "h:2"}, page(help_text))])
        parse_exposition(merged)
        assert merged.count("# HELP edl_flip") == 1
        assert merged.count("# TYPE edl_flip gauge") == 1


def test_many_dead_targets_scrape_in_one_timeout(memkv, fleet):
    # satellite: the fan-out pool is sized to len(targets) — with 20
    # blackholed targets (connected, never answered) the whole collect
    # must cost ~ONE scrape timeout, not ceil(20/8) waves of them
    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc())
    blackhole = socket.socket()
    blackhole.bind(("127.0.0.1", 0))
    blackhole.listen(32)                 # accept queue only: never served
    ep = f"127.0.0.1:{blackhole.getsockname()[1]}"
    regs = [advert.advertise_metrics(memkv, "job", "ghost", ep,
                                     name=f"ghost-{i}", ttl=30)
            for i in range(20)]
    try:
        agg = Aggregator(memkv, "job", scrape_timeout=0.75, cache_s=0.0)
        t0 = time.monotonic()
        merged, info = agg.collect()
        elapsed = time.monotonic() - t0
        assert len(info["errors"]) == 20
        assert any(name == "edl_t_total"
                   for name, _ in parse_exposition(merged))
        # serial: 15s; min(8, n) pool: ~2.25s; len(n) pool: ~0.75s
        assert elapsed < 2.0, f"dead-target fan-out took {elapsed:.2f}s"
    finally:
        for r in regs:
            r.stop()
        blackhole.close()


def test_job_summary_caches_recovery_read(memkv, fleet):
    from edl_tpu.cluster import recovery

    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc())
    recovery.write_launcher_half(
        memkv, "job", "s1", "podA",
        {"detect": 100.0, "killed": 101.0, "barrier": 101.5, "spawn": 102.0})
    calls = {"n": 0}
    real = memkv.get_prefix

    def counting(prefix):
        if "recovery" in prefix:
            calls["n"] += 1
        return real(prefix)

    memkv.get_prefix = counting
    try:
        agg = Aggregator(memkv, "job", cache_s=0.0)
        for _ in range(5):
            # collect() is cache-cold every time (cache_s=0), but the
            # recovery read must NOT re-hit the store per health probe
            assert agg.job_summary()["resizes"] == 1
        assert calls["n"] == 1
    finally:
        memkv.get_prefix = real


def test_job_summary_windowed_gateway_quantiles(memkv, fleet):
    reg_holder = {}

    def build(r):
        reg_holder["hist"] = r.histogram(
            "edl_gateway_request_seconds", "lat", buckets=(0.1, 1.0))
        for _ in range(100):
            reg_holder["hist"].observe(0.05)

    fleet("gateway", build)
    agg = Aggregator(memkv, "job", cache_s=0.0, include_self=False,
                     quantile_window=60.0)
    # no TSDB history yet: lifetime fallback, explicitly marked
    s = agg.job_summary()
    assert s["gateway"]["window"] == "lifetime"
    assert s["gateway"]["p99_s"] is not None
    assert s["alerts"] == {"firing": 0, "names": []}

    # two scrapes with ONLY slow traffic in between: the windowed
    # quantile must see the window's distribution, not the lifetime's
    agg.scrape_once(now=1000.0)
    for _ in range(50):
        reg_holder["hist"].observe(0.5)
    agg._cached = None                      # force a fresh fan-out
    agg.scrape_once(now=1010.0)
    s = agg.job_summary()
    assert s["gateway"]["window"] == "60s"
    assert s["gateway"]["requests"] == 50.0          # window, not lifetime
    assert s["gateway"]["p50_s"] > 0.1               # all-slow window


def test_job_summary_robustness_headlines(memkv, fleet):
    def build(r):
        r.counter("edl_hang_restarts_total", "hangs").inc(2)
        r.counter("edl_data_spans_requeued_total", "req",
                  ("reader",)).labels(reader="r0").inc(37)
        r.gauge("edl_coord_outage_seconds", "mttr").set(3.25)
    fleet("launcher", build)
    agg = Aggregator(memkv, "job", cache_s=0.0, include_self=False)
    rb = agg.job_summary()["robustness"]
    assert rb["hang_restarts"] == 2.0
    assert rb["data_spans_requeued"] == 37.0
    assert rb["coord_restart_mttr_s"] == 3.25
    assert rb["data_leader_mttr_s"] is None          # nothing reported it


def test_scrape_loop_feeds_rules_and_alerts_endpoint(memkv, fleet):
    from edl_tpu.obs.rules import Rule

    holder = {}

    def build(r):
        holder["g"] = r.gauge("edl_smoke_pressure", "p")
        holder["g"].set(0.0)

    fleet("trainer", build)
    rules = [Rule("pressure-high", kind="gauge",
                  metric="edl_smoke_pressure", op=">", threshold=5.0,
                  window=60.0, severity="critical", summary="too high")]
    srv = AggregatorServer(memkv, "job", host="127.0.0.1", cache_s=0.0,
                           include_self=False, scrape_interval=0.1,
                           rules=rules, incident_dir="").start()
    try:
        body = json.loads(urllib.request.urlopen(
            f"http://{srv.endpoint}/alerts", timeout=10).read().decode())
        assert body["firing"] == [] and len(body["rules"]) == 1
        holder["g"].set(9.0)
        deadline = time.time() + 30
        while time.time() < deadline:
            body = json.loads(urllib.request.urlopen(
                f"http://{srv.endpoint}/alerts", timeout=10).read().decode())
            if body["firing"]:
                break
            time.sleep(0.05)
        assert [a["alert"] for a in body["firing"]] == ["pressure-high"]
        assert body["firing"][0]["severity"] == "critical"
        # the /healthz roll-up sees it too
        health = json.loads(urllib.request.urlopen(
            f"http://{srv.endpoint}/healthz", timeout=10).read().decode())
        assert health["alerts"]["names"] == ["pressure-high"]
    finally:
        srv.stop()


def test_job_trace_publish_roundtrip(memkv):
    from edl_tpu.obs import context as obs_context

    assert advert.current_job_trace(memkv, "job") is None
    ctx = obs_context.new_trace()
    advert.publish_job_trace(memkv, "job", ctx, stage="s1")
    rec = advert.current_job_trace(memkv, "job")
    assert rec["trace_id"] == ctx.trace_id and rec["stage"] == "s1"
    # the aggregator's incident trace provider reads the same record
    agg = Aggregator(memkv, "job", cache_s=0.0)
    assert agg._job_trace_id() == ctx.trace_id


def test_render_top_frame():
    from edl_tpu.obs.top import render_top

    health = {"job_id": "rn50", "live_targets": 3,
              "components": {"trainer": 2, "launcher": 1},
              "rates": {"train_steps_per_s": 12.5},
              "gateway": {"p50_s": 0.01, "p99_s": 0.2, "requests": 100.0,
                          "window": "120s"},
              "robustness": {"coord_restart_mttr_s": 1.5,
                             "data_leader_mttr_s": None,
                             "hang_restarts": 0.0,
                             "data_spans_requeued": 0.0},
              "scrape_errors": {}}
    alerts = {"firing": [{"alert": "trainer-hang", "severity": "critical",
                          "value": 0.0, "firing_since": time.time() - 5,
                          "summary": "no step progress"}]}
    text = render_top(health, alerts)
    assert "job rn50" in text and "trainer" in text
    assert "trainer-hang" in text and "critical" in text
    assert "p99=0.2s" in text
    quiet = render_top(health, {"firing": [], "pending": []})
    assert "none firing" in quiet


def test_aggregator_server_metrics_and_healthz(memkv, fleet):
    from edl_tpu.cluster import recovery

    fleet("trainer", lambda r: r.counter("edl_t_total", "t").inc())
    fleet("gateway", lambda r: r.histogram(
        "edl_gateway_request_seconds", "lat",
        buckets=(0.1, 1.0)).observe(0.05))
    recovery.write_launcher_half(
        memkv, "job", "s1", "podA",
        {"detect": 100.0, "killed": 101.0, "barrier": 101.5, "spawn": 102.0})
    # include_self=False: under the full suite this process's registry
    # already holds gateway histograms from other tests — the healthz
    # numbers here must come from the fleet pages alone
    srv = AggregatorServer(memkv, "job", host="127.0.0.1",
                           cache_s=0.0, include_self=False).start()
    try:
        page = urllib.request.urlopen(
            f"http://{srv.endpoint}/metrics", timeout=10).read().decode()
        parse_exposition(page)
        assert 'component="gateway"' in page
        health = json.loads(urllib.request.urlopen(
            f"http://{srv.endpoint}/healthz", timeout=10).read().decode())
        assert health["live_targets"] == 2
        assert health["components"] == {"trainer": 1, "gateway": 1}
        assert health["resizes"] == 1
        assert health["last_resize"]["stage"] == "s1"
        assert health["gateway"]["requests"] == 1.0
        assert health["gateway"]["p99_s"] is not None
    finally:
        srv.stop()
