"""Delta-resize placement diff (memstate/reshard.py) + the resize
handshake records (cluster/resize.py).

The plan is a pure function: these tests pin the properties the live
path leans on — only changed-owner shards move, survivor seats are
stable, input enumeration order is irrelevant, and the move source is
the departed owner's ring replica when it survives.
"""

from edl_tpu.cluster import resize as resize_rec
from edl_tpu.memstate import placement
from edl_tpu.memstate.reshard import reshard_plan, stable_ranking


def shards_for(owners: dict[str, int], nbytes: int = 100) -> dict:
    """{owner: n_shards} -> manifest-shaped shards dict."""
    out = {}
    for owner, n in owners.items():
        for i in range(n):
            out[f"['w']@{owner[-1]}{i}:0"] = {"owner": owner,
                                              "nbytes": nbytes}
    return out


# -- stable_ranking --------------------------------------------------------
def test_stable_ranking_survivors_keep_order_joiners_sorted():
    assert stable_ranking(["b", "a", "c"], ["c", "a", "z", "x"]) == \
        ["a", "c", "x", "z"]


def test_stable_ranking_ignores_new_pod_enumeration_order():
    old = ["p1", "p2", "p3"]
    assert stable_ranking(old, ["p9", "p3", "p1"]) == \
        stable_ranking(old, ["p1", "p3", "p9"]) == ["p1", "p3", "p9"]


# -- reshard_plan ----------------------------------------------------------
def test_grow_by_one_moves_nothing():
    old = ["pod-a", "pod-b"]
    shards = shards_for({"pod-a": 3, "pod-b": 2})
    plan = reshard_plan(old, ["pod-a", "pod-b", "pod-c"], shards)
    assert plan.moves == []
    assert plan.kept_bytes == 500 and plan.moved_bytes == 0
    assert plan.kept_fraction == 1.0
    assert plan.shards_total == 5
    assert plan.ranking == ["pod-a", "pod-b", "pod-c"]


def test_shrink_by_one_moves_only_the_departed_owners_shards():
    old = ["pod-a", "pod-b", "pod-c"]
    shards = shards_for({"pod-a": 2, "pod-b": 2, "pod-c": 3})
    plan = reshard_plan(old, ["pod-a", "pod-b"], shards)
    assert sorted(m.key for m in plan.moves) == \
        sorted(k for k, e in shards.items() if e["owner"] == "pod-c")
    assert all(m.old_owner == "pod-c" for m in plan.moves)
    assert plan.moved_bytes == 300 and plan.kept_bytes == 400
    # the departed rank-2 seat folds onto rank 2 % 2 = 0
    assert all(m.new_owner == "pod-a" for m in plan.moves)


def test_shrink_source_is_the_surviving_ring_replica():
    old = ["pod-a", "pod-b", "pod-c"]
    shards = shards_for({"pod-c": 2})
    plan = reshard_plan(old, ["pod-a", "pod-b"], shards)
    want = placement.replica_for("pod-c", old)
    assert want in {"pod-a", "pod-b"}  # ring replica survived
    assert all(m.src == want for m in plan.moves)


def test_swap_moves_only_the_departed_owner_to_the_joiner_seat():
    old = ["pod-a", "pod-b", "pod-c"]
    new = ["pod-a", "pod-c", "pod-d"]  # b left, d joined
    shards = shards_for({"pod-a": 2, "pod-b": 2, "pod-c": 2})
    plan = reshard_plan(old, new, shards)
    assert all(m.old_owner == "pod-b" for m in plan.moves)
    assert len(plan.moves) == 2
    # survivors keep their shards even though pod-c's RANK changed
    assert sorted(plan.kept) == sorted(
        k for k, e in shards.items() if e["owner"] != "pod-b")
    # pod-b sat at rank 1; the canonical new ranking [a, c, d] seats
    # pod-c there — the seat moves with the rank, not the identity
    assert all(m.new_owner == "pod-c" for m in plan.moves)


def test_plan_stable_under_pod_set_reordering():
    old = ["pod-a", "pod-b", "pod-c"]
    shards = shards_for({"pod-a": 1, "pod-b": 2, "pod-c": 3})
    p1 = reshard_plan(old, ["pod-d", "pod-a", "pod-b"], shards)
    p2 = reshard_plan(old, ["pod-b", "pod-d", "pod-a"], shards)
    assert p1.ranking == p2.ranking == ["pod-a", "pod-b", "pod-d"]
    assert [(m.key, m.src, m.new_owner) for m in p1.moves] == \
        [(m.key, m.src, m.new_owner) for m in p2.moves]
    assert p1.kept == p2.kept


def test_plan_with_no_surviving_copy_marks_src_none():
    # both the owner AND its ring replica departed: the move has no
    # cache source (restore falls back to storage for those shards)
    old = ["pod-a", "pod-b"]
    shards = shards_for({"pod-b": 1})
    replica = placement.replica_for("pod-b", old)
    assert replica == "pod-a"
    plan = reshard_plan(old, ["pod-x"], shards)
    assert [m.src for m in plan.moves] == [None]


def test_empty_shards_is_a_full_keep():
    plan = reshard_plan(["a"], ["a", "b"], {})
    assert plan.kept_fraction == 1.0 and plan.moves == []


# -- handshake records -----------------------------------------------------
def test_resize_records_roundtrip(memkv):
    resize_rec.flag_resize(memkv, "j", "s-old", "grow", "s-new", "pod-a")
    flag = resize_rec.read_resize_flag(memkv, "j", "s-old")
    assert flag["mode"] == "grow" and flag["new_stage"] == "s-new"
    assert resize_rec.read_resize_flag(memkv, "j", "other") is None

    resize_rec.write_go(memkv, "j", "s-old", "s-new", "grow")
    go = resize_rec.read_go(memkv, "j", "s-old")
    assert go["new_stage"] == "s-new" and go["mode"] == "grow"

    resize_rec.publish_world_service(memkv, "j", "s-new",
                                     "10.0.0.1:4242", 3)
    svc = resize_rec.read_world_service(memkv, "j", "s-new")
    assert svc["endpoint"] == "10.0.0.1:4242" and svc["world"] == 3
    assert resize_rec.read_world_service(memkv, "j", "s-old") is None

    resize_rec.write_done(memkv, "j", "s-new", "pod-a",
                          {"mode": "grow", "seconds": 1.5})
    resize_rec.write_done(memkv, "j", "s-new", "pod-b")
    done = resize_rec.load_done(memkv, "j", "s-new")
    assert set(done) == {"pod-a", "pod-b"}
    assert done["pod-a"]["seconds"] == 1.5


def test_collect_shard_map_counts_owner_sets_once(memkv):
    """The shard map feeding the plan counts only owner-held sets — a
    ring replica is a copy of the same keys, not extra bytes."""
    from edl_tpu.memstate import advert
    from edl_tpu.memstate.reshard import collect_shard_map
    from edl_tpu.memstate.service import StateCacheService
    from edl_tpu.rpc.server import RpcServer

    servers = []
    regs = []
    try:
        for pod in ("pod-a", "pod-b"):
            svc = StateCacheService(memkv, "j", pod)
            srv = RpcServer("127.0.0.1", 0)
            srv.register_instance(svc)
            srv.start()
            servers.append((pod, svc, srv))
            regs.append(advert.advertise(memkv, "j", pod,
                                         f"127.0.0.1:{srv.port}", ttl=30))
        # pod-a owns a 2-shard set at step 7; pod-b holds a replica of
        # it plus its own 1-shard set
        import zlib
        for pod, svc, _srv in servers:
            owners = {"pod-a": [("k1", b"abcd"), ("k2", b"efgh")]}
            if pod == "pod-b":
                owners["pod-b"] = [("k3", b"ij")]
            for owner, blobs in owners.items():
                for key, data in blobs:
                    svc.cache_put_chunk(owner, 7, key, 0, data, True)
                svc.cache_commit(owner, 7, {
                    key: {"crc": zlib.crc32(data), "nbytes": len(data),
                          "dtype": "uint8", "shape": [len(data)],
                          "index": [[0, len(data)]],
                          "gshape": [len(data)], "leaf": key}
                    for key, data in blobs})
        advert.write_committed_step(memkv, "j", 7)
        shard_map = collect_shard_map(memkv, "j")
        assert set(shard_map) == {"k1", "k2", "k3"}
        assert shard_map["k1"]["owner"] == "pod-a"
        assert shard_map["k3"] == {"owner": "pod-b", "nbytes": 2}
    finally:
        for r in regs:
            r.stop()
        for _pod, _svc, srv in servers:
            srv.stop()
