"""LM generation service: TeacherServer hosting generate() + CLI restore."""

import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples", "lm"))

from serve_lm import build_predict_fn, request  # noqa: E402

from edl_tpu.distill.teacher import TeacherServer  # noqa: E402
from edl_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, TransformerLM,
)

CFG = TransformerConfig(vocab_size=53, num_layers=1, embed_dim=32,
                        num_heads=2, mlp_dim=64, max_len=64,
                        dtype=jnp.float32, attention_impl="dense",
                        remat=False)


def _params():
    return TransformerLM(CFG).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]


def test_serve_generate_roundtrip():
    params = _params()
    server = TeacherServer(build_predict_fn(CFG, params, max_new_tokens=6,
                                            temperature=0.0, top_k=0))
    try:
        prompts = np.asarray([[3, 1, 4], [1, 5, 9]], np.int32)
        toks = request(server.endpoint, prompts)
        assert toks.shape == (2, 6)
        assert toks.dtype == np.int32
        assert toks.min() >= 0 and toks.max() < CFG.vocab_size
        # greedy decode is deterministic across requests
        np.testing.assert_array_equal(request(server.endpoint, prompts), toks)
        assert server.stats()["rows"] == 4
    finally:
        server.stop()


def test_serve_sampling_varies_between_requests():
    params = _params()
    server = TeacherServer(build_predict_fn(CFG, params, max_new_tokens=8,
                                            temperature=1.2, top_k=0))
    try:
        prompts = np.asarray([[7, 7]], np.int32)
        a = request(server.endpoint, prompts)
        b = request(server.endpoint, prompts)
        # per-request rng fold: identical prompts, different samples
        assert (a != b).any()
    finally:
        server.stop()


def test_continuous_server_roundtrip():
    """--continuous wire: concurrent TeacherClient requests share the
    engine's decode batch; greedy output matches the batch server."""
    from serve_lm import _ContinuousServer

    from edl_tpu.serving import ContinuousBatcher

    params = _params()
    engine = ContinuousBatcher(CFG, params, slots=2, temperature=0.0,
                               prefill_buckets=(8, 16), steps_per_sync=4)
    server = _ContinuousServer(engine, max_new_tokens=6)
    try:
        prompts = np.asarray([[3, 1, 4], [1, 5, 9]], np.int32)
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(3) as pool:   # concurrent clients
            results = list(pool.map(
                lambda _: request(server.endpoint, prompts), range(3)))
        from edl_tpu.models.generate import generate
        want = np.asarray(generate(CFG, params, jnp.asarray(prompts), 6,
                                   temperature=0.0))
        for toks in results:
            np.testing.assert_array_equal(toks, want)
        stats = server._engine.stats()
        assert stats["requests_done"] == 6
        assert stats["tokens_emitted"] == 36
    finally:
        server.stop()


def _save_ckpt(tmp_path, params):
    import optax

    from edl_tpu.train.checkpoint import CheckpointManager
    from edl_tpu.train.state import TrainState

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, TrainState.create(params, optax.adamw(1e-3)))
    ckpt.wait()
    ckpt.close()


def _boot_cli(tmp_path, extra_args=(), n_devices: int = 0):
    """Boot the serve_lm CLI on the tiny CFG checkpoint; returns
    (proc, endpoint).  ``n_devices`` > 0 forces a virtual CPU mesh."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    if n_devices:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "examples", "lm", "serve_lm.py"),
         "--checkpoint_dir", str(tmp_path / "ckpt"), "--vocab", "53",
         "--layers", "1", "--embed", "32", "--heads", "2", "--mlp", "64",
         "--max_len", "64", "--max_new_tokens", "4", "--temperature", "0",
         "--port", "0", *extra_args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    import selectors
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    endpoint = None
    deadline = time.time() + 180
    while time.time() < deadline:
        # select-gated readline: a wedged server fails at the
        # deadline instead of blocking the test forever
        if not sel.select(timeout=1.0):
            if proc.poll() is not None:
                raise AssertionError("serve_lm died silently")
            continue
        line = proc.stdout.readline()
        if "[serve_lm] serving on" in line:
            endpoint = line.split("serving on")[1].split()[0]
            break
        if not line and proc.poll() is not None:
            raise AssertionError("serve_lm died before announcing")
    assert endpoint, "server never announced its endpoint"
    return proc, endpoint


def _stop_cli(proc):
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()


@pytest.mark.slow
def test_serve_lm_cli_restores_checkpoint(tmp_path):
    """Save a TrainState, boot the CLI against it, query, SIGTERM."""
    params = _params()
    _save_ckpt(tmp_path, params)
    proc, endpoint = _boot_cli(tmp_path)
    try:
        toks = request(endpoint, np.asarray([[2, 4, 6]], np.int32))
        assert toks.shape == (1, 4)

        # the served params ARE the checkpoint's: greedy output must match
        # in-process generation from the same weights
        from edl_tpu.models.generate import generate
        want = generate(CFG, params, jnp.asarray([[2, 4, 6]], jnp.int32), 4,
                        temperature=0)
        np.testing.assert_array_equal(toks, np.asarray(want))
    finally:
        _stop_cli(proc)


@pytest.mark.slow
def test_serve_lm_cli_tp_continuous(tmp_path):
    """serve_lm --tp 2 --continuous 2 on a virtual 8-device CPU mesh:
    tensor-parallel continuous batching through the full CLI + RPC
    stack, greedy output equal to in-process replicated generation."""
    params = _params()
    _save_ckpt(tmp_path, params)
    proc, endpoint = _boot_cli(tmp_path, ("--tp", "2", "--continuous", "2"),
                               n_devices=8)
    try:
        toks = request(endpoint, np.asarray([[2, 4, 6]], np.int32),
                       timeout=300.0)
        from edl_tpu.models.generate import generate
        want = generate(CFG, params, jnp.asarray([[2, 4, 6]], jnp.int32), 4,
                        temperature=0)
        np.testing.assert_array_equal(toks, np.asarray(want))
    finally:
        _stop_cli(proc)
