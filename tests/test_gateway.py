"""Elastic serving gateway (edl_tpu/gateway) + ReplicaServer
(edl_tpu/serving/replica.py).

Failure paths use the REAL ReplicaServer wire + advert machinery around
a fake engine with controllable latency (so a hedge race or a lease
expiry is deterministic, not a scheduling accident); the zero-lost
kill-under-load test runs real ContinuousBatcher engines and asserts
greedy parity after failover.  The SIGKILL-a-process variant lives in
scripts/gateway_smoke.py / tests/test_serving_failover_e2e.py.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edl_tpu.gateway import Gateway, GatewayConfig, GatewayServer, fleet
from edl_tpu.gateway.gateway import (
    _HEDGE_WINS, _HEDGES, _RETRIES, _TokenBucket,
)
from edl_tpu.serving.replica import ReplicaServer, publish_engine_stats
from edl_tpu.utils.exceptions import EdlOverloadedError, EdlUnavailableError


class _FakeEngine:
    """ContinuousBatcher stand-in: resolves ``np.arange(max_new) +
    prompt[0]`` after ``delay`` seconds.  Only the surface ReplicaServer
    touches (submit/stats/drain/stop) is implemented."""

    def __init__(self, delay: float = 0.0, slots: int = 4,
                 free_slots: int | None = None, queue_depth: int = 0):
        self.delay = delay
        self.slots = slots
        self._free = slots if free_slots is None else free_slots
        self._queue_depth = queue_depth
        self.served: list[list[int]] = []
        self._pending: list[Future] = []
        self._lock = threading.Lock()
        self._stopped = False

    def submit(self, ids, max_new: int, session: str | None = None) -> Future:
        del session   # fakes have no KV chains to pin
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine stopping")
            fut: Future = Future()
            self._pending.append(fut)
        self.served.append([int(x) for x in ids])

        def run():
            time.sleep(self.delay)
            if not fut.done():
                fut.set_result(np.arange(max_new, dtype=np.int32)
                               + int(ids[0]))

        threading.Thread(target=run, daemon=True).start()
        return fut

    def stats(self) -> dict:
        return {"slots": self.slots,
                "active_slots": self.slots - self._free,
                "queue_depth": self._queue_depth, "prefill_stall_s": 0.0,
                "tokens_per_s": 0.0, "max_prompt_len": 63,
                "draining": False}

    def kill(self) -> None:
        """Hard death: every pending future fails the way a stopped
        engine fails them."""
        with self._lock:
            self._stopped = True
            pending, self._pending = self._pending, []
        for fut in pending:
            if not fut.done():
                fut.set_exception(
                    RuntimeError("engine stopped mid-generation"))

    def drain(self, timeout=None) -> bool:
        deadline = time.monotonic() + (timeout or 60.0)
        while any(not f.done() for f in self._pending):
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        self._stopped = True
        return True

    def stop(self) -> None:
        self.kill()


def _fake_replica(store, rid, *, delay=0.0, free_slots=None, queue_depth=0,
                  ttl=5.0, advert_period=0.2):
    eng = _FakeEngine(delay=delay, free_slots=free_slots,
                      queue_depth=queue_depth)
    srv = ReplicaServer(store, "job", eng, replica_id=rid, host="127.0.0.1",
                        ttl=ttl, advert_period=advert_period)
    return eng, srv


def _gateway(store, **kw):
    kw.setdefault("max_inflight", 8)
    kw.setdefault("max_queue", 8)
    kw.setdefault("request_timeout_s", 60.0)
    kw.setdefault("wait_slice_s", 0.05)
    kw.setdefault("poll_period_s", 0.05)
    kw.setdefault("quarantine_s", 30.0)
    return Gateway(store, "job", GatewayConfig(**kw))


def _expected(prompt, max_new):
    return np.arange(max_new, dtype=np.int32) + int(prompt[0])


# -- fleet ------------------------------------------------------------------
def test_fleet_advert_roundtrip_and_ttl_expiry(memkv):
    reg = fleet.advertise(memkv, "job", "r0",
                          {"endpoint": "1.2.3.4:5", "free_slots": 3},
                          ttl=0.4)
    try:
        got = fleet.list_replicas(memkv, "job")
        assert got["r0"]["endpoint"] == "1.2.3.4:5"
        reg.stop_heartbeat_only()
        deadline = time.monotonic() + 10
        while "r0" in fleet.list_replicas(memkv, "job"):
            assert time.monotonic() < deadline, "advert never expired"
            time.sleep(0.05)
    finally:
        reg.stop()


def test_fleet_view_tracks_membership(memkv):
    view = fleet.FleetView(memkv, "job", period=0.05)
    regs = [fleet.advertise(memkv, "job", f"r{i}",
                            {"endpoint": f"h:{i}"}, ttl=5) for i in range(3)]
    try:
        assert view.wait_for(3, timeout=10)
        assert view.ring.get_node("sess") in {"r0", "r1", "r2"}
        regs[1].stop()
        deadline = time.monotonic() + 10
        while "r1" in view.replicas():
            assert time.monotonic() < deadline
            time.sleep(0.05)
        assert "r1" not in view.ring.nodes
    finally:
        view.stop()
        for r in regs:
            r.stop()


# -- admission --------------------------------------------------------------
def test_token_bucket_grants_then_backpressures():
    tb = _TokenBucket(rate=10.0, burst=2)
    assert tb.take() == 0.0
    assert tb.take() == 0.0
    ra = tb.take()
    assert 0.0 < ra <= 0.11
    time.sleep(ra + 0.01)
    assert tb.take() == 0.0


def test_admission_rejects_when_queue_full(memkv):
    eng, srv = _fake_replica(memkv, "r0", delay=0.5)
    gw = _gateway(memkv, max_inflight=1, max_queue=0)
    try:
        assert gw.wait_for_replicas(1, 10)
        fut = gw.submit([7], 4)
        with pytest.raises(EdlOverloadedError) as ei:
            gw.submit([8], 4)
        assert ei.value.retry_after > 0
        np.testing.assert_array_equal(fut.result(timeout=30),
                                      _expected([7], 4))
        # capacity freed: admitted again
        np.testing.assert_array_equal(
            gw.submit([9], 4).result(timeout=30), _expected([9], 4))
    finally:
        gw.close()
        srv.close()


def test_admission_rate_limit_rejects_with_retry_after(memkv):
    eng, srv = _fake_replica(memkv, "r0")
    gw = _gateway(memkv, rate=1.0, burst=1.0)
    try:
        assert gw.wait_for_replicas(1, 10)
        gw.submit([3], 2).result(timeout=30)
        with pytest.raises(EdlOverloadedError) as ei:
            gw.submit([4], 2)
        assert 0.0 < ei.value.retry_after <= 1.1
    finally:
        gw.close()
        srv.close()


def test_no_replicas_request_fails_at_deadline(memkv):
    gw = _gateway(memkv, request_timeout_s=0.4)
    try:
        fut = gw.submit([1], 2)     # admitted: fleet gaps don't reject
        with pytest.raises(TimeoutError):
            fut.result(timeout=30)
    finally:
        gw.close()


# -- routing ----------------------------------------------------------------
def test_least_loaded_routing_prefers_free_replica(memkv):
    eng_a, srv_a = _fake_replica(memkv, "ra", free_slots=0, queue_depth=6)
    eng_b, srv_b = _fake_replica(memkv, "rb", free_slots=4)
    gw = _gateway(memkv)
    try:
        assert gw.wait_for_replicas(2, 10)
        picked = gw._pick(None, set())
        assert picked is not None and picked[0] == "rb"
        for i in range(4):
            gw.submit([10 + i], 3).result(timeout=30)
        assert len(eng_b.served) == 4 and not eng_a.served
    finally:
        gw.close()
        srv_a.close()
        srv_b.close()


def test_routing_weighs_kv_warmth_among_comparable_replicas(memkv):
    """ISSUE 15 satellite: with identical load, _pick prefers the
    replica advertising a warmer paged-KV cache (higher prefix hit
    rate, then more free blocks) — never overriding the load score."""
    base = {"endpoint": "127.0.0.1:1", "free_slots": 4, "queue_depth": 0}
    fleet.advertise(memkv, "job", "cold", dict(base, kv_block=8,
                                               kv_prefix_hit_rate=0.1,
                                               kv_blocks_free=10), ttl=30)
    fleet.advertise(memkv, "job", "warm", dict(base, kv_block=8,
                                               kv_prefix_hit_rate=0.9,
                                               kv_blocks_free=2), ttl=30)
    gw = _gateway(memkv)
    try:
        gw._fleet.refresh()
        assert gw._pick(None, set())[0] == "warm"
        # equal hit rates: free blocks break the tie
        fleet.advertise(memkv, "job", "roomy", dict(base, kv_block=8,
                                                    kv_prefix_hit_rate=0.9,
                                                    kv_blocks_free=64),
                        ttl=30)
        gw._fleet.refresh()
        assert gw._pick(None, set())[0] == "roomy"
        # load still dominates: a genuinely less-loaded cold replica wins
        fleet.advertise(memkv, "job", "idle", dict(base, free_slots=8),
                        ttl=30)
        gw._fleet.refresh()
        assert gw._pick(None, set())[0] == "idle"
        # replicas with no kv fields at all keep working (pre-paged)
        assert gw._pick(None, {"idle", "warm", "roomy"})[0] == "cold"
    finally:
        gw.close()


def test_session_affinity_sticks_to_ring_owner(memkv):
    engines = {}
    servers = []
    for rid in ("ra", "rb", "rc"):
        eng, srv = _fake_replica(memkv, rid)
        engines[rid] = eng
        servers.append(srv)
    gw = _gateway(memkv)
    try:
        assert gw.wait_for_replicas(3, 10)
        owner = gw._fleet.ring.get_node("user-42")
        for i in range(5):
            gw.submit([20 + i], 2, session="user-42").result(timeout=30)
        assert len(engines[owner].served) == 5
        assert sum(len(e.served) for e in engines.values()) == 5
    finally:
        gw.close()
        for s in servers:
            s.close()


def test_draining_replica_excluded_from_routing(memkv):
    eng_a, srv_a = _fake_replica(memkv, "ra", free_slots=4)
    eng_b, srv_b = _fake_replica(memkv, "rb", free_slots=1)
    gw = _gateway(memkv)
    try:
        assert gw.wait_for_replicas(2, 10)
        srv_a.serve_drain()
        deadline = time.monotonic() + 10
        while not fleet.list_replicas(memkv, "job").get(
                "ra", {"draining": True})["draining"]:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        gw._fleet.refresh()
        gw.submit([5], 2).result(timeout=30)
        assert len(eng_b.served) == 1 and not eng_a.served
    finally:
        gw.close()
        srv_a.close()
        srv_b.close()


# -- failure paths ----------------------------------------------------------
def test_failover_replica_death_mid_request(memkv):
    """A replica dying with the request in flight: the gateway replays
    it on the survivor and the caller never notices (the acceptance
    contract — accepted work survives a kill)."""
    eng_a, srv_a = _fake_replica(memkv, "ra", delay=30.0, free_slots=4)
    eng_b, srv_b = _fake_replica(memkv, "rb", delay=0.05, free_slots=1)
    gw = _gateway(memkv)
    try:
        assert gw.wait_for_replicas(2, 10)
        retries0 = _RETRIES.value
        fut = gw.submit([7], 5)      # lands on ra (freest), stuck 30s
        deadline = time.monotonic() + 10
        while not eng_a.served:
            assert time.monotonic() < deadline, "request never reached ra"
            time.sleep(0.01)
        eng_a.kill()                  # in-flight future fails
        np.testing.assert_array_equal(fut.result(timeout=30),
                                      _expected([7], 5))
        assert eng_b.served == [[7]]
        assert _RETRIES.value == retries0 + 1
        assert "ra" in gw.stats()["quarantined"]
    finally:
        gw.close()
        srv_a.close()
        srv_b.close()


def test_lease_expiry_mid_assignment_completes_then_reroutes(memkv):
    """An advert expiring under a live replica must not kill its
    in-flight request (the replica is alive; only new routing skips
    it)."""
    eng_a, srv_a = _fake_replica(memkv, "ra", delay=1.0, free_slots=4,
                                 ttl=0.5, advert_period=10.0)
    eng_b, srv_b = _fake_replica(memkv, "rb", delay=0.0, free_slots=1)
    gw = _gateway(memkv)
    try:
        assert gw.wait_for_replicas(2, 10)
        fut = gw.submit([11], 3)     # ra wins on free slots
        deadline = time.monotonic() + 10
        while not eng_a.served:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        srv_a._register.stop_heartbeat_only()   # lease expires mid-flight
        while "ra" in gw._fleet.replicas():
            assert time.monotonic() < deadline, "advert never expired"
            time.sleep(0.05)
        np.testing.assert_array_equal(fut.result(timeout=30),
                                      _expected([11], 3))
        gw.submit([12], 3).result(timeout=30)   # new work: survivor only
        assert eng_b.served == [[12]]
    finally:
        gw.close()
        srv_a.close()
        srv_b.close()


def test_hedge_fires_and_loser_is_released(memkv):
    """A request stuck past hedge_after_s is re-issued on a second
    replica; the fast leg wins, the slow leg's buffer is released and
    its tracking cleared."""
    eng_a, srv_a = _fake_replica(memkv, "ra", delay=5.0, free_slots=4)
    eng_b, srv_b = _fake_replica(memkv, "rb", delay=0.05, free_slots=1)
    gw = _gateway(memkv, hedge_after_s=0.3)
    try:
        assert gw.wait_for_replicas(2, 10)
        hedges0, wins0 = _HEDGES.value, _HEDGE_WINS.value
        t0 = time.monotonic()
        out = gw.submit([9], 4).result(timeout=30)
        dt = time.monotonic() - t0
        np.testing.assert_array_equal(out, _expected([9], 4))
        assert eng_a.served == [[9]] and eng_b.served == [[9]]
        assert _HEDGES.value == hedges0 + 1
        assert _HEDGE_WINS.value == wins0 + 1
        assert dt < 4.0, f"hedge did not rescue the tail: {dt:.2f}s"
        # loser cancelled: ra's tracking is cleared by serve_release
        deadline = time.monotonic() + 10
        while srv_a.serve_stats()["tracked_requests"]:
            assert time.monotonic() < deadline, "loser never released"
            time.sleep(0.05)
    finally:
        gw.close()
        srv_a.close()
        srv_b.close()


def test_zero_lost_when_replica_killed_under_load(memkv):
    """2 real engines, sustained load, one replica hard-killed: every
    accepted request still completes, greedy-parity-correct (the fast
    in-process version of the SIGKILL smoke)."""
    from edl_tpu.models.generate import generate
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.serving import ContinuousBatcher

    cfg = TransformerConfig(vocab_size=53, num_layers=1, embed_dim=32,
                            num_heads=2, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    servers = []
    for rid in ("kill-me", "survivor"):
        eng = ContinuousBatcher(cfg, params, slots=2, temperature=0.0,
                                prefill_buckets=(8, 16), steps_per_sync=4)
        servers.append(ReplicaServer(memkv, "job", eng, replica_id=rid,
                                     host="127.0.0.1", ttl=5,
                                     advert_period=0.2))
    gw = _gateway(memkv, max_inflight=8, max_queue=16,
                  request_timeout_s=120.0)
    try:
        assert gw.wait_for_replicas(2, 10)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 53, (n,)).astype(np.int32)
                   for n in (3, 7, 5, 9, 4, 6, 8, 3, 5, 7, 4, 6)]
        futs = [gw.submit(p, 10) for p in prompts]
        time.sleep(0.3)               # let some land on each replica
        victim = servers[0]
        victim._rpc.stop()            # wire dies
        victim._engine.stop()         # in-flight futures fail
        victim._register.stop()       # advert gone
        outs = [f.result(timeout=120) for f in futs]
        for p, o in zip(prompts, outs):
            want = np.asarray(generate(cfg, params, jnp.asarray(p[None]),
                                       10, temperature=0.0))[0]
            np.testing.assert_array_equal(o, want)
    finally:
        gw.close()
        for s in servers[1:]:
            s.close()


# -- replica server ---------------------------------------------------------
def test_replica_drain_finishes_inflight_then_refuses(memkv):
    eng, srv = _fake_replica(memkv, "r0", delay=0.3)
    fut = eng.submit([1], 2)          # simulate in-flight work
    assert "r0" in fleet.list_replicas(memkv, "job")
    assert srv.drain(timeout=30)
    fut.result(timeout=1)             # in-flight completed, not failed
    with pytest.raises(EdlUnavailableError):
        srv.serve_submit(request_id="x", prompt=[1], max_new=2)
    assert "r0" not in fleet.list_replicas(memkv, "job")
    srv.close()


def test_replica_wire_chunked_fetch_roundtrip(memkv):
    from edl_tpu.rpc import chunks
    from edl_tpu.rpc.client import RpcClient

    eng, srv = _fake_replica(memkv, "r0")
    try:
        with RpcClient(srv.endpoint) as client:
            client.call("serve_submit", request_id="q1", prompt=[40],
                        max_new=6)
            # idempotent re-submit (gateway transport retry)
            client.call("serve_submit", request_id="q1", prompt=[40],
                        max_new=6)
            deadline = time.monotonic() + 10
            while True:
                r = client.call("serve_wait", request_id="q1", timeout=0.1)
                if r["done"]:
                    break
                assert time.monotonic() < deadline
            import functools
            data = chunks.fetch_bytes(
                functools.partial(client.call, "serve_fetch",
                                  request_id="q1"),
                r["nbytes"], chunk_bytes=8)   # force multiple chunks
            np.testing.assert_array_equal(np.frombuffer(data, np.int32),
                                          _expected([40], 6))
            client.call("serve_release", request_id="q1")
            assert srv.serve_stats()["tracked_requests"] == 0
        assert eng.served == [[40]]
    finally:
        srv.close()


def test_publish_engine_stats_sets_gauges():
    from edl_tpu.obs.metrics import REGISTRY

    publish_engine_stats({"slots": 8, "active_slots": 3, "queue_depth": 5,
                          "prefill_stall_s": 1.25, "tokens_per_s": 321.0})
    assert REGISTRY.get("edl_serving_free_slots").value == 5.0
    assert REGISTRY.get("edl_serving_queue_depth").value == 5.0
    assert REGISTRY.get("edl_serving_prefill_stall_seconds").value == 1.25
    assert REGISTRY.get("edl_serving_tokens_per_s").value == 321.0
    assert REGISTRY.get("edl_serving_active_slots").value == 3.0


# -- session KV migration on drain ------------------------------------------
def _paged_replica(memkv, rid, cfg, params, *, kv_block=4):
    from edl_tpu.serving import ContinuousBatcher

    eng = ContinuousBatcher(cfg, params, slots=2, temperature=0.0,
                            prefill_buckets=(8, 16), steps_per_sync=4,
                            kv_block=kv_block, kv_pool_blocks=64)
    return ReplicaServer(memkv, "job", eng, replica_id=rid, host="127.0.0.1",
                         ttl=5, advert_period=0.2)


def _session_owned_by(gw, rid):
    return next(s for s in (f"sess-{i}" for i in range(1000))
                if gw._fleet.ring.get_node(s) == rid)


def _tiny_lm():
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=53, num_layers=1, embed_dim=32,
                            num_heads=2, mlp_dim=64, max_len=64,
                            remat=False, dtype=jnp.float32)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    return cfg, params


def test_session_repin_lands_on_migration_target(memkv):
    """Drain-with-migration end to end: the draining replica pushes the
    session's KV chain to the survivor, the survivor publishes the pin,
    the gateway routes the next turn to the PIN (not the ring owner),
    and that turn resumes from the migrated chain instead of
    re-prefilling — greedy parity throughout."""
    from edl_tpu.models.generate import generate

    cfg, params = _tiny_lm()
    origin = _paged_replica(memkv, "origin", cfg, params)
    target = _paged_replica(memkv, "target", cfg, params)
    gw = _gateway(memkv, request_timeout_s=120.0)
    try:
        assert gw.wait_for_replicas(2, 10)
        sess = _session_owned_by(gw, "origin")
        p1 = np.asarray([7, 11, 13, 5, 9, 2], np.int32)
        out1 = gw.generate(p1, 8, session=sess, timeout=120)
        want1 = np.asarray(generate(cfg, params, jnp.asarray(p1[None]), 8,
                                    temperature=0.0))[0]
        np.testing.assert_array_equal(out1, want1)
        assert origin._engine.stats()["kv_sessions"] == 1

        assert origin.drain(timeout=30)
        # the pin record now maps the session to the adopter
        assert fleet.list_session_pins(memkv, "job") == {sess: "target"}
        gw._fleet.refresh()
        assert gw._fleet.session_pin(sess) == "target"

        p2 = np.concatenate([p1, out1,
                             np.asarray([3, 1], np.int32)])
        out2 = gw.generate(p2, 6, session=sess, timeout=120)
        want2 = np.asarray(generate(cfg, params, jnp.asarray(p2[None]), 6,
                                    temperature=0.0))[0]
        np.testing.assert_array_equal(out2, want2)
        stats = target._engine.stats()
        # the turn resumed warm: the migrated chain covered the prefix
        assert stats["kv_prefix_hits"] >= 1, stats
        assert stats["kv_prefill_tokens_skipped"] > 0, stats
    finally:
        gw.close()
        origin.close()
        target.close()


def test_migration_refused_falls_back_to_cold_prefill(memkv):
    """A target that cannot adopt (no paged cache — the stand-in for a
    peer that died mid-export) refuses the push; the drain still
    completes, no pin is published, and the session's next turn simply
    cold-prefills on the survivor — no lost accepted request."""
    from edl_tpu.models.generate import generate

    cfg, params = _tiny_lm()
    origin = _paged_replica(memkv, "origin", cfg, params)
    target = _paged_replica(memkv, "target", cfg, params, kv_block=0)
    gw = _gateway(memkv, request_timeout_s=120.0)
    try:
        assert gw.wait_for_replicas(2, 10)
        sess = _session_owned_by(gw, "origin")
        p1 = np.asarray([4, 8, 15, 16, 23, 42], np.int32)
        out1 = gw.generate(p1, 8, session=sess, timeout=120)
        assert origin.drain(timeout=30)       # refusal must not wedge it
        assert fleet.list_session_pins(memkv, "job") == {}
        gw._fleet.refresh()
        p2 = np.concatenate([p1, out1, np.asarray([6], np.int32)])
        out2 = gw.generate(p2, 6, session=sess, timeout=120)
        want2 = np.asarray(generate(cfg, params, jnp.asarray(p2[None]), 6,
                                    temperature=0.0))[0]
        np.testing.assert_array_equal(out2, want2)
    finally:
        gw.close()
        origin.close()
        target.close()


def test_gateway_server_wire_roundtrip(memkv):
    from edl_tpu.rpc.client import RpcClient

    eng, srv = _fake_replica(memkv, "r0")
    gws = GatewayServer(memkv, "job", GatewayConfig(
        max_inflight=2, max_queue=0, wait_slice_s=0.05,
        poll_period_s=0.05), host="127.0.0.1")
    try:
        assert gws.gateway.wait_for_replicas(1, 10)
        with RpcClient(gws.endpoint) as client:
            r = client.call("gate_generate", prompt=[30], max_new=4)
            assert r["tokens"] == [int(x) for x in _expected([30], 4)]
            stats = client.call("gate_stats")
            assert "r0" in stats["replicas"]
    finally:
        gws.stop()
        srv.close()
