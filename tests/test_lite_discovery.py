"""Lite (redis-variant) discovery: the zero-framework JSON wire over a
select() loop (reference python/edl/distill/redis/*), sharing the RPC
discovery's greedy rebalance — proof the discovery plane is pluggable.
"""

import time

import numpy as np
import pytest

from edl_tpu.coord.register import Register
from edl_tpu.distill.balance import server_key
from edl_tpu.distill.lite_discovery import LiteBalanceServer, LiteDiscoveryClient


def register_teacher(memkv, service, endpoint, ttl=1.0):
    return Register(memkv, server_key(service, endpoint), endpoint.encode(),
                    ttl=ttl)


def wait_for(fn, timeout=10.0, period=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    raise AssertionError("condition never became true")


def test_register_heartbeat_rebalance(memkv):
    regs = [register_teacher(memkv, "svc", f"10.0.0.{i}:90") for i in (1, 2)]
    server = LiteBalanceServer(memkv, host="127.0.0.1", poll_period=0.2)
    clients = []
    try:
        clients = [LiteDiscoveryClient(server.endpoint, "svc",
                                       require_num=1, period=0.1).start()
                   for _ in range(2)]
        # both students get a teacher, balanced across the two
        wait_for(lambda: all(c.servers() for c in clients))
        assigned = [c.servers() for c in clients]
        assert all(len(a) == 1 for a in assigned), assigned
        assert {a[0] for a in assigned} == {"10.0.0.1:90", "10.0.0.2:90"}

        # teacher death (lease expiry) -> reassignment via heartbeats
        dead = assigned[0][0]
        regs[0 if dead.endswith(".1:90") else 1].stop()
        wait_for(lambda: all(c.servers() == ["10.0.0.2:90" if dead.endswith(".1:90")
                                             else "10.0.0.1:90"]
                             for c in clients))

        # a new teacher joining raises versions and spreads again
        regs.append(register_teacher(memkv, "svc", "10.0.0.3:90"))
        wait_for(lambda: {c.servers()[0] for c in clients if c.servers()}
                 and len({c.servers()[0] for c in clients}) == 2)
    finally:
        for c in clients:
            c.stop()
        server.stop()
        for r in regs:
            r.stop()


def test_distill_reader_over_lite_discovery(memkv):
    """End-to-end: DistillReader streams through the lite wire (custom
    servers_fn) with the nop teacher backend."""
    from edl_tpu.distill import reader as reader_mod

    reg = register_teacher(memkv, "lite-svc", "127.0.0.1:1")
    server = LiteBalanceServer(memkv, host="127.0.0.1", poll_period=0.2)
    client = LiteDiscoveryClient(server.endpoint, "lite-svc",
                                 require_num=2, period=0.1).start()
    old = reader_mod._NOP_PREDICT_TEST
    reader_mod._NOP_PREDICT_TEST = True
    try:
        wait_for(lambda: client.servers())
        dr = reader_mod.DistillReader(ins=["x", "y"], predicts=["p"],
                                      feeds=["x"], teacher_batch_size=4)
        def fn():
            return client.servers()
        fn.close = client.stop  # type: ignore[attr-defined]
        dr.set_servers_fn(fn)

        def gen():
            for i in range(6):
                yield np.full((8, 2), i, np.float32), np.arange(8, dtype=np.int32)
        dr.set_batch_generator(gen)
        got = list(dr)
        assert len(got) == 6  # original batch shapes reassembled
        for x, y, p in got:
            assert len(x) == len(y) == len(p) == 8
    finally:
        reader_mod._NOP_PREDICT_TEST = old
        server.stop()
        reg.stop()
