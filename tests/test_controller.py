"""Elastic controller/autoscaler (edl_tpu/controller) + the
desired-size scaling channel (cluster/scale.py, generator cap,
launcher DESCALED exit).

Reference parity target: the k8s TrainingJob controller
(/root/reference/k8s/edl_controller.yaml, -max_load_desired 0.9) —
policy unit tests against fabricated views, store-level reconcile
tests on MemoryKV, and a live two-launcher scale-in e2e driven by a
real Controller.
"""

import os
import time

import pytest

from edl_tpu.cluster import scale
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.status import Status, save_job_status, save_pod_status
from edl_tpu.cluster.train_status import TrainStatus, save_train_status
from edl_tpu.collective.generator import ClusterGenerator
from edl_tpu.collective.resource import load_resource_pods, register_pod
from edl_tpu.controller import Controller, JobView, compute_desired
from edl_tpu.utils import constants
from tests.test_cluster_model import make_pod
from tests.test_elastic_control import JOB, wait_for


class FakeActuator:
    def __init__(self, ok: bool = True):
        self.calls: list[tuple[str, int]] = []
        self.ok = ok

    def scale(self, job_id: str, replicas: int) -> bool:
        self.calls.append((job_id, replicas))
        return self.ok


# -- policy (pure) -----------------------------------------------------------
def test_policy_fair_share_and_clamps():
    jobs = [JobView("a", 1, 8, 2), JobView("b", 2, 3, 2),
            JobView("c", 1, 2, 1)]
    # capacity 10 @ 0.9 -> budget 9 -> shares 3/3/3, clamped per range;
    # the slot b and c's max-clamps free waterfills to a (the budget is
    # a FILL target — clamped members must not strand capacity a
    # classmate can use)
    out = compute_desired(jobs, capacity=10, max_load_desired=0.9)
    assert out == {"a": 4, "b": 3, "c": 2}
    assert sum(out.values()) == 9


def test_policy_remainder_goes_to_earliest_jobs():
    jobs = [JobView("a", 1, 8, 1), JobView("b", 1, 8, 1),
            JobView("c", 1, 8, 1)]
    out = compute_desired(jobs, capacity=7, max_load_desired=1.0)
    assert out == {"a": 3, "b": 2, "c": 2}         # 7 = 3+2+2


def test_policy_min_nodes_floor_even_over_budget():
    out = compute_desired([JobView("a", 4, 8, 4)], capacity=2,
                          max_load_desired=1.0)
    assert out == {"a": 4}      # the job's own floor wins over the budget


def test_policy_non_scalable_freezes():
    jobs = [JobView("a", 1, 8, 5, scalable=False)]
    out = compute_desired(jobs, capacity=100)
    assert out == {"a": 5}


def test_policy_frozen_jobs_consume_budget():
    # a NEARTHEEND job holding 8 pods leaves only 1 of the 9-pod budget
    # for the flexible job — total desired must respect max_load_desired
    jobs = [JobView("a", 1, 8, 8, scalable=False), JobView("b", 1, 8, 2)]
    out = compute_desired(jobs, capacity=10, max_load_desired=0.9)
    assert out == {"a": 8, "b": 1}
    assert sum(out.values()) <= 9


def test_policy_empty():
    assert compute_desired([], capacity=8) == {}


# -- generator honors the desired record -------------------------------------
@pytest.fixture
def three_pods(memkv):
    pods = [make_pod(f"10.0.0.{i}") for i in range(3)]
    regs = [register_pod(memkv, JOB, p, ttl=0.8) for p in pods]
    from edl_tpu.cluster import paths
    memkv.put(paths.key(JOB, constants.ETCD_POD_RANK, "0"),
              pods[0].pod_id.encode())
    yield pods, regs
    for r in regs:
        r.stop()


def test_generator_scale_in_to_desired(memkv, three_pods):
    pods, regs = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=1,
                           max_nodes=3, period=0.1)
    c1 = gen.reconcile_once()
    assert len(c1.pods) == 3
    # the generator published the job's range for controllers
    assert scale.load_nodes_range(memkv, JOB) == (1, 3)

    scale.save_desired_nodes(memkv, JOB, 2)
    c2 = gen.reconcile_once()
    assert c2.stage != c1.stage
    assert len(c2.pods) == 2
    assert c2.pods[0].pod_id == pods[0].pod_id     # leader survives
    assert c2.pod_ids() == c1.pod_ids()[:2]        # highest rank dropped

    # idempotent at the target
    c3 = gen.reconcile_once()
    assert c3.stage == c2.stage


def test_generator_desired_caps_joiners_and_clamps_to_min(memkv, three_pods):
    pods, regs = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=2,
                           max_nodes=3, period=0.1)
    scale.save_desired_nodes(memkv, JOB, 1)        # below min_nodes
    c1 = gen.reconcile_once()
    assert len(c1.pods) == 2                       # clamped to min_nodes

    # the pod the cap excluded also competes for re-admission; retire
    # its advert so the NEW pod is the only joiner candidate
    excluded = [p for p in pods if p.pod_id not in c1.pod_ids()]
    for p, r in zip(pods, regs):
        if p in excluded:
            r.stop_heartbeat_only()
    assert wait_for(lambda: all(p.pod_id not in load_resource_pods(memkv, JOB)
                                for p in excluded), 5.0)

    pod_new = make_pod("10.0.0.9")
    reg_new = register_pod(memkv, JOB, pod_new, ttl=0.8)
    assert wait_for(lambda: pod_new.pod_id in load_resource_pods(memkv, JOB))
    c2 = gen.reconcile_once()
    assert pod_new.pod_id not in c2.pod_ids()      # desired blocks joiners
    scale.save_desired_nodes(memkv, JOB, 3)
    c3 = gen.reconcile_once()
    assert pod_new.pod_id in c3.pod_ids()          # raised desired admits
    reg_new.stop()


def test_generator_no_scale_in_near_end(memkv, three_pods):
    pods, regs = three_pods
    gen = ClusterGenerator(memkv, JOB, pods[0].pod_id, min_nodes=1,
                           max_nodes=3, period=0.1)
    c1 = gen.reconcile_once()
    save_train_status(memkv, JOB, pods[0].pod_id, TrainStatus.NEARTHEEND)
    scale.save_desired_nodes(memkv, JOB, 1)
    c2 = gen.reconcile_once()
    assert c2.stage == c1.stage and len(c2.pods) == 3


# -- controller reconcile against the store ----------------------------------
def _publish_job(store, job_id, pods, min_n, max_n):
    scale.save_nodes_range(store, job_id, min_n, max_n)
    cluster = Cluster.from_pods(pods)
    # cluster writes are leader-guarded; stamp the record directly
    from edl_tpu.cluster import paths
    store.put(paths.key(job_id, constants.ETCD_CLUSTER, "cluster"),
              cluster.to_json().encode())
    return cluster


def _put_cluster(store, job_id, pods):
    from edl_tpu.cluster import paths
    cluster = Cluster.from_pods(pods)
    store.put(paths.key(job_id, constants.ETCD_CLUSTER, "cluster"),
              cluster.to_json().encode())
    return cluster


def test_controller_reconcile_writes_record_and_actuates(memkv):
    pods = [make_pod(f"10.1.0.{i}") for i in range(2)]
    _publish_job(memkv, "j1", pods, 1, 8)
    act = FakeActuator()
    ctl = Controller(memkv, capacity=10, max_load_desired=0.9,
                     actuator=act, cooldown=0.0)
    assert ctl.discover_jobs() == ["j1"]
    acted = ctl.reconcile_once()
    assert acted == {"j1": 8}                      # budget 9, clamped to max 8
    assert scale.load_desired_nodes(memkv, "j1") == 8
    assert act.calls == [("j1", 8)]

    # converged cluster -> no further action
    _put_cluster(memkv, "j1", [make_pod(f"10.1.1.{i}") for i in range(8)])
    assert ctl.reconcile_once() == {}


def test_controller_cooldown_blocks_flapping(memkv):
    pods = [make_pod("10.2.0.1")]
    _publish_job(memkv, "j2", pods, 1, 8)
    act = FakeActuator()
    ctl = Controller(memkv, capacity=4, max_load_desired=1.0,
                     actuator=act, cooldown=60.0)
    assert ctl.reconcile_once() == {"j2": 4}
    # capacity changes -> new target, but inside the cooldown window
    ctl._capacity = 2
    assert ctl.reconcile_once() == {}
    assert scale.load_desired_nodes(memkv, "j2") == 4


def test_controller_redrives_actuator_while_unconverged(memkv):
    pods = [make_pod("10.3.0.1")]
    _publish_job(memkv, "j3", pods, 1, 4)
    act = FakeActuator()
    ctl = Controller(memkv, capacity=4, max_load_desired=1.0,
                     actuator=act, cooldown=0.0)
    assert ctl.reconcile_once() == {"j3": 4}
    # record in place but replicas haven't appeared: actuator re-driven,
    # no new record stamp
    assert ctl.reconcile_once() == {}
    assert act.calls == [("j3", 4), ("j3", 4)]


def test_controller_skips_near_end_and_reaps_terminal(memkv):
    pods = [make_pod("10.4.0.1"), make_pod("10.4.0.2")]
    _publish_job(memkv, "j4", pods, 1, 8)
    save_train_status(memkv, "j4", pods[0].pod_id, TrainStatus.NEARTHEEND)
    act = FakeActuator()
    ctl = Controller(memkv, capacity=16, max_load_desired=1.0,
                     actuator=act, cooldown=0.0)
    assert ctl.reconcile_once() == {}              # frozen near the end

    save_job_status(memkv, "j4", Status.SUCCEED)
    ctl.reconcile_once()
    assert ("j4", 0) in act.calls                  # terminal job reaped
    n_calls = len(act.calls)
    ctl.reconcile_once()
    assert len(act.calls) == n_calls               # reaped once only


def test_kubectl_actuator_invocation(tmp_path):
    """KubectlActuator shells the documented command and survives a
    failing/missing kubectl without raising."""
    from edl_tpu.controller.actuator import KubectlActuator

    log = tmp_path / "calls.log"
    fake = tmp_path / "kubectl"
    # printf, not echo: echo would eat the leading "-n" namespace flag
    fake.write_text(f"#!/bin/sh\nprintf '%s ' \"$@\" >> {log}\n"
                    f"printf '\\n' >> {log}\nexit 0\n")
    fake.chmod(0o755)
    act = KubectlActuator(namespace="ns1", kubectl=str(fake))
    assert act.scale("rn50", 3) is True
    assert log.read_text().strip() == "-n ns1 scale statefulset/rn50 --replicas=3"

    failing = tmp_path / "kubectl-fail"
    failing.write_text("#!/bin/sh\necho boom >&2\nexit 1\n")
    failing.chmod(0o755)
    assert KubectlActuator(kubectl=str(failing)).scale("j", 1) is False
    assert KubectlActuator(kubectl="/nonexistent/kubectl").scale("j", 1) is False

    # custom workload mapping
    act2 = KubectlActuator(namespace="ns2", kubectl=str(fake),
                           workload_of=lambda j: f"deployment/{j}-workers")
    assert act2.scale("lm", 0) is True
    assert "deployment/lm-workers --replicas=0" in log.read_text()


# -- live scale-in e2e --------------------------------------------------------
@pytest.mark.slow
def test_controller_scales_in_live_job(coord_server, tmp_path):
    """Two launchers running; a real Controller (capacity 1) writes
    desired=1; the generator shrinks the cluster; the descaled launcher
    exits 0 with pod status DESCALED; the survivor SUCCEEDs the job."""
    from edl_tpu.cluster.status import load_job_status, load_pods_status
    from edl_tpu.coord.client import CoordClient
    from tests.test_launch_integration import finish, spawn_launcher

    ep = f"127.0.0.1:{coord_server.port}"
    client = CoordClient(ep)
    tmp = str(tmp_path)
    env = {"EDL_TPU_DEMO_SLEEP": "25", "EDL_TPU_DEMO_SLEEP_SOLO": "4"}
    a = spawn_launcher("j-scale", ep, tmp, "a", "1:2", env)
    b = spawn_launcher("j-scale", ep, tmp, "b", "1:2", env)
    try:
        assert wait_for(
            lambda: (c := Cluster.load_from_store(client, "j-scale"))
            is not None and len(c.pods) == 2, 30.0), "cluster never formed"

        ctl = Controller(client, capacity=1, max_load_desired=1.0,
                         cooldown=0.0, period=0.5).start()
        try:
            assert wait_for(
                lambda: len(Cluster.load_from_store(client,
                                                    "j-scale").pods) == 1,
                30.0), "controller never shrank the cluster"
        finally:
            ctl.stop()

        rets = sorted([finish(a, 90), finish(b, 90)])
        assert rets == [0, 0], f"launcher exit codes {rets}"
        statuses = sorted(load_pods_status(client, "j-scale").values(),
                          key=lambda s: s.value)
        assert Status.DESCALED in statuses
        assert load_job_status(client, "j-scale") == Status.SUCCEED
    finally:
        for proc in (a, b):
            if proc.poll() is None:
                proc.kill()
        client.close()


# -- observed (metrics-driven) controller inputs ------------------------------
def test_policy_remainder_prefers_pending_pods():
    """A job with a registered-but-unplaced replica gets the remainder
    pod first: the hardware is up and joining is free."""
    jobs = [JobView("a", 1, 8, 2), JobView("b", 1, 8, 2, pending_pods=1)]
    out = compute_desired(jobs, capacity=5, max_load_desired=1.0)
    assert out == {"a": 2, "b": 3}
    # without the pending signal, earliest job_id wins as before
    jobs = [JobView("a", 1, 8, 2), JobView("b", 1, 8, 2)]
    assert compute_desired(jobs, 5, 1.0) == {"a": 3, "b": 2}


def test_controller_observes_capacity_from_live_pods(memkv):
    """capacity=0 = observe: the budget tracks the high-water mark of
    live adverts (members + pending) instead of a typed constant."""
    pods = [make_pod(f"10.3.0.{i}") for i in range(2)]
    _publish_job(memkv, "j3", pods, 1, 8)
    for p in pods:
        register_pod(memkv, "j3", p, ttl=5.0)
    # one extra live advert NOT in the cluster: a pending replica
    extra = make_pod("10.3.0.9")
    register_pod(memkv, "j3", extra, ttl=5.0)
    act = FakeActuator()
    # default max_load_desired: observe mode must IGNORE the trim — the
    # mark is demonstrated usage, and 0.9x it would evict healthy pods
    ctl = Controller(memkv, capacity=0, actuator=act, cooldown=0.0)
    view = ctl.job_view("j3")
    assert view.pending_pods == 1
    acted = ctl.reconcile_once()
    # observed capacity = 2 members + 1 pending = 3: admit the pending
    assert acted == {"j3": 3}
    assert ctl._capacity_observed == 3
    # converged at the mark -> no shrink, no flapping
    _put_cluster(memkv, "j3", pods + [extra])
    assert ctl.reconcile_once() == {}
    # the high-water mark survives adverts expiring WITHIN the window
    # (capacity is the infra's recently demonstrated size, not the
    # instantaneous liveness)
    ctl._capacity_samples.append((time.monotonic(), 5))
    assert ctl._effective_capacity([view]) == 5


def test_observed_capacity_highwater_decays(memkv):
    """ADVICE r5: the observed-capacity mark is WINDOWED — infra that
    permanently shrank ages out, so the controller stops proposing
    unschedulable scale-ups forever."""
    ctl = Controller(memkv, capacity=0, actuator=FakeActuator(),
                     cooldown=0.0, observe_window_s=100.0)
    views = [JobView("j", 1, 16, 2, pending_pods=0)]
    t0 = 1000.0
    # a burst demonstrated 8 slots at t0
    ctl._capacity_samples.append((t0, 8))
    assert ctl._effective_capacity(views, now=t0 + 1) == 8
    # still inside the window: the mark holds even though only 2 live
    assert ctl._effective_capacity(views, now=t0 + 99) == 8
    # past the window: the 8-slot sample expired; the mark decays to
    # the current liveness, never below 1
    assert ctl._effective_capacity(views, now=t0 + 101) == 2
    assert ctl._effective_capacity([JobView("j", 1, 16, 0)],
                                   now=t0 + 102) == 2  # 2 is still in-window
    assert ctl._effective_capacity([JobView("j", 1, 16, 0)],
                                   now=t0 + 300) == 1  # floor


# -- multi-job arbitration (ISSUE 15) ----------------------------------------
def test_policy_priority_classes_split_surplus_top_down():
    """Surplus goes to the highest class first; lower classes keep
    their floors — training yields to serving, no job starves."""
    jobs = [JobView("serve", 1, 8, 2, kind="serving", priority=100),
            JobView("train", 1, 8, 5, kind="training", priority=0)]
    out = compute_desired(jobs, capacity=6, max_load_desired=1.0)
    assert out == {"serve": 5, "train": 1}          # serving takes the surplus
    # with a demand cap the serving job takes only what it asked for
    jobs[0].demand = 3
    out = compute_desired(jobs, capacity=6, max_load_desired=1.0)
    assert out == {"serve": 3, "train": 3}          # training reclaims
    # demand decays to min: training reclaims everything above its floor
    jobs[0].demand = 1
    out = compute_desired(jobs, capacity=6, max_load_desired=1.0)
    assert out == {"serve": 1, "train": 5}


def test_policy_gang_all_or_nothing_under_shrinking_capacity():
    gang = JobView("distill", 4, 4, 4, kind="distill", priority=50,
                   gang=True)
    train = JobView("train", 1, 8, 3, kind="training", priority=0)
    out = compute_desired([gang, train], capacity=8, max_load_desired=1.0)
    assert out == {"distill": 4, "train": 4}        # gang placed whole
    # capacity shrinks below the gang: it gets EXACTLY 0, never 1-3 —
    # a partial gang would strand chips it cannot use atomically
    out = compute_desired([gang, train], capacity=3, max_load_desired=1.0)
    assert out == {"distill": 0, "train": 3}
    # a non-gang job of the same shape keeps its min floor instead
    loose = JobView("distill", 4, 4, 4, kind="distill", priority=50)
    out = compute_desired([loose, train], capacity=3, max_load_desired=1.0)
    assert out["distill"] == 4


def test_policy_demand_clamp_does_not_strand_class_capacity():
    """Review pin: a member clamped down by its demand cap must not
    strand budget its classmates (then lower classes) can still use."""
    jobs = [JobView("s1", 1, 8, 1, kind="serving", priority=100, demand=2),
            JobView("s2", 1, 8, 1, kind="serving", priority=100, demand=8),
            JobView("train", 1, 8, 1, kind="training", priority=0)]
    out = compute_desired(jobs, capacity=9, max_load_desired=1.0)
    # the naive even split gave s1 4 (clamped to 2) and stranded 2
    # slots; the waterfill hands them to s2, every slot granted
    assert out == {"s1": 2, "s2": 6, "train": 1}
    assert sum(out.values()) == 9
    # when the whole class caps out, the leftover flows DOWN a class
    jobs[1].demand = 3
    out = compute_desired(jobs, capacity=9, max_load_desired=1.0)
    assert out == {"s1": 2, "s2": 3, "train": 4}


def test_policy_priority_floors_still_granted_to_low_class():
    """A higher class's demand can squeeze training to its floor but
    never below it (the no-starvation rail)."""
    jobs = [JobView("serve", 1, 16, 2, kind="serving", priority=100,
                    demand=16),
            JobView("train", 2, 8, 6, kind="training", priority=0)]
    out = compute_desired(jobs, capacity=10, max_load_desired=1.0)
    assert out["train"] == 2                        # floor, not zero
    assert out["serve"] == 8                        # the rest of the pool


def test_controller_serving_job_view_counts_replica_adverts(memkv):
    """kind=serving jobs are measured by their serving adverts and
    capped by the autoscaler's demand."""
    import json as _json

    from edl_tpu.gateway import fleet
    scale.save_nodes_range(memkv, "svc", 1, 4)
    scale.save_job_spec(memkv, "svc", kind="serving")
    for i in range(2):
        memkv.put(fleet.node_key("svc", f"r{i}"),
                  _json.dumps({"endpoint": f"127.0.0.1:9{i}"}).encode())
    ctl = Controller(memkv, capacity=8, actuator=FakeActuator(),
                     cooldown=0.0)
    view = ctl.job_view("svc")
    assert view.kind == "serving" and view.current_nodes == 2
    assert view.priority == 100                     # kind default
    assert view.demand == 2                         # hold at current
    # a fresh demand record (the dispatcher's scale-out) raises it
    scale.save_demand(memkv, "svc", 3, reason="gateway-p99-slo")
    assert ctl.job_view("svc").demand == 3
    acted = ctl.reconcile_once()
    assert acted["svc"] == 3
    assert scale.load_desired_nodes(memkv, "svc") == 3


def test_controller_graceful_shrink_flags_preempt_then_commits(memkv):
    """preempt_grace_s > 0: a training shrink first preempt-flags the
    retiring (highest-rank) pods with a reason; the desired record
    lands only after they depart — preemption-grace accounting."""
    from edl_tpu.cluster import preempt
    pods = [make_pod(f"10.7.0.{i}") for i in range(3)]
    cluster = _publish_job(memkv, "j7", pods, 1, 8)
    act = FakeActuator()
    ctl = Controller(memkv, capacity=2, max_load_desired=1.0,
                     actuator=act, cooldown=0.0, preempt_grace_s=60.0)
    acted = ctl.reconcile_once()
    # tick 1: flag only — no record yet, trainers get their checkpoint
    assert acted == {}
    assert scale.load_desired_nodes(memkv, "j7") is None
    retiring = cluster.pod_ids()[2:]
    info = preempt.pod_preempt_info(memkv, "j7", cluster.stage, retiring[0])
    assert info is not None and info[1] == "descale"
    surviving = cluster.pod_ids()[:2]
    assert preempt.pod_preempt_info(memkv, "j7", cluster.stage,
                                    surviving[0]) is None
    # tick 2: still draining -> hands off
    assert ctl.reconcile_once() == {}
    assert scale.load_desired_nodes(memkv, "j7") is None
    # the flagged pod departs; the shrink record commits
    _put_cluster(memkv, "j7", pods[:2])
    acted = ctl.reconcile_once()
    assert acted == {"j7": 2}
    assert scale.load_desired_nodes(memkv, "j7") == 2
    assert act.calls == [("j7", 2)]


def test_controller_graceful_shrink_reason_priority_yield(memkv):
    """A shrink forced by a higher class's growth carries reason
    priority-yield, not descale."""
    import json as _json

    from edl_tpu.cluster import preempt
    from edl_tpu.gateway import fleet
    pods = [make_pod(f"10.8.0.{i}") for i in range(3)]
    cluster = _publish_job(memkv, "j8", pods, 1, 8)
    scale.save_nodes_range(memkv, "svc8", 1, 4)
    scale.save_job_spec(memkv, "svc8", kind="serving")
    memkv.put(fleet.node_key("svc8", "r0"),
              _json.dumps({"endpoint": "127.0.0.1:90"}).encode())
    scale.save_demand(memkv, "svc8", 3, reason="gateway-p99-slo")
    ctl = Controller(memkv, capacity=5, max_load_desired=1.0,
                     actuator=FakeActuator(), cooldown=0.0,
                     preempt_grace_s=60.0)
    ctl.reconcile_once()
    # serving wants 3 of 5 slots -> training shrinks 3 -> 2, yielding
    retiring = cluster.pod_ids()[2:]
    info = preempt.pod_preempt_info(memkv, "j8", cluster.stage, retiring[0])
    assert info is not None and info[1] == "priority-yield"


def test_controller_cooldown_scales_with_resize_cost(memkv):
    """A job whose last stop-resume took 12 s gets a 120 s effective
    cooldown (10 x) even with a 30 s base."""
    import json as _json

    from edl_tpu.cluster import paths as _paths
    pods = [make_pod("10.4.0.1")]
    _publish_job(memkv, "j4", pods, 1, 8)
    # fabricate a complete recovery record (launcher + trainer halves)
    stage = "s1"
    memkv.put(_paths.key("j4", constants.ETCD_RECOVERY,
                         f"{stage}/launcher/p1"),
              _json.dumps({"detect": 100.0, "killed": 101.0,
                           "barrier": 104.0, "spawn": 105.0}).encode())
    memkv.put(_paths.key("j4", constants.ETCD_RECOVERY,
                         f"{stage}/trainer/p1"),
              _json.dumps({"restored": 110.0,
                           "first_step": 112.0}).encode())
    ctl = Controller(memkv, capacity=4, cooldown=30.0,
                     cooldown_per_resize_s=10.0)
    view = ctl.job_view("j4")
    assert view.resize_cost_s == 12.0
    assert ctl._effective_cooldown(view) == 120.0
    # an unmeasured job keeps the base cooldown
    assert ctl._effective_cooldown(JobView("x", 1, 2, 1)) == 30.0
