"""Image-scale service distillation e2e (the reference's flagship
workload at toy scale): teacher trained clean -> 2-server TPU teacher
fleet behind discovery -> ResNet_vd student whose labels are >50%
systematically wrong -> distilled student beats the label-only baseline
decisively, with live (non-nop) teacher QPS recorded.

Plus: the student role runs under the real elastic launcher with the
DistillReader streaming through discovery (VERDICT r2 #3).
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from tests.test_launch_integration import FAST, finish

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "distill", "train_image_distill.py")


@pytest.mark.slow
def test_local_distill_beats_noisy_baseline(tmp_path):
    sys.path.insert(0, os.path.dirname(EXAMPLE))
    try:
        from train_image_distill import main
    finally:
        sys.path.pop(0)
    out = str(tmp_path / "summary.json")
    summary = main(["--role", "local",
                    "--data_dir", str(tmp_path / "data"),
                    "--teacher_dir", str(tmp_path / "teacher"),
                    "--out", out])
    assert summary["teacher_top1"] >= 0.9, summary
    # the asymmetric-noise baseline learns the wrong mapping; the
    # teacher's soft labels rescue the student (README.md:83-85 effect)
    assert summary["gain"] >= 0.3, summary
    assert summary["distill_top1"] >= 0.7, summary
    # live QPS from real TeacherServers (not the nop test backend)
    assert summary["teacher_rows"] > 0 and summary["teacher_rows_per_s"] > 0
    assert summary["teacher_forward_passes"] > 0
    assert json.load(open(out))["gain"] == summary["gain"]


@pytest.mark.slow
def test_student_under_elastic_launcher(coord_server, tmp_path):
    """Teacher fleet + discovery in-process; the student runs under a
    real launcher pod and distills through dynamic discovery."""
    sys.path.insert(0, os.path.dirname(EXAMPLE))
    try:
        import train_image_distill as tid
    finally:
        sys.path.pop(0)
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.distill.discovery import DiscoveryServer

    ep = f"127.0.0.1:{coord_server.port}"
    store = CoordClient(ep)
    data_dir = str(tmp_path / "data")
    args = tid.parse_args(["--data_dir", data_dir,
                           "--teacher_dir", str(tmp_path / "teacher")])
    train_files, _val = tid.ensure_data(args)
    tmodel, tvars = tid.train_teacher(args, train_files)

    disc = DiscoveryServer(store, host="127.0.0.1")
    server = tid.serve_teacher(args, store, model=tmodel, variables=tvars,
                               block=False)
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["EDL_TPU_DEMO_MARKER"] = str(tmp_path / "marker")
    log = open(tmp_path / "launcher.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", "img-distill", "--coord_endpoints", ep,
         "--nodes_range", "1:1", "--nproc_per_node", "1",
         "--log_dir", str(tmp_path / "log"), EXAMPLE, "--",
         "--role", "student", "--data_dir", data_dir,
         "--discovery", disc.endpoint, "--student_epochs", "3"],
        env=env, cwd=str(tmp_path), stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001
    try:
        assert finish(proc, 420) == 0, \
            (tmp_path / "launcher.log").read_text(errors="replace")[-3000:]
    finally:
        server.stop()
        disc.stop()
        store.close()
    marker = (tmp_path / "marker").read_text()
    rec = json.loads([l for l in marker.splitlines()
                      if l.startswith("done ")][-1][5:])
    assert rec["val_top1"] >= 0.7, rec
    assert rec["distill_img_s"] > 0, rec
