"""Alert-driven remediation (controller/remediate.py + autoscale.py):
the action handlers, the safety rails (cooldown, circuit breaker,
dry-run), the audit trail, and the serving autoscaler's decisions.

The acceptance pin for ISSUE 15's safety rail lives here: a
deliberately flapping rule trips the breaker after N actions inside
the window, breaker-open surfaces as its own builtin alert, and no
further restarts land until the breaker half-opens.
"""

import json

import pytest

from edl_tpu.cluster import heartbeat, paths, preempt, scale
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.controller.autoscale import ServingAutoscaler
from edl_tpu.controller.remediate import (
    CircuitBreaker, RemediationDispatcher, _BREAKER_G,
)
from edl_tpu.obs.rules import Rule, RuleEngine, builtin_rules
from edl_tpu.obs.tsdb import TSDB
from edl_tpu.utils import constants
from tests.test_cluster_model import make_pod

JOB = "remjob"


def _rule(name="trainer-hang", action="restart", window=60.0):
    return Rule(name, kind="gauge", metric="edl_g", op=">", threshold=0.0,
                window=window, action=action)


def _put_cluster(store, pods, job=JOB):
    cluster = Cluster.from_pods(pods)
    store.put(paths.key(job, constants.ETCD_CLUSTER, "cluster"),
              cluster.to_json().encode())
    return cluster


def _advertise(store, name, endpoint, pod_id, job=JOB):
    store.put(paths.key(job, constants.ETCD_OBS, f"metrics/{name}"),
              json.dumps({"endpoint": endpoint, "component": "trainer",
                          "pod": pod_id}).encode())


def _dispatcher(store, **kw):
    kw.setdefault("enabled", True)
    kw.setdefault("cooldown_s", 0.0)
    return RemediationDispatcher(store, JOB, **kw)


# -- circuit breaker ---------------------------------------------------------

def test_breaker_trips_after_n_then_half_opens_then_closes():
    b = CircuitBreaker(max_actions=3, window_s=10.0, reset_s=30.0)
    t = 100.0
    assert all(b.allow(t + i) for i in range(3))     # N actions pass
    assert b.state == "closed"
    assert not b.allow(t + 3)                        # N+1 inside window: trip
    assert b.state == "open"
    assert not b.allow(t + 10)                       # open: denied
    assert not b.allow(t + 32.9)                     # still inside reset
    assert b.allow(t + 3 + 30.1)                     # half-open: ONE trial
    assert b.state == "half_open"
    # flapping continues: the trial's window hasn't drained -> re-open
    assert not b.allow(t + 3 + 30.2)
    assert b.state == "open"
    # a second half-open trial that stays quiet for a window closes it
    t2 = t + 3 + 30.2 + 31.0
    assert b.allow(t2) and b.state == "half_open"
    assert b.allow(t2 + 11.0)                        # quiet window: closed
    assert b.state == "closed"


def test_breaker_window_prunes_old_actions():
    b = CircuitBreaker(max_actions=2, window_s=5.0, reset_s=60.0)
    assert b.allow(0.0) and b.allow(1.0)
    assert b.allow(7.0)                  # the first two aged out
    assert b.state == "closed"


# -- dispatch rails ----------------------------------------------------------

def test_dispatch_cooldown_skips_and_does_not_feed_breaker(memkv):
    d = _dispatcher(memkv, cooldown_s=60.0, breaker_n=2)
    _put_cluster(memkv, [make_pod("10.9.0.1")])
    rule = _rule()
    assert d.dispatch("restart", rule, "", 1.0, now=100.0) == "ok"
    assert d.dispatch("restart", rule, "", 1.0, now=101.0) == "cooldown"
    assert d.dispatch("restart", rule, "", 1.0, now=102.0) == "cooldown"
    # cooled-down triggers never count as executions for the breaker
    assert d.breakers()["restart"] == "closed"
    ring = d.recent()
    assert [r["outcome"] for r in ring] == ["ok", "cooldown", "cooldown"]


def test_flapping_rule_trips_breaker_and_fires_its_own_alert(memkv):
    """The ISSUE 15 safety-rail pin: N actions in the window trip the
    breaker, the edl_remediation_breaker_open gauge fires the builtin
    remediation-breaker-open alert, and nothing lands until the
    half-open trial."""
    d = _dispatcher(memkv, breaker_n=3, breaker_window_s=60.0,
                    breaker_reset_s=120.0)
    _put_cluster(memkv, [make_pod("10.9.1.1")])
    rule = _rule()
    for i in range(3):
        assert d.dispatch("restart", rule, "", 1.0, now=200.0 + i) == "ok"
    # the flap: 4th firing inside the window is SUPPRESSED
    assert d.dispatch("restart", rule, "", 1.0, now=204.0) == "breaker_open"
    assert d.breakers()["restart"] == "open"
    assert _BREAKER_G.labels(action="restart").value == 1.0
    # no restart flag was re-written after the trip: the flag ts is
    # from the third execution, not the suppressed fourth
    pod = Cluster.load_from_store(memkv, JOB).pods[0].pod_id
    stage = Cluster.load_from_store(memkv, JOB).stage
    flag = heartbeat.read_pod_restart(memkv, JOB, stage, pod)
    assert flag is not None

    # the gauge rides the merged page into the TSDB; the builtin rule
    # turns it into a firing alert
    t = TSDB(retention_s=600.0)
    rules = {r.name: r for r in builtin_rules()}
    breaker_rule = rules["remediation-breaker-open"]
    eng = RuleEngine(t, [breaker_rule])
    t.ingest({("edl_remediation_breaker_open",
               (("action", "restart"),)): 1.0}, 1000.0)
    firing = eng.evaluate(now=1000.5)
    assert [a["alert"] for a in firing] == ["remediation-breaker-open"]
    assert firing[0]["action"] == "restart"

    # still suppressed until the reset elapses; then ONE trial runs
    assert d.dispatch("restart", rule, "", 1.0, now=250.0) == "breaker_open"
    assert d.dispatch("restart", rule, "", 1.0, now=340.0) == "ok"
    assert d.breakers()["restart"] == "half_open"
    assert _BREAKER_G.labels(action="restart").value == 0.0


def test_dry_run_records_plan_without_touching_store(memkv):
    d = _dispatcher(memkv, enabled=False, breaker_n=2)
    cluster = _put_cluster(memkv, [make_pod("10.9.2.1")])
    rule = _rule()
    assert d.dispatch("restart", rule, "", 1.0) == "dryrun"
    pod = cluster.pods[0].pod_id
    assert heartbeat.read_pod_restart(memkv, JOB, cluster.stage, pod) is None
    rec = d.recent()[-1]
    assert rec["outcome"] == "dryrun"
    assert rec["detail"]["pods"] == [pod]
    # observe-only never moves the rails: a rehearsal firing past the
    # breaker budget must not trip it (or page the operator)
    for i in range(5):
        assert d.dispatch("restart", rule, "", 1.0,
                          now=500.0 + i) == "dryrun"
    assert d.breakers()["restart"] == "closed"


def test_action_incident_records_are_durable_and_trace_joined(tmp_path,
                                                              memkv):
    from edl_tpu.obs.rules import IncidentLog
    log = IncidentLog(str(tmp_path), "obs-agg", JOB)
    d = _dispatcher(memkv, incident_log=log, trace_provider=lambda: "t1" * 8)
    _put_cluster(memkv, [make_pod("10.9.3.1")])
    assert d.dispatch("restart", _rule(), "", 1.0) == "ok"
    recs = [json.loads(line) for line in open(log.path, encoding="utf-8")]
    assert recs and recs[-1]["name"] == "action/restart"
    assert recs[-1]["state"] == "ok"
    assert recs[-1]["rule"] == "trainer-hang"
    assert recs[-1]["trace_id"] == "t1" * 8


# -- the actions -------------------------------------------------------------

def test_restart_single_pod_targeted_multi_pod_coordinated(memkv):
    """A single-pod job restarts in place via the per-pod flag; a
    multi-pod job ALWAYS takes the coordinated hang flag — its pods
    share one collective world, and killing one pod's trainers
    unilaterally just crashes the peers (heartbeat.py's invariant).
    The stale-beat pods ride the audit detail for blame."""
    import time as _time
    pod = make_pod("10.9.4.9")
    cluster = _put_cluster(memkv, [pod])
    d = _dispatcher(memkv)
    assert d.dispatch("restart", _rule(), "", 1.0) == "ok"
    assert heartbeat.read_pod_restart(
        memkv, JOB, cluster.stage, pod.pod_id) is not None
    assert d.recent()[-1]["detail"]["mode"] == "targeted"
    assert heartbeat.get_hang(memkv, JOB, cluster.stage) is None

    pods = [make_pod(f"10.9.4.{i}") for i in range(3)]
    cluster = _put_cluster(memkv, pods)
    now = _time.time()
    heartbeat.beat(memkv, JOB, pods[0].pod_id, now=now - 500.0,
                   threshold=60.0)
    for p in pods[1:]:
        heartbeat.beat(memkv, JOB, p.pod_id, now=now, threshold=60.0)
    d2 = _dispatcher(memkv)
    assert d2.dispatch("restart", _rule(), "", 1.0) == "ok"
    assert d2.recent()[-1]["detail"]["mode"] == "coordinated"
    assert d2.recent()[-1]["detail"]["stale"] == [pods[0].pod_id]
    assert heartbeat.get_hang(memkv, JOB, cluster.stage) is not None
    for p in pods:
        assert heartbeat.read_pod_restart(
            memkv, JOB, cluster.stage, p.pod_id) is None


def test_restart_without_cluster_is_noop(memkv):
    d = _dispatcher(memkv)
    assert d.dispatch("restart", _rule(), "", 1.0) == "noop"


def test_evict_flags_preemption_with_reason(memkv):
    pods = [make_pod(f"10.9.5.{i}") for i in range(3)]
    cluster = _put_cluster(memkv, pods)
    scale.save_nodes_range(memkv, JOB, 1, 4)
    _advertise(memkv, "t0", "10.9.5.0:9100", pods[0].pod_id)
    d = _dispatcher(memkv)
    rule = _rule("trainer-straggler", action="evict")
    assert d.dispatch("evict", rule, "10.9.5.0:9100", 3.0) == "ok"
    info = preempt.pod_preempt_info(memkv, JOB, cluster.stage,
                                    pods[0].pod_id)
    assert info is not None and info[1] == "straggler-evict"
    # the stage flag is up too (trainers poll it for the agreed save)
    assert preempt.get_preempt(memkv, JOB, cluster.stage) is not None


def test_evict_refuses_below_min_nodes(memkv):
    pods = [make_pod("10.9.6.1"), make_pod("10.9.6.2")]
    cluster = _put_cluster(memkv, pods)
    scale.save_nodes_range(memkv, JOB, 2, 4)     # already at the floor
    _advertise(memkv, "t0", "10.9.6.1:9100", pods[0].pod_id)
    d = _dispatcher(memkv)
    out = d.dispatch("evict", _rule("trainer-straggler", action="evict"),
                     "10.9.6.1:9100", 3.0)
    assert out == "no_capacity"
    assert preempt.pod_preempt_info(memkv, JOB, cluster.stage,
                                    pods[0].pod_id) is None


def test_evict_unmapped_instance_is_noop(memkv):
    _put_cluster(memkv, [make_pod("10.9.7.1"), make_pod("10.9.7.2")])
    scale.save_nodes_range(memkv, JOB, 1, 4)
    d = _dispatcher(memkv)
    assert d.dispatch("evict", _rule(action="evict"),
                      "1.2.3.4:9", 3.0) == "noop"


def test_scale_out_writes_demand_record_clamped_to_range(memkv):
    from edl_tpu.gateway import fleet
    scale.save_nodes_range(memkv, JOB, 1, 3)
    for i in range(2):
        memkv.put(fleet.node_key(JOB, f"r{i}"),
                  json.dumps({"endpoint": f"127.0.0.1:9{i}"}).encode())
    d = _dispatcher(memkv)
    rule = _rule("gateway-p99-slo", action="scale-out")
    assert d.dispatch("scale-out", rule, "", 9.0) == "ok"
    rec = scale.load_demand(memkv, JOB)
    assert rec["replicas"] == 3 and rec["reason"] == "gateway-p99-slo"
    # at max already: noop, demand unchanged
    memkv.put(fleet.node_key(JOB, "r2"),
              json.dumps({"endpoint": "127.0.0.1:92"}).encode())
    d2 = _dispatcher(memkv)
    assert d2.dispatch("scale-out", rule, "", 9.0) == "noop"


# -- engine integration ------------------------------------------------------

def test_engine_runs_comma_chained_actions_with_outcomes():
    from edl_tpu.obs.rules import _ACTIONS_TOTAL
    t = TSDB(retention_s=600.0)
    calls = []
    rule = Rule("r", kind="gauge", metric="edl_g", op=">", threshold=0.5,
                window=60.0, action="first,second")
    eng = RuleEngine(t, [rule], actions={
        "first": lambda r, g, v: calls.append("first") or "cooldown",
        "second": lambda r, g, v: calls.append("second"),   # None -> ok
    })
    before_cd = _ACTIONS_TOTAL.labels(action="first",
                                      outcome="cooldown").value
    before_ok = _ACTIONS_TOTAL.labels(action="second", outcome="ok").value
    t.ingest({("edl_g", ()): 1.0}, 1000.0)
    eng.evaluate(now=1000.5)
    assert calls == ["first", "second"]
    assert _ACTIONS_TOTAL.labels(action="first",
                                 outcome="cooldown").value == before_cd + 1
    assert _ACTIONS_TOTAL.labels(action="second",
                                 outcome="ok").value == before_ok + 1


# -- serving autoscaler ------------------------------------------------------

def test_autoscaler_demand_record_drives_target_and_ttl_expires(memkv):
    a = ServingAutoscaler(memkv, quiet_s=50.0, demand_ttl=120.0)
    # no signal: hold at current
    assert a.desired(JOB, 1, 8, 2, now=100.0) == 2
    scale.save_demand(memkv, JOB, 4, reason="gateway-p99-slo")
    assert a.desired(JOB, 1, 8, 2, now=101.0) == 4
    # demand clamps to the range
    scale.save_demand(memkv, JOB, 99, reason="gateway-p99-slo")
    assert a.desired(JOB, 1, 8, 2, now=102.0) == 8
    # an EXPIRED record is not a signal; target decays on quiet
    import time as _time
    memkv.put(paths.key(JOB, constants.ETCD_SCALE, "demand"),
              json.dumps({"replicas": 99, "reason": "stale",
                          "at": _time.time() - 999.0}).encode())
    assert a.desired(JOB, 1, 8, 2, now=140.0) == 8    # quiet < quiet_s
    assert a.desired(JOB, 1, 8, 2, now=160.0) == 7    # one step per window
    assert a.desired(JOB, 1, 8, 2, now=215.0) == 6


def test_autoscaler_firing_alert_steps_from_current(memkv):
    a = ServingAutoscaler(memkv, alerts_url="http://unused/alerts",
                          step=1, quiet_s=60.0)
    a._alerts_cache = (100.0, {"gateway-p99-slo"})   # injected poll result
    assert a.desired(JOB, 1, 8, 2, now=100.0) == 3
    a._alerts_cache = (100.5, {"gateway-p99-slo"})
    assert a.desired(JOB, 1, 8, 3, now=100.5) == 4
    # quiet: decays back toward min one step per window
    a._alerts_cache = (161.0, set())
    assert a.desired(JOB, 1, 8, 4, now=161.0) == 3


def test_autoscaler_never_below_min_or_above_max(memkv):
    a = ServingAutoscaler(memkv, quiet_s=1.0)
    assert a.desired(JOB, 2, 3, 1, now=0.0) == 2     # floor
    for i in range(10):
        out = a.desired(JOB, 2, 3, 2, now=10.0 + i * 5)
    assert out == 2                                   # decay floor = min


# -- the edl-obs-top actions pane -------------------------------------------

def test_render_top_shows_recent_actions_and_breakers():
    from edl_tpu.obs.top import render_top
    alerts = {"firing": [], "pending": [],
              "actions": [{"ts": 1000.0, "rule": "trainer-hang",
                           "action": "restart", "outcome": "ok",
                           "group": ""},
                          {"ts": 1001.0, "rule": "trainer-hang",
                           "action": "restart", "outcome": "breaker_open",
                           "group": ""}],
              "breakers": {"restart": "open", "evict": "closed"}}
    out = render_top({"job_id": "j", "live_targets": 0}, alerts)
    assert "recent actions (2)" in out
    assert "breakers: restart=open" in out
    assert "evict" not in out.split("breakers:")[1].splitlines()[0].replace(
        "restart=open", "")          # closed breakers are not noise
    assert "trainer-hang -> restart [breaker_open]" in out
    assert "trainer-hang -> restart [ok]" in out
