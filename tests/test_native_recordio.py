"""Native record IO: build, round-trip, cross-impl compatibility,
corruption detection, shuffle completeness."""

import os
import struct
import zlib

import pytest

from edl_tpu.native import (
    RecordReader, RecordWriter, ShuffleReader, native_available, write_records,
)

RECORDS = [f"record-{i}".encode() * (i % 5 + 1) for i in range(50)]


def test_native_builds():
    assert native_available(), "g++ build of csrc/ failed"


@pytest.mark.parametrize("write_native,read_native",
                         [(False, False), (True, True),
                          (False, True), (True, False)])
def test_roundtrip_cross_impl(tmp_path, write_native, read_native):
    p = str(tmp_path / "data.rec")
    write_records(p, RECORDS, use_native=write_native)
    r = RecordReader(p, use_native=read_native)
    assert list(r) == RECORDS
    r.close()


@pytest.mark.parametrize("read_native", [False, True])
def test_corruption_detected(tmp_path, read_native):
    p = str(tmp_path / "corrupt.rec")
    write_records(p, RECORDS[:10], use_native=False)
    data = bytearray(open(p, "rb").read())
    data[-3] ^= 0xFF  # flip a payload byte of the last record
    open(p, "wb").write(bytes(data))
    with pytest.raises(OSError):
        list(RecordReader(p, use_native=read_native))


@pytest.mark.parametrize("use_native", [False, True])
def test_shuffle_complete_and_shuffled(tmp_path, use_native):
    paths = []
    for f in range(3):
        p = str(tmp_path / f"s{f}.rec")
        write_records(p, [f"f{f}-{i}".encode() for i in range(40)],
                      use_native=use_native)
        paths.append(p)
    sr = ShuffleReader(paths, buffer_size=32, seed=7, use_native=use_native)
    out = list(sr)
    sr.close()
    expected = sorted(f"f{f}-{i}".encode() for f in range(3) for i in range(40))
    assert sorted(out) == expected
    assert out != expected  # order actually shuffled


def test_shuffle_handles_large_records(tmp_path):
    if not native_available():
        pytest.skip("native lib unavailable")
    p = str(tmp_path / "big.rec")
    big = [os.urandom(100_000), os.urandom(200_000), b"small"]
    write_records(p, big, use_native=True)
    sr = ShuffleReader([p], buffer_size=4, seed=1, use_native=True)
    assert sorted(list(sr)) == sorted(big)
    sr.close()
